#!/usr/bin/env python
"""Sequence-parallel convolution of a long signal over a device mesh.

Shards a 4M-sample signal across all available devices, halo-exchanges
the filter history over ICI (``ppermute``), convolves each shard locally
on the MXU, and checks the result — the distributed form of the
reference's overlap-save block pipeline.  On one box this provisions a
virtual 8-device CPU mesh; the identical code lays the collectives onto
ICI on a real slice (and the dp axis onto DCN across hosts — see
``veles.simd_tpu.parallel.distributed``).

Run:  python examples/sharded_longsignal.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu.utils.platform import (
    cpu_devices, maybe_override_platform)

maybe_override_platform()


def main():
    with cpu_devices(8) as devices:
        import jax.numpy as jnp

        from veles.simd_tpu.parallel import (
            make_mesh, sharded_convolve, sharded_convolve_batch)

        mesh = make_mesh({"sp": len(devices)}, devices=devices)
        rng = np.random.RandomState(0)
        n, k = 1 << 22, 255
        x = rng.randn(n).astype(np.float32)
        h = rng.randn(k).astype(np.float32)

        y = np.asarray(sharded_convolve(x, h, mesh, axis="sp"))
        print(f"sharded convolve: {n} samples over {len(devices)} shards "
              f"-> {y.shape[-1]} output samples")

        # spot-check a window against NumPy (full oracle conv of 4M on one
        # core takes a while; a strided sample is plenty for a demo)
        idx = rng.randint(k, n - k, 64)
        for i in idx:
            want = float(np.dot(x[i - k + 1:i + 1].astype(np.float64),
                                h[::-1].astype(np.float64)))
            assert abs(y[i] - want) < 1e-2 * max(1.0, abs(want)), i
        print("spot-check vs oracle: ok")

        # dp x sp: a batch of signals over a 2D mesh tile (batch 5 is not
        # divisible by dp=2 — the layer pads and slices)
        mesh2 = make_mesh({"dp": 2, "sp": 4}, devices=devices)
        xb = rng.randn(5, 1 << 16).astype(np.float32)
        yb = np.asarray(sharded_convolve_batch(jnp.asarray(xb),
                                               jnp.asarray(h), mesh2))
        ref0 = np.convolve(xb[0], h)
        assert np.max(np.abs(yb[0] - ref0)) < 1e-3 * np.max(np.abs(ref0))
        print(f"dp x sp batch: {yb.shape} ok")

        # distributed wavelet round trip: sharded à-trous analysis, then
        # the sharded synthesis adjoint (left-halo ring) — the signal
        # never leaves the mesh
        from veles.simd_tpu.parallel import (
            sharded_swt, sharded_swt_reconstruct)

        xs = x[: 1 << 20]
        bands = sharded_swt("daub", 8, 3, xs, mesh, axis="sp")
        rec = np.asarray(sharded_swt_reconstruct("daub", 8, 3, bands, mesh,
                                                 axis="sp"))
        err = float(np.max(np.abs(rec - xs)))
        assert err < 1e-3, err
        print(f"sharded SWT L3 analysis -> synthesis round trip over "
              f"{len(devices)} shards: max|err| {err:.1e} ok")


if __name__ == "__main__":
    main()
