"""Multi-host bootstrap: the distributed communication backend.

The reference is a single-process library (SURVEY.md §2: "Distributed
communication backend: none"), so this module is the TPU build's *new*
scale-out capability: process bootstrap + hybrid ICI/DCN meshes, with XLA
collectives doing all communication (no NCCL/MPI — ``psum``/``ppermute``
lower to ICI transfers within a slice and to DCN/gRPC across hosts).

Usage on an N-host slice (same program on every host):

    from veles.simd_tpu.parallel import distributed
    distributed.initialize()            # TPU pods: args auto-detected
    mesh = distributed.hybrid_mesh(dcn={"dp": distributed.process_count()},
                                   ici={"sp": 2, "tp": 2})
    # ... shard_map / pjit over `mesh`: "dp" hops ride DCN, "sp"/"tp" ICI

The same code path is exercised for real in ``tests/test_distributed.py``
by spawning multiple *processes* on localhost (CPU backend, Gloo
cross-process collectives standing in for DCN) — multi-host semantics,
one box.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["initialize", "shutdown", "process_count", "process_index",
           "hybrid_mesh"]


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join (or create) the distributed runtime.

    On TPU pods all three arguments are auto-detected from the metadata
    server — call with no arguments.  Off-pod (CPU/GPU clusters, or the
    localhost test rig) pass them explicitly; process 0 must be reachable
    at ``coordinator_address``.

    Must run before any jax backend initialization (the runtime has to
    register every process's local devices into the global topology).
    """
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def shutdown() -> None:
    """Leave the distributed runtime (idempotent)."""
    try:
        jax.distributed.shutdown()
    except RuntimeError:
        pass  # never initialized


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def hybrid_mesh(dcn: dict[str, int] | None = None,
                ici: dict[str, int] | None = None) -> Mesh:
    """Mesh whose ``dcn`` axes span hosts and ``ici`` axes stay intra-host.

    Axis order puts DCN axes outermost — collectives over an inner (ICI)
    axis then touch only devices of one host, and only the outer axes pay
    cross-host latency.  This is the layout rule that makes a sharded
    overlap-save halo (one ``ppermute`` hop over "sp") ride ICI while the
    batch axis ("dp") spans the fleet.

    DCN sizes must multiply to ``jax.process_count()`` and ICI sizes to
    ``jax.local_device_count()``.  Uses
    ``mesh_utils.create_hybrid_device_mesh`` for physical-topology-aware
    placement on real slices, with a process-major reshape fallback.
    """
    dcn = dict(dcn or {})
    ici = dict(ici or {})
    if not dcn and not ici:
        raise ValueError("at least one dcn or ici axis is required")
    n_proc = jax.process_count()
    n_local = jax.local_device_count()
    dcn_sizes = [int(s) for s in dcn.values()]
    ici_sizes = [int(s) for s in ici.values()]
    if int(np.prod(dcn_sizes or [1])) != n_proc:
        raise ValueError(f"dcn axes {dcn} must multiply to "
                         f"process_count()={n_proc}")
    if int(np.prod(ici_sizes or [1])) != n_local:
        raise ValueError(f"ici axes {ici} must multiply to "
                         f"local_device_count()={n_local}")
    names = tuple(dcn) + tuple(ici)
    shape = dcn_sizes + ici_sizes
    # per-dimension shapes for create_hybrid_device_mesh: DCN dims are 1
    # in the ICI shape and vice versa
    ici_shape = [1] * len(dcn) + ici_sizes
    dcn_shape = dcn_sizes + [1] * len(ici)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=jax.devices())
    except Exception:
        # process-major fallback: jax.devices() orders by process index
        dev_array = np.asarray(jax.devices())
    return Mesh(dev_array.reshape(shape), names)
