"""Tests for veles.simd_tpu.ops.mathfun.

Port of ``tests/mathfun.cc:59-84``: libm (NumPy) is the oracle; parameterized
over sizes {1, 3, 64, 199} × functions, non-finite inputs excluded for log
(the reference skips them at ``tests/mathfun.cc:69``).
"""

import numpy as np
import pytest

from veles.simd_tpu.ops import mathfun as mf

RNG = np.random.RandomState(42)
SIZES = [1, 3, 64, 199, 100003]


@pytest.mark.parametrize("length", SIZES)
@pytest.mark.parametrize("name,fn", [("sin", mf.sin_psv), ("cos", mf.cos_psv)])
def test_trig(name, fn, length):
    data = (RNG.rand(length).astype(np.float32) - 0.5) * 20.0
    np.testing.assert_allclose(np.asarray(fn(data, simd=True)),
                               fn(data, simd=False), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("length", SIZES)
def test_exp(length):
    data = (RNG.rand(length).astype(np.float32) - 0.5) * 20.0
    np.testing.assert_allclose(np.asarray(mf.exp_psv(data, simd=True)),
                               mf.exp_psv(data, simd=False), rtol=1e-5)


@pytest.mark.parametrize("length", SIZES)
def test_log(length):
    data = RNG.rand(length).astype(np.float32) * 1000.0 + 1e-6
    # XLA's f32 log is a few ulp off libm; absolute tolerance on the output
    np.testing.assert_allclose(np.asarray(mf.log_psv(data, simd=True)),
                               mf.log_psv(data, simd=False),
                               rtol=1e-4, atol=1e-4)


def test_pow_sqrt():
    base = RNG.rand(512).astype(np.float32) * 10.0 + 0.1
    exponent = (RNG.rand(512).astype(np.float32) - 0.5) * 4.0
    np.testing.assert_allclose(
        np.asarray(mf.pow_psv(base, exponent, simd=True)),
        mf.pow_psv(base, exponent, simd=False), rtol=2e-5)
    np.testing.assert_allclose(np.asarray(mf.sqrt_psv(base, simd=True)),
                               mf.sqrt_psv(base, simd=False), rtol=1e-6)


def test_golden_values():
    np.testing.assert_allclose(
        np.asarray(mf.sin_psv(np.array([0.0, np.pi / 2], np.float32))),
        [0.0, 1.0], atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(mf.exp_psv(np.array([0.0, 1.0], np.float32))),
        [1.0, np.e], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(mf.log_psv(np.array([1.0, np.e], np.float32))),
        [0.0, 1.0], atol=2e-5)
