"""Tests for veles.simd_tpu.parallel on the virtual 8-device CPU mesh.

The reference has no distributed layer (SURVEY.md §2 checklist) — these
tests validate the new TPU capability: sharded results must be bitwise-
close to the single-device ops they decompose.
"""

import numpy as np
import pytest

import jax

from veles.simd_tpu import parallel as par
from veles.simd_tpu.ops import convolve as cv

# slow tier: multi-stage sharded sweeps on the 8-device mesh (~6 min) — excluded from `make tests-quick`
pytestmark = pytest.mark.slow

RNG = np.random.RandomState(51)


def test_virtual_mesh_has_8_devices():
    assert len(jax.devices()) == 8  # conftest.py forces this


def test_make_mesh_shapes():
    m = par.make_mesh({"dp": 2, "sp": 4})
    assert m.shape == {"dp": 2, "sp": 4}
    m2 = par.make_mesh({"dp": 2, "tp": -1})
    assert m2.shape["tp"] == 4
    with pytest.raises(ValueError):
        par.make_mesh({"dp": 3})


@pytest.mark.parametrize("n,k", [(1 << 12, 65), (1000, 17), (8192, 129)])
def test_sharded_convolve_matches_single_device(n, k):
    """Sequence-parallel conv == the single-chip op (halo correctness)."""
    mesh = par.make_mesh({"sp": 8})
    x = RNG.randn(n).astype(np.float32)
    h = RNG.randn(k).astype(np.float32)
    got = np.asarray(par.sharded_convolve(x, h, mesh))
    want = np.asarray(cv.convolve_simd(x, h, simd=True))
    assert got.shape == (n + k - 1,)
    np.testing.assert_allclose(
        got, want, atol=1e-3 * max(1, np.abs(want).max()))


def test_sharded_convolve_2d_mesh_axis():
    """Works on a named axis of a 2D mesh."""
    mesh = par.make_mesh({"dp": 2, "sp": 4})
    x = RNG.randn(4096).astype(np.float32)
    h = RNG.randn(33).astype(np.float32)
    got = np.asarray(par.sharded_convolve(x, h, mesh, axis="sp"))
    want = np.convolve(x.astype(np.float64), h.astype(np.float64))
    np.testing.assert_allclose(got, want.astype(np.float32), atol=1e-2)


def test_sharded_matmul_matches_dot():
    mesh = par.make_mesh({"tp": 8})
    a = RNG.randn(64, 256).astype(np.float32)
    b = RNG.randn(256, 48).astype(np.float32)
    got = np.asarray(par.sharded_matmul(a, b, mesh))
    want = a.astype(np.float64) @ b.astype(np.float64)
    np.testing.assert_allclose(got, want.astype(np.float32), atol=1e-3)


def test_sharded_matmul_contract_violations():
    mesh = par.make_mesh({"tp": 8})
    with pytest.raises(ValueError):
        par.sharded_matmul(np.zeros((4, 5), np.float32),
                           np.zeros((6, 4), np.float32), mesh)


def test_sharded_matmul_pads_indivisible_k():
    """K=300 is not a multiple of 8: zero-padding must keep the result
    exact (VERDICT r1: the divisibility requirement was a gap)."""
    mesh = par.make_mesh({"tp": 8})
    a = RNG.randn(32, 300).astype(np.float32)
    b = RNG.randn(300, 24).astype(np.float32)
    got = np.asarray(par.sharded_matmul(a, b, mesh))
    want = (a.astype(np.float64) @ b.astype(np.float64)).astype(np.float32)
    assert got.shape == (32, 24)
    np.testing.assert_allclose(got, want, atol=1e-3)


def test_sharded_convolve_batch_dpxsp():
    """dp×sp tiled convolution == per-row np.convolve."""
    mesh = par.make_mesh({"dp": 2, "sp": 4})
    x = RNG.randn(6, 2048).astype(np.float32)
    h = RNG.randn(65).astype(np.float32)
    got = np.asarray(par.sharded_convolve_batch(x, h, mesh))
    assert got.shape == (6, 2048 + 64)
    for i in range(6):
        want = np.convolve(x[i].astype(np.float64), h.astype(np.float64))
        np.testing.assert_allclose(got[i], want.astype(np.float32),
                                   atol=1e-3 * max(1, np.abs(want).max()))


def test_sharded_convolve_batch_contract():
    mesh = par.make_mesh({"dp": 2, "sp": 4})
    # batch not divisible by dp pads-and-slices (r2 generalization)
    out = par.sharded_convolve_batch(np.zeros((3, 512), np.float32),
                                     np.zeros(9, np.float32), mesh)
    assert np.asarray(out).shape == (3, 520)
    with pytest.raises(ValueError):  # 1D input
        par.sharded_convolve_batch(np.zeros(512, np.float32),
                                   np.zeros(9, np.float32), mesh)


@pytest.mark.parametrize("levels", [1, 2, 3])
def test_sharded_swt_matches_single_device(levels):
    """Sharded à-trous cascade == the single-chip SWT with PERIODIC."""
    from veles.simd_tpu.ops import wavelet as wv
    from veles.simd_tpu.ops.wavelet_coeffs import WaveletType

    mesh = par.make_mesh({"sp": 8})
    x = RNG.randn(2048).astype(np.float32)
    got = par.sharded_swt(WaveletType.DAUBECHIES, 8, levels, x, mesh)
    want = wv.stationary_wavelet_transform(
        WaveletType.DAUBECHIES, 8, wv.ExtensionType.PERIODIC, x, levels,
        simd=True)
    assert len(got) == levels + 1
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-4)


def test_sharded_swt_contracts():
    from veles.simd_tpu.ops.wavelet_coeffs import WaveletType

    mesh = par.make_mesh({"sp": 8})
    with pytest.raises(ValueError):  # length not divisible by shards
        par.sharded_swt(WaveletType.DAUBECHIES, 8, 1,
                        np.zeros(1001, np.float32), mesh)
    with pytest.raises(ValueError):  # halo exceeds block
        par.sharded_swt(WaveletType.DAUBECHIES, 8, 6,
                        np.zeros(1024, np.float32), mesh)


def test_data_parallel_batched_op():
    from veles.simd_tpu.ops import wavelet as wv
    from veles.simd_tpu.ops.wavelet_coeffs import WaveletType

    mesh = par.make_mesh({"dp": 8})
    x = RNG.randn(16, 256).astype(np.float32)
    dwt = par.data_parallel(
        lambda b: wv.wavelet_apply(WaveletType.DAUBECHIES, 8,
                                   wv.ExtensionType.PERIODIC, b, simd=True),
        mesh)
    hi, lo = dwt(x)
    hi_1, lo_1 = wv.wavelet_apply(WaveletType.DAUBECHIES, 8,
                                  wv.ExtensionType.PERIODIC, x, simd=True)
    np.testing.assert_allclose(np.asarray(hi), np.asarray(hi_1), atol=1e-5)
    np.testing.assert_allclose(np.asarray(lo), np.asarray(lo_1), atol=1e-5)


def test_sharded_convolve_accepts_batch():
    """Leading batch dims ride along replicated (r2 generalization)."""
    mesh = par.make_mesh({"sp": 8})
    out = par.sharded_convolve(np.zeros((2, 64), np.float32),
                               np.zeros(5, np.float32), mesh)
    assert np.asarray(out).shape == (2, 68)


def test_sharded_convolve_length1_kernel():
    """halo_len=0 edge: a length-1 kernel is a pure scale."""
    mesh = par.make_mesh({"sp": 8})
    x = RNG.randn(512).astype(np.float32)
    h = np.array([2.5], np.float32)
    got = np.asarray(par.sharded_convolve(x, h, mesh))
    np.testing.assert_allclose(got, 2.5 * x, atol=1e-5)


def test_sharded_convolve_halo_too_large_auto_rings():
    """Filters longer than a shard block auto-select the multi-hop ring
    pipeline (round 2 raised here)."""
    mesh = par.make_mesh({"sp": 8})
    x = RNG.randn(256).astype(np.float32)
    h = RNG.randn(40).astype(np.float32)   # halo 39 > ceil(295/8)=37
    got = np.asarray(par.sharded_convolve(x, h, mesh))
    want = np.convolve(x.astype(np.float64), h.astype(np.float64))
    np.testing.assert_allclose(got, want.astype(np.float32),
                               atol=1e-3 * float(np.max(np.abs(want))))


class TestSharded2D:
    def test_matches_oracle_2x2(self):
        from veles.simd_tpu.ops import convolve2d as cv2
        from veles.simd_tpu.parallel import make_mesh, sharded_convolve2d

        rng = np.random.RandomState(21)
        mesh = make_mesh({"dp": 4, "sp": 2})
        x = rng.randn(30, 26).astype(np.float32)
        h = rng.randn(4, 5).astype(np.float32)
        got = np.asarray(sharded_convolve2d(x, h, mesh))
        np.testing.assert_allclose(got, cv2.convolve2d_na(x, h), atol=1e-3)

    def test_matches_oracle_2x4_uneven(self):
        from veles.simd_tpu.ops import convolve2d as cv2
        from veles.simd_tpu.parallel import make_mesh, sharded_convolve2d

        rng = np.random.RandomState(22)
        mesh = make_mesh({"dp": 2, "sp": 4})
        x = rng.randn(17, 53).astype(np.float32)   # needs output padding
        h = rng.randn(3, 3).astype(np.float32)
        got = np.asarray(sharded_convolve2d(x, h, mesh))
        np.testing.assert_allclose(got, cv2.convolve2d_na(x, h), atol=1e-3)

    def test_halo_too_large_auto_rings(self):
        """Kernels whose halo exceeds a tile auto-select the 2D ring
        (round 2 raised here)."""
        from veles.simd_tpu.ops import convolve2d as cv2
        from veles.simd_tpu.parallel import make_mesh, sharded_convolve2d

        mesh = make_mesh({"dp": 2, "sp": 4})
        rng = np.random.RandomState(24)
        x = rng.randn(8, 8).astype(np.float32)
        h = rng.randn(2, 7).astype(np.float32)
        got = np.asarray(sharded_convolve2d(x, h, mesh))
        np.testing.assert_allclose(got, cv2.convolve2d_na(x, h), atol=1e-3)

    def test_large_kernel_takes_fft_tile_path(self):
        from veles.simd_tpu.ops import convolve2d as cv2
        from veles.simd_tpu.parallel import make_mesh, sharded_convolve2d

        rng = np.random.RandomState(23)
        mesh = make_mesh({"dp": 2, "sp": 4})
        x = rng.randn(80, 160).astype(np.float32)
        h = rng.randn(33, 33).astype(np.float32)  # area >= fft crossover
        assert cv2.select_algorithm2d(33, 33) == "fft"
        got = np.asarray(sharded_convolve2d(x, h, mesh))
        np.testing.assert_allclose(got, cv2.convolve2d_na(x, h), atol=2e-3)


class TestShardedSynthesis:
    """Distributed analysis -> synthesis round trips (VERDICT r2 item 5:
    the sharded layer must cover the full round trip, not just analysis)."""

    def test_dwt_reconstruct_matches_input(self):
        from veles.simd_tpu.ops import wavelet as wv
        from veles.simd_tpu.parallel import (
            make_mesh, sharded_wavelet_reconstruct)

        rng = np.random.RandomState(31)
        mesh = make_mesh({"sp": 8})
        x = rng.randn(512).astype(np.float32)
        hi, lo = wv.wavelet_apply_na("daub", 8, wv.ExtensionType.PERIODIC, x)
        rec = np.asarray(sharded_wavelet_reconstruct("daub", 8, hi, lo,
                                                     mesh))
        np.testing.assert_allclose(rec, x, atol=2e-4)

    def test_swt_cascade_round_trip(self):
        from veles.simd_tpu.parallel import (
            make_mesh, sharded_swt, sharded_swt_reconstruct)

        rng = np.random.RandomState(32)
        mesh = make_mesh({"dp": 2, "sp": 4})
        x = rng.randn(512).astype(np.float32)
        bands = sharded_swt("sym", 8, 3, x, mesh)
        rec = np.asarray(sharded_swt_reconstruct("sym", 8, 3, bands, mesh))
        np.testing.assert_allclose(rec, x, atol=2e-4)

    def test_swt_batched(self):
        from veles.simd_tpu.ops import wavelet as wv
        from veles.simd_tpu.parallel import (
            make_mesh, sharded_swt, sharded_swt_reconstruct)

        rng = np.random.RandomState(33)
        mesh = make_mesh({"dp": 2, "sp": 4})
        xb = rng.randn(3, 256).astype(np.float32)
        bands = sharded_swt("daub", 8, 2, xb, mesh)
        want = wv.stationary_wavelet_transform(
            "daub", 8, wv.ExtensionType.PERIODIC, xb, 2, simd=False)
        for b, w in zip(bands, want):
            np.testing.assert_allclose(np.asarray(b), np.asarray(w),
                                       atol=5e-4)
        rec = np.asarray(sharded_swt_reconstruct("daub", 8, 2, bands, mesh))
        np.testing.assert_allclose(rec, xb, atol=2e-4)

    def test_synthesis_halo_too_large_raises(self):
        from veles.simd_tpu.parallel import (
            make_mesh, sharded_swt_reconstruct)

        mesh = make_mesh({"sp": 8})
        bands = [np.zeros(64, np.float32)] * 4
        with pytest.raises(ValueError, match="halo"):
            sharded_swt_reconstruct("daub", 8, 3, bands, mesh)


class TestShardedGeneralization:
    def test_batched_sharded_convolve(self):
        from veles.simd_tpu.parallel import make_mesh, sharded_convolve

        rng = np.random.RandomState(34)
        mesh = make_mesh({"sp": 8})
        xb = rng.randn(3, 256).astype(np.float32)
        h = rng.randn(17).astype(np.float32)
        got = np.asarray(sharded_convolve(xb, h, mesh))
        for i in range(3):
            np.testing.assert_allclose(got[i], np.convolve(xb[i], h),
                                       atol=1e-3)

    def test_batch_pad_and_slice(self):
        """batch % dp != 0 pads instead of raising (VERDICT r2 weak 4)."""
        from veles.simd_tpu.parallel import (
            make_mesh, sharded_convolve_batch)

        rng = np.random.RandomState(35)
        mesh = make_mesh({"dp": 4, "sp": 2})
        x = rng.randn(5, 128).astype(np.float32)   # 5 % 4 != 0
        h = rng.randn(9).astype(np.float32)
        got = np.asarray(sharded_convolve_batch(x, h, mesh))
        assert got.shape == (5, 128 + 8)
        for i in range(5):
            np.testing.assert_allclose(got[i], np.convolve(x[i], h),
                                       atol=1e-3)


class TestRingConvolve:
    """Multi-hop ring pipeline for filters longer than a shard block —
    the ring-attention communication pattern applied to convolution."""

    @pytest.mark.parametrize("n,k", [(1024, 300), (2048, 1500),
                                     (1024, 1024), (1000, 999)])
    def test_matches_oracle(self, n, k):
        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(41)
        x = rng.randn(n).astype(np.float32)
        h = rng.randn(k).astype(np.float32)
        got = np.asarray(par.sharded_convolve_ring(x, h, mesh))
        want = np.convolve(x.astype(np.float64), h.astype(np.float64))
        assert got.shape == want.shape
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-4, rel

    def test_auto_selected_by_sharded_convolve(self):
        """The one-hop entry point falls back to the ring instead of
        raising when the halo exceeds a block (r2: it raised)."""
        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(42)
        x = rng.randn(512).astype(np.float32)
        h = rng.randn(400).astype(np.float32)   # halo 399 > 512/8
        got = np.asarray(par.sharded_convolve(x, h, mesh))
        want = np.convolve(x.astype(np.float64), h.astype(np.float64))
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-4, rel

    def test_batched(self):
        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(43)
        xb = rng.randn(3, 512).astype(np.float32)
        h = rng.randn(450).astype(np.float32)
        got = np.asarray(par.sharded_convolve_ring(xb, h, mesh))
        for i in range(3):
            want = np.convolve(xb[i].astype(np.float64),
                               h.astype(np.float64))
            rel = np.max(np.abs(got[i] - want)) / np.max(np.abs(want))
            assert rel < 1e-4, rel

    def test_h_longer_than_x_works(self):
        """The ring has no operand-size restriction: the hop count
        clamps at S-1, covering every causal block pair."""
        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(49)
        x = rng.randn(64).astype(np.float32)
        h = rng.randn(200).astype(np.float32)
        got = np.asarray(par.sharded_convolve_ring(x, h, mesh))
        want = np.convolve(x.astype(np.float64), h.astype(np.float64))
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-4, rel


class TestRingConvolveBatched:
    def test_batch_axis_dpxsp(self):
        """Ring with the batch sharded over dp — the dp×sp long-filter
        form sharded_convolve_batch falls back to."""
        mesh = par.make_mesh({"dp": 2, "sp": 4})
        rng = np.random.RandomState(44)
        xb = rng.randn(5, 512).astype(np.float32)   # 5 % 2 != 0 too
        h = rng.randn(400).astype(np.float32)
        got = np.asarray(par.sharded_convolve_ring(
            xb, h, mesh, axis="sp", batch_axis="dp"))
        assert got.shape == (5, 911)
        for i in range(5):
            want = np.convolve(xb[i].astype(np.float64),
                               h.astype(np.float64))
            rel = np.max(np.abs(got[i] - want)) / np.max(np.abs(want))
            assert rel < 1e-4, (i, rel)

    def test_batch_entry_falls_back_to_ring(self):
        mesh = par.make_mesh({"dp": 2, "sp": 4})
        rng = np.random.RandomState(45)
        xb = rng.randn(4, 256).astype(np.float32)
        h = rng.randn(250).astype(np.float32)   # halo 249 > block
        got = np.asarray(par.sharded_convolve_batch(xb, h, mesh))
        for i in range(4):
            want = np.convolve(xb[i].astype(np.float64),
                               h.astype(np.float64))
            rel = np.max(np.abs(got[i] - want)) / np.max(np.abs(want))
            assert rel < 1e-4, (i, rel)

    def test_fft_hop_path(self):
        """Blocks big enough to cross AUTO_FFT_MIN_PRODUCT take the
        spectral per-hop form."""
        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(46)
        x = rng.randn(1 << 15).astype(np.float32)
        h = rng.randn(1 << 14).astype(np.float32)
        got = np.asarray(par.sharded_convolve_ring(x, h, mesh))
        want = np.convolve(x.astype(np.float64), h.astype(np.float64))
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-4, rel


class TestRingConvolve2D:
    """2D ring pipeline: kernels larger than a shard tile."""

    @pytest.mark.parametrize("img,ker", [
        ((64, 64), (40, 30)),     # halo exceeds both tile dims
        ((48, 96), (48, 96)),     # kernel == image
        ((64, 64), (5, 60)),      # one axis rings, the other fits
        ((33, 57), (20, 41))])    # uneven sizes + padding
    def test_matches_oracle(self, img, ker):
        from veles.simd_tpu.ops import convolve2d as cv2

        mesh = par.make_mesh({"dp": 2, "sp": 4})
        rng = np.random.RandomState(47)
        x = rng.randn(*img).astype(np.float32)
        h = rng.randn(*ker).astype(np.float32)
        got = np.asarray(par.sharded_convolve2d_ring(x, h, mesh))
        want = cv2.convolve2d_na(x, h)
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-4, rel

    def test_auto_selected_by_sharded_convolve2d(self):
        from veles.simd_tpu.ops import convolve2d as cv2

        mesh = par.make_mesh({"dp": 2, "sp": 4})
        rng = np.random.RandomState(48)
        x = rng.randn(40, 40).astype(np.float32)
        h = rng.randn(30, 30).astype(np.float32)  # halo > tile
        got = np.asarray(par.sharded_convolve2d(x, h, mesh))
        want = cv2.convolve2d_na(x, h)
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-4, rel

    @pytest.mark.parametrize("ker", [(3, 12), (12, 3), (20, 20)])
    def test_kernel_larger_than_image_works(self, ker):
        """Mixed-aspect and strictly-larger kernels all work — the
        per-axis hop clamp covers every causal tile pair."""
        from veles.simd_tpu.ops import convolve2d as cv2

        mesh = par.make_mesh({"dp": 2, "sp": 4})
        rng = np.random.RandomState(50)
        x = rng.randn(8, 8).astype(np.float32)
        h = rng.randn(*ker).astype(np.float32)
        got = np.asarray(par.sharded_convolve2d_ring(x, h, mesh))
        want = cv2.convolve2d_na(x, h)
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-4, rel


class TestAllToAll2DWavelet:
    """The all-to-all (Ulysses-style) pattern: rows local -> A2A
    transpose -> columns local; every pass sees complete rows/columns,
    so all four extensions are exact."""

    @pytest.mark.parametrize("ext_name", ["periodic", "mirror",
                                          "constant", "zero"])
    def test_matches_single_chip_every_ext(self, ext_name):
        from veles.simd_tpu.ops import wavelet as wv

        mesh = par.make_mesh({"sp": 8})
        ext = wv.ExtensionType(ext_name)
        rng = np.random.RandomState(52)
        img = rng.randn(64, 96).astype(np.float32)
        got = par.sharded_wavelet_apply2d("daub", 8, ext, img, mesh)
        want = wv.wavelet_apply2d("daub", 8, ext, img, simd=False)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=5e-4)

    def test_round_trip(self):
        from veles.simd_tpu.ops import wavelet as wv

        mesh = par.make_mesh({"sp": 4, "dp": 2})
        rng = np.random.RandomState(53)
        img = rng.randn(64, 64).astype(np.float32)
        ll, lh, hl, hh = par.sharded_wavelet_apply2d(
            "sym", 8, wv.ExtensionType.PERIODIC, img, mesh, axis="sp")
        rec = par.sharded_wavelet_reconstruct2d("sym", 8, ll, lh, hl, hh,
                                                mesh, axis="sp")
        np.testing.assert_allclose(np.asarray(rec), img, atol=2e-4)

    def test_divisibility_contract(self):
        from veles.simd_tpu.ops import wavelet as wv

        mesh = par.make_mesh({"sp": 8})
        with pytest.raises(ValueError, match="divisible"):
            par.sharded_wavelet_apply2d(
                "daub", 8, wv.ExtensionType.PERIODIC,
                np.zeros((60, 64), np.float32), mesh)


class TestShardedDWTAnalysis:
    def test_matches_single_chip(self):
        from veles.simd_tpu.ops import wavelet as wv

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(54)
        x = rng.randn(512).astype(np.float32)
        hi, lo = par.sharded_wavelet_apply("daub", 8, x, mesh)
        whi, wlo = wv.wavelet_apply_na("daub", 8,
                                       wv.ExtensionType.PERIODIC, x)
        np.testing.assert_allclose(np.asarray(hi), whi, atol=5e-4)
        np.testing.assert_allclose(np.asarray(lo), wlo, atol=5e-4)

    def test_full_sharded_round_trip(self):
        """analysis -> synthesis entirely on the mesh."""
        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(55)
        x = rng.randn(1024).astype(np.float32)
        hi, lo = par.sharded_wavelet_apply("sym", 12, x, mesh)
        rec = par.sharded_wavelet_reconstruct("sym", 12, hi, lo, mesh)
        np.testing.assert_allclose(np.asarray(rec), x, atol=2e-4)

    def test_batched(self):
        from veles.simd_tpu.ops import wavelet as wv

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(56)
        xb = rng.randn(3, 512).astype(np.float32)
        hi, lo = par.sharded_wavelet_apply("daub", 8, xb, mesh)
        whi, wlo = wv.wavelet_apply_na("daub", 8,
                                       wv.ExtensionType.PERIODIC, xb)
        np.testing.assert_allclose(np.asarray(hi), whi, atol=5e-4)
        np.testing.assert_allclose(np.asarray(lo), wlo, atol=5e-4)

    def test_contracts(self):
        mesh = par.make_mesh({"sp": 8})
        with pytest.raises(ValueError, match="divisible"):
            par.sharded_wavelet_apply("daub", 8,
                                      np.zeros(1004, np.float32), mesh)
        with pytest.raises(ValueError, match="halo"):
            par.sharded_wavelet_apply("daub", 76,
                                      np.zeros(512, np.float32), mesh)

    def test_multi_level_cascade_round_trip(self):
        from veles.simd_tpu.ops import wavelet as wv

        mesh = par.make_mesh({"sp": 4, "dp": 2})
        rng = np.random.RandomState(57)
        x = rng.randn(1024).astype(np.float32)
        coeffs = par.sharded_wavelet_transform("daub", 8, x, 3, mesh,
                                               axis="sp")
        want = wv.wavelet_transform("daub", 8, wv.ExtensionType.PERIODIC,
                                    x, 3, simd=False)
        assert len(coeffs) == 4
        for c, w in zip(coeffs, want):
            np.testing.assert_allclose(np.asarray(c), np.asarray(w),
                                       atol=5e-4)
        rec = par.sharded_wavelet_inverse_transform("daub", 8, coeffs,
                                                    mesh, axis="sp")
        np.testing.assert_allclose(np.asarray(rec), x, atol=5e-4)


class TestShardedSTFT:
    """Sequence-parallel STFT/ISTFT vs the single-chip spectral ops."""

    def test_matches_single_chip(self):
        from veles.simd_tpu.ops import spectral as sp

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(58)
        n, fl, hop = 4096, 256, 64
        x = rng.randn(n).astype(np.float32)
        got = np.asarray(par.sharded_stft(x, fl, hop, mesh))
        want = np.asarray(sp.stft(x, fl, hop, simd=True))
        assert got.shape == want.shape == (sp.frame_count(n, fl, hop),
                                           fl // 2 + 1)
        np.testing.assert_allclose(got, want, atol=1e-3)

    def test_round_trip(self):
        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(59)
        n, fl, hop = 2048, 128, 32
        x = rng.randn(n).astype(np.float32)
        spec = par.sharded_stft(x, fl, hop, mesh)
        rec = np.asarray(par.sharded_istft(spec, n, fl, hop, mesh))
        # interior exact; boundary frames normalized by partial envelope
        np.testing.assert_allclose(rec[fl:n - fl], x[fl:n - fl], atol=1e-3)

    def test_istft_matches_single_chip(self):
        from veles.simd_tpu.ops import spectral as sp

        mesh = par.make_mesh({"dp": 2, "sp": 4})
        rng = np.random.RandomState(60)
        n, fl, hop = 1024, 128, 64
        x = rng.randn(n).astype(np.float32)
        spec = np.asarray(sp.stft(x, fl, hop, simd=True))
        got = np.asarray(par.sharded_istft(spec, n, fl, hop, mesh,
                                           axis="sp"))
        want = np.asarray(sp.istft(spec, n, fl, hop, simd=True))
        np.testing.assert_allclose(got, want, atol=1e-3)

    def test_hop_equals_frame_length(self):
        """Zero overlap: the halo path degenerates to empty exchanges."""
        from veles.simd_tpu.ops import spectral as sp

        mesh = par.make_mesh({"dp": 2, "sp": 4})
        rng = np.random.RandomState(61)
        n, fl = 1024, 64
        x = rng.randn(n).astype(np.float32)
        got = np.asarray(par.sharded_stft(x, fl, fl, mesh))
        want = np.asarray(sp.stft(x, fl, fl, simd=True))
        np.testing.assert_allclose(got, want, atol=1e-3)
        rec = np.asarray(par.sharded_istft(got, n, fl, fl, mesh))
        wrec = np.asarray(sp.istft(want, n, fl, fl, simd=True))
        np.testing.assert_allclose(rec, wrec, atol=1e-3)

    def test_batched(self):
        from veles.simd_tpu.ops import spectral as sp

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(62)
        xb = rng.randn(3, 2048).astype(np.float32)
        got = np.asarray(par.sharded_stft(xb, 128, 32, mesh))
        want = np.asarray(sp.stft(xb, 128, 32, simd=True))
        np.testing.assert_allclose(got, want, atol=1e-3)

    def test_contracts(self):
        mesh = par.make_mesh({"sp": 8})
        x = np.zeros(4096, np.float32)
        with pytest.raises(ValueError, match="divisible"):
            par.sharded_stft(np.zeros(4095, np.float32), 256, 64, mesh)
        with pytest.raises(ValueError, match="hop"):
            par.sharded_stft(x, 256, 96, mesh)  # 512 % 96 != 0
        with pytest.raises(ValueError, match="overlap"):
            par.sharded_stft(x, 1024, 64, mesh)  # halo 960 > block 512
        with pytest.raises(ValueError, match="inconsistent"):
            par.sharded_istft(np.zeros((3, 129), np.complex64), 4096,
                              256, 64, mesh)


class TestShardedSosfilt:
    """Sequence-parallel IIR vs the single-chip cascade."""

    def test_matches_single_chip(self):
        from veles.simd_tpu.ops import iir

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(63)
        sos = iir.butterworth(4, 0.25, "lowpass")
        x = rng.randn(4096).astype(np.float32)
        got = np.asarray(par.sharded_sosfilt(sos, x, mesh))
        want = np.asarray(iir.sosfilt(sos, x, simd=True))
        scale = max(1.0, np.abs(want).max())
        np.testing.assert_allclose(got, want, atol=5e-5 * scale)

    def test_matches_oracle_bandpass(self):
        from veles.simd_tpu.ops import iir

        mesh = par.make_mesh({"dp": 2, "sp": 4})
        rng = np.random.RandomState(64)
        sos = iir.butterworth(3, (0.2, 0.5), "bandpass")
        x = rng.randn(1024).astype(np.float32)
        got = np.asarray(par.sharded_sosfilt(sos, x, mesh, axis="sp"))
        want = iir.sosfilt_na(sos, x)
        scale = max(1.0, np.abs(want).max())
        np.testing.assert_allclose(got, want, atol=5e-5 * scale)

    def test_batched(self):
        from veles.simd_tpu.ops import iir

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(65)
        sos = iir.butterworth(2, 0.3, "highpass")
        xb = rng.randn(3, 2048).astype(np.float32)
        got = np.asarray(par.sharded_sosfilt(sos, xb, mesh))
        want = iir.sosfilt_na(sos, xb)
        np.testing.assert_allclose(got, want, atol=5e-5)

    def test_state_crosses_every_boundary(self):
        """An impulse in shard 0 must ring through all later shards
        (the cross-shard state handoff, not just local scans)."""
        from veles.simd_tpu.ops import iir

        mesh = par.make_mesh({"sp": 8})
        # pole radius ~0.992: the ringing spans all 8 blocks of 128
        sos = iir.butterworth(2, 0.005, "lowpass")
        x = np.zeros(1024, np.float32)
        x[3] = 1.0
        got = np.asarray(par.sharded_sosfilt(sos, x, mesh))
        want = iir.sosfilt_na(sos, x)
        # every shard's block must carry a non-negligible response
        for s in range(8):
            blk = slice(s * 128, (s + 1) * 128)
            assert np.max(np.abs(want[blk])) > 1e-9
            np.testing.assert_allclose(got[blk], want[blk], atol=1e-5)

    def test_contracts(self):
        from veles.simd_tpu.ops import iir

        mesh = par.make_mesh({"sp": 8})
        sos = iir.butterworth(2, 0.3, "lowpass")
        with pytest.raises(ValueError, match="divisible"):
            par.sharded_sosfilt(sos, np.zeros(1001, np.float32), mesh)


class TestShardedWelch:
    def test_matches_single_chip(self):
        from veles.simd_tpu.ops import spectral as sp

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(66)
        x = rng.randn(8192).astype(np.float32)
        f1, p1 = par.sharded_welch(x, mesh, fs=100.0, nperseg=256)
        f2, p2 = sp.welch(x, fs=100.0, nperseg=256, simd=True)
        np.testing.assert_allclose(f1, f2, atol=1e-12)
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                                   atol=1e-5 * float(np.max(p2)))

    def test_tone_peak_and_overhang_mask(self):
        """A non-divisible frame layout (overhang frames masked) still
        matches; tone lands in the right bin."""
        from veles.simd_tpu.ops import spectral as sp

        mesh = par.make_mesh({"dp": 2, "sp": 4})
        fs, n = 1000.0, 4096
        t = np.arange(n) / fs
        x = np.sin(2 * np.pi * 125.0 * t).astype(np.float32)
        f1, p1 = par.sharded_welch(x, mesh, axis="sp", fs=fs,
                                   nperseg=512, noverlap=384)
        _, p2 = sp.welch(x, fs=fs, nperseg=512, noverlap=384, simd=True)
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                                   atol=1e-5 * float(np.max(p2)))
        assert abs(f1[int(np.argmax(np.asarray(p1)))] - 125.0) < fs / 512

    def test_contracts(self):
        mesh = par.make_mesh({"sp": 8})
        with pytest.raises(ValueError, match="divisible"):
            par.sharded_welch(np.zeros(4095, np.float32), mesh)


class TestShardedResample:
    @pytest.mark.parametrize("n,up,down", [
        (2048, 2, 1), (2048, 1, 4), (2352, 160, 147), (4096, 3, 2)])
    def test_matches_single_chip(self, n, up, down):
        from veles.simd_tpu.ops import resample as rs

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(67)
        x = rng.randn(n).astype(np.float32)
        got = np.asarray(par.sharded_resample_poly(x, up, down, mesh))
        want = np.asarray(rs.resample_poly(x, up, down, simd=True))
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_batched_and_2d_mesh(self):
        from veles.simd_tpu.ops import resample as rs

        mesh = par.make_mesh({"dp": 2, "sp": 4})
        rng = np.random.RandomState(68)
        xb = rng.randn(3, 1024).astype(np.float32)
        got = np.asarray(par.sharded_resample_poly(xb, 2, 1, mesh,
                                                   axis="sp"))
        want = np.asarray(rs.resample_poly(xb, 2, 1, simd=True))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_tone_preserved(self):
        """48k -> 44.1k of a tone keeps its frequency (physics check
        across the shard boundaries)."""
        mesh = par.make_mesh({"sp": 8})
        fs = 48000.0
        n = 2352 * 4
        t = np.arange(n) / fs
        x = np.sin(2 * np.pi * 997.0 * t).astype(np.float32)
        y = np.asarray(par.sharded_resample_poly(x, 160, 147, mesh))
        t2 = np.arange(len(y)) * 147 / (160 * fs)
        core = slice(400, -400)
        np.testing.assert_allclose(
            y[core], np.sin(2 * np.pi * 997.0 * t2)[core], atol=5e-3)

    def test_contracts(self):
        mesh = par.make_mesh({"sp": 8})
        with pytest.raises(ValueError, match="divisible into"):
            par.sharded_resample_poly(np.zeros(1001, np.float32), 2, 1,
                                      mesh)
        with pytest.raises(ValueError, match="ownership"):
            par.sharded_resample_poly(np.zeros(2048, np.float32), 160,
                                      147, mesh)  # 256*160 % 147 != 0

    def test_empty_signal(self):
        mesh = par.make_mesh({"sp": 8})
        with pytest.raises(ValueError, match="empty"):
            par.sharded_resample_poly(np.zeros(0, np.float32), 2, 1,
                                      mesh)


class TestSharded2DSWT:
    """Undecimated 2D SWT via the all-to-all transpose: complete
    rows/columns per pass, so every extension is exact."""

    @pytest.mark.parametrize("ext_name", ["periodic", "mirror",
                                          "constant", "zero"])
    def test_matches_single_chip_every_ext(self, ext_name):
        from veles.simd_tpu.ops import wavelet as wv

        mesh = par.make_mesh({"sp": 8})
        ext = wv.ExtensionType(ext_name)
        rng = np.random.RandomState(61)
        img = rng.randn(64, 48).astype(np.float32)
        got = par.sharded_swt_apply2d("daub", 8, 2, ext, img, mesh)
        want = wv.stationary_wavelet_apply2d("daub", 8, 2, ext, img,
                                             simd=False)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=5e-4)

    def test_contracts(self):
        mesh = par.make_mesh({"sp": 8})
        from veles.simd_tpu.ops import wavelet as wv

        with pytest.raises(ValueError, match="divisible"):
            par.sharded_swt_apply2d("daub", 8, 1,
                                    wv.ExtensionType.PERIODIC,
                                    np.zeros((60, 48), np.float32), mesh)


class TestSharded2DPackets:
    def test_leaves_match_single_chip(self):
        from veles.simd_tpu.ops import wavelet as wv

        mesh = par.make_mesh({"sp": 4, "dp": 2})
        rng = np.random.RandomState(62)
        img = rng.randn(64, 64).astype(np.float32)
        got = par.sharded_wavelet_packet_transform2d(
            "daub", 4, wv.ExtensionType.PERIODIC, img, 2, mesh,
            axis="sp")
        want = wv.wavelet_packet_transform2d(
            "daub", 4, wv.ExtensionType.PERIODIC, img, 2, simd=False)
        assert len(got) == len(want) == 16
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       atol=5e-4)

    def test_contracts(self):
        from veles.simd_tpu.ops import wavelet as wv

        mesh = par.make_mesh({"sp": 8})
        with pytest.raises(ValueError, match="divisible"):
            par.sharded_wavelet_packet_transform2d(
                "daub", 4, wv.ExtensionType.PERIODIC,
                np.zeros((48, 64), np.float32), 2, mesh)  # 48 % 32 != 0
        with pytest.raises(ValueError, match="levels"):
            par.sharded_wavelet_packet_transform2d(
                "daub", 4, wv.ExtensionType.PERIODIC,
                np.zeros((64, 64), np.float32), 0, mesh)


class TestShardedRankFilters:
    """Halo-exchange median/rank filters: the open ppermute edge IS the
    single-chip zero padding, so parity is exact."""

    @pytest.mark.parametrize("k", [3, 9, 15])
    def test_medfilt_exact(self, k):
        from veles.simd_tpu.ops import filters as fl

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(63)
        x = rng.randn(2048).astype(np.float32)
        got = np.asarray(par.sharded_medfilt(x, k, mesh))
        want = fl.medfilt_na(x, k)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_order_filter_erode(self):
        from veles.simd_tpu.ops import filters as fl

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(64)
        x = rng.randn(1024).astype(np.float32)
        got = np.asarray(par.sharded_order_filter(x, 0, 7, mesh))
        want = fl.order_filter_na(x, 0, 7)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_contracts(self):
        mesh = par.make_mesh({"sp": 8})
        with pytest.raises(ValueError, match="halo"):
            par.sharded_medfilt(np.zeros(64, np.float32), 31, mesh)
        with pytest.raises(ValueError, match="rank"):
            par.sharded_order_filter(np.zeros(64, np.float32), 9, 9,
                                     mesh)


class TestShardedSavgol:
    @pytest.mark.parametrize("mode", ["interp", "constant", "nearest"])
    @pytest.mark.parametrize("deriv", [0, 1])
    def test_matches_single_chip(self, mode, deriv):
        from veles.simd_tpu.ops import filters as fl

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(65)
        x = rng.randn(1024).astype(np.float32)
        got = np.asarray(par.sharded_savgol_filter(
            x, 11, 3, mesh, deriv=deriv, delta=0.5, mode=mode))
        want = fl.savgol_filter(x, 11, 3, deriv=deriv, delta=0.5,
                                mode=mode, simd=False)
        scale = max(1.0, np.max(np.abs(want)))
        np.testing.assert_allclose(got, want, atol=5e-4 * scale)

    def test_quadratic_reproduced_interp(self):
        """SG with polyorder >= 2 reproduces a quadratic exactly,
        including the interp edges — across shard boundaries."""
        mesh = par.make_mesh({"sp": 8})
        t = np.linspace(-1, 1, 512)
        x = (3 * t * t - 0.5 * t + 1).astype(np.float32)
        got = np.asarray(par.sharded_savgol_filter(x, 9, 2, mesh))
        np.testing.assert_allclose(got, x, atol=1e-4)

    def test_contracts(self):
        mesh = par.make_mesh({"sp": 8})
        with pytest.raises(ValueError, match="mode"):
            par.sharded_savgol_filter(np.zeros(512, np.float32), 9, 2,
                                      mesh, mode="wrap")
        with pytest.raises(ValueError, match="reach"):
            par.sharded_savgol_filter(np.zeros(64, np.float32), 15, 2,
                                      mesh, mode="interp")


class TestShardedLombScargle:
    def test_matches_oracle(self):
        from veles.simd_tpu.ops import spectral as sp

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(66)
        t = np.sort(rng.rand(1024)) * 100.0
        x = (np.sin(1.3 * t) + 0.4 * rng.randn(1024)).astype(np.float32)
        freqs = np.linspace(0.5, 3.0, 64)
        got = np.asarray(par.sharded_lombscargle(t, x, freqs, mesh))
        want = sp.lombscargle_na(t, x, freqs)
        np.testing.assert_allclose(got, want,
                                   atol=1e-3 * np.max(want))

    def test_finds_planted_tone(self):
        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(67)
        t = np.sort(rng.rand(2048)) * 200.0
        x = np.cos(2.1 * t).astype(np.float32)
        freqs = np.linspace(0.5, 4.0, 128)
        p = np.asarray(par.sharded_lombscargle(t, x, freqs, mesh))
        assert abs(freqs[np.argmax(p)] - 2.1) < 0.05

    def test_contracts(self):
        mesh = par.make_mesh({"sp": 8})
        with pytest.raises(ValueError, match="positive"):
            par.sharded_lombscargle(np.arange(64.0),
                                    np.zeros(64, np.float32),
                                    np.array([-1.0]), mesh)

    def test_indivisible_length_padded_exactly(self):
        """Any sample count works: zero-weighted padding drops out of
        every Scargle sum, so an indivisible length matches the oracle
        to the same tolerance as a divisible one (VERDICT r4 item 7)."""
        from veles.simd_tpu.ops import spectral as sp

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(68)
        n = 1021                               # prime, 1021 % 8 = 5
        t = np.sort(rng.rand(n)) * 100.0
        x = (np.sin(1.3 * t) + 0.4 * rng.randn(n)).astype(np.float32)
        freqs = np.linspace(0.5, 3.0, 64)
        got = np.asarray(par.sharded_lombscargle(t, x, freqs, mesh))
        want = sp.lombscargle_na(t, x, freqs)
        np.testing.assert_allclose(got, want, atol=1e-3 * np.max(want))

    def test_weights_channel(self):
        """Zero-weighting a block of samples equals removing them, and
        the sharded path agrees with the weighted oracle."""
        from veles.simd_tpu.ops import spectral as sp

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(69)
        n = 1024
        t = np.sort(rng.rand(n)) * 100.0
        x = (np.sin(1.3 * t) + 0.4 * rng.randn(n)).astype(np.float32)
        w = np.ones(n)
        w[100:200] = 0.0
        freqs = np.linspace(0.5, 3.0, 64)
        got = np.asarray(
            par.sharded_lombscargle(t, x, freqs, mesh, weights=w))
        want = sp.lombscargle_na(np.delete(t, np.s_[100:200]),
                                 np.delete(x, np.s_[100:200]), freqs)
        np.testing.assert_allclose(got, want, atol=1e-3 * np.max(want))
        np.testing.assert_allclose(sp.lombscargle_na(t, x, freqs, w),
                                   want, atol=1e-10 * np.max(want))


class TestShardedNormalize2d:
    def test_matches_single_chip(self):
        from veles.simd_tpu.ops import normalize as nm

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(90)
        img = rng.randint(0, 256, (64, 48)).astype(np.uint8)
        got = np.asarray(par.sharded_normalize2d(img, mesh))
        want = np.asarray(nm.normalize2D(img, simd=True))
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_indivisible_rows_and_flat_plane(self):
        from veles.simd_tpu.ops import normalize as nm

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(91)
        img = rng.randint(0, 256, (61, 33)).astype(np.uint8)  # 61 % 8 != 0
        got = np.asarray(par.sharded_normalize2d(img, mesh))
        assert got.shape == (61, 33)
        want = np.asarray(nm.normalize2D(img, simd=True))
        np.testing.assert_allclose(got, want, atol=1e-6)
        # max == min -> all zeros (the reference's rule)
        flat = np.full((16, 8), 7, np.uint8)
        np.testing.assert_array_equal(
            np.asarray(par.sharded_normalize2d(flat, mesh)),
            np.zeros((16, 8), np.float32))

    def test_flat_plane_clean_under_debug_nans(self):
        """The guarded denominator must not manufacture inf/nan on a
        flat plane — jax_debug_nans sees intermediates the final
        where() masks out of the result."""
        import jax

        mesh = par.make_mesh({"sp": 8})
        flat = np.full((16, 8), 3, np.uint8)
        jax.config.update("jax_debug_nans", True)
        try:
            got = np.asarray(par.sharded_normalize2d(flat, mesh))
        finally:
            jax.config.update("jax_debug_nans", False)
        np.testing.assert_array_equal(got,
                                      np.zeros((16, 8), np.float32))

    def test_fewer_rows_than_shards_and_float_dtype(self):
        """pad > h (wrap-padding must cover it) and a non-u8 plane
        (the single-chip op accepts any numeric dtype — review
        finding: the forced u8 cast wrecked float planes)."""
        from veles.simd_tpu.ops import normalize as nm

        mesh = par.make_mesh({"sp": 8})
        rng = np.random.RandomState(92)
        tiny = rng.randint(0, 256, (3, 12)).astype(np.uint8)  # 3 < 8
        got = np.asarray(par.sharded_normalize2d(tiny, mesh))
        want = np.asarray(nm.normalize2D(tiny, simd=True))
        assert got.shape == (3, 12)
        np.testing.assert_allclose(got, want, atol=1e-6)
        fimg = rng.randn(19, 7).astype(np.float32)
        got = np.asarray(par.sharded_normalize2d(fimg, mesh))
        want = np.asarray(nm.normalize2D(fimg, simd=True))
        np.testing.assert_allclose(got, want, atol=1e-6)
