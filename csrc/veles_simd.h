/* veles_simd.h — C API of the TPU-native veles.simd rebuild.
 *
 * Mirrors the reference's public header surface
 * (/root/reference/inc/simd/{matrix,convolve,correlate,wavelet,normalize,
 * detect_peaks,mathfun,memory}.h) so C callers of the original library can
 * source-port with minimal changes (not binary relink: handles are opaque
 * pointers instead of by-value structs, the auto-select initializers gained
 * an `algorithm` parameter, and void functions return error codes — see
 * each section).  The compute path dispatches through an embedded CPython
 * interpreter into veles.simd_tpu (JAX/XLA), per the SURVEY.md §7 target
 * architecture.  Pure-host helpers (aligned alloc, zero padding, reversed
 * copies) are implemented natively in C with no Python involvement.
 *
 * Every compute entry point keeps the reference's `int simd` flag:
 * nonzero -> the XLA backend (TPU when available), zero -> the NumPy
 * oracle twin.  All functions return 0 on success, nonzero on error
 * (the reference used assert(); a linkable library wants error codes).
 */

#ifndef VELES_SIMD_H_
#define VELES_SIMD_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- runtime ---------------------------------------------------------- */

/* Initialize the embedded interpreter + backend. Optional: every compute
 * call bootstraps lazily. `repo_root` may be NULL (auto-detect from
 * VELES_SIMD_PYROOT or the shared object's location).
 *
 * Backend-init watchdog: if the XLA backend takes longer than
 * VELES_SIMD_INIT_DEADLINE seconds to come up (default 180), the
 * process hard-exits with a diagnosis instead of hanging forever — the
 * failure mode of a wedged remote-relay transport, where the first
 * device probe blocks indefinitely in native code.  Embedded hosts that
 * prefer to own that policy (slow-but-healthy cold init, custom
 * recovery) set VELES_SIMD_INIT_DEADLINE=0 in the environment to
 * disable the watchdog, or a larger value to extend it. */
int veles_simd_init(const char *repo_root);
void veles_simd_shutdown(void);
/* Human-readable description of the active backend ("xla:tpu", "xla:cpu"). */
const char *veles_simd_backend(void);
/* Last error message (thread-unsafe convenience, like dlerror()). */
const char *veles_simd_last_error(void);

/* ---- matrix (inc/simd/matrix.h:40-89) --------------------------------- */

int matrix_add(int simd, const float *m1, const float *m2,
               size_t w, size_t h, float *res);
int matrix_sub(int simd, const float *m1, const float *m2,
               size_t w, size_t h, float *res);
int matrix_multiply(int simd, const float *m1, const float *m2,
                    size_t w1, size_t h1, size_t w2, size_t h2, float *res);
int matrix_multiply_transposed(int simd, const float *m1, const float *m2,
                               size_t w1, size_t h1, size_t w2, size_t h2,
                               float *res);

/* ---- convolve / correlate (inc/simd/convolve.h, correlate.h) ---------- */

typedef struct VelesConvolutionHandle VelesConvolutionHandle;

enum {
  VELES_CONV_ALGORITHM_AUTO = 0,
  VELES_CONV_ALGORITHM_BRUTE_FORCE = 1,
  VELES_CONV_ALGORITHM_FFT = 2,
  VELES_CONV_ALGORITHM_OVERLAP_SAVE = 3
};

/* algorithm: 0 = auto (reference convolve_initialize heuristic re-derived
 * for TPU), 1 = brute force, 2 = FFT, 3 = overlap-save. */
VelesConvolutionHandle *convolve_initialize(size_t x_length, size_t h_length,
                                            int algorithm);
int convolve(VelesConvolutionHandle *handle, const float *x, const float *h,
             float *result);
void convolve_finalize(VelesConvolutionHandle *handle);
int convolve_simd(int simd, const float *x, size_t x_length,
                  const float *h, size_t h_length, float *result);

/* Named per-algorithm entry points (inc/simd/convolve.h:58-96).  The
 * reference types ConvolutionFFTHandle / ConvolutionOverlapSaveHandle are
 * one opaque handle type here; the algorithm is pinned at initialize. */
VelesConvolutionHandle *convolve_fft_initialize(size_t x_length,
                                                size_t h_length);
int convolve_fft(VelesConvolutionHandle *handle, const float *x,
                 const float *h, float *result);
void convolve_fft_finalize(VelesConvolutionHandle *handle);
VelesConvolutionHandle *convolve_overlap_save_initialize(size_t x_length,
                                                         size_t h_length);
int convolve_overlap_save(VelesConvolutionHandle *handle, const float *x,
                          const float *h, float *result);
void convolve_overlap_save_finalize(VelesConvolutionHandle *handle);
/* Legacy alias used by the reference's doc comments
 * (inc/simd/convolve.h:123-124); same as convolve_overlap_save_initialize. */
VelesConvolutionHandle *convolve_overlap_initialize(size_t x_length,
                                                    size_t h_length);

VelesConvolutionHandle *cross_correlate_initialize(size_t x_length,
                                                   size_t h_length,
                                                   int algorithm);
int cross_correlate(VelesConvolutionHandle *handle, const float *x,
                    const float *h, float *result);
void cross_correlate_finalize(VelesConvolutionHandle *handle);
int cross_correlate_simd(int simd, const float *x, size_t x_length,
                         const float *h, size_t h_length, float *result);

/* 2D convolution / cross-correlation — no reference analog (the
 * reference filters 1D only).  result must hold
 * (n0 + k0 - 1) * (n1 + k1 - 1) floats, row-major. */
int convolve2d(int simd, const float *x, size_t n0, size_t n1,
               const float *h, size_t k0, size_t k1, float *result);
int cross_correlate2d(int simd, const float *x, size_t n0, size_t n1,
                      const float *h, size_t k0, size_t k1, float *result);
/* scipy convolve2d/correlate2d mode/boundary semantics.  mode: 0 full,
 * 1 same, 2 valid; boundary: 0 fill (with fillvalue), 1 wrap, 2 symm.
 * result sizes, per axis (m = n, k of that axis): full m+k-1, same m,
 * valid max(m,k)-min(m,k)+1.  reverse nonzero = correlation. */
int convolve2d_mb(int simd, int reverse, const float *x, size_t n0,
                  size_t n1, const float *h, size_t k0, size_t k1,
                  int mode, int boundary, float fillvalue,
                  float *result);

/* Streaming convolution — no reference analog (the reference's handles
 * are one-shot).  Chunks of fixed chunk_length arrive one at a time;
 * state is the trailing h_length-1 inputs; the concatenation of every
 * process() output plus the flush() tail equals the one-shot full
 * convolution.  reverse=1 streams cross-correlation.  result must hold
 * chunk_length floats; tail must hold h_length-1 floats.  process/flush
 * return nonzero after flush (stream is consumed). */
typedef struct VelesStreamingConvolution VelesStreamingConvolution;
VelesStreamingConvolution *streaming_convolve_initialize(
    const float *h, size_t h_length, size_t chunk_length, int reverse,
    int simd);
int streaming_convolve_process(VelesStreamingConvolution *stream,
                               const float *chunk, float *result);
int streaming_convolve_flush(VelesStreamingConvolution *stream, float *tail);
void streaming_convolve_finalize(VelesStreamingConvolution *stream);

/* Named per-algorithm entry points (inc/simd/correlate.h:57-105). */
VelesConvolutionHandle *cross_correlate_fft_initialize(size_t x_length,
                                                       size_t h_length);
int cross_correlate_fft(VelesConvolutionHandle *handle, const float *x,
                        const float *h, float *result);
void cross_correlate_fft_finalize(VelesConvolutionHandle *handle);
VelesConvolutionHandle *cross_correlate_overlap_save_initialize(
    size_t x_length, size_t h_length);
int cross_correlate_overlap_save(VelesConvolutionHandle *handle,
                                 const float *x, const float *h,
                                 float *result);
void cross_correlate_overlap_save_finalize(VelesConvolutionHandle *handle);
/* Legacy alias used by the reference's doc comments
 * (inc/simd/correlate.h:132-134); same as
 * cross_correlate_overlap_save_initialize. */
VelesConvolutionHandle *cross_correlate_overlap_initialize(size_t x_length,
                                                           size_t h_length);

/* numpy-style output windows for conv/correlation results. */
typedef enum {
  VELES_MODE_FULL = 0,
  VELES_MODE_SAME = 1,  /* max(in_len, in2_len) outputs (numpy.correlate
                           convention — differs from scipy.signal when
                           in_len < in2_len) */
  VELES_MODE_VALID = 2,
} VelesCorrMode;

/* Entries of correlation_lags(in_len, in2_len, mode): pure C. */
size_t correlation_lags_length(size_t in_len, size_t in2_len,
                               VelesCorrMode mode);
/* Lag axis for the cross-correlation output: entry i of the correlation
 * corresponds to displacement lags[i] of the second input relative to
 * the first.  lags: correlation_lags_length() entries. */
int correlation_lags(size_t in_len, size_t in2_len, VelesCorrMode mode,
                     long *lags);
/* Polynomial long division (scipy deconvolve):
 * signal = convolve(divisor, quotient) + remainder.  Float64 host-side
 * (an inherently sequential recurrence on tiny operands).  quotient:
 * sig_len - div_len + 1 entries (requires sig_len >= div_len and
 * divisor[0] != 0); remainder: sig_len entries. */
int deconvolve(const double *signal, size_t sig_len,
               const double *divisor, size_t div_len,
               double *quotient, double *remainder);

/* ---- wavelet (inc/simd/wavelet.h) ------------------------------------- */

typedef enum {
  WAVELET_TYPE_DAUBECHIES = 0,
  WAVELET_TYPE_COIFLET = 1,
  WAVELET_TYPE_SYMLET = 2
} WaveletType;

typedef enum {
  EXTENSION_TYPE_PERIODIC = 0,
  EXTENSION_TYPE_MIRROR = 1,
  EXTENSION_TYPE_CONSTANT = 2,
  EXTENSION_TYPE_ZERO = 3
} ExtensionType;

int wavelet_validate_order(WaveletType type, int order);

/* Layout helpers (inc/simd/wavelet.h:55-88).  The reference's AVX build
 * returns a duplicated shifted-copy layout from wavelet_prepare_array; XLA
 * owns device layout, so here it is a plain copy (the non-AVX reference
 * semantics) — returned buffers come from mallocf(), free() them. */
float *wavelet_prepare_array(int order, const float *src, size_t length);
float *wavelet_allocate_destination(int order, size_t source_length);
/* Splits src into four quarters for cascade reuse; pointers become NULL
 * when length is 0 or not divisible by 4 (src/wavelet.c:138-165). */
void wavelet_recycle_source(int order, float *src, size_t length,
                            float **desthihi, float **desthilo,
                            float **destlohi, float **destlolo);

/* desthi/destlo must hold length/2 floats (decimated DWT). */
int wavelet_apply(int simd, WaveletType type, int order, ExtensionType ext,
                  const float *src, size_t length,
                  float *desthi, float *destlo);
/* desthi/destlo must hold `length` floats (stationary/undecimated). */
int stationary_wavelet_apply(int simd, WaveletType type, int order, int level,
                             ExtensionType ext, const float *src,
                             size_t length, float *desthi, float *destlo);
/* Oracle twins, published as separate symbols like the reference's
 * (inc/simd/wavelet.h:45-162) — identical to passing simd=0 above. */
int wavelet_apply_na(WaveletType type, int order, ExtensionType ext,
                     const float *src, size_t length,
                     float *desthi, float *destlo);
int stationary_wavelet_apply_na(WaveletType type, int order, int level,
                                ExtensionType ext, const float *src,
                                size_t length, float *desthi, float *destlo);

/* Synthesis — no reference analog; the reference library is
 * analysis-only.  `ext` must name the extension the analysis used:
 * PERIODIC inverts exactly (scaled-orthogonal adjoint); MIRROR/CONSTANT/
 * ZERO use a least-squares boundary correction — exact for the SWT
 * (full-rank frame), least-squares for the DWT (whose fixed-size
 * non-periodic analysis is provably rank-deficient; re-analyzing the
 * reconstruction reproduces the coefficients).  wavelet_reconstruct:
 * desthi/destlo hold `length` floats each, result holds 2*length.
 * stationary_wavelet_reconstruct: all three hold `length` floats. */
int wavelet_reconstruct(int simd, WaveletType type, int order,
                        ExtensionType ext, const float *desthi,
                        const float *destlo, size_t length, float *result);
int stationary_wavelet_reconstruct(int simd, WaveletType type, int order,
                                   int level, ExtensionType ext,
                                   const float *desthi, const float *destlo,
                                   size_t length, float *result);

/* Separable 2D wavelet transforms — no reference analog (1D only).
 * wavelet_apply2d: src is [n0, n1] row-major; the four bands are each
 * [n0/2, n1/2] (DWT) or [n0, n1] (stationary).  reconstruct2d inverts
 * with band dims [m0, m1] -> result [2*m0, 2*m1] (DWT) / [m0, m1]
 * (stationary).  `ext` must match the analysis (PERIODIC exact). */
int wavelet_apply2d(int simd, WaveletType type, int order,
                    ExtensionType ext, const float *src, size_t n0,
                    size_t n1, float *ll, float *lh, float *hl, float *hh);
int wavelet_reconstruct2d(int simd, WaveletType type, int order,
                          ExtensionType ext, const float *ll,
                          const float *lh, const float *hl,
                          const float *hh, size_t m0, size_t m1,
                          float *result);
int stationary_wavelet_apply2d(int simd, WaveletType type, int order,
                               int level, ExtensionType ext,
                               const float *src, size_t n0, size_t n1,
                               float *ll, float *lh, float *hl, float *hh);
int stationary_wavelet_reconstruct2d(int simd, WaveletType type, int order,
                                     int level, ExtensionType ext,
                                     const float *ll, const float *lh,
                                     const float *hl, const float *hh,
                                     size_t m0, size_t m1, float *result);

/* Wavelet packets — full binary filter-bank tree (no reference analog;
 * the layout its wavelet_recycle_source quartering anticipates).  The
 * 2^levels leaves (hi-first natural order, each length/2^levels floats)
 * are written/read concatenated in `leaves`, which holds exactly
 * `length` floats.  length must be divisible by 2^levels. */
int wavelet_packet_transform(int simd, WaveletType type, int order,
                             ExtensionType ext, const float *src,
                             size_t length, int levels, float *leaves);
int wavelet_packet_inverse_transform(int simd, WaveletType type, int order,
                                     ExtensionType ext, const float *leaves,
                                     size_t length, int levels,
                                     float *result);
/* 2D quad-tree packets: the 4^levels leaf bands (natural
 * (ll, lh, hl, hh) order, leaf 0 = all-LL — NOTE the reverse of the 1D
 * hi-first order), each [m0/2^levels, m1/2^levels] row-major, are
 * written/read concatenated in `leaves` (exactly m0*m1 floats).  Both
 * image dims must be divisible by 2^levels. */
int wavelet_packet_transform2d(int simd, WaveletType type, int order,
                               ExtensionType ext, const float *src,
                               size_t m0, size_t m1, int levels,
                               float *leaves);
int wavelet_packet_inverse_transform2d(int simd, WaveletType type,
                                       int order, ExtensionType ext,
                                       const float *leaves, size_t m0,
                                       size_t m1, int levels,
                                       float *result);

/* ---- mathfun (inc/simd/mathfun.h:142-204) ----------------------------- */

int sin_psv(int simd, const float *src, size_t length, float *res);
int cos_psv(int simd, const float *src, size_t length, float *res);
int log_psv(int simd, const float *src, size_t length, float *res);
int exp_psv(int simd, const float *src, size_t length, float *res);
/* Beyond the reference's four (neon_mathfun.h:307,314 have these; the
 * AVX header only pow): elementwise base^exponent and sqrt. */
int pow_psv(int simd, const float *base, const float *exponent,
            size_t length, float *res);
int sqrt_psv(int simd, const float *src, size_t length, float *res);

/* ---- spectral — no reference analog (time-frequency analysis over the
 * same batched-FFT machinery as the convolve FFT path).  Complex outputs
 * are interleaved (re, im) float pairs, row-major. ----------------------- */

/* Frames a length-`length` signal yields: 0 when length < frame_length,
 * else 1 + (length - frame_length) / hop (no padding).  Pure C. */
size_t stft_frame_count(size_t length, size_t frame_length, size_t hop);
/* window: frame_length floats, or NULL for the periodic Hann window.
 * spec must hold frames * (frame_length/2 + 1) * 2 floats. */
int stft(int simd, const float *x, size_t length, size_t frame_length,
         size_t hop, const float *window, float *spec);
/* Windowed overlap-add inverse with COLA normalization; `length` is the
 * output signal length the STFT was taken over.  result: length floats. */
int istft(int simd, const float *spec, size_t length, size_t frame_length,
          size_t hop, const float *window, float *result);
/* |STFT|^2: power must hold frames * (frame_length/2 + 1) floats. */
int spectrogram(int simd, const float *x, size_t length,
                size_t frame_length, size_t hop, const float *window,
                float *power);
/* Analytic signal x + i*H[x]: analytic holds length * 2 floats. */
int hilbert(int simd, const float *x, size_t length, float *analytic);
/* Instantaneous amplitude |analytic(x)|: env holds length floats. */
int envelope(int simd, const float *x, size_t length, float *env);
/* Morlet continuous wavelet transform (center frequency w0, scales in
 * samples): result holds n_scales * length * 2 floats. */
int morlet_cwt(int simd, const float *x, size_t length,
               const double *scales, size_t n_scales, double w0,
               float *result);

/* PSD estimation layer (scipy welch/periodogram/csd/coherence
 * conventions; Hann window, constant detrend).  freqs buffers are
 * float64 of (min(nperseg, length) / 2 + 1) entries — use
 * welch_bins().  noverlap < 0 selects the nperseg/2 default. */
size_t welch_bins(size_t length, size_t nperseg);
/* Remove a linear (kind 0) or constant (kind 1) trend. */
int spectral_detrend(int simd, const float *x, size_t length, int kind,
                     float *result);
int spectral_welch(int simd, const float *x, size_t length, double fs,
                   size_t nperseg, long noverlap, double *freqs,
                   float *psd);
int spectral_periodogram(int simd, const float *x, size_t length,
                         double fs, double *freqs, float *psd);
/* pxy: interleaved (re, im) float pairs, welch_bins() entries. */
int spectral_csd(int simd, const float *x, const float *y, size_t length,
                 double fs, size_t nperseg, long noverlap, double *freqs,
                 float *pxy);
int spectral_coherence(int simd, const float *x, const float *y,
                       size_t length, double fs, size_t nperseg,
                       long noverlap, double *freqs, float *coh);

/* Chirp-Z transform (Bluestein): m z-transform samples along the
 * spiral z = a * w^-k; w = 0+0i selects the DFT default
 * exp(-2 pi i / m).  result: m interleaved (re, im) float pairs. */
int spectral_czt(int simd, const float *x, size_t length, size_t m,
                 double w_re, double w_im, double a_re, double a_im,
                 float *result);
/* Zoomed DFT over [f1, f2) at sample rate fs (endpoint-exclusive grid,
 * scipy zoom_fft): freqs holds m float64, result m (re, im) pairs. */
int spectral_zoom_fft(int simd, const float *x, size_t length, double f1,
                      double f2, size_t m, double fs, double *freqs,
                      float *result);
/* Lomb-Scargle periodogram for UNEVENLY sampled data: t float64
 * timestamps, freqs float64 positive ANGULAR frequencies; power holds
 * n_freqs floats. */
int spectral_lombscargle(int simd, const double *t, const float *x,
                         size_t length, const double *freqs,
                         size_t n_freqs, float *power);

/* ---- resample — no reference analog (rate conversion over the same
 * conv machinery as src/convolve.c; the polyphase cascade runs as one
 * dilated/strided XLA conv). ------------------------------------------- */

/* Output length of resample_poly: ceil(length * up / down).  Pure C. */
size_t resample_length(size_t length, size_t up, size_t down);
/* Rational-rate polyphase resampling.  taps: odd-length anti-aliasing
 * FIR with DC gain `up`, or NULL (num_taps ignored) for the default
 * windowed-sinc design.  result: resample_length(...) floats. */
int resample_poly(int simd, const float *x, size_t length, size_t up,
                  size_t down, const float *taps, size_t num_taps,
                  float *result);
/* Fourier-domain resampling to exactly `num` samples (periodic
 * assumption).  result: num floats. */
int resample_fourier(int simd, const float *x, size_t length, size_t num,
                     float *result);
/* The raw polyphase primitive (scipy upfirdn): zero-stuff by up, FIR
 * with h (h_len float64 taps), stride by down — no group-delay
 * centering.  Pure-C length helper; result: upfirdn_length floats. */
size_t upfirdn_length(size_t length, size_t h_len, size_t up,
                      size_t down);
int upfirdn(int simd, const double *h, size_t h_len, const float *x,
            size_t length, size_t up, size_t down, float *result);

/* ---- iir — no reference analog (recursive filtering; the recurrence
 * runs as an O(log n) associative scan on device).  SOS rows are
 * [b0 b1 b2 1 a1 a2] float64, the scipy convention. ------------------- */

typedef enum {
  VELES_IIR_LOWPASS = 0,
  VELES_IIR_HIGHPASS = 1,
  VELES_IIR_BANDPASS = 2,
  VELES_IIR_BANDSTOP = 3,
} VelesIirBandType;

/* Digital Butterworth design; cutoffs as fractions of Nyquist in (0, 1)
 * (`high` ignored for low/highpass).  Writes [n_sections][6] float64
 * rows into sos when non-NULL and returns the section count (call with
 * sos = NULL first to size the buffer); negative on error. */
int iir_butterworth(size_t order, double low, double high,
                    VelesIirBandType btype, double *sos);
/* Bessel/Thomson (maximally-flat group delay, phase norm) and
 * Chebyshev type-I (rp dB passband ripple) / type-II (rs dB stopband
 * attenuation) designs; same calling convention as iir_butterworth. */
int iir_bessel(size_t order, double low, double high,
               VelesIirBandType btype, double *sos);
int iir_cheby1(size_t order, double rp, double low, double high,
               VelesIirBandType btype, double *sos);
int iir_cheby2(size_t order, double rs, double low, double high,
               VelesIirBandType btype, double *sos);
/* Elliptic (Cauer): rp dB passband ripple AND rs dB stopband
 * attenuation — the steepest rolloff per order. */
int iir_ellip(size_t order, double rp, double rs, double low, double high,
              VelesIirBandType btype, double *sos);
/* Single-biquad notch / peak at w0 (fraction of Nyquist), -3 dB
 * bandwidth w0/Q.  sos: 1 row of 6 float64; returns 1 or negative. */
int iir_notch(double w0, double q, double *sos);
int iir_peak(double w0, double q, double *sos);
/* Minimum order meeting (gpass dB passband loss, gstop dB stopband
 * attenuation): wp/ws hold n_edges (1 or 2) band edges as Nyquist
 * fractions (pair order decides band type, scipy convention); wn_out
 * receives n_edges natural frequencies for the matching design
 * function.  Returns the order, negative on error. */
int iir_buttord(const double *wp, const double *ws, size_t n_edges,
                double gpass, double gstop, double *wn_out);
int iir_cheb1ord(const double *wp, const double *ws, size_t n_edges,
                 double gpass, double gstop, double *wn_out);
int iir_cheb2ord(const double *wp, const double *ws, size_t n_edges,
                 double gpass, double gstop, double *wn_out);
int iir_ellipord(const double *wp, const double *ws, size_t n_edges,
                 double gpass, double gstop, double *wn_out);
/* Streaming block filter: zi_inout ([n_sections][2] float64 DF2T
 * states, zeros to start) is read as the incoming state and
 * overwritten with the exit state, so consecutive calls concatenate
 * to the one-shot result within f32 round-off (length >= 2). */
int iir_sosfilt_stream(int simd, const double *sos, size_t n_sections,
                       const float *x, size_t length, double *zi_inout,
                       float *result);
/* Second-order-section cascade filter.  zi: per-section DF2T initial
 * states [n_sections][2] float64, or NULL for zero.  result: length
 * floats (in-place x == result is NOT supported). */
int iir_sosfilt(int simd, const double *sos, size_t n_sections,
                const float *x, size_t length, const double *zi,
                float *result);
/* Zero-phase forward-backward filtering (odd-extension padding;
 * padlen < 0 selects the scipy default).  result: length floats. */
int iir_sosfiltfilt(int simd, const double *sos, size_t n_sections,
                    const float *x, size_t length, long padlen,
                    float *result);
/* Settled step-response states (scipy sosfilt_zi): zi_out holds
 * n_sections * 2 float64. */
int iir_sosfilt_zi(const double *sos, size_t n_sections, double *zi_out);
/* Direct transfer-function filter y = (b/a) * x, denominator order
 * (na - 1) <= 32; use sosfilt beyond.  result: length floats. */
int iir_lfilter(int simd, const double *b, size_t nb, const double *a,
                size_t na, const float *x, size_t length, float *result);

/* ---- filters — no reference analog (nonlinear/smoothing toolkit:
 * median/rank selection runs as a static gather + lane sort on
 * device; Savitzky-Golay and firwin taps are float64 host designs). - */

/* Median filter, scipy medfilt semantics (zero-padded edges, odd
 * kernel_size).  result: length floats. */
int filt_medfilt(int simd, const float *x, size_t length,
                 size_t kernel_size, float *result);
/* Rank-order filter: rank-th smallest of each window (rank k/2 is the
 * median; 0 erodes, k-1 dilates). */
int filt_order_filter(int simd, const float *x, size_t length,
                      size_t rank, size_t kernel_size, float *result);
/* 2D median filter over a row-major [height][width] image, odd window
 * kh x kw.  result: height * width floats. */
int filt_medfilt2d(int simd, const float *img, size_t height,
                   size_t width, size_t kh, size_t kw, float *result);

typedef enum {
  VELES_SAVGOL_INTERP = 0,   /* polynomial edge fits (scipy default) */
  VELES_SAVGOL_CONSTANT = 1, /* zero-padded edges */
  VELES_SAVGOL_NEAREST = 2,  /* edge-replicated */
} VelesSavgolMode;

/* Savitzky-Golay smoothing / differentiation (scipy conventions).
 * result: length floats. */
int filt_savgol(int simd, const float *x, size_t length,
                size_t window_length, size_t polyorder, size_t deriv,
                double delta, VelesSavgolMode mode, float *result);
/* Adaptive Wiener denoise (scipy wiener): noise NAN selects the
 * mean-local-variance estimate.  result: length floats. */
int filt_wiener(int simd, const float *x, size_t length, size_t mysize,
                double noise, float *result);
/* The SG taps themselves (np.convolve orientation, scipy
 * savgol_coeffs): taps holds window_length float64. */
int filt_savgol_coeffs(size_t window_length, size_t polyorder,
                       size_t deriv, double delta, double *taps);
/* Window-method FIR design (scipy firwin): cutoffs ascending in (0,1)
 * as Nyquist fractions; window 0 = Hamming, 1 = Hann.  taps: numtaps
 * float64. */
int filt_firwin(size_t numtaps, const double *cutoffs, size_t n_cutoffs,
                int pass_zero, int window, double *taps);
/* firwin with the full VelesWindowKind range: beta feeds
 * VELES_WINDOW_KAISER and is ignored by the fixed windows. */
int filt_firwin_w(size_t numtaps, const double *cutoffs,
                  size_t n_cutoffs, int pass_zero, int window,
                  double beta, double *taps);
/* Kaiser FIR order estimate (scipy kaiserord): smallest numtaps (and
 * its beta) meeting `ripple` dB of attenuation with transition width
 * `width` as a fraction of Nyquist.  Pair with filt_firwin_w. */
int filt_kaiserord(double ripple, double width, size_t *numtaps,
                   double *beta);
/* Frequency-sampling FIR design (scipy firwin2, Type I/II): taps whose
 * magnitude response linearly interpolates the (freq, gain)
 * breakpoints, freq ascending in [0, 1] with Nyquist = 1.  nfreqs 0
 * selects the default interpolation grid; window takes VelesWindowKind
 * codes 0-4 (kaiser needs beta and is rejected here).
 * taps: numtaps float64. */
int filt_firwin2(size_t numtaps, const double *freq, const double *gain,
                 size_t n_freq, size_t nfreqs, int window, double *taps);
/* Parks-McClellan optimal equiripple FIR (scipy remez, bandpass type):
 * bands holds 2*n_bands ascending edges in [0, fs/2], desired one gain
 * per band, weight one positive weight per band or NULL for all-ones.
 * taps: numtaps float64. */
int filt_remez(size_t numtaps, const double *bands, size_t n_bands,
               const double *desired, const double *weight, double fs,
               double *taps);

/* ---- waveforms — no reference analog (scipy-convention signal
 * generators; the classic test/excitation signals a DSP library's
 * users synthesize before filtering).  Elementwise generators take the
 * time/phase array `t` and write `length` floats. ---------------------- */

typedef enum {
  VELES_CHIRP_LINEAR = 0,
  VELES_CHIRP_QUADRATIC = 1,
  VELES_CHIRP_LOGARITHMIC = 2,
  VELES_CHIRP_HYPERBOLIC = 3,
} VelesChirpMethod;

/* Frequency-swept cosine: instantaneous frequency runs f0 -> f1 over
 * [0, t1] along `method`'s law; phi is the initial phase in DEGREES
 * (scipy convention). */
int wave_chirp(int simd, const float *t, size_t length, double f0,
               double t1, double f1, VelesChirpMethod method, double phi,
               float *result);
/* Square wave of period 2*pi over phase array t: +1 for the first
 * `duty` fraction of each cycle, -1 after (0 <= duty <= 1 inclusive;
 * the degenerate endpoints give a constant signal). */
int wave_square(int simd, const float *t, size_t length, double duty,
                float *result);
/* Sawtooth/triangle of period 2*pi: rises -1 -> 1 over the first
 * `width` fraction, falls back over the rest (width=0.5 triangle). */
int wave_sawtooth(int simd, const float *t, size_t length, double width,
                  float *result);
/* Gaussian-modulated sinusoid (real part): carrier fc Hz, fractional
 * bandwidth bw measured bwr dB down the spectral envelope (bwr < 0). */
int wave_gausspulse(int simd, const float *t, size_t length, double fc,
                    double bw, double bwr, float *result);
/* Discrete delta: n zeros with a 1 at idx. */
int wave_unit_impulse(int simd, size_t n, size_t idx, float *result);
/* Maximum-length sequence (Fibonacci LFSR, scipy max_len_seq):
 * `length` bits in {0,1} into seq.  state_io: nbits bytes, the shift
 * register — all-ones start when NULL (the scipy default; final state
 * then discarded), else read and overwritten with the final state so a
 * long sequence can be generated in resumable pieces.  nbits in
 * [2, 32]; length capped at 2^22 per call (resume via state_io). */
int wave_max_len_seq(int nbits, uint8_t *state_io, size_t length,
                     uint8_t *seq);

typedef enum {
  VELES_WINDOW_HAMMING = 0,  /* same codes as filt_firwin's window */
  VELES_WINDOW_HANN = 1,
  VELES_WINDOW_BLACKMAN = 2,
  VELES_WINDOW_BARTLETT = 3,
  VELES_WINDOW_BOXCAR = 4,
  VELES_WINDOW_KAISER = 5,   /* needs beta; others ignore it */
} VelesWindowKind;

/* Symmetric analysis window by kind: n float64 into result. */
int wave_get_window(VelesWindowKind window, size_t n, double beta,
                    double *result);

/* ---- normalize (inc/simd/normalize.h:48-90) --------------------------- */

int normalize2D(int simd, const uint8_t *src, size_t src_stride,
                size_t width, size_t height, float *dst, size_t dst_stride);
int minmax2D(int simd, const uint8_t *src, size_t src_stride,
             size_t width, size_t height, uint8_t *min, uint8_t *max);
/* Normalization with precomputed extrema (inc/simd/normalize.h:66-79). */
int normalize2D_minmax(int simd, uint8_t min, uint8_t max,
                       const uint8_t *src, size_t src_stride,
                       size_t width, size_t height,
                       float *dst, size_t dst_stride);
int minmax1D(int simd, const float *src, size_t length,
             float *min, float *max);

/* ---- detect_peaks (inc/simd/detect_peaks.h:38-63) --------------------- */

typedef enum {
  kExtremumTypeMaximum = 1,
  kExtremumTypeMinimum = 2,
  kExtremumTypeBoth = 3
} ExtremumType;

typedef struct {
  int position;
  float value;
} ExtremumPoint;

/* *results is malloc()ed (free() it); NULL when no peaks found. */
int detect_peaks(int simd, const float *data, size_t size, ExtremumType type,
                 ExtremumPoint **results, size_t *results_length);

/* scipy-style peak analysis — no reference analog.  peaks: int64
 * indices (e.g. from find_peaks or the detect_peaks output). */

/* Prominence of each peak: prom_out holds n_peaks floats. */
int peak_prominences(int simd, const float *x, size_t length,
                     const int64_t *peaks, size_t n_peaks,
                     float *prom_out);
/* Width at rel_height (in [0, 1)) of each peak's prominence; all four
 * output arrays hold n_peaks floats. */
int peak_widths(int simd, const float *x, size_t length,
                const int64_t *peaks, size_t n_peaks, double rel_height,
                float *widths, float *width_heights, float *left_ips,
                float *right_ips);
/* Filtered local-maxima search (scipy find_peaks for the height /
 * threshold / distance / prominence conditions).  NaN bounds are
 * "unset"; distance 0 disables that filter.  Writes at most max_out
 * int64 indices and returns the TOTAL count (negative on error) —
 * call again with a bigger buffer if it exceeds max_out. */
long find_peaks(int simd, const float *x, size_t length,
                double height_min, double height_max,
                double threshold_min, double threshold_max,
                size_t distance, double prom_min, double prom_max,
                int64_t *peaks_out, size_t max_out);

/* ---- arithmetic conversions (inc/simd/arithmetic.h) ------------------- */

int int16_to_float(int simd, const int16_t *src, size_t length, float *dst);
int float_to_int16(int simd, const float *src, size_t length, int16_t *dst);
int int32_to_float(int simd, const int32_t *src, size_t length, float *dst);
int float_to_int32(int simd, const float *src, size_t length, int32_t *dst);
int int16_to_int32(int simd, const int16_t *src, size_t length, int32_t *dst);
/* Saturating narrow (arithmetic.h:270 packs semantics). */
int int32_to_int16(int simd, const int32_t *src, size_t length, int16_t *dst);
/* IEEE binary16 bit patterns -> float32 incl. subnormals/inf/nan
 * (arithmetic.h:92-127). */
int float16_to_float(int simd, const uint16_t *src, size_t length,
                     float *dst);

/* ---- arithmetic multiply/reduce family (inc/simd/arithmetic.h) -------- */

/* The reference publishes these as header-only inline primitives; here they
 * are linkable host-side C symbols with the same names and semantics so the
 * reference's FFT-multiply pipelines (src/convolve.c:202-219) source-port
 * directly.  Fixed-width block ops use the reference's AVX widths; `_na`
 * twins keep the reference's scalar semantics (single element / pair for
 * the block primitives — arithmetic.h:129-160).  Pure C, no Python. */

#define VELES_SIMD_FLOAT_STEP 8     /* floats per block op (AVX width)     */
#define VELES_SIMD_INT16MUL_STEP 16 /* int16 lanes per int16_multiply      */

/* res[i] = a[i] * b[i], i = 0..7 (arithmetic.h:624-630). */
void real_multiply(const float *a, const float *b, float *res);
/* Single element: *res = *a * *b (arithmetic.h:129-132). */
void real_multiply_na(const float *a, const float *b, float *res);
/* res[j] = a[j] * b[j] over the whole array (arithmetic.h:638-651). */
void real_multiply_array(const float *a, const float *b, size_t length,
                         float *res);
void real_multiply_array_na(const float *a, const float *b, size_t length,
                            float *res);
/* res[i] = array[i] * value (arithmetic.h:747-785). */
void real_multiply_scalar(const float *array, size_t length, float value,
                          float *res);
void real_multiply_scalar_na(const float *array, size_t length, float value,
                             float *res);
/* 4 interleaved complex products per call (arithmetic.h:653-672). */
void complex_multiply(const float *a, const float *b, float *res);
/* One complex product (arithmetic.h:142-150). */
void complex_multiply_na(const float *a, const float *b, float *res);
/* Conjugate-b variants (arithmetic.h:674-693, :152-160). */
void complex_multiply_conjugate(const float *a, const float *b, float *res);
void complex_multiply_conjugate_na(const float *a, const float *b,
                                   float *res);
/* Negate imaginary lanes of an interleaved array (arithmetic.h:695-740). */
void complex_conjugate(const float *array, size_t length, float *res);
void complex_conjugate_na(const float *array, size_t length, float *res);
/* Widening i16*i16 -> i32, 16 lanes (arithmetic.h:211-221). */
void int16_multiply(const int16_t *a, const int16_t *b, int32_t *res);
/* Horizontal sum (arithmetic.h:791-808). */
float sum_elements(const float *input, size_t length);
float sum_elements_na(const float *input, size_t length);
/* output[j] = input[j] + value (arithmetic.h:815-830). */
void add_to_all(const float *input, size_t length, float value,
                float *output);
void add_to_all_na(const float *input, size_t length, float value,
                   float *output);

/* ---- memory (inc/simd/memory.h:40-179) — pure C, no Python ------------ */

void *malloc_aligned(size_t size);
void *malloc_aligned_offset(size_t size, int offset);
float *mallocf(size_t length);
void memsetf(float *ptr, float value, size_t length);
/* Returns a newly mallocf()ed buffer of *new_length floats. */
float *zeropadding(const float *data, size_t length, size_t *new_length);
float *zeropaddingex(const float *data, size_t length, size_t *new_length,
                     size_t additional_length);
float *rmemcpyf(float *dest, const float *src, size_t length);
float *crmemcpyf(float *dest, const float *src, size_t length);
int next_highest_power_of_2(int value);
/* Elements from ptr to the next 64-byte boundary (inc/simd/memory.h:120-179;
 * the reference uses its 32-byte AVX alignment, this build the 64-byte host
 * staging alignment). */
int align_complement_f32(const float *ptr);
int align_complement_i16(const int16_t *ptr);
int align_complement_u16(const uint16_t *ptr);
int align_complement_i32(const int32_t *ptr);
int align_complement_u32(const uint32_t *ptr);

#ifdef __cplusplus
}
#endif

#endif /* VELES_SIMD_H_ */
