"""Exporters for telemetry snapshots: JSON, Prometheus text, human table.

All three render the JSON-native snapshot dict produced by
:func:`veles.simd_tpu.obs.snapshot` — exporters never touch live
registry state, so a snapshot taken under load serializes consistently.

* :func:`to_json` / :func:`from_json` — lossless round trip (the CI
  artifact format; ``bench.py`` embeds these in BENCH_DETAILS.json and
  ``tools/obs_report.py`` pretty-prints them back).
* :func:`to_prometheus` / :func:`parse_prometheus` — the Prometheus text
  exposition format (`metric{label="v"} value`), for scraping a serving
  process.  Counter samples get the conventional ``_total`` suffix;
  histograms emit ``_bucket``/``_sum``/``_count`` series.
* :func:`report` — a terminal table for humans.
"""

from __future__ import annotations

import json
import re

__all__ = ["to_json", "from_json", "to_prometheus", "parse_prometheus",
           "report", "flatten_counters", "histogram_quantile",
           "histogram_quantiles", "span_summary", "serving_summary",
           "render_resources", "render_caches", "PROMETHEUS_PREFIX"]

PROMETHEUS_PREFIX = "veles_simd_"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
_UNESCAPE_RE = re.compile(r"\\(.)")
_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _prom_name(name: str) -> str:
    return PROMETHEUS_PREFIX + _NAME_RE.sub("_", name)


def _escape_label_value(v) -> str:
    """Prometheus exposition escaping for label values: backslash
    FIRST (or the other escapes' backslashes double-escape), then
    quote and newline."""
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        '%s="%s"' % (_NAME_RE.sub("_", k), _escape_label_value(v))
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def to_json(snapshot: dict, indent: int | None = 2) -> str:
    """Serialize a snapshot losslessly (strict JSON, no NaN)."""
    return json.dumps(snapshot, indent=indent, allow_nan=False,
                      sort_keys=False)


def from_json(text: str) -> dict:
    """Inverse of :func:`to_json`."""
    return json.loads(text)


def to_prometheus(snapshot: dict) -> str:
    """Render counters/gauges/histograms in the Prometheus text format.

    Events are *not* exported here (Prometheus is for aggregates; the
    event log travels in the JSON snapshot).
    """
    lines = []
    for c in snapshot.get("counters", []):
        name = _prom_name(c["name"]) + "_total"
        lines.append("# TYPE %s counter" % name)
        lines.append("%s%s %d" % (name, _prom_labels(c["labels"]),
                                  c["value"]))
    for g in snapshot.get("gauges", []):
        name = _prom_name(g["name"])
        lines.append("# TYPE %s gauge" % name)
        lines.append("%s%s %s" % (name, _prom_labels(g["labels"]),
                                  repr(float(g["value"]))))
    for h in snapshot.get("histograms", []):
        name = _prom_name(h["name"])
        lines.append("# TYPE %s histogram" % name)
        acc = 0
        for le, cnt in h["buckets"].items():
            acc += cnt
            lines.append("%s_bucket%s %d" % (
                name, _prom_labels({**h["labels"], "le": le}), acc))
        lines.append("%s_sum%s %s" % (name, _prom_labels(h["labels"]),
                                      repr(float(h["sum"]))))
        lines.append("%s_count%s %d" % (name, _prom_labels(h["labels"]),
                                        h["count"]))
    for drop_key in ("events_dropped", "spans_dropped"):
        dv = snapshot.get(drop_key)
        if dv is not None:
            name = _prom_name(drop_key) + "_total"
            lines.append("# TYPE %s counter" % name)
            lines.append("%s %d" % (name, dv))
    lines += _prometheus_resources(snapshot.get("resources", []))
    lines += _prometheus_caches(snapshot.get("caches", {}))
    return "\n".join(lines) + "\n"


# per-(op, route) resource fields exported as gauges (the latest
# harvested geometry's numbers — Prometheus is for the current state,
# history lives in the JSON snapshots bench.py archives)
_RESOURCE_GAUGES = ("flops", "bytes_accessed", "arith_intensity",
                    "attainable_pct_of_roofline", "peak_bytes",
                    "argument_bytes", "output_bytes", "temp_bytes",
                    "generated_code_bytes")
_CACHE_GAUGES = ("size", "capacity", "hits", "misses", "evictions")


def _prometheus_resources(entries) -> list:
    lines = []
    for field in _RESOURCE_GAUGES:
        rows = [(e, e.get(field)) for e in entries
                if isinstance(e.get(field), (int, float))]
        if not rows:
            continue
        name = _prom_name("resource." + field)
        lines.append("# TYPE %s gauge" % name)
        for e, v in rows:
            lines.append("%s%s %s" % (
                name, _prom_labels({"op": e["op"], "route": e["route"]}),
                repr(float(v))))
    return lines


def _prometheus_caches(caches: dict) -> list:
    lines = []
    for field in _CACHE_GAUGES:
        rows = [(n, s.get(field)) for n, s in sorted(caches.items())
                if isinstance(s, dict)
                and isinstance(s.get(field), (int, float))]
        if not rows:
            continue
        name = _prom_name("cache." + field)
        lines.append("# TYPE %s gauge" % name)
        for n, v in rows:
            lines.append("%s%s %s" % (name, _prom_labels({"cache": n}),
                                      repr(float(v))))
    return lines


def parse_prometheus(text: str) -> dict:
    """Parse Prometheus text back to ``{(name, ((k, v), ...)): float}``.

    Covers the subset :func:`to_prometheus` emits — enough for the
    round-trip test and for ``tools/obs_report.py`` to diff two scrapes.
    """
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if not m:
            raise ValueError("unparseable exposition line: %r" % line)
        # single left-to-right pass: chained str.replace would misread
        # the tail of an escaped backslash followed by 'n' as a newline
        labels = tuple(
            (k, _UNESCAPE_RE.sub(
                lambda esc: "\n" if esc.group(1) == "n"
                else esc.group(1), v))
            for k, v in _LABEL_RE.findall(m.group("labels") or ""))
        out[(m.group("name"), labels)] = float(m.group("value"))
    return out


def histogram_quantile(hist: dict, q: float) -> float | None:
    """Estimate the ``q``-quantile (0..1) of one snapshot histogram.

    Prometheus ``histogram_quantile`` semantics: find the bucket the
    target rank falls in and interpolate linearly between its bounds
    (the lower bound of the first bucket is 0).  A rank landing in the
    ``+Inf`` bucket returns the highest finite bound — the honest
    answer for a fixed-bucket histogram.  Returns None for an empty
    histogram.
    """
    total = hist.get("count", 0)
    if not total:
        return None
    target = q * total
    cum = 0
    prev_le = 0.0
    for le_str, cnt in hist["buckets"].items():
        finite = le_str != "+Inf"
        le = float(le_str) if finite else float("inf")
        if cum + cnt >= target and cnt:
            if not finite:
                return prev_le
            return prev_le + (le - prev_le) * (target - cum) / cnt
        cum += cnt
        if finite:
            prev_le = le
    return prev_le


def histogram_quantiles(hist: dict, qs=(0.5, 0.95, 0.99)) -> dict:
    """``{"p50": ..., "p95": ..., "p99": ...}`` for one snapshot
    histogram (None values for an empty one)."""
    return {"p%g" % (q * 100): histogram_quantile(hist, q) for q in qs}


def span_summary(snapshot: dict) -> dict:
    """Latency summary of the ``span.*`` histograms in a snapshot:
    ``{name: {phase: {count, total_s, p50_s, p95_s, p99_s}}}`` — the
    shared shape ``bench.py`` embeds per config and
    ``tools/obs_report.py`` renders as its latency section."""
    out = {}
    for h in snapshot.get("histograms", []):
        if not h["name"].startswith("span."):
            continue
        qs = histogram_quantiles(h)
        phase = h["labels"].get("phase", "all")
        out.setdefault(h["name"][len("span."):], {})[phase] = {
            "count": h["count"], "total_s": h["sum"],
            "p50_s": qs["p50"], "p95_s": qs["p95"],
            "p99_s": qs["p99"],
        }
    return out


def serving_summary(snapshot: dict) -> dict | None:
    """The serving layer's story out of one snapshot (the Serving
    section of ``tools/obs_report.py``): queue/tenant depth gauges,
    per-status completion tallies with shed and deadline-miss rates,
    per-(op, status) request-latency quantiles, degraded-batch and
    breaker-shed counts, the latest per-class breaker states (from the
    retained ``breaker_transition`` decision events), and the
    request-axis + SLO summaries when the snapshot carries them.
    Returns None when the snapshot holds no ``serve_*`` metrics."""
    counters: dict = {}
    for c in snapshot.get("counters", []):
        name = c["name"]
        if name.startswith(("serve_", "fault_", "breaker_", "slo_")):
            counters.setdefault(name, {"total": 0, "by_label": {}})
            counters[name]["total"] += c["value"]
            key = ",".join("%s=%s" % kv
                           for kv in sorted(c["labels"].items()))
            counters[name]["by_label"][key] = c["value"]
    if not any(n.startswith("serve_") for n in counters):
        return None
    gauges = {}
    for g in snapshot.get("gauges", []):
        if g["name"].startswith(("serve_", "slo_")):
            key = g["name"]
            if g["labels"]:
                key += "{" + ",".join(
                    "%s=%s" % kv
                    for kv in sorted(g["labels"].items())) + "}"
            gauges[key] = g["value"]
    latency = {}
    for h in snapshot.get("histograms", []):
        if h["name"] != "serve.request_latency":
            continue
        op = h["labels"].get("op", "?")
        status = h["labels"].get("status", "all")
        latency[(op, status)] = {"count": h["count"],
                                 **histogram_quantiles(h)}
    submitted = counters.get("serve_submitted", {}).get("total", 0)
    completed = counters.get("serve_completed", {"by_label": {}})
    by_status: dict = {}
    for key, v in completed["by_label"].items():
        for part in key.split(","):
            if part.startswith("status="):
                status = part.split("=", 1)[1]
                by_status[status] = by_status.get(status, 0) + v
    shed = counters.get("serve_shed", {}).get("total", 0)
    misses = counters.get("serve_deadline_miss", {}).get("total", 0)
    breakers = {}
    for e in snapshot.get("events", []):
        if e.get("op") == "breaker_transition":
            breakers[(e.get("site"), e.get("key"))] = e.get("decision")
    return {
        "gauges": gauges,
        "submitted": submitted,
        "by_status": dict(sorted(by_status.items())),
        "shed": shed,
        "shed_rate": shed / submitted if submitted else None,
        "deadline_misses": misses,
        "deadline_miss_rate": (misses / submitted
                               if submitted else None),
        "degraded_batches": counters.get(
            "serve_degraded_batch", {}).get("total", 0),
        "breaker_shed": counters.get(
            "serve_breaker_shed", {}).get("total", 0),
        "latency": {"%s/%s" % k: v
                    for k, v in sorted(latency.items())},
        "breaker_states": {"%s %s" % k: v
                           for k, v in sorted(breakers.items())},
        "requests": snapshot.get("requests"),
        "slo": snapshot.get("slo"),
    }


def flatten_counters(snapshot: dict) -> dict:
    """Counters as one flat ``{"name{k=v,...}": value}`` dict — the
    compact form ``bench.py`` embeds per config and :func:`report`
    tabulates."""
    flat = {}
    for c in snapshot.get("counters", []):
        key = c["name"]
        if c["labels"]:
            key += "{" + ",".join("%s=%s" % kv
                                  for kv in sorted(c["labels"].items())) \
                + "}"
        flat[key] = c["value"]
    return flat


def _fmt_qty(v) -> str:
    """Compact engineering format for FLOP/byte counts."""
    if v is None:
        return "-"
    v = float(v)
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                          (1e3, "k")):
        if abs(v) >= scale:
            return "%.2f%s" % (v / scale, suffix)
    return "%g" % v


def render_resources(entries, indent="  ") -> list:
    """Lines for a snapshot's per-(op, route) resource entries — the
    shared renderer for :func:`report`, ``tools/obs_report.py``, and
    bench-details mode."""
    lines = []
    for e in entries:
        ai = e.get("arith_intensity")
        pct = e.get("attainable_pct_of_roofline")
        lines.append(
            "%s%-28s flops=%-8s bytes=%-8s AI=%-7s%s" % (
                indent, "%s/%s" % (e.get("op"), e.get("route")),
                _fmt_qty(e.get("flops")),
                _fmt_qty(e.get("bytes_accessed")),
                "-" if ai is None else "%.1f" % ai,
                "" if pct is None
                else " roofline<=%.0f%%" % pct))
        mem = [(k, e.get(k)) for k in ("argument_bytes", "output_bytes",
                                       "temp_bytes",
                                       "generated_code_bytes")]
        if any(v is not None for _, v in mem):
            lines.append("%s  mem: %s peak=%s" % (
                indent,
                " ".join("%s=%s" % (k.replace("_bytes", ""),
                                    _fmt_qty(v)) for k, v in mem),
                _fmt_qty(e.get("peak_bytes"))))
    return lines


def render_caches(caches: dict, indent="  ") -> list:
    """Lines for a snapshot's unified cache view (shared renderer)."""
    lines = []
    for name, s in sorted(caches.items()):
        if not isinstance(s, dict):
            continue
        cap = s.get("capacity")
        lines.append(
            "%s%-28s size=%s%s hits=%s misses=%s evictions=%s" % (
                indent, name, s.get("size", "-"),
                "" if cap is None else "/%s" % cap,
                s.get("hits", "-"), s.get("misses", "-"),
                s.get("evictions", "-")))
    return lines


def report(snapshot: dict, max_events: int = 20) -> str:
    """Human-readable table of a snapshot (newest events last)."""
    lines = ["== veles.simd_tpu telemetry =="]
    flat = flatten_counters(snapshot)
    if flat:
        lines.append("")
        lines.append("counters:")
        width = max(len(k) for k in flat)
        for k, v in sorted(flat.items()):
            lines.append("  %-*s %12d" % (width, k, v))
    if snapshot.get("gauges"):
        lines.append("")
        lines.append("gauges:")
        for g in snapshot["gauges"]:
            lines.append("  %s%s = %g" % (
                g["name"],
                _prom_labels(g["labels"]).replace('"', ""), g["value"]))
    if snapshot.get("spans_dropped"):
        lines.append("")
        lines.append("spans dropped (trace ring overflow): %d"
                     % snapshot["spans_dropped"])
    if snapshot.get("histograms"):
        lines.append("")
        lines.append("histograms (seconds):")
        for h in snapshot["histograms"]:
            mean = h["sum"] / h["count"] if h["count"] else 0.0
            qs = histogram_quantiles(h)
            lines.append(
                "  %-40s n=%-8d mean=%.3e p50=%.1e p95=%.1e "
                "p99=%.1e" % (
                    h["name"]
                    + _prom_labels(h["labels"]).replace('"', ""),
                    h["count"], mean, qs["p50"] or 0.0,
                    qs["p95"] or 0.0, qs["p99"] or 0.0))
    if snapshot.get("resources"):
        lines.append("")
        lines.append("compiled-program resources (latest geometry per "
                     "op/route; roofline<= is the attainable share "
                     "of the MXU bound at this arithmetic "
                     "intensity):")
        lines += render_resources(snapshot["resources"])
    caches = snapshot.get("caches") or {}
    if any(isinstance(s, dict) and s.get("size") for s in
           caches.values()):
        lines.append("")
        lines.append("compile caches:")
        lines += render_caches(caches)
    events = snapshot.get("events", [])
    if events:
        lines.append("")
        lines.append("decision events (last %d of %d retained, %d "
                     "dropped):" % (min(max_events, len(events)),
                                    len(events),
                                    snapshot.get("events_dropped", 0)))
        for e in events[-max_events:]:
            extras = ", ".join(
                "%s=%s" % (k, v) for k, v in e.items()
                if k not in ("seq", "op", "decision") and v is not None)
            lines.append("  #%-6d %-24s -> %-18s %s" % (
                e["seq"], e["op"], e["decision"], extras))
    if len(lines) == 1:
        lines.append("(empty)")
    return "\n".join(lines) + "\n"
