"""Coefficient-table parity against the reference-published values.

The reference tables (``/root/reference/src/{daubechies,symlets,coiflets}.c``)
are the spec (VERDICT round-1 item 3): every (family, order) this framework
exposes must agree with the published double rows.  Symlets are stored
verbatim from the published table (it is the drop-in parity contract);
Daubechies and Coiflets are derived numerically and must land on the
published values to their printed precision.

A second layer cross-checks *provenance*: the symlet root selections
recovered in ``wavelet_coeffs._SYMLET_SELECTIONS`` rebuild each published
row in exact arithmetic to within the published table's own generation
error (``tools/gen_wavelet_tables.published_drift_bound``), demonstrating
the stored rows are the least-asymmetric family members they claim to be.

Skipped wholesale when the reference checkout isn't mounted.
"""

import os
import re
import sys

import numpy as np
import pytest

from veles.simd_tpu.ops import wavelet_coeffs as wc

REFERENCE = os.environ.get("VELES_SIMD_REFERENCE", "/root/reference")

pytestmark = pytest.mark.skipif(
    not os.path.exists(os.path.join(REFERENCE, "src", "symlets.c")),
    reason="reference tables not mounted")


def _parse_table(filename, symbol):
    src = open(os.path.join(REFERENCE, "src", filename)).read()
    body = src[src.index(symbol):]
    body = body[:body.index("};\n")]
    rows = re.findall(r"\{([^{}]*)\}", body)
    return [np.array([float(v) for v in re.findall(r"[-+0-9.eE]+", r)])
            for r in rows]


@pytest.fixture(scope="module")
def ref_tables():
    return {
        wc.WaveletType.DAUBECHIES: _parse_table("daubechies.c",
                                                "kDaubechiesD"),
        wc.WaveletType.SYMLET: _parse_table("symlets.c", "kSymletsD"),
        wc.WaveletType.COIFLET: _parse_table("coiflets.c", "kCoifletsD"),
    }


def _ref_row(ref_tables, wtype, order):
    if wtype is wc.WaveletType.COIFLET:
        row = ref_tables[wtype][order // 6 - 1]
    else:
        row = ref_tables[wtype][order // 2 - 1]
    assert len(row) == order, (wtype, order, len(row))
    return row


@pytest.mark.parametrize("wtype", list(wc.WaveletType))
def test_every_order_matches_published(wtype, ref_tables):
    """VERDICT item 3: all 38 daub + 38 sym + 5 coif orders vs published."""
    for order in wc.supported_orders(wtype):
        ref = _ref_row(ref_tables, wtype, order)
        ours = wc.scaling_coefficients(wtype, order)
        if wtype is wc.WaveletType.DAUBECHIES:
            # derived; must land on the published values to their printed
            # precision (~13 significant digits)
            np.testing.assert_allclose(
                ours, ref, atol=1e-11, rtol=0,
                err_msg=f"{wtype.value}{order}")
        else:
            # symlets/coiflets are stored verbatim from the published
            # tables (their high orders carry the reference's own
            # generation error — see tools/gen_wavelet_tables.py)
            np.testing.assert_array_equal(
                ours, ref, err_msg=f"{wtype.value}{order}")


@pytest.mark.parametrize("order", [8, 16, 34, 40, 50])
def test_symlet_selection_rebuilds_published(order, ref_tables):
    """Provenance: the recovered root selection reproduces the published row
    in exact arithmetic (fast orders only; the full 38-order sweep runs in
    tools/gen_wavelet_tables.py)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "tools"))
    from gen_wavelet_tables import published_drift_bound

    ref = _ref_row(ref_tables, wc.WaveletType.SYMLET, order)
    mirror, bits = wc._SYMLET_SELECTIONS[order]
    h = wc._symlet_from_selection(order, mirror, bits) / np.sqrt(2)
    drift = float(np.max(np.abs(h - ref)))
    assert drift < published_drift_bound(order), (order, drift)


def test_symlet_selections_cover_all_orders():
    orders = set(wc.supported_orders(wc.WaveletType.SYMLET))
    assert set(wc._SYMLET_SELECTIONS) == orders - {2}
