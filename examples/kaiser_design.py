#!/usr/bin/env python
"""Attenuation-driven FIR design: spec → kaiserord → filter → verify.

The classic textbook flow, exercising the round-5 design surface:

1. ``filters.kaiserord``         sizes the filter from an attenuation
                                 spec (60 dB) and transition width,
2. ``filters.firwin``            designs it with the ``("kaiser", β)``
                                 window,
3. ``iir.frequency_response``    confirms the design meets spec,
4. ``convolve.oaconvolve``       applies it to a long two-tone signal
                                 (the tuned blocked method, by its
                                 scipy name),
5. ``spectral.welch``            (kaiser window, by name) shows the
                                 stopband tone gone from the PSD.

Run:  python examples/kaiser_design.py
      VELES_SIMD_PLATFORM=cpu python examples/kaiser_design.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu.utils.platform import maybe_override_platform

maybe_override_platform()

from veles.simd_tpu.ops import convolve as cv  # noqa: E402
from veles.simd_tpu.ops import filters as fl  # noqa: E402
from veles.simd_tpu.ops import iir  # noqa: E402
from veles.simd_tpu.ops import spectral as sp  # noqa: E402


def main():
    fs = 8000.0
    atten_db, width = 60.0, 0.05        # spec: 60 dB, 200 Hz transition
    cutoff = 0.25                        # 1 kHz passband edge (Nyquist=1)

    # 1-2. size and design
    numtaps, beta = fl.kaiserord(atten_db, width)
    taps = fl.firwin(numtaps, cutoff, window=("kaiser", beta))
    print(f"spec {atten_db:.0f} dB / width {width} -> "
          f"{numtaps} taps, beta {beta:.3f}")

    # 3. verify the magnitude response against the spec
    w, h = iir.frequency_response(taps, [1.0], n_points=2048)
    mag_db = 20 * np.log10(np.maximum(np.abs(h), 1e-12))
    stop = mag_db[w >= cutoff + width]
    print(f"worst stopband rejection: {stop.max():.1f} dB")
    assert stop.max() <= -atten_db + 1.0, stop.max()

    # 4. filter a long two-tone signal on the device
    n = 1 << 17
    t = np.arange(n) / fs
    x = (np.sin(2 * np.pi * 440.0 * t)            # passband tone
         + np.sin(2 * np.pi * 2500.0 * t)         # stopband tone
         + 0.01 * np.random.RandomState(5).randn(n)).astype(np.float32)
    y = np.asarray(cv.oaconvolve(x, taps.astype(np.float32),
                                 mode="same", simd=True))

    # 5. PSD before/after (kaiser analysis window, requested by name)
    f, p_in = sp.welch(x, fs=fs, nperseg=2048, window=("kaiser", 8.0),
                       simd=True)
    f, p_out = sp.welch(y, fs=fs, nperseg=2048, window=("kaiser", 8.0),
                        simd=True)
    p_in, p_out = np.asarray(p_in), np.asarray(p_out)
    bin_440 = np.argmin(np.abs(f - 440.0))
    bin_2500 = np.argmin(np.abs(f - 2500.0))
    keep = 10 * np.log10(p_out[bin_440] / p_in[bin_440])
    kill = 10 * np.log10(p_out[bin_2500] / p_in[bin_2500])
    print(f"440 Hz tone change: {keep:+.2f} dB (want ~0)")
    print(f"2500 Hz tone change: {kill:+.1f} dB (want <= -{atten_db:.0f})")
    assert abs(keep) < 1.0 and kill < -atten_db
    print("OK")


if __name__ == "__main__":
    main()
