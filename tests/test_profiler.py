"""Profiler + persistent compilation cache (utils/profiler.py)."""

import glob
import os

import numpy as np
import pytest

from veles.simd_tpu.utils import profiler


def test_trace_writes_artifacts(tmp_path):
    import jax.numpy as jnp

    log_dir = str(tmp_path / "trace")
    with profiler.trace(log_dir):
        with profiler.annotate("veles-test-span"):
            (jnp.arange(128.0) * 2).block_until_ready()
    hits = glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"),
                     recursive=True)
    assert hits, f"no xplane artifacts under {log_dir}"


def test_annotate_outside_trace_is_noop():
    with profiler.annotate("orphan"):
        pass


@pytest.fixture
def _restore_cache_config():
    """Snapshot/restore every jax config knob enable_compilation_cache
    mutates, so tests stay order-independent."""
    import jax

    keys = ("jax_compilation_cache_dir",
            "jax_persistent_cache_min_entry_size_bytes",
            "jax_persistent_cache_min_compile_time_secs")
    saved = {k: getattr(jax.config, k) for k in keys}
    yield
    for k, v in saved.items():
        jax.config.update(k, v)


def test_enable_compilation_cache_populates(tmp_path, _restore_cache_config):
    import jax
    import jax.numpy as jnp

    cache_dir = profiler.enable_compilation_cache(str(tmp_path / "cache"))
    # a shape unlikely to be compiled elsewhere in the suite
    x = jnp.asarray(np.random.randn(7, 131).astype(np.float32))
    jax.jit(lambda v: jnp.tanh(v) @ v.T)(x).block_until_ready()
    entries = os.listdir(cache_dir)
    assert entries, "compilation cache stayed empty"


def test_cache_dir_env_default(tmp_path, monkeypatch, _restore_cache_config):
    monkeypatch.setenv("VELES_SIMD_CACHE_DIR", str(tmp_path / "envcache"))
    assert profiler.enable_compilation_cache() == str(tmp_path / "envcache")
    assert os.path.isdir(str(tmp_path / "envcache"))
