"""Multi-host semantics with real processes (localhost, CPU backend).

Spawns N ``distributed_worker.py`` processes that join one
``jax.distributed`` runtime — actual cross-process collectives (Gloo over
localhost standing in for DCN), not a virtual mesh in one process.  This
is the closest a single box gets to multi-host: separate backends,
separate address spaces, a coordinator, and an all-reduce that crosses
them.  In-process ``hybrid_mesh`` unit tests live at the bottom of this
file; sharded-op coverage lives in ``test_parallel.py``.
"""

import os
import socket
import subprocess
import sys

import pytest

# slow tier: spawns real multi-process Gloo runtimes — excluded from `make tests-quick`
pytestmark = pytest.mark.slow

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nproc", [2])
def test_multiprocess_collectives(nproc):
    port = _free_port()
    env = dict(os.environ)
    # workers pin their own platform/devices; drop any pytest-level pin
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(nproc), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            text=True)
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {pid} rc={p.returncode}\n{out[-3000:]}")
        assert f"worker {pid}/{nproc} ok" in out


# ---- in-process hybrid_mesh unit tests (single process: process_count=1,
# 8 virtual local devices from conftest's pin) ----------------------------

def test_hybrid_mesh_single_process():
    import jax
    from veles.simd_tpu.parallel import distributed

    mesh = distributed.hybrid_mesh(dcn={"dp": 1}, ici={"sp": 2, "tp": 4})
    assert mesh.axis_names == ("dp", "sp", "tp")
    assert mesh.shape == {"dp": 1, "sp": 2, "tp": 4}
    assert mesh.devices.size == jax.local_device_count()


def test_hybrid_mesh_ici_only():
    from veles.simd_tpu.parallel import distributed

    mesh = distributed.hybrid_mesh(ici={"tp": 8})
    assert mesh.shape == {"tp": 8}


def test_hybrid_mesh_validates_sizes():
    from veles.simd_tpu.parallel import distributed

    with pytest.raises(ValueError, match="dcn"):
        distributed.hybrid_mesh(dcn={"dp": 3}, ici={"sp": 8})
    with pytest.raises(ValueError, match="ici"):
        distributed.hybrid_mesh(dcn={"dp": 1}, ici={"sp": 3})
    with pytest.raises(ValueError, match="at least one"):
        distributed.hybrid_mesh()
