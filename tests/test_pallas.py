"""Pallas filter-bank kernel: interpreter-mode cross-validation.

The compiled Mosaic path runs only on real TPU hardware (exercised by
``bench.py --check``); here the same kernel runs under the Pallas
interpreter on the CPU test platform and is cross-validated against the
NumPy oracles — the SIMD-vs-``_na`` discipline of the reference test
suite (``/root/reference/tests/wavelet.cc:224-250``) applied to the
hand-written kernel layer.
"""

import numpy as np
import pytest

from veles.simd_tpu.ops import wavelet as wv
from veles.simd_tpu.ops.pallas_kernels import filter_bank_pallas

rng = np.random.RandomState(42)


def _oracle(x_ext, filters, stride, dilation, n_out):
    outs = []
    for ch in filters:
        o = np.zeros(x_ext.shape[:-1] + (n_out,), np.float32)
        for i in range(n_out):
            for j, w in enumerate(ch):
                o[..., i] += w * x_ext[..., i * stride + j * dilation]
        outs.append(o)
    return outs


@pytest.mark.parametrize("stride,dilation", [(1, 1), (2, 1), (1, 4)])
@pytest.mark.parametrize("order", [2, 7, 8])
def test_filter_bank_matches_oracle(stride, dilation, order):
    n_out = 32
    need = (n_out - 1) * stride + (order - 1) * dilation + 1
    x_ext = rng.randn(3, need + 5).astype(np.float32)
    filters = rng.randn(2, order).astype(np.float32)
    got = filter_bank_pallas(x_ext, filters, stride, dilation, n_out,
                             interpret=True)
    want = _oracle(x_ext, filters, stride, dilation, n_out)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), w, atol=1e-4)


def test_single_channel_direct_conv_shape():
    # C=1 is the direct-convolution use: y = correlate(x_ext, h)
    x = rng.randn(4, 50).astype(np.float32)
    h = rng.randn(1, 9).astype(np.float32)
    x_ext = np.pad(x, [(0, 0), (8, 8)])
    (y,) = filter_bank_pallas(x_ext, h, 1, 1, 58, interpret=True)
    want = np.stack([np.convolve(row, h[0][::-1], mode="full") for row in x])
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-4)


def test_batch_not_multiple_of_tile():
    # 12 rows -> tile 8, pad 4: exercises _fb_call's pad-and-slice branch
    # (_tile_rows keeps full 8-sublane tiles, so rows < 9 never pad)
    from veles.simd_tpu.ops import pallas_kernels as pk
    x_ext = rng.randn(12, 40).astype(np.float32)
    f = rng.randn(2, 4).astype(np.float32)
    assert pk._tile_rows(12, 40 + 2 * 37) == 8   # guard the premise
    got = filter_bank_pallas(x_ext, f, 1, 1, 37, interpret=True)
    want = _oracle(x_ext, f, 1, 1, 37)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), w, atol=1e-4)


def test_leading_batch_dims_flattened():
    x_ext = rng.randn(2, 3, 40).astype(np.float32)
    f = rng.randn(2, 4).astype(np.float32)
    got = filter_bank_pallas(x_ext, f, 2, 1, 18, interpret=True)
    assert got[0].shape == (2, 3, 18)
    want = _oracle(x_ext, f, 2, 1, 18)
    np.testing.assert_allclose(np.asarray(got[1]), want[1], atol=1e-4)


def test_too_short_input_raises():
    x_ext = rng.randn(3, 10).astype(np.float32)
    f = rng.randn(2, 8).astype(np.float32)
    with pytest.raises(ValueError, match="too short"):
        filter_bank_pallas(x_ext, f, 2, 1, 32, interpret=True)


def test_bad_filters_shape_raises():
    x_ext = rng.randn(3, 64).astype(np.float32)
    with pytest.raises(ValueError, match="channels"):
        filter_bank_pallas(x_ext, np.zeros(8, np.float32), 1, 1, 32,
                           interpret=True)


# --------------------------------------------------------------------------
# integrated wavelet path (gate monkeypatched open; interpret auto-selects
# the CPU interpreter)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("ext", list(wv.ExtensionType))
@pytest.mark.parametrize("type,order", [("daub", 8), ("sym", 6),
                                        ("coif", 12)])
def test_wavelet_apply_pallas_vs_oracle(monkeypatch, ext, type, order):
    monkeypatch.setattr(wv, "_use_pallas", lambda *a: True)
    src = rng.randn(4, 64).astype(np.float32)
    hi, lo = wv.wavelet_apply(type, order, ext, src, simd=True)
    want_hi, want_lo = wv.wavelet_apply_na(type, order, ext, src)
    np.testing.assert_allclose(np.asarray(hi), want_hi, atol=5e-4)
    np.testing.assert_allclose(np.asarray(lo), want_lo, atol=5e-4)


@pytest.mark.parametrize("level", [1, 2, 3])
def test_swt_pallas_vs_oracle(monkeypatch, level):
    monkeypatch.setattr(wv, "_use_pallas", lambda *a: True)
    src = rng.randn(3, 64).astype(np.float32)
    hi, lo = wv.stationary_wavelet_apply(
        "daub", 4, level, wv.ExtensionType.PERIODIC, src, simd=True)
    want_hi, want_lo = wv.stationary_wavelet_apply_na(
        "daub", 4, level, wv.ExtensionType.PERIODIC, src)
    np.testing.assert_allclose(np.asarray(hi), want_hi, atol=5e-4)
    np.testing.assert_allclose(np.asarray(lo), want_lo, atol=5e-4)


def test_pallas_gate_off_on_cpu():
    # on the CPU test platform the gate must be closed by default
    assert not wv._use_pallas((512, 4096), 8, 1, 2)


def test_vmem_gate_rejects_extreme_rows(monkeypatch):
    # a row too long for a 1-row VMEM tile must stay on the XLA path
    # (pallas_available forced open to isolate the fits_vmem term)
    from veles.simd_tpu.ops import convolve as cv
    from veles.simd_tpu.ops import pallas_kernels as pk
    monkeypatch.setattr(pk, "pallas_available", lambda: True)
    assert cv._use_pallas_direct((8, 4096), 65)
    assert not cv._use_pallas_direct((8, 2_000_000), 65)
    assert wv._use_pallas((512, 4096), 8, 1, 2)
    assert not wv._use_pallas((8, 4_000_000), 8, 1, 2)


def test_runtime_taps_do_not_bake():
    # same shapes, different tap values must give different results from
    # the same compiled kernel (taps are SMEM data, not constants)
    x_ext = rng.randn(3, 40).astype(np.float32)
    f1 = rng.randn(1, 4).astype(np.float32)
    f2 = f1 + 1.0
    (y1,) = filter_bank_pallas(x_ext, f1, 1, 1, 37, interpret=True)
    (y2,) = filter_bank_pallas(x_ext, f2, 1, 1, 37, interpret=True)
    np.testing.assert_allclose(np.asarray(y1), _oracle(x_ext, f1, 1, 1, 37)[0],
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(y2), _oracle(x_ext, f2, 1, 1, 37)[0],
                               atol=1e-4)


# --------------------------------------------------------------------------
# integrated direct-convolution path (gate monkeypatched open)
# --------------------------------------------------------------------------

def test_convolve_direct_pallas_vs_oracle(monkeypatch):
    from veles.simd_tpu.ops import convolve as cv
    monkeypatch.setattr(cv, "_use_pallas_direct", lambda *a: True)
    x = rng.randn(4, 100).astype(np.float32)
    h = rng.randn(17).astype(np.float32)
    got = np.asarray(cv.convolve_simd(x, h, simd=True))
    want = cv.convolve_na(x, h)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_correlate_direct_pallas_vs_oracle(monkeypatch):
    from veles.simd_tpu.ops import convolve as cv
    from veles.simd_tpu.ops import correlate as cr
    monkeypatch.setattr(cv, "_use_pallas_direct", lambda *a: True)
    x = rng.randn(4, 100).astype(np.float32)
    h = rng.randn(17).astype(np.float32)
    got = np.asarray(cr.cross_correlate_simd(x, h, simd=True))
    want = cr.cross_correlate_na(x, h)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_brute_force_handle_routes_pallas(monkeypatch):
    from veles.simd_tpu.ops import convolve as cv
    calls = []
    orig = cv._conv_direct_pallas

    def spy(x, h, reverse=False):
        calls.append(x.shape)
        return orig(x, h, reverse=reverse)

    monkeypatch.setattr(cv, "_use_pallas_direct", lambda *a: True)
    monkeypatch.setattr(cv, "_conv_direct_pallas", spy)
    x = rng.randn(4, 64).astype(np.float32)
    h = rng.randn(9).astype(np.float32)
    handle = cv.convolve_initialize(64, 9, cv.ConvolutionAlgorithm.BRUTE_FORCE)
    got = np.asarray(cv.convolve(handle, x, h, simd=True))
    assert calls, "handle BRUTE_FORCE path did not route through pallas"
    np.testing.assert_allclose(got, cv.convolve_na(x, h), atol=1e-4)


# --------------------------------------------------------------------------
# 2D kernel (interpret mode)
# --------------------------------------------------------------------------

def test_filter_2d_matches_oracle():
    from veles.simd_tpu.ops.pallas_kernels import filter_2d_pallas
    x_ext = rng.randn(2, 12, 14).astype(np.float32)
    k = rng.randn(3, 4).astype(np.float32)
    got = np.asarray(filter_2d_pallas(x_ext, k, 10, 11, interpret=True))
    want = np.zeros((2, 10, 11), np.float32)
    for p in range(3):
        for q in range(4):
            want += k[p, q] * x_ext[:, p:p + 10, q:q + 11]
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_filter_2d_single_image():
    from veles.simd_tpu.ops import pallas_kernels as pk
    x_ext = rng.randn(6, 8).astype(np.float32)
    k = rng.randn(2, 2).astype(np.float32)
    got = np.asarray(pk.filter_2d_pallas(x_ext, k, 5, 7, interpret=True))
    want = sum(k[p, q] * x_ext[p:p + 5, q:q + 7]
               for p in range(2) for q in range(2))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_filter_2d_batch_pads_to_tile():
    from veles.simd_tpu.ops import pallas_kernels as pk
    # 20 images with a tile of 16 -> pad 12: exercises _f2d_call's
    # pad-and-unpad branch (guard the premise first)
    x_ext = rng.randn(20, 10, 12).astype(np.float32)
    k = rng.randn(3, 3).astype(np.float32)
    imgs = pk._tile_rows(20, 10 * 12 + 8 * 10)
    assert 20 % imgs != 0, imgs
    got = np.asarray(pk.filter_2d_pallas(x_ext, k, 8, 10, interpret=True))
    assert got.shape == (20, 8, 10)
    want = sum(k[p, q] * x_ext[:, p:p + 8, q:q + 10]
               for p in range(3) for q in range(3))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_filter_2d_contracts():
    from veles.simd_tpu.ops.pallas_kernels import filter_2d_pallas
    with pytest.raises(ValueError, match="kernel2d"):
        filter_2d_pallas(np.zeros((4, 4), np.float32),
                         np.zeros(3, np.float32), 2, 2, interpret=True)
    with pytest.raises(ValueError, match="too short"):
        filter_2d_pallas(np.zeros((4, 4), np.float32),
                         np.zeros((3, 3), np.float32), 4, 4, interpret=True)


def test_convolve2d_pallas_route_vs_oracle(monkeypatch):
    from veles.simd_tpu.ops import convolve2d as cv2
    monkeypatch.setattr(cv2, "_use_pallas_direct2d", lambda *a: True)
    x = rng.randn(3, 16, 20).astype(np.float32)
    h = rng.randn(4, 3).astype(np.float32)
    got = np.asarray(cv2.convolve2d(x, h, algorithm="direct", simd=True))
    np.testing.assert_allclose(got, cv2.convolve2d_na(x, h), atol=1e-3)
    got = np.asarray(cv2.cross_correlate2d(x, h, algorithm="direct",
                                           simd=True))
    np.testing.assert_allclose(got, cv2.cross_correlate2d_na(x, h),
                               atol=1e-3)


# --------------------------------------------------------------------------
# fused multi-level cascade (gate monkeypatched open; one Pallas pass
# computes every level)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("type,order,levels,n", [
    ("daub", 8, 2, 256), ("daub", 8, 3, 512), ("sym", 8, 2, 256),
    ("daub", 4, 4, 1024), ("coif", 12, 2, 512)])
def test_fused_cascade_vs_level_loop(monkeypatch, type, order, levels, n):
    from veles.simd_tpu.ops import pallas_kernels as pk

    monkeypatch.setattr(pk, "should_route", lambda *a: True)
    # the fused route is opt-in since round 5 (measured slower than the
    # level loop on hardware); the kernel itself stays correct
    monkeypatch.setenv("VELES_SIMD_FORCE_FUSED_CASCADE", "1")
    x = rng.randn(8, n).astype(np.float32)
    assert wv._use_fused_cascade(x.shape, order,
                                 wv.ExtensionType.PERIODIC, levels)
    got = wv.wavelet_transform(type, order, wv.ExtensionType.PERIODIC,
                               x, levels, simd=True)
    want, cur = [], x
    for _ in range(levels):
        hi, lo = wv.wavelet_apply_na(type, order,
                                     wv.ExtensionType.PERIODIC, cur)
        want.append(hi)
        cur = lo
    want.append(cur)
    assert len(got) == levels + 1
    for g, w in zip(got, want):
        scale = max(1.0, float(np.max(np.abs(w))))
        np.testing.assert_allclose(np.asarray(g), w,
                                   atol=5e-4 * scale)


def test_fused_cascade_gate_terms(monkeypatch):
    from veles.simd_tpu.ops import pallas_kernels as pk

    monkeypatch.setattr(pk, "should_route", lambda *a: True)
    P = wv.ExtensionType.PERIODIC
    # default OFF since round 5: the level loop measured faster on
    # hardware, so the fused route must be explicitly forced
    assert not wv._use_fused_cascade((8, 256), 8, P, 2)
    monkeypatch.setenv("VELES_SIMD_FORCE_FUSED_CASCADE", "1")
    assert wv._use_fused_cascade((8, 256), 8, P, 2)
    # non-periodic extensions keep the level loop (filtering does not
    # commute with their extension)
    assert not wv._use_fused_cascade((8, 256), 8,
                                     wv.ExtensionType.MIRROR, 2)
    assert not wv._use_fused_cascade((8, 256), 8, P, 1)   # single level
    assert not wv._use_fused_cascade((8, 250), 8, P, 2)   # n % 2^L
    assert not wv._use_fused_cascade((8, 64), 8, P, 4)    # reach >= n
    # MAC budget: deep sym16 cascade exceeds the unroll cap
    assert not wv._use_fused_cascade((8, 4096), 16, P, 4)


def test_composed_filters_match_direct_cascade():
    """The a-trous composition identity in float64: filtering with the
    composed filters equals the explicit two-level cascade."""
    gs, g_lo = wv._composed_cascade_filters("daub", 8, 2)
    hi, lo = (f.astype(np.float64) for f in wv._filters("daub", 8))
    rng_ = np.random.RandomState(9)
    x = rng_.randn(512)
    xe = np.concatenate([x, x[:64]])
    lo1 = np.array([lo @ xe[2 * i:2 * i + 8] for i in range(256)])
    lo1e = np.concatenate([lo1, lo1[:32]])
    want_hi2 = np.array([hi @ lo1e[2 * i:2 * i + 8] for i in range(128)])
    got_hi2 = np.array([gs[1] @ xe[4 * i:4 * i + len(gs[1])]
                        for i in range(128)])
    np.testing.assert_allclose(got_hi2, want_hi2, atol=1e-10)


def test_filter_bank_stacked_output_path():
    """The stacked single-buffer output branch (n_ch > 1, n_out % 128
    == 0): channel slicing of the fused [rows, C*n_out] buffer must
    match the per-channel path bit-for-bit (interpret mode)."""
    from veles.simd_tpu.ops import pallas_kernels as pk

    order, n_out, stride = 8, 128, 2
    n_ext = (n_out - 1) * stride + order
    x = rng.randn(4, n_ext).astype(np.float32)
    f = rng.randn(2, order).astype(np.float32)
    hi, lo = pk.filter_bank_pallas(x, f, stride, 1, n_out,
                                   interpret=True)
    assert hi.shape == lo.shape == (4, n_out)
    want = np.zeros((2, 4, n_out), np.float64)
    for c in range(2):
        for i in range(n_out):
            want[c, :, i] = (x[:, i * stride:i * stride + order].astype(
                np.float64) @ f[c].astype(np.float64))
    np.testing.assert_allclose(np.asarray(hi), want[0], atol=1e-4)
    np.testing.assert_allclose(np.asarray(lo), want[1], atol=1e-4)


# ---------------------------------------------------------------------------
# fused overlap-save kernel
# ---------------------------------------------------------------------------


class TestOverlapSavePallas:
    """Interpreter-mode cross-validation of the fused overlap-save
    kernel (carried-halo MXU block matmul) against the float64 oracle,
    plus the convolve routing that serves it on TPU."""

    @pytest.mark.parametrize("n,k,step", [
        (5000, 257, 256),     # headline shape class (k-1 not step mult)
        (4096, 511, 256),     # jb = 2
        (2048, 300, 256),     # k-1 > step, partial last shift
        (1000, 129, 128),     # small step
        (1537, 513, 512),     # step 512, partial tail tile
        (900, 2, 256),        # minimal halo (jb = 1, single-tap shift)
    ])
    def test_matches_oracle(self, n, k, step):
        from veles.simd_tpu.ops.pallas_kernels import overlap_save_pallas

        r = np.random.RandomState(n + k)
        x = r.randn(n).astype(np.float32)
        h = r.randn(k).astype(np.float32)
        got = np.asarray(overlap_save_pallas(x, h, step=step,
                                             interpret=True))
        want = np.convolve(x.astype(np.float64), h.astype(np.float64))
        assert got.shape == want.shape
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-5

    def test_batched_carry_restarts_per_row(self):
        # each batch row must see zero history, not the previous row's
        # tail — the t == 0 carry reset in the kernel
        from veles.simd_tpu.ops.pallas_kernels import overlap_save_pallas

        r = np.random.RandomState(3)
        x = r.randn(3, 4000).astype(np.float32)
        h = r.randn(301).astype(np.float32)
        got = np.asarray(overlap_save_pallas(x, h, interpret=True))
        want = np.stack([np.convolve(row.astype(np.float64),
                                     h.astype(np.float64)) for row in x])
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-5

    def test_rejects_bad_inputs(self):
        from veles.simd_tpu.ops import pallas_kernels as pk

        with pytest.raises(ValueError, match=">= 2 taps"):
            pk.overlap_save_pallas(np.ones(100, np.float32),
                                   np.ones(1, np.float32), interpret=True)
        with pytest.raises(ValueError, match="128-lane"):
            pk.overlap_save_pallas(np.ones(100, np.float32),
                                   np.ones(9, np.float32), step=100,
                                   interpret=True)
        with pytest.raises(ValueError, match="taps must be 1D"):
            pk.overlap_save_pallas(np.ones(100, np.float32),
                                   np.ones((2, 9), np.float32),
                                   interpret=True)

    def test_convolve_routes_through_fused_kernel(self, monkeypatch):
        # force the TPU-only gate on; on the CPU platform the kernel
        # then runs under the interpreter (interpret auto-select)
        from veles.simd_tpu.ops import convolve as cv

        monkeypatch.setattr(cv, "_use_pallas_os", lambda k: True)
        r = np.random.RandomState(11)
        x = r.randn(9000).astype(np.float32)
        h = r.randn(741).astype(np.float32)
        handle = cv.convolve_overlap_save_initialize(len(x), len(h))
        assert handle.os_matmul
        got = np.asarray(cv.convolve_overlap_save(handle, x, h, simd=True))
        want = np.convolve(x.astype(np.float64), h.astype(np.float64))
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-5

    def test_reverse_handle_correlates(self, monkeypatch):
        from veles.simd_tpu.ops import convolve as cv

        monkeypatch.setattr(cv, "_use_pallas_os", lambda k: True)
        r = np.random.RandomState(12)
        x = r.randn(6000).astype(np.float32)
        h = r.randn(401).astype(np.float32)
        handle = cv.convolve_overlap_save_initialize(len(x), len(h),
                                                     reverse=True)
        got = np.asarray(cv.convolve_overlap_save(handle, x, h, simd=True))
        want = np.convolve(x.astype(np.float64),
                           h.astype(np.float64)[::-1])
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-5

    def test_gate_respects_env_optout(self, monkeypatch):
        from veles.simd_tpu.ops import convolve as cv
        from veles.simd_tpu.ops import pallas_kernels as pk

        monkeypatch.setattr(pk, "pallas_available", lambda: True)
        monkeypatch.setenv(pk._PALLAS_OS_ENV, "1")
        assert not cv._use_pallas_os(2047)
        monkeypatch.delenv(pk._PALLAS_OS_ENV)
        assert cv._use_pallas_os(2047)
        assert not cv._use_pallas_os(64)          # below PALLAS_OS_MIN_H
        assert not cv._use_pallas_os(1 << 16)     # factors exceed VMEM

    def test_mosaic_oom_demotes_to_xla_matmul(self, monkeypatch):
        # a scoped-vmem compile failure falls back to the XLA block
        # matmul and caches the rejection; other errors propagate
        from veles.simd_tpu.ops import convolve as cv

        monkeypatch.setattr(cv, "_use_pallas_os", lambda k: True)
        monkeypatch.setattr(cv, "_PALLAS_OS_REJECTED", set())

        def boom(x, h, reverse=False, precision=None):
            raise RuntimeError(
                "Ran out of memory in memory space vmem while "
                "allocating on stack: scoped allocation 22M > 16M")

        monkeypatch.setattr(cv, "_conv_os_pallas", boom)
        r = np.random.RandomState(13)
        x = r.randn(5000).astype(np.float32)
        h = r.randn(441).astype(np.float32)
        handle = cv.convolve_overlap_save_initialize(len(x), len(h))
        got = np.asarray(cv.convolve_overlap_save(handle, x, h,
                                                  simd=True))
        want = np.convolve(x.astype(np.float64), h.astype(np.float64))
        rel = np.max(np.abs(got - want)) / np.max(np.abs(want))
        assert rel < 1e-5
        assert 441 in cv._PALLAS_OS_REJECTED
        # non-OOM failures are not swallowed
        monkeypatch.setattr(cv, "_PALLAS_OS_REJECTED", set())
        monkeypatch.setattr(
            cv, "_conv_os_pallas",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
        with pytest.raises(RuntimeError, match="boom"):
            cv.convolve_overlap_save(handle, x, h, simd=True)
