"""Vectorized transcendental functions: sin / cos / log / exp (+ pow, sqrt).

TPU-native rebuild of ``/root/reference/inc/simd/mathfun.h`` (dispatchers at
``:142-204``) and the vendored cephes-style polynomial kernels it wraps
(``avx_mathfun.h:161-729``, ``neon_mathfun.h:57-336``).  Those hand-rolled
range-reduction + polynomial evaluations are exactly what XLA's elementwise
lowering emits for the TPU VPU, so the entire L2 vendored layer is subsumed by
``jnp.sin/cos/log/exp`` (SURVEY.md §2 "⊘" components) — and fuses into
adjacent ops for free.

Naming keeps the reference's ``*_psv`` suffix ("packed single vector").
Oracle twins use NumPy's libm-backed ufuncs, matching the reference tests'
use of libm as the oracle (``tests/mathfun.cc:59-84``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from veles.simd_tpu import obs
from veles.simd_tpu.utils.config import resolve_simd

__all__ = ["sin_psv", "cos_psv", "log_psv", "exp_psv", "pow_psv", "sqrt_psv"]


def _log_f32(x):
    """Range-reduced f32 natural log, ~2 ulp on TPU.

    XLA's TPU ``log`` lowering loses ~350 ulp near 1 (measured 4.6e-5
    max-relative on U[0.1, 5]); this reimplements the cephes scheme the
    reference vendors (``avx_mathfun.h:161-245``): split x = m·2^e with
    m ∈ [√½, √2), evaluate log(m) = 2·atanh((m−1)/(m+1)) as an odd
    polynomial in s², and recombine with a two-part (Cody-Waite) ln2 so
    e·ln2_hi is exact in f32.

    Subnormal inputs return -inf: XLA flushes subnormals to zero on both
    the TPU and CPU backends (verified: ``x * 2**23`` is 0 and ``x == 0``
    is true for x = 1e-40 on both), matching ``jnp.log``'s own platform
    semantics, so no upscaling branch is attempted.
    """
    x = jnp.asarray(x, jnp.float32)
    bits = jax.lax.bitcast_convert_type(x, jnp.int32)
    e = ((bits >> 23) & 0xFF) - 126  # m in [0.5, 1)
    m = jax.lax.bitcast_convert_type(
        (bits & jnp.int32(0x007FFFFF)) | jnp.int32(0x3F000000), jnp.float32)
    low = m < jnp.float32(0.7071067811865476)
    m = jnp.where(low, m * 2, m)
    e = (e - low.astype(jnp.int32)).astype(jnp.float32)
    s = (m - 1) / (m + 1)
    z = s * s
    poly = jnp.float32(1.0 / 9.0)
    for c in (1.0 / 7.0, 1.0 / 5.0, 1.0 / 3.0, 1.0):
        poly = poly * z + jnp.float32(c)
    logm = 2 * s * poly
    ln2_hi = jnp.float32(0.693359375)  # 0x3F318000: 10 significand bits
    ln2_lo = jnp.float32(-2.12194440e-4)
    r = e * ln2_hi + (logm + e * ln2_lo)
    r = jnp.where(x == 0, -jnp.inf, r)
    r = jnp.where(jnp.isinf(x) & (x > 0), jnp.inf, r)
    r = jnp.where((x < 0) | jnp.isnan(x), jnp.nan, r)
    return r


_XLA = {
    "sin": obs.instrumented_jit(jnp.sin, op="mathfun", route="sin"),
    "cos": obs.instrumented_jit(jnp.cos, op="mathfun", route="cos"),
    "log": obs.instrumented_jit(_log_f32, op="mathfun", route="log"),
    "exp": obs.instrumented_jit(jnp.exp, op="mathfun", route="exp"),
    "sqrt": obs.instrumented_jit(jnp.sqrt, op="mathfun",
                                 route="sqrt"),
}
_POW = obs.instrumented_jit(jnp.power, op="mathfun", route="pow")

_NA = {"sin": np.sin, "cos": np.cos, "log": np.log, "exp": np.exp,
       "sqrt": np.sqrt}


def _psv(name, data, simd):
    if resolve_simd(simd, op="mathfun"):
        return _XLA[name](jnp.asarray(data, dtype=jnp.float32))
    return _NA[name](np.asarray(data, dtype=np.float32))


def sin_psv(data, simd=None):
    """``mathfun.h:142-156``."""
    return _psv("sin", data, simd)


def cos_psv(data, simd=None):
    """``mathfun.h:158-172``."""
    return _psv("cos", data, simd)


def log_psv(data, simd=None):
    """``mathfun.h:174-188``."""
    return _psv("log", data, simd)


def exp_psv(data, simd=None):
    """``mathfun.h:190-204``."""
    return _psv("exp", data, simd)


def pow_psv(base, exponent, simd=None):
    """``avx_mathfun.h:720`` / ``neon_mathfun.h:307`` pow_ps."""
    if resolve_simd(simd, op="mathfun"):
        return _POW(jnp.asarray(base, dtype=jnp.float32),
                    jnp.asarray(exponent, dtype=jnp.float32))
    return np.power(np.asarray(base, np.float32),
                    np.asarray(exponent, np.float32))


def sqrt_psv(data, simd=None):
    """``neon_mathfun.h:314`` sqrt_ps."""
    return _psv("sqrt", data, simd)


# reference-compatible oracle names (mathfun.h PsvStdFunc scalar path,
# mathfun.h:42-65) — f32 in/out like the dispatched oracle branch
def sin_psv_na(data):
    return np.sin(np.asarray(data, np.float32))


def cos_psv_na(data):
    return np.cos(np.asarray(data, np.float32))


def log_psv_na(data):
    return np.log(np.asarray(data, np.float32))


def exp_psv_na(data):
    return np.exp(np.asarray(data, np.float32))
