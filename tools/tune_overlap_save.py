#!/usr/bin/env python
"""Measure the overlap-save block-matmul step-size sweep on the device.

The reference's algorithm thresholds are hardcoded from offline
measurement (``/root/reference/src/convolve.c:328-364``); this is the
measurement tool for ours.  For each filter length it times the MXU
block-matmul overlap-save (``_conv_os_matmul``) across output-block
sizes and both precisions with chained on-device loops, checks accuracy
against a float64 oracle, and prints the winning step per (k, precision)
— the data behind ``ops/convolve.py``'s ``overlap_save_step`` and
``AUTO_*`` constants.  Rerun on new hardware generations.

Since PR 7 the sweep also emits TUNE-CACHE ENTRIES (the same
version-stamped format the online autotuner persists,
``runtime/routing.py``): per filter length it times the engine's two
``convolve.os`` candidates — the fused Pallas kernel when its gate
admits the length, and the XLA block matmul at the engine's step —
and stores the accuracy-gated winner under the engine's geometry key
with ``source="sweep"``.  A hand sweep and the online tuner build one
artifact; point ``--cache`` at the same file ``tools/autotune_pack.py``
writes (default: ``$VELES_SIMD_AUTOTUNE_CACHE`` when set, else no
emission).

Since the bf16_comp PR the sweep carries a ``--precisions`` axis
(default ``highest,high,bf16_comp``): every swept precision — XLA's
f32-emulation knobs AND the compensated-precision routes
(``runtime/precision.py``) — gets its own step table, its own
accuracy gate against the per-precision error budget, and its own
precision-keyed tune-cache entries, so a pre-warmed pack covers the
``xla_matmul_bf16_comp`` route alongside the classic ones.

Run:  python tools/tune_overlap_save.py [--quick] [--n 1048576]
          [--cache autotune_pack.json]
          [--precisions highest,high,bf16_comp]
      VELES_SIMD_PLATFORM=cpu ... works but only validates plumbing —
      step size is an MXU tiling decision, so tune on the real chip.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu.utils.platform import maybe_override_platform  # noqa: E402

# steps whose rel. error exceeds this never become winners — matches the
# TPU smoke gate for convolve (tools/tpu_smoke.py).  Precisions with a
# TIGHTER budget (runtime/precision.py ERROR_BUDGETS) gate at their
# own bound via _err_gate(); looser ones (bf16/int8, forced-only)
# still gate here.
ERR_GATE = 1e-4


def _err_gate(precision: str) -> float:
    from veles.simd_tpu.runtime import precision as prx

    return min(ERR_GATE, prx.ERROR_BUDGETS.get(precision, ERR_GATE))


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--n", type=int, default=1 << 20)
    parser.add_argument(
        "--cache",
        default=os.environ.get("VELES_SIMD_AUTOTUNE_CACHE") or None,
        help="tune-cache file to emit route winners into (default: "
             "$VELES_SIMD_AUTOTUNE_CACHE; omit to print tables only)")
    parser.add_argument(
        "--precisions", default="highest,high,bf16_comp",
        help="comma-separated precision sweep axis (XLA knobs "
             "highest/high/default and the precision-layer routes "
             "bf16_comp/bf16/int8); each emits precision-keyed "
             "tune-cache entries")
    args = parser.parse_args()
    maybe_override_platform()
    quick = args.quick
    n = args.n

    import jax
    import jax.numpy as jnp

    from veles.simd_tpu.ops import convolve as cv
    from veles.simd_tpu.runtime import precision as prx
    from veles.simd_tpu.runtime import routing
    from veles.simd_tpu.utils.benchmark import device_time_chained

    cache = routing.TuneCache(args.cache) if args.cache else None

    rng = np.random.RandomState(0)
    x_np = rng.randn(n).astype(np.float32)
    x = jnp.asarray(x_np)
    print(f"device: {jax.devices()[0]}  signal: {n}", flush=True)

    ks = (127, 2047) if quick else (127, 511, 2047, 8191)
    steps = (256, 512, 1024, 2048)
    precisions = tuple(p for p in args.precisions.split(",")
                       if p.strip())
    for p in precisions:
        if p not in prx.PRECISIONS:
            parser.error(f"unknown precision {p!r} (choose from "
                         f"{sorted(prx.PRECISIONS)})")
    winners = {}
    for k in ks:
        h_np = rng.randn(k).astype(np.float32)
        h = jnp.asarray(h_np)
        want = np.convolve(x_np.astype(np.float64), h_np.astype(np.float64))
        scale = np.max(np.abs(want))
        for prec in precisions:
            best = (float("inf"), None)
            for step in steps:
                got = np.asarray(
                    cv._conv_os_matmul(x, h, step, precision=prec),
                    np.float64)
                err = float(np.max(np.abs(got - want)) / scale)

                def stp(v, step=step, prec=prec, h=h):
                    y = cv._conv_os_matmul(v, h, step, precision=prec)
                    return v + 1e-30 * y[..., :n]

                t = device_time_chained(stp, x, iters=64, repeats=2)
                gate = _err_gate(prec)
                gated = " (fails accuracy gate)" if err > gate else ""
                print(f"k={k:5d} prec={prec:8s} step={step:5d}: "
                      f"{t * 1e3:7.3f} ms  {n / t / 1e6:7.0f} Ms/s  "
                      f"rel_err={err:.1e}{gated}", flush=True)
                if err <= gate and t < best[0]:
                    best = (t, step)
            winners[(k, prec)] = best[1]
            cur = cv.overlap_save_step(k)
            print(f"  -> k={k} {prec}: best step {best[1]} "
                  f"(overlap_save_step gives {cur})", flush=True)

        # route-level sweep -> tune-cache entries: time the engine's
        # convolve.os candidates at the engine's own step and store
        # the accuracy-gated winner in the shared autotune format —
        # one entry PER BASE PRECISION in the sweep (the tune class
        # keys Config.conv_precision, so a conv_precision='high'
        # service never consults a 'highest'-measured winner), with
        # the xla_matmul_bf16_comp precision route riding every
        # probe round it was swept in.
        if cache is None:
            continue
        step = cv.overlap_save_step(k)

        def probe(run, precision, want=want, scale=scale):
            got = np.asarray(run(x), np.float64)
            err = float(np.max(np.abs(got - want)) / scale)
            if err > _err_gate(precision):
                return None

            def stp(v):
                return v + 1e-30 * run(v)[..., :n]

            t = device_time_chained(stp, x, iters=64, repeats=2)
            # device_time_chained returns NaN for unresolvable
            # measurements; NaN must never become a winner (every
            # min() comparison against it is False) nor a JSON token
            return t * 1e6 if np.isfinite(t) else None

        base_precs = [p for p in precisions
                      if p in prx.JAX_PRECISIONS] or ["highest"]
        for base in base_precs:
            timings_us = {}
            timings_us["xla_matmul"] = probe(
                lambda v, base=base: cv._conv_os_matmul(
                    v, h, step, precision=base), base)
            if "bf16_comp" in precisions:
                timings_us["xla_matmul_bf16_comp"] = probe(
                    lambda v: cv._conv_os_matmul(
                        v, h, step, precision="bf16_comp"),
                    "bf16_comp")
            if cv._use_pallas_os(k):
                try:
                    timings_us["pallas_fused"] = probe(
                        lambda v, base=base: cv._conv_os_pallas(
                            v, h, precision=base), base)
                except Exception as e:  # noqa: BLE001 — sweep explores
                    print(f"  pallas_fused probe failed: "
                          f"{str(e)[:60]}", flush=True)
                    timings_us["pallas_fused"] = None
            measured = {r: t for r, t in timings_us.items()
                        if t is not None}
            if not measured:
                continue
            winner = min(measured, key=measured.get)
            # keys match dispatch exactly: rows=1 (the sweep times
            # single signals — batched classes need an online probe),
            # x_length pow2-bucketed, precision = the base knob the
            # dispatching service would resolve via os_precision()
            key = cache.store(
                "convolve.os",
                {"rows": 1, "x_length": routing.pow2_bucket(n),
                 "h_length": k, "step": step,
                 "precision": base},
                winner, timings_us=timings_us, source="sweep")
            print(f"  -> cache entry {key} = {winner}", flush=True)
    print("winners:", winners)
    if cache is not None:
        print(f"tune cache {args.cache}: "
              f"{len(cache.entries())} entries")


if __name__ == "__main__":
    main()
