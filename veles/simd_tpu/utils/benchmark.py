"""Device timing utilities (the framework's profiling layer).

The reference's only profiling is ``std::chrono`` around synchronous CPU
calls (``/root/reference/tests/benchmark.inc:74-107``).  On an
asynchronous accelerator runtime that pattern silently measures dispatch,
not compute — ``block_until_ready`` is not reliable through remote-relay
PJRT transports either (observed on the axon tunnel: a 3-second
convolution "completed" in 40µs).

The primary method is :func:`device_time_chained` — the workload as an
``x -> x`` step run K times inside one ``lax.fori_loop`` dispatch, per-op
time taken as the marginal between two trip counts.  It is the only
scheme that resolves sub-millisecond ops through a relay with ~66 ms
round-trip and ~2.6 ms jitter.

:func:`device_time` (pipelined host-side bursts) remains for ops that
cannot be expressed as a shape-preserving step, but is only trustworthy
when the per-op time comfortably exceeds the transport jitter — for
microsecond-scale ops its marginal is noise.
"""

from __future__ import annotations

import os
import time
import warnings

import numpy as np

__all__ = ["device_time", "device_time_chained", "host_time",
           "rms_normalize", "mxu_peak_tflops", "mxu_f32_bound_tflops",
           "mxu_int8_peak_tops",
           "conv_roofline", "stft_roofline", "rfft_flops",
           "analytical_roofline", "gemm_roofline",
           "roofline_disagreement_pct", "hbm_bw_gbps",
           "ici_bw_gbps", "xla_fft_eff_gflops", "a2a_ici_bytes",
           "ct_dft_flops", "dft_matmul_roofline",
           "MXU_PEAK_TFLOPS_BF16", "MXU_PEAK_TOPS_INT8",
           "MXU_F32_PASSES", "HBM_BW_GBPS",
           "ICI_BW_GBPS", "XLA_FFT_EFF_GFLOPS",
           "ROOFLINE_DISAGREEMENT_WARN_PCT"]


# ---------------------------------------------------------------------------
# MXU roofline accounting (the denominators BASELINE.md's % figures use)
# ---------------------------------------------------------------------------

# public TPU v5e ceiling; override with $VELES_SIMD_MXU_PEAK_TFLOPS on
# other hardware generations (the % -of-bound figures in the bench rows
# all key off this one constant)
MXU_PEAK_TFLOPS_BF16 = 197.0
# public TPU v5e int8 ceiling (TOPS) — the MXU's quantized rate is ~2x
# its bf16 rate; override with $VELES_SIMD_MXU_PEAK_TOPS_INT8
MXU_PEAK_TOPS_INT8 = 394.0
# bf16 MXU pass counts per precision knob — the denominators the
# per-precision roofline %s divide by, so a bf16_comp number is judged
# against ITS OWN ceiling instead of flattering itself against the
# 6-pass f32 bound: "highest" = 6-pass bf16 (full f32 emulation),
# "high" = 3-pass (~1.3e-5 rel err on the conv oracle), "bf16_comp" =
# 3-pass split/compensated accumulation (~5e-6 rel err,
# runtime/precision.py), "bf16"/"default" = 1 plain pass (~2.4e-3).
MXU_F32_PASSES = {"highest": 6, "high": 3, "bf16_comp": 3,
                  "bf16": 1, "default": 1}
# public TPU v5e HBM bandwidth ceiling (GB/s); override with
# $VELES_SIMD_HBM_BW_GBPS on other hardware.  Denominator of the
# analytical-roofline attainable-% figures (obs resource axis).
HBM_BW_GBPS = 819.0


def mxu_peak_tflops() -> float:
    """bf16 MXU peak in TFLOP/s (env-overridable hardware constant)."""
    return float(os.environ.get("VELES_SIMD_MXU_PEAK_TFLOPS",
                                MXU_PEAK_TFLOPS_BF16))


def mxu_f32_bound_tflops(precision: str = "highest") -> float:
    """The MXU roofline at a precision knob: bf16 peak divided by the
    bf16 pass count (32.8 TFLOP/s for 6-pass ``highest`` at the v5e
    default peak — the denominator of BASELINE.md's 69% conv figure;
    65.7 for the 3-pass ``bf16_comp`` route, 197 for plain ``bf16``).
    ``int8`` reads its own TOPS ceiling (:func:`mxu_int8_peak_tops`)
    — the quantized rate is not a bf16 pass-count multiple."""
    if precision == "int8":
        return mxu_int8_peak_tops()
    try:
        passes = MXU_F32_PASSES[precision]
    except KeyError:
        raise ValueError(
            f"precision must be one of "
            f"{sorted(MXU_F32_PASSES) + ['int8']}, got "
            f"{precision!r}") from None
    return mxu_peak_tflops() / passes


def mxu_int8_peak_tops() -> float:
    """int8 MXU peak in TOPS (env-overridable hardware constant)."""
    return float(os.environ.get("VELES_SIMD_MXU_PEAK_TOPS_INT8",
                                MXU_PEAK_TOPS_INT8))


def hbm_bw_gbps() -> float:
    """HBM bandwidth in GB/s (env-overridable hardware constant)."""
    return float(os.environ.get("VELES_SIMD_HBM_BW_GBPS", HBM_BW_GBPS))


# effective per-device ICI all-to-all bandwidth (GB/s): what one chip
# can stream into the interconnect during a tiled ``all_to_all``, the
# denominator of the sharded-DFT selector's transfer-cost term.
# Public v5e per-link figures are higher; this is the conservative
# *achieved* figure a 1D ring realizes. Override with
# $VELES_SIMD_ICI_BW_GBPS on other topologies.
ICI_BW_GBPS = 45.0

# effective single-chip throughput of XLA's 1D FFT lowering in useful
# GFLOP/s (split-radix op count / wall time) — the local-FFT side of
# the sharded-DFT cost model.  XLA's TPU FFT leaves the MXU idle
# (arXiv:2002.03260), so this is far below the matmul bound; override
# with $VELES_SIMD_FFT_EFF_GFLOPS after measuring a new backend.
XLA_FFT_EFF_GFLOPS = 180.0


def ici_bw_gbps() -> float:
    """Per-device effective ICI all-to-all bandwidth in GB/s
    (env-overridable hardware constant)."""
    return float(os.environ.get("VELES_SIMD_ICI_BW_GBPS", ICI_BW_GBPS))


def xla_fft_eff_gflops() -> float:
    """Effective useful-GFLOP/s of the local XLA FFT route
    (env-overridable measured constant)."""
    return float(os.environ.get("VELES_SIMD_FFT_EFF_GFLOPS",
                                XLA_FFT_EFF_GFLOPS))


def a2a_ici_bytes(n_elems: int, itemsize: int, n_shards: int) -> int:
    """Bytes that actually cross ICI in ONE tiled ``all_to_all`` of a
    global ``n_elems``-element array over ``n_shards`` devices: each
    device keeps 1/S of its shard and ships the rest, so the global
    payload is ``elems * itemsize * (S - 1) / S``.  The single
    accounting the sharded-DFT selector, its decision events, and the
    MULTICHIP bench rows share."""
    if n_shards <= 1:
        return 0
    return int(n_elems) * int(itemsize) * (n_shards - 1) // n_shards


def ct_dft_flops(n: int, n1: int, n2: int) -> float:
    """Useful-FLOP count of one length-``n = n1*n2`` Cooley-Tukey
    factorized matmul DFT: two dense per-factor stages (a length-n2
    DFT for each of n1 columns and vice versa, 8 real FLOPs per
    complex MAC) plus the twiddle multiply (6 FLOPs/sample) — the
    ``sharded_matmul_dft`` route's hand constant next to
    :func:`rfft_flops` for the FFT route."""
    return 8.0 * float(n) * (int(n1) + int(n2)) + 6.0 * float(n)


def dft_matmul_roofline(samples_per_s: float, n1: int, n2: int,
                        precision: str = "highest") -> dict:
    """Roofline attribution of a factorized matmul-DFT sample rate
    against the f32 MXU bound — same dict shape as
    :func:`conv_roofline` so bench rows embed it verbatim."""
    n = int(n1) * int(n2)
    bound = mxu_f32_bound_tflops(precision)
    eff = ct_dft_flops(n, n1, n2) / n * samples_per_s / 1e12
    return {"tflops_effective": eff,
            "roofline_bound_tflops": bound,
            "pct_of_roofline": 100.0 * eff / bound,
            "flops_per_sample": ct_dft_flops(n, n1, n2) / n,
            "precision": precision}


def analytical_roofline(flops: float, t_seconds: float,
                        precision: str = "highest") -> dict:
    """Roofline attribution from XLA's OWN cost model: effective
    TFLOP/s of ``flops`` (``compiled.cost_analysis()['flops']`` — the
    compiled program's count, redundant MACs included) executed in
    ``t_seconds``, against the f32 MXU bound at ``precision``.

    The *analytical* twin of :func:`conv_roofline` (whose FLOP count
    is the hand-maintained useful-work constant): printing the two
    side by side, with a warning when they disagree by more than
    ``ROOFLINE_DISAGREEMENT_WARN_PCT``, is the drift detector for the
    hand-coded constants — the obs-v3 acceptance contract.
    """
    bound = mxu_f32_bound_tflops(precision)
    eff = float(flops) / float(t_seconds) / 1e12
    return {"tflops_analytical": eff,
            "roofline_bound_tflops": bound,
            "analytical_pct_of_roofline": 100.0 * eff / bound,
            "xla_flops": float(flops),
            "precision": precision}


# analytical-vs-measured disagreement above this % is worth a warning:
# the hand-coded FLOP constants (or the route attribution) drifted
ROOFLINE_DISAGREEMENT_WARN_PCT = 15.0


def roofline_disagreement_pct(measured_pct: float,
                              analytical_pct: float) -> float:
    """Relative disagreement (%) between the measured and analytical
    roofline figures, normalized by the measured one."""
    if not measured_pct:
        return float("inf") if analytical_pct else 0.0
    return 100.0 * abs(analytical_pct - measured_pct) / abs(
        measured_pct)


def gemm_roofline(flops: float, t_seconds: float,
                  precision: str = "highest") -> dict:
    """Roofline attribution of one GEMM: ``flops`` (the 2mnk useful
    count) in ``t_seconds`` against the MXU bound at ``precision`` —
    the per-precision honesty contract (a ``bf16_comp`` rate divides
    by the 3-pass bound, never the 6-pass f32 one).  Same dict shape
    as :func:`conv_roofline` so bench rows embed it verbatim."""
    bound = mxu_f32_bound_tflops(precision)
    eff = float(flops) / float(t_seconds) / 1e12
    return {"tflops_effective": eff,
            "roofline_bound_tflops": bound,
            "pct_of_roofline": 100.0 * eff / bound,
            "precision": precision}


def conv_roofline(samples_per_s: float, h_length: int,
                  precision: str = "highest") -> dict:
    """Roofline attribution of a 1D-convolution rate: effective
    TFLOP/s (2·h useful FLOPs per output sample — the convolution's
    own work, NOT the blocked algorithm's redundant MACs) and the % of
    the f32 MXU bound at the given precision knob.  Returns a dict so
    bench rows can embed it verbatim."""
    bound = mxu_f32_bound_tflops(precision)
    eff = 2.0 * int(h_length) * samples_per_s / 1e12
    return {"tflops_effective": eff,
            "roofline_bound_tflops": bound,
            "pct_of_roofline": 100.0 * eff / bound,
            "precision": precision}


def rfft_flops(n: int) -> float:
    """Split-radix real-FFT op-count estimate ``2.5 n log2 n`` — the
    ``xla_fft`` spectral route's useful-work constant, the measured-%
    denominator next to the matmul-DFT route's dense count below."""
    import math

    n = int(n)
    return 2.5 * n * math.log2(n)


def stft_roofline(frames_per_s: float, frame_length: int,
                  precision: str = "highest",
                  route: str = "rdft_matmul") -> dict:
    """Roofline attribution of an STFT frame rate.

    The useful-FLOP constant is per route — the drift-detector
    contract (``analytical_roofline`` vs these hand constants) only
    means something when the constant matches the formulation actually
    run:

    * matmul-DFT routes (``rdft_matmul`` / ``pallas_fused``): the two
      dense ``[*, L] x [L, bins]`` cos/sin dots, ``4 * L * bins``
      FLOPs per frame (basis-padding lanes excluded);
    * ``xla_fft``: the split-radix real-FFT estimate
      :func:`rfft_flops` (window multiply is noise next to it).

    Returns the same dict shape as :func:`conv_roofline` so bench rows
    embed it verbatim."""
    L = int(frame_length)
    if route in ("rdft_matmul", "pallas_fused"):
        flops_per_frame = 4.0 * L * (L // 2 + 1)
    elif route == "xla_fft":
        flops_per_frame = rfft_flops(L)
    else:
        raise ValueError(f"unknown stft route {route!r}")
    bound = mxu_f32_bound_tflops(precision)
    eff = flops_per_frame * frames_per_s / 1e12
    return {"tflops_effective": eff,
            "roofline_bound_tflops": bound,
            "pct_of_roofline": 100.0 * eff / bound,
            "flops_per_frame": flops_per_frame,
            "route": route,
            "precision": precision}


def rms_normalize(p, eps: float = 1e-30):
    """RMS-normalize a jax array — the standard way to keep a chained
    GEMM/gemv step bounded over hundreds of iterations (the reduction is
    negligible next to the matmul it stabilizes)."""
    import jax.numpy as jnp

    return p / (jnp.sqrt(jnp.mean(p * p)) + eps)


def _sync(out):
    """Force completion of `out` (any jax array / pytree of them).

    Empty pytrees (``None``, ``{}``, ``[]``) and non-array leaves
    (Python scalars, strings, host metadata riding along in a result
    dict) have nothing to wait on — they are skipped rather than
    crashing the timer; the sync targets the LAST array leaf, which on
    a single-stream device orders after everything before it."""
    import jax

    leaves = [leaf for leaf in jax.tree.leaves(out)
              if hasattr(leaf, "ravel")]
    if not leaves:
        return
    np.asarray(leaves[-1].ravel()[-1:])


def device_time(fn, *, burst: int = 8, repeats: int = 3,
                warmup: int = 2) -> float:
    """Marginal per-call device time of ``fn`` (which must return a jax
    array or pytree of them)."""
    for _ in range(warmup):
        _sync(fn())

    def burst_time(k):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = None
            for _ in range(k):
                out = fn()
            _sync(out)
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = burst_time(1)
    tk = burst_time(burst)
    per_op = (tk - t1) / (burst - 1)
    # degenerate case (dispatch-dominated tiny op): fall back to t1
    return max(per_op, 1e-9) if per_op > 0 else t1


def device_time_chained(step, x0, *, iters: int = 256, base: int = 8,
                        repeats: int = 3, min_window: float = 0.04,
                        max_iters: int = 1 << 15) -> float:
    """Per-iteration device time of ``step`` (an ``x -> x`` function),
    measured by running it inside a single-dispatch ``lax.fori_loop``.

    Host-burst timing (:func:`device_time`) degenerates when the per-op
    time is below the relay's round-trip jitter (~2.6 ms observed): up to
    ~8 dispatched ops hide entirely inside the ~66 ms fixed RTT, so the
    marginal estimate is noise.  Chaining the op on-device removes host
    dispatch from the measurement entirely: one jit call runs the loop
    ``k`` times with a data dependency between iterations (single-stream
    TPU execution serializes them), and the marginal time between two
    trip counts cancels the RTT, transfer, and loop-setup overhead:

        per_op = (T(k) - T(base)) / (k - base)

    ``k`` starts at ``iters`` and quadruples until the marginal window
    ``T(k) - T(base)`` clears ``min_window`` (default 40 ms ≈ 15x the
    observed RTT jitter), so microsecond-scale ops get the trip count
    they need automatically.  The trip count is a traced scalar, so every
    measurement shares one compiled executable.

    Two caveats, deliberate:

    * ``step`` must not be an affine map with constant coefficients
      (e.g. ``v + 1``) — XLA reduces such loops and the timing reflects
      the reduced program;
    * loop-invariant operands that fit in VMEM stay resident across
      iterations, so bandwidth-bound steps report *steady-state* rates
      that can exceed cold HBM bandwidth.  This is real, reproducible
      device behavior, not a timing artifact.

    ``step`` must preserve shape/dtype and keep values bounded (it is
    applied up to ``max_iters`` times).
    """
    import jax
    from jax import lax

    @jax.jit
    def runk(x, k):
        return lax.fori_loop(0, k, lambda i, v: step(v), x)

    def timed(k):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            _sync(runk(x0, k))
            best = min(best, time.perf_counter() - t0)
        return best

    _sync(runk(x0, base))  # compile + warm
    tb = timed(base)
    k = max(iters, base * 2)
    while True:
        tk = timed(k)
        if tk - tb >= min_window or k >= max_iters:
            if tk - tb < min_window:
                # an unresolvable measurement must not masquerade as a
                # plausible number — return NaN (callers' derived rates
                # turn NaN too) alongside the warning
                warnings.warn(
                    f"device_time_chained: marginal window {tk - tb:.4f}s "
                    f"below {min_window}s at max_iters={max_iters}; the "
                    "estimate is transport-jitter noise (step too fast, "
                    "or reduced by XLA — see docstring caveats); "
                    "returning NaN", RuntimeWarning, stacklevel=2)
                return float("nan")
            return max((tk - tb) / (k - base), 1e-9)
        k = min(k * 4, max_iters)


def host_time(fn, *, repeats: int = 3, warmup: int = 1) -> float:
    """Best-of-N wall time for a synchronous host function."""
    for _ in range(warmup):
        fn()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best
