"""The AOT artifact store (veles/simd_tpu/runtime/artifacts.py).

Pins the zero-warmup subsystem's contracts: round-trip parity (a
loaded executable computes exactly what the fresh compile computes),
stale-stamp refusal (schema / jax version / device / device-count
mismatches are a MISS — a wrong-runtime program is never loaded),
corrupt-file and torn-payload degradation (counters, never crashes),
readonly-mode write refusal, the instrumented_jit load-before-compile
counters and ``artifact`` decision events, serve preload end-to-end
(the first request after a preload runs packed executables — zero
persistent-cache misses), and the profiler shim's delegation with the
``compile.cache_*`` bridge verified against a warm load.
"""

import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from veles.simd_tpu import obs  # noqa: E402
from veles.simd_tpu.runtime import artifacts as art  # noqa: E402


@pytest.fixture(autouse=True)
def _telemetry():
    obs.enable()
    obs.reset()
    yield
    obs.reset()


def _core(x, w):
    # module-level, closure-free: self-identifies to the store via
    # qualname + bytecode digest
    return jnp.tanh(x @ w) * 2.0 + 0.5


def _operands(n=32, m=16, k=8, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(n, m).astype(np.float32)),
            jnp.asarray(rng.randn(m, k).astype(np.float32)))


def _fresh_wrapper(op="artifact_test", route="r"):
    return obs.instrumented_jit(_core, op=op, route=route)


def _drive_on(store_dir):
    """One export drive: dispatch under mode=on so the store fills."""
    x, w = _operands()
    with art.private_artifact_store(store_dir) as st:
        with art.artifacts_mode_override("on"):
            y = np.asarray(_fresh_wrapper()(x, w))
    return y, st.info()


# ---------------------------------------------------------------------------
# round trip + keys
# ---------------------------------------------------------------------------


def test_roundtrip_parity_vs_fresh_compile(tmp_path):
    d = str(tmp_path / "pack")
    y_fresh, info = _drive_on(d)
    assert info["stores"] == 1 and info["misses"] == 1
    x, w = _operands()
    with art.private_artifact_store(d):
        with art.artifacts_mode_override("readonly"):
            wrapper = _fresh_wrapper()
            y_loaded = np.asarray(wrapper(x, w))
            st_info = art.store().info()
    assert st_info["hits"] == 1 and st_info["stale"] == 0
    np.testing.assert_array_equal(y_fresh, y_loaded)
    assert obs.counter_value("artifact_hit", op="artifact_test",
                             route="r") == 1
    events = [e for e in obs.events() if e["op"] == "artifact"]
    assert any(e["decision"] == "hit" for e in events)


def test_distinct_geometries_distinct_entries(tmp_path):
    d = str(tmp_path / "pack")
    with art.private_artifact_store(d) as st:
        with art.artifacts_mode_override("on"):
            w1 = _fresh_wrapper()
            w1(*_operands(n=32))
            w1(*_operands(n=64))
        assert st.info()["size"] == 2
        assert len(st.keys()) == 2


def test_closure_without_key_never_touches_store(tmp_path):
    d = str(tmp_path / "pack")
    taps = 3.0

    def closed(x, w):
        return _core(x, w) * taps

    with art.private_artifact_store(d) as st:
        with art.artifacts_mode_override("on"):
            obs.instrumented_jit(closed, op="cl")(*_operands())
        assert st.info()["size"] == 0
        assert st.info()["misses"] == 0


def test_artifact_key_separates_identical_shapes(tmp_path):
    """Two closures baking different params over identical call
    geometry: the explicit artifact_key (the handle-LRU discipline)
    keeps their packed executables apart — and each loads back its
    OWN program."""
    d = str(tmp_path / "pack")

    def make(scale):
        def fn(x, w):
            return _core(x, w) * scale
        return fn

    x, w = _operands()
    with art.private_artifact_store(d) as st:
        with art.artifacts_mode_override("on"):
            y2 = np.asarray(obs.instrumented_jit(
                make(2.0), op="k", artifact_key="scale=2")(x, w))
            y5 = np.asarray(obs.instrumented_jit(
                make(5.0), op="k", artifact_key="scale=5")(x, w))
        assert st.info()["size"] == 2
        with art.artifacts_mode_override("readonly"):
            l2 = np.asarray(obs.instrumented_jit(
                make(2.0), op="k", artifact_key="scale=2")(x, w))
            l5 = np.asarray(obs.instrumented_jit(
                make(5.0), op="k", artifact_key="scale=5")(x, w))
        assert st.info()["hits"] == 2
    np.testing.assert_array_equal(y2, l2)
    np.testing.assert_array_equal(y5, l5)
    assert not np.allclose(l2, l5)


def test_static_and_donating_wrappers_excluded():
    fn_static = obs.instrumented_jit(lambda x, n: x * n,
                                     static_argnames=("n",))
    assert fn_static._artifact_ident is None
    fn_donate = obs.instrumented_jit(_core, donate_argnums=(0,),
                                     artifact_key="k")
    assert fn_donate._artifact_ident is None


# ---------------------------------------------------------------------------
# stale stamps: never loaded, always counted
# ---------------------------------------------------------------------------


def _edit_manifest(d, mutate):
    path = os.path.join(d, art.MANIFEST_NAME)
    with open(path) as f:
        data = json.load(f)
    mutate(data)
    with open(path, "w") as f:
        json.dump(data, f)


@pytest.mark.parametrize("mutate, reason", [
    (lambda m: m.update(schema=99), "schema"),
    (lambda m: m.update(jax="9.9.9/9.9.9"), "jax version"),
    (lambda m: m.update(device="TPU v99"), "device kind"),
])
def test_stale_manifest_stamp_is_a_miss(tmp_path, mutate, reason):
    d = str(tmp_path / "pack")
    _drive_on(d)
    _edit_manifest(d, mutate)
    x, w = _operands()
    with art.private_artifact_store(d):
        with art.artifacts_mode_override("readonly"):
            y = np.asarray(_fresh_wrapper()(x, w))   # fresh compile
        info = art.store().info()
    assert info["hits"] == 0, reason
    assert info["stale"] == 1, reason
    np.testing.assert_allclose(y, np.asarray(_core(x, w)), rtol=1e-6)


def test_stale_device_count_entry_stamp_is_a_miss(tmp_path):
    """The per-entry device-count class (the mesh-stamp discipline):
    an executable exported under another topology never loads."""
    d = str(tmp_path / "pack")
    _drive_on(d)

    def mutate(m):
        for e in m["entries"].values():
            e["devices"] = "d999"

    _edit_manifest(d, mutate)
    x, w = _operands()
    with art.private_artifact_store(d):
        with art.artifacts_mode_override("readonly"):
            np.asarray(_fresh_wrapper()(x, w))
        info = art.store().info()
    assert info["hits"] == 0
    assert info["stale"] == 1
    assert obs.counter_value("artifact_stale", op="artifact_test",
                             route="r") == 1


def test_stale_surfaces_in_obs_caches(tmp_path):
    d = str(tmp_path / "pack")
    _drive_on(d)
    _edit_manifest(d, lambda m: m.update(device="TPU v99"))
    with art.private_artifact_store(d):
        with art.artifacts_mode_override("readonly"):
            np.asarray(_fresh_wrapper()(*_operands()))
            snap = obs.caches()["artifact_store"]
    for key in ("path", "mode", "hits", "misses", "stale",
                "evictions"):
        assert key in snap
    assert snap["stale"] == 1 and snap["mode"] == "readonly"


# ---------------------------------------------------------------------------
# corruption: degrade, never crash
# ---------------------------------------------------------------------------


def test_corrupt_manifest_degrades_to_empty(tmp_path):
    d = str(tmp_path / "pack")
    _drive_on(d)
    with open(os.path.join(d, art.MANIFEST_NAME), "w") as f:
        f.write("{ not json !!!")
    with art.private_artifact_store(d) as st:
        with art.artifacts_mode_override("readonly"):
            y = np.asarray(_fresh_wrapper()(*_operands()))
        info = st.info()
    assert info["load_errors"] == 1 and info["hits"] == 0
    assert np.isfinite(y).all()


def test_torn_payload_is_a_load_error_miss(tmp_path):
    """The atomic-write torn-file gate: a payload whose bytes do not
    match the manifest sha256 (a torn copy, a hand edit) must never
    deserialize."""
    d = str(tmp_path / "pack")
    _drive_on(d)
    with art.private_artifact_store(d) as st:
        (key,) = st.keys()
        entry = st.entry(key)
        with open(os.path.join(d, entry["file"]), "r+b") as f:
            f.truncate(max(1, entry["size"] // 2))
        with art.artifacts_mode_override("readonly"):
            y = np.asarray(_fresh_wrapper()(*_operands()))
        info = st.info()
    assert info["load_errors"] == 1 and info["hits"] == 0
    assert obs.counter_value("artifact_load_error",
                             op="artifact_test", route="r") == 1
    x, w = _operands()
    np.testing.assert_allclose(y, np.asarray(_core(x, w)), rtol=1e-6)


def test_missing_payload_file_is_a_miss(tmp_path):
    d = str(tmp_path / "pack")
    _drive_on(d)
    with art.private_artifact_store(d) as st:
        (key,) = st.keys()
        os.unlink(os.path.join(d, st.entry(key)["file"]))
        data, outcome = st.load_bytes(key)
    assert data is None and outcome == "load_error"


# ---------------------------------------------------------------------------
# readonly: never writes
# ---------------------------------------------------------------------------


def test_readonly_mode_never_writes(tmp_path):
    d = str(tmp_path / "pack")
    _drive_on(d)
    before = sorted(os.listdir(d))
    manifest_before = open(os.path.join(d, art.MANIFEST_NAME)).read()
    x64 = _operands(n=64)
    with art.private_artifact_store(d) as st:
        with art.artifacts_mode_override("readonly"):
            _fresh_wrapper()(*x64)           # unseen geometry: a miss
            assert not st.store_bytes("k", b"data")
        info = st.info()
    assert info["stores"] == 0
    assert info["write_refused"] >= 1
    # the directory is byte-for-byte untouched (xla_cache excluded:
    # the persistent-compile leg is the fallback FOR the miss)
    after = sorted(p for p in os.listdir(d)
                   if p != art.XLA_CACHE_SUBDIR)
    assert after == sorted(p for p in before
                           if p != art.XLA_CACHE_SUBDIR)
    assert open(os.path.join(d, art.MANIFEST_NAME)).read() \
        == manifest_before


def test_save_refuses_foreign_manifest(tmp_path):
    """A valid pack stamped for another runtime is never overwritten
    (the TuneCache save_refused discipline)."""
    d = str(tmp_path / "pack")
    os.makedirs(d)
    foreign = {"schema": art.ARTIFACT_SCHEMA, "jax": "9.9.9/9.9.9",
               "device": "TPU v99", "entries": {}}
    with open(os.path.join(d, art.MANIFEST_NAME), "w") as f:
        json.dump(foreign, f)
    with art.private_artifact_store(d) as st:
        with art.artifacts_mode_override("on"):
            _fresh_wrapper()(*_operands())
        info = st.info()
    assert info["save_refused"] >= 1
    with open(os.path.join(d, art.MANIFEST_NAME)) as f:
        assert json.load(f)["device"] == "TPU v99"


# ---------------------------------------------------------------------------
# preload + serve end to end
# ---------------------------------------------------------------------------


def test_preload_compiles_every_entry(tmp_path):
    d = str(tmp_path / "pack")
    with art.private_artifact_store(d) as st:
        with art.artifacts_mode_override("on"):
            w1 = _fresh_wrapper()
            w1(*_operands(n=32))
            w1(*_operands(n=64))
        with art.artifacts_mode_override("readonly"):
            report = art.preload()
        assert report["loaded"] == 2 and report["failed"] == 0
        assert st.info()["runners"] == 2
    events = [e for e in obs.events() if e["op"] == "artifact"]
    assert any(e["decision"] == "preload" and e["loaded"] == 2
               for e in events)


def test_preload_off_mode_is_a_noop(tmp_path):
    with art.private_artifact_store(str(tmp_path)):
        report = art.preload()
    assert report == {"loaded": 0, "failed": 0, "mode": "off",
                      "path": str(tmp_path)}


def test_serve_preload_first_request_zero_cache_misses(
        tmp_path, monkeypatch):
    """The subsystem's whole point, end to end: build a mini warm
    pack by serving one request in ``on`` mode, then start a SECOND
    server against the pack in ``readonly`` — its preload loads the
    executables, the first request records an ``artifact`` hit event,
    and the ``compile.cache_misses`` delta across that first request
    is ZERO (nothing compiled cold).  Configured through the
    PROCESS-GLOBAL env/dir bindings (not the thread-local overrides):
    serve dispatch happens on worker threads, exactly as in
    production."""
    from veles.simd_tpu import serve
    from veles.simd_tpu.ops import batched, iir

    obs.install_compile_listeners()
    d = str(tmp_path / "pack")
    sos = np.asarray(iir.butterworth(4, 0.25, "lowpass"))
    x = np.random.RandomState(3).randn(512).astype(np.float32)

    def submit_one(srv):
        return srv.submit(op="sosfilt", x=x,
                          params={"sos": sos}).result(timeout=120.0)

    art.set_artifact_dir(d)
    try:
        monkeypatch.setenv(art.ARTIFACTS_ENV, "on")
        batched.clear_handle_cache()
        with serve.Server(max_batch=2, max_wait_ms=1.0,
                          obs_port=-1) as srv:
            y_on = submit_one(srv)
        obs.reset()
        monkeypatch.setenv(art.ARTIFACTS_ENV, "readonly")
        batched.clear_handle_cache()       # a "fresh process's" LRU
        with serve.Server(max_batch=2, max_wait_ms=1.0,
                          obs_port=-1) as srv:
            assert srv.stats()["artifact_preload"]["loaded"] >= 1
            misses_before = obs.counter_value(
                "compile.cache_misses")
            y_ro = submit_one(srv)
            misses_after = obs.counter_value(
                "compile.cache_misses")
        info = art.store().info()
    finally:
        art.set_artifact_dir(None)
    np.testing.assert_array_equal(y_on, y_ro)
    assert info["hits"] >= 1
    assert misses_after == misses_before, \
        "first request after preload must not compile cold"
    events = [e for e in obs.events() if e["op"] == "artifact"]
    assert any(e["decision"] == "hit" for e in events)


def test_pipeline_artifact_round_trip(tmp_path):
    """Compiled pipelines are artifacts too: one entry per
    (name, block_len), loaded back by a freshly-compiled chain."""
    from veles.simd_tpu import pipeline as pl
    from veles.simd_tpu.ops import iir

    d = str(tmp_path / "pack")
    sos = iir.butterworth(2, 0.3, "lowpass")

    def build():
        return pl.Pipeline([pl.sosfilt(sos)],
                           name="artline").compile(256)

    x = np.random.RandomState(5).randn(256).astype(np.float32)
    with art.private_artifact_store(d) as st:
        with art.artifacts_mode_override("on"):
            cp = build()
            y_on, _ = cp.process(x, cp.init_state())
        keys = st.keys()
        assert any("pipeline:artline:256" in k for k in keys)
        with art.artifacts_mode_override("readonly"):
            cp2 = build()
            y_ro, _ = cp2.process(x, cp2.init_state())
        assert st.info()["hits"] >= 1
    np.testing.assert_array_equal(np.asarray(y_on),
                                  np.asarray(y_ro))


# ---------------------------------------------------------------------------
# the persistent-compile-cache leg + the profiler shim
# ---------------------------------------------------------------------------


def test_profiler_shim_delegates_and_bridge_counts_warm_load(
        tmp_path):
    """``utils/profiler.enable_compilation_cache`` is a delegating
    shim over the artifact subsystem, and the ``compile.cache_*``
    jax.monitoring bridge sees a warm load: two jits of
    byte-identical programs — the second backend compile must be a
    persistent-cache HIT."""
    from veles.simd_tpu.utils import profiler

    obs.install_compile_listeners()
    cache_dir = str(tmp_path / "xc")
    assert profiler.enable_compilation_cache(cache_dir) == cache_dir
    x = jnp.ones((64, 64), jnp.float32)
    hits0 = obs.counter_value("compile.cache_hits")
    # two distinct function objects, identical jaxprs -> identical
    # module hash -> the second compile is a cache hit
    np.asarray(jax.jit(lambda v: jnp.sin(v) * 3.0 + 1.0)(x))
    np.asarray(jax.jit(lambda v: jnp.sin(v) * 3.0 + 1.0)(x))
    assert obs.counter_value("compile.cache_hits") > hits0


def test_mode_parsing(monkeypatch):
    monkeypatch.setenv(art.ARTIFACTS_ENV, "readonly")
    assert art.artifacts_mode() == "readonly"
    monkeypatch.setenv(art.ARTIFACTS_ENV, "bogus")
    assert art.artifacts_mode() == "off"
    monkeypatch.delenv(art.ARTIFACTS_ENV)
    assert art.artifacts_mode() == "off"
    with art.artifacts_mode_override("on"):
        assert art.artifacts_mode() == "on"
    assert art.artifacts_mode() == "off"
    with pytest.raises(ValueError):
        with art.artifacts_mode_override("sideways"):
            pass


def test_store_eviction_bounds_entries(tmp_path, monkeypatch):
    monkeypatch.setattr(art, "MAX_ARTIFACT_ENTRIES", 3)
    d = str(tmp_path / "pack")
    with art.private_artifact_store(d) as st:
        with art.artifacts_mode_override("on"):
            for i in range(5):
                st.store_bytes(f"key{i}", b"payload%d" % i)
        info = st.info()
    assert info["size"] == 3
    assert info["evictions"] == 2
    # evicted payload files are gone too (best effort, same process)
    bins = [p for p in os.listdir(d) if p.endswith(".bin")]
    assert len(bins) == 3
    # and the MANIFEST agrees: save()'s read-merge-write must not
    # resurrect evicted keys as dangling file references (a fresh
    # process would read them straight into load_errors)
    with open(os.path.join(d, art.MANIFEST_NAME)) as f:
        entries = json.load(f)["entries"]
    assert sorted(entries) == ["key2", "key3", "key4"]
    with art.private_artifact_store(d) as st2:
        for key in sorted(entries):
            data, outcome = st2.load_bytes(key)
            assert outcome == "hit", (key, outcome)
