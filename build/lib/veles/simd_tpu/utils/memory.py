"""Buffer & layout helpers (replaces ``inc/simd/memory.h`` + ``src/memory.c``).

On TPU, XLA owns buffer layout and alignment: the reference's 64-byte aligned
allocators (``/root/reference/src/memory.c:71-91``) become device arrays in
HBM, and the alignment-complement queries (``src/memory.c:41-69``) are
meaningless (kept as 0-returning compatibility stubs).  What *does* survive is
the arithmetic the rest of the library builds on:

* ``next_highest_power_of_2``   (``inc/simd/arithmetic.h:1227-1235``)
* ``zeropadding`` / ``zeropadding_ex`` — pad to 2 × next-pow-2, the FFT-size
  helper (``src/memory.c:126-146``); XLA likes these shapes too.
* ``rmemcpyf`` / ``crmemcpyf`` — reversed (complex-pairwise) copies used by
  correlation's flip-h trick (``src/memory.c:148-183``,
  ``src/correlate.c:37-72``).

All helpers accept NumPy or JAX arrays and stay in that domain (NumPy in,
NumPy out), so they are usable both from the oracle path and inside traced
code.
"""

from __future__ import annotations

import numpy as np


def next_highest_power_of_2(value: int) -> int:
    """Smallest power of two >= ``value``.

    Semantics of ``next_highest_power_of_2`` at
    ``/root/reference/inc/simd/arithmetic.h:1227-1235`` (bit-smearing trick).
    """
    value = int(value)
    if value <= 1:
        return 1
    return 1 << (value - 1).bit_length()


def zeropadding_length(length: int) -> int:
    """The reference's FFT padding size: 2 × (next power of 2 > length).

    Matches the loop at ``/root/reference/src/memory.c:131-137``: e.g.
    100 → 256, 128 → 512, 1 → 4.
    """
    length = int(length)
    nl = length
    log = 2
    while nl:
        nl >>= 1
        log += 1
    return 1 << (log - 1)


def zeropadding(data, new_length: int | None = None):
    """Zero-pad ``data`` to :func:`zeropadding_length` (or ``new_length``).

    Returns ``(padded, new_length)`` like ``src/memory.c:126-129`` returns the
    buffer and writes ``*newLength``.
    """
    xp = _ns(data)
    n = data.shape[-1]
    nl = zeropadding_length(n) if new_length is None else int(new_length)
    pad = [(0, 0)] * (data.ndim - 1) + [(0, nl - n)]
    return xp.pad(data, pad), nl


def zeropadding_ex(data, additional_length: int):
    """Like :func:`zeropadding` with extra zero tail beyond the reported
    length (``src/memory.c:129-142``: the C version allocates
    ``nl + additionalLength`` floats but writes ``*newLength = nl``, so the
    returned length excludes the extra tail — preserved here)."""
    xp = _ns(data)
    n = data.shape[-1]
    nl = zeropadding_length(n)
    pad = [(0, 0)] * (data.ndim - 1) + [(0, nl + int(additional_length) - n)]
    return xp.pad(data, pad), nl


def rmemcpyf(data):
    """Reversed copy: ``out[i] = in[n-1-i]`` (``src/memory.c:148-176``)."""
    return data[..., ::-1]


def crmemcpyf(data):
    """Complex-pairwise reversed copy of an interleaved re/im array:
    reverses the complex samples but keeps each (re, im) pair in order
    (``src/memory.c:178-183``)."""
    n = data.shape[-1]
    if n % 2:
        raise ValueError("interleaved complex array must have even length")
    xp = _ns(data)
    pairs = data.reshape(data.shape[:-1] + (n // 2, 2))
    return xp.flip(pairs, axis=-2).reshape(data.shape)


def memsetf(shape, value, dtype=np.float32):
    """Filled array (``src/memory.c:93-124``); XLA fuses broadcasts anyway."""
    return np.full(shape, value, dtype=dtype)


def malloc_aligned(size: int) -> np.ndarray:
    """Compatibility stub for ``src/memory.c:77-87``: returns a zeroed host
    byte buffer.  Device allocations live in HBM and are managed by XLA."""
    return np.zeros(int(size), dtype=np.uint8)


def malloc_aligned_offset(size: int, offset: int) -> np.ndarray:
    """Compatibility stub for ``inc/simd/memory.h:100`` (alloc whose
    ``ptr + offset`` is aligned): a view at ``offset`` into a fresh
    buffer — XLA owns real layout, so only the length contract matters."""
    return np.zeros(int(size) + int(offset), dtype=np.uint8)[int(offset):]


def mallocf(length: int) -> np.ndarray:
    """Compatibility stub for ``src/memory.c:89-91``."""
    return np.zeros(int(length), dtype=np.float32)


def align_complement(ptr_or_array, dtype=np.float32) -> int:
    """Alignment-complement stub (``src/memory.c:41-69``): XLA owns layout,
    every device buffer is "aligned", so the complement is always 0."""
    return 0


def _ns(data):
    """NumPy-or-jnp namespace for ``data`` without importing jax eagerly."""
    if isinstance(data, np.ndarray) or np.isscalar(data):
        return np
    import jax.numpy as jnp

    return jnp
