"""Tests for veles.simd_tpu.ops.wavelet + wavelet_coeffs.

Port of ``tests/wavelet.cc``: XLA-vs-oracle cross-validation with the
reference tolerance (ε=0.0005, ``tests/wavelet.cc:84-86``), golden
Daubechies-8 properties (``:88-167``), the parameterized
{family}×{order}×{extension}×{level} sweep (``:252-288``), and structural
tests of the layout helpers (``:44-74``).
"""

import numpy as np
import pytest

from veles.simd_tpu.ops import wavelet as wv
from veles.simd_tpu.ops import wavelet_coeffs as wc

RNG = np.random.RandomState(21)
EPS = 5e-4  # tests/wavelet.cc:84-86

EXTS = list(wv.ExtensionType)
TYPES_ORDERS = (
    [(wc.WaveletType.DAUBECHIES, o) for o in (2, 4, 6, 8, 12, 16, 40, 76)]
    + [(wc.WaveletType.SYMLET, o) for o in (2, 4, 6, 8, 12, 16, 40, 76)]
    + [(wc.WaveletType.COIFLET, o) for o in (6, 12, 18, 24, 30)]
)  # tests/wavelet.cc:252-288 instantiation, extended to the high orders
#   the reference also ships (VERDICT r1: the old ≤16 sweep let 29
#   diverging symlet orders sail through untested)


# ---- coefficient generation ------------------------------------------------

def test_daubechies_known_values():
    """db2 is the textbook filter (front-loaded, Σ=√2)."""
    h = wc.daubechies(4)
    want = np.array([0.48296291314453414, 0.8365163037378079,
                     0.22414386804201338, -0.12940952255126037])
    np.testing.assert_allclose(h, want, atol=1e-12)


def test_haar_rows():
    np.testing.assert_allclose(wc.daubechies(2), [2 ** -0.5] * 2, atol=1e-14)
    np.testing.assert_allclose(wc.symlet(2), [0.5, 0.5], atol=1e-14)


def test_symlet4_reference_values():
    """sym4 row of the reference table (sum=1 convention),
    src/symlets.c:53-61."""
    h = wc.symlet(8)
    want = np.array([2.278517294800000e-02, -8.912350720850001e-03,
                     -7.015881208950001e-02, 2.106172671020000e-01,
                     5.683291217050001e-01, 3.518695343280000e-01,
                     -2.095548256255000e-02, -5.357445070900000e-02])
    np.testing.assert_allclose(h, want, atol=1e-9)


def test_coiflet6_reference_values():
    """coif1 row of the reference table (sum=1), src/coiflets.c:36-41."""
    h = wc.coiflet(6)
    want = np.array([-5.14297284710e-02, 2.38929728471e-01, 6.02859456942e-01,
                     2.72140543058e-01, -5.14297284710e-02,
                     -1.10702715290e-02])
    np.testing.assert_allclose(h, want, atol=1e-9)


@pytest.mark.parametrize("wtype,order,tol", [
    (wc.WaveletType.DAUBECHIES, 8, 1e-9), (wc.WaveletType.DAUBECHIES, 76,
                                           1e-9),
    (wc.WaveletType.SYMLET, 8, 1e-9), (wc.WaveletType.SYMLET, 40, 1e-9),
    # symlet/coiflet high orders are stored verbatim from the published
    # tables, which carry the reference's own generation error (see
    # tools/gen_wavelet_tables.py drift bounds); the tolerance is that
    # residual, not ours
    (wc.WaveletType.SYMLET, 76, 1e-4),
    (wc.WaveletType.COIFLET, 18, 1e-9), (wc.WaveletType.COIFLET, 30, 2e-8),
])
def test_orthonormality(wtype, order, tol):
    """Every shipped filter is an orthonormal QMF (after undoing the
    per-family normalization), to the precision of its source."""
    h = wc.scaling_coefficients(wtype, order)
    h = h * np.sqrt(2) / h.sum()
    for k in range(order // 2):
        want = 1.0 if k == 0 else 0.0
        assert abs(np.dot(h[: order - 2 * k], h[2 * k:]) - want) < tol


@pytest.mark.parametrize("wtype,order,p", [
    (wc.WaveletType.DAUBECHIES, 8, 4), (wc.WaveletType.SYMLET, 12, 6),
    (wc.WaveletType.COIFLET, 12, 4),
])
def test_vanishing_moments(wtype, order, p):
    """Highpass kills polynomials up to degree p-1."""
    lo = wc.scaling_coefficients(wtype, order)
    hi = wc.qmf_highpass(lo.astype(np.float64))
    n = np.arange(order, dtype=np.float64)
    for j in range(p):
        assert abs(np.dot(n ** j, hi)) < 1e-7, j


def test_validate_order():
    assert wv.wavelet_validate_order(wc.WaveletType.DAUBECHIES, 8)
    assert not wv.wavelet_validate_order(wc.WaveletType.DAUBECHIES, 7)
    assert not wv.wavelet_validate_order(wc.WaveletType.DAUBECHIES, 78)
    assert wv.wavelet_validate_order(wc.WaveletType.COIFLET, 24)
    assert not wv.wavelet_validate_order(wc.WaveletType.COIFLET, 8)


# ---- DWT / SWT transforms --------------------------------------------------

@pytest.mark.parametrize("ext", EXTS)
@pytest.mark.parametrize("wtype,order", TYPES_ORDERS)
def test_dwt_xla_vs_oracle(wtype, order, ext):
    """tests/wavelet.cc:224-250 cross-validation, ε=0.0005."""
    x = RNG.randn(512).astype(np.float32)
    hi, lo = wv.wavelet_apply(wtype, order, ext, x, simd=True)
    hi_na, lo_na = wv.wavelet_apply_na(wtype, order, ext, x)
    assert hi.shape == lo.shape == (256,)
    np.testing.assert_allclose(np.asarray(hi), hi_na, atol=EPS)
    np.testing.assert_allclose(np.asarray(lo), lo_na, atol=EPS)


@pytest.mark.parametrize("level", [1, 2, 3, 4])
@pytest.mark.parametrize("ext", [wv.ExtensionType.PERIODIC,
                                 wv.ExtensionType.ZERO])
def test_swt_xla_vs_oracle(level, ext):
    x = RNG.randn(256).astype(np.float32)
    hi, lo = wv.stationary_wavelet_apply(
        wc.WaveletType.DAUBECHIES, 8, level, ext, x, simd=True)
    hi_na, lo_na = wv.stationary_wavelet_apply_na(
        wc.WaveletType.DAUBECHIES, 8, level, ext, x)
    assert hi.shape == lo.shape == (256,)
    np.testing.assert_allclose(np.asarray(hi), hi_na, atol=EPS)
    np.testing.assert_allclose(np.asarray(lo), lo_na, atol=EPS)


def test_dwt_haar_golden():
    """Haar DWT has a closed form: (x0±x1)/√2 pairs."""
    x = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0], np.float32)
    hi, lo = wv.wavelet_apply(wc.WaveletType.DAUBECHIES, 2,
                              wv.ExtensionType.PERIODIC, x, simd=True)
    r2 = np.sqrt(2.0, dtype=np.float32)
    np.testing.assert_allclose(np.asarray(lo), [3 / r2, 7 / r2, 11 / r2],
                               atol=1e-5)
    # reference QMF: hp = [C0, -C0] (src/wavelet.c:187-209 sign pattern),
    # so hi = (x[2i] - x[2i+1])/sqrt(2)
    np.testing.assert_allclose(np.asarray(hi), [-1 / r2, -1 / r2, -1 / r2],
                               atol=1e-5)


def test_dwt_energy_preservation():
    """Orthonormal DWT preserves energy (periodic extension)."""
    x = RNG.randn(1024).astype(np.float32)
    hi, lo = wv.wavelet_apply(wc.WaveletType.DAUBECHIES, 8,
                              wv.ExtensionType.PERIODIC, x, simd=True)
    e_in = float(np.sum(x.astype(np.float64) ** 2))
    e_out = float(np.sum(np.asarray(hi, np.float64) ** 2)
                  + np.sum(np.asarray(lo, np.float64) ** 2))
    assert abs(e_in - e_out) / e_in < 1e-5


def test_dwt_constant_signal():
    """Lowpass of a constant is the constant × Σlo; highpass is ~0."""
    x = np.full(128, 3.0, np.float32)
    hi, lo = wv.wavelet_apply(wc.WaveletType.DAUBECHIES, 8,
                              wv.ExtensionType.CONSTANT, x, simd=True)
    np.testing.assert_allclose(np.asarray(hi), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(lo), 3.0 * np.sqrt(2), atol=1e-4)


def test_swt_level1_equals_undecimated_dwt():
    """SWT level 1 at even offsets equals the DWT (same filters, no
    decimation)."""
    x = RNG.randn(128).astype(np.float32)
    hi_s, lo_s = wv.stationary_wavelet_apply(
        wc.WaveletType.DAUBECHIES, 8, 1, wv.ExtensionType.PERIODIC, x,
        simd=True)
    hi_d, lo_d = wv.wavelet_apply(
        wc.WaveletType.DAUBECHIES, 8, wv.ExtensionType.PERIODIC, x,
        simd=True)
    np.testing.assert_allclose(np.asarray(hi_s)[::2], np.asarray(hi_d),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(lo_s)[::2], np.asarray(lo_d),
                               atol=1e-5)


def test_multi_level_cascade():
    x = RNG.randn(512).astype(np.float32)
    coeffs = wv.wavelet_transform(wc.WaveletType.SYMLET, 8,
                                  wv.ExtensionType.PERIODIC, x, 3, simd=True)
    assert [c.shape[-1] for c in coeffs] == [256, 128, 64, 64]
    coeffs_na = wv.wavelet_transform(wc.WaveletType.SYMLET, 8,
                                     wv.ExtensionType.PERIODIC, x, 3,
                                     simd=False)
    for a, b in zip(coeffs, coeffs_na):
        np.testing.assert_allclose(np.asarray(a), b, atol=2e-3)


def test_batched_dwt():
    x = RNG.randn(8, 256).astype(np.float32)
    hi, lo = wv.wavelet_apply(wc.WaveletType.DAUBECHIES, 8,
                              wv.ExtensionType.MIRROR, x, simd=True)
    assert hi.shape == (8, 128)
    for b in range(8):
        hb, lb = wv.wavelet_apply_na(wc.WaveletType.DAUBECHIES, 8,
                                     wv.ExtensionType.MIRROR, x[b])
        np.testing.assert_allclose(np.asarray(hi)[b], hb, atol=EPS)
        np.testing.assert_allclose(np.asarray(lo)[b], lb, atol=EPS)


# ---- contract violations & shims ------------------------------------------

def test_contract_violations():
    x = RNG.randn(33).astype(np.float32)  # odd length
    with pytest.raises(ValueError):
        wv.wavelet_apply(wc.WaveletType.DAUBECHIES, 8,
                         wv.ExtensionType.PERIODIC, x, simd=True)
    with pytest.raises(ValueError):
        wv.wavelet_apply(wc.WaveletType.DAUBECHIES, 7,
                         wv.ExtensionType.PERIODIC, RNG.randn(64), simd=True)
    with pytest.raises(ValueError):
        wv.stationary_wavelet_apply(wc.WaveletType.DAUBECHIES, 8, 0,
                                    wv.ExtensionType.PERIODIC,
                                    RNG.randn(64).astype(np.float32))


def test_layout_shims():
    """tests/wavelet.cc:44-74 structural checks, XLA-era semantics."""
    x = RNG.randn(64).astype(np.float32)
    prep = wv.wavelet_prepare_array(8, x, 64)
    np.testing.assert_array_equal(prep, x)
    dest = wv.wavelet_allocate_destination(8, 64)
    assert dest.shape == (32,) and dest.dtype == np.float32
    quarters = wv.wavelet_recycle_source(8, np.arange(64, dtype=np.float32))
    assert all(q.shape == (16,) for q in quarters)
    np.testing.assert_array_equal(quarters[1], np.arange(16, 32))
    assert wv.wavelet_recycle_source(8, np.arange(6)) == (None,) * 4
    with pytest.raises(ValueError):
        wv.wavelet_allocate_destination(8, 66)
