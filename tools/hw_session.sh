#!/bin/sh
# One-shot hardware validation session: run every device-pending item in
# priority order the moment the axon relay is reachable.  Each step is
# independently logged and failure-isolated; the bench headline (the
# driver's BENCH_r03 artifact input) goes first so a short device window
# still captures it.
#
#   sh tools/hw_session.sh [outdir]        # default /tmp/hw_session
#
# Steps:
#   1. bench.py            -> headline JSON + BENCH_DETAILS.json + smoke
#   2. tools/tpu_smoke.py  -> per-family TPU-CHECK lines (13 families)
#   3. tools/tune_conv2d.py --quick   -> 2D crossover measurement
#   4. tools/tune_overlap_save.py --quick  -> 1D step-size re-check
set -u
OUT=${1:-/tmp/hw_session}
mkdir -p "$OUT"
OUT=$(cd "$OUT" && pwd)   # absolutize before the repo-root cd below
cd "$(dirname "$0")/.."

echo "== hw_session $(date -u +%FT%TZ) -> $OUT"

run() {
  name=$1; shift
  echo "== $name: $*"
  start=$(date +%s)
  "$@" >"$OUT/$name.out" 2>"$OUT/$name.err"
  rc=$?
  echo "== $name: rc=$rc (${name}.out/.err, $(($(date +%s) - start))s)"
  return 0
}

run bench        python bench.py --all
cp -f BENCH_DETAILS.json "$OUT/" 2>/dev/null || true
run smoke        python tools/tpu_smoke.py
run tune_conv2d  python tools/tune_conv2d.py --quick
run tune_os      python tools/tune_overlap_save.py --quick

echo "== headline:"
head -1 "$OUT/bench.out" 2>/dev/null
echo "== done $(date -u +%FT%TZ)"
