"""The pipeline compiler: an op chain fused into ONE dispatch per block.

``examples/sensor_pipeline.py``'s six-stage chain used to run as six
separate dispatches with six HBM round-trips per block; TINA
(arXiv:2408.16551) frames whole-algorithm-to-accelerator mapping — not
per-op routing — as where the wins live, and arXiv:1810.09868's
whole-program TPU compilation is the model.  :class:`Pipeline` holds a
declarative stage chain (:mod:`veles.simd_tpu.pipeline.stages`);
:meth:`Pipeline.compile` validates the geometry once, resolves every
routed stage's kernel through the EXISTING ``routing.family`` tables
(autotuned winners and rejection caches steer the fused step; tune
classes are stamped :func:`~veles.simd_tpu.runtime.routing.\
pipeline_tune_geom`), and builds one ``obs.instrumented_jit`` step —
``(block, state) -> (out, state')`` with EVERY stage's carried state
(IIR ``zi``, FIR halo, STFT frame overlap, resampler history)
threaded explicitly through the step as a pytree.

The compiled step dispatches under
:func:`veles.simd_tpu.runtime.faults.breaker_guarded` at the
``pipeline.dispatch`` site with a per-pipeline-class breaker:
transient device faults retry, exhaustion degrades THAT BLOCK to the
stage-by-stage NumPy oracle twin (identical streaming semantics, so
the stream continues with exact state and block-streamed output still
matches the one-shot oracle), and a persistently failing pipeline
class short-circuits straight to the oracle without dragging sibling
classes down.

Parity contract (``tests/test_pipeline.py``): for any block
decomposition, ``stream(x)`` equals :meth:`CompiledPipeline.oracle`
on the whole signal — including block boundaries straddling IIR
state, overlap-save halo, STFT overlap, and resampler history, and
across a mid-stream injected fault at ``pipeline.dispatch``.

Usage::

    from veles.simd_tpu import pipeline as pl

    chain = pl.Pipeline([pl.resample_poly(2, 1), pl.sosfilt(sos),
                         pl.stft(256, 64), pl.power()],
                        name="sensor")
    cp = chain.compile(block_len=1024)
    state = cp.init_state()
    for block in blocks:
        out, state = cp.process(block, state)   # ONE dispatch each
"""

from __future__ import annotations

import copy

import numpy as np

from veles.simd_tpu import obs
from veles.simd_tpu.runtime import faults, routing
from veles.simd_tpu.pipeline.stages import MODES, Stage

__all__ = ["Pipeline", "CompiledPipeline", "PIPELINE_SITE"]

# the fused step's fault-policy site: VELES_SIMD_FAULT_PLAN entries
# (`pipeline.dispatch:device_lost:1`) exercise retry/degrade on CPU CI
PIPELINE_SITE = "pipeline.dispatch"


def _tree_map(fn, tree):
    """Structure-preserving map over the nested tuple/list state pytree
    (host-side — no jax import for the NumPy paths)."""
    if isinstance(tree, (tuple, list)):
        return tuple(_tree_map(fn, t) for t in tree)
    return fn(tree)


def _cast_out(leaf):
    """Oracle float64/complex128 outputs -> the device dtypes, so a
    degraded block is shape- and dtype-compatible with the fused ones."""
    a = np.asarray(leaf)
    if a.dtype == np.float64:
        return a.astype(np.float32)
    if a.dtype == np.complex128:
        return a.astype(np.complex64)
    return a


class Pipeline:
    """A declarative op chain: ordered :class:`~veles.simd_tpu.\
pipeline.stages.Stage` descriptors, not yet bound to a block size.
    :meth:`compile` produces the runnable :class:`CompiledPipeline`."""

    def __init__(self, stages, name: str = "pipeline"):
        stages = list(stages)
        if not stages:
            raise ValueError("a pipeline needs at least one stage")
        for st in stages:
            if not isinstance(st, Stage):
                raise TypeError(f"not a pipeline stage: {st!r}")
        for st in stages[:-1]:
            if st.terminal:
                raise ValueError(
                    f"terminal stage {st.name!r} must come last")
        names = [st.name for st in stages]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate stage names: {names}")
        self.stages = stages
        self.name = str(name)

    def compile(self, block_len: int, name: str | None = None
                ) -> "CompiledPipeline":
        """Validate the chain against ``block_len``, resolve every
        routed stage's kernel, and build the fused step."""
        return CompiledPipeline(self, int(block_len),
                                name=name or self.name)


class CompiledPipeline:
    """One chain bound to one block size: a single fused
    ``obs.instrumented_jit`` step plus the stage-by-stage oracle twin
    (see the module docstring for the full story)."""

    def __init__(self, pipeline: Pipeline, block_len: int,
                 name: str):
        if block_len < 1:
            raise ValueError("block_len must be positive")
        self.name = str(name)
        self.block_len = int(block_len)
        # PRIVATE stage copies: plan()/resolve() write block geometry
        # and routes into the stage objects, and a Pipeline may be
        # compiled at several block sizes — sharing the descriptors
        # would let the second compile silently corrupt the first
        self._stages = copy.deepcopy(pipeline.stages)
        # geometry pass: thread (block, mode) through the chain
        block, mode = self.block_len, "samples"
        self._links = []
        for st in self._stages:
            block, mode = st.plan(block, mode)
            if mode not in MODES:
                raise ValueError(f"stage {st.name!r} returned unknown "
                                 f"mode {mode!r}")
            self._links.append({"stage": st.name, "block_out": block,
                                "mode": mode})
        self.out_block = block
        self.mode = mode
        self.terminal_tree = self._stages[-1].terminal
        # route pass: every routed stage resolves through its
        # routing.family table NOW (compile time), with the tune class
        # stamped as pipeline-compiled
        for st in self._stages:
            route = st.resolve(routing.pipeline_tune_geom)
            if route is not None:
                obs.record_decision(
                    "pipeline_stage_route", route, pipeline=self.name,
                    stage=st.name, family=st.family)
        obs.record_decision(
            "pipeline_compile", self.name, block=self.block_len,
            out_block=self.out_block, mode=self.mode,
            stages=",".join(st.name for st in self._stages),
            routes=",".join(f"{st.name}={st.route}"
                            for st in self._stages
                            if st.route is not None))

        stages = self._stages

        def _step(x, states):
            new_states = []
            y = x
            for st, s in zip(stages, states):
                y, s2 = st.apply(y, s)
                new_states.append(s2)
            return y, tuple(new_states)

        # THE fused step: one compiled program, one dispatch per block.
        # The artifact key is the pipeline's serving identity — ONE
        # store entry per (name, block_len), so a warm pack built from
        # the same declared chain hands a fresh process the fused
        # executable before the first block ever traces (the stage
        # list itself is closure state the generic fingerprint cannot
        # see, which is exactly what the explicit key is for)
        self._step = obs.instrumented_jit(
            _step, op="pipeline", route=self.name,
            artifact_key=f"pipeline:{self.name}:{self.block_len}")
        # the honest-comparison twin: the SAME stage kernels, one
        # dispatch per stage per block (what the chain cost before
        # fusing) — built lazily, only the bench/examples pay for it
        self._stage_jits = None

    # -- state --------------------------------------------------------------

    def init_state(self, batch_shape: tuple = ()) -> tuple:
        """Zero-seeded carried state for a fresh stream (optionally
        batched: one independent stream per leading row)."""
        return tuple(st.init_state(tuple(batch_shape))
                     for st in self._stages)

    # -- the block step -----------------------------------------------------

    def _to_device_state(self, state):
        import jax.numpy as jnp

        return _tree_map(lambda a: jnp.asarray(a, jnp.float32), state)

    def _run_fused(self, block, state):
        import jax.numpy as jnp

        return self._step(jnp.asarray(block, jnp.float32),
                          self._to_device_state(state))

    def _run_unfused(self, block, state):
        """Per-stage dispatch of the SAME kernels (the pre-fusion
        cost model): one jit call per stage per block."""
        import jax.numpy as jnp

        if self._stage_jits is None:
            self._stage_jits = [
                obs.instrumented_jit(st.apply, op="pipeline_stage",
                                     route=f"{self.name}:{st.name}")
                for st in self._stages]
        y = jnp.asarray(block, jnp.float32)
        state = self._to_device_state(state)
        new_states = []
        for st, jfn, s in zip(self._stages, self._stage_jits, state):
            y, s2 = jfn(y, s)
            new_states.append(s2)
        return y, tuple(new_states)

    def oracle_step(self, block, state):
        """One block through the stage-by-stage NumPy oracle twin —
        the degradation target (identical streaming semantics, exact
        state threading, cannot fault)."""
        y = np.asarray(block, np.float64)
        new_states = []
        for st, s in zip(self._stages, state):
            y, s2 = st.apply_na(y, s)
            new_states.append(s2)
        return _tree_map(_cast_out, y), tuple(new_states)

    def process(self, block, state=None, fused: bool = True):
        """Feed one block (``[..., block_len]``); returns ``(out,
        state')``.  The fused path is ONE ``instrumented_jit``
        dispatch under the pipeline class's circuit breaker at
        ``pipeline.dispatch``; transient faults retry then degrade
        THIS block to the oracle twin and the stream continues with
        exact state.  ``fused=False`` dispatches stage-by-stage (the
        honest pre-fusion baseline) through the same fault policy."""
        if np.shape(block)[-1] != self.block_len:
            raise ValueError(
                f"block length {np.shape(block)[-1]} != compiled "
                f"{self.block_len}")
        if state is None:
            state = self.init_state(np.shape(block)[:-1])
        with obs.span("pipeline.dispatch", pipeline=self.name,
                      fused=bool(fused)):
            return faults.breaker_guarded(
                PIPELINE_SITE, (self.name, self.block_len),
                (lambda: self._run_fused(block, state)) if fused
                else (lambda: self._run_unfused(block, state)),
                fallback=lambda: self.oracle_step(block, state),
                fallback_name="oracle", subsite=self.name)

    def serve_step(self, block, state, budget_s: float | None = None,
                   on_fault=None):
        """One (possibly row-batched) block for the SERVING layer:
        the same per-pipeline-class breaker + guarded dispatch as
        :meth:`process`, with the batch's remaining deadline budget
        threaded in, returning ``(out, state', degraded)`` so the
        server can label oracle-served tickets.  The breaker key is
        the pipeline class — ``serve.dispatch`` traffic and direct
        :meth:`process` callers share one breaker, and a chaos plan
        poisons the class via the ``pipeline.dispatch@<name>``
        subsite.  ``on_fault`` is the request-axis observer the server
        threads in (:func:`veles.simd_tpu.runtime.faults.guarded`):
        every retry/degrade of the fused step lands as a ``retried`` /
        ``degraded`` edge on each co-batched invocation's trace."""
        box = {"deg": False}

        def fallback():
            box["deg"] = True
            return self.oracle_step(block, state)

        with obs.span("pipeline.dispatch", pipeline=self.name,
                      served=True):
            out, new_state = faults.breaker_guarded(
                PIPELINE_SITE, (self.name, self.block_len),
                lambda: self._run_fused(block, state),
                fallback=fallback, fallback_name="oracle",
                subsite=self.name, budget_s=budget_s,
                on_fault=on_fault)
        return out, new_state, box["deg"]

    # -- serving-layer state marshalling ------------------------------------

    def check_state(self, state) -> None:
        """Validate a caller-supplied carried state against this
        pipeline's structure and per-stream leaf shapes — the serving
        layer's SUBMIT-time gate: a malformed state (saved from a
        different pipeline or block size) must fail its own caller
        synchronously with ValueError, never surface inside the
        worker where it would error every co-batched stream."""
        ref = self.init_state(())

        def walk(r, s, path):
            where = "/".join(path) or "state"
            if isinstance(r, tuple):
                if not isinstance(s, (tuple, list)) or len(s) != len(r):
                    raise ValueError(
                        f"pipeline {self.name!r} state at {where}: "
                        f"expected a {len(r)}-element tuple, got "
                        f"{type(s).__name__}")
                for i, (ri, si) in enumerate(zip(r, s)):
                    walk(ri, si, path + [str(i)])
                return
            try:
                shape = tuple(np.shape(s))
            except Exception:
                raise ValueError(
                    f"pipeline {self.name!r} state at {where}: not "
                    "an array") from None
            want = tuple(np.shape(r))
            if shape != want:
                raise ValueError(
                    f"pipeline {self.name!r} state at {where}: shape "
                    f"{shape} != expected {want} (state from another "
                    "pipeline or block size?)")

        walk(ref, state, [])

    def batch_states(self, row_states, rows: int) -> tuple:
        """Stack per-stream states into one ``rows``-row batched state
        (the serve batcher's marshalling): ``row_states[i]`` is stream
        ``i``'s carried state or None (fresh stream); missing rows and
        pad rows stay zero-seeded."""
        base = self.init_state((int(rows),))

        def fill(base_node, idx, state_node):
            if isinstance(base_node, tuple):
                for b, s in zip(base_node, state_node):
                    fill(b, idx, s)
            else:
                base_node[idx] = np.asarray(state_node)

        for i, rs in enumerate(row_states):
            if rs is not None:
                fill(base, i, rs)
        return base

    def state_rows(self, state, count: int) -> list:
        """Split a batched state back into ``count`` per-stream
        states (NumPy) — the serve batcher's un-marshalling."""
        state = _tree_map(np.asarray, state)
        return [_tree_map(lambda a, i=i: a[i], state)
                for i in range(count)]

    def out_rows(self, out, count: int) -> list:
        """Split a batched step output into ``count`` per-stream
        outputs (arrays, or per-leaf for a terminal pytree stage)."""
        if self.terminal_tree:
            out = _tree_map(np.asarray, out)
            return [_tree_map(lambda a, i=i: a[i], out)
                    for i in range(count)]
        out = np.asarray(out)
        return [out[i] for i in range(count)]

    # -- whole-signal helpers ----------------------------------------------

    def _split(self, x):
        n = np.shape(x)[-1]
        if n % self.block_len != 0 or n == 0:
            raise ValueError(
                f"signal length {n} is not whole blocks of "
                f"{self.block_len}")
        return [x[..., i:i + self.block_len]
                for i in range(0, n, self.block_len)]

    def assemble(self, outs):
        """Per-block outputs -> the whole-stream array, per the chain
        mode: ``samples``/``frames`` concatenate (last / frames axis),
        ``rows`` stack a new block axis.  Terminal pytree stages
        (detect_peaks) assemble per leaf on a new block axis."""
        if self.terminal_tree:
            leaves = zip(*outs)
            return tuple(np.stack([np.asarray(v) for v in leaf])
                         for leaf in leaves)
        outs = [np.asarray(o) for o in outs]
        if self.mode == "samples":
            return np.concatenate(outs, axis=-1)
        if self.mode == "frames":
            return np.concatenate(outs, axis=-2)
        return np.stack(outs, axis=-2)

    def stream(self, x, state=None, fused: bool = True):
        """Block the whole signal, thread state through
        :meth:`process`, and :meth:`assemble` — the test/bench
        convenience.  Returns ``(assembled, final_state)``."""
        if state is None:
            state = self.init_state(np.shape(x)[:-1])
        outs = []
        for block in self._split(x):
            out, state = self.process(block, state, fused=fused)
            outs.append(out)
        return self.assemble(outs), state

    def oracle(self, x):
        """ONE-SHOT whole-signal oracle of the streamed chain: each
        stage's closed-form streaming semantics evaluated over the
        full signal in NumPy float64 (no blocking, no state) — what
        any block decomposition of :meth:`stream` must reproduce."""
        y = np.asarray(x, np.float64)
        block, mode = self.block_len, "samples"
        for st, link in zip(self._stages, self._links):
            y = st.oracle(y, block, mode)
            block, mode = link["block_out"], link["mode"]
        return y if self.terminal_tree else np.asarray(y)

    # -- introspection ------------------------------------------------------

    def describe(self) -> dict:
        """JSON-native chain summary (stages, routes, per-stage
        latencies, block geometry)."""
        return {"pipeline": self.name, "block_len": self.block_len,
                "out_block": self.out_block, "mode": self.mode,
                "stages": [dict(st.describe(), **{
                    k: v for k, v in link.items() if k != "stage"})
                    for st, link in zip(self._stages, self._links)]}

    def routes(self) -> dict:
        """Stage name -> resolved route (routed stages only)."""
        return {st.name: st.route for st in self._stages
                if st.route is not None}

    def compile_cache_size(self) -> int | None:
        """Number of compiled executables behind the fused step (the
        one-dispatch-per-block test gate); None when the jax version
        does not expose it."""
        try:
            return int(self._step._jfn._cache_size())
        except Exception:  # noqa: BLE001 — introspection only
            return None
