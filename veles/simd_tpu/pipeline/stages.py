"""Pipeline stage descriptors: declarative links of an op chain.

A :class:`Stage` describes ONE link of a streaming op chain — what it
computes per fixed-size block, what state it carries between blocks,
and how its kernel is chosen — in a form the pipeline compiler
(:mod:`veles.simd_tpu.pipeline.compiler`) can fuse into a single
block-processing step:

* ``plan(block_in, mode)`` validates the stage's geometry against the
  incoming block length and chain mode and returns the outgoing
  ``(block_out, mode)`` — called once at compile time;
* ``resolve(tune_stamp)`` picks the stage's kernel through the
  EXISTING ``routing.family`` candidate tables (``convolve`` for the
  FIR link, ``stft`` for the spectral link), so autotuned winners,
  rejection memory, and the persistent tune cache steer the fused
  step exactly as they steer standalone dispatch — with the tune
  class stamped :func:`veles.simd_tpu.runtime.routing.\
pipeline_tune_geom` so pipeline-compiled selections key their own
  entries;
* ``init_state(batch_shape)`` builds the stage's zero-seeded carried
  state (IIR ``zi``, FIR/overlap-save halo, STFT frame overlap,
  resampler history — each re-exported from its op module's
  state hooks);
* ``apply(x, state)`` is the TRACEABLE per-block kernel ``(x, state)
  -> (y, state')`` the compiler inlines into the one fused jit;
  ``apply_na(x, state)`` is its NumPy float64 twin (the stage-by-stage
  degradation path);
* ``oracle(x, block_in, mode)`` is the ONE-SHOT whole-signal NumPy
  reference of the stage's STREAMING semantics — block-streamed
  output must match it exactly across any block decomposition (the
  parity contract ``tests/test_pipeline.py`` pins).

Chain **modes** thread through ``plan``: ``"samples"`` (a continuous
sample stream — per-block outputs concatenate on the last axis),
``"frames"`` (an STFT stream — outputs concatenate on the frames
axis), ``"rows"`` (one row per block, e.g. a per-block Welch PSD —
outputs stack on a new block axis).  Stages that need sample
continuity (fir/sosfilt/resample/medfilt/stft/welch) demand
``"samples"``; per-row operators (savgol, power, detect_peaks) accept
any mode and inherit it.

Streaming semantics note: stages with LOOKAHEAD (the centered
resampler, the centered median) and the STFT's zero-seeded frame
overlap emit a few pre-roll samples of left transient before the
first "interior" output — each stage reports that as ``latency`` (in
its own output samples) and its ``oracle`` reproduces it exactly, so
streamed-vs-oracle parity is bit-for-block from sample 0.
"""

from __future__ import annotations

import numpy as np

from veles.simd_tpu.ops import convolve as _cv
from veles.simd_tpu.ops import detect_peaks as _dp
from veles.simd_tpu.ops import filters as _fl
from veles.simd_tpu.ops import iir as _iir
from veles.simd_tpu.ops import resample as _rs
from veles.simd_tpu.ops import spectral as _sp
from veles.simd_tpu.runtime import routing

__all__ = [
    "Stage", "fir", "correlate", "matched_filter", "sosfilt",
    "resample_poly", "medfilt", "detrend", "stft", "power",
    "power_db", "welch", "savgol", "detect_peaks", "MODES",
]

MODES = ("samples", "frames", "rows")


def _jnp():
    import jax.numpy as jnp

    return jnp


class Stage:
    """One chain link.  Subclasses fill in the five hooks; factory
    functions (:func:`fir`, :func:`sosfilt`, ...) are the public
    spelling.  ``family`` names the ``routing.family`` table the stage
    resolves through (None = single-kernel stage); ``route`` holds the
    resolved kernel after :meth:`resolve`."""

    family: str | None = None
    terminal = False

    def __init__(self, name: str):
        self.name = str(name)
        self.route: str | None = None
        self.latency = 0
        self._block_in: int | None = None

    # -- compile-time hooks -------------------------------------------------

    def plan(self, block_in: int, mode: str) -> tuple:
        """Validate geometry; return ``(block_out, mode_out)``."""
        raise NotImplementedError

    def resolve(self, tune_stamp) -> str | None:
        """Pick the kernel through the stage's routing family (called
        once at compile time; ``tune_stamp(geom)`` stamps the tune
        class as pipeline-compiled).  Default: single-kernel stage."""
        return None

    def init_state(self, batch_shape: tuple):
        """Zero-seeded carried state (NumPy), or ``()`` if stateless."""
        return ()

    # -- runtime hooks ------------------------------------------------------

    def apply(self, x, state):
        """TRACEABLE ``(x, state) -> (y, state')``."""
        raise NotImplementedError

    def apply_na(self, x, state):
        """NumPy float64 twin of :meth:`apply`."""
        raise NotImplementedError

    def oracle(self, x, block_in: int, mode: str):
        """One-shot whole-signal NumPy reference of the STREAMING
        semantics (pre-roll included)."""
        raise NotImplementedError

    def describe(self) -> dict:
        return {"stage": self.name, "family": self.family,
                "route": self.route, "latency": self.latency}


def _require_mode(stage, mode: str, want: str = "samples") -> None:
    if mode != want:
        raise ValueError(
            f"stage {stage.name!r} needs a {want!r}-mode input, got "
            f"{mode!r} (it cannot follow a frame/row-producing stage)")


# ---------------------------------------------------------------------------
# sample-stream stages with carried state
# ---------------------------------------------------------------------------


class _FirStage(Stage):
    """Causal FIR (convolution or cross-correlation) with the
    overlap-save halo carried between blocks."""

    family = "convolve"

    def __init__(self, h, reverse: bool, name: str):
        super().__init__(name)
        self._h = np.asarray(h, np.float32)
        if self._h.ndim != 1 or self._h.shape[0] < 1:
            raise ValueError("h must be a non-empty 1D filter")
        self._k = int(self._h.shape[0])
        self._reverse = bool(reverse)
        self._carry = _cv.streaming_carry_len(self._k)

    def plan(self, block_in, mode):
        _require_mode(self, mode)
        if block_in < 1:
            raise ValueError("block must be positive")
        self._block_in = int(block_in)
        return int(block_in), mode

    def resolve(self, tune_stamp):
        ext = self._carry + self._block_in
        self.route = _cv.select_stream_route(
            ext, self._k,
            tune_geom=tune_stamp(
                {"x_length": routing.pow2_bucket(ext),
                 "h_length": self._k}))
        return self.route

    def init_state(self, batch_shape):
        if self._carry == 0:
            return ()
        return np.zeros(tuple(batch_shape) + (self._carry,), np.float32)

    def apply(self, x, state):
        jnp = _jnp()
        h = jnp.asarray(self._h)
        if self._carry == 0:
            return _cv.causal_stream_block(x, h, self.route,
                                           reverse=self._reverse), ()
        ext = jnp.concatenate([state, x], axis=-1)
        y = _cv.causal_stream_block(ext, h, self.route,
                                    reverse=self._reverse)
        return y, ext[..., -self._carry:]

    def apply_na(self, x, state):
        if self._carry == 0:
            return _cv.causal_stream_block_na(
                x, self._h, reverse=self._reverse), ()
        ext = np.concatenate([np.asarray(state, np.float64),
                              np.asarray(x, np.float64)], axis=-1)
        y = _cv.causal_stream_block_na(ext, self._h,
                                       reverse=self._reverse)
        return y, ext[..., -self._carry:]

    def oracle(self, x, block_in, mode):
        x = np.asarray(x, np.float64)
        pre = np.zeros(x.shape[:-1] + (self._carry,), np.float64)
        return _cv.causal_stream_block_na(
            np.concatenate([pre, x], axis=-1), self._h,
            reverse=self._reverse)


class _SosfiltStage(Stage):
    """IIR second-order-section cascade with carried DF2T ``zi``."""

    def __init__(self, sos, name: str = "sosfilt"):
        super().__init__(name)
        self._sos = _iir._check_sos(sos)

    def plan(self, block_in, mode):
        _require_mode(self, mode)
        if block_in < 2:
            raise ValueError("sosfilt streaming needs blocks >= 2")
        self._block_in = int(block_in)
        return int(block_in), mode

    def init_state(self, batch_shape):
        return np.zeros(tuple(batch_shape) + (len(self._sos), 2),
                        np.float32)

    def apply(self, x, state):
        return _iir.sos_stream_step(x, self._sos, state)

    def apply_na(self, x, state):
        return _iir.sos_stream_step_na(np.asarray(x, np.float64),
                                       self._sos,
                                       np.asarray(state, np.float64))

    def oracle(self, x, block_in, mode):
        return _iir.sosfilt_na(self._sos, np.asarray(x, np.float64))


class _ResampleStage(Stage):
    """Rational polyphase resampler with carried input history; the
    centered anti-aliasing filter's lookahead appears as ``latency``
    pre-roll samples (see :func:`veles.simd_tpu.ops.resample.\
resample_stream_plan`)."""

    def __init__(self, up: int, down: int, taps=None,
                 name: str = "resample_poly"):
        super().__init__(name)
        self._up, self._down, self._taps_arg = int(up), int(down), taps
        self._plan: dict | None = None

    def plan(self, block_in, mode):
        _require_mode(self, mode)
        self._plan = _rs.resample_stream_plan(self._up, self._down,
                                              int(block_in),
                                              self._taps_arg)
        self._block_in = int(block_in)
        self.latency = self._plan["preroll"]
        return self._plan["out_block"], mode

    def init_state(self, batch_shape):
        return np.zeros(tuple(batch_shape) + (self._plan["hist"],),
                        np.float32)

    def apply(self, x, state):
        jnp = _jnp()
        ext = jnp.concatenate([state, x], axis=-1)
        taps = jnp.asarray(self._plan["taps"], jnp.float32)
        y = _rs.resample_stream_step(ext, taps, self._plan)
        return y, ext[..., -self._plan["hist"]:]

    def apply_na(self, x, state):
        ext = np.concatenate([np.asarray(state, np.float64),
                              np.asarray(x, np.float64)], axis=-1)
        y = _rs.resample_stream_step_na(ext, self._plan)
        return y, ext[..., -self._plan["hist"]:]

    def oracle(self, x, block_in, mode):
        return _rs.resample_stream_oracle(np.asarray(x, np.float64),
                                          self._plan)


class _MedfiltStage(Stage):
    """Centered sliding median with the ``k - 1`` halo carried; the
    center lookahead appears as ``k // 2`` pre-roll samples."""

    def __init__(self, kernel_size: int, name: str = "medfilt"):
        super().__init__(name)
        self._k = _fl._check_kernel(kernel_size)
        self.latency = self._k // 2

    def plan(self, block_in, mode):
        _require_mode(self, mode)
        if block_in < 1:
            raise ValueError("block must be positive")
        self._block_in = int(block_in)
        return int(block_in), mode

    def init_state(self, batch_shape):
        if self._k == 1:
            return ()
        return np.zeros(tuple(batch_shape) + (self._k - 1,),
                        np.float32)

    def _windows(self, ext, xp, b):
        lanes = [ext[..., j:j + b] for j in range(self._k)]
        return xp.stack(lanes, axis=-1)

    def apply(self, x, state):
        jnp = _jnp()
        if self._k == 1:
            return x, ()
        ext = jnp.concatenate([state, x], axis=-1)
        win = self._windows(ext, jnp, x.shape[-1])
        y = jnp.sort(win, axis=-1)[..., self._k // 2]
        return y, ext[..., -(self._k - 1):]

    def apply_na(self, x, state):
        if self._k == 1:
            return np.asarray(x, np.float64), ()
        ext = np.concatenate([np.asarray(state, np.float64),
                              np.asarray(x, np.float64)], axis=-1)
        win = self._windows(ext, np, np.shape(x)[-1])
        y = np.sort(win, axis=-1)[..., self._k // 2]
        return y, ext[..., -(self._k - 1):]

    def oracle(self, x, block_in, mode):
        x = np.asarray(x, np.float64)
        pre = np.zeros(x.shape[:-1] + (self._k // 2,), np.float64)
        y = _fl.medfilt_na(np.concatenate([pre, x], axis=-1), self._k)
        return y[..., :x.shape[-1]]


class _StftStage(Stage):
    """Short-time Fourier transform with the frame overlap carried;
    emits ``block/hop`` complex frames per block and switches the
    chain into ``"frames"`` mode."""

    family = "stft"

    def __init__(self, frame_length: int, hop: int, window=None,
                 name: str = "stft"):
        super().__init__(name)
        self._L, self._hop = int(frame_length), int(hop)
        self._carry = _sp.stft_stream_carry(self._L, self._hop)
        self._window = _sp._resolve_window(window, self._L)
        self.latency = self._L // self._hop - 1  # pre-roll frames

    def plan(self, block_in, mode):
        _require_mode(self, mode)
        if block_in % self._hop != 0 or block_in < self._hop:
            raise ValueError(
                f"stft stage needs hop {self._hop} dividing the "
                f"block, got block {block_in}")
        self._block_in = int(block_in)
        self._frames = block_in // self._hop
        return self._L // 2 + 1, "frames"

    def resolve(self, tune_stamp):
        self.route = _sp.select_stft_stream_route(
            self._L, self._hop, self._frames,
            tune_geom=tune_stamp({"frame_length": self._L,
                                  "hop": self._hop}))
        return self.route

    def init_state(self, batch_shape):
        if self._carry == 0:
            return ()
        return np.zeros(tuple(batch_shape) + (self._carry,),
                        np.float32)

    def apply(self, x, state):
        jnp = _jnp()
        ext = (x if self._carry == 0
               else jnp.concatenate([state, x], axis=-1))
        spec = _sp.stft_stream_step(ext, self._L, self._hop,
                                    self._window, self.route)
        new = () if self._carry == 0 else ext[..., -self._carry:]
        return spec, new

    def apply_na(self, x, state):
        x = np.asarray(x, np.float64)
        ext = (x if self._carry == 0
               else np.concatenate([np.asarray(state, np.float64), x],
                                   axis=-1))
        spec = _sp.stft_na(ext, self._L, self._hop, self._window)
        new = () if self._carry == 0 else ext[..., -self._carry:]
        return spec, new

    def oracle(self, x, block_in, mode):
        return _sp.stft_stream_oracle(np.asarray(x, np.float64),
                                      self._L, self._hop, self._window)


# ---------------------------------------------------------------------------
# blockwise / per-row stages (stateless)
# ---------------------------------------------------------------------------


class _DetrendStage(Stage):
    """Least-squares de-trending.  In ``samples`` mode this is
    BLOCK-WISE detrending (each block's own trend removed — the
    always-on monitoring semantics); in frame/row modes it detrends
    each row."""

    def __init__(self, type: str = "linear",  # noqa: A002
                 name: str = "detrend"):
        super().__init__(name)
        if type not in ("linear", "constant"):
            raise ValueError(f"type must be 'linear' or 'constant', "
                             f"got {type!r}")
        self._type = type

    def plan(self, block_in, mode):
        self._block_in = int(block_in)
        self._mode = mode
        return int(block_in), mode

    def apply(self, x, state):
        return _sp.detrend(x, self._type, simd=True), ()

    def apply_na(self, x, state):
        return _sp.detrend_na(np.asarray(x, np.float64), self._type), ()

    def oracle(self, x, block_in, mode):
        x = np.asarray(x, np.float64)
        if mode != "samples":
            return _sp.detrend_na(x, self._type)
        blocked = x.reshape(x.shape[:-1] + (-1, block_in))
        out = _sp.detrend_na(blocked, self._type)
        return out.reshape(x.shape)


class _WelchStage(Stage):
    """Per-block Welch PSD: every block yields one averaged one-sided
    periodogram row — the chain switches into ``"rows"`` mode (the
    always-on spectral monitor's heartbeat)."""

    def __init__(self, fs: float = 1.0, nperseg: int = 256,
                 noverlap=None, window=None,
                 detrend_type: str = "constant",
                 scaling: str = "density", name: str = "welch"):
        super().__init__(name)
        self._kw = dict(fs=float(fs), nperseg=int(nperseg),
                        noverlap=noverlap, window=window,
                        detrend_type=detrend_type, scaling=scaling)
        self.freqs = None

    def plan(self, block_in, mode):
        _require_mode(self, mode)
        if block_in < self._kw["nperseg"]:
            raise ValueError(
                f"welch stage needs blocks >= nperseg "
                f"{self._kw['nperseg']}, got {block_in}")
        self._block_in = int(block_in)
        self.freqs = np.fft.rfftfreq(self._kw["nperseg"],
                                     1.0 / self._kw["fs"])
        return self._kw["nperseg"] // 2 + 1, "rows"

    def apply(self, x, state):
        _, pxx = _sp.welch(x, simd=True, **self._kw)
        return pxx, ()

    def apply_na(self, x, state):
        _, pxx = _sp.welch_na(np.asarray(x, np.float64), **self._kw)
        return pxx, ()

    def oracle(self, x, block_in, mode):
        x = np.asarray(x, np.float64)
        blocked = x.reshape(x.shape[:-1] + (-1, block_in))
        _, pxx = _sp.welch_na(blocked, **self._kw)
        return pxx


class _PowerStage(Stage):
    """Pointwise power ``|x|^2`` (complex STFT frames -> real power);
    inherits the chain mode."""

    def __init__(self, name: str = "power"):
        super().__init__(name)

    def plan(self, block_in, mode):
        self._block_in = int(block_in)
        return int(block_in), mode

    def apply(self, x, state):
        jnp = _jnp()
        return (jnp.real(x) ** 2 + jnp.imag(x) ** 2).astype(
            jnp.float32), ()

    def apply_na(self, x, state):
        x = np.asarray(x)
        return np.real(x) ** 2 + np.imag(x) ** 2, ()

    def oracle(self, x, block_in, mode):
        return self.apply_na(x, ())[0]


class _PowerDbStage(Stage):
    """Pointwise ``10 log10(max(x, floor))`` — dB view of a power row;
    inherits the chain mode."""

    def __init__(self, floor: float = 1e-12, name: str = "power_db"):
        super().__init__(name)
        self._floor = float(floor)

    def plan(self, block_in, mode):
        self._block_in = int(block_in)
        return int(block_in), mode

    def apply(self, x, state):
        jnp = _jnp()
        return 10.0 * jnp.log10(jnp.maximum(x, self._floor)), ()

    def apply_na(self, x, state):
        x = np.asarray(x, np.float64)
        return 10.0 * np.log10(np.maximum(x, self._floor)), ()

    def oracle(self, x, block_in, mode):
        return self.apply_na(x, ())[0]


class _SavgolStage(Stage):
    """Savitzky-Golay smoothing along the last axis — a per-row
    operator for PSD/frame rows (``mode='interp'`` is host-side and
    cannot trace; the streaming form uses ``'nearest'``/
    ``'constant'``)."""

    def __init__(self, window_length: int, polyorder: int,
                 deriv: int = 0, delta: float = 1.0,
                 mode: str = "nearest", name: str = "savgol"):
        super().__init__(name)
        if mode not in ("nearest", "constant"):
            raise ValueError(
                "pipeline savgol supports mode='nearest'/'constant' "
                "(mode='interp' fits edges host-side and cannot fuse)")
        self._args = (int(window_length), int(polyorder), int(deriv),
                      float(delta), mode)
        _fl._check_kernel(int(window_length), "window_length")

    def plan(self, block_in, mode):
        if mode == "samples":
            raise ValueError(
                f"stage {self.name!r} is a per-row smoother — placed "
                "in a samples-mode chain its window would ignore "
                "block boundaries; put it after a frames/rows stage")
        w = self._args[0]
        if block_in < w:
            raise ValueError(f"savgol window {w} exceeds row length "
                             f"{block_in}")
        self._block_in = int(block_in)
        return int(block_in), mode

    def apply(self, x, state):
        w, p, d, delta, mode = self._args
        return _fl.savgol_filter(x, w, p, deriv=d, delta=delta,
                                 mode=mode, simd=True), ()

    def apply_na(self, x, state):
        w, p, d, delta, mode = self._args
        return _fl.savgol_filter_na(np.asarray(x, np.float64), w, p,
                                    deriv=d, delta=delta, mode=mode), ()

    def oracle(self, x, block_in, mode):
        return self.apply_na(x, ())[0]


class _DetectPeaksStage(Stage):
    """Fixed-capacity local-extrema read-off along the last axis —
    the terminal alerting stage.  Emits the pytree ``(positions,
    values, count)`` per block (positions ``int32`` padded with -1)."""

    terminal = True

    def __init__(self, type=_dp.ExtremumType.MAXIMUM,  # noqa: A002
                 max_peaks: int = 64, name: str = "detect_peaks"):
        super().__init__(name)
        self._type = _dp.ExtremumType(int(type))
        self._max = int(max_peaks)
        if self._max < 1:
            raise ValueError("max_peaks must be >= 1")

    def plan(self, block_in, mode):
        if block_in < 3:
            raise ValueError("detect_peaks needs rows of >= 3 samples")
        self._block_in = int(block_in)
        return self._max, mode

    def apply(self, x, state):
        return _dp._peaks_fixed(x, self._type, self._max), ()

    def apply_na(self, x, state):
        d = np.asarray(x, np.float64)
        n = d.shape[-1]
        prev, curr, nxt = d[..., :-2], d[..., 1:-1], d[..., 2:]
        d1, d2 = curr - prev, curr - nxt
        is_ext = (d1 * d2) > 0
        want = np.zeros_like(is_ext)
        if self._type & _dp.ExtremumType.MAXIMUM:
            want |= d1 > 0
        if self._type & _dp.ExtremumType.MINIMUM:
            want |= d1 < 0
        pad = [(0, 0)] * (d.ndim - 1) + [(1, 1)]
        mask = np.pad(is_ext & want, pad)
        flat_m = mask.reshape(-1, n)
        flat_d = d.reshape(-1, n)
        pos = np.full((flat_m.shape[0], self._max), -1, np.int32)
        vals = np.zeros((flat_m.shape[0], self._max), np.float64)
        for r in range(flat_m.shape[0]):
            idx = np.nonzero(flat_m[r])[0][: self._max]
            pos[r, : len(idx)] = idx
            vals[r, : len(idx)] = flat_d[r, idx]
        shape = d.shape[:-1] + (self._max,)
        count = mask.sum(axis=-1)
        return (pos.reshape(shape), vals.reshape(shape), count), ()

    def oracle(self, x, block_in, mode):
        return self.apply_na(x, ())[0]


# ---------------------------------------------------------------------------
# factory functions — the public chain-declaration vocabulary
# ---------------------------------------------------------------------------


def fir(h, name: str = "fir") -> Stage:
    """Causal FIR filter stage (overlap-save halo carried between
    blocks); kernel resolved through the ``convolve`` routing family
    at compile time."""
    return _FirStage(h, reverse=False, name=name)


def correlate(h, name: str = "correlate") -> Stage:
    """Causal cross-correlation stage (the matched filter): the FIR
    link with the template un-flipped, ``src/correlate.c``'s
    flip-reuse trick in streaming form."""
    return _FirStage(h, reverse=True, name=name)


def matched_filter(template, name: str = "matched_filter") -> Stage:
    """Alias of :func:`correlate` for the radar/biosignal idiom."""
    return _FirStage(template, reverse=True, name=name)


def sosfilt(sos, name: str = "sosfilt") -> Stage:
    """IIR cascade stage with carried DF2T ``zi`` state."""
    return _SosfiltStage(sos, name=name)


def resample_poly(up: int, down: int, taps=None,
                  name: str = "resample_poly") -> Stage:
    """Rational polyphase resampler stage with carried input history
    (``block * up`` must divide by ``down``)."""
    return _ResampleStage(up, down, taps=taps, name=name)


def medfilt(kernel_size: int, name: str = "medfilt") -> Stage:
    """Centered sliding-median despiker with carried halo."""
    return _MedfiltStage(kernel_size, name=name)


def detrend(type: str = "linear",  # noqa: A002
            name: str = "detrend") -> Stage:
    """Block-wise (or per-row) least-squares detrending stage."""
    return _DetrendStage(type, name=name)


def stft(frame_length: int, hop: int, window=None,
         name: str = "stft") -> Stage:
    """STFT stage with carried frame overlap; kernel resolved through
    the ``stft`` routing family at compile time.  Switches the chain
    into ``frames`` mode."""
    return _StftStage(frame_length, hop, window=window, name=name)


def power(name: str = "power") -> Stage:
    """Pointwise ``|x|^2`` stage (complex frames -> real power)."""
    return _PowerStage(name=name)


def power_db(floor: float = 1e-12, name: str = "power_db") -> Stage:
    """Pointwise ``10 log10(max(x, floor))`` stage."""
    return _PowerDbStage(floor, name=name)


def welch(fs: float = 1.0, nperseg: int = 256, noverlap=None,
          window=None, detrend_type: str = "constant",
          scaling: str = "density", name: str = "welch") -> Stage:
    """Per-block Welch PSD stage (one averaged periodogram row per
    block).  Switches the chain into ``rows`` mode."""
    return _WelchStage(fs, nperseg, noverlap, window, detrend_type,
                       scaling, name=name)


def savgol(window_length: int, polyorder: int, deriv: int = 0,
           delta: float = 1.0, mode: str = "nearest",
           name: str = "savgol") -> Stage:
    """Savitzky-Golay per-row smoothing stage (PSD/frame rows)."""
    return _SavgolStage(window_length, polyorder, deriv=deriv,
                        delta=delta, mode=mode, name=name)


def detect_peaks(type=_dp.ExtremumType.MAXIMUM,  # noqa: A002
                 max_peaks: int = 64,
                 name: str = "detect_peaks") -> Stage:
    """Terminal fixed-capacity peak read-off stage: emits
    ``(positions, values, count)`` per block."""
    return _DetectPeaksStage(type, max_peaks, name=name)
