"""Dependency-free line coverage for the test harness.

The container ships neither ``coverage`` nor ``pytest-cov``, so
``tools/run_tests.py`` collects line coverage with a stdlib
``sys.settrace`` hook instead: the global trace function prunes every
frame whose code lives outside the repo's ``veles/`` tree (returning
``None`` disables local tracing for that frame, so numpy/jax/pytest
internals only pay the per-call event), and repo frames record their
executed line numbers into one set.

Two halves:

* **collector** (runs inside the per-suite child): :func:`start`
  installs the tracer (both ``sys.settrace`` and ``threading.settrace``
  — bench-harness tests spawn worker threads) and registers an atexit
  dump of ``{filename: [lines]}`` JSON.
* **reporter** (runs in the parent): :func:`merge` folds the per-suite
  dumps, :func:`executable_lines` computes each module's denominator
  from the *compiled* code objects (``co_lines`` over the nested code
  tree — exactly the set a tracer could ever report, so docstrings and
  blank lines never count against coverage), and :func:`table` renders
  the per-module report ``run_tests.py`` appends to ``tests.log`` and
  gates the ``veles/simd_tpu/obs/`` floor on.
"""

from __future__ import annotations

import json
import os
import sys
import threading

__all__ = ["start", "merge", "executable_lines", "table",
           "aggregate_pct", "DEFAULT_FLOORS"]

# the gated scopes: repo-relative directory -> minimum aggregate line
# coverage % (consumed by tools/run_tests.py; CLI flags override).
# obs/ is pure host-side Python (untested lines there are plain
# negligence — VERDICT item 6); serve/ is the production request path
# whose failure handling is exactly the code that only runs when
# things go wrong, so untraced lines there are untested outage
# behavior.
DEFAULT_FLOORS = {
    "veles/simd_tpu/obs": 60.0,
    # bumped with the control axis (obs v7): serve/ gained scaler.py
    # at ~95% suite coverage, so the aggregate floor can hold a
    # little higher without flaking (subset lower bound: 84%)
    "veles/simd_tpu/serve": 62.0,
}


def start(prefix: str, out_path: str) -> None:
    """Install the tracer for files under ``prefix`` and dump counts
    to ``out_path`` at interpreter exit (atomic rename, so a killed
    suite leaves no torn JSON)."""
    import atexit

    prefix = os.path.abspath(prefix) + os.sep
    hits: dict = {}

    def _global(frame, event, arg):
        if event != "call":
            return None
        fname = frame.f_code.co_filename
        if not fname.startswith(prefix):
            return None     # foreign frame: no local line tracing
        target = hits.setdefault(fname, set())
        target.add(frame.f_lineno)

        def local(frame, event, arg):
            if event == "line":
                target.add(frame.f_lineno)
            return local
        return local

    def _dump():
        sys.settrace(None)
        threading.settrace(None)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({k: sorted(v) for k, v in hits.items()}, f)
        os.replace(tmp, out_path)

    atexit.register(_dump)
    threading.settrace(_global)
    sys.settrace(_global)


def merge(paths) -> dict:
    """Union the per-suite dumps into ``{filename: set(lines)}``."""
    merged: dict = {}
    for p in paths:
        try:
            with open(p) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue        # skipped/killed suite: no dump, not fatal
        for fname, lines in data.items():
            merged.setdefault(fname, set()).update(lines)
    return merged


def executable_lines(path: str) -> set:
    """Line numbers the compiled module could ever report: the union
    of ``co_lines()`` over the module's nested code objects."""
    with open(path) as f:
        src = f.read()
    try:
        code = compile(src, path, "exec")
    except SyntaxError:
        return set()
    lines: set = set()
    stack = [code]
    while stack:
        co = stack.pop()
        for _, _, line in co.co_lines():
            if line is not None:
                lines.add(line)
        for const in co.co_consts:
            if hasattr(const, "co_lines"):
                stack.append(const)
    return lines


def _module_rows(merged: dict, repo: str, scope: str):
    scope_abs = os.path.join(os.path.abspath(repo), scope)
    rows = []
    for root, _dirs, files in os.walk(scope_abs):
        if "__pycache__" in root:
            continue
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            exe = executable_lines(path)
            if not exe:
                continue
            hit = merged.get(path, set()) & exe
            rel = os.path.relpath(path, repo)
            rows.append((rel, len(hit), len(exe)))
    return rows


def table(merged: dict, repo: str, scope: str = "veles") -> str:
    """Per-module coverage table over ``scope`` (repo-relative dir)."""
    rows = _module_rows(merged, repo, scope)
    if not rows:
        return "(no coverage data)\n"
    width = max(len(r[0]) for r in rows)
    lines = ["%-*s %8s %8s %6s" % (width, "module", "covered",
                                   "lines", "pct")]
    tot_hit = tot_exe = 0
    for rel, hit, exe in rows:
        tot_hit += hit
        tot_exe += exe
        lines.append("%-*s %8d %8d %5.1f%%"
                     % (width, rel, hit, exe, 100.0 * hit / exe))
    lines.append("%-*s %8d %8d %5.1f%%"
                 % (width, "TOTAL", tot_hit, tot_exe,
                    100.0 * tot_hit / max(tot_exe, 1)))
    return "\n".join(lines) + "\n"


def aggregate_pct(merged: dict, repo: str, scope: str) -> float:
    """Aggregate line-coverage % over one repo-relative directory —
    the number ``run_tests.py`` gates (the ``veles/simd_tpu/obs/``
    floor)."""
    rows = _module_rows(merged, repo, scope)
    hit = sum(r[1] for r in rows)
    exe = sum(r[2] for r in rows)
    return 100.0 * hit / exe if exe else 0.0
