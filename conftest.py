"""Root pytest config: run the suite on a virtual 8-device CPU mesh.

Must run before jax is imported anywhere: forces the CPU platform with 8
virtual devices so the multi-chip sharding paths (veles/simd_tpu/parallel)
compile and execute without TPU hardware, mirroring how the driver validates
``__graft_entry__.dryrun_multichip``.
"""

import os
import sys

# force CPU even when the environment pins another platform (e.g. the
# axon TPU tunnel sets JAX_PLATFORMS=axon globally): the suite needs the
# 8-device virtual mesh, and per-op TPU validation happens in bench.py /
# verification drives instead.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# The axon TPU plugin (registered by a sitecustomize on PYTHONPATH) pins
# the platform before conftest runs; the env var alone doesn't win. Force
# the config too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
