#!/usr/bin/env python
"""Measure the overlap-save block-matmul step-size sweep on the device.

The reference's algorithm thresholds are hardcoded from offline
measurement (``/root/reference/src/convolve.c:328-364``); this is the
measurement tool for ours.  For each filter length it times the MXU
block-matmul overlap-save (``_conv_os_matmul``) across output-block
sizes and both precisions with chained on-device loops, checks accuracy
against a float64 oracle, and prints the winning step per (k, precision)
— the data behind ``ops/convolve.py``'s ``overlap_save_step`` and
``AUTO_*`` constants.  Rerun on new hardware generations.

Since PR 7 the sweep also emits TUNE-CACHE ENTRIES (the same
version-stamped format the online autotuner persists,
``runtime/routing.py``): per filter length it times the engine's two
``convolve.os`` candidates — the fused Pallas kernel when its gate
admits the length, and the XLA block matmul at the engine's step —
and stores the accuracy-gated winner under the engine's geometry key
with ``source="sweep"``.  A hand sweep and the online tuner build one
artifact; point ``--cache`` at the same file ``tools/autotune_pack.py``
writes (default: ``$VELES_SIMD_AUTOTUNE_CACHE`` when set, else no
emission).

Run:  python tools/tune_overlap_save.py [--quick] [--n 1048576]
          [--cache autotune_pack.json]
      VELES_SIMD_PLATFORM=cpu ... works but only validates plumbing —
      step size is an MXU tiling decision, so tune on the real chip.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu.utils.platform import maybe_override_platform  # noqa: E402

# steps whose rel. error exceeds this never become winners — matches the
# TPU smoke gate for convolve (tools/tpu_smoke.py)
ERR_GATE = 1e-4


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--n", type=int, default=1 << 20)
    parser.add_argument(
        "--cache",
        default=os.environ.get("VELES_SIMD_AUTOTUNE_CACHE") or None,
        help="tune-cache file to emit route winners into (default: "
             "$VELES_SIMD_AUTOTUNE_CACHE; omit to print tables only)")
    args = parser.parse_args()
    maybe_override_platform()
    quick = args.quick
    n = args.n

    import jax
    import jax.numpy as jnp

    from veles.simd_tpu.ops import convolve as cv
    from veles.simd_tpu.runtime import routing
    from veles.simd_tpu.utils.benchmark import device_time_chained

    cache = routing.TuneCache(args.cache) if args.cache else None

    rng = np.random.RandomState(0)
    x_np = rng.randn(n).astype(np.float32)
    x = jnp.asarray(x_np)
    print(f"device: {jax.devices()[0]}  signal: {n}", flush=True)

    ks = (127, 2047) if quick else (127, 511, 2047, 8191)
    steps = (256, 512, 1024, 2048)
    precisions = ("highest", "high")
    winners = {}
    for k in ks:
        h_np = rng.randn(k).astype(np.float32)
        h = jnp.asarray(h_np)
        want = np.convolve(x_np.astype(np.float64), h_np.astype(np.float64))
        scale = np.max(np.abs(want))
        for prec in precisions:
            best = (float("inf"), None)
            for step in steps:
                got = np.asarray(
                    cv._conv_os_matmul(x, h, step, precision=prec),
                    np.float64)
                err = float(np.max(np.abs(got - want)) / scale)

                def stp(v, step=step, prec=prec, h=h):
                    y = cv._conv_os_matmul(v, h, step, precision=prec)
                    return v + 1e-30 * y[..., :n]

                t = device_time_chained(stp, x, iters=64, repeats=2)
                gated = " (fails accuracy gate)" if err > ERR_GATE else ""
                print(f"k={k:5d} prec={prec:8s} step={step:5d}: "
                      f"{t * 1e3:7.3f} ms  {n / t / 1e6:7.0f} Ms/s  "
                      f"rel_err={err:.1e}{gated}", flush=True)
                if err <= ERR_GATE and t < best[0]:
                    best = (t, step)
            winners[(k, prec)] = best[1]
            cur = cv.overlap_save_step(k)
            print(f"  -> k={k} {prec}: best step {best[1]} "
                  f"(overlap_save_step gives {cur})", flush=True)

        # route-level sweep -> tune-cache entry: time the engine's
        # convolve.os candidates at the engine's own step and store
        # the accuracy-gated winner in the shared autotune format
        if cache is None:
            continue
        step = cv.overlap_save_step(k)
        timings_us = {}

        def probe(run, want=want, scale=scale):
            got = np.asarray(run(x), np.float64)
            if float(np.max(np.abs(got - want)) / scale) > ERR_GATE:
                return None

            def stp(v):
                return v + 1e-30 * run(v)[..., :n]

            t = device_time_chained(stp, x, iters=64, repeats=2)
            # device_time_chained returns NaN for unresolvable
            # measurements; NaN must never become a winner (every
            # min() comparison against it is False) nor a JSON token
            return t * 1e6 if np.isfinite(t) else None

        timings_us["xla_matmul"] = probe(
            lambda v: cv._conv_os_matmul(v, h, step,
                                         precision="highest"))
        if cv._use_pallas_os(k):
            try:
                timings_us["pallas_fused"] = probe(
                    lambda v: cv._conv_os_pallas(v, h,
                                                 precision="highest"))
            except Exception as e:  # noqa: BLE001 — sweep explores
                print(f"  pallas_fused probe failed: "
                      f"{str(e)[:60]}", flush=True)
                timings_us["pallas_fused"] = None
        measured = {r: t for r, t in timings_us.items()
                    if t is not None}
        if measured:
            winner = min(measured, key=measured.get)
            # keys match dispatch exactly: rows=1 (the sweep times
            # single signals — batched classes need an online probe),
            # x_length pow2-bucketed, and precision="highest" since
            # the probes above pin it — a conv_precision='high'
            # service never consults a 'highest'-measured winner
            key = cache.store(
                "convolve.os",
                {"rows": 1, "x_length": routing.pow2_bucket(n),
                 "h_length": k, "step": step,
                 "precision": "highest"},
                winner, timings_us=timings_us, source="sweep")
            print(f"  -> cache entry {key} = {winner}", flush=True)
    print("winners:", winners)
    if cache is not None:
        print(f"tune cache {args.cache}: "
              f"{len(cache.entries())} entries")


if __name__ == "__main__":
    main()
