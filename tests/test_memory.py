"""Tests for veles.simd_tpu.utils.memory (the platform buffer helpers).

VERDICT round-1 item 8: these Python implementations are load-bearing for
``ops/convolve.py`` (FFT pad sizes) but were only exercised through their
separate C twins.  Goldens follow the reference semantics:
``src/memory.c:131-137`` (zeropadding sizes), ``:148-183`` (reversed and
complex-pairwise-reversed copies), ``inc/simd/arithmetic.h:1227-1235``
(next power of 2).
"""

import numpy as np
import pytest

from veles.simd_tpu.utils import memory as mem


# ---- next_highest_power_of_2 (arithmetic.h:1227-1235) ---------------------

@pytest.mark.parametrize("value,want", [
    (1, 1), (2, 2), (3, 4), (4, 4), (5, 8), (100, 128), (128, 128),
    (129, 256), (1 << 20, 1 << 20), ((1 << 20) + 1, 1 << 21),
])
def test_next_highest_power_of_2(value, want):
    assert mem.next_highest_power_of_2(value) == want


# ---- zeropadding sizes (src/memory.c:131-137 golden loop) -----------------

def _reference_zeropadding_length(length):
    """The reference's literal bit-count loop."""
    nl = length
    log = 2
    while nl:
        nl >>= 1
        log += 1
    return 1 << (log - 1)


@pytest.mark.parametrize("length,want", [
    (1, 4), (2, 8), (3, 8), (5, 16), (100, 256), (127, 256),
    (128, 512), (129, 512), (1000, 2048),
])
def test_zeropadding_length_goldens(length, want):
    # want = 2 * next power of 2 > length (doc example: 100 -> 256)
    assert mem.zeropadding_length(length) == want
    assert mem.zeropadding_length(length) == \
        _reference_zeropadding_length(length)


def test_zeropadding_pads_with_zeros():
    data = np.arange(1, 6, dtype=np.float32)
    padded, nl = mem.zeropadding(data)
    assert nl == 16
    assert padded.shape == (16,)
    np.testing.assert_array_equal(padded[:5], data)
    assert np.all(padded[5:] == 0)


def test_zeropadding_explicit_length_and_batch():
    data = np.ones((3, 10), np.float32)
    padded, nl = mem.zeropadding(data, new_length=32)
    assert nl == 32 and padded.shape == (3, 32)
    assert np.all(padded[:, 10:] == 0)


def test_zeropadding_ex_extra_tail():
    """C semantics (src/memory.c:129-142): the buffer gains
    additional_length extra zeros but *newLength excludes them."""
    data = np.arange(100, dtype=np.float32)
    padded, nl = mem.zeropadding_ex(data, 5)
    assert nl == 256            # doc example: 100 -> 256
    assert padded.shape == (261,)
    assert np.all(padded[100:] == 0)


# ---- reversed copies (src/memory.c:148-183) -------------------------------

def test_rmemcpyf():
    data = np.array([1, 2, 3, 4, 5], np.float32)
    np.testing.assert_array_equal(mem.rmemcpyf(data), [5, 4, 3, 2, 1])


def test_crmemcpyf_pairs_stay_intact():
    # 3 complex samples (1,2) (3,4) (5,6) -> (5,6) (3,4) (1,2)
    data = np.array([1, 2, 3, 4, 5, 6], np.float32)
    np.testing.assert_array_equal(mem.crmemcpyf(data), [5, 6, 3, 4, 1, 2])


def test_crmemcpyf_odd_length_rejected():
    with pytest.raises(ValueError):
        mem.crmemcpyf(np.zeros(5, np.float32))


def test_reversed_copies_work_on_jax_arrays():
    import jax.numpy as jnp

    data = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    np.testing.assert_array_equal(np.asarray(mem.rmemcpyf(data)),
                                  [4, 3, 2, 1])
    np.testing.assert_array_equal(np.asarray(mem.crmemcpyf(data)),
                                  [3, 4, 1, 2])


# ---- stubs keep their documented contracts --------------------------------

def test_memsetf_and_alloc_stubs():
    buf = mem.memsetf((4,), 2.5)
    assert buf.dtype == np.float32 and np.all(buf == 2.5)
    assert mem.mallocf(8).shape == (8,)
    assert mem.malloc_aligned(16).nbytes == 16
    assert mem.align_complement(buf) == 0
