"""Root pytest config: run the suite on a virtual 8-device CPU mesh.

Must run before jax is imported anywhere: forces the CPU platform with 8
virtual devices so the multi-chip sharding paths (veles/simd_tpu/parallel)
compile and execute without TPU hardware, mirroring how the driver validates
``__graft_entry__.dryrun_multichip``.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
