"""Tests for the runtime telemetry subsystem (``veles.simd_tpu.obs``).

Four contracts pinned here:

* the registry is thread-safe and the event log is bounded;
* both export formats (JSON, Prometheus text) round-trip;
* every ``select_algorithm`` threshold boundary records a decision
  event naming the algorithm actually selected;
* telemetry on or off, traced programs are byte-identical — the whole
  layer lives strictly at the Python dispatch layer.
"""

import concurrent.futures
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veles.simd_tpu import obs
from veles.simd_tpu.obs import export as obs_export
from veles.simd_tpu.obs.events import DEFAULT_MAX_EVENTS, EventLog
from veles.simd_tpu.obs.registry import MetricsRegistry
from veles.simd_tpu.ops import convolve as cv
from veles.simd_tpu.ops import spectral as sp
from veles.simd_tpu.ops import wavelet as wv
from veles.simd_tpu.ops.wavelet_coeffs import WaveletType

RNG = np.random.RandomState(0)


@pytest.fixture
def telemetry():
    """Telemetry ON (with the jax.monitoring bridge), clean slate, and a
    guaranteed return to the disabled default afterwards."""
    obs.enable()
    obs.reset()
    yield obs
    obs.disable()
    obs.reset()
    obs.configure(max_events=DEFAULT_MAX_EVENTS)


# --------------------------------------------------------------------------
# registry / event log primitives
# --------------------------------------------------------------------------


def test_registry_thread_safety_under_concurrent_increments():
    reg = MetricsRegistry()
    threads, per_thread = 8, 2000

    def worker(_):
        for _ in range(per_thread):
            reg.count("hammered", op="x")
        return True

    with concurrent.futures.ThreadPoolExecutor(threads) as ex:
        assert all(ex.map(worker, range(threads)))
    assert reg.counter_value("hammered", op="x") == threads * per_thread


def test_obs_facade_thread_safety(telemetry):
    threads, per_thread = 8, 1000
    obs.configure(max_events=threads * per_thread)

    def worker(i):
        for _ in range(per_thread):
            obs.count("facade.hammered")
            obs.record_decision("op", "path", worker=i)
        return True

    with concurrent.futures.ThreadPoolExecutor(threads) as ex:
        assert all(ex.map(worker, range(threads)))
    assert obs.counter_value("facade.hammered") == threads * per_thread
    # every recorded event survived into the (large enough) ring intact
    evs = obs.events()
    assert len(evs) == threads * per_thread
    assert sorted(e["seq"] for e in evs) == list(range(len(evs)))


def test_event_log_bounding():
    log = EventLog(max_events=32)
    for i in range(100):
        log.record("op", "decision", i=i)
    evs = log.events()
    assert len(evs) == 32
    assert log.dropped == 68
    # ring keeps the NEWEST events, oldest-first
    assert [e["i"] for e in evs] == list(range(68, 100))
    assert [e["seq"] for e in evs] == list(range(68, 100))


def test_event_log_bounding_through_facade(telemetry):
    obs.configure(max_events=16)
    for i in range(50):
        obs.record_decision("op", "d", i=i)
    snap = obs.snapshot()
    assert len(snap["events"]) == 16
    assert snap["events_dropped"] == 34
    # aggregates survive the wraparound
    assert obs.counter_value("decisions", op="op", decision="d") == 50


def test_disabled_records_nothing():
    obs.disable()
    obs.reset()
    obs.count("should.not.exist")
    obs.record_decision("op", "d")
    obs.observe("hist", 0.5)
    obs.gauge("g", 1.0)
    snap = obs.snapshot()
    assert snap["counters"] == []
    assert snap["events"] == []
    assert snap["histograms"] == []
    assert snap["gauges"] == []
    assert snap["enabled"] is False


# --------------------------------------------------------------------------
# exporters
# --------------------------------------------------------------------------


def _populated_snapshot():
    obs.count("dispatch", 3, op="convolve", backend="xla")
    obs.count("dispatch", op="convolve", backend="oracle")
    obs.gauge("mesh.devices", 8.0)
    obs.observe("compile.backend_compile_secs", 0.025)
    obs.observe("compile.backend_compile_secs", 2.5)
    obs.record_decision("convolve", "overlap_save",
                        x_length=1 << 20, h_length=2047)
    return obs.snapshot()


def test_json_export_round_trip(telemetry):
    snap = _populated_snapshot()
    assert obs_export.from_json(obs.to_json(snap)) == snap
    # strict JSON (bench artifacts use allow_nan=False)
    json.loads(obs.to_json(snap))


def test_json_save_load_round_trip(telemetry, tmp_path):
    snap = _populated_snapshot()
    path = obs.save(str(tmp_path / "snap.json"), snap)
    assert obs.load(path) == snap


def test_prometheus_export_round_trip(telemetry):
    snap = _populated_snapshot()
    text = obs.to_prometheus(snap)
    parsed = obs_export.parse_prometheus(text)
    # every counter and gauge sample is recoverable with its value
    for c in snap["counters"]:
        key = (obs_export.PROMETHEUS_PREFIX
               + c["name"].replace(".", "_") + "_total",
               tuple(sorted(c["labels"].items())))
        assert parsed[key] == c["value"], key
    for g in snap["gauges"]:
        key = (obs_export.PROMETHEUS_PREFIX
               + g["name"].replace(".", "_"),
               tuple(sorted(g["labels"].items())))
        assert parsed[key] == g["value"]
    # histogram series: cumulative buckets, sum and count
    hist = snap["histograms"][0]
    hname = (obs_export.PROMETHEUS_PREFIX
             + hist["name"].replace(".", "_"))
    assert parsed[(hname + "_count", ())] == hist["count"] == 2
    assert parsed[(hname + "_sum", ())] == pytest.approx(hist["sum"])
    assert parsed[(hname + "_bucket", (("le", "+Inf"),))] == 2


def test_report_renders(telemetry):
    snap = _populated_snapshot()
    text = obs.report(snap)
    assert "overlap_save" in text
    assert "dispatch{backend=xla,op=convolve}" in text


# --------------------------------------------------------------------------
# decision events at the select_algorithm threshold boundaries
# --------------------------------------------------------------------------

BF = cv.ConvolutionAlgorithm.BRUTE_FORCE
FFT = cv.ConvolutionAlgorithm.FFT
OS = cv.ConvolutionAlgorithm.OVERLAP_SAVE

# (x_length, h_length) straddling both thresholds:
# product boundary x*h = AUTO_FFT_MIN_PRODUCT (8192) and
# ratio boundary x = AUTO_OVERLAP_SAVE_MIN_RATIO * h (8h)
BOUNDARY_CASES = [
    (127, 64, BF),       # 8128 < 8192: latency floor
    (128, 64, FFT),      # 8192 hits the product threshold, ratio 2
    (8191, 1, BF),       # one under the product threshold
    (8192, 1, OS),       # at threshold AND ratio 8192 >= 8
    (1023, 128, FFT),    # ratio just under 8
    (1024, 128, OS),     # ratio exactly 8
    (1025, 128, OS),     # ratio just over 8
    (4096, 4096, FFT),   # large balanced problem
]


@pytest.mark.parametrize("x_len,h_len,expect", BOUNDARY_CASES)
def test_decision_event_at_threshold_boundary(telemetry, x_len, h_len,
                                              expect):
    assert cv.select_algorithm(x_len, h_len) is expect
    handle = cv.convolve_initialize(x_len, h_len)
    assert handle.algorithm is expect
    ev = obs.events()[-1]
    assert ev["op"] == "convolve"
    assert ev["decision"] == expect.value
    assert ev["x_length"] == x_len and ev["h_length"] == h_len
    assert ev["forced"] is False
    if expect is OS:
        assert ev["block_length"] == handle.block_length
        assert ev["step"] == handle.step
    if expect is FFT:
        assert ev["fft_length"] == handle.fft_length


def test_forced_algorithm_flagged(telemetry):
    cv.convolve_initialize(100, 50, cv.ConvolutionAlgorithm.FFT)
    ev = obs.events()[-1]
    assert ev["decision"] == "fft" and ev["forced"] is True


# --------------------------------------------------------------------------
# dispatch-surface wiring
# --------------------------------------------------------------------------


def test_dispatch_counters_per_backend(telemetry):
    x, h = RNG.randn(64).astype(np.float32), np.ones(4, np.float32)
    cv.convolve(x, h, simd=True)
    cv.convolve(x, h, simd=False)
    assert obs.counter_value("dispatch", op="convolve",
                             backend="xla") == 1
    assert obs.counter_value("dispatch", op="convolve",
                             backend="oracle") == 1


def test_stft_istft_framing_decisions(telemetry):
    x = RNG.randn(2048).astype(np.float32)
    sp.stft(x, 256, 64, simd=True)           # 256 % 64 == 0, r=4
    assert obs.events()[-1]["op"] == "stft"
    assert obs.events()[-1]["decision"] == "reshape_interleave"
    sp.stft(x, 256, 96, simd=True)           # non-dividing hop
    assert obs.events()[-1]["decision"] == "gather"
    spec = sp.stft(x, 256, 64, simd=True)
    sp.istft(spec, 2048, 256, 64, simd=True)
    assert obs.events()[-1]["op"] == "istft"
    assert obs.events()[-1]["decision"] == "reshape_overlap_add"


def test_wavelet_decisions(telemetry):
    x = RNG.randn(4, 256).astype(np.float32)
    wv.wavelet_apply(WaveletType.DAUBECHIES, 8, wv.ExtensionType.PERIODIC,
                     x, simd=True)
    ev = obs.events()[-1]
    assert ev["op"] == "wavelet_apply"
    assert ev["decision"] in ("pallas", "xla_conv")
    assert ev["family"] == "daub" and ev["order"] == 8
    wv.wavelet_transform(WaveletType.DAUBECHIES, 4,
                         wv.ExtensionType.PERIODIC, x, 2, simd=True)
    evs = [e for e in obs.events() if e["op"] == "wavelet_transform"]
    assert evs[-1]["decision"] in ("level_loop", "fused_cascade")
    assert evs[-1]["levels"] == 2


def test_sharded_convolve_geometry_event(telemetry):
    from veles.simd_tpu.parallel import mesh as pm
    from veles.simd_tpu.parallel import ops as pops

    mesh = pm.default_mesh("sp")
    x = RNG.randn(1024).astype(np.float32)
    h = RNG.randn(17).astype(np.float32)
    pops.sharded_convolve(x, h, mesh, axis="sp")
    evs = [e for e in obs.events() if e["op"] == "sharded_convolve"]
    assert evs[-1]["decision"] == "one_hop_halo"
    assert evs[-1]["n_shards"] == mesh.shape["sp"]
    assert evs[-1]["halo"] == 16


# --------------------------------------------------------------------------
# the traced-program contract: telemetry must be invisible to XLA
# --------------------------------------------------------------------------


def _convolve_jaxpr():
    x = jnp.zeros(300, jnp.float32)
    h = jnp.zeros(30, jnp.float32)
    return str(jax.make_jaxpr(lambda a, b: cv.convolve(a, b))(x, h))


def _stft_jaxpr():
    x = jnp.zeros(1024, jnp.float32)
    return str(jax.make_jaxpr(
        lambda a: sp.stft(a, 128, 32, simd=True))(x))


@pytest.mark.parametrize("build", [_convolve_jaxpr, _stft_jaxpr],
                         ids=["convolve", "stft"])
def test_jaxpr_identical_with_telemetry_on_and_off(build):
    obs.disable()
    obs.reset()
    jaxpr_off = build()
    obs.enable()
    try:
        jaxpr_on = build()
        assert obs.events(), "telemetry was on but recorded nothing"
    finally:
        obs.disable()
        obs.reset()
    assert jaxpr_off == jaxpr_on


# --------------------------------------------------------------------------
# acceptance: a 1M-point convolve under telemetry tells the whole story
# --------------------------------------------------------------------------


def test_1m_convolve_snapshot_names_algorithm_and_compiles(telemetry):
    n, k = 1 << 20, 2049
    x = RNG.randn(n).astype(np.float32)
    h = RNG.randn(k).astype(np.float32)
    y = cv.convolve(x, h, simd=True)
    np.asarray(y[-1:])  # force execution
    snap = obs.snapshot()
    ev = [e for e in snap["events"] if e["op"] == "convolve"][-1]
    assert ev["decision"] == "overlap_save"       # x >= 8h
    assert ev["x_length"] == n and ev["h_length"] == k
    assert obs.counter_value("dispatch", op="convolve",
                             backend="xla") >= 1
    # the jax.monitoring bridge saw the backend compile
    assert obs.counter_value("compile.backend_compile") >= 1
    hists = {h_["name"] for h_ in snap["histograms"]}
    assert "compile.backend_compile_secs" in hists
    # exportable both ways, naming the selected algorithm
    as_json = obs.to_json(snap)
    assert "overlap_save" in as_json
    parsed = obs_export.parse_prometheus(obs.to_prometheus(snap))
    assert parsed[("veles_simd_decisions_total",
                   (("decision", "overlap_save"),
                    ("op", "convolve")))] >= 1
