"""Replica-group serving: N servers behind a breaker-aware front router.

One :class:`~veles.simd_tpu.serve.server.Server` is one process on one
host mesh — one health machine, one admission bound, one ceiling.
This module is the layer that removes the ceiling (ROADMAP item 3, the
"millions of users" shape): a :class:`ReplicaGroup` managing N server
replicas, and a :class:`FrontRouter` placing each submitted request on
one of them, built so the *service* survives losing a whole replica
the way PRs 9-10 proved a single server survives losing a device:

* **placement** — :meth:`FrontRouter.submit` scores every live
  replica for the request's shape class and places on the cheapest:
  admitted queue depth (:meth:`Server.depth`) is the base load signal,
  a DEGRADED health machine adds a large penalty, and an OPEN circuit
  breaker *for that shape class* (the replica-keyed
  ``serve.dispatch`` breaker) adds a class-local penalty — an open
  breaker or degraded replica is **deprioritized per shape class, not
  blacklisted globally** (its other classes, and last-resort traffic,
  still flow).  Padding-aware placement subtracts an **occupancy
  bonus** (``$VELES_SIMD_ROUTER_OCCUPANCY_WEIGHT``) for a replica
  whose batcher already holds a forming batch of the request's shape
  class — the request completes that batch instead of opening one
  that will pad.  ``VELES_SIMD_ROUTER_POLICY=round_robin`` swaps the
  scoring for a rotation (the A/B control);
* **failover** — every backend ticket carries a completion hook: a
  replica that dies with the request queued (``status="closed"``) or
  sheds it (``status="shed"``) triggers re-submission onto a
  surviving replica with the *original* end-to-end deadline carried
  over (the absolute deadline is stamped once at router admission;
  every re-submission gets the remaining budget, never a fresh one)
  and a shared failover budget (``max_failovers`` across ALL
  attempts, not per replica).  The router ticket is deduped by its
  router rid — it completes exactly once, so the group-wide
  zero-double-answer accounting holds even if a late duplicate
  completion ever raced (counted ``router_dedup``, never surfaced);
* **draining** — :meth:`ReplicaGroup.drain` is graceful removal:
  intake stops (the router skips DRAINING replicas), in-flight and
  queued work is answered by the replica itself, and only then is the
  replica DEAD — zero lost requests by construction.
  :meth:`ReplicaGroup.kill` is the abrupt form (no drain): queued
  work is answered ``closed`` and *re-routed by the failover hook*
  onto survivors;
* **heartbeats** — the group heartbeats every replica on a fixed
  cadence (``VELES_SIMD_HEARTBEAT_MS``); ``miss_limit`` consecutive
  missed beats mark the replica wedged and auto-drain it without
  operator action (``replica_lifecycle``/``wedged`` decision event).
  The ``cluster.heartbeat@<rid>`` injection site makes a wedge
  deterministic on CPU CI (``VELES_SIMD_FAULT_PLAN``);
* **aggregation endpoint** — :meth:`ReplicaGroup.start` arms ONE
  router-level scrape endpoint (``obs_port=`` / ``$VELES_SIMD_OBS_PORT``;
  per-replica endpoints stay disarmed in thread mode): ``/healthz``
  answers 200 while at least one replica is up and healthy, 503 once
  none is — the load-balancer contract, live through kills and drains
  (the replicated chaos campaign gates exactly that);
* **fleet collector — the obs v5 feed** — a collector thread sweeps
  the group every ``$VELES_SIMD_FLEET_TICK_MS`` (default 100 ms):
  in-process replicas are sampled directly (depth / health /
  completed counts / open breakers), subprocess replicas are scraped
  over their existing ``/metrics`` endpoints (a failed scrape is a
  counted ``fleet_scrape_stale``, never a crash), and every sample
  lands in the bounded fleet store
  (:mod:`veles.simd_tpu.obs.timeseries`, window
  ``$VELES_SIMD_FLEET_WINDOW``).  ``obs.signals()`` reads the typed
  bundle back out; the aggregation endpoint serves it as
  ``/signals``.  ``_collect_fleet_sample`` is THE cross-replica
  metrics funnel (lint-enforced): serve/cluster code never scrapes
  registries ad hoc;
* **autoscaler — the obs v7 control axis** — when armed
  (``scaler=True`` / ``$VELES_SIMD_SCALER``), the group starts a
  :class:`~veles.simd_tpu.serve.scaler.ScalerEngine` alongside the
  collector: it reads ``obs.signals()`` on its own cadence and acts
  back through the group's verbs — :meth:`spawn_replica` under
  rising SLO burn or queue velocity, :meth:`retire` of the
  least-loaded replica after a sustained idle window,
  :meth:`restart` of down/stale replicas — every tick a journaled
  ``scaler`` decision event (``make chaos-scale`` is the scripted
  proof).

**Spawn modes.** ``spawn="thread"`` (default) runs replicas as
in-process servers — the CI topology.  ``spawn="subprocess"`` runs
each replica as a child process (``python -m
veles.simd_tpu.serve.cluster``) that arms its own ``/healthz`` +
``/metrics`` + ``POST /submit`` endpoint and reports its port; the
group heartbeats it over HTTP, and the :class:`FrontRouter` places
requests on it over the RPC data plane
(:mod:`veles.simd_tpu.serve.rpc`): each subprocess replica carries a
pooled persistent-connection :class:`~veles.simd_tpu.serve.rpc.
RpcClient`, requests cross the wire in binary npy framing with the
remaining deadline budget re-stamped per attempt, and the typed
errors (``Overloaded`` / ``DeadlineExceeded`` / ``ServerClosed`` /
shed) map losslessly back — so failover, shed, and carried-deadline
semantics are identical to the in-process path and both spawn modes
flow through the same ``_submit_to_replica`` funnel.  Pipelines
cross the process boundary declaratively: pass ``pipeline_specs=``
(:func:`veles.simd_tpu.pipeline.pipeline_from_spec` specs) to the
group and each child rebuilds, compiles, and registers them before
reporting ready.

Usage::

    from veles.simd_tpu.serve import cluster

    with cluster.ReplicaGroup(3, max_batch=8, obs_port=0) as group:
        router = cluster.FrontRouter(group)
        t = router.submit(op="sosfilt", x=x, params={"sos": sos})
        y = t.result(timeout=5.0)
        group.kill("r0")        # abrupt: queued work fails over
        group.drain("r1")       # graceful: answered, then removed

Chaos: ``make chaos-replicas`` (``tools/chaos.py --replicas``) runs
the scripted replica-kill campaign — one replica killed without drain
and one drained gracefully mid-traffic, gated on zero lost / zero
double-answered requests across the group, carried failover
deadlines, survivor absorption, terminal traces on the killed
replica's requests, and a live group ``/healthz`` throughout.
"""

from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import threading

from veles.simd_tpu import obs
from veles.simd_tpu.obs import export as obs_export
from veles.simd_tpu.obs import http as obs_http
from veles.simd_tpu.obs import incidents as obs_incidents
from veles.simd_tpu.obs import journal as obs_journal
from veles.simd_tpu.obs import timeseries as _timeseries
from veles.simd_tpu.runtime import breaker as _breaker
from veles.simd_tpu.runtime import faults
from veles.simd_tpu.serve import rpc as _rpc
from veles.simd_tpu.serve import scaler as _scaler
from veles.simd_tpu.serve.admission import Overloaded
from veles.simd_tpu.serve.server import (DeadlineExceeded, Request,
                                         Server, ServerClosed,
                                         classify_request,
                                         env_deadline_ms)

__all__ = [
    "Replica", "ReplicaGroup", "FrontRouter", "RouterTicket",
    "NoReplicaAvailable", "UP", "DRAINING", "DEAD", "RESTARTING",
    "REPLICAS_ENV", "ROUTER_POLICY_ENV", "HEARTBEAT_MS_ENV",
    "OCCUPANCY_WEIGHT_ENV",
    "DEFAULT_REPLICAS", "DEFAULT_HEARTBEAT_MS", "DEFAULT_MISS_LIMIT",
    "DEFAULT_OCCUPANCY_WEIGHT",
    "ROUTER_POLICIES", "env_replicas", "env_router_policy",
    "env_heartbeat_s", "env_occupancy_weight",
]

REPLICAS_ENV = "VELES_SIMD_REPLICAS"
ROUTER_POLICY_ENV = "VELES_SIMD_ROUTER_POLICY"
HEARTBEAT_MS_ENV = "VELES_SIMD_HEARTBEAT_MS"
OCCUPANCY_WEIGHT_ENV = "VELES_SIMD_ROUTER_OCCUPANCY_WEIGHT"

# two replicas is the smallest group with a failover story; the env
# default exists for tooling (loadgen --replicas 0 -> env -> 2)
DEFAULT_REPLICAS = 2
# 100 ms heartbeats notice a wedged replica in ~miss_limit/10 s while
# costing ~10 lock-cheap pings/s per replica
DEFAULT_HEARTBEAT_MS = 100.0
DEFAULT_MISS_LIMIT = 3

LEAST_LOADED = "least_loaded"
ROUND_ROBIN = "round_robin"
ROUTER_POLICIES = (LEAST_LOADED, ROUND_ROBIN)

# replica lifecycle states
UP = "up"
DRAINING = "draining"
DEAD = "dead"
# transient restart() guard state: not placeable, not re-restartable
RESTARTING = "restarting"

# scoring: depth is O(queue); the penalties must dominate any sane
# queue depth so a healthy replica always outranks a degraded one for
# the class, while a lone degraded survivor still takes traffic
# (deprioritized, not blacklisted)
BREAKER_OPEN_PENALTY = 1e3
DEGRADED_PENALTY = 1e6

# padding-aware placement: a replica with a FORMING batch of the
# request's shape class gets a bonus (the request completes that
# batch — riding a padding slot — instead of opening a fresh one
# that will pad).  The term is bounded strictly below 1 request of
# depth so it only breaks near-ties, never outranks real load.
DEFAULT_OCCUPANCY_WEIGHT = 0.5


def env_replicas() -> int:
    """Group size from ``$VELES_SIMD_REPLICAS`` (default 2)."""
    raw = os.environ.get(REPLICAS_ENV, "").strip()
    if not raw:
        return DEFAULT_REPLICAS
    try:
        value = int(raw)
    except ValueError:
        return DEFAULT_REPLICAS
    return value if value >= 1 else DEFAULT_REPLICAS


def env_router_policy() -> str:
    """Placement policy from ``$VELES_SIMD_ROUTER_POLICY``
    (``least_loaded`` default / ``round_robin``)."""
    raw = os.environ.get(ROUTER_POLICY_ENV, "").strip().lower()
    return raw if raw in ROUTER_POLICIES else LEAST_LOADED


def env_heartbeat_s() -> float:
    """Heartbeat interval in seconds from ``$VELES_SIMD_HEARTBEAT_MS``
    (default 100 ms)."""
    raw = os.environ.get(HEARTBEAT_MS_ENV, "").strip()
    if not raw:
        return DEFAULT_HEARTBEAT_MS / 1e3
    try:
        value = float(raw)
    except ValueError:
        return DEFAULT_HEARTBEAT_MS / 1e3
    return (value if value > 0 else DEFAULT_HEARTBEAT_MS) / 1e3


def env_occupancy_weight() -> float:
    """Occupancy-bonus weight for the padding-aware placement term
    from ``$VELES_SIMD_ROUTER_OCCUPANCY_WEIGHT`` (default 0.5;
    0 disables the term; negative / malformed falls back)."""
    raw = os.environ.get(OCCUPANCY_WEIGHT_ENV, "").strip()
    if not raw:
        return DEFAULT_OCCUPANCY_WEIGHT
    try:
        value = float(raw)
    except ValueError:
        return DEFAULT_OCCUPANCY_WEIGHT
    return value if value >= 0 else DEFAULT_OCCUPANCY_WEIGHT


class NoReplicaAvailable(Overloaded):
    """Typed router rejection: no live replica could take the request
    (none up, or the failover budget died with the last candidate).
    An :class:`~veles.simd_tpu.serve.admission.Overloaded` subclass —
    group exhaustion is admission exhaustion at cluster scope, and
    every consumer that already handles typed sheds handles this."""

    def __init__(self, message: str, *, tenant: str = "default"):
        super().__init__(message, tenant=tenant, scope="cluster")


def _call_with_timeout(fn, timeout_s: float):
    """Run ``fn()`` under the fault engine's dispatch watchdog
    (:func:`faults._call_with_deadline` — ONE home for the
    abandoned-daemon-thread containment), translating its typed
    :class:`faults.FaultTimeout` into the stdlib TimeoutError the
    spawn-handshake caller classifies on.  Used for the one-shot
    subprocess port handshake; steady-state heartbeats run on
    persistent prober threads instead (no per-call thread churn)."""
    try:
        return faults._call_with_deadline(fn, timeout_s,
                                          "cluster.spawn")
    except faults.FaultTimeout as e:
        raise TimeoutError(str(e)) from e


class Replica:
    """One managed server replica: identity (``rid``), lifecycle
    state, heartbeat bookkeeping, and the spawn-mode-specific start /
    ping / stop plumbing.  Thread mode holds a live in-process
    :class:`Server` (named ``rid``, so its breakers/health are
    replica-keyed); subprocess mode holds a child process, the port
    of its ``/healthz``+``/metrics``+``/submit`` endpoint, and the
    pooled :class:`~veles.simd_tpu.serve.rpc.RpcClient` the router
    places requests through.  ``pipeline_specs`` (declarative
    :func:`~veles.simd_tpu.pipeline.pipeline_from_spec` dicts) are
    forwarded to a subprocess child, which registers the compiled
    chains before reporting ready."""

    def __init__(self, rid: str, *, spawn: str = "thread",
                 server_kwargs: dict | None = None,
                 pipeline_specs: list | None = None):
        self.rid = str(rid)
        self.spawn = spawn
        self.state = UP
        self.misses = 0
        self.last_beat = None
        # birth stamp: the fleet collector exports age as the
        # per-replica ``birth_age_s`` series (scaler/dashboard input)
        self.born = faults.monotonic()
        # last health state a ping observed ("healthy"/"degraded";
        # None = never pinged) — the subprocess aggregation signal,
        # since the group cannot read a child's health machine
        # in-process
        self.last_health = None
        self.server: Server | None = None
        self.proc = None
        self.port = None
        # the RPC data plane handle (subprocess mode only): armed in
        # start() once the child reports its port, closed after the
        # child is gone so in-flight answers drain first
        self.rpc: _rpc.RpcClient | None = None
        self._pipeline_specs = [dict(s) for s in
                                (pipeline_specs or [])]
        self._kwargs = dict(server_kwargs or {})
        if spawn == "thread":
            # per-replica endpoints stay disarmed: the group owns ONE
            # aggregation endpoint (N in-process replicas arming N
            # ports from one env var is the EndpointUnavailable story)
            self._kwargs.setdefault("obs_port", -1)
            self.server = Server(name=self.rid, **self._kwargs)
        elif spawn != "subprocess":
            raise ValueError(
                f"spawn must be 'thread' or 'subprocess', got "
                f"{spawn!r}")

    # -- lifecycle ---------------------------------------------------------

    def start(self, spawn_timeout_s: float = 60.0) -> None:
        if self.spawn == "thread":
            self.server.start()
            return
        port_arg = int(self._kwargs.get("obs_port") or 0)
        if port_arg < 0:
            raise ValueError(
                "subprocess replicas need a scrape endpoint (their "
                "/healthz IS the heartbeat surface) — obs_port must "
                "be >= 0 (0 = ephemeral), not disarmed")
        # -c instead of -m: the serve package imports this module at
        # init, and runpy warns on re-executing an already-imported
        # submodule in the child
        cmd = [sys.executable, "-c",
               "import sys; "
               "from veles.simd_tpu.serve.cluster import _replica_main; "
               "sys.exit(_replica_main(sys.argv[1:]))",
               "--obs-port", str(port_arg),
               # the child stamps this identity into its own journal
               # file (it inherits $VELES_SIMD_JOURNAL_DIR and writes
               # journal-<childpid>-*.jsonl in the shared pack), so
               # obs_query can attribute its records after it is dead
               "--name", self.rid]
        # forward the server policy knobs the child's Server takes —
        # a subprocess replica must run the operator's batching/worker
        # policy, not silent defaults
        for flag, key in (("--max-batch", "max_batch"),
                          ("--max-wait-ms", "max_wait_ms"),
                          ("--workers", "workers")):
            value = self._kwargs.get(key)
            if value is not None:
                cmd += [flag, str(value)]
        # pipelines cross the process boundary declaratively: the
        # child rebuilds + registers each spec before reporting ready,
        # so the router never places pipeline traffic on a replica
        # that would answer "unregistered pipeline"
        for spec in self._pipeline_specs:
            cmd += ["--pipeline-spec", json.dumps(spec)]
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True)
        # the child prints one JSON line with its bound endpoint port
        # once its server is up; anything else on stdout is skipped.
        # Each readline runs under the remaining-deadline watchdog —
        # a child that wedges before reporting (and never closes
        # stdout) must raise, not hang group.start() forever.
        deadline = faults.monotonic() + spawn_timeout_s
        while True:
            remaining = deadline - faults.monotonic()
            if remaining <= 0:
                self.proc.kill()
                raise TimeoutError(
                    f"replica {self.rid} subprocess did not report "
                    f"its endpoint port within {spawn_timeout_s}s")
            try:
                line = _call_with_timeout(self.proc.stdout.readline,
                                          remaining)
            except TimeoutError:
                self.proc.kill()
                raise TimeoutError(
                    f"replica {self.rid} subprocess did not report "
                    f"its endpoint port within {spawn_timeout_s}s")
            if not line:
                raise RuntimeError(
                    f"replica {self.rid} subprocess exited before "
                    f"reporting its endpoint port "
                    f"(rc={self.proc.poll()})")
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if isinstance(msg, dict) \
                    and msg.get("port") is not None:
                self.port = int(msg["port"])
                # arm the data plane: pooled keep-alive connections
                # into the child's POST /submit route
                self.rpc = _rpc.RpcClient(obs_http.BIND_HOST,
                                          self.port,
                                          replica=self.rid)
                return

    def ping(self) -> dict:
        """One heartbeat: the ``cluster.heartbeat@<rid>`` injection
        site fires first (deterministic wedge simulation), then the
        replica's health surface is read — in-process stats in thread
        mode, ``GET /healthz`` in subprocess mode (200 *and* 503 are
        beats: a degraded replica is alive).  Any exception is a
        missed beat."""
        faults.inject(f"cluster.heartbeat@{self.rid}")
        if self.spawn == "thread":
            self.last_health = self.server.health
            return {"state": self.last_health,
                    "depth": self.server.depth()}
        import urllib.error
        import urllib.request

        url = f"http://{obs_http.BIND_HOST}:{self.port}/healthz"
        code = 200
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                body = r.read()
        except urllib.error.HTTPError as e:
            if e.code != 503:
                raise
            code, body = e.code, e.read()   # degraded but alive
        parsed = json.loads(body)
        health = parsed.get("health")
        if isinstance(health, dict):
            health = health.get("state")
        self.last_health = ("degraded" if code == 503
                            else health or "healthy")
        return parsed

    def kill(self) -> None:
        """Abrupt stop: no drain — queued work answers ``closed`` (and
        the front router's failover hook re-routes it)."""
        if self.spawn == "thread":
            self.server.stop(drain=False)
        elif self.proc is not None:
            self.proc.kill()
            self.proc.wait()
            if self.rpc is not None:
                # after the child is gone: every in-flight RPC hits a
                # dead socket and answers typed "closed" — the
                # failover hook re-routes exactly as in thread mode
                self.rpc.close()

    def drain_stop(self) -> None:
        """Graceful stop: queued and in-flight work is answered by
        this replica before it exits."""
        if self.spawn == "thread":
            self.server.stop(drain=True)
        elif self.proc is not None:
            try:        # closing stdin asks the child to drain + exit
                self.proc.stdin.close()
            except OSError:
                pass
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.proc.terminate()
                self.proc.wait()
            if self.rpc is not None:
                # the child drained before exiting, so its answers
                # are already on the wire; close() lets the sender
                # threads finish them, then typed-closes stragglers
                self.rpc.close()

    def snapshot(self) -> dict:
        """JSON-native view for the group's aggregation endpoint."""
        info = {"rid": self.rid, "state": self.state,
                "spawn": self.spawn, "misses": self.misses,
                "last_beat": self.last_beat}
        if self.spawn == "thread" and self.state != DEAD:
            info["health"] = self.server.health
            info["depth"] = self.server.depth()
            info["counts"] = self.server.stats()["counts"]
        elif self.spawn == "subprocess":
            # the last ping's observation, not a live read: an
            # unresponsive child keeps its last-known state until the
            # heartbeat machinery drains it
            info["health"] = self.last_health or "healthy"
            info["port"] = self.port
            if self.proc is not None:
                info["returncode"] = self.proc.poll()
            if self.rpc is not None:
                # the data-plane health block obs_dash --fleet reads:
                # in-flight, connection-reuse ratio, transport errors
                info["rpc"] = self.rpc.stats()
        return info


class ReplicaGroup:
    """N managed replicas + the heartbeat loop + the aggregation
    endpoint (module docstring has the full story).  ``replicas`` is a
    count (default ``$VELES_SIMD_REPLICAS``); remaining keyword
    arguments are passed to every replica's :class:`Server` in thread
    mode.  Use as a context manager, or :meth:`start`/:meth:`stop`."""

    def __init__(self, replicas: int | None = None, *,
                 spawn: str = "thread",
                 heartbeat_ms: float | None = None,
                 miss_limit: int = DEFAULT_MISS_LIMIT,
                 obs_port: int | None = None,
                 fleet_tick_ms: float | None = None,
                 scaler: bool | None = None,
                 scaler_tick_ms: float | None = None,
                 scaler_kwargs: dict | None = None,
                 pipeline_specs: list | None = None,
                 **server_kwargs):
        n = int(replicas) if replicas else env_replicas()
        if n < 1:
            raise ValueError("a replica group needs >= 1 replica")
        self.spawn = spawn
        self.heartbeat_s = (float(heartbeat_ms) / 1e3
                            if heartbeat_ms else env_heartbeat_s())
        self.miss_limit = int(miss_limit)
        if self.miss_limit < 1:
            raise ValueError("miss_limit must be >= 1")
        self._server_kwargs = dict(server_kwargs)
        # pipelines registered through the GROUP, replayed onto a
        # restarted replica (a fresh Server has no registrations —
        # without the replay, the router would place pipeline traffic
        # onto a replica that answers "unregistered pipeline")
        self._group_pipelines: dict = {}
        # declarative pipeline specs (pipeline_from_spec dicts): the
        # ONE pipeline spelling that survives a process boundary —
        # thread mode compiles + registers them at start(); subprocess
        # children rebuild them from their command line
        self._pipeline_specs = [dict(s) for s in
                                (pipeline_specs or [])]
        self.replicas = [
            Replica(f"r{i}", spawn=spawn,
                    server_kwargs=server_kwargs,
                    pipeline_specs=self._pipeline_specs)
            for i in range(n)]
        self._by_rid = {r.rid: r for r in self.replicas}
        self._lock = threading.Lock()
        self._obs_port_arg = obs_port
        self._endpoint = None
        self._hb_stop = threading.Event()
        self._hb_thread = None
        self._probers: list = []
        # fleet collector (obs v5): cadence from fleet_tick_ms= or
        # $VELES_SIMD_FLEET_TICK_MS; the thread starts in start()
        self.fleet_tick_s = (float(fleet_tick_ms) / 1e3
                             if fleet_tick_ms
                             else _timeseries.env_tick_s())
        self._collector_thread = None
        self._started = False
        self._incidents_hold = False
        # control axis (obs v7): the SLO-driven autoscaler, OFF by
        # default (an idle test group must not get scale-down-drained
        # under the test's feet) — armed by scaler=True or a truthy
        # $VELES_SIMD_SCALER; started/stopped with the group
        self._scaler_armed = (bool(scaler) if scaler is not None
                              else _scaler.armed_by_env())
        self._scaler_tick_s = (float(scaler_tick_ms) / 1e3
                               if scaler_tick_ms else None)
        self._scaler_kwargs = dict(scaler_kwargs or {})
        self._scaler_engine = None
        # spawn_replica() mints r<next>: never reuse a live/dead rid
        self._next_rid = n
        self._sweeps = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplicaGroup":
        """Start every replica, the heartbeat loop, and (when armed)
        the router-level aggregation endpoint (idempotent)."""
        if self._started:
            return self
        # the endpoint arms first — same contract as Server.start: a
        # bind failure (EndpointUnavailable) leaves nothing running
        if self._obs_port_arg is not None and self._obs_port_arg < 0:
            self._endpoint = None
        else:
            self._endpoint = obs_http.start(self._obs_port_arg,
                                            health=self.stats)
        try:
            for r in self.replicas:
                r.start()
        except BaseException:
            for r in self.replicas:
                try:
                    r.kill()
                except Exception:  # noqa: BLE001 — best-effort unwind
                    pass
            if self._endpoint is not None:
                self._endpoint.stop()
                self._endpoint = None
            raise
        self._started = True
        if self.spawn == "thread" and self._pipeline_specs:
            # subprocess children registered their specs before
            # reporting ready; thread replicas compile + register the
            # same specs here, so both spawn modes answer the same
            # pipeline surface
            from veles.simd_tpu import pipeline as _pl

            for spec in self._pipeline_specs:
                self.register_pipeline(spec["name"],
                                       _pl.pipeline_from_spec(spec))
        for r in self.replicas:
            t = threading.Thread(target=self._probe_replica,
                                 args=(r,), daemon=True,
                                 name=f"veles-replica-probe-{r.rid}")
            t.start()
            self._probers.append(t)
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name="veles-replica-heartbeat")
        self._hb_thread.start()
        self._collector_thread = threading.Thread(
            target=self._collector_loop, daemon=True,
            name="veles-fleet-collector")
        self._collector_thread.start()
        # the incident engine rides the collector: it ticks over
        # obs.signals() (which the collector feeds) and serves
        # /incidents on this group's aggregation endpoint; open/close
        # edges flow through record_decision — the journal funnel.
        # Starts are reference-counted, so this group only holds (and
        # later releases) its own share of the process-wide ticker.
        obs_incidents.start()
        self._incidents_hold = True
        # the control axis rides the same feed: the scaler ticks over
        # obs.signals() and acts back through THIS group's verbs; its
        # engine registers module-level so /scaler and
        # obs.scaler_snapshot() serve it
        if self._scaler_armed:
            self._scaler_engine = _scaler.ScalerEngine(
                self, **self._scaler_kwargs)
            _scaler._register(self._scaler_engine)
            self._scaler_engine.start(self._scaler_tick_s)
        obs.gauge("replica_alive", float(self.alive()))
        obs.record_decision("replica_lifecycle", "group_start",
                            replicas=len(self.replicas),
                            spawn=self.spawn)
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the heartbeat loop and every live replica (drained or
        abruptly), then the aggregation endpoint."""
        # the scaler stops FIRST: no verb may fire into a group that
        # is tearing down
        if self._scaler_engine is not None:
            self._scaler_engine.stop()
            _scaler._unregister(self._scaler_engine)
            self._scaler_engine = None
        if self._incidents_hold:
            self._incidents_hold = False
            obs_incidents.stop()
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
            self._hb_thread = None
        if self._collector_thread is not None:
            self._collector_thread.join(timeout=5.0)
            self._collector_thread = None
        for t in self._probers:
            # a prober wedged inside a replica's ping cannot be
            # joined — it is daemon-contained, not waited on
            t.join(timeout=1.0)
        self._probers = []
        for r in self.replicas:
            with self._lock:
                if r.state == DEAD:
                    continue
                r.state = DEAD
            if drain:
                r.drain_stop()
            else:
                r.kill()
        obs.gauge("replica_alive", 0.0)
        if self._endpoint is not None:
            self._endpoint.stop()
            self._endpoint = None

    def __enter__(self) -> "ReplicaGroup":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop(drain=exc_type is None)
        return False

    # -- membership --------------------------------------------------------

    def replica(self, rid: str) -> Replica:
        """The replica named ``rid`` (KeyError otherwise)."""
        return self._by_rid[rid]

    def alive(self) -> int:
        """Replicas currently accepting placements (state UP)."""
        with self._lock:
            return sum(1 for r in self.replicas if r.state == UP)

    def live_replicas(self) -> list:
        """The placeable replicas (state UP), in id order."""
        with self._lock:
            return [r for r in self.replicas if r.state == UP]

    def kill(self, rid: str) -> None:
        """Abrupt removal, no drain: the replica is un-placeable
        immediately, its queued-but-unanswered work answers ``closed``
        and is re-routed by the router's failover hook.  The scripted
        campaign's mid-traffic kill."""
        r = self._by_rid[rid]
        with self._lock:
            if r.state == DEAD:
                return
            r.state = DEAD
        obs.record_decision("replica_lifecycle", "kill", replica=rid)
        obs.count("replica_killed", replica=rid)
        r.kill()
        obs.gauge("replica_alive", float(self.alive()))

    def drain(self, rid: str, reason: str = "operator") -> None:
        """Graceful removal: stop intake (the router skips DRAINING
        replicas), answer everything queued and in flight, then mark
        DEAD.  Subsequent traffic redistributes to the survivors."""
        r = self._by_rid[rid]
        with self._lock:
            if r.state != UP:
                return
            r.state = DRAINING
        obs.record_decision("replica_lifecycle", "drain", replica=rid,
                            reason=reason)
        obs.count("replica_drained", replica=rid)
        r.drain_stop()
        with self._lock:
            r.state = DEAD
        obs.record_decision("replica_lifecycle", "dead", replica=rid,
                            reason=reason)
        obs.gauge("replica_alive", float(self.alive()))

    def restart(self, rid: str) -> Replica:
        """Cold-restart a DEAD replica under the same id: a FRESH
        :class:`Replica` (new Server / new subprocess, the group's
        original server kwargs) replaces the dead record, starts —
        which preloads the warm artifact pack when the store is armed
        (``Server.start``) — and rejoins heartbeating and placement.
        This is the autoscaling/preemption-recovery moment the
        zero-warmup subsystem exists for, and the chaos campaign's
        cold-replica-restart phase gates exactly this path: the
        restarted replica's FIRST request must land within budget of
        the survivors' steady state.  Restarting a live replica is a
        ValueError (kill or drain it first)."""
        with self._lock:
            old = self._by_rid[rid]
            if old.state != DEAD:
                # also closes the concurrent-restart race: the first
                # caller flips the record to RESTARTING under this
                # lock, so a second restart() of the same rid raises
                # instead of starting a twin Server nothing would
                # ever stop
                raise ValueError(
                    f"replica {rid!r} is {old.state!r}, not dead — "
                    "kill() or drain() it before restart()")
            old.state = RESTARTING
        try:
            fresh = Replica(rid, spawn=self.spawn,
                            server_kwargs=self._server_kwargs,
                            pipeline_specs=self._pipeline_specs)
            fresh.start()
            if self.spawn == "thread":
                # a fresh Server has no pipeline registrations —
                # replay the group's so pipeline traffic placed here
                # keeps answering
                for name, compiled in self._group_pipelines.items():
                    fresh.server.register_pipeline(name, compiled)
        except BaseException:
            with self._lock:
                old.state = DEAD     # a failed restart stays dead
            raise
        # treat the successful start as the first beat: the staleness
        # monitor otherwise judges last_beat=None against the GROUP
        # start time and would wedge-drain a replica restarted any
        # real interval later, before its prober's first ping lands
        fresh.last_beat = faults.monotonic()
        with self._lock:
            self._by_rid[rid] = fresh
            self.replicas = [fresh if r.rid == rid else r
                             for r in self.replicas]
        if self._started:
            t = threading.Thread(target=self._probe_replica,
                                 args=(fresh,), daemon=True,
                                 name=f"veles-replica-probe-{rid}")
            t.start()
            self._probers.append(t)
        obs.record_decision("replica_lifecycle", "restart",
                            replica=rid)
        obs.count("replica_restarted", replica=rid)
        obs.gauge("replica_alive", float(self.alive()))
        return fresh

    def spawn_replica(self) -> Replica:
        """Grow the group by one: a FRESH replica under a never-used
        id (``r<next>``) starts — preloading the warm artifact pack
        when the store is armed, the ~23%-of-cold birth the scaler's
        scale-up counts on — gets the group's pipeline registrations
        replayed, and joins heartbeating and placement.  The scaler's
        scale-up verb; also an operator verb in its own right."""
        if not self._started:
            raise ValueError(
                "spawn_replica() needs a started group (the probers "
                "and collector it joins only run after start())")
        with self._lock:
            rid = f"r{self._next_rid}"
            self._next_rid += 1
        fresh = Replica(rid, spawn=self.spawn,
                        server_kwargs=self._server_kwargs,
                        pipeline_specs=self._pipeline_specs)
        fresh.start()
        if self.spawn == "thread":
            for name, compiled in self._group_pipelines.items():
                fresh.server.register_pipeline(name, compiled)
        # the successful start is the first beat (same staleness
        # rationale as restart())
        fresh.last_beat = faults.monotonic()
        with self._lock:
            self.replicas = self.replicas + [fresh]
            self._by_rid[rid] = fresh
        t = threading.Thread(target=self._probe_replica,
                             args=(fresh,), daemon=True,
                             name=f"veles-replica-probe-{rid}")
        t.start()
        self._probers.append(t)
        obs.record_decision("replica_lifecycle", "spawn",
                            replica=rid)
        obs.count("replica_spawned", replica=rid)
        obs.gauge("replica_alive", float(self.alive()))
        return fresh

    def retire(self, rid: str, reason: str = "scale_down") -> None:
        """Shrink the group by one: gracefully :meth:`drain` ``rid``
        (zero lost by construction), then REMOVE it from membership —
        unlike a plain drain, the record does not linger as a DEAD
        replica, so the fleet collector stops sampling it (and
        forgets its series) and the incident engine's ``replica_down``
        rule does not fire forever on an intentional scale-down.  The
        scaler's scale-down verb."""
        self.drain(rid, reason=reason)
        with self._lock:
            self._by_rid.pop(rid, None)
            self.replicas = [x for x in self.replicas
                             if x.rid != rid]
        obs.record_decision("replica_lifecycle", "retire",
                            replica=rid, reason=reason)
        obs.count("replica_retired", replica=rid)
        obs.gauge("replica_alive", float(self.alive()))

    def register_pipeline(self, name: str, compiled) -> str:
        """Register a compiled pipeline on EVERY thread-mode replica
        (the group twin of :meth:`Server.register_pipeline`); returns
        the op string.  Recorded group-side too, so a replica revived
        by :meth:`restart` gets the same registrations replayed."""
        if self.spawn != "thread":
            raise ValueError(
                "a compiled pipeline cannot cross a process boundary "
                "— pass pipeline_specs= (declarative "
                "pipeline_from_spec dicts) to the group instead; "
                "subprocess replicas rebuild and register them "
                "before taking traffic")
        op = None
        for r in self.replicas:
            op = r.server.register_pipeline(name, compiled)
        self._group_pipelines[str(name)] = compiled
        return op

    # -- heartbeats --------------------------------------------------------
    #
    # One PERSISTENT prober thread per replica (no per-ping watchdog
    # threads — a 100 ms cadence over N replicas would otherwise mint
    # 10*N threads/s steady-state): the prober pings on the cadence,
    # stamping last_beat / counting misses; a ping that RAISES (the
    # injected-wedge form) counts a miss immediately, and a ping that
    # BLOCKS wedges only its own prober — the monitor loop notices
    # the stale last_beat and triggers the same auto-drain, so a
    # truly wedged replica is contained by exactly one abandoned
    # thread, never an accumulating pile.

    def _mark_wedged(self, r: Replica, reason: str) -> None:
        obs.record_decision("replica_lifecycle", "wedged",
                            replica=r.rid, misses=r.misses,
                            error=reason[:200])
        # auto-drain off-thread: state flips to DRAINING inside
        # drain() immediately (intake stops), while a truly wedged
        # stop can block only its own daemon thread
        threading.Thread(target=self.drain, args=(r.rid, "wedged"),
                         daemon=True,
                         name=f"veles-replica-drain-{r.rid}").start()

    def _probe_replica(self, r: Replica) -> None:
        while r.state == UP and not self._hb_stop.is_set():
            try:
                r.ping()
            except Exception as e:  # noqa: BLE001 — any = miss
                r.misses += 1
                obs.count("replica_heartbeat_miss", replica=r.rid)
                if r.misses >= self.miss_limit and r.state == UP:
                    self._mark_wedged(r, str(e))
                    return
            else:
                r.misses = 0
                r.last_beat = faults.monotonic()
            self._hb_stop.wait(self.heartbeat_s)

    def _heartbeat_loop(self) -> None:
        """The staleness monitor: a prober whose ping BLOCKS can't
        count its own misses — this loop watches last_beat age and
        drains a replica whose beats stopped arriving.  The floor is
        seconds-scale on purpose: a CPU-bound XLA compile holds the
        GIL long enough to starve a perfectly healthy prober for
        hundreds of milliseconds, and a starved prober must never
        read as a wedged replica (a ping that RAISES is the fast
        path — the prober counts those misses itself on the
        heartbeat cadence)."""
        stale_s = max(self.miss_limit * self.heartbeat_s, 5.0)
        started = faults.monotonic()
        while not self._hb_stop.wait(self.heartbeat_s):
            now = faults.monotonic()
            for r in self.replicas:
                if r.state != UP:
                    continue
                ref = r.last_beat if r.last_beat is not None \
                    else started
                if now - ref > stale_s:
                    r.misses = max(r.misses, self.miss_limit)
                    obs.count("replica_heartbeat_miss",
                              replica=r.rid)
                    self._mark_wedged(
                        r, f"no heartbeat for {now - ref:.2f}s "
                           f"(stale after {stale_s:.2f}s)")

    # -- fleet collector (obs v5) ------------------------------------------
    #
    # One daemon thread sweeping the whole group on the fleet tick:
    # strictly additive telemetry — a sweep never mutates replica
    # state, never blocks intake, and never raises out of its loop.
    # Thread-mode replicas are sampled in-process (lock-cheap depth /
    # health / count reads); subprocess replicas are scraped over
    # their own /metrics endpoints, where a dead or wedged child is a
    # COUNTED fleet_scrape_stale and a widening staleness_s in the
    # signals, never an exception (the child's liveness verdict
    # belongs to the heartbeat machinery, not the collector).

    def _collector_loop(self) -> None:
        while not self._hb_stop.wait(self.fleet_tick_s):
            try:
                self._collect_fleet_sample()
            except Exception:  # noqa: BLE001 — sampling never kills
                obs.count("fleet_collector_error")

    def _collect_fleet_sample(self) -> None:
        """THE cross-replica metrics funnel (lint-enforced —
        tools/lint.py fleet funnel rule): the only place serve/cluster
        code may read another replica's metrics (in-process reads,
        ``/metrics`` scrapes, registry walks).  Everything it learns
        lands in the fleet store via ``obs.fleet_record``; consumers
        read it back through the typed ``obs.signals()`` facade."""
        now = faults.monotonic()
        store = obs.fleet_series()
        store.tick_s = self.fleet_tick_s
        breakers = None
        total_depth = 0.0
        with self._lock:
            # membership can move under the sweep now (spawn_replica
            # / retire): sample a consistent snapshot
            replicas = list(self.replicas)
        for r in replicas:
            obs.fleet_record(r.rid, "up",
                             1.0 if r.state == UP else 0.0, t_s=now)
            born = getattr(r, "born", None)
            if born is not None:
                obs.fleet_record(r.rid, "birth_age_s",
                                 max(0.0, now - born), t_s=now)
            if r.state != UP:
                continue
            if r.spawn == "thread":
                depth = float(r.server.depth())
                counts = r.server.counts()
                obs.fleet_record(r.rid, "depth", depth, t_s=now)
                # open-batch occupancy: rows queued in forming
                # batches — the padding-aware placement signal,
                # exported so dashboards/autoscalers see where
                # batches are forming across the fleet
                obs.fleet_record(r.rid, "occupancy",
                                 float(r.server.occupancy()),
                                 t_s=now)
                obs.fleet_record(
                    r.rid, "healthy",
                    1.0 if r.server.health == "healthy" else 0.0,
                    t_s=now)
                obs.fleet_record(r.rid, "completed",
                                 float(counts["completed"]), t_s=now)
                obs.fleet_record(r.rid, "shed",
                                 float(counts["shed"]), t_s=now)
                total_depth += depth
                if breakers is None:    # one registry walk per sweep
                    breakers = _breaker.snapshot()
                opens = sum(
                    1 for b in breakers
                    if b["site"] in ("serve.dispatch",
                                     "pipeline.dispatch")
                    and b["state"] == _breaker.OPEN
                    and b["key"].startswith(f"('{r.rid}'"))
                obs.fleet_record(r.rid, "breaker_open",
                                 float(opens), t_s=now)
            else:
                import urllib.request

                rpc = getattr(r, "rpc", None)
                if rpc is not None:
                    # the data plane's own health, sampled from the
                    # parent-side client (no scrape needed): what
                    # obs_dash --fleet shows next to scrape staleness
                    rstats = rpc.stats()
                    obs.fleet_record(r.rid, "rpc_in_flight",
                                     float(rstats["in_flight"]),
                                     t_s=now)
                    obs.fleet_record(
                        r.rid, "rpc_reuse_ratio",
                        float(rstats["reuse_ratio"] or 0.0),
                        t_s=now)
                    obs.fleet_record(
                        r.rid, "rpc_transport_errors",
                        float(rstats["transport_errors"]), t_s=now)
                url = (f"http://{obs_http.BIND_HOST}:{r.port}"
                       f"/metrics")
                try:
                    with urllib.request.urlopen(
                            url, timeout=max(1.0,
                                             2 * self.fleet_tick_s)
                            ) as resp:
                        text = resp.read().decode("utf-8")
                    parsed = obs_export.parse_prometheus(text)
                except Exception:  # noqa: BLE001 — counted staleness
                    obs.count("fleet_scrape_stale", replica=r.rid)
                    continue
                completed = sum(
                    v for (name, _), v in parsed.items()
                    if name == "veles_simd_serve_completed_total")
                obs.fleet_record(r.rid, "completed", completed,
                                 t_s=now)
                obs.fleet_record(r.rid, "scraped_series",
                                 float(len(parsed)), t_s=now)
                obs.fleet_record(
                    r.rid, "healthy",
                    0.0 if r.last_health == "degraded" else 1.0,
                    t_s=now)
        obs.fleet_record("_fleet", "queue_depth_total", total_depth,
                         t_s=now)
        # replica-count series (scaler + dashboard input): how many
        # members sit in each lifecycle bucket right now
        obs.fleet_record("_fleet", "replica_count_up", float(
            sum(1 for r in replicas if r.state == UP)), t_s=now)
        obs.fleet_record("_fleet", "replica_count_draining", float(
            sum(1 for r in replicas if r.state == DRAINING)), t_s=now)
        obs.fleet_record("_fleet", "replica_count_down", float(
            sum(1 for r in replicas
                if r.state in (DEAD, RESTARTING))), t_s=now)
        for tenant, acct in sorted(
                (obs.slo_snapshot().get("accounts") or {}).items()):
            burn = acct.get("burn_rate")
            if burn is not None:
                obs.fleet_record("_fleet", f"slo_burn:{tenant}",
                                 float(burn), t_s=now)
        # a retired replica leaves membership — drop its rings, or
        # its aging samples read as a "stale" replica forever
        known = {r.rid for r in replicas} | {"_fleet"}
        for ghost in store.replicas():
            if ghost not in known:
                store.forget(ghost)
        store.tick()
        # the group owner reclaims journal segments from dead pids
        # (killed subprocess replicas, previous campaign epochs):
        # rotation's own-pid pruning never touches them, so the pack
        # would otherwise outgrow its total-disk budget forever.
        # Every ~64 sweeps (~6 s at the default tick) is plenty.
        self._sweeps += 1
        if self._sweeps % 64 == 0 and obs_journal.armed():
            live = [r.proc.pid for r in replicas
                    if r.proc is not None and r.proc.poll() is None]
            pruned = obs_journal.prune_foreign(live_pids=live)
            if pruned:
                obs.count("journal_pruned_foreign", pruned)

    # -- introspection -----------------------------------------------------

    @property
    def obs_port(self) -> int | None:
        """The aggregation endpoint's bound port (None = disarmed)."""
        return self._endpoint.port if self._endpoint else None

    def stats(self) -> dict:
        """JSON-native aggregate: per-replica snapshots plus the
        group ``health`` block the scrape endpoint's ``/healthz``
        answers from — ``healthy`` while at least one replica is up
        and healthy (503 only once the whole group is gone), so the
        router-level endpoint stays live through single-replica kills
        and drains."""
        snaps = [r.snapshot() for r in self.replicas]
        up_healthy = sum(
            1 for s in snaps
            if s["state"] == UP and s.get("health", "healthy")
            != "degraded")
        eng = self._scaler_engine
        return {
            "replicas": snaps,
            "alive": self.alive(),
            "spawn": self.spawn,
            "heartbeat_s": self.heartbeat_s,
            "miss_limit": self.miss_limit,
            "health": {"state": "healthy" if up_healthy
                       else "degraded",
                       "up_healthy": up_healthy},
            "obs_port": self.obs_port,
            "scaler": eng.summary() if eng is not None else None,
        }


class RouterTicket:
    """The caller's handle on one routed request — the
    :class:`~veles.simd_tpu.serve.server.Ticket` contract (``result``
    / ``done`` / ``status`` / ``degraded`` / ``trace`` / ``wait_s``),
    completed exactly once by the router whatever the backend story
    (dedup by router rid: a late duplicate completion is counted
    ``router_dedup`` and dropped, so group-wide zero-double-answer
    accounting holds).  ``replica`` is the replica that answered,
    ``failovers`` how many re-submissions it took, ``prior_traces``
    the terminal traces of the attempts that died under the request
    (the killed-replica evidence the chaos campaign gates), and
    ``deadlines_ms`` the deadline stamped on each attempt — the
    carried-deadline proof: entries only ever shrink."""

    __slots__ = ("rid", "op", "tenant", "status", "wait_s", "trace",
                 "replica", "failovers", "prior_traces",
                 "deadlines_ms", "attempt_replicas", "_event",
                 "_value", "_error", "_lock")

    def __init__(self, rid: int, op: str, tenant: str):
        self.rid = rid
        self.op = op
        self.tenant = tenant
        self.status = "pending"
        self.wait_s = None
        self.trace = None
        self.replica = None
        self.failovers = 0
        self.prior_traces: list = []
        self.deadlines_ms: list = []
        # the replica each attempt was placed on, in attempt order —
        # what lets obs.stitch_fleet_trace name every track of the
        # stitched fleet trace
        self.attempt_replicas: list = []
        self._event = threading.Event()
        self._value = None
        self._error = None
        self._lock = threading.Lock()

    def _complete(self, *, value=None, error=None, status="ok",
                  wait_s=None, trace=None, replica=None) -> bool:
        with self._lock:
            if self.status != "pending":
                obs.count("router_dedup", op=self.op)
                return False
            self._value = value
            self._error = error
            self.status = status
            self.wait_s = wait_s
            if trace is not None:
                self.trace = trace
            if self.trace is not None \
                    and getattr(self.trace, "status", None) \
                    not in (None, status):
                # a dead-end completion (router-side expiry, group
                # exhaustion) whose retained trace closed under a
                # DIFFERENT status: the trace belongs to a failed
                # attempt, not this answer — retain it as evidence,
                # never as the ticket's own chain (a status-mismatched
                # trace would read as an orphan to the completeness
                # gates).  Identity-guarded: the failover path may
                # have filed this same attempt already.
                if all(tr is not self.trace
                       for tr in self.prior_traces):
                    self.prior_traces.append(self.trace)
                self.trace = None
            self.replica = replica
        self._event.set()
        return True

    def done(self) -> bool:
        """Answered (any status but ``pending``)?"""
        return self._event.is_set()

    @property
    def degraded(self) -> bool:
        """Was the answer served by a replica's oracle path?"""
        return self.status == "degraded"

    def result(self, timeout: float | None = None):
        """Block for the answer; same contract as
        :meth:`veles.simd_tpu.serve.server.Ticket.result`."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"routed request {self.op!r} unanswered after "
                f"{timeout}s")
        if self._error is not None:
            raise self._error
        return self._value


class FrontRouter:
    """Breaker-aware placement + failover over a
    :class:`ReplicaGroup` — thread-mode replicas through in-process
    submits, subprocess replicas through their pooled
    :class:`~veles.simd_tpu.serve.rpc.RpcClient` data plane, both
    through the same ``_submit_to_replica`` funnel so the failover /
    shed / carried-deadline semantics are identical (module
    docstring has the full story).

    ``policy`` is ``least_loaded`` (default;
    ``$VELES_SIMD_ROUTER_POLICY``) or ``round_robin``;
    ``max_failovers`` is the shared re-submission budget per request
    (default: one attempt per additional replica)."""

    def __init__(self, group: ReplicaGroup, *,
                 policy: str | None = None,
                 max_failovers: int | None = None,
                 occupancy_weight: float | None = None):
        self.group = group
        self.policy = policy or env_router_policy()
        if self.policy not in ROUTER_POLICIES:
            raise ValueError(
                f"unknown router policy {self.policy!r} "
                f"(known: {', '.join(ROUTER_POLICIES)})")
        self.max_failovers = (
            int(max_failovers) if max_failovers is not None
            else max(1, len(group.replicas) - 1))
        self.occupancy_weight = (
            float(occupancy_weight) if occupancy_weight is not None
            else env_occupancy_weight())
        self._lock = threading.Lock()
        self._rids = itertools.count()
        self._rr = itertools.count()
        self._placed: dict = {}
        self._answered: dict = {}
        self._failovers = 0
        self._placement_failures = 0

    # -- scoring -----------------------------------------------------------

    def score(self, replica: Replica, key) -> float:
        """Placement cost of ``replica`` for shape class ``key``:
        admitted depth, plus the DEGRADED-health penalty, plus the
        open-breaker penalty when THIS class's breaker on THIS
        replica is open (per shape class — an open sosfilt breaker
        does not deprioritize the replica's stft traffic), minus the
        padding-aware **occupancy bonus**: a replica whose batcher
        already holds a forming batch of this class scores lower (the
        new request completes that batch, riding a row slot that
        would otherwise dispatch as zero padding).  The bonus is
        ``occupancy_weight * min(occ, max_batch-1)/max_batch`` —
        bounded strictly below one queued request at the default
        weight, so occupancy breaks near-ties but never outranks real
        load (or either penalty).

        A SUBPROCESS replica scores on what the parent can see
        without a round trip: the RPC client's in-flight count is
        the depth signal (requests submitted, not yet answered —
        the same O(queue) magnitude), and the last heartbeat's
        health observation stands in for the health machine.  Its
        breaker and batch-occupancy terms live in the child and are
        not scored — depth dominates placement in practice, and a
        child's dispatch failures still surface as shed/degraded
        answers the failover hook acts on."""
        if replica.spawn != "thread":
            s = (float(replica.rpc.in_flight())
                 if replica.rpc is not None else 0.0)
            if replica.last_health == "degraded":
                s += DEGRADED_PENALTY
            return s
        server = replica.server
        s = float(server.depth())
        if server.health == "degraded":
            s += DEGRADED_PENALTY
        # ragged classes carry their breaker on the packed-dispatch
        # site (the per-segment salvage lives inside it); plain
        # classes on the serve dispatch — score must read the breaker
        # the dispatch will actually consult
        site = ("segments.dispatch"
                if isinstance(key, tuple) and key
                and key[-1] == "ragged" else "serve.dispatch")
        br = _breaker.lookup(site, server.breaker_key(key))
        if br is not None and br.state == _breaker.OPEN:
            s += BREAKER_OPEN_PENALTY
        if self.occupancy_weight:
            occ = server.open_occupancy(key)
            if occ > 0:
                mb = max(1, server.max_batch)
                s -= self.occupancy_weight * min(occ, mb - 1) / mb
        return s

    def _pick(self, key, exclude) -> Replica | None:
        alive = self.group.live_replicas()
        if not alive:
            return None
        fresh = [r for r in alive if r.rid not in exclude]
        # every survivor already tried: the failover budget (not the
        # exclusion set) is the retry bound — re-trying a survivor
        # beats failing a placeable request
        candidates = fresh or alive
        if self.policy == ROUND_ROBIN:
            return candidates[next(self._rr) % len(candidates)]
        return min(candidates, key=lambda r: (self.score(r, key),
                                              r.rid))

    # -- intake ------------------------------------------------------------

    def submit(self, request: Request | None = None, *,
               op: str | None = None, x=None,
               params: dict | None = None, tenant: str = "default",
               block: bool = False, timeout: float | None = None,
               deadline_ms: float | None = None) -> RouterTicket:
        """Place one request on the group; returns its
        :class:`RouterTicket`.  Same call shape as
        :meth:`Server.submit`.  The end-to-end deadline (argument,
        request field, or the ``VELES_SIMD_SERVE_DEADLINE_MS``
        default) is resolved ONCE here to an absolute stamp; every
        placement and failover re-submission carries the remaining
        budget of that one deadline."""
        if request is None:
            request = Request(op=op, x=x, params=params or {},
                              tenant=tenant)
        key = self._shape_class(request)
        dl_ms = deadline_ms
        if dl_ms is None:
            dl_ms = request.deadline_ms
        if dl_ms is None:
            dl_ms = env_deadline_ms()
        has_deadline = dl_ms is not None and dl_ms > 0
        ticket = RouterTicket(next(self._rids), request.op,
                              request.tenant)
        ctx = {
            "deadline": (faults.monotonic() + float(dl_ms) / 1e3
                         if has_deadline else None),
            "attempts": 0,
            "tried": set(),
            "block": block,
            "timeout": timeout,
        }
        self._place(ticket, request, key, ctx)
        return ticket

    def _shape_class(self, request: Request) -> tuple:
        """The request's shape-class triple — derived by the SAME
        helper the replica's submit uses (:func:`veles.simd_tpu.
        serve.server.classify_request`), so scoring reads exactly the
        breaker the dispatch will consult.  Validation errors raise
        synchronously, exactly like a direct submit."""
        return classify_request(request.op, request.x,
                                request.params)[3]

    # -- placement + failover ----------------------------------------------

    def _place(self, ticket: RouterTicket, request: Request, key,
               ctx) -> None:
        """Place (or re-place) one request: pick a survivor, submit
        through the guarded funnel, arm the failover hook.  Placement
        failure (a replica racing death) retries the next candidate;
        group exhaustion answers typed."""
        # bounded by construction: each pass either returns or burns
        # one placement-failure credit (a replica can only race death
        # once per request, but the explicit bound keeps a bookkeeping
        # bug from ever spinning here)
        credits = len(self.group.replicas) + self.max_failovers + 1
        while True:
            credits -= 1
            if ticket.done():
                return
            if credits < 0:
                ticket._complete(
                    error=NoReplicaAvailable(
                        f"RESOURCE_EXHAUSTED: placement retries "
                        f"exhausted for {request.op!r}",
                        tenant=request.tenant),
                    status="shed" if ticket.trace is None
                    else "closed")
                return
            if ctx["deadline"] is not None \
                    and faults.monotonic() >= ctx["deadline"]:
                ticket._complete(
                    error=DeadlineExceeded(
                        f"DEADLINE_EXCEEDED: routed request "
                        f"{request.op!r} exhausted its end-to-end "
                        f"deadline before a replica answered"),
                    status="expired")
                return
            target = self._pick(key, ctx["tried"])
            if target is None:
                ticket._complete(
                    error=NoReplicaAvailable(
                        f"RESOURCE_EXHAUSTED: no live replica for "
                        f"{request.op!r} "
                        f"(group alive={self.group.alive()})",
                        tenant=request.tenant),
                    status="shed" if ticket.trace is None
                    else "closed")
                return
            try:
                backend = self._submit_to_replica(target, request,
                                                  ctx)
            except ServerClosed:
                # raced a kill/drain between pick and submit: typed
                # placement failure, try the next survivor
                ctx["tried"].add(target.rid)
                with self._lock:
                    self._placement_failures += 1
                obs.count("router_placement_failure",
                          replica=target.rid)
                continue
            with self._lock:
                self._placed[target.rid] = \
                    self._placed.get(target.rid, 0) + 1
            obs.count("router_placed", replica=target.rid,
                      policy=self.policy)
            ticket.attempt_replicas.append(target.rid)
            ticket.trace = backend.trace
            backend.add_done_callback(
                lambda t, r=target: self._on_backend(
                    ticket, request, key, ctx, r, t))
            return

    def _submit_to_replica(self, replica: Replica, request: Request,
                           ctx):
        """THE guarded dispatch funnel: the only call site allowed to
        submit into a replica (lint-enforced — tools/lint.py cluster
        router rule), so every placement path shares the
        carried-deadline arithmetic and the typed placement-failure
        handling around it."""
        remaining_ms = None
        if ctx["deadline"] is not None:
            remaining_ms = max(
                0.001, (ctx["deadline"] - faults.monotonic()) * 1e3)
        ctx.setdefault("stamps", []).append(remaining_ms)
        if replica.spawn == "thread":
            return replica.server.submit(
                request, block=ctx["block"], timeout=ctx["timeout"],
                deadline_ms=remaining_ms)
        if replica.rpc is None:
            # racing the replica's own start/stop window: typed
            # placement failure, same as submitting into a closed
            # server — the caller tries the next survivor
            raise ServerClosed(
                f"replica {replica.rid} has no armed RPC data plane")
        return replica.rpc.submit(
            request, block=ctx["block"], timeout=ctx["timeout"],
            deadline_ms=remaining_ms)

    def _on_backend(self, ticket: RouterTicket, request: Request,
                    key, ctx, replica: Replica, backend) -> None:
        """One backend ticket went terminal: answer the router
        ticket, or fail the request over to a survivor."""
        status = backend.status
        if status in ("ok", "degraded"):
            if ticket._complete(
                    value=backend._value, status=status,
                    wait_s=backend.wait_s, trace=backend.trace,
                    replica=replica.rid):
                with self._lock:
                    self._answered[replica.rid] = \
                        self._answered.get(replica.rid, 0) + 1
            return
        if status == "expired":
            # the request's OWN deadline — failing over cannot help
            ticket._complete(error=backend._error, status="expired",
                             trace=backend.trace,
                             replica=replica.rid)
            return
        if status in ("closed", "shed") \
                and ctx["attempts"] < self.max_failovers:
            # the replica died under the request (closed) or shed it
            # (overload): re-route onto a survivor, original deadline
            # and the SHARED failover budget carried over
            ctx["attempts"] += 1
            ctx["tried"].add(replica.rid)
            ticket.failovers = ctx["attempts"]
            ticket.prior_traces.append(backend.trace)
            ticket.deadlines_ms = list(ctx.get("stamps", []))
            with self._lock:
                self._failovers += 1
            obs.count("router_failover", replica=replica.rid,
                      reason=status)
            obs.record_decision("router_failover", status,
                                replica=replica.rid,
                                request_op=request.op,
                                attempt=ctx["attempts"])
            self._place(ticket, request, key, ctx)
            ticket.deadlines_ms = list(ctx.get("stamps", []))
            return
        # terminal without recourse: propagate the typed error
        ticket._complete(error=backend._error, status=status,
                         trace=backend.trace, replica=replica.rid)

    # -- introspection -----------------------------------------------------

    @property
    def obs_port(self) -> int | None:
        """The group aggregation endpoint's port (scrape target)."""
        return self.group.obs_port

    def stats(self) -> dict:
        """JSON-native router view: per-replica placement/answer
        tallies, failover/dedup/placement-failure counts, and the
        group aggregate (so a router handle quacks like a server for
        health-minded consumers)."""
        with self._lock:
            placed = dict(sorted(self._placed.items()))
            answered = dict(sorted(self._answered.items()))
            failovers = self._failovers
            placement_failures = self._placement_failures
        group = self.group.stats()
        return {
            "policy": self.policy,
            "max_failovers": self.max_failovers,
            "placed_by_replica": placed,
            "answered_by_replica": answered,
            "failovers": failovers,
            "placement_failures": placement_failures,
            "group": group,
            "health": group["health"],
        }


# ---------------------------------------------------------------------------
# subprocess replica entry point (python -m veles.simd_tpu.serve.cluster)
# ---------------------------------------------------------------------------


def _replica_main(argv=None) -> int:
    """Run ONE replica server in this process: arm its scrape
    endpoint, report the bound port as a JSON line on stdout, serve
    until stdin closes (the parent's graceful-drain signal), then
    drain and exit.  The ``spawn='subprocess'`` child body."""
    import argparse

    ap = argparse.ArgumentParser(description=_replica_main.__doc__)
    ap.add_argument("--obs-port", type=int, default=0)
    ap.add_argument("--max-batch", type=int, default=None)
    ap.add_argument("--max-wait-ms", type=float, default=None)
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--name", default=None)
    ap.add_argument("--pipeline-spec", action="append", default=[],
                    help="declarative pipeline_from_spec JSON; "
                         "repeatable — each is compiled and "
                         "registered before the replica reports "
                         "ready")
    args = ap.parse_args(argv)
    obs.enable()
    # history axis: every record this process journals carries its
    # replica identity (the pack is shared; the pid alone names the
    # file, the replica names the story)
    obs_journal.set_replica(args.name or f"pid-{os.getpid()}")
    kwargs = {}
    if args.workers:
        kwargs["workers"] = args.workers
    srv = Server(max_batch=args.max_batch,
                 max_wait_ms=args.max_wait_ms,
                 obs_port=args.obs_port, **kwargs)
    if args.pipeline_spec:
        from veles.simd_tpu import pipeline as _pl

        # registration precedes start(): by the time the port is on
        # stdout (and the router starts placing), every pipeline the
        # group promised answers here
        for raw in args.pipeline_spec:
            spec = json.loads(raw)
            srv.register_pipeline(spec["name"],
                                  _pl.pipeline_from_spec(spec))
    # start() preloads the warm artifact pack when the store is armed
    # (the child inherits VELES_SIMD_ARTIFACTS/_ARTIFACT_DIR from the
    # group's environment), so a subprocess replica reports its port —
    # and starts heartbeating — only once its executables are ready:
    # the first request a failover lands here hits steady-state p99
    srv.start()
    ready = {"port": srv.obs_port, "pid": os.getpid()}
    if srv._preload is not None:
        ready["artifact_preload"] = srv._preload
    print(json.dumps(ready), flush=True)
    try:
        sys.stdin.read()        # parked until the parent lets go
    except Exception:  # noqa: BLE001 — any stdin failure = shutdown
        pass
    srv.stop(drain=True)
    return 0


if __name__ == "__main__":      # pragma: no cover — subprocess body
    sys.exit(_replica_main())
