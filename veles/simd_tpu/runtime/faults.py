"""Fault-policy engine: demote-and-remember, retry/backoff, injection.

The paper's core mechanism is per-op best-algorithm selection; the
*fallback* path when the selected algorithm fails on hardware is what
keeps that mechanism correctness-preserving.  Before this module each
routed op family hand-rolled its own copy of the pattern (convolve's
fused overlap-save, convolve2d's shifted-MAC kernel, spectral's fused
STFT — three near-identical try/except blocks, two of them remembering
rejections in unbounded ``set()``s), and the only failures CI could
exercise were monkeypatched ones.  Meanwhile whole bench runs were
lost to device-unreachable hangs the runtime had no story for.  This
module is the one shared layer, three pieces:

* **demote-and-remember** (:func:`demote_and_remember`) — the compile-
  rejection policy.  A Mosaic scoped-vmem OOM is *permanent for the
  geometry* (the same shape will OOM again): classify it
  (:func:`is_mosaic_vmem_oom`), remember the geometry key in a bounded
  rejection cache (:func:`register_rejection_cache` puts every such
  cache in ``obs.caches()``), count and record the demotion, and
  invoke the caller's fallback route.  A *forced* route re-raises
  after remembering — a caller who pinned a kernel must never silently
  get another route's numbers.

* **guarded dispatch** (:func:`guarded`) — the transient-fault policy,
  composed around the ``obs.instrumented_jit``-compiled cores at the
  Python dispatch layer (inside the dispatch ``obs.span``, outside the
  traced program).  Device-unreachable / device-lost errors and
  watchdog deadline overruns (:func:`is_transient`) get bounded
  jittered-exponential retry (``VELES_SIMD_FAULT_RETRIES`` /
  ``VELES_SIMD_FAULT_BACKOFF``); on exhaustion the op degrades
  gracefully to its fallback route (the NumPy oracle twin — correct
  output beats no output) and the crash flight recorder is armed with
  the accumulated fault history.  Every step is a ``fault_*`` counter
  (``veles_simd_fault_*`` in the Prometheus export) and a
  ``fault_policy`` decision event.

* **deterministic fault injection** (:func:`inject` /
  ``VELES_SIMD_FAULT_PLAN``) — ``site:kind:count,...`` raises
  synthetic faults (``vmem_oom`` / ``device_lost`` / ``timeout`` /
  ``overload``) whose messages match the real classifiers at named
  engine sites, so every demotion and retry path runs on CPU CI
  without hardware or monkeypatching.  :func:`armed` lets route
  *gates* open for a planned site, so the doomed route is actually
  selected and the whole demote path executes end to end.  The
  serving layer (:mod:`veles.simd_tpu.serve`) adds two sites:
  ``serve.dispatch`` (batch dispatch, guarded — device-lost/timeout
  kinds drive retry → DEGRADED) and ``serve.admission`` (the
  ``overload`` kind forces the typed shed path); the pipeline
  compiler (:mod:`veles.simd_tpu.pipeline`) adds ``pipeline.dispatch``
  (the fused block step, behind a per-pipeline-class breaker —
  exhaustion degrades one block to the stage-by-stage oracle twin and
  the stream continues with exact state).  A guarded site may
  carry a *subsite* (``site@subsite`` plan entries — e.g.
  ``serve.dispatch@stft``, or ``serve.dispatch@pipeline:sensor`` for
  a served pipeline class), so a chaos plan can poison ONE shape
  class while its siblings stay healthy.  A plan may also be a
  **phase schedule** — ``label=entries;label=entries;...`` — the
  chaos-campaign form (:mod:`tools.chaos`): :func:`set_fault_plan`
  activates the first phase, :func:`advance_phase` steps through the
  rest (an empty body clears injection for that phase), and every
  step is a ``fault_phase`` decision event.

Two policy layers compose around :func:`guarded`: a per-request
deadline budget (``budget_s`` — the serving layer threads each
request's remaining end-to-end budget in, so the retry/backoff loop is
clipped to what the caller can still use) and the per-class circuit
breakers (:mod:`veles.simd_tpu.runtime.breaker` — the caller admits
through the breaker and passes it in; ``guarded`` records the
success/failure outcomes, never counting typed overloads).

``bench.py`` stage supervision and ``tools/tpu_smoke.py`` ride the
same classifiers (per-stage retry + fault record instead of
skip-on-first-failure); ``tools/lint.py`` forbids raw ``except
Exception`` around pallas/compile call sites in ``ops/``/``parallel/``
so a fourth hand-rolled copy cannot reappear.
"""

from __future__ import annotations

import collections
import contextlib
import os
import random
import threading
import time

from veles.simd_tpu import obs

__all__ = [
    "is_mosaic_vmem_oom", "is_device_lost", "is_timeout", "is_transient",
    "is_overload",
    "InjectedFault", "FaultTimeout", "make_fault", "monotonic",
    "inject", "armed", "set_fault_plan", "fault_plan", "plan_snapshot",
    "parse_phase_plan", "advance_phase", "current_phase",
    "demote_and_remember", "guarded", "breaker_guarded",
    "register_rejection_cache",
    "fault_retries", "fault_backoff", "fault_deadline", "backoff_delay",
    "fault_history", "reset_fault_history",
    "FAULT_PLAN_ENV", "FAULT_RETRIES_ENV", "FAULT_BACKOFF_ENV",
    "FAULT_DEADLINE_ENV", "DEFAULT_RETRIES", "DEFAULT_BACKOFF_S",
    "FAULT_KINDS", "FAULT_HISTORY_MAXLEN",
]

FAULT_PLAN_ENV = "VELES_SIMD_FAULT_PLAN"
FAULT_RETRIES_ENV = "VELES_SIMD_FAULT_RETRIES"
FAULT_BACKOFF_ENV = "VELES_SIMD_FAULT_BACKOFF"
FAULT_DEADLINE_ENV = "VELES_SIMD_FAULT_DEADLINE"

# transient-fault retry budget per dispatch (attempts = retries + 1)
# and the base backoff delay; both env-tunable.  The defaults are
# sized for a relay hiccup (sub-second), not a wedged relay — a truly
# wedged in-flight call is the stage watchdog's job (bench.py).
DEFAULT_RETRIES = 2
DEFAULT_BACKOFF_S = 0.05

# retained fault records for the flight recorder (per process)
FAULT_HISTORY_MAXLEN = 64


# ---------------------------------------------------------------------------
# exception classifiers
# ---------------------------------------------------------------------------

def is_mosaic_vmem_oom(e: BaseException) -> bool:
    """Match Mosaic's scoped-vmem compile failures, e.g. (observed live
    2026-07-31): "Ran out of memory in memory space vmem while
    allocating on stack for %_f2d_call... Scoped allocation with size
    22.34M and limit 16.00M" / "Ran out of memory in memory space
    vmem. Used 160.14M of 128.00M" — pinned by unit tests.  Permanent
    for the geometry: the demote-and-remember policy, never retry."""
    msg = str(e).lower()
    return "vmem" in msg and ("ran out of memory" in msg
                              or "scoped" in msg)


# device-lost / unreachable markers (lowercase substrings), from the
# r02-r04 bench post-mortems (axon relay drops) plus the gRPC status
# vocabulary jax surfaces for a dead backend
_DEVICE_LOST_MARKERS = (
    "device unreachable", "device lost", "unavailable:",
    "socket closed", "connection reset", "failed to connect",
    "data_loss", "device or resource busy",
)
# NB: "UNIMPLEMENTED: TPU backend error" (a relay capability gap) is
# deliberately NOT here — it is permanent, and the smoke harness
# reports it distinctly as UNSUPPORTED-BY-BACKEND; retrying or quietly
# degrading would hide the gap.

_TIMEOUT_MARKERS = (
    "deadline exceeded", "deadline_exceeded", "timed out", "timeout",
)


def is_device_lost(e: BaseException) -> bool:
    """A device/transport loss: the call never computed, the backend
    may come back — the retry-then-degrade policy."""
    msg = str(e).lower()
    return any(m in msg for m in _DEVICE_LOST_MARKERS)


def is_timeout(e: BaseException) -> bool:
    """A deadline overrun (including :class:`FaultTimeout` from the
    watchdog): same retry-then-degrade policy as device loss."""
    if isinstance(e, FaultTimeout):
        return True
    msg = str(e).lower()
    return any(m in msg for m in _TIMEOUT_MARKERS)


def is_transient(e: BaseException) -> bool:
    """Worth retrying?  Device losses and timeouts are; compile
    rejections (:func:`is_mosaic_vmem_oom`), admission overloads
    (:func:`is_overload` — retrying into a full queue is how retry
    storms start), and ordinary bugs are not."""
    return is_device_lost(e) or is_timeout(e)


_OVERLOAD_MARKERS = (
    "resource_exhausted", "queue full",
)


def is_overload(e: BaseException) -> bool:
    """An admission-capacity rejection (the serving layer's typed shed
    path, or an injected ``overload`` fault at ``serve.admission``).
    Deliberately NOT transient: the caller gets a typed answer now
    instead of a queued timeout later."""
    msg = str(e).lower()
    return any(m in msg for m in _OVERLOAD_MARKERS)


def monotonic() -> float:
    """The engine's deadline clock (monotonic seconds).  The serving
    layer's batching deadlines and backpressure timeouts read THIS
    instead of ``time.*`` — ``tools/lint.py`` bans raw clock reads
    under ``serve/`` so latency measurement stays on ``obs.span`` and
    deadline arithmetic stays on one shared clock."""
    return time.monotonic()


def _fault_kind(e: BaseException) -> str:
    return "timeout" if is_timeout(e) else "device_lost"


# ---------------------------------------------------------------------------
# synthetic faults + the deterministic injection plan
# ---------------------------------------------------------------------------

class InjectedFault(RuntimeError):
    """A synthetic fault raised by :func:`inject`.  Its *message* is
    crafted to satisfy the same string classifier as the real error it
    imitates, so injection exercises the production classification
    path, not a bypass."""


class FaultTimeout(RuntimeError):
    """Raised by :func:`guarded`'s watchdog when a dispatch overruns
    its deadline (classified transient by :func:`is_timeout`)."""


FAULT_KINDS = ("vmem_oom", "device_lost", "timeout", "overload")

_FAULT_MESSAGES = {
    "vmem_oom": ("Ran out of memory in memory space vmem while "
                 "allocating on stack: scoped allocation (injected "
                 "at %s)"),
    "device_lost": "UNAVAILABLE: device unreachable (injected at %s)",
    "timeout": ("DEADLINE_EXCEEDED: dispatch deadline overrun "
                "(injected at %s)"),
    # the serve chaos kind: forces the admission controller's typed
    # shed path deterministically (classified by is_overload, never
    # retried) so overload behavior runs on CPU CI without having to
    # race a queue full
    "overload": ("RESOURCE_EXHAUSTED: admission queue full (injected "
                 "at %s)"),
}


def make_fault(kind: str, site: str = "synthetic") -> InjectedFault:
    """A synthetic fault instance of ``kind`` (for tests and the bench
    harness; :func:`inject` raises these per the active plan)."""
    if kind not in _FAULT_MESSAGES:
        raise ValueError(f"unknown fault kind {kind!r} "
                         f"(known: {sorted(_FAULT_MESSAGES)})")
    return InjectedFault(_FAULT_MESSAGES[kind] % site)


_plan_lock = threading.Lock()
_plan_override: str | None = None       # set_fault_plan() programmatic
_plan_src: str | None = None            # spec the cache was parsed from
_plan_cache: dict | None = None         # {site: [kind, remaining]}
_phase_list: list | None = None         # [(label, body|None), ...]
_phase_idx: int = 0


def _is_phased(spec: str) -> bool:
    """Phase-schedule syntax?  Plain plans are ``site:kind:count,...``
    and never contain ``;`` or ``=``; a phase schedule is
    ``label=entries;label=entries;...``."""
    return ";" in spec or "=" in spec


def parse_phase_plan(spec: str) -> list:
    """``label=entries;label=entries;...`` ->
    ``[(label, entries_or_None), ...]`` — the chaos-campaign phase
    schedule.  ``entries`` is an ordinary plan body
    (``site:kind:count,...``, validated eagerly); an EMPTY body
    (``recovery=``) means *no injection* during that phase — the
    clear/recovery step of a scripted campaign.  Labels are optional
    (``phaseN`` is minted); empty segments (a trailing ``;``) are
    skipped."""
    phases = []
    for i, part in enumerate(spec.split(";")):
        part = part.strip()
        if not part:
            continue
        head, sep, rest = part.partition("=")
        if sep:
            label, body = head.strip(), rest.strip()
        else:
            label, body = "", part
        if body:
            _parse_plan(body)       # validate eagerly
        phases.append((label or f"phase{i}", body or None))
    if not phases:
        raise ValueError(f"phase plan {spec!r} holds no phases")
    return phases


def _parse_plan(spec: str) -> dict:
    """``site:kind:count,...`` -> ``{site: [kind, remaining]}``.
    ``count`` defaults to 1; a malformed entry raises (a typo'd plan
    silently injecting nothing would defeat the harness)."""
    plan = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) == 2:
            site, kind, count = parts[0], parts[1], "1"
        elif len(parts) == 3:
            site, kind, count = parts
        else:
            raise ValueError(
                f"fault-plan entry {entry!r} is not site:kind[:count]")
        if kind not in _FAULT_MESSAGES:
            raise ValueError(
                f"fault-plan entry {entry!r}: unknown kind {kind!r} "
                f"(known: {sorted(_FAULT_MESSAGES)})")
        plan[site.strip()] = [kind.strip(), int(count)]
    return plan


def _active_plan() -> dict | None:
    """The live plan (reparsed when the env var or override changed;
    None when no plan is set — the zero-cost steady state).  An
    env-supplied phase schedule activates its FIRST phase; stepping
    through the rest is :func:`advance_phase` (which requires the
    schedule to have gone through :func:`set_fault_plan`)."""
    global _plan_src, _plan_cache
    spec = _plan_override
    if spec is None:
        spec = os.environ.get(FAULT_PLAN_ENV, "") or None
    with _plan_lock:
        if spec != _plan_src:
            _plan_src = spec
            body = spec
            if spec and _is_phased(spec):
                body = parse_phase_plan(spec)[0][1]
            _plan_cache = _parse_plan(body) if body else None
        return _plan_cache


def set_fault_plan(spec: str | None) -> None:
    """Programmatic plan override (None restores the env lookup).
    Validates eagerly so a bad spec fails at the set, not mid-run.
    A phase schedule (``label=entries;...``) activates its first
    phase and arms :func:`advance_phase`; any other set clears the
    schedule."""
    global _plan_override, _plan_src, _plan_cache
    global _phase_list, _phase_idx
    phases = None
    if spec is not None:
        if _is_phased(spec):
            phases = parse_phase_plan(spec)
        else:
            _parse_plan(spec)
    with _plan_lock:
        _phase_list = phases
        _phase_idx = 0
        if phases is not None:
            # "" (not None) when the phase body is empty: an explicit
            # no-injection phase must not fall through to the env plan
            _plan_override = phases[0][1] or ""
        else:
            _plan_override = spec
        _plan_src = None        # force reparse on next lookup
        _plan_cache = None
    if phases is not None:
        obs.record_decision("fault_phase", phases[0][0], index=0,
                            plan=phases[0][1] or "")


def advance_phase() -> str | None:
    """Step the active phase schedule to its next phase (the scripted
    chaos-campaign tick).  Returns the new phase's label, or None when
    the schedule is exhausted (injection cleared).  Each step records
    a ``fault_phase`` decision event.  Raises RuntimeError when no
    phase schedule is active."""
    global _plan_override, _plan_src, _plan_cache, _phase_idx
    with _plan_lock:
        phases = _phase_list
        if phases is None:
            raise RuntimeError(
                "no phase schedule active — set_fault_plan with "
                "'label=entries;label=entries;...' first")
        _phase_idx += 1
        idx = _phase_idx
        if idx < len(phases):
            label, body = phases[idx]
        else:
            label, body = None, None
        _plan_override = body or ""
        _plan_src = None
        _plan_cache = None
    obs.record_decision("fault_phase", label or "done", index=idx,
                        plan=body or "")
    return label


def current_phase() -> str | None:
    """The active phase's label (None when no schedule is active or
    the schedule is exhausted)."""
    with _plan_lock:
        if _phase_list is None or _phase_idx >= len(_phase_list):
            return None
        return _phase_list[_phase_idx][0]


@contextlib.contextmanager
def fault_plan(spec: str):
    """Scoped :func:`set_fault_plan` — the test-suite idiom.  Restores
    the previous plan AND phase schedule (if any) on exit."""
    global _plan_override, _plan_src, _plan_cache
    global _phase_list, _phase_idx
    with _plan_lock:
        prev_override = _plan_override
        prev_phases = _phase_list
        prev_idx = _phase_idx
    set_fault_plan(spec)
    try:
        yield
    finally:
        with _plan_lock:
            _plan_override = prev_override
            _phase_list = prev_phases
            _phase_idx = prev_idx
            _plan_src = None
            _plan_cache = None


def armed(site: str, kind: str | None = None) -> bool:
    """Does the active plan still hold injections for ``site``?  Route
    *gates* consult this so a planned site's route is actually
    selected on CPU (where the hardware gates would refuse it) and the
    full demote/retry path runs — deterministic, no monkeypatching."""
    plan = _active_plan()
    if plan is None:
        return False
    with _plan_lock:
        entry = plan.get(site)
        return bool(entry and entry[1] > 0
                    and (kind is None or entry[0] == kind))


def inject(site: str) -> None:
    """Raise the planned synthetic fault for ``site``, if any remain
    (decrementing the plan's count); no-op otherwise.  Called by the
    engine at every policy site, so a plan drives the production
    paths themselves."""
    plan = _active_plan()
    if plan is None:
        return
    with _plan_lock:
        entry = plan.get(site)
        if not entry or entry[1] <= 0:
            return
        entry[1] -= 1
        kind = entry[0]
    obs.count("fault_injected", site=site, kind=kind)
    raise make_fault(kind, site)


def plan_snapshot() -> dict:
    """JSON-native view of the remaining plan (for bundles/tests)."""
    plan = _active_plan()
    if plan is None:
        return {}
    with _plan_lock:
        return {site: {"kind": kind, "remaining": n}
                for site, (kind, n) in sorted(plan.items())}


# ---------------------------------------------------------------------------
# fault history (what the flight recorder carries)
# ---------------------------------------------------------------------------

_history_lock = threading.Lock()
_FAULT_HISTORY: collections.deque = collections.deque(
    maxlen=FAULT_HISTORY_MAXLEN)


def _note_fault(site: str, kind: str, action: str, attempt: int,
                error: BaseException) -> dict:
    rec = {"site": site, "kind": kind, "action": action,
           "attempt": attempt, "error": str(error)[:300],
           "unix": time.time()}
    with _history_lock:
        _FAULT_HISTORY.append(rec)
    return rec


def fault_history() -> list:
    """Oldest-first copy of the retained fault records (embedded in
    every flight-recorder bundle)."""
    with _history_lock:
        return [dict(r) for r in _FAULT_HISTORY]


def reset_fault_history() -> None:
    """Clear the retained fault records AND the per-class circuit
    breakers — the one-call engine reset every fault-injection test
    fixture uses (a breaker opened by one scenario's exhaustions must
    not short-circuit the next scenario's dispatches)."""
    with _history_lock:
        _FAULT_HISTORY.clear()
    from veles.simd_tpu.runtime import breaker as _breaker

    _breaker.reset()


def _arm_flightrec(site: str, exc: BaseException) -> str | None:
    """Write a flight-recorder bundle on retry exhaustion, when a
    flight dir is armed — through the recorder's shared
    ``MAX_AUTO_BUNDLES`` budget, so a service that permanently lost
    its device and keeps degrading per call cannot fill the disk with
    one bundle per dispatch.  Never raises — the policy's answer
    (degrade or re-raise) must win over recorder trouble."""
    try:
        from veles.simd_tpu.obs import flightrec

        return flightrec.maybe_record(f"fault_exhausted:{site}", exc)
    except Exception:  # noqa: BLE001
        return None


# ---------------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------------

def _env_number(name: str, default, cast):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = cast(raw)
    except ValueError:
        return default
    return value if value >= 0 else default


def fault_retries() -> int:
    """Transient-fault retries per dispatch
    (``$VELES_SIMD_FAULT_RETRIES``, default 2)."""
    return _env_number(FAULT_RETRIES_ENV, DEFAULT_RETRIES, int)


def fault_backoff() -> float:
    """Base backoff seconds (``$VELES_SIMD_FAULT_BACKOFF``, default
    0.05; 0 disables sleeping — the deterministic-test setting)."""
    return _env_number(FAULT_BACKOFF_ENV, DEFAULT_BACKOFF_S, float)


def fault_deadline() -> float:
    """Watchdog deadline seconds for :func:`guarded` dispatches
    (``$VELES_SIMD_FAULT_DEADLINE``, default 0 = no watchdog)."""
    return _env_number(FAULT_DEADLINE_ENV, 0.0, float)


def backoff_delay(attempt: int, base: float | None = None) -> float:
    """Jittered exponential backoff: ``base * 2^attempt`` scaled by a
    uniform [0.5, 1.0) jitter so retry storms decorrelate."""
    if base is None:
        base = fault_backoff()
    if base <= 0:
        return 0.0
    return base * (2 ** attempt) * (0.5 + random.random() / 2)


# ---------------------------------------------------------------------------
# the demote-and-remember policy (permanent compile rejections)
# ---------------------------------------------------------------------------

def register_rejection_cache(name: str, getter, capacity: int) -> None:
    """Put a rejection cache in ``obs.caches()`` under ``name``.

    ``getter`` is a zero-arg callable returning the cache *currently
    bound* in the owning module (tests substitute plain ``set``s
    through the module global, so the provider must re-read it per
    snapshot).  An :class:`~veles.simd_tpu.obs.lru.LRUSet` reports its
    own hit/miss/eviction counters; a plain set reports size against
    the intended capacity."""
    def provider():
        cache = getter()
        if hasattr(cache, "info"):
            return cache.info()
        return {"size": len(cache), "capacity": capacity}
    obs.register_cache(name, provider)


def demote_and_remember(site: str, run, fallback=None, *, cache, key,
                        route: str, fallback_route: str, counter: str,
                        forced: bool = False, reason: str = "compile_oom",
                        classify=None):
    """THE demote-and-remember implementation (one home, three users).

    Runs ``run()`` (the doomed-candidate route) after giving the
    injection plan its shot at ``site``.  An exception ``classify``
    accepts — by default a Mosaic scoped-vmem compile OOM, which is
    permanent for the geometry — adds ``key`` to ``cache`` (the
    bounded rejection set the route's *gate* consults, so the next
    call skips the route without re-raising), bumps ``counter`` (the
    family's historical demotion counter) plus the engine's
    ``fault_demotion`` counter, records a ``fault_policy`` decision
    event, and answers via ``fallback()``.  ``forced=True`` (a caller
    who pinned the route) still remembers but re-raises; any other
    exception propagates untouched.  ``classify`` defaults to
    :func:`is_mosaic_vmem_oom` (None keeps the default — a live
    callable here would bake a memory address into the generated
    docs).
    """
    if classify is None:
        classify = is_mosaic_vmem_oom
    try:
        inject(site)
        return run()
    except Exception as e:
        if not classify(e):
            raise
        cache.add(key)
        kind = "vmem_oom" if classify is is_mosaic_vmem_oom \
            else _fault_kind(e)
        _note_fault(site, kind, "demote", 0, e)
        obs.count(counter, reason=reason)
        obs.count("fault_demotion", site=site)
        obs.record_decision(
            "fault_policy", "demote", site=site, route=route,
            fallback=fallback_route, reason=reason, key=repr(key),
            forced=bool(forced))
        if forced or fallback is None:
            raise
        return fallback()


# ---------------------------------------------------------------------------
# the guarded-dispatch policy (transient device faults)
# ---------------------------------------------------------------------------

def _call_with_deadline(thunk, deadline: float, site: str):
    """Run ``thunk`` under a watchdog: past ``deadline`` seconds the
    worker is abandoned (daemon thread — a wedged in-flight device
    call blocks in native code and cannot be cancelled; the bench
    stage supervisor uses the same containment) and a
    :class:`FaultTimeout` is raised for the retry policy to handle."""
    if not deadline or deadline <= 0:
        return thunk()
    box = {}
    done = threading.Event()

    def work():
        try:
            box["result"] = thunk()
        except BaseException as e:  # noqa: BLE001 — relayed below
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=work, daemon=True,
                         name=f"veles-fault-deadline-{site}")
    t.start()
    if not done.wait(deadline):
        raise FaultTimeout(
            f"DEADLINE_EXCEEDED: dispatch at {site} overran the "
            f"{deadline:.3f}s fault-policy watchdog")
    if "error" in box:
        raise box["error"]
    return box.get("result")


def guarded(site: str, thunk, *, fallback=None, retries: int | None = None,
            backoff: float | None = None, deadline: float | None = None,
            fallback_name: str = "oracle", budget_s: float | None = None,
            breaker=None, subsite: str | None = None, on_fault=None):
    """Dispatch ``thunk()`` under the transient-fault policy.

    Composes *around* the ``obs.instrumented_jit``-compiled cores at
    the Python dispatch layer (inside the dispatch span, outside the
    traced program — jaxprs are untouched).  Per attempt the injection
    plan fires first (:func:`inject` at ``site``, then at
    ``site@subsite`` when a ``subsite`` — e.g. the op of a serve batch
    — is given, so a chaos plan can poison one class of a shared
    site), then the call runs under the optional watchdog
    ``deadline``.  A transient fault (:func:`is_transient`) is retried
    up to ``retries`` times with jittered exponential ``backoff``; on
    exhaustion the flight recorder is armed with the fault history and
    the call degrades to ``fallback()`` (typically the op's NumPy
    oracle twin — correct output beats no output) or re-raises when no
    fallback exists.  Non-transient exceptions propagate immediately —
    and typed admission sheds (:func:`is_overload`) propagate before
    ANY accounting: a shed is a policy outcome, not a fault, so it
    must neither burn retries, nor arm the flight recorder, nor count
    against a breaker.

    ``budget_s`` clips the whole retry loop to the caller's remaining
    end-to-end budget (the serving layer threads each request's
    deadline in): a retry whose backoff would overrun the budget is
    skipped and the call degrades immediately (``fault_budget_clipped``
    counter, ``budget_clipped`` decision field) — a request can no
    longer exceed its deadline inside the retry loop.

    ``breaker`` is an optional
    :class:`veles.simd_tpu.runtime.breaker.Breaker` the caller already
    admitted through: ``guarded`` records the outcome (success, or
    failure on retry exhaustion) so the breaker's sliding window sees
    exactly the dispatches that reached the device.

    ``on_fault`` is an optional per-caller fault observer — the
    request-axis hook (:mod:`veles.simd_tpu.obs.requests`): called
    best-effort (exceptions swallowed — an observer must never change
    the policy's answer) as ``on_fault("retry", kind, attempt)`` per
    retry, ``on_fault("degrade", kind, attempt)`` when the call
    degrades to its fallback, and ``on_fault("exhausted", kind,
    attempt)`` when it re-raises.  The serving layer and the pipeline
    compiler thread a callback here that appends ``retried`` /
    ``degraded`` edges to every request trace in the dispatched batch.

    ``retries`` / ``backoff`` / ``deadline`` default to the env knobs
    (``VELES_SIMD_FAULT_RETRIES`` / ``_BACKOFF`` / ``_DEADLINE``).
    """

    def _observe_fault(action: str, kind: str, attempt: int) -> None:
        if on_fault is None:
            return
        try:
            on_fault(action, kind, attempt)
        except Exception:  # noqa: BLE001 — observers never change policy
            pass
    if retries is None:
        retries = fault_retries()
    if backoff is None:
        backoff = fault_backoff()
    if deadline is None:
        deadline = fault_deadline()
    t0 = monotonic() if budget_s is not None else 0.0
    attempt = 0
    while True:
        try:
            inject(site)
            if subsite is not None:
                inject(f"{site}@{subsite}")
            result = _call_with_deadline(thunk, deadline, site)
        except Exception as e:
            if is_overload(e):
                # typed shed: a policy outcome, not a fault — no
                # retry, no breaker mark, no flight recorder
                raise
            if not is_transient(e):
                raise
            kind = _fault_kind(e)
            obs.count("fault_transient", site=site, kind=kind)
            delay = backoff_delay(attempt, backoff)
            within_budget = (budget_s is None
                             or monotonic() - t0 + delay <= budget_s)
            if attempt < retries and within_budget:
                _note_fault(site, kind, "retry", attempt + 1, e)
                obs.count("fault_retry", site=site)
                obs.record_decision(
                    "fault_policy", "retry", site=site, kind=kind,
                    attempt=attempt + 1, retries=retries,
                    delay_s=delay)
                _observe_fault("retry", kind, attempt + 1)
                if delay > 0:
                    time.sleep(delay)
                attempt += 1
                continue
            clipped = attempt < retries and not within_budget
            if clipped:
                obs.count("fault_budget_clipped", site=site)
            _note_fault(site, kind, "exhausted", attempt, e)
            obs.count("fault_exhausted", site=site, kind=kind)
            if breaker is not None:
                breaker.failure()
            bundle = _arm_flightrec(site, e)
            # attempt count + backoff delay travel on the durable
            # record (obs v6 journal): a postmortem reading only the
            # journal must see how hard the policy fought before it
            # gave the request away
            obs.record_decision(
                "fault_policy",
                "degrade" if fallback is not None else "exhausted",
                site=site, kind=kind, attempt=attempt,
                retries=retries,
                flight_bundle=bundle, budget_clipped=clipped,
                fallback=fallback_name if fallback is not None
                else None)
            if fallback is None:
                _observe_fault("exhausted", kind, attempt)
                raise
            obs.count("fault_degraded", site=site, to=fallback_name)
            _observe_fault("degrade", kind, attempt)
            return fallback()
        else:
            if breaker is not None:
                breaker.success()
            return result


def breaker_guarded(site: str, key, thunk, *, fallback=None,
                    fallback_name: str = "oracle",
                    breaker_site: str | None = None, **kwargs):
    """:func:`guarded` behind the ``(site, key)`` circuit breaker —
    the standard composition for a dispatch site whose shape classes
    can fail independently (the ``ops/`` guarded dispatchers, the
    sharded ``parallel/`` sites; ``serve/`` hand-rolls the same steps
    to interleave its health machine).

    The class's breaker (minted at ``breaker_site`` or ``site``) is
    admitted first: **open** answers straight from ``fallback()``
    (``fault_breaker_short_circuit`` counter + ``short_circuit``
    decision — zero retry latency for a known-bad class), a half-open
    **probe** (and an open class with no fallback, e.g. a forced
    route) dispatches with a zero-retry budget, and **closed** runs
    the full policy.  Outcomes flow back into the breaker through
    :func:`guarded`'s ``breaker=`` wiring.  Remaining ``kwargs``
    (``budget_s``, ``subsite``, ``backoff``, ...) pass through."""
    from veles.simd_tpu.runtime import breaker as _breaker

    br = _breaker.breaker_for(breaker_site or site, key)
    verdict = br.admit()
    if verdict == _breaker.OPEN:
        if fallback is not None:
            obs.count("fault_breaker_short_circuit", site=site)
            obs.record_decision(
                "fault_policy", "short_circuit", site=site,
                key=repr(key), fallback=fallback_name)
            on_fault = kwargs.get("on_fault")
            if on_fault is not None:
                try:    # observer only — never changes the answer
                    on_fault("degrade", "breaker_open", 0)
                except Exception:  # noqa: BLE001
                    pass
            return fallback()
        verdict = "probe"   # no fallback to shed to: zero-retry trial
    if verdict != _breaker.CLOSED:
        kwargs["retries"] = 0
    return guarded(site, thunk, fallback=fallback,
                   fallback_name=fallback_name, breaker=br, **kwargs)
