"""veles.simd_tpu.serve — the resilient request path in front of the ops.

The "millions of users" front half (ROADMAP item 1): every op in this
library is a one-shot call, which at short-signal sizes is
dispatch-bound by design — the throughput form of heterogeneous
traffic is *coalesced* dispatch.  This package is the serving loop
that does the coalescing and, more importantly, keeps answering when
the traffic or the hardware misbehaves:

* :class:`~veles.simd_tpu.serve.server.Server` — submit
  :class:`~veles.simd_tpu.serve.server.Request`\\ s
  (op + signal + params + tenant), get
  :class:`~veles.simd_tpu.serve.server.Ticket`\\ s; requests are
  bucketed by shape class, zero-padded to power-of-two buckets, and
  dispatched as batches through the
  :mod:`veles.simd_tpu.ops.batched` compiled-handle LRU;
* :mod:`~veles.simd_tpu.serve.batcher` — the dynamic-batching policy:
  a bucket dispatches when full (``max_batch``) or when its oldest
  request hits the latency deadline (``max_wait``), whichever fires
  first;
* :mod:`~veles.simd_tpu.serve.admission` — bounded global/per-tenant
  queue depth; over-limit submits are answered *immediately* with a
  typed :class:`~veles.simd_tpu.serve.admission.Overloaded` (never
  queued to time out), or block-with-deadline when the caller opts
  into backpressure;
* :mod:`~veles.simd_tpu.serve.health` — the HEALTHY/DEGRADED state
  machine over :func:`veles.simd_tpu.runtime.faults.guarded`
  dispatch: transient device faults retry, persistent ones degrade
  the server to the NumPy oracle (parity-correct answers, flight
  recorder armed) while zero-retry probes hunt for recovery.

Knobs (constructor args override the environment):
``VELES_SIMD_SERVE_MAX_BATCH``, ``VELES_SIMD_SERVE_MAX_WAIT_MS``,
``VELES_SIMD_SERVE_QUEUE_DEPTH``, ``VELES_SIMD_SERVE_TENANT_DEPTH``.
Chaos: ``VELES_SIMD_FAULT_PLAN`` sites ``serve.dispatch``
(device_lost/timeout -> retry/degrade) and ``serve.admission``
(overload -> deterministic shed).  ``tools/loadgen.py`` drives all of
it (Poisson + burst arrivals, mixed tenants) as the chaos harness and
the ``make bench-serve`` family.
"""

from veles.simd_tpu.serve.admission import (DEFAULT_QUEUE_DEPTH,
                                            DEFAULT_TENANT_DEPTH,
                                            QUEUE_DEPTH_ENV,
                                            TENANT_DEPTH_ENV,
                                            AdmissionController,
                                            Overloaded)
from veles.simd_tpu.serve.batcher import (DEFAULT_MAX_BATCH,
                                          DEFAULT_MAX_WAIT_MS,
                                          MAX_BATCH_ENV, MAX_WAIT_ENV,
                                          Batcher, bucket_length)
from veles.simd_tpu.serve.health import (DEGRADED, HEALTHY,
                                         HealthMonitor)
from veles.simd_tpu.serve.server import (SUPPORTED_OPS, Request,
                                         Server, ServerClosed, Ticket)

__all__ = [
    "Server", "Request", "Ticket", "ServerClosed", "Overloaded",
    "AdmissionController", "Batcher", "HealthMonitor",
    "bucket_length", "SUPPORTED_OPS", "HEALTHY", "DEGRADED",
    "MAX_BATCH_ENV", "MAX_WAIT_ENV", "QUEUE_DEPTH_ENV",
    "TENANT_DEPTH_ENV", "DEFAULT_MAX_BATCH", "DEFAULT_MAX_WAIT_MS",
    "DEFAULT_QUEUE_DEPTH", "DEFAULT_TENANT_DEPTH",
]
