"""The compensated-precision layer (runtime/precision.py) and its
routes: per-(route, precision) error-budget parity vs the float64
NumPy oracles, the adversarial bf16_comp-beats-bf16 gate, engine
eligibility/refusal (int8 opt-in, bf16 forced-only,
VELES_SIMD_DISABLE_BF16_COMP), the fast= deprecation shim, and the
end-to-end autotune gate — the measured tuner crowning a PRECISION
winner per geometry with decision-event + tune-cache introspection
proof (the test_routing stft pattern)."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from veles.simd_tpu import obs
from veles.simd_tpu.ops import convolve as cv
from veles.simd_tpu.ops import matrix as mx
from veles.simd_tpu.ops import spectral as sp
from veles.simd_tpu.runtime import precision as prx
from veles.simd_tpu.runtime import routing
from veles.simd_tpu.utils import benchmark as bm

RNG = np.random.RandomState(59)

BUDGET = prx.ERROR_BUDGETS["bf16_comp"]


def _rel(got, want):
    """Max-normalized relative error — the tune tools' metric.
    ``got`` may be real or complex; the difference promotes to
    ``want``'s float64/complex128."""
    return float(np.max(np.abs(np.asarray(got) - want))
                 / max(1e-30, np.max(np.abs(want))))


def _adversarial(shape, rng):
    """Large-dynamic-range operand: randn scaled by per-element
    powers of ten across six decades — the input that exposes plain
    bf16's mantissa loss."""
    return (rng.randn(*shape)
            * 10.0 ** rng.uniform(-3, 3, shape)).astype(np.float32)


@pytest.fixture
def fresh_cache(tmp_path, monkeypatch):
    path = str(tmp_path / "tune.json")
    monkeypatch.setenv(routing.AUTOTUNE_CACHE_ENV, path)
    routing.set_cache_path(None)
    yield path
    routing.set_cache_path(None)


@pytest.fixture
def autotune_on(monkeypatch):
    monkeypatch.setenv(routing.AUTOTUNE_ENV, "on")
    yield
    routing.set_cache_path(None)


def _fake_timer(table):
    def timer(thunk, name):
        thunk()
        if name not in table:
            raise RuntimeError(f"no timing for {name}")
        return table[name]
    return timer


# ---------------------------------------------------------------------------
# the layer's primitives
# ---------------------------------------------------------------------------

class TestPrimitives:
    def test_split_reconstructs(self):
        x = jnp.asarray(RNG.randn(256).astype(np.float32))
        hi, lo = prx.split_bf16(x)
        rec = hi.astype(jnp.float32) + lo.astype(jnp.float32)
        # two bf16 mantissas stack to ~16 bits: ~2^-17 relative
        assert _rel(rec, np.asarray(x, np.float64)) < 5e-5

    @pytest.mark.parametrize("precision,budget", [
        ("highest", prx.ERROR_BUDGETS["highest"]),
        ("bf16_comp", prx.ERROR_BUDGETS["bf16_comp"]),
        ("bf16", prx.ERROR_BUDGETS["bf16"]),
        ("int8", prx.ERROR_BUDGETS["int8"]),
    ])
    def test_einsum_within_budget(self, precision, budget):
        a = RNG.randn(128, 256).astype(np.float32)
        b = RNG.randn(256, 64).astype(np.float32)
        want = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
        got = prx.p_einsum("ij,jk->ik", jnp.asarray(a),
                           jnp.asarray(b), precision=precision)
        assert _rel(got, want) <= budget, precision

    def test_bf16_comp_beats_bf16_10x_adversarial(self):
        """The satellite gate: on a large-dynamic-range input the
        compensated route's error is >= 10x smaller than plain
        bf16's (measured ~460x on the randn-decades input)."""
        a = _adversarial((256, 256), RNG)
        b = _adversarial((256, 256), RNG)
        want = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
        err_bf16 = _rel(prx.p_matmul(jnp.asarray(a), jnp.asarray(b),
                                     precision="bf16"), want)
        err_comp = _rel(prx.p_matmul(jnp.asarray(a), jnp.asarray(b),
                                     precision="bf16_comp"), want)
        assert err_comp * 10 <= err_bf16, (err_comp, err_bf16)
        assert err_comp <= BUDGET

    def test_eligibility_policy(self, monkeypatch):
        assert prx.precision_allowed("highest")
        assert prx.precision_allowed("bf16_comp")
        assert not prx.precision_allowed("bf16")   # forced-only
        assert not prx.precision_allowed("int8")   # opt-in
        monkeypatch.setenv(prx.INT8_ENV, "1")
        assert prx.precision_allowed("int8")
        monkeypatch.setenv(prx.BF16_COMP_ENV, "1")
        assert not prx.precision_allowed("bf16_comp")

    def test_unknown_precision_rejected(self):
        with pytest.raises(ValueError):
            prx.p_matmul(jnp.zeros((2, 2)), jnp.zeros((2, 2)),
                         precision="fp64")

    def test_route_name_round_trip(self):
        assert prx.comp_route("rdft_matmul") == \
            "rdft_matmul_bf16_comp"
        assert prx.base_route("rdft_matmul_bf16_comp") == \
            "rdft_matmul"
        assert prx.base_route("xla_fft") == "xla_fft"


# ---------------------------------------------------------------------------
# per-(route, precision) parity vs the float64 oracles
# ---------------------------------------------------------------------------

class TestGemmRoutes:
    def test_bf16_comp_within_budget(self):
        a = RNG.randn(256, 512).astype(np.float32)
        b = RNG.randn(512, 128).astype(np.float32)
        want = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
        got = mx.matrix_multiply(a, b, simd=True,
                                 precision="bf16_comp")
        assert _rel(got, want) <= BUDGET

    def test_transposed_bf16_comp_within_budget(self):
        a = RNG.randn(128, 512).astype(np.float32)
        bt = RNG.randn(64, 512).astype(np.float32)
        want = np.einsum("ij,kj->ik", np.asarray(a, np.float64),
                         np.asarray(bt, np.float64))
        got = mx.matrix_multiply_transposed(a, bt, simd=True,
                                            precision="bf16_comp")
        assert _rel(got, want) <= BUDGET

    def test_gemv_precision_forced(self):
        m = RNG.randn(300, 256).astype(np.float32)
        v = RNG.randn(256).astype(np.float32)
        want = np.asarray(m, np.float64) @ np.asarray(v, np.float64)
        got = mx.matrix_vector_multiply(m, v, simd=True,
                                        precision="bf16_comp")
        assert _rel(got, want) <= BUDGET

    def test_forced_int8_loose_budget(self):
        """int8 is forceable without the env opt-in; its error sits
        inside its own (loose) budget on unit-scale input."""
        a = RNG.randn(128, 128).astype(np.float32)
        b = RNG.randn(128, 128).astype(np.float32)
        want = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
        got = mx.matrix_multiply(a, b, simd=True, precision="int8")
        assert _rel(got, want) <= prx.ERROR_BUDGETS["int8"]

    def test_fast_shim_maps_to_bf16_route(self):
        """The deprecation shim: fast=True -> the bf16 route, with a
        DeprecationWarning and a matrix_precision_route decision
        event — the last precision choice outside the engine gone."""
        a = RNG.randn(64, 64).astype(np.float32)
        b = RNG.randn(64, 64).astype(np.float32)
        obs.enable()
        obs.reset()
        try:
            with pytest.warns(DeprecationWarning):
                got = mx.matrix_multiply(a, b, simd=True, fast=True)
            ev = [e for e in obs.events()
                  if e["op"] == "matrix_precision_route"][-1]
            assert ev["decision"] == "bf16"
            assert ev["forced"]
            want = np.asarray(mx.matrix_multiply(
                a, b, simd=True, precision="bf16"))
            np.testing.assert_allclose(np.asarray(got), want)
        finally:
            obs.disable()
            obs.reset()

    def test_engine_default_is_fp32(self):
        """With autotune off the static prior stays the
        oracle-parity fp32 route — precision candidates never change
        the default."""
        a = RNG.randn(64, 64).astype(np.float32)
        b = RNG.randn(64, 64).astype(np.float32)
        obs.enable()
        obs.reset()
        try:
            mx.matrix_multiply(a, b, simd=True)
            ev = [e for e in obs.events()
                  if e["op"] == "matrix_precision_route"][-1]
            assert ev["decision"] == "fp32"
            assert not ev["forced"]
        finally:
            obs.disable()
            obs.reset()

    def test_bad_precision_rejected(self):
        a = np.zeros((4, 4), np.float32)
        with pytest.raises(ValueError):
            mx.matrix_multiply(a, a, simd=True, precision="fp16")

    def test_family_registered(self):
        fams = routing.families()
        assert "matrix.gemm" in fams
        assert set(fams["matrix.gemm"].names()) == {
            "fp32", "bf16_comp", "int8", "bf16"}


class TestSpectralRoutes:
    def test_stft_istft_round_trip_within_budget(self):
        x = RNG.randn(8192).astype(np.float32)
        spec = sp.stft(x, 512, 128, simd=True,
                       route="rdft_matmul_bf16_comp")
        want = sp.stft_na(x, 512, 128)
        assert _rel(np.asarray(spec), want) <= BUDGET
        rec = sp.istft(np.asarray(spec), 8192, 512, 128, simd=True,
                       route="rdft_matmul_bf16_comp")
        interior = slice(512, -512)
        assert _rel(np.asarray(rec)[interior],
                    np.asarray(x, np.float64)[interior]) <= BUDGET

    def test_hilbert_within_budget(self):
        x = RNG.randn(512).astype(np.float32)
        got = sp.hilbert(x, simd=True, route="matmul_dft_bf16_comp")
        want = sp.hilbert_na(x)
        assert _rel(got, want) <= BUDGET

    def test_cwt_within_budget(self):
        x = RNG.randn(512).astype(np.float32)
        scales = [2.0, 4.0, 8.0]
        got = sp.morlet_cwt(x, scales, simd=True,
                            route="matmul_dft_bf16_comp")
        want = sp.morlet_cwt_na(x, scales)
        assert _rel(got, want) <= BUDGET

    def test_disable_env_closes_comp_gates(self, monkeypatch):
        assert sp._STFT_FAMILY.gate("rdft_matmul_bf16_comp",
                                    frame_length=512, hop=128,
                                    frames=100)
        monkeypatch.setenv(prx.BF16_COMP_ENV, "1")
        for fam, geom in (
                (sp._STFT_FAMILY,
                 {"frame_length": 512, "hop": 128, "frames": 100}),
                (sp._ISTFT_FAMILY, {"frame_length": 512, "hop": 128}),
                (sp._HILBERT_FAMILY, {"n": 512}),
                (sp._CWT_FAMILY, {"n": 512})):
            comp = [r for r in fam.names() if r.endswith("bf16_comp")]
            assert comp and not fam.gate(comp[0], **geom), fam.name

    def test_static_priors_unchanged(self):
        """The comp candidates sit after the terminal fallback: the
        static selection (autotune off) never picks them."""
        assert sp._select_stft_route(512, 128, 100) == "rdft_matmul"
        assert sp._STFT_FAMILY.static_select(
            frame_length=8192, hop=1024, frames=10) == "xla_fft"


class TestConvolveRoutes:
    def test_os_matmul_bf16_comp_within_budget(self):
        x = RNG.randn(1 << 15).astype(np.float32)
        h = RNG.randn(511).astype(np.float32)
        want = np.convolve(np.asarray(x, np.float64),
                           np.asarray(h, np.float64))
        got = cv._conv_os_matmul(jnp.asarray(x), jnp.asarray(h),
                                 cv.overlap_save_step(511),
                                 precision="bf16_comp")
        assert _rel(got, want) <= BUDGET

    def test_comp_beats_bf16_on_adversarial_signal(self):
        x = _adversarial((1 << 14,), RNG)
        h = RNG.randn(127).astype(np.float32)
        want = np.convolve(np.asarray(x, np.float64),
                           np.asarray(h, np.float64))
        step = cv.overlap_save_step(127)
        err_bf16 = _rel(cv._conv_os_matmul(
            jnp.asarray(x), jnp.asarray(h), step,
            precision="bf16"), want)
        err_comp = _rel(cv._conv_os_matmul(
            jnp.asarray(x), jnp.asarray(h), step,
            precision="bf16_comp"), want)
        assert err_comp * 10 <= err_bf16, (err_comp, err_bf16)
        assert err_comp <= BUDGET

    def test_comp_route_in_family_and_eligible(self):
        fam = routing.get_family("convolve.os")
        assert "xla_matmul_bf16_comp" in fam.names()
        assert fam.gate("xla_matmul_bf16_comp", h_length=511)

    def test_dispatched_comp_route_records_decision(
            self, fresh_cache, monkeypatch):
        """A tune-cache winner steers the real dispatch onto the comp
        route, and the convolve_os_route decision event attributes
        it (readonly mode: consult, never probe)."""
        n, k = 1 << 15, 511
        handle = cv.convolve_overlap_save_initialize(n, k)
        routing.tune_cache().store(
            "convolve.os",
            {"rows": 1, "x_length": routing.pow2_bucket(n),
             "h_length": k, "step": handle.step,
             "precision": cv.os_precision()},
            "xla_matmul_bf16_comp", source="test")
        x = RNG.randn(n).astype(np.float32)
        h = RNG.randn(k).astype(np.float32)
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "readonly")
        obs.enable()
        obs.reset()
        try:
            got = cv.convolve_overlap_save(handle, jnp.asarray(x),
                                           jnp.asarray(h), simd=True)
            ev = [e for e in obs.events()
                  if e["op"] == "convolve_os_route"][-1]
            assert ev["decision"] == "xla_matmul_bf16_comp"
            want = np.convolve(np.asarray(x, np.float64),
                               np.asarray(h, np.float64))
            assert _rel(got, want) <= BUDGET
        finally:
            obs.disable()
            obs.reset()


@pytest.mark.parametrize("n", [4096])
class TestShardedRoutes:
    def test_sharded_rfft_bf16_comp_within_budget(self, n):
        from veles.simd_tpu import parallel as par
        from veles.simd_tpu.parallel import fourier as fr
        from veles.simd_tpu.utils.platform import to_host

        mesh = par.make_mesh({"sp": 8})
        x = RNG.randn(n).astype(np.float32)
        want = np.fft.rfft(np.asarray(x, np.float64))
        obs.enable()
        obs.reset()
        try:
            got = to_host(fr.sharded_rfft(
                x, mesh, route="sharded_matmul_dft_bf16_comp"))
            assert _rel(got, want) <= BUDGET
            ev = [e for e in obs.events()
                  if e["op"] == "sharded_rfft"][-1]
            assert ev["decision"] == "sharded_matmul_dft_bf16_comp"
            assert ev["precision"] == "bf16_comp"
            assert ev["ici_bytes"] > 0
            # the model's payload width: the comp route ships the
            # exact f32 pair (a lossy bf16 payload fails the budget
            # — A2A_PAYLOAD_BYTES doc)
            assert ev["ici_bytes"] == fr.a2a_ici_bytes(
                n, fr.A2A_PAYLOAD_BYTES["bf16_comp"], 8)
        finally:
            obs.disable()
            obs.reset()

    def test_sharded_irfft_round_trip(self, n):
        from veles.simd_tpu import parallel as par
        from veles.simd_tpu.parallel import fourier as fr
        from veles.simd_tpu.utils.platform import to_host

        mesh = par.make_mesh({"sp": 8})
        x = RNG.randn(n).astype(np.float32)
        spec = np.fft.rfft(np.asarray(x, np.float64)).astype(
            np.complex64)
        got = to_host(fr.sharded_irfft(
            spec, n, mesh, route="sharded_matmul_dft_bf16_comp"))
        assert _rel(got, np.asarray(x, np.float64)) <= BUDGET


# ---------------------------------------------------------------------------
# the autotuner crowns a precision winner per geometry (decision event
# + tune-cache introspection, the test_routing end-to-end pattern)
# ---------------------------------------------------------------------------

class TestAutotunedPrecision:
    def test_gemm_precision_winner_selected_persisted_reloaded(
            self, fresh_cache, autotune_on):
        a = RNG.randn(96, 96).astype(np.float32)
        b = RNG.randn(96, 96).astype(np.float32)
        timer = _fake_timer({"fp32": 5.0, "bf16_comp": 1.0,
                             "int8": 9.0, "bf16": 9.0})
        obs.enable()
        obs.reset()
        try:
            with routing.probe_timer(timer):
                mx.matrix_multiply(a, b, simd=True)
            route_ev = [e for e in obs.events()
                        if e["op"] == "matrix_precision_route"][-1]
            assert route_ev["decision"] == "bf16_comp"
            tune_ev = [e for e in obs.events()
                       if e["op"] == "autotune"][-1]
            assert tune_ev["family"] == "matrix.gemm"
            assert tune_ev["decision"] == "bf16_comp"
            assert tune_ev["static"] == "fp32"
            # persisted under the gemm geometry class...
            data = json.load(open(fresh_cache))
            keys = [k for k in data["entries"]
                    if k.startswith("matrix.gemm|")]
            assert keys
            assert data["entries"][keys[0]]["route"] == "bf16_comp"
            # ...and a fresh cache object (= new process) serves the
            # winner with NO probing
            routing.set_cache_path(None)
            obs.reset()
            with routing.probe_timer(_fake_timer({})):
                mx.matrix_multiply(a, b, simd=True)
            route_ev = [e for e in obs.events()
                        if e["op"] == "matrix_precision_route"][-1]
            assert route_ev["decision"] == "bf16_comp"
            assert not [e for e in obs.events()
                        if e["op"] == "autotune"]
            assert obs.counter_value("autotune_cache_hit",
                                     family="matrix.gemm") >= 1
        finally:
            obs.disable()
            obs.reset()

    def test_convolve_os_precision_winner(self, fresh_cache,
                                          autotune_on):
        """The os family's comp candidate wins its probe round and
        the winner steers the next dispatch of the same class."""
        n, k = 1 << 15, 511
        x = RNG.randn(n).astype(np.float32)
        h = RNG.randn(k).astype(np.float32)
        handle = cv.convolve_overlap_save_initialize(n, k)
        timer = _fake_timer({"xla_matmul": 5.0,
                             "xla_matmul_bf16_comp": 1.0,
                             "pallas_fused": 9.0})
        obs.enable()
        obs.reset()
        try:
            with routing.probe_timer(timer):
                cv.convolve_overlap_save(handle, jnp.asarray(x),
                                         jnp.asarray(h), simd=True)
            ev = [e for e in obs.events()
                  if e["op"] == "convolve_os_route"][-1]
            assert ev["decision"] == "xla_matmul_bf16_comp"
            entry = routing.tune_cache().entry(
                "convolve.os",
                {"rows": 1, "x_length": routing.pow2_bucket(n),
                 "h_length": k, "step": handle.step,
                 "precision": cv.os_precision()})
            assert entry is not None
            assert entry["route"] == "xla_matmul_bf16_comp"
            assert entry["source"] == "measured"
        finally:
            obs.disable()
            obs.reset()


# ---------------------------------------------------------------------------
# per-precision roofline honesty (utils/benchmark.py)
# ---------------------------------------------------------------------------

class TestRooflineConstants:
    def test_per_precision_bounds(self):
        peak = bm.mxu_peak_tflops()
        assert bm.mxu_f32_bound_tflops("highest") == peak / 6
        assert bm.mxu_f32_bound_tflops("bf16_comp") == peak / 3
        assert bm.mxu_f32_bound_tflops("bf16") == peak
        assert bm.mxu_f32_bound_tflops("int8") == \
            bm.mxu_int8_peak_tops()
        with pytest.raises(ValueError):
            bm.mxu_f32_bound_tflops("fp64")

    def test_gemm_roofline_uses_own_ceiling(self):
        r32 = bm.gemm_roofline(1e12, 1.0, "highest")
        rc = bm.gemm_roofline(1e12, 1.0, "bf16_comp")
        assert rc["roofline_bound_tflops"] == \
            2 * r32["roofline_bound_tflops"]
        assert rc["pct_of_roofline"] == pytest.approx(
            r32["pct_of_roofline"] / 2)

    def test_conv_roofline_accepts_comp(self):
        roof = bm.conv_roofline(1e9, 2047, "bf16_comp")
        assert roof["precision"] == "bf16_comp"


# ---------------------------------------------------------------------------
# docs contract (the test_routing env-documentation pattern)
# ---------------------------------------------------------------------------

class TestDocs:
    def test_envs_and_section_documented(self):
        import os
        guide = open(os.path.join(os.path.dirname(__file__),
                                  os.pardir, "docs",
                                  "GUIDE.md")).read()
        assert "VELES_SIMD_DISABLE_BF16_COMP" in guide
        assert "VELES_SIMD_ENABLE_INT8" in guide
        assert "Precision routes" in guide
