"""Nonlinear & smoothing filters: median/rank, Savitzky-Golay, FIR design.

NEW capability beyond the reference: the reference's filtering is linear
convolution only (``/root/reference/src/convolve.c``).  This module adds
the standard nonlinear/smoothing toolkit — median and rank filtering
(impulse-noise rejection that no linear filter can do), Savitzky-Golay
polynomial smoothing (including derivatives), and window-method FIR
design for all four band types.

TPU-first design:

* **Median/rank filtering is a Batcher compare-exchange network over
  shifted slices** (window area <= 32): the k window taps are k
  shifted views of the full signal/plane, sorted as a LIST of vectors
  by ~k log^2 k fused ``jnp.minimum``/``maximum`` pairs — no window
  matrix, no gather, no generic sort; NaNs keep ``jnp.sort``'s
  order-last semantics via an inf-substitution + non-NaN count
  (``_apply_rank_network``).  Measured round 5 on v5e: 82 GSamples/s
  for medfilt k=7.  Larger windows fall back to the original static
  gather + ``jnp.sort`` over a ``[..., n, k]`` window matrix.
* **Savitzky-Golay is just an FIR correlation** whose taps are a
  host-side least-squares solve (Vandermonde pseudo-inverse), plus
  host-side polynomial edge fits for the scipy ``interp`` mode — the
  device work is one ``conv_general_dilated``.
* **firwin** generalizes :func:`veles.simd_tpu.ops.resample.design_lowpass`
  to highpass/bandpass/bandstop by spectral inversion, all float64
  host-side.

scipy.signal conventions throughout (``medfilt`` zero-padding,
``savgol_filter`` ``interp``/``constant``/``nearest`` modes, ``firwin``
``pass_zero`` semantics) so ports are drop-in; the test-suite pins
parity against scipy.  Oracle twins (``*_na``) are float64 NumPy
implementing the definitions directly (the reference's
two-implementations discipline, ``/root/reference/tests/matrix.cc:94-98``).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from veles.simd_tpu import obs
from veles.simd_tpu.utils.config import resolve_simd
from veles.simd_tpu.runtime import precision as prx

__all__ = [
    "medfilt", "medfilt_na", "medfilt2d", "medfilt2d_na", "order_filter",
    "order_filter_na", "savgol_coeffs", "savgol_filter",
    "savgol_filter_na", "firwin", "firwin2", "remez", "wiener",
    "wiener_na", "deconvolve", "kaiserord", "kaiser_beta",
    "kaiser_atten",
]


# ---------------------------------------------------------------------------
# median / rank
# ---------------------------------------------------------------------------


def _check_kernel(kernel_size: int, what: str = "kernel_size") -> int:
    kernel_size = int(kernel_size)
    if kernel_size < 1 or kernel_size % 2 == 0:
        raise ValueError(f"{what} must be odd and positive, "
                         f"got {kernel_size}")
    return kernel_size


def _shifted_lanes_1d(x, k):
    """k shifted full-signal views of the zero-padded input — the
    lane form of :func:`_window_view_1d` (lane j at sample i equals
    window element [i, j]).  The single home for the pad-and-slice
    construction the rank and Wiener fast paths share."""
    half = k // 2
    pad = [(0, 0)] * (x.ndim - 1) + [(half, half)]
    xpad = jnp.pad(x, pad)
    n = x.shape[-1]
    return [jax.lax.slice_in_dim(xpad, j, j + n, axis=-1)
            for j in range(k)]


def _window_view_1d(x, k, xp):
    """Zero-padded sliding windows ``[..., n, k]`` (scipy medfilt pads
    with zeros on both sides)."""
    half = k // 2
    pad = [(0, 0)] * (x.ndim - 1) + [(half, half)]
    xpad = xp.pad(x, pad)
    idx = np.arange(x.shape[-1])[:, None] + np.arange(k)[None, :]
    if xp is np:
        return xpad[..., idx]
    return jnp.take(xpad, jnp.asarray(idx), axis=-1)


def _batcher_pairs(k: int):
    """Compare-exchange pairs of Batcher's odd-even mergesort network
    for ``k`` inputs (host-side, static).  ~k log^2 k pairs; sorts any
    input ascending when applied in order."""
    pairs = []

    def merge(lo, n, step):
        m = step * 2
        if m < n:
            merge(lo, n, m)
            merge(lo + step, n, m)
            for i in range(lo + step, lo + n - step, m):
                pairs.append((i, i + step))
        else:
            pairs.append((lo, lo + step))

    def sort(lo, n):
        if n > 1:
            m = n // 2
            sort(lo, m)
            sort(lo + m, n - m)
            merge(lo, n, 1)

    # Batcher's construction wants a power-of-2 width; pad virtually
    # and drop pairs touching the padding (+inf sentinels sort high
    # and never move, so the pruned network still sorts the real k)
    n2 = 1 << (k - 1).bit_length()
    sort(0, n2)
    return [(a, b) for a, b in pairs if a < k and b < k]


# the network beats gather + generic jnp.sort up to this window size
# (~k log^2 k fused min/max on full vectors vs a lane sort over a
# materialized [..., n, k] window matrix); measured on v5e round 5:
# medfilt k=7 64x65536 82,194 Msamples/s, medfilt2d 3x3 16x512^2
# 73,596 Ms/s (the old sort path measured 44 Ms/s on the 8x4k suite
# entry)
_RANK_NETWORK_MAX_K = 32


def _apply_rank_network(lanes, rank):
    """Select the ``rank``-th smallest across a list of equal-shape
    vectors via Batcher compare-exchanges — with ``jnp.sort``'s NaN
    semantics (NaNs order LAST): min/max would smear NaN across every
    lane, so NaNs are substituted with +inf for the network and the
    output is NaN exactly when the window has <= ``rank`` non-NaN
    elements (what sort-then-index returns).  Shared by the 1D and 2D
    rank filters."""
    masks = [jnp.isnan(v) for v in lanes]
    lanes = [jnp.where(m, jnp.inf, v) for m, v in zip(masks, lanes)]
    for a, b in _batcher_pairs(len(lanes)):
        lo = jnp.minimum(lanes[a], lanes[b])
        hi = jnp.maximum(lanes[a], lanes[b])
        lanes[a], lanes[b] = lo, hi
    n_nonnan = sum((~m).astype(jnp.int32) for m in masks)
    return jnp.where(rank < n_nonnan, lanes[rank], jnp.nan)


@functools.partial(obs.instrumented_jit, static_argnames=("k", "rank"))
def _rank_filter_xla(x, k, rank):
    if k > _RANK_NETWORK_MAX_K:
        win = _window_view_1d(x, k, jnp)
        return jnp.sort(win, axis=-1)[..., rank]
    # k shifted full-signal slices; run the sorting network on the
    # slice LIST (k vectors), then take the rank-th — everything is
    # elementwise min/max on [..., n] vectors, XLA fuses the lot
    return _apply_rank_network(_shifted_lanes_1d(x, k), rank)


def order_filter(x, rank: int, kernel_size: int, simd=None):
    """Rank-order filter: the ``rank``-th smallest of each zero-padded
    length-``kernel_size`` window (``rank = k // 2`` is the median)."""
    k = _check_kernel(kernel_size)
    rank = int(rank)
    if not 0 <= rank < k:
        raise ValueError(f"rank {rank} outside [0, {k})")
    if resolve_simd(simd, op="filters"):
        return _rank_filter_xla(jnp.asarray(x, jnp.float32), k, rank)
    return order_filter_na(x, rank, k).astype(np.float32)


def order_filter_na(x, rank: int, kernel_size: int):
    """NumPy float64 oracle twin of :func:`order_filter`."""
    k = _check_kernel(kernel_size)
    x = np.asarray(x, np.float64)
    win = _window_view_1d(x, k, np)
    return np.sort(win, axis=-1)[..., int(rank)]


def medfilt(x, kernel_size: int = 3, simd=None):
    """Median filter (scipy's ``medfilt``: zero-padded edges)."""
    k = _check_kernel(kernel_size)
    return order_filter(x, k // 2, k, simd=simd)


def medfilt_na(x, kernel_size: int = 3):
    k = _check_kernel(kernel_size)
    return order_filter_na(x, k // 2, k)


def _window_view_2d(img, kh, kw, xp):
    """Zero-padded ``[..., H, W, kh*kw]`` windows."""
    hh, hw = kh // 2, kw // 2
    pad = [(0, 0)] * (img.ndim - 2) + [(hh, hh), (hw, hw)]
    p = xp.pad(img, pad)
    h_count, w_count = img.shape[-2], img.shape[-1]
    ri = (np.arange(h_count)[:, None] + np.arange(kh)[None, :])  # [H, kh]
    ci = (np.arange(w_count)[:, None] + np.arange(kw)[None, :])  # [W, kw]
    if xp is np:
        win = p[..., ri[:, None, :, None], ci[None, :, None, :]]
    else:
        win = jnp.take(p, jnp.asarray(ri), axis=-2)   # [..., H, kh, Wp]
        win = jnp.take(win, jnp.asarray(ci), axis=-1)  # [..., H, kh, W, kw]
        win = jnp.moveaxis(win, -3, -2)               # [..., H, W, kh, kw]
    return win.reshape(win.shape[:-2] + (kh * kw,))


@functools.partial(obs.instrumented_jit, static_argnames=("kh", "kw"))
def _medfilt2d_xla(img, kh, kw):
    k = kh * kw
    if k > _RANK_NETWORK_MAX_K:
        win = _window_view_2d(img, kh, kw, jnp)
        return jnp.sort(win, axis=-1)[..., k // 2]
    # kh*kw shifted full-plane slices through the Batcher network —
    # same trick as the 1D rank filter, two shift axes
    hh, hw = kh // 2, kw // 2
    pad = [(0, 0)] * (img.ndim - 2) + [(hh, hh), (hw, hw)]
    p = jnp.pad(img, pad)
    h_count, w_count = img.shape[-2], img.shape[-1]
    lanes = [
        jax.lax.slice_in_dim(
            jax.lax.slice_in_dim(p, i, i + h_count, axis=-2),
            j, j + w_count, axis=-1)
        for i in range(kh) for j in range(kw)]
    return _apply_rank_network(lanes, k // 2)


def medfilt2d(img, kernel_size=3, simd=None):
    """2D median filter (scipy's ``medfilt2d``: zero-padded edges).

    ``kernel_size`` is an int or an ``(kh, kw)`` pair of odd ints.
    """
    if np.isscalar(kernel_size):
        kh = kw = _check_kernel(kernel_size)
    else:
        kh, kw = (_check_kernel(k) for k in kernel_size)
    img_np = img if hasattr(img, "ndim") else np.asarray(img)
    if img_np.ndim < 2:
        raise ValueError("medfilt2d needs [..., H, W]")
    if resolve_simd(simd, op="filters"):
        return _medfilt2d_xla(jnp.asarray(img, jnp.float32), kh, kw)
    return medfilt2d_na(img, (kh, kw)).astype(np.float32)


def medfilt2d_na(img, kernel_size=3):
    if np.isscalar(kernel_size):
        kh = kw = _check_kernel(kernel_size)
    else:
        kh, kw = (_check_kernel(k) for k in kernel_size)
    img = np.asarray(img, np.float64)
    win = _window_view_2d(img, kh, kw, np)
    return np.sort(win, axis=-1)[..., (kh * kw) // 2]


# ---------------------------------------------------------------------------
# Savitzky-Golay
# ---------------------------------------------------------------------------


def _savgol_corr_taps(window_length: int, polyorder: int,
                      deriv: int, delta: float) -> np.ndarray:
    """Correlation-oriented SG taps: ``taps @ x[t-half : t+half+1]``
    evaluates the deriv-th derivative of the LSQ polynomial at t."""
    window_length = _check_kernel(window_length, "window_length")
    polyorder = int(polyorder)
    deriv = int(deriv)
    if polyorder >= window_length:
        raise ValueError("polyorder must be < window_length")
    if deriv < 0:
        raise ValueError("deriv must be >= 0")
    if deriv > polyorder:
        return np.zeros(window_length)
    half = window_length // 2
    pos = np.arange(-half, half + 1, dtype=np.float64)
    # A[i, j] = pos_i^j; taps = row `deriv` of pinv, times d!/delta^d
    a_mat = pos[:, None] ** np.arange(polyorder + 1)[None, :]
    coeffs = np.linalg.pinv(a_mat)[deriv]
    return coeffs * math.factorial(deriv) / (float(delta) ** deriv)


def savgol_coeffs(window_length: int, polyorder: int,
                  deriv: int = 0, delta: float = 1.0) -> np.ndarray:
    """FIR taps of the Savitzky-Golay filter, float64 host-side —
    scipy's ``savgol_coeffs`` convention: oriented for ``np.convolve``
    (reversed relative to a correlation read of the window)."""
    return _savgol_corr_taps(window_length, polyorder, deriv,
                             delta)[::-1]


def _savgol_edge_mats(window_length, polyorder, deriv, delta):
    """mode='interp' edge fix-up as LINEAR MAPS, host-side float64:
    ``head = head_mat @ x[:w]`` and ``tail = tail_mat @ x[-w:]`` give
    the deriv-th derivative of the polynomial fitted to the first/last
    full window, evaluated at the edge positions.  The matrix form is
    what the sharded path (``parallel.sharded_savgol_filter``) applies
    on-device inside ``shard_map``."""
    half = window_length // 2
    pos = np.arange(window_length, dtype=np.float64)
    a_mat = pos[:, None] ** np.arange(polyorder + 1)[None, :]
    pinv = np.linalg.pinv(a_mat)

    def mat(at):
        m = np.zeros((len(at), window_length))
        for j in range(deriv, polyorder + 1):
            fac = math.factorial(j) / math.factorial(j - deriv)
            m += fac * (at[:, None] ** (j - deriv)) * pinv[j][None, :]
        return m / float(delta) ** deriv

    at = np.arange(half, dtype=np.float64)
    return mat(at), mat(at + (window_length - half))


def _savgol_edge_fits(x_np, window_length, polyorder, deriv, delta):
    """Polynomial edge values for mode='interp' (scipy semantics): the
    :func:`_savgol_edge_mats` maps applied to the end windows."""
    head_mat, tail_mat = _savgol_edge_mats(window_length, polyorder,
                                           deriv, delta)
    head = np.einsum("hw,...w->...h", head_mat,
                     x_np[..., :window_length])
    tail = np.einsum("hw,...w->...h", tail_mat,
                     x_np[..., -window_length:])
    return head, tail


def savgol_filter(x, window_length: int, polyorder: int, deriv: int = 0,
                  delta: float = 1.0, mode: str = "interp", simd=None):
    """Savitzky-Golay smoothing / differentiation (scipy conventions).

    ``mode='interp'`` (default) replaces each edge half-window with the
    evaluation of a polynomial fitted to the first/last full window;
    ``'constant'`` zero-pads; ``'nearest'`` edge-replicates.
    """
    window_length = _check_kernel(window_length, "window_length")
    n = np.shape(x)[-1]
    if mode == "interp" and window_length > n:
        raise ValueError(f"mode='interp' needs window_length "
                         f"{window_length} <= signal length {n}")
    if mode not in ("interp", "constant", "nearest"):
        raise ValueError(f"unknown mode {mode!r}")
    taps = _savgol_corr_taps(window_length, polyorder, deriv, delta)
    half = window_length // 2
    if resolve_simd(simd, op="filters"):
        xj = jnp.asarray(x, jnp.float32)
        if mode == "nearest":
            xe = jnp.concatenate(
                [jnp.repeat(xj[..., :1], half, axis=-1), xj,
                 jnp.repeat(xj[..., -1:], half, axis=-1)], axis=-1)
        else:
            xe = jnp.pad(xj, [(0, 0)] * (xj.ndim - 1) + [(half, half)])
        t = jnp.asarray(taps, jnp.float32)
        lhs = xe.reshape((-1, 1, xe.shape[-1]))
        rhs = t[None, None, :]  # lax conv = correlation (no flip)
        out = jax.lax.conv_general_dilated(
            lhs, rhs, window_strides=(1,), padding="VALID",
            precision=prx.HIGHEST)
        out = out.reshape(xj.shape[:-1] + (n,))
        if mode == "interp":
            head, tail = _savgol_edge_fits(
                np.asarray(x, np.float64), window_length, polyorder,
                int(deriv), float(delta))
            out = jnp.concatenate(
                [jnp.asarray(head, jnp.float32), out[..., half:n - half],
                 jnp.asarray(tail, jnp.float32)], axis=-1)
        return out
    return savgol_filter_na(x, window_length, polyorder, deriv, delta,
                            mode).astype(np.float32)


def savgol_filter_na(x, window_length: int, polyorder: int,
                     deriv: int = 0, delta: float = 1.0,
                     mode: str = "interp"):
    """NumPy float64 oracle twin of :func:`savgol_filter`."""
    window_length = _check_kernel(window_length, "window_length")
    x = np.asarray(x, np.float64)
    n = x.shape[-1]
    taps = _savgol_corr_taps(window_length, polyorder, deriv, delta)
    half = window_length // 2
    if mode == "nearest":
        xe = np.concatenate(
            [np.repeat(x[..., :1], half, axis=-1), x,
             np.repeat(x[..., -1:], half, axis=-1)], axis=-1)
    elif mode in ("constant", "interp"):
        xe = np.pad(x, [(0, 0)] * (x.ndim - 1) + [(half, half)])
    else:
        raise ValueError(f"unknown mode {mode!r}")
    # correlation with the taps
    out = np.empty_like(x)
    for t in range(n):
        out[..., t] = np.einsum("k,...k->...", taps,
                                xe[..., t:t + window_length])
    if mode == "interp":
        head, tail = _savgol_edge_fits(x, window_length, polyorder,
                                       int(deriv), float(delta))
        out[..., :half] = head
        out[..., n - half:] = tail
    return out


# ---------------------------------------------------------------------------
# FIR design (window method, all band types)
# ---------------------------------------------------------------------------


_FIRWIN_PASS_ZERO = {"lowpass": (True, 1), "bandstop": (True, 2),
                     "highpass": (False, 1), "bandpass": (False, 2)}


def _design_window(window, numtaps: int) -> np.ndarray:
    """Resolve a firwin/firwin2 ``window`` argument to taps-length
    float64 samples: a :func:`waveforms.get_window` name or
    ``(name, param)`` tuple (scipy convention — ``("kaiser", beta)``,
    ``("gaussian", std)``, ``("tukey", alpha)`` — handled by
    ``get_window`` itself), or an explicit array of ``numtaps``
    samples."""
    from veles.simd_tpu.ops import waveforms as wf

    # only str/tuple are window SPECS (scipy's convention) — a numeric
    # list is window samples and falls through to the array path
    if isinstance(window, (str, tuple)):
        return wf.get_window(window, numtaps)
    win = np.asarray(window, np.float64)
    if win.shape != (numtaps,):
        raise ValueError(f"window array must have shape ({numtaps},), "
                         f"got {win.shape}")
    return win


def kaiser_beta(a: float) -> float:
    """Kaiser's beta for ``a`` dB of stopband attenuation (scipy's
    ``kaiser_beta``; Kaiser 1974 empirical fit)."""
    a = float(a)
    if a > 50.0:
        return 0.1102 * (a - 8.7)
    if a > 21.0:
        return 0.5842 * (a - 21.0) ** 0.4 + 0.07886 * (a - 21.0)
    return 0.0


def kaiser_atten(numtaps: int, width: float) -> float:
    """Attenuation (dB) of a ``numtaps``-tap Kaiser FIR with transition
    width ``width`` (fraction of Nyquist) — scipy's ``kaiser_atten``."""
    return 2.285 * (int(numtaps) - 1) * np.pi * float(width) + 7.95


def kaiserord(ripple: float, width: float):
    """``(numtaps, beta)`` for a Kaiser-window FIR meeting ``ripple``
    dB of stopband attenuation with transition width ``width`` (fraction
    of Nyquist) — scipy's ``kaiserord``.  Feed the result to
    ``firwin(numtaps, cutoff, window=("kaiser", beta))``.
    """
    ripple = abs(float(ripple))
    if ripple < 8:
        raise ValueError(
            "ripple attenuation too small for the Kaiser formula "
            "(need >= 8 dB)")
    beta = kaiser_beta(ripple)
    numtaps = (ripple - 7.95) / (2.285 * np.pi * float(width)) + 1
    return int(np.ceil(numtaps)), beta


def firwin(numtaps: int, cutoff, pass_zero=True,
           window="hamming") -> np.ndarray:
    """Window-method linear-phase FIR design (scipy's ``firwin``).

    ``cutoff``: scalar or ``(low, high)`` as fractions of Nyquist.
    ``pass_zero``: True keeps DC (lowpass / bandstop), False rejects it
    (highpass / bandpass), or one of the scipy strings ``'lowpass'`` /
    ``'highpass'`` / ``'bandpass'`` / ``'bandstop'``.  A response that
    passes Nyquist needs odd ``numtaps`` (a Type II filter has a forced
    Nyquist zero).  ``window``: any :func:`waveforms.get_window` name,
    a ``("kaiser", beta)``-style tuple, or an explicit taps-length
    array (pair with :func:`kaiserord` for the classic attenuation-
    driven design).  Float64 host-side; unit passband gain.
    """
    numtaps = int(numtaps)
    if numtaps < 1:
        raise ValueError("numtaps must be >= 1")
    edges = np.atleast_1d(np.asarray(cutoff, np.float64))
    if np.any(edges <= 0.0) or np.any(edges >= 1.0):
        raise ValueError(f"cutoffs {edges} must be in (0, 1)")
    if np.any(np.diff(edges) <= 0):
        raise ValueError("cutoffs must be strictly increasing")
    if isinstance(pass_zero, str):
        if pass_zero not in _FIRWIN_PASS_ZERO:
            raise ValueError(f"pass_zero must be a bool or one of "
                             f"{sorted(_FIRWIN_PASS_ZERO)}, "
                             f"got {pass_zero!r}")
        pass_zero, want_edges = _FIRWIN_PASS_ZERO[pass_zero]
        if len(edges) != want_edges:
            raise ValueError(f"that band type takes {want_edges} "
                             f"cutoff(s), got {len(edges)}")
    else:
        pass_zero = bool(pass_zero)
    # the response passes Nyquist iff the LAST band is a passband
    passes_nyquist = pass_zero if len(edges) % 2 == 0 else not pass_zero
    if passes_nyquist and numtaps % 2 == 0:
        raise ValueError("a response that passes Nyquist needs odd "
                         "numtaps (Type II filters have a Nyquist zero)")
    m = np.arange(numtaps, dtype=np.float64) - (numtaps - 1) / 2.0
    win = _design_window(window, numtaps)

    def sinc_lp(fc):  # ideal lowpass impulse response at cutoff fc
        return fc * np.sinc(fc * m)

    # build from band edges: alternate bands starting at DC per pass_zero
    bands = np.concatenate([[0.0], edges, [1.0]])
    h = np.zeros(numtaps)
    keep = pass_zero
    for lo, hi in zip(bands[:-1], bands[1:]):
        if keep:
            h += sinc_lp(hi) - sinc_lp(lo)
        keep = not keep
    h *= win
    # normalize at scipy's scale frequency: DC when the first passband
    # touches DC, Nyquist when it touches Nyquist, else its center
    if pass_zero:
        h /= np.sum(h)
    else:
        left = edges[0]
        right = edges[1] if len(edges) > 1 else 1.0
        fc_mid = 1.0 if right == 1.0 else (left + right) / 2.0
        gain = np.abs(np.sum(h * np.exp(-1j * np.pi * fc_mid * m)))
        h /= gain
    return h


# ---------------------------------------------------------------------------
# Wiener (adaptive local-statistics) filter
# ---------------------------------------------------------------------------


def _wiener_core(x, k, noise, xp):
    # Local statistics in the locally-demeaned windowed form
    # mean((x_w - mean_w)^2): algebraically identical to scipy's
    # E[x^2] - mean^2 over the zero-padded window, but every quantity
    # squared is ALREADY small, so there is no catastrophic f32
    # cancellation for DC-offset signals (x ~ 1e3 puts x*x at ulp ~0.06
    # while the variance of interest may be 0.01) — and, unlike an
    # algebraically pre-cancelled sum of terms, nothing here degrades
    # if the XLA simplifier reassociates (observed: a decomposed
    # centered-cumsum formulation was re-fused into the cancelling form
    # under jit on the CPU backend).
    if xp is jnp and k <= _RANK_NETWORK_MAX_K:
        # k shifted full-signal slices (the medfilt trick): the local
        # mean/variance are k fused adds each — no [..., n, k] window
        # matrix through HBM.  Same demeaned arithmetic as the gather
        # form below, term for term.  Same size cap as the rank
        # network: beyond it the unrolled program and the serial f32
        # accumulation both grow with k, so the window matrix wins.
        lanes = _shifted_lanes_1d(x, k)
        mean = sum(lanes) / k
        var = sum((ln - mean) ** 2 for ln in lanes) / k
    else:
        win = _window_view_1d(x, k, xp)
        mean = xp.mean(win, axis=-1)
        var = xp.mean((win - mean[..., None]) ** 2, axis=-1)
    if noise is None:
        noise = xp.mean(var, axis=-1, keepdims=True)
    excess = xp.maximum(var - noise, 0.0)
    denom = xp.maximum(var, noise)
    # scipy: mean + (1 - noise/var)+ * (x - mean), var clipped below at
    # the noise floor (where the local variance is all noise, output
    # the local mean)
    return mean + excess / xp.maximum(denom, 1e-30) * (x - mean)


@functools.partial(obs.instrumented_jit, static_argnames=("k",))
def _wiener_xla(x, k, noise):
    return _wiener_core(x, k, noise, jnp)


def wiener(x, mysize: int = 3, noise=None, simd=None):
    """Adaptive Wiener denoise (scipy's 1D ``wiener``): each sample is
    pulled toward its local mean by the fraction of the local variance
    the noise explains — flat regions are smoothed hard, busy regions
    are left alone.  ``noise`` defaults to the mean of the local
    variances (scipy's estimate).  The local statistics are windowed
    demeaned sums — shifted-slice lanes for ``mysize`` <=
    ``_RANK_NETWORK_MAX_K``, the gathered window matrix beyond — in
    one jitted XLA program (formulation rationale in ``_wiener_core``).
    """
    mysize = _check_kernel(mysize, "mysize")
    if resolve_simd(simd, op="filters"):
        xj = jnp.asarray(x, jnp.float32)
        nz = None if noise is None else jnp.float32(noise)
        return _wiener_xla(xj, mysize, nz)
    return wiener_na(x, mysize, noise).astype(np.float32)


def wiener_na(x, mysize: int = 3, noise=None):
    """NumPy float64 oracle twin of :func:`wiener`."""
    mysize = _check_kernel(mysize, "mysize")
    x = np.asarray(x, np.float64)
    return _wiener_core(x, mysize, noise, np)


def firwin2(numtaps: int, freq, gain, nfreqs=None,
            window="hamming") -> np.ndarray:
    """Frequency-sampling FIR design (scipy's ``firwin2`` for Type I/II
    filters): taps whose magnitude response linearly interpolates the
    ``(freq, gain)`` breakpoints (``freq`` ascending in [0, 1], Nyquist
    = 1).  ``window`` as in :func:`firwin` (name, ``(name, param)``
    tuple, array, or None for rectangular).  Float64 host-side.
    """
    numtaps = int(numtaps)
    if numtaps < 3:
        raise ValueError("numtaps must be >= 3")
    freq = np.asarray(freq, np.float64)
    gain = np.asarray(gain, np.float64)
    if freq.shape != gain.shape or freq.ndim != 1 or len(freq) < 2:
        raise ValueError("freq and gain must be equal-length 1D with "
                         ">= 2 points")
    if freq[0] != 0.0 or freq[-1] != 1.0:
        raise ValueError("freq must start at 0 and end at 1")
    if np.any(np.diff(freq) < 0):
        raise ValueError("freq must be nondecreasing")
    if numtaps % 2 == 0 and gain[-1] != 0.0:
        raise ValueError("even numtaps (Type II) forces zero gain at "
                         "Nyquist; set gain[-1] = 0")
    if nfreqs is None:
        nfreqs = 1 + (1 << int(np.ceil(np.log2(numtaps))))
    nfreqs = int(nfreqs)
    if nfreqs < numtaps:
        raise ValueError("nfreqs must be >= numtaps")
    # scipy's SYMMETRIC eps nudge: each duplicated breakpoint (brick
    # wall) moves eps*nfreqs to either side, so a grid point landing
    # exactly on the discontinuity samples the jump midpoint like scipy
    f = freq.copy()
    d = np.diff(f)
    if (d == 0).any():
        eps = np.finfo(np.float64).eps * nfreqs
        for k in np.nonzero(d == 0)[0]:
            f[k] -= eps
            f[k + 1] += eps
    grid = np.linspace(0.0, 1.0, nfreqs)
    mag = np.interp(grid, f, gain)
    # linear phase: delay (numtaps-1)/2, then one irfft
    shift = np.exp(-(numtaps - 1) / 2.0 * 1j * np.pi * grid)
    h = np.fft.irfft(mag * shift, 2 * (nfreqs - 1))[:numtaps]
    win = np.ones(numtaps) if window is None \
        else _design_window(window, numtaps)
    return h * win


def _bary_eval(x, xe, ye, gamma):
    """Second-form barycentric evaluation of the degree-(r-1)
    interpolant through nodes ``xe[:-1]`` with values ``ye[:-1]``
    (weights rescaled from the full-set ``gamma``).  The single
    evaluator behind both the exchange loop and the final tap
    sampling — they must interpolate the SAME polynomial."""
    n_ext = len(xe)
    num = np.zeros_like(x)
    den = np.zeros_like(x)
    exact = np.full(x.shape, -1, dtype=int)
    for j in range(n_ext - 1):
        dx = x - xe[j]
        hit = np.abs(dx) < 1e-14
        exact[hit] = j
        dx[hit] = 1.0
        w_j = gamma[j] * (xe[j] - xe[n_ext - 1])
        num += w_j / dx * ye[j]
        den += w_j / dx
    out = num / den
    known = exact >= 0
    out[known] = ye[exact[known]]
    return out


def _bary_weights(diff: np.ndarray) -> np.ndarray:
    """Barycentric weights ``1 / prod_k (x_j - x_k)`` from the
    zero-diagonal-filled difference matrix, computed in log space and
    normalized to unit max magnitude: products over 50+ node gaps
    under/overflow float64, and every use (the leveled-error ratio,
    the second-form interpolant) is scale-invariant."""
    logs = np.sum(np.log(np.abs(diff)), axis=1)
    signs = np.prod(np.sign(diff), axis=1)
    return signs * np.exp(-(logs - logs.min()))


def remez(numtaps: int, bands, desired, weight=None, fs: float = 1.0,
          grid_density: int = 16, maxiter: int = 50) -> np.ndarray:
    """Parks-McClellan optimal equiripple FIR design (scipy's ``remez``
    for ``type='bandpass'``, the multiband magnitude fit): linear-phase
    taps whose weighted Chebyshev error against the piecewise-constant
    ``desired`` response is minimax over the ``bands``.

    ``bands``: 2k monotonically increasing edges in [0, fs/2];
    ``desired``: k per-band target gains; ``weight``: k per-band error
    weights (default 1).  Host-side float64 (a few hundred scalars of
    exchange iteration — design-time work, like every ``*ord``/
    ``firwin`` routine here).  scipy's ``differentiator``/``hilbert``
    (antisymmetric) types are not offered.

    Implementation: the textbook Remez exchange on the cosine-domain
    barycentric Lagrange interpolant (McClellan-Parks-Rabiner):
    initialize ``r+1`` extremal frequencies uniformly over the dense
    band grid, solve for the leveled error ``delta``, re-pick the
    alternating local maxima of the weighted error, repeat until the
    extremals fix; taps come from sampling the interpolant at the DFT
    frequencies (inverse DFT of a real even spectrum).
    """
    numtaps = int(numtaps)
    if numtaps < 3:
        raise ValueError("numtaps must be >= 3")
    fs = float(fs)
    bands = np.asarray(bands, np.float64).ravel() / fs  # -> [0, 0.5]
    if bands.ndim != 1 or len(bands) < 2 or len(bands) % 2:
        raise ValueError("bands needs an even number of edges "
                         "(pairs of band boundaries)")
    if np.any(np.diff(bands) <= 0) or bands[0] < 0 or bands[-1] > 0.5:
        # STRICTLY increasing: touching bands (a brick wall) would put
        # duplicate nodes on the design grid and poison the barycentric
        # weights
        raise ValueError("band edges must strictly increase within "
                         "[0, fs/2] (no touching bands)")
    n_bands = len(bands) // 2
    desired = np.asarray(desired, np.float64).ravel()
    if len(desired) != n_bands:
        raise ValueError(f"need one desired gain per band "
                         f"({n_bands}), got {len(desired)}")
    if weight is None:
        weight = np.ones(n_bands)
    weight = np.asarray(weight, np.float64).ravel()
    if len(weight) != n_bands or np.any(weight <= 0):
        raise ValueError("need one positive weight per band")

    odd = numtaps % 2
    # half-length of the cosine series: H(f) = sum_k a_k cos(2 pi f k)
    # (type I); type II factors out cos(pi f) first
    r = (numtaps + 1) // 2 if odd else numtaps // 2
    if not odd and desired[-1] != 0 and bands[-1] == 0.5:
        raise ValueError("even numtaps (type II) forces zero gain at "
                         "Nyquist")

    # dense grid, uniform spacing across all bands (scipy's layout):
    # ~grid_density points per extremal; band edges always on-grid
    df = 0.5 / (grid_density * r)
    grid, des_g, wt_g, seg = [], [], [], []
    pos = 0
    for b in range(n_bands):
        lo, hi = bands[2 * b], bands[2 * b + 1]
        m = max(2, int(np.ceil((hi - lo) / df)) + 1)
        g = np.linspace(lo, hi, m)
        grid.append(g)
        des_g.append(np.full(m, desired[b]))
        wt_g.append(np.full(m, weight[b]))
        seg.append((pos, pos + m))
        pos += m
    grid = np.concatenate(grid)
    des_g = np.concatenate(des_g)
    wt_g = np.concatenate(wt_g)
    if not odd:
        # type II: H(f) = cos(pi f) P(f); fit P on the modified
        # target/weight (standard McClellan transformation)
        c = np.cos(np.pi * grid)
        keep = c > 1e-9          # exclude f = 0.5 where the factor dies
        grid, des_g, wt_g, c = (a[keep] for a in (grid, des_g, wt_g, c))
        des_g = des_g / c
        wt_g = wt_g * c
        kept = np.nonzero(keep)[0]
        remap = {old: new for new, old in enumerate(kept)}
        seg2 = []
        for s, e in seg:
            inside = [remap[i] for i in range(s, e) if i in remap]
            if inside:
                seg2.append((inside[0], inside[-1] + 1))
        seg = seg2
    n_grid = len(grid)
    n_ext = r + 1
    if n_grid < n_ext:
        raise ValueError("bands too narrow for this numtaps: the "
                         "design grid has fewer points than extremals")

    ext = np.round(np.linspace(0, n_grid - 1, n_ext)).astype(int)
    x_g = np.cos(2 * np.pi * grid)

    for _ in range(int(maxiter)):
        xe = x_g[ext]
        de = des_g[ext]
        we = wt_g[ext]
        # barycentric weights on the extremal cosines
        diff = xe[:, None] - xe[None, :]
        np.fill_diagonal(diff, 1.0)
        gamma = _bary_weights(diff)
        signs = (-1.0) ** np.arange(n_ext)
        delta = (gamma @ de) / (gamma @ (signs / we))
        # interpolate H through r of the extremals (drop the last; its
        # value is implied by the leveled error)
        ye = de - signs * delta / we
        h_g = _bary_eval(x_g, xe, ye, gamma)
        err = wt_g * (des_g - h_g)
        # new extremals: ONE candidate per sign-region per band (the
        # |err| argmax of each maximal same-sign run) — a plain
        # local-maximum test loses the tiny +-delta regions squeezed
        # between huge opposite-sign transition peaks, stalling the
        # exchange
        cand = []
        ae = np.abs(err)
        sg = np.sign(err)
        for s, e in seg:
            i = s
            while i < e:
                j = i + 1
                while j < e and sg[j] == sg[i]:
                    j += 1
                cand.append(i + int(np.argmax(ae[i:j])))
                i = j
        # enforce sign alternation: within runs of equal sign keep the
        # largest magnitude
        alt = []
        for i in cand:
            if alt and np.sign(err[i]) == np.sign(err[alt[-1]]):
                if abs(err[i]) > abs(err[alt[-1]]):
                    alt[-1] = i
            else:
                alt.append(i)
        if len(alt) < n_ext:
            # exchange degenerated (flat error) — accept convergence
            break
        # keep the n_ext consecutive candidates with the largest
        # smallest-magnitude member (drop from whichever end is weaker)
        while len(alt) > n_ext:
            if abs(err[alt[0]]) < abs(err[alt[-1]]):
                alt.pop(0)
            else:
                alt.pop()
        new_ext = np.asarray(alt)
        if np.array_equal(new_ext, ext):
            break
        ext = new_ext

    # final cosine-series values at the DFT frequencies via the same
    # barycentric interpolant, then an inverse real-even DFT for taps
    xe = x_g[ext]
    de = des_g[ext]
    we = wt_g[ext]
    diff = xe[:, None] - xe[None, :]
    np.fill_diagonal(diff, 1.0)
    gamma = _bary_weights(diff)
    signs = (-1.0) ** np.arange(n_ext)
    delta = (gamma @ de) / (gamma @ (signs / we))
    ye = de - signs * delta / we

    m = 1 << int(np.ceil(np.log2(8 * numtaps)))
    fgrid = np.arange(m // 2 + 1) / m            # [0, 0.5]
    h_s = _bary_eval(np.cos(2 * np.pi * fgrid), xe, ye, gamma)
    if not odd:
        h_s = h_s * np.cos(np.pi * fgrid)
        h_s[-1] = 0.0                            # the Nyquist zero
    # linear phase: delay (numtaps-1)/2, inverse rfft, center-crop
    shift = np.exp(-1j * np.pi * fgrid * (numtaps - 1) * 2 / 2)
    taps = np.fft.irfft(h_s * shift, m)[:numtaps]
    return taps


def deconvolve(signal, divisor):
    """Polynomial long division (scipy's ``deconvolve``): the
    ``(quotient, remainder)`` with ``signal = convolve(divisor,
    quotient) + remainder``.  An inherently sequential recurrence on
    tiny operands — float64 host-side by design (use :mod:`.iir`'s
    ``lfilter`` machinery for long-signal inverse filtering instead).
    """
    num = np.atleast_1d(np.asarray(signal, np.float64))
    den = np.atleast_1d(np.asarray(divisor, np.float64))
    if num.ndim != 1 or den.ndim != 1:
        raise ValueError("signal and divisor must be 1D")
    if den[0] == 0.0:
        raise ValueError("divisor[0] must be nonzero")
    if len(num) < len(den):
        # scipy convention: empty quotient (the zero polynomial)
        return np.zeros(0), num.copy()
    n_out = len(num) - len(den) + 1
    quot = np.zeros(n_out)
    rem = num.copy()
    for i in range(n_out):
        q = rem[i] / den[0]
        quot[i] = q
        rem[i:i + len(den)] -= q * den
    return quot, rem
