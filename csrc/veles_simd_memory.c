/* veles_simd_memory.c — native memory/layout helpers.
 *
 * Rebuild of /root/reference/src/memory.c semantics in pure C (no Python):
 * 64-byte aligned allocation, float fill, FFT zero-padding sizes, reversed
 * (complex-pairwise) copies, power-of-2 helper.  On the device side XLA
 * owns layout, so align_complement_f32 is always 0; these helpers serve
 * host-side staging buffers for the C ABI.
 */

#define _POSIX_C_SOURCE 200112L

#include "veles_simd.h"

#include <stdlib.h>
#include <string.h>

#define VELES_ALIGNMENT 64

void *malloc_aligned(size_t size) {
  void *ptr = NULL;
  if (posix_memalign(&ptr, VELES_ALIGNMENT, size) != 0) {
    return NULL;
  }
  return ptr;
}

void *malloc_aligned_offset(size_t size, int offset) {
  /* reference semantics (src/memory.c:71-75): aligned base, returned
   * pointer shifted by offset; caller frees (ptr - offset). */
  char *base = malloc_aligned(size + (size_t)offset);
  if (base == NULL) {
    return NULL;
  }
  return base + offset;
}

float *mallocf(size_t length) {
  return malloc_aligned(length * sizeof(float));
}

void memsetf(float *ptr, float value, size_t length) {
  for (size_t i = 0; i < length; i++) {
    ptr[i] = value;
  }
}

int next_highest_power_of_2(int value) {
  /* inc/simd/arithmetic.h:1227-1235 bit-smear */
  if (value <= 1) {
    return 1;
  }
  value--;
  value |= value >> 1;
  value |= value >> 2;
  value |= value >> 4;
  value |= value >> 8;
  value |= value >> 16;
  return value + 1;
}

static size_t zeropadding_length(size_t length) {
  /* src/memory.c:131-137: 2 x the next power of 2 > length */
  size_t nl = length;
  int log = 2;
  while (nl) {
    nl >>= 1;
    log++;
  }
  return (size_t)1 << (log - 1);
}

float *zeropadding(const float *data, size_t length, size_t *new_length) {
  return zeropaddingex(data, length, new_length, 0);
}

float *zeropaddingex(const float *data, size_t length, size_t *new_length,
                     size_t additional_length) {
  size_t nl = zeropadding_length(length);
  float *res = mallocf(nl + additional_length);
  if (res == NULL) {
    return NULL;
  }
  memcpy(res, data, length * sizeof(float));
  memsetf(res + length, 0.f, nl + additional_length - length);
  *new_length = nl;
  return res;
}

float *rmemcpyf(float *dest, const float *src, size_t length) {
  for (size_t i = 0; i < length; i++) {
    dest[i] = src[length - i - 1];
  }
  return dest;
}

float *crmemcpyf(float *dest, const float *src, size_t length) {
  /* complex-pairwise reverse: flip sample order, keep (re, im) intact
   * (src/memory.c:178-183); length counts floats, must be even. */
  size_t pairs = length / 2;
  for (size_t i = 0; i < pairs; i++) {
    dest[2 * i] = src[2 * (pairs - i - 1)];
    dest[2 * i + 1] = src[2 * (pairs - i - 1) + 1];
  }
  return dest;
}

/* Elements from ptr to the next 64-byte boundary (src/memory.c:42-68
 * pattern; the reference divides its 32-byte AVX alignment, this build the
 * 64-byte host staging alignment used by malloc_aligned). */
static int align_offset_bytes(const void *ptr) {
  uintptr_t addr = (uintptr_t)ptr;
  if ((addr & (VELES_ALIGNMENT - 1)) != 0) {
    return (int)(VELES_ALIGNMENT - (addr % VELES_ALIGNMENT));
  }
  return 0;
}

int align_complement_f32(const float *ptr) {
  return align_offset_bytes(ptr) / 4;
}

int align_complement_i16(const int16_t *ptr) {
  return align_offset_bytes(ptr) / 2;
}

int align_complement_u16(const uint16_t *ptr) {
  return align_offset_bytes(ptr) / 2;
}

int align_complement_i32(const int32_t *ptr) {
  return align_offset_bytes(ptr) / 4;
}

int align_complement_u32(const uint32_t *ptr) {
  return align_offset_bytes(ptr) / 4;
}

/* ---- wavelet layout helpers (inc/simd/wavelet.h:55-88) ----------------
 * The reference's AVX build interleaves shifted copies for aligned
 * dp_ps loads (src/wavelet.c:100-165); XLA owns device layout, so these
 * follow the reference's non-AVX semantics: plain copy / plain halves. */

float *wavelet_prepare_array(int order, const float *src, size_t length) {
  (void)order;
  float *res = mallocf(length);
  if (res != NULL) {
    memcpy(res, src, length * sizeof(*src));
  }
  return res;
}

float *wavelet_allocate_destination(int order, size_t source_length) {
  (void)order;
  if (source_length < 2 || source_length % 2 != 0) {
    return NULL;
  }
  return mallocf(source_length / 2);
}

void wavelet_recycle_source(int order, float *src, size_t length,
                            float **desthihi, float **desthilo,
                            float **destlohi, float **destlolo) {
  (void)order;
  if (length == 0 || length % 4 != 0) {
    *desthihi = NULL;
    *desthilo = NULL;
    *destlohi = NULL;
    *destlolo = NULL;
    return;
  }
  size_t lq = length / 4;
  *desthihi = src;
  *desthilo = src + lq;
  *destlohi = src + lq * 2;
  *destlolo = src + lq * 3;
}
