"""Request-axis tracing: one causal story per served request.

obs v1-v3 gave the library metrics/decisions (*what was decided*), the
time axis (*what dispatch cost*), and the resource axis (*what the
compiled programs consume*) — all aggregates.  The serving layer
(:mod:`veles.simd_tpu.serve`) made the missing axis obvious: a request
is submitted on one thread, waits in a batcher bucket, is dispatched by
a worker, may retry or degrade inside the fault policy, and is answered
(or shed, or expired) — and none of the existing telemetry can say
*which tenant, which shape class, which phase* ate one request's
budget.  Spans cannot: they are thread-local, and a request's life
crosses threads.  This module is the request axis:

* **:class:`RequestTrace`** — one per ``Server.submit`` (plain ops and
  pipeline invocations alike): a process-monotonic id, the tenant/op/
  shape-class identity, the end-to-end deadline, and a causally-ordered
  event list every lifecycle edge appends to — ``admitted`` (queue and
  tenant depth at entry), ``bucketed``, ``batch_formed`` (batch id,
  co-batched count, padding rows), ``dispatched`` (route + breaker
  state), ``retried``, ``degraded``, and exactly one terminal event
  (``answered`` / ``shed`` / ``expired`` / ``closed`` / ``error``).
  The trace object travels ON the pending-request record across
  threads, so the chain is causal by construction, not by correlation.
* **phase decomposition** — :meth:`RequestTrace.phases` splits the
  total into ``queue_wait`` (mint -> batch formed), ``batch_wait``
  (batch formed -> dispatched), and ``device`` (dispatched ->
  terminal), derived from the SAME event timestamps so the three
  always sum to the total exactly (the loadgen/chaos completeness
  invariant).  Phases land in bounded per-(op, tenant) histograms
  (``request.total`` / ``request.queue_wait`` / ...; tenant label
  cardinality is capped — overflow tenants fold into ``_other``).
* **survivorship-bias-free latency** — EVERY terminal outcome lands in
  ``serve.request_latency{op, status}``: shed, expired, and
  breaker-shed requests finally show up in the latency distribution
  exactly where p99 used to lie by omission.
* **exemplar retention** — the slowest trace per op and every degraded
  trace (bounded ring) are kept as FULL traces; the flight recorder
  embeds them in crash / SLO-breach bundles, and the live endpoint
  (:mod:`veles.simd_tpu.obs.http`) serves them at ``/debug/requests``.
* **per-tenant SLO accounting** — :meth:`RequestTracer.set_slo` (the
  ``obs.slo(...)`` facade) registers a target latency and deadline-hit
  rate per tenant (env defaults: ``$VELES_SIMD_SLO_MS`` /
  ``$VELES_SIMD_SLO_HIT_RATE``); every terminal trace updates the
  tenant's account, exports ``slo_hit_rate`` / ``slo_burn_rate``
  gauges (burn = miss rate over error budget; >1 means the budget is
  burning faster than the target allows), and the first crossing into
  burn records an ``slo``/``breach`` decision event and arms a
  flight-recorder bundle with the exemplars attached.

Cost discipline, same contract as spans: with telemetry off the facade
returns the shared :data:`NULL_REQUEST` after one flag check and every
edge is a no-op; with telemetry on an edge is one lock + list append,
and only the terminal edge touches histograms.  Like the registry and
the event log this module is jax-free and numpy-free — nothing here
can enter a traced program.
"""

from __future__ import annotations

import collections
import os
import threading
import time

__all__ = [
    "RequestTrace", "RequestTracer", "NULL_REQUEST",
    "TERMINAL_STATUSES", "DEFAULT_MAX_TRACES", "DEFAULT_MAX_EXEMPLARS",
    "DEFAULT_MAX_TENANTS", "DEFAULT_SLO_HIT_RATE", "SLO_MS_ENV",
    "SLO_HIT_RATE_ENV", "MAX_TRACES_ENV",
]

# retained completed traces (the /debug/requests ring) and exemplars
# (slowest-per-op + degraded ring); both runtime-configurable
DEFAULT_MAX_TRACES = 256
DEFAULT_MAX_EXEMPLARS = 64
# distinct tenant label values admitted into histogram/gauge labels
# before folding into "_other" — the cardinality bound that lets the
# per-(op, tenant) histograms stay O(ops x tenants) in a multi-tenant
# service without trusting tenant ids to be few
DEFAULT_MAX_TENANTS = 32

SLO_MS_ENV = "VELES_SIMD_SLO_MS"
SLO_HIT_RATE_ENV = "VELES_SIMD_SLO_HIT_RATE"
MAX_TRACES_ENV = "VELES_SIMD_OBS_MAX_TRACES"

# the default deadline-hit / latency-hit rate target when an SLO names
# no rate: three nines is the classic serving starting point, and the
# matching error budget (1e-2) keeps burn rates readable
DEFAULT_SLO_HIT_RATE = 0.99

# ticket status -> terminal event name; "ok"/"degraded" both ANSWER the
# caller (degraded answers are the oracle's — still answers)
TERMINAL_STATUSES = {
    "ok": "answered",
    "degraded": "answered",
    "shed": "shed",
    "expired": "expired",
    "closed": "closed",
    "error": "error",
}

# SLO breach detection waits for a minimum sample so one slow warmup
# request cannot "breach" a fresh tenant
_SLO_MIN_REQUESTS = 20


def _env_float(name: str, default):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        return default
    return value if value > 0 else default


def env_slo_defaults() -> tuple:
    """``(target_ms_or_None, hit_rate)`` from the environment — the SLO
    applied to tenants nobody registered explicitly
    (``$VELES_SIMD_SLO_MS`` unset = no default SLO)."""
    return (_env_float(SLO_MS_ENV, None),
            min(_env_float(SLO_HIT_RATE_ENV, DEFAULT_SLO_HIT_RATE),
                0.999999))


class _NullRequestTrace:
    """Shared no-op trace returned while telemetry is off — every edge
    is one attribute lookup on a singleton, the advertised disabled
    cost."""

    __slots__ = ()
    rid = -1
    op = tenant = shape_class = status = None

    def event(self, name: str, **fields) -> None:
        pass

    def finish(self, status: str, **fields) -> None:
        pass

    def absorb_remote(self, events, replica=None) -> None:
        pass

    def events(self) -> list:
        return []

    def phases(self) -> dict:
        return {}

    def __repr__(self):
        # stable (no memory address): this singleton's repr lands in
        # generated docs, which are committed and freshness-gated
        return "NULL_REQUEST"


NULL_REQUEST = _NullRequestTrace()


class RequestTrace:
    """One request's causal record (minted by
    :meth:`RequestTracer.start`, carried on the server's pending
    record across threads; not constructed directly).

    ``rid`` is process-monotonic; event timestamps are seconds since
    the mint on the shared monotonic clock, so cross-thread edges stay
    causally ordered and phase arithmetic needs no clock translation.
    """

    __slots__ = ("rid", "op", "tenant", "shape_class", "deadline_s",
                 "status", "total_s", "_t0", "_events", "_lock",
                 "_tracer")

    def __init__(self, tracer, rid: int, op: str, tenant: str,
                 shape_class, deadline_s):
        self._tracer = tracer
        self.rid = rid
        self.op = str(op)
        self.tenant = str(tenant)
        self.shape_class = shape_class
        self.deadline_s = deadline_s
        self.status = None
        self.total_s = None
        self._t0 = time.perf_counter()
        self._events: list = []
        self._lock = threading.Lock()

    # -- edges ---------------------------------------------------------------

    def event(self, name: str, **fields) -> None:
        """Append one lifecycle edge (no-op once terminal — a late
        edge must not corrupt a finished trace's phase arithmetic)."""
        t = time.perf_counter() - self._t0
        with self._lock:
            if self.status is not None:
                return
            self._events.append({"event": str(name),
                                 "t_s": t, **fields})

    def finish(self, status: str, **fields) -> None:
        """Record the terminal edge exactly once (idempotent: the
        first caller wins) and hand the completed trace to the tracer
        for histograms, SLO accounting, and exemplar retention."""
        terminal = TERMINAL_STATUSES.get(str(status), "error")
        t = time.perf_counter() - self._t0
        with self._lock:
            if self.status is not None:
                return
            self.status = str(status)
            self.total_s = t
            self._events.append({"event": terminal, "t_s": t,
                                 "status": str(status), **fields})
        self._tracer._finished(self)

    def absorb_remote(self, events, replica=None) -> None:
        """Splice another process's trace events into this chain (the
        RPC client calls this with the replica-side events its reply
        carried, BEFORE completing the ticket).  Remote terminal
        edges are dropped — this trace closes through its own
        :meth:`finish`, exactly once — and lifecycle edges keep their
        names (``batch_formed``/``dispatched`` stay real phase
        anchors) plus a ``replica`` tag marking the process boundary.

        Remote stamps are re-anchored so the LAST absorbed edge lands
        at the splice instant on this trace's clock: monotonic clocks
        do not cross process boundaries, but the whole remote chain
        finished before the reply arrived, so ordering (and the
        phases-sum-to-total invariant) holds by construction.  No-op
        once terminal or for an empty event list."""
        terminal_names = set(TERMINAL_STATUSES.values())
        remote = [dict(e) for e in events
                  if isinstance(e, dict)
                  and e.get("event") not in terminal_names
                  and isinstance(e.get("t_s"), (int, float))]
        if not remote:
            return
        now = time.perf_counter() - self._t0
        offset = now - max(e["t_s"] for e in remote)
        with self._lock:
            if self.status is not None:
                return
            for e in remote:
                e["t_s"] = max(0.0, offset + float(e["t_s"]))
                if replica is not None:
                    e.setdefault("replica", replica)
                self._events.append(e)

    # -- reads ---------------------------------------------------------------

    def events(self) -> list:
        """Causally-ordered copy of the recorded edges."""
        with self._lock:
            return [dict(e) for e in self._events]

    def _event_time(self, name: str):
        for e in self._events:
            if e["event"] == name:
                return e["t_s"]
        return None

    def phases(self) -> dict:
        """The request's phase decomposition, from the event stamps:
        ``queue_wait`` (mint -> batch formed), ``batch_wait`` (batch
        formed -> dispatched), ``device`` (dispatched -> terminal),
        and ``total``.  A phase whose edges never happened (a shed
        request never batches) collapses onto the next known anchor,
        so the three phases ALWAYS sum to the total exactly — the
        completeness invariant loadgen and the chaos campaign gate.
        Empty until terminal."""
        with self._lock:
            if self.status is None:
                return {}
            total = self.total_s
            t_bf = self._event_time("batch_formed")
            t_disp = self._event_time("dispatched")
        if t_disp is None:
            t_disp = total
        if t_bf is None:
            t_bf = t_disp
        return {"queue_wait_s": t_bf,
                "batch_wait_s": t_disp - t_bf,
                "device_s": total - t_disp,
                "total_s": total}

    def to_dict(self) -> dict:
        """JSON-native snapshot of the whole trace (the
        ``/debug/requests`` and flight-bundle form)."""
        with self._lock:
            events = [dict(e) for e in self._events]
            status = self.status
            total = self.total_s
        return {"rid": self.rid, "op": self.op, "tenant": self.tenant,
                "shape_class": self.shape_class,
                "deadline_s": self.deadline_s, "status": status,
                "total_s": total, "events": events,
                "phases": self.phases()}


class RequestTracer:
    """Mint + retention + accounting behind one lock (the storage
    layer of the request axis; the :mod:`veles.simd_tpu.obs` facade
    owns the singleton and the enabled gate).

    ``registry`` is the shared :class:`~veles.simd_tpu.obs.registry.
    MetricsRegistry` the terminal edges feed; ``decision`` is a
    ``record_decision``-compatible callable for SLO breach events;
    ``on_breach`` (optional) is called once per tenant breach
    crossing — the facade wires the flight recorder's budgeted
    auto-capture there."""

    def __init__(self, registry, decision=None, on_breach=None,
                 max_traces: int | None = None,
                 max_exemplars: int = DEFAULT_MAX_EXEMPLARS,
                 max_tenants: int = DEFAULT_MAX_TENANTS):
        if max_traces is None:
            max_traces = int(_env_float(MAX_TRACES_ENV,
                                        DEFAULT_MAX_TRACES))
        if max_traces < 1 or max_exemplars < 1 or max_tenants < 1:
            raise ValueError("request-trace bounds must be >= 1")
        self._registry = registry
        self._decision = decision
        self.on_breach = on_breach
        self._lock = threading.Lock()
        self._next_rid = 0
        self._started = 0
        self._finished_count = 0
        self._by_status: dict = {}
        self._recent = collections.deque(maxlen=int(max_traces))
        self._slowest: dict = {}            # op -> completed trace
        self._degraded = collections.deque(maxlen=int(max_exemplars))
        self._max_tenants = int(max_tenants)
        self._tenant_labels: set = set()
        # tenant -> {"target_ms", "hit_rate"} (explicit registrations;
        # env defaults fill unregistered tenants lazily)
        self._slo: dict = {}
        # tenant -> {"requests", "good", "deadline_misses", "breached"}
        self._accounts: dict = {}

    # -- mint + finish -------------------------------------------------------

    def start(self, op: str, tenant: str = "default", *,
              shape_class=None, deadline_s=None) -> RequestTrace:
        """Mint one trace with the next process-monotonic id."""
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            self._started += 1
        return RequestTrace(self, rid, op, tenant, shape_class,
                            deadline_s)

    def _tenant_label(self, tenant: str) -> str:
        """``tenant``, or ``"_other"`` past the cardinality bound."""
        with self._lock:
            if tenant in self._tenant_labels:
                return tenant
            if len(self._tenant_labels) < self._max_tenants:
                self._tenant_labels.add(tenant)
                return tenant
        return "_other"

    def _finished(self, trace: RequestTrace) -> None:
        """Terminal-edge accounting (called exactly once per trace by
        :meth:`RequestTrace.finish`)."""
        status = trace.status
        tlabel = self._tenant_label(trace.tenant)
        phases = trace.phases()
        # EVERY terminal outcome lands in the latency histogram with a
        # status label — shed and expired requests included, so the
        # tail the server refused is visible in the same distribution
        # as the tail it served (the survivorship-bias fix)
        self._registry.observe("serve.request_latency", trace.total_s,
                               op=trace.op, status=status)
        self._registry.count("serve_completed", op=trace.op,
                             status=status)
        if status == "expired":
            self._registry.count("serve_deadline_miss", op=trace.op,
                                 tenant=tlabel)
        for name in ("queue_wait", "batch_wait", "device", "total"):
            self._registry.observe("request." + name,
                                   phases[name + "_s"],
                                   op=trace.op, tenant=tlabel)
        degraded = status == "degraded"
        with self._lock:
            self._finished_count += 1
            self._by_status[status] = self._by_status.get(status, 0) + 1
            self._recent.append(trace)
            slow = self._slowest.get(trace.op)
            if slow is None or (trace.total_s or 0.0) \
                    > (slow.total_s or 0.0):
                self._slowest[trace.op] = trace
            if degraded:
                self._degraded.append(trace)
        self._slo_account(trace, tlabel)

    # -- SLO accounting ------------------------------------------------------

    def set_slo(self, tenant: str, target_ms: float,
                hit_rate: float = DEFAULT_SLO_HIT_RATE) -> dict:
        """Register ``tenant``'s SLO: answers within ``target_ms``
        (end-to-end, shed/expired count as misses) at ``hit_rate``.
        Returns the stored JSON-native target."""
        target_ms = float(target_ms)
        hit_rate = float(hit_rate)
        if target_ms <= 0:
            raise ValueError("target_ms must be positive")
        if not 0 < hit_rate < 1:
            raise ValueError("hit_rate must be in (0, 1)")
        slo = {"target_ms": target_ms, "hit_rate": hit_rate}
        with self._lock:
            self._slo[str(tenant)] = slo
            # a registered tenant always earns its own label — the
            # cardinality cap bounds UNregistered tenant churn, not
            # operator-declared SLOs
            self._tenant_labels.add(str(tenant))
        self._registry.gauge("slo_target_ms", target_ms,
                             tenant=str(tenant))
        return dict(slo)

    def _slo_for(self, tenant: str) -> dict | None:
        with self._lock:
            slo = self._slo.get(tenant)
        if slo is not None:
            return slo
        target_ms, hit_rate = env_slo_defaults()
        if target_ms is None:
            return None
        return {"target_ms": target_ms, "hit_rate": hit_rate}

    def _slo_account(self, trace: RequestTrace, tlabel: str) -> None:
        slo = self._slo_for(trace.tenant)
        if slo is None:
            return
        good = (trace.status in ("ok", "degraded")
                and trace.total_s * 1e3 <= slo["target_ms"])
        with self._lock:
            # accounts are keyed by the FOLDED label, so per-user
            # tenant churn under an env-default SLO stays bounded at
            # max_tenants + 1 entries ("_other" aggregates the
            # overflow) instead of growing with process lifetime
            acct = self._accounts.setdefault(
                tlabel, {"requests": 0, "good": 0,
                         "deadline_misses": 0, "breached": False})
            acct["requests"] += 1
            if good:
                acct["good"] += 1
            if trace.status == "expired":
                acct["deadline_misses"] += 1
            n, g = acct["requests"], acct["good"]
            observed = g / n
            budget = 1.0 - slo["hit_rate"]
            burn = (1.0 - observed) / budget if budget > 0 else 0.0
            breached = n >= _SLO_MIN_REQUESTS and burn > 1.0
            # crossing detection is a single read-modify-write under
            # THE lock: concurrent terminal traces must elect exactly
            # one winner per crossing (one breach event, one budgeted
            # flight bundle — not one per racing worker)
            crossed = breached != acct["breached"]
            acct["breached"] = breached
        self._registry.gauge("slo_hit_rate", observed, tenant=tlabel)
        self._registry.gauge("slo_burn_rate", burn, tenant=tlabel)
        if not (crossed and breached):
            return
        self._registry.count("slo_breach", tenant=tlabel)
        if self._decision is not None:
            self._decision("slo", "breach", tenant=tlabel,
                           target_ms=slo["target_ms"],
                           hit_rate_target=slo["hit_rate"],
                           observed=round(observed, 6),
                           burn_rate=round(burn, 3), requests=n)
        if self.on_breach is not None:
            try:    # budgeted flight-recorder capture; never raises
                self.on_breach(trace.tenant, burn)
            except Exception:  # noqa: BLE001
                pass

    # -- reads ---------------------------------------------------------------

    def slo_snapshot(self) -> dict:
        """Per-tenant SLO state: targets + live accounts + burn."""
        with self._lock:
            targets = {t: dict(s) for t, s in self._slo.items()}
            accounts = {t: dict(a) for t, a in self._accounts.items()}
        env_ms, env_rate = env_slo_defaults()
        out = {"targets": targets, "accounts": {},
               "env_default": ({"target_ms": env_ms,
                                "hit_rate": env_rate}
                               if env_ms is not None else None)}
        for tenant, acct in sorted(accounts.items()):
            slo = targets.get(tenant) or self._slo_for(tenant)
            n, g = acct["requests"], acct["good"]
            observed = g / n if n else None
            burn = None
            if slo is not None and observed is not None:
                budget = 1.0 - slo["hit_rate"]
                burn = round((1.0 - observed) / budget, 4) \
                    if budget > 0 else 0.0
            out["accounts"][tenant] = {
                **acct,
                "hit_rate_observed": (round(observed, 6)
                                      if observed is not None
                                      else None),
                "burn_rate": burn,
            }
        return out

    def summary(self) -> dict:
        """Compact JSON-native tally (embedded in ``obs.snapshot()``):
        counts only — full traces travel via :meth:`traces_snapshot`
        so a metrics snapshot stays small."""
        with self._lock:
            return {"started": self._started,
                    "finished": self._finished_count,
                    "open": self._started - self._finished_count,
                    "by_status": dict(sorted(self._by_status.items())),
                    "retained": len(self._recent),
                    "exemplar_slowest": len(self._slowest),
                    "exemplar_degraded": len(self._degraded)}

    def traces_snapshot(self, recent: int = 50) -> dict:
        """Full traces for the live endpoint and flight bundles: the
        last ``recent`` completed traces plus both exemplar families."""
        with self._lock:
            tail = list(self._recent)[-int(recent):]
            slowest = dict(self._slowest)
            degraded = list(self._degraded)
        return {
            "summary": self.summary(),
            "recent": [t.to_dict() for t in tail],
            "slowest_by_op": {op: t.to_dict()
                              for op, t in sorted(slowest.items())},
            "degraded": [t.to_dict() for t in degraded],
            "slo": self.slo_snapshot(),
        }

    def configure(self, max_traces: int | None = None,
                  max_exemplars: int | None = None) -> None:
        """Re-bound the retention rings (history is kept up to the new
        bound)."""
        with self._lock:
            if max_traces is not None:
                if int(max_traces) < 1:
                    raise ValueError("max_traces must be >= 1")
                self._recent = collections.deque(
                    self._recent, maxlen=int(max_traces))
            if max_exemplars is not None:
                if int(max_exemplars) < 1:
                    raise ValueError("max_exemplars must be >= 1")
                self._degraded = collections.deque(
                    self._degraded, maxlen=int(max_exemplars))

    def reset(self) -> None:
        """Clear retention, accounts, and tallies (ids keep rising —
        a reset must not mint duplicate rids)."""
        with self._lock:
            self._started = 0
            self._finished_count = 0
            self._by_status.clear()
            self._recent.clear()
            self._slowest.clear()
            self._degraded.clear()
            self._tenant_labels.clear()
            self._slo.clear()
            self._accounts.clear()
