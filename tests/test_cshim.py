"""Build + run the native C shim test suite.

The reference is consumed as a C library (``Simd.pc.in`` pkg-config,
SURVEY.md §1 L0); this test proves the TPU rebuild offers the same C ABI:
it compiles ``csrc/`` and runs the C test binary, which embeds CPython and
drives every op family through ``libveles_simd.so``.
"""

import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CSRC = os.path.join(REPO, "csrc")


@pytest.mark.skipif(shutil.which("gcc") is None or
                    shutil.which("python3-config") is None,
                    reason="native toolchain unavailable")
def test_build_and_run_c_suite():
    build = subprocess.run(["make", "-C", CSRC, "all"],
                           capture_output=True, text=True)
    assert build.returncode == 0, build.stderr[-3000:]

    env = dict(os.environ)
    env["VELES_SIMD_PYROOT"] = REPO
    # fast deterministic backend for CI (JAX_PLATFORMS alone loses to the
    # axon sitecustomize; cshim honors this explicit override)
    env["VELES_SIMD_PLATFORM"] = "cpu"
    run = subprocess.run(
        [os.path.join(CSRC, "build", "test_veles_simd")],
        capture_output=True, text=True, env=env, timeout=600)
    assert run.returncode == 0, (run.stdout[-2000:], run.stderr[-3000:])
    assert "0 failures" in run.stdout

    # the standalone C example must keep running too (make -C csrc demo)
    demo = subprocess.run(["make", "-C", CSRC, "demo"],
                          capture_output=True, text=True, env=env,
                          timeout=600)
    assert demo.returncode == 0, (demo.stdout[-2000:], demo.stderr[-3000:])
    assert "oracle peak agrees: yes" in demo.stdout
