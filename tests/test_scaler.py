"""The control axis (obs v7): the SLO-driven autoscaler
(``veles/simd_tpu/serve/scaler.py``).

Everything here is deterministic — the engine's clock is the signals
bundle's own ``at_s`` stamp, so hysteresis, cooldown, and the
sustained-idle window are driven by a scripted fake clock with ZERO
sleeps.  Contracts pinned:

* every rule fires on its own signal shape (replica_down, slo_burn,
  burn_velocity, queue_velocity, queue_depth, idle) and the priority
  order is replace > scale_up > scale_down;
* hysteresis: below ``up_ticks``/``down_ticks`` consecutive firing
  ticks the decision is a typed ``hysteresis_pending`` no-op, and a
  non-winning action's streak resets;
* cooldown after EVERY action, min/max bounds, and the scale-down
  victim (least queue depth, ties to the newest rid) — all typed
  no-ops, never silent;
* verb failures demote to typed no-ops (``replace_pending`` on the
  ValueError "not DEAD yet", ``spawn_failed``/``retire_failed`` on a
  blown-up verb) and a replaced-by-retire rid is never resurrected;
* a breaker flap-storm produces ZERO actions;
* the decision record carries the full input vector + the triggering
  incident id, lands in the bounded tail, the schema-stamped
  snapshot, and (when armed) the durable journal;
* env knob parsing, the module-level registry the ``/scaler`` route
  serves, and the ReplicaGroup arm/disarm lifecycle.
"""

import sys
import threading
from pathlib import Path
from types import SimpleNamespace

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from veles.simd_tpu import obs  # noqa: E402
from veles.simd_tpu.obs import journal as obs_journal  # noqa: E402
from veles.simd_tpu.runtime import breaker, faults  # noqa: E402
from veles.simd_tpu.serve import cluster  # noqa: E402
from veles.simd_tpu.serve import scaler  # noqa: E402


@pytest.fixture
def telemetry(monkeypatch):
    """Telemetry on, zero backoff, fresh registries before/after."""
    monkeypatch.setenv("VELES_SIMD_FAULT_BACKOFF", "0")
    obs.enable(compile_listeners=False)
    obs.reset()
    breaker.reset()
    faults.reset_fault_history()
    scaler._reset_for_tests()
    yield
    scaler._reset_for_tests()
    obs.disable()
    obs.reset()
    breaker.reset()
    faults.reset_fault_history()


class FakeReplica:
    def __init__(self, rid):
        self.rid = rid


class FakeGroup:
    """Just enough ReplicaGroup surface for the engine's verbs, with
    scriptable failure modes."""

    def __init__(self, n=1, restart_exc=None, spawn_exc=None,
                 retire_exc=None):
        self.rids = [f"r{i}" for i in range(n)]
        self._next = n
        self.calls = []
        self.restart_exc = restart_exc
        self.spawn_exc = spawn_exc
        self.retire_exc = retire_exc

    def alive(self):
        return len(self.rids)

    def live_replicas(self):
        return [FakeReplica(r) for r in self.rids]

    def spawn_replica(self):
        self.calls.append(("spawn",))
        if self.spawn_exc is not None:
            raise self.spawn_exc
        rid = f"r{self._next}"
        self._next += 1
        self.rids.append(rid)
        return FakeReplica(rid)

    def retire(self, rid, reason="scale_down"):
        self.calls.append(("retire", rid, reason))
        if self.retire_exc is not None:
            raise self.retire_exc
        self.rids.remove(rid)

    def restart(self, rid):
        self.calls.append(("restart", rid))
        if self.restart_exc is not None:
            raise self.restart_exc
        return FakeReplica(rid)


def _sig(t, *, burn=0.0, bvel=0.0, depth=0.0, per_replica=None,
         flaps=0, goodput=1.0, health=None, incidents=()):
    """A FleetSignals-shaped bundle with a scripted clock."""
    return SimpleNamespace(
        at_s=float(t),
        slo_burn={"carol": float(burn)} if burn else {},
        slo_burn_velocity={"carol": float(bvel)} if bvel else {},
        queue_depth=dict(per_replica or {}),
        queue_depth_total=float(depth),
        breaker_flaps={"serve": int(flaps)} if flaps else {},
        goodput_overall=float(goodput),
        health=dict(health or {}),
        incidents=list(incidents),
    )


def _engine(group=None, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("cooldown_s", 1.0)
    kw.setdefault("up_ticks", 2)
    kw.setdefault("down_ticks", 3)
    return scaler.ScalerEngine(group or FakeGroup(2), **kw)


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

class TestRules:
    def test_slo_burn_scales_up(self, telemetry):
        g = FakeGroup(1)
        eng = _engine(g)
        assert eng.tick(_sig(0.0, burn=3.0))["reason"] \
            == "hysteresis_pending"
        rec = eng.tick(_sig(0.1, burn=3.0))
        assert rec["action"] == "scale_up"
        assert rec["rule"] == "slo_burn"
        assert rec["replica"] == "r1"
        assert g.alive() == 2

    def test_burn_velocity_needs_warm_burn(self, telemetry):
        eng = _engine(FakeGroup(1))
        # rising velocity over COLD burn: not a firing rule (a noisy
        # derivative alone must not spawn); depth keeps idle quiet
        rec = eng.tick(_sig(0.0, burn=0.1, bvel=2.0, depth=5))
        assert rec["action"] is None and rec["rule"] is None
        assert rec["reason"] == "idle"
        # same velocity with burn already warm fires
        eng2 = _engine(FakeGroup(1))
        eng2.tick(_sig(0.0, burn=0.6, bvel=2.0, depth=5))
        rec = eng2.tick(_sig(0.1, burn=0.6, bvel=2.0, depth=5))
        assert rec["action"] == "scale_up"
        assert rec["rule"] == "burn_velocity"

    def test_queue_velocity_from_depth_slope(self, telemetry):
        eng = _engine(FakeGroup(1), queue_velocity=10.0,
                      depth_high=1e9)
        eng.tick(_sig(0.0, depth=5))
        # 45 queued in 1s = 45/s > 10/s threshold, two ticks in a row
        eng.tick(_sig(1.0, depth=50))
        rec = eng.tick(_sig(2.0, depth=95))
        assert rec["action"] == "scale_up"
        assert rec["rule"] == "queue_velocity"
        assert rec["inputs"]["queue_velocity"] == pytest.approx(45.0)

    def test_queue_depth_per_replica(self, telemetry):
        g = FakeGroup(2)
        eng = _engine(g, depth_high=8.0, queue_velocity=1e9)
        eng.tick(_sig(0.0, depth=20))  # 10/replica > 8
        rec = eng.tick(_sig(0.1, depth=20))
        assert rec["action"] == "scale_up"
        assert rec["rule"] == "queue_depth"
        # 2 replicas at depth 14 = 7/replica: under threshold
        eng2 = _engine(FakeGroup(2), depth_high=8.0,
                       queue_velocity=1e9)
        eng2.tick(_sig(0.0, depth=14))
        rec = eng2.tick(_sig(0.1, depth=14))
        assert rec["action"] is None

    def test_idle_scales_down_after_window(self, telemetry):
        g = FakeGroup(3)
        eng = _engine(g, down_ticks=3)
        for i in range(2):
            rec = eng.tick(_sig(i * 0.1, depth=0))
            assert rec["action"] is None
            assert rec["reason"] == "hysteresis_pending"
        rec = eng.tick(_sig(0.2, depth=0))
        assert rec["action"] == "scale_down"
        assert rec["rule"] == "idle"
        assert g.alive() == 2

    def test_replace_fires_on_down_health(self, telemetry):
        g = FakeGroup(2)
        eng = _engine(g)
        eng.tick(_sig(0.0, health={"r0": "down", "r1": "up"}))
        rec = eng.tick(_sig(0.1, health={"r0": "down", "r1": "up"}))
        assert rec["action"] == "replace"
        assert rec["rule"] == "replica_down"
        assert rec["replica"] == "r0"
        assert ("restart", "r0") in g.calls

    def test_replace_wins_priority_over_scale_up(self, telemetry):
        g = FakeGroup(2)
        eng = _engine(g)
        s = _sig(0.0, burn=5.0, health={"r1": "stale"})
        eng.tick(s)
        rec = eng.tick(_sig(0.1, burn=5.0,
                            health={"r1": "stale"}))
        assert rec["action"] == "replace"
        assert rec["replica"] == "r1"

    def test_replace_never_resurrects_a_retired_rid(self, telemetry):
        g = FakeGroup(3)
        eng = _engine(g, down_ticks=1)
        rec = eng.tick(_sig(0.0, depth=0))
        assert rec["action"] == "scale_down"
        retired = rec["replica"]
        assert retired in eng.snapshot()["retired"]
        # the drained replica's heartbeat goes stale as it dies — the
        # replace rule must not flap it back up (depth keeps the idle
        # rule quiet so NO rule fires here)
        rec = eng.tick(_sig(5.0, depth=5, health={retired: "down"}))
        assert rec["action"] is None and rec["rule"] is None
        assert ("restart", retired) not in g.calls


# ---------------------------------------------------------------------------
# hysteresis / cooldown / bounds / victim
# ---------------------------------------------------------------------------

class TestStability:
    def test_hysteresis_pending_carries_streak(self, telemetry):
        eng = _engine(FakeGroup(1), up_ticks=3)
        rec = eng.tick(_sig(1.0, burn=3.0))
        assert rec["reason"] == "hysteresis_pending"
        assert rec["streak"] == 1 and rec["pending_s"] == 0.0
        rec = eng.tick(_sig(1.5, burn=3.0))
        assert rec["streak"] == 2
        assert rec["pending_s"] == pytest.approx(0.5)

    def test_streak_resets_when_winner_changes(self, telemetry):
        eng = _engine(FakeGroup(1), up_ticks=2)
        eng.tick(_sig(0.0, burn=3.0))          # scale_up streak 1
        eng.tick(_sig(0.1))                    # idle: streak resets
        rec = eng.tick(_sig(0.2, burn=3.0))    # back to streak 1
        assert rec["reason"] == "hysteresis_pending"
        assert rec["streak"] == 1

    def test_cooldown_after_action(self, telemetry):
        g = FakeGroup(1)
        eng = _engine(g, cooldown_s=2.0)
        eng.tick(_sig(0.0, burn=3.0))
        assert eng.tick(_sig(0.1, burn=3.0))["action"] == "scale_up"
        # rule still fires + full hysteresis, but inside the window
        eng.tick(_sig(0.2, burn=3.0))
        rec = eng.tick(_sig(0.3, burn=3.0))
        assert rec["action"] is None
        assert rec["reason"] == "cooldown"
        assert g.alive() == 2
        # past the window (streak already built through the cooldown
        # ticks) it acts again
        assert eng.tick(_sig(2.2, burn=3.0))["action"] == "scale_up"
        assert g.alive() == 3

    def test_max_bound_is_typed(self, telemetry):
        eng = _engine(FakeGroup(4), max_replicas=4)
        eng.tick(_sig(0.0, burn=3.0))
        rec = eng.tick(_sig(0.1, burn=3.0))
        assert rec["action"] is None
        assert rec["reason"] == "at_bound"

    def test_min_bound_is_typed(self, telemetry):
        eng = _engine(FakeGroup(1), min_replicas=1, down_ticks=2)
        eng.tick(_sig(0.0))
        rec = eng.tick(_sig(0.1))
        assert rec["action"] is None
        assert rec["reason"] == "at_bound"

    def test_victim_is_least_loaded_ties_to_newest(self, telemetry):
        g = FakeGroup(3)
        eng = _engine(g, down_ticks=1)
        # r1 carries depth: victim is the least-loaded of r0/r2, and
        # the depth tie between them breaks to the NEWEST (r2)
        rec = eng.tick(_sig(0.0, depth=0.5,
                            per_replica={"r1": 0.5}))
        assert rec["action"] == "scale_down"
        assert rec["replica"] == "r2"
        assert g.rids == ["r0", "r1"]

    def test_flap_storm_yields_zero_actions(self, telemetry):
        g = FakeGroup(2)
        eng = _engine(g, up_ticks=2, down_ticks=100)
        for i in range(40):
            hot = i % 2 == 0
            eng.tick(_sig(i * 0.05,
                          burn=5.0 if hot else 0.0,
                          flaps=12 if hot else 0,
                          goodput=0.3 if hot else 1.0))
        snap = eng.snapshot()
        assert snap["actions"] == {}
        assert g.calls == []
        # every tick flips the winner (hot = scale_up, cold = idle
        # scale_down), so no streak ever builds: every single one of
        # the 40 decisions is a typed hysteresis_pending no-op
        assert set(snap["noops"]) <= set(scaler.NOOP_REASONS)
        assert snap["noops"]["hysteresis_pending"] == 40


# ---------------------------------------------------------------------------
# verb failures demote to typed no-ops
# ---------------------------------------------------------------------------

class TestVerbFailures:
    def test_restart_not_dead_yet_is_replace_pending(self, telemetry):
        g = FakeGroup(2, restart_exc=ValueError("r0 is not DEAD"))
        eng = _engine(g)
        eng.tick(_sig(0.0, health={"r0": "stale"}))
        rec = eng.tick(_sig(0.1, health={"r0": "stale"}))
        assert rec["action"] is None
        assert rec["reason"] == "replace_pending"
        assert "error" not in rec

    def test_spawn_blowup_is_spawn_failed(self, telemetry):
        g = FakeGroup(1, spawn_exc=RuntimeError("no slots"))
        eng = _engine(g)
        eng.tick(_sig(0.0, burn=3.0))
        rec = eng.tick(_sig(0.1, burn=3.0))
        assert rec["action"] is None
        assert rec["reason"] == "spawn_failed"
        assert "no slots" in rec["error"]

    def test_retire_blowup_is_retire_failed(self, telemetry):
        g = FakeGroup(2, retire_exc=RuntimeError("draining"))
        eng = _engine(g, down_ticks=1)
        rec = eng.tick(_sig(0.0))
        assert rec["action"] is None
        assert rec["reason"] == "retire_failed"


# ---------------------------------------------------------------------------
# decision records / snapshot / journal
# ---------------------------------------------------------------------------

class TestDecisionRecords:
    def test_record_carries_full_input_vector(self, telemetry):
        eng = _engine(FakeGroup(2))
        rec = eng.tick(_sig(1.0, burn=0.4, bvel=0.1, depth=3,
                            flaps=2, goodput=0.9))
        for k in ("t", "action", "rule", "reason", "replica",
                  "incident_id", "pending_s", "streak", "inputs"):
            assert k in rec
        inp = rec["inputs"]
        assert inp["burn_max"] == pytest.approx(0.4)
        assert inp["burn_velocity_max"] == pytest.approx(0.1)
        assert inp["queue_depth_total"] == 3
        assert inp["breaker_flaps_max"] == 2
        assert inp["goodput"] == pytest.approx(0.9)
        assert inp["alive"] == 2
        assert (inp["min"], inp["max"]) == (1, 4)

    def test_incident_affinity_links_the_open_incident(self,
                                                       telemetry):
        eng = _engine(FakeGroup(1))
        incs = [{"rule": "slo_burn", "id": "inc-7-1"},
                {"rule": "goodput_collapse", "id": "inc-7-2"}]
        eng.tick(_sig(0.0, burn=3.0, incidents=incs))
        rec = eng.tick(_sig(0.1, burn=3.0, incidents=incs))
        assert rec["action"] == "scale_up"
        assert rec["incident_id"] == "inc-7-1"

    def test_decision_events_reach_obs(self, telemetry):
        eng = _engine(FakeGroup(1))
        eng.tick(_sig(0.0, burn=3.0))
        eng.tick(_sig(0.1, burn=3.0))
        evs = [e for e in obs.events() if e["op"] == "scaler"]
        assert [e["decision"] for e in evs] == ["noop", "scale_up"]
        assert evs[0]["reason"] == "hysteresis_pending"
        assert evs[1]["rule"] == "slo_burn"
        assert "inputs" in evs[1]
        assert obs.counter_value("scaler_action", action="scale_up",
                                 rule="slo_burn") == 1

    def test_snapshot_shape_and_bounded_tail(self, telemetry):
        eng = _engine(FakeGroup(1))
        for i in range(scaler.MAX_DECISIONS + 10):
            eng.tick(_sig(i * 0.1))
        snap = eng.snapshot()
        assert snap["schema"] == scaler.SCHEMA
        assert snap["armed"] is True and snap["running"] is False
        assert snap["ticks"] == scaler.MAX_DECISIONS + 10
        assert len(snap["decisions"]) == scaler.MAX_DECISIONS
        assert snap["replicas"] == {"min": 1, "max": 4, "alive": 1}
        assert snap["config"]["up_ticks"] == 2
        assert snap["noops"]["at_bound"] > 0

    def test_decisions_are_journal_durable(self, telemetry, tmp_path):
        obs_journal._reset_for_tests()
        obs.configure(journal_dir=str(tmp_path))
        try:
            eng = _engine(FakeGroup(1))
            eng.tick(_sig(0.0, burn=3.0))
            eng.tick(_sig(0.1, burn=3.0))
            records, skipped = obs_journal.read_pack(str(tmp_path))
        finally:
            obs.configure(journal_dir="")
            obs_journal._reset_for_tests()
        assert skipped == 0
        sc = [r for r in records if r["op"] == "scaler"]
        assert [r["decision"] for r in sc] == ["noop", "scale_up"]
        assert sc[1]["data"]["rule"] == "slo_burn"
        assert sc[1]["data"]["inputs"]["burn_max"] \
            == pytest.approx(3.0)


# ---------------------------------------------------------------------------
# env knobs / registry / lifecycle
# ---------------------------------------------------------------------------

class TestKnobsAndRegistry:
    def test_env_parsing_falls_back_on_garbage(self, monkeypatch):
        monkeypatch.setenv(scaler.BURN_ENV, "not-a-float")
        monkeypatch.setenv(scaler.MAX_ENV, "-3")
        monkeypatch.setenv(scaler.UP_TICKS_ENV, "5")
        eng = scaler.ScalerEngine(FakeGroup(1))
        assert eng.burn == scaler.DEFAULT_BURN
        assert eng.max_replicas == scaler.DEFAULT_MAX
        assert eng.up_ticks == 5

    def test_armed_by_env_truthy_forms(self, monkeypatch):
        for raw, want in [("1", True), ("true", True), ("YES", True),
                          (" on ", True), ("0", False), ("", False),
                          ("off", False)]:
            monkeypatch.setenv(scaler.ARM_ENV, raw)
            assert scaler.armed_by_env() is want
        monkeypatch.delenv(scaler.ARM_ENV)
        assert scaler.armed_by_env() is False

    def test_disarmed_shell_is_schema_stamped(self, telemetry):
        snap = scaler.snapshot()
        assert snap["schema"] == scaler.SCHEMA
        assert snap["armed"] is False
        assert snap["decisions"] == []
        assert scaler.summary()["armed"] is False
        assert scaler.armed() is False
        assert obs.scaler_snapshot()["armed"] is False

    def test_registry_serves_the_registered_engine(self, telemetry):
        eng = _engine(FakeGroup(1))
        scaler._register(eng)
        eng.tick(_sig(0.0, burn=3.0))
        assert scaler.armed() is True
        assert scaler.engine() is eng
        assert scaler.snapshot()["ticks"] == 1
        assert obs.scaler_snapshot()["ticks"] == 1
        assert obs.snapshot()["scaler"]["ticks"] == 1
        scaler._unregister(eng)
        assert scaler.engine() is None

    def test_start_stop_thread_lifecycle(self, telemetry):
        eng = _engine(FakeGroup(1))
        eng.start(interval_s=30.0)   # ticks on its own clock; we only
        try:                         # probe the thread lifecycle here
            assert eng.snapshot()["running"] is True
            names = [t.name for t in threading.enumerate()]
            assert "veles-serve-scaler" in names
            eng.start(interval_s=30.0)   # idempotent
        finally:
            eng.stop()
        assert eng.snapshot()["running"] is False
        names = [t.name for t in threading.enumerate()]
        assert "veles-serve-scaler" not in names

    def test_group_arms_and_disarms_the_scaler(self, telemetry):
        """ReplicaGroup(scaler=True) registers the engine for the
        /scaler route and the stats surface; stop() disarms it."""
        with cluster.ReplicaGroup(
                1, max_wait_ms=2.0, obs_port=-1, scaler=True,
                scaler_tick_ms=60_000.0,
                scaler_kwargs=dict(min_replicas=1, max_replicas=2),
        ) as group:
            assert scaler.armed() is True
            assert scaler.engine().group is group
            st = group.stats()["scaler"]
            assert st["armed"] is True and st["running"] is True
        assert scaler.armed() is False
        assert scaler.snapshot()["armed"] is False

    def test_group_default_is_disarmed(self, telemetry, monkeypatch):
        monkeypatch.delenv(scaler.ARM_ENV, raising=False)
        with cluster.ReplicaGroup(1, max_wait_ms=2.0,
                                  obs_port=-1) as group:
            assert scaler.armed() is False
            assert group.stats()["scaler"] is None
