"""IIR filtering: Butterworth design, biquad cascades, zero-phase filtering.

NEW capability beyond the reference: ``/root/reference`` stops at FIR
filtering (``src/convolve.c`` — every filter is a finite kernel).  The
classic infinite-impulse-response stack — recursive filters designed
from analog prototypes, run as second-order-section (SOS) cascades, and
applied forward-backward for zero phase — is the other half of a
signal-processing library, and it is the canonical "can't vectorize"
workload: each output sample depends on the previous one.

TPU-first design — the recurrence is NOT sequential here:

* **Parallel linear recurrence.** An order-p IIR section is the affine
  state recurrence ``s[t] = A s[t-1] + u[t]`` (companion matrix ``A``,
  input drive ``u[t]`` = the FIR half, computed as a plain convolution).
  Affine maps compose associatively, so the whole scan runs as
  ``jax.lax.associative_scan`` over ``(A, u)`` pairs — O(log n) depth,
  every step a batched 2x2 (or pxp) matmul that rides the VPU/MXU,
  instead of an n-step ``lax.scan`` serial chain.  This is the Blelloch
  formulation of recurrence parallelization.
* **The FIR drive is a convolution**: ``u[t] = b0 x[t] + b1 x[t-1] +
  b2 x[t-2]`` — shifted adds fused by XLA, no gather.
* **Design is host-side float64 NumPy**: pole placement, bilinear
  transform, and SOS pairing are a few dozen scalars computed once at
  trace time — exactly like the wavelet coefficient tables
  (``ops/wavelet_coeffs.py``), they never belong on the device.

Conventions match scipy.signal (``butter(..., output='sos')`` /
``sosfilt`` / ``sosfiltfilt`` / ``lfilter``) so users can port
pipelines; the test-suite pins parity against scipy directly.

Oracle twins (``*_na``) run the textbook sequential recurrence in
float64 NumPy — deliberately a different algorithm from the scan, so
cross-validation is meaningful (the reference's two-implementations
discipline, ``/root/reference/tests/matrix.cc:94-98``).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from veles.simd_tpu import obs
from veles.simd_tpu.utils.config import resolve_simd
from veles.simd_tpu.runtime import precision as prx

__all__ = [
    "butterworth", "cheby1", "cheby2", "bessel", "ellip", "iirnotch",
    "iirpeak", "buttord", "cheb1ord", "cheb2ord", "ellipord",
    "tf2zpk", "zpk2tf", "zpk2sos", "tf2sos", "sos2tf", "group_delay",
    "filtfilt", "sosfilt",
    "sosfilt_na",
    "sosfiltfilt", "sosfiltfilt_na", "lfilter", "lfilter_na",
    "sos_frequency_response", "frequency_response", "sosfilt_zi",
    "lfilter_zi", "StreamingSosfilt", "sos_stream_step",
    "sos_stream_step_na",
]


# ---------------------------------------------------------------------------
# design (host-side float64)
# ---------------------------------------------------------------------------


def _butter_analog_poles(order: int) -> np.ndarray:
    """Left-half-plane poles of the analog Butterworth prototype
    (|p| = 1, maximally flat)."""
    k = np.arange(1, order + 1)
    theta = np.pi * (2 * k - 1) / (2 * order) + np.pi / 2
    return np.exp(1j * theta)


def _bilinear_zpk(z, p, k, fs: float):
    """Bilinear s->z transform of a zero/pole/gain analog filter."""
    z, p = np.asarray(z, complex), np.asarray(p, complex)
    fs2 = 2.0 * fs
    zd = (fs2 + z) / (fs2 - z)
    pd = (fs2 + p) / (fs2 - p)
    # zeros at analog infinity land at z = -1
    zd = np.append(zd, -np.ones(len(p) - len(z)))
    kd = k * np.real(np.prod(fs2 - z) / np.prod(fs2 - p))
    return zd, pd, kd


def _zpk_to_sos(z, p, k) -> np.ndarray:
    """Pair conjugate roots into second-order sections [n_sections, 6].

    Simple pairing (conjugate pairs together, leftover reals paired in
    order, overall gain on the first section): section ordering affects
    fixed-point scaling, not the float transfer function the tests pin.
    """
    def _pair(roots):
        roots = sorted(np.asarray(roots, complex),
                       key=lambda r: (abs(r.imag) < 1e-12, r.real,
                                      abs(r.imag)))
        used = [False] * len(roots)
        pairs = []
        for i, r in enumerate(roots):
            if used[i]:
                continue
            used[i] = True
            if abs(r.imag) > 1e-12:
                # find its conjugate
                for j in range(i + 1, len(roots)):
                    if not used[j] and abs(roots[j] - r.conjugate()) < 1e-8:
                        used[j] = True
                        pairs.append((r, r.conjugate()))
                        break
                else:
                    raise ValueError(f"unpaired complex root {r}")
            else:
                # real root: pair with the next unused real (or alone)
                mate = None
                for j in range(i + 1, len(roots)):
                    if not used[j] and abs(roots[j].imag) < 1e-12:
                        used[j] = True
                        mate = roots[j]
                        break
                pairs.append((r, mate))
        return pairs

    # degree-match with roots at the ORIGIN (scipy's convention: an
    # origin zero/pole is b or a = [1, 0], a pure coefficient shift the
    # shared roll below cancels) — this makes FIR inputs (no poles) and
    # fewer-zeros-than-poles inputs exact, with no spurious delay
    z = np.concatenate([np.asarray(z, complex),
                        np.zeros(max(0, len(p) - len(z)), complex)])
    p = np.concatenate([np.asarray(p, complex),
                        np.zeros(max(0, len(z) - len(p)), complex)])
    zp, pp = _pair(z), _pair(p)
    sos = []
    for (z1, z2), (p1, p2) in zip(zp, pp):
        def _poly(r1, r2):
            # degree matching guarantees r1 exists for every pair
            assert r1 is not None
            if r2 is None:
                return np.array([0.0, 1.0, -r1.real])
            c = np.poly([r1, r2])
            return np.real(c)
        b = _poly(z1, z2)
        a = _poly(p1, p2)
        # normalize to a leading 1 in a (a[0] may be 0 for first-order)
        nz = np.nonzero(np.abs(a) > 0)[0][0]
        sos.append(np.concatenate([np.roll(b, -nz), np.roll(a, -nz)]))
    sos = np.asarray(sos, np.float64)
    sos[0, :3] *= k
    return sos


def butterworth(order: int, cutoff, btype: str = "lowpass") -> np.ndarray:
    """Digital Butterworth filter as second-order sections.

    ``cutoff`` is the -3 dB edge as a fraction of the Nyquist frequency
    (scipy's ``Wn``): a scalar for ``lowpass``/``highpass``, a
    ``(low, high)`` pair for ``bandpass``/``bandstop``.  Returns
    ``[n_sections, 6]`` float64 rows ``[b0, b1, b2, 1, a1, a2]`` for
    :func:`sosfilt`.  Matches ``scipy.signal.butter(..., output='sos')``
    up to section pairing (same transfer function).
    """
    p = _butter_analog_poles(_check_order(order))
    k = float(np.real(np.prod(-p)))  # unit DC gain prototype (= 1 here)
    return _prototype_to_digital_sos(np.array([], complex), p, k, cutoff,
                                     btype)


def _check_order(order) -> int:
    order = int(order)
    if order < 1:
        raise ValueError("order must be >= 1")
    return order


def _prototype_to_digital_sos(z, p, k, cutoff, btype) -> np.ndarray:
    """Analog lowpass prototype (zpk, edge at 1 rad/s) -> digital SOS:
    pre-warp, general lp2lp/hp/bp/bs zpk transform (finite zeros
    supported — Chebyshev II needs them), bilinear, pair."""
    btype = btype.lower()
    fs = 2.0  # Nyquist = 1, scipy's normalized convention
    z = np.asarray(z, complex)
    p = np.asarray(p, complex)
    degree = len(p) - len(z)
    if degree < 0:
        raise ValueError("prototype has more zeros than poles")
    if btype in ("lowpass", "highpass"):
        wn = float(np.squeeze(cutoff))
        if not 0.0 < wn < 1.0:
            raise ValueError(f"cutoff {wn} must be in (0, 1)")
        wo = 2 * fs * math.tan(math.pi * wn / fs)
        if btype == "lowpass":      # s -> s / wo
            z, p = z * wo, p * wo
            k = k * wo ** degree
        else:                        # lp2hp: s -> wo / s
            zp, pp = z, p            # (prod of an empty array is 1.0)
            z = np.append(wo / zp, np.zeros(degree))
            p = wo / pp
            k = k * np.real(np.prod(-zp) / np.prod(-pp))
    elif btype in ("bandpass", "bandstop"):
        lo, hi = (float(c) for c in np.ravel(cutoff))
        if not 0.0 < lo < hi < 1.0:
            raise ValueError(f"band edges ({lo}, {hi}) must satisfy "
                             "0 < low < high < 1")
        w1 = 2 * fs * math.tan(math.pi * lo / fs)
        w2 = 2 * fs * math.tan(math.pi * hi / fs)
        bw, wo = w2 - w1, math.sqrt(w1 * w2)

        def _split(r, scale_first):
            rs = (r * bw / 2) if scale_first else ((bw / 2) / r)
            return np.concatenate([rs + np.sqrt(rs ** 2 - wo ** 2),
                                   rs - np.sqrt(rs ** 2 - wo ** 2)])

        if btype == "bandpass":     # s -> (s^2 + wo^2) / (bw s)
            z = np.append(_split(z, True), np.zeros(degree))
            p = _split(p, True)
            k = k * bw ** degree
        else:                        # lp2bs: s -> (bw s) / (s^2 + wo^2)
            zp, pp = z, p
            z = np.append(_split(zp, False),
                          np.concatenate([1j * wo * np.ones(degree),
                                          -1j * wo * np.ones(degree)]))
            p = _split(pp, False)
            k = k * np.real(np.prod(-zp) / np.prod(-pp))
    else:
        raise ValueError(f"unknown btype {btype!r}")
    zd, pd, kd = _bilinear_zpk(z, p, k, fs)
    return _zpk_to_sos(zd, pd, kd)


def bessel(order: int, cutoff, btype: str = "lowpass") -> np.ndarray:
    """Bessel/Thomson digital filter as SOS (scipy's ``bessel(...,
    norm='phase', output='sos')``): maximally-flat GROUP DELAY — the
    design for pulse shapes that must not ring.  ``cutoff`` marks the
    phase-normalized characteristic frequency (scipy's default norm),
    as a fraction of Nyquist.

    The analog prototype's poles are the roots of the reverse Bessel
    polynomial ``theta_n(s) = sum_k (2n-k)! / (2^(n-k) k! (n-k)!) s^k``
    scaled by ``a_0^(-1/n)`` (the phase normalization), all host-side
    float64.
    """
    order = _check_order(order)
    coeffs = [math.factorial(2 * order - k)
              / (2 ** (order - k) * math.factorial(k)
                 * math.factorial(order - k))
              for k in range(order + 1)]
    p = np.roots(coeffs[::-1]) / coeffs[0] ** (1.0 / order)
    k = float(np.real(np.prod(-p)))  # == 1 by the normalization
    return _prototype_to_digital_sos(np.array([], complex), p, k, cutoff,
                                     btype)


def cheby1(order: int, rp: float, cutoff,
           btype: str = "lowpass") -> np.ndarray:
    """Chebyshev type-I digital filter as second-order sections
    (scipy's ``cheby1(..., output='sos')``): equiripple passband
    (``rp`` dB of ripple), monotone stopband, sharper rolloff than
    Butterworth at the same order.  ``cutoff`` marks the END of the
    ripple band (scipy convention), as a fraction of Nyquist.
    """
    order = _check_order(order)
    rp = float(rp)
    if rp <= 0:
        raise ValueError("rp (passband ripple, dB) must be > 0")
    eps = math.sqrt(10.0 ** (rp / 10.0) - 1.0)
    mu = math.asinh(1.0 / eps) / order
    kk = np.arange(1, order + 1)
    theta = math.pi * (2 * kk - 1) / (2 * order)
    p = -math.sinh(mu) * np.sin(theta) + 1j * math.cosh(mu) * np.cos(theta)
    k = np.real(np.prod(-p))
    if order % 2 == 0:  # even orders dip: DC gain is -rp dB
        k /= math.sqrt(1.0 + eps ** 2)
    return _prototype_to_digital_sos(np.array([], complex), p, k, cutoff,
                                     btype)


def cheby2(order: int, rs: float, cutoff,
           btype: str = "lowpass") -> np.ndarray:
    """Chebyshev type-II (inverse Chebyshev) digital filter as SOS
    (scipy's ``cheby2(..., output='sos')``): monotone passband,
    equiripple stopband ``rs`` dB down.  ``cutoff`` marks the START of
    the stopband (scipy convention), as a fraction of Nyquist.
    """
    order = _check_order(order)
    rs = float(rs)
    if rs <= 0:
        raise ValueError("rs (stopband attenuation, dB) must be > 0")
    eps = 1.0 / math.sqrt(10.0 ** (rs / 10.0) - 1.0)
    mu = math.asinh(1.0 / eps) / order
    kk = np.arange(1, order + 1)
    theta = math.pi * (2 * kk - 1) / (2 * order)
    # type-I poles, then invert to move the ripple to the stopband
    p1 = -math.sinh(mu) * np.sin(theta) \
        + 1j * math.cosh(mu) * np.cos(theta)
    p = 1.0 / p1
    # zeros on the imaginary axis at the ripple frequencies (the odd
    # order's cos(pi/2) = 0 zero-at-infinity is dropped)
    ct = np.cos(theta)
    ct = ct[np.abs(ct) > 1e-12]
    z = 1j / ct
    k = np.real(np.prod(-p) / np.prod(-z))
    return _prototype_to_digital_sos(z, p, k, cutoff, btype)


# -- elliptic (Cauer) design machinery: complete elliptic integrals via
#    the AGM, Jacobi sn/cn/dn via the descending Landen/Gauss
#    transformation (Abramowitz & Stegun 16.4 / 16.12), and scalar
#    bisection for the two transcendental solves.  All host-side
#    float64, a few dozen scalars per design.


def _agm(a: float, b: float) -> float:
    # tolerance must sit above 1 ulp (2.2e-16 relative) or the loop
    # never exits; quadratic convergence makes the last step exact
    while abs(a - b) > 4e-16 * a:
        a, b = 0.5 * (a + b), math.sqrt(a * b)
    return a


def _ellipk(m: float) -> float:
    """Complete elliptic integral K(m) (PARAMETER m = modulus^2, scipy
    convention): pi / (2 agm(1, sqrt(1-m)))."""
    if not 0.0 <= m < 1.0:
        raise ValueError(f"parameter m={m} must be in [0, 1)")
    return math.pi / (2.0 * _agm(1.0, math.sqrt(1.0 - m)))


def _ellipkp(m: float) -> float:
    """Complementary integral K'(m) = K(1-m), computed from ``m``
    directly so tiny moduli don't round 1-m to 1.0."""
    if not 0.0 < m <= 1.0:
        raise ValueError(f"parameter m={m} must be in (0, 1]")
    return math.pi / (2.0 * _agm(1.0, math.sqrt(m)))


def _ellipj(u, m: float, mc: float | None = None):
    """Jacobi elliptic (sn, cn, dn)(u | m), vectorized over ``u``.

    Descending Landen ladder: run the AGM down to the circular case,
    evaluate sin/cos there, then climb back up with the Gauss ascending
    recurrence (A&S 16.12.2-4).  ``mc`` optionally supplies the
    complementary parameter 1-m exactly (the inverse-sc solve needs
    parameter 1-m1 with m1 tiny, where forming 1-m loses it).
    """
    u = np.asarray(u, np.float64)
    if mc is None:
        mc = 1.0 - m
    if m == 0.0:
        return np.sin(u), np.cos(u), np.ones_like(u)
    if mc <= 0.0:
        sech = 1.0 / np.cosh(u)
        return np.tanh(u), sech, sech
    # AGM ladder a_{k+1} = (a_k+b_k)/2, c_{k+1} = (a_k-b_k)/2; keep the
    # ratios c_k/a_k for k = 1..N that the descent needs
    a, b = 1.0, math.sqrt(mc)
    ratios = []
    while True:
        a_next, b_next = 0.5 * (a + b), math.sqrt(a * b)
        c_next = 0.5 * (a - b)
        ratios.append(c_next / a_next)
        a, b = a_next, b_next
        if c_next <= 1e-15 * a_next:
            break
    phi = (2.0 ** len(ratios)) * a * u
    for ra in reversed(ratios):
        # A&S 16.12.2: sin(2 phi_{k-1} - phi_k) = (c_k/a_k) sin(phi_k)
        phi = 0.5 * (phi + np.arcsin(
            np.clip(ra * np.sin(phi), -1.0, 1.0)))
    sn = np.sin(phi)
    cn = np.cos(phi)
    dn = np.sqrt(np.maximum(1.0 - (1.0 - mc) * sn * sn, 0.0))
    return sn, cn, dn


def _bisect(f, lo: float, hi: float, iters: int = 200) -> float:
    """Plain bisection for a monotone-bracketed root (float64-exact
    after ~60 halvings; extra iterations are free at design time)."""
    flo = f(lo)
    for _ in range(iters):
        mid = 0.5 * (lo + hi)
        if mid == lo or mid == hi:
            break
        if (f(mid) > 0) == (flo > 0):
            lo = mid
        else:
            hi = mid
    return 0.5 * (lo + hi)


def _ellip_analog_zpk(order: int, rp: float, rs: float):
    """Analog elliptic lowpass prototype (passband edge 1 rad/s):
    equiripple in BOTH bands.  The construction scipy's ``ellipap``
    uses — degree equation for the transition modulus, Jacobi-function
    pole/zero placement on the elliptic grid."""
    eps_sq = 10.0 ** (0.1 * rp) - 1.0
    eps = math.sqrt(eps_sq)
    # ripple modulus m1 = (eps_p / eps_s)^2
    m1 = eps_sq / (10.0 ** (0.1 * rs) - 1.0)
    if m1 <= 0.0 or m1 >= 1.0:
        raise ValueError("need rs > rp (stopband deeper than passband "
                         "ripple)")
    k_m1 = _ellipk(m1)
    kp_m1 = _ellipkp(m1)
    krat = order * k_m1 / kp_m1
    # degree equation: find m with K(m)/K'(m) = krat (monotone in m)
    m = _bisect(
        lambda mm: _ellipk(mm) / _ellipkp(mm) - krat,
        1e-300, 1.0 - 1e-16)
    capk = _ellipk(m)
    j = np.arange(1 - order % 2, order, 2, dtype=np.float64)
    s, c, d = _ellipj(j * capk / order, m)
    # zeros at +-j / (sqrt(m) sn(j K / N)); drop the odd order's
    # sn(0) = 0 zero-at-infinity
    snz = s[np.abs(s) > 1e-14]
    z = 1j / (math.sqrt(m) * snz)
    z = np.concatenate([z, np.conj(z)])
    # v0 from the inverse sc with COMPLEMENTARY modulus (scipy's
    # _arc_jac_sc1, from sn(i z | m1) = i sc(z | 1-m1)):
    # solve sc(r | 1-m1) = 1/eps, r in (0, K(1-m1)) where sc is
    # monotone 0 -> inf
    r = _bisect(
        lambda u: (lambda sn_, cn_, _:
                   sn_ / cn_ - 1.0 / eps)(
                       *_ellipj(u, 1.0 - m1, mc=m1)),
        1e-300, kp_m1 * (1.0 - 1e-14))
    v0 = capk * r / (order * k_m1)
    sv, cv, dv = _ellipj(v0, 1.0 - m)
    p = -(c * d * sv * cv + 1j * s * dv) / (1.0 - (d * sv) ** 2)
    if order % 2:
        # the j=0 pole is real; the rest pair with their conjugates
        real_mask = np.abs(p.imag) <= 1e-14 * np.abs(p)
        p = np.concatenate([p, np.conj(p[~real_mask])])
    else:
        p = np.concatenate([p, np.conj(p)])
    k = np.real(np.prod(-p) / np.prod(-z))
    if order % 2 == 0:
        k /= math.sqrt(1.0 + eps_sq)
    return z, p, float(k)


def ellip(order: int, rp: float, rs: float, cutoff,
          btype: str = "lowpass") -> np.ndarray:
    """Elliptic (Cauer) digital filter as second-order sections
    (scipy's ``ellip(..., output='sos')``): equiripple in BOTH bands —
    ``rp`` dB of passband ripple, stopband at least ``rs`` dB down —
    the steepest possible rolloff for a given order.  ``cutoff`` marks
    the end of the passband ripple (scipy convention), as a fraction
    of Nyquist.
    """
    order = _check_order(order)
    rp, rs = float(rp), float(rs)
    if rp <= 0:
        raise ValueError("rp (passband ripple, dB) must be > 0")
    if rs <= rp:
        raise ValueError("rs (stopband attenuation, dB) must exceed rp")
    if order == 1:
        # degenerate: no finite zeros; scipy reduces to Chebyshev I
        return cheby1(1, rp, cutoff, btype)
    z, p, k = _ellip_analog_zpk(order, rp, rs)
    return _prototype_to_digital_sos(z, p, k, cutoff, btype)


def _notch_peak_sos(w0: float, Q: float, peak: bool) -> np.ndarray:
    """Single-biquad notch/peak at ``w0`` (fraction of Nyquist) with
    quality factor ``Q`` (scipy ``iirnotch``/``iirpeak``): -3 dB
    bandwidth ``w0/Q``, unit gain away from (notch) or at (peak) the
    center frequency."""
    w0 = float(w0)
    Q = float(Q)
    if not 0.0 < w0 < 1.0:
        raise ValueError(f"w0 {w0} must be in (0, 1) (Nyquist = 1)")
    if Q <= 0:
        raise ValueError("Q must be > 0")
    wr = w0 * math.pi
    beta = math.tan(w0 * math.pi / (2.0 * Q))  # GB = 1/sqrt(2)
    gain = 1.0 / (1.0 + beta)
    if peak:
        b = (1.0 - gain) * np.array([1.0, 0.0, -1.0])
    else:
        b = gain * np.array([1.0, -2.0 * math.cos(wr), 1.0])
    a1 = -2.0 * gain * math.cos(wr)
    a2 = 2.0 * gain - 1.0
    return np.array([[b[0], b[1], b[2], 1.0, a1, a2]], np.float64)


# -- representation conversions (scipy's tf2zpk/zpk2tf/tf2sos/sos2tf/
#    zpk2sos family + group_delay): the plumbing a user porting a
#    scipy.signal pipeline needs to move between the ba / zpk / sos
#    forms this module's designers and runners use.  Host-side float64.


def tf2zpk(b, a):
    """Transfer-function numerator/denominator to (zeros, poles, gain)
    — scipy's ``tf2zpk``: leading coefficients normalized out into the
    gain, roots via the companion eigenvalues (``np.roots``)."""
    b = np.atleast_1d(np.asarray(b, np.float64))
    a = np.atleast_1d(np.asarray(a, np.float64))
    b, a = _normalize_ba(b, a)
    b = np.trim_zeros(b, "f")   # leading zeros shift degree, like scipy
    p = np.roots(a) if len(a) > 1 else np.array([], complex)
    if len(b) == 0:
        return np.array([], complex), p, 0.0
    k = b[0]
    z = np.roots(b / k) if len(b) > 1 else np.array([], complex)
    return z, p, float(k)


def zpk2tf(z, p, k):
    """(zeros, poles, gain) to ``(b, a)`` polynomial coefficients
    (scipy's ``zpk2tf``): real outputs when roots pair conjugately."""
    b = float(k) * np.poly(np.asarray(z, complex))
    a = np.poly(np.asarray(p, complex))
    if np.allclose(b.imag, 0, atol=1e-12):
        b = b.real
    if np.allclose(a.imag, 0, atol=1e-12):
        a = a.real
    return np.atleast_1d(b), np.atleast_1d(a)


def zpk2sos(z, p, k) -> np.ndarray:
    """(zeros, poles, gain) to ``[n_sections, 6]`` second-order
    sections for :func:`sosfilt`.  Same transfer function as scipy's
    ``zpk2sos`` up to section pairing/ordering (this module pairs
    conjugates simply; scipy's 'nearest' pairing optimizes fixed-point
    scaling, which float execution does not need — the frequency-
    response tests pin the equivalence)."""
    return _zpk_to_sos(np.asarray(z, complex), np.asarray(p, complex),
                       float(k))


def tf2sos(b, a) -> np.ndarray:
    """``(b, a)`` to second-order sections (via zpk)."""
    return zpk2sos(*tf2zpk(b, a))


def sos2tf(sos):
    """Second-order sections to a single ``(b, a)`` pair (scipy's
    ``sos2tf``): polynomial products of the section numerators and
    denominators."""
    sos = _check_sos(sos)
    b = np.array([1.0])
    a = np.array([1.0])
    for row in sos:
        b = np.convolve(b, row[:3])
        a = np.convolve(a, row[3:])
    return b, a


def group_delay(system, n_points: int = 512):
    """Group delay ``-d(phase)/d(omega)`` of a digital filter in
    samples (scipy's ``group_delay``): ``system`` is a ``(b, a)``
    pair.  Returns ``(w, gd)`` on the same ``linspace(0, 1, n,
    endpoint=False)`` Nyquist-fraction axis as
    :func:`sos_frequency_response` (also scipy's default grid scaled
    by pi), so the two overlay point-for-point.

    Uses the Fourier-differentiation identity on ``c = b * reversed(a)``:
    gd = Re[(sum k c_k z^-k)/(sum c_k z^-k)] - (len(a) - 1), which
    avoids numerical phase unwrapping entirely.  At frequencies where
    the response is singular (a zero ON the unit circle at a grid
    point) the group delay is undefined — set to 0 with a warning,
    matching scipy.
    """
    b, a = system
    b = np.atleast_1d(np.asarray(b, np.float64))
    a = np.atleast_1d(np.asarray(a, np.float64))
    c = np.convolve(b, a[::-1])
    cr = c * np.arange(len(c))
    w = np.linspace(0.0, 1.0, int(n_points), endpoint=False)
    zm = np.exp(-1j * np.pi * w)
    num = np.polyval(cr[::-1], zm)
    den = np.polyval(c[::-1], zm)
    singular = np.abs(den) < 10 * np.finfo(np.float64).eps * max(
        1.0, float(np.sum(np.abs(c))))
    if np.any(singular):
        import warnings

        warnings.warn("group_delay is singular at some frequencies "
                      "(response zero on the unit circle); setting "
                      "those points to 0", RuntimeWarning,
                      stacklevel=2)
    gd = np.real(num / np.where(singular, 1.0, den)) - (len(a) - 1)
    gd[singular] = 0.0
    return w, gd


# -- order estimation (scipy's buttord/cheb1ord/cheb2ord/ellipord):
#    the "how many poles do I need" front door of filter design.
#    Host-side float64; digital band edges as Nyquist fractions,
#    pre-warped through the bilinear transform exactly as the design
#    functions themselves do.


def _golden_min(f, lo: float, hi: float, iters: int = 120) -> float:
    """Golden-section minimum of a unimodal f on [lo, hi] (the
    bandstop passband-edge optimization; 120 iterations shrink the
    bracket below float64 resolution)."""
    inv_phi = (math.sqrt(5.0) - 1.0) / 2.0
    a, b = lo, hi
    c = b - inv_phi * (b - a)
    d = a + inv_phi * (b - a)
    fc, fd = f(c), f(d)
    for _ in range(iters):
        if fc < fd:
            b, d, fd = d, c, fc
            c = b - inv_phi * (b - a)
            fc = f(c)
        else:
            a, c, fc = c, d, fd
            d = a + inv_phi * (b - a)
            fd = f(d)
        if b - a < 1e-14 * (abs(a) + abs(b)):
            break
    return 0.5 * (a + b)


def _order_band_args(wp, ws, gpass, gstop):
    """Shared validation + pre-warp: returns ``(passb, stopb, ftype)``
    with scipy's type codes (1 low, 2 high, 3 bandstop, 4 bandpass)."""
    gpass, gstop = float(gpass), float(gstop)
    if not 0 < gpass < gstop:
        raise ValueError("need 0 < gpass < gstop (dB)")
    wp = np.atleast_1d(np.asarray(wp, np.float64))
    ws = np.atleast_1d(np.asarray(ws, np.float64))
    if wp.shape != ws.shape or wp.ndim != 1 or len(wp) not in (1, 2):
        raise ValueError("wp and ws must both be scalars or both be "
                         "(low, high) pairs")
    if np.any(wp <= 0) or np.any(wp >= 1) or np.any(ws <= 0) \
            or np.any(ws >= 1):
        raise ValueError("band edges must be in (0, 1) (Nyquist = 1)")
    ftype = 2 * (len(wp) - 1) + 1
    if wp[0] >= ws[0]:
        ftype += 1
    if len(wp) == 2:
        # the bands must nest strictly, or the selectivity formulas
        # (and the bandstop edge optimization's bracket) are meaningless
        if ftype == 3 and not (wp[0] < ws[0] < ws[1] < wp[1]):
            raise ValueError(
                f"bandstop needs wp[0] < ws[0] < ws[1] < wp[1], got "
                f"wp={wp.tolist()}, ws={ws.tolist()}")
        if ftype == 4 and not (ws[0] < wp[0] < wp[1] < ws[1]):
            raise ValueError(
                f"bandpass needs ws[0] < wp[0] < wp[1] < ws[1], got "
                f"wp={wp.tolist()}, ws={ws.tolist()}")
    passb = np.tan(np.pi * wp / 2.0)
    stopb = np.tan(np.pi * ws / 2.0)
    return passb, stopb, ftype, gpass, gstop


def _selectivity(passb, stopb, ftype):
    """Lowpass-prototype selectivity for fixed band edges."""
    if ftype == 1:
        nat = stopb / passb
    elif ftype == 2:
        nat = passb / stopb
    elif ftype == 3:
        nat = (stopb * (passb[0] - passb[1])
               / (stopb ** 2 - passb[0] * passb[1]))
    else:
        nat = ((stopb ** 2 - passb[0] * passb[1])
               / (stopb * (passb[0] - passb[1])))
    return float(np.min(np.abs(nat)))


def _order_measure(nat, gpass, gstop, kind):
    """The (real-valued) minimum order meeting (gpass, gstop) at
    selectivity ``nat`` for the given family."""
    gs = 10.0 ** (0.1 * gstop) - 1.0
    gp = 10.0 ** (0.1 * gpass) - 1.0
    if kind == "butter":
        return math.log10(gs / gp) / (2.0 * math.log10(nat))
    if kind == "cheby":
        return math.acosh(math.sqrt(gs / gp)) / math.acosh(nat)
    # elliptic: the degree equation N = [K/K'](1/nat^2) / [K/K'](m1)
    m0 = 1.0 / (nat * nat)
    m1 = gp / gs
    return (_ellipk(m0) * _ellipkp(m1)) / (_ellipkp(m0) * _ellipk(m1))


def _nat_freq(passb, stopb, ftype, gpass, gstop, kind):
    """Selectivity with scipy's bandstop refinement: for bandstop the
    passband edges may be moved INWARD (toward the stopband) without
    violating the spec wherever that lowers the required order — scipy
    optimizes each edge separately, and so does this.

    KNOWN DIVERGENCE: scipy's fminbound stops at xatol=1e-5 while this
    golden section converges to float64 resolution, so on rare
    bandstop specs sitting exactly at a ceil() boundary the tighter
    optimum yields an order ONE LOWER than scipy's (the design still
    meets the dB spec — the estimate is simply sharper).  Fixed-edge
    band types are bit-identical to scipy."""
    if ftype == 3:
        passb = passb.copy()

        def obj(w, ind):
            p = passb.copy()
            p[ind] = w
            return _order_measure(_selectivity(p, stopb, 3), gpass,
                                  gstop, kind)

        passb[0] = _golden_min(lambda w: obj(w, 0), passb[0],
                               stopb[0] - 1e-12)
        passb[1] = _golden_min(lambda w: obj(w, 1), stopb[1] + 1e-12,
                               passb[1])
    return _selectivity(passb, stopb, ftype), passb


def _wn_out(WN):
    wn = np.arctan(np.atleast_1d(WN)) * 2.0 / np.pi
    return float(wn[0]) if len(wn) == 1 else wn


def buttord(wp, ws, gpass: float, gstop: float):
    """Minimum Butterworth order (scipy's ``buttord``): the smallest
    order losing at most ``gpass`` dB in the passband and at least
    ``gstop`` dB in the stopband, plus the natural frequency ``wn``
    that EXACTLY meets the passband spec — feed ``(ord, wn)`` straight
    into :func:`butterworth`."""
    passb, stopb, ftype, gpass, gstop = _order_band_args(wp, ws, gpass,
                                                         gstop)
    nat, passb = _nat_freq(passb, stopb, ftype, gpass, gstop, "butter")
    order = int(math.ceil(_order_measure(nat, gpass, gstop, "butter")))
    gp = 10.0 ** (0.1 * gpass) - 1.0
    w0 = gp ** (-1.0 / (2.0 * order)) if order > 0 else 1.0
    if ftype == 1:
        WN = w0 * passb
    elif ftype == 2:
        WN = passb / w0
    elif ftype == 3:
        d = math.sqrt((passb[1] - passb[0]) ** 2
                      + 4 * w0 ** 2 * passb[0] * passb[1])
        WN = np.sort(np.abs([(passb[1] - passb[0] + d) / (2 * w0),
                             (passb[1] - passb[0] - d) / (2 * w0)]))
    else:
        w0_pair = np.array([-w0, w0])
        WN = np.sort(np.abs(
            -w0_pair * (passb[1] - passb[0]) / 2.0
            + np.sqrt(w0_pair ** 2 / 4.0 * (passb[1] - passb[0]) ** 2
                      + passb[0] * passb[1])))
    return order, _wn_out(WN)


def cheb1ord(wp, ws, gpass: float, gstop: float):
    """Minimum Chebyshev-I order (scipy's ``cheb1ord``); ``wn`` is the
    (bandstop-refined) passband edge, ready for :func:`cheby1`."""
    passb, stopb, ftype, gpass, gstop = _order_band_args(wp, ws, gpass,
                                                         gstop)
    nat, passb = _nat_freq(passb, stopb, ftype, gpass, gstop, "cheby")
    order = int(math.ceil(_order_measure(nat, gpass, gstop, "cheby")))
    return order, _wn_out(passb)


def cheb2ord(wp, ws, gpass: float, gstop: float):
    """Minimum Chebyshev-II order (scipy's ``cheb2ord``); ``wn`` is
    moved to the frequency where the response first reaches -gpass, so
    :func:`cheby2` at ``(ord, wn)`` meets the passband spec exactly."""
    passb, stopb, ftype, gpass, gstop = _order_band_args(wp, ws, gpass,
                                                         gstop)
    nat, passb = _nat_freq(passb, stopb, ftype, gpass, gstop, "cheby")
    v = _order_measure(nat, gpass, gstop, "cheby")
    order = int(math.ceil(v))
    gs = 10.0 ** (0.1 * gstop) - 1.0
    gp = 10.0 ** (0.1 * gpass) - 1.0
    new_freq = 1.0 / math.cosh(math.acosh(math.sqrt(gs / gp)) / order)
    if ftype == 1:
        WN = passb / new_freq
    elif ftype == 2:
        WN = passb * new_freq
    elif ftype == 3:
        n0 = (new_freq / 2.0 * (passb[0] - passb[1])
              + math.sqrt(new_freq ** 2 * (passb[1] - passb[0]) ** 2
                          / 4.0 + passb[1] * passb[0]))
        WN = np.array([n0, passb[0] * passb[1] / n0])
    else:
        n0 = ((passb[0] - passb[1]) / (2.0 * new_freq)
              + math.sqrt((passb[1] - passb[0]) ** 2
                          / (4.0 * new_freq ** 2)
                          + passb[1] * passb[0]))
        WN = np.array([n0, passb[0] * passb[1] / n0])
    return order, _wn_out(WN)


def ellipord(wp, ws, gpass: float, gstop: float):
    """Minimum elliptic order (scipy's ``ellipord``) via the degree
    equation on the AGM elliptic integrals; ``wn`` is the passband
    edge, ready for :func:`ellip`."""
    passb, stopb, ftype, gpass, gstop = _order_band_args(wp, ws, gpass,
                                                         gstop)
    nat, passb = _nat_freq(passb, stopb, ftype, gpass, gstop, "ellip")
    order = int(math.ceil(_order_measure(nat, gpass, gstop, "ellip")))
    return order, _wn_out(passb)


def iirnotch(w0: float, Q: float) -> np.ndarray:
    """Narrow band-reject biquad (scipy's ``iirnotch``) as a 1-section
    SOS: unit gain everywhere except a -3 dB-bandwidth ``w0/Q`` null at
    ``w0`` (fraction of Nyquist) — the classic mains-hum remover."""
    return _notch_peak_sos(w0, Q, peak=False)


def iirpeak(w0: float, Q: float) -> np.ndarray:
    """Narrow band-pass biquad (scipy's ``iirpeak``) as a 1-section
    SOS: unit gain only in the -3 dB band ``w0/Q`` around ``w0``."""
    return _notch_peak_sos(w0, Q, peak=True)


def _check_sos(sos) -> np.ndarray:
    sos = np.asarray(sos, np.float64)
    if sos.ndim != 2 or sos.shape[1] != 6:
        raise ValueError(f"sos must be [n_sections, 6], got {sos.shape}")
    if not np.allclose(sos[:, 3], 1.0):
        raise ValueError("sos rows must be normalized (a0 == 1)")
    return sos


def sos_frequency_response(sos, n_points: int = 512):
    """Complex response H(e^{jw}) on ``n_points`` frequencies in
    [0, pi) — host-side float64 design diagnostic (scipy's ``sosfreqz``).
    Returns ``(w, h)`` with ``w`` normalized to Nyquist = 1."""
    sos = _check_sos(sos)
    w = np.linspace(0.0, 1.0, n_points, endpoint=False)
    zinv = np.exp(-1j * np.pi * w)
    h = np.ones_like(zinv)
    for b0, b1, b2, _, a1, a2 in sos:
        h *= ((b0 + b1 * zinv + b2 * zinv ** 2)
              / (1.0 + a1 * zinv + a2 * zinv ** 2))
    return w, h


def frequency_response(b, a, n_points: int = 512):
    """Complex response of a transfer function ``b(z)/a(z)`` (host-side
    float64; scipy's ``freqz``).  ``w`` normalized to Nyquist = 1."""
    b = np.atleast_1d(np.asarray(b, np.float64))
    a = np.atleast_1d(np.asarray(a, np.float64))
    w = np.linspace(0.0, 1.0, n_points, endpoint=False)
    zinv = np.exp(-1j * np.pi * w)
    num = np.polyval(b[::-1], zinv)
    den = np.polyval(a[::-1], zinv)
    return w, num / den


def sosfilt_zi(sos) -> np.ndarray:
    """Steady-state section states for a unit step input
    (scipy's ``sosfilt_zi``, same direct-form-II-transposed ``(z1, z2)``
    convention): scale by the signal's edge value to start a filter
    "already settled" — used by :func:`sosfiltfilt`.

    DF2T recurrence: ``y[t] = b0 x[t] + z1[t-1]``,
    ``z1[t] = b1 x[t] - a1 y[t] + z2[t-1]``,
    ``z2[t] = b2 x[t] - a2 y[t]``.  For constant input the states solve
    in closed form; each cascaded section sees the previous section's DC
    output as its constant input.  Returns ``[n_sections, 2]``.
    """
    sos = _check_sos(sos)
    zi = np.zeros((len(sos), 2))
    scale = 1.0
    for i, (b0, b1, b2, _, a1, a2) in enumerate(sos):
        y_ss = scale * (b0 + b1 + b2) / (1.0 + a1 + a2)
        z2_ss = scale * b2 - a2 * y_ss
        z1_ss = scale * (b1 + b2) - (a1 + a2) * y_ss
        zi[i] = (z1_ss, z2_ss)
        scale = y_ss
    return zi


# ---------------------------------------------------------------------------
# runtime (associative-scan recurrence)
# ---------------------------------------------------------------------------


def _delay(x, k: int):
    """``x`` delayed ``k`` samples with zero fill (concat, NOT scatter:
    an ``x.at[k:].add`` drive feeding an ``.at[..., 0].set`` drive-vector
    build was observed to MISCOMPILE under jit on the CPU backend —
    wrong numerics from a fused scatter pair; concat/pad also lowers
    better on TPU, where scatter is the slow path)."""
    if k == 0:
        return x
    zeros = jnp.zeros(x.shape[:-1] + (k,), x.dtype)
    return jnp.concatenate([zeros, x[..., :-k]], axis=-1)


def _affine_combine(e1, e2):
    """Compose affine maps s -> A s + b (elementwise over leading dims).

    Precision.HIGHEST is load-bearing: TPU einsum defaults to bf16 MXU
    passes, and the scan tree composes O(log n) of these 2x2 products —
    bf16 rounding compounds to ~1e-2 rel err on the device (measured
    round 5: iir smoke 8.5e-3 vs tol 1e-3 before the pin, 1e-7 after).
    """
    a1, b1 = e1
    a2, b2 = e2
    hi = prx.HIGHEST
    return (jnp.einsum("...ij,...jk->...ik", a2, a1, precision=hi),
            jnp.einsum("...ij,...j->...i", a2, b1, precision=hi) + b2)


def _biquad_affine_scan(a1, a2, drive):
    """Associative scan of ``s[t] = A s[t-1] + d[t]`` for the biquad
    companion matrix ``A = [[-a1, -a2], [1, 0]]``.

    ``drive`` is ``[..., n, 2]``.  Returns ``(cum_a, states)`` — the
    cumulative transition products ``cum_a[t] = A^(t+1)`` come free from
    the same scan and let callers apply an incoming state as
    ``states + cum_a @ s_in`` without a second pass (used by
    ``parallel.sharded_sosfilt``).
    """
    a_mat = jnp.broadcast_to(
        jnp.asarray([[-a1, -a2], [1.0, 0.0]], drive.dtype),
        drive.shape[:-1] + (2, 2))
    return jax.lax.associative_scan(_affine_combine, (a_mat, drive),
                                    axis=-3)


def _biquad_apply(x, b0, b1, b2, a1, a2, s_in=None):
    """One biquad over ``x[..., n]`` via associative scan.

    State ``s[t] = (y[t], y[t-1])``; ``s[t] = A s[t-1] + (u[t], 0)`` with
    ``u`` the FIR drive and ``A = [[-a1, -a2], [1, 0]]``.  ``s_in`` is
    the incoming DF2T state ``(z1, z2)`` (scipy's ``sosfilt_zi``
    convention): unrolling the DF2T recurrence, ``z1`` lands as a
    ``+z1`` correction on ``y[0]`` and ``z2`` as ``+z2`` on ``y[1]`` —
    pure drive corrections, the scan itself is unchanged.
    """
    n = x.shape[-1]
    # FIR drive: shifted adds via concat delays (XLA fuses; no scatter)
    u = b0 * x
    if n > 1:
        u = u + b1 * _delay(x, 1)
    if n > 2:
        u = u + b2 * _delay(x, 2)
    if s_in is not None:
        # z1 corrects y[0], z2 corrects y[1] (DF2T unroll); zi may be
        # unbatched [2] against a batched x — broadcast it up first
        s_in = jnp.broadcast_to(s_in, u.shape[:-1] + (2,))
        zpad = jnp.zeros(u.shape[:-1] + (max(n - 2, 0),), u.dtype)
        corr = jnp.concatenate(
            [s_in[..., :1], s_in[..., 1:2], zpad], axis=-1)
        u = u + corr[..., :n]
    drive = jnp.stack([u, jnp.zeros_like(u)], axis=-1)
    _, states = _biquad_affine_scan(a1, a2, drive)
    return states[..., 0]


def _section_exit_state(b1, b2, a1, a2, x_sec, y_sec, xp):
    """DF2T exit state of one section from its last 2 in/out samples:
    ``z2 = b2 x[-1] - a2 y[-1]``,
    ``z1 = b1 x[-1] - a1 y[-1] + b2 x[-2] - a2 y[-2]``
    (valid for n >= 2 regardless of the incoming state)."""
    z2 = b2 * x_sec[..., -1] - a2 * y_sec[..., -1]
    z1 = (b1 * x_sec[..., -1] - a1 * y_sec[..., -1]
          + b2 * x_sec[..., -2] - a2 * y_sec[..., -2])
    return xp.stack([z1, z2], axis=-1)


def _sos_scan(x, sos_rows, zi_rows=None, want_zf=False):
    zf = []
    for i, (b0, b1, b2, _, a1, a2) in enumerate(sos_rows):
        s_in = None if zi_rows is None else zi_rows[i]
        y = _biquad_apply(x, b0, b1, b2, a1, a2, s_in=s_in)
        if want_zf:
            zf.append(_section_exit_state(b1, b2, a1, a2, x, y, jnp))
        x = y
    if want_zf:
        return x, jnp.stack(zf, axis=-2)
    return x


@functools.partial(obs.instrumented_jit,
                   static_argnames=("sos_key", "want_zf"))
def _sosfilt_xla(x, sos_key, zi, want_zf=False):
    sos_rows = np.asarray(sos_key, np.float32)
    # zi may carry leading batch dims: [..., n_sections, 2]
    zi_rows = (None if zi is None
               else [zi[..., i, :] for i in range(len(sos_rows))])
    return _sos_scan(x, sos_rows, zi_rows, want_zf)


def sosfilt(sos, x, zi=None, simd=None, return_zf=False):
    """Filter ``x[..., n]`` through a cascade of second-order sections.

    ``sos`` is ``[n_sections, 6]`` (``[b0 b1 b2 1 a1 a2]`` rows, e.g.
    from :func:`butterworth`).  ``zi`` optionally gives each section's
    incoming state ``[..., n_sections, 2]`` in scipy's direct-form-II-
    transposed ``(z1, z2)`` convention (see :func:`sosfilt_zi`).  The
    recurrence runs as an
    O(log n)-depth ``associative_scan`` of 2x2 affine maps — a parallel
    formulation of the serial textbook loop the oracle implements.

    With ``return_zf=True`` also returns the exit states
    ``[..., n_sections, 2]`` (same DF2T convention) — feed them as the
    next block's ``zi`` to stream block-by-block (needs ``n >= 2``;
    see :class:`StreamingSosfilt`).
    """
    sos = _check_sos(sos)
    if return_zf and np.shape(x)[-1] < 2:
        raise ValueError("return_zf needs at least 2 samples per block")
    if resolve_simd(simd, op="iir"):
        sos_key = tuple(tuple(float(v) for v in row) for row in sos)
        zi_j = None if zi is None else jnp.asarray(zi, jnp.float32)
        with obs.span("sosfilt.dispatch", sections=len(sos)):
            return _sosfilt_xla(jnp.asarray(x, jnp.float32), sos_key,
                                zi_j, return_zf)
    if return_zf:
        y, zf = sosfilt_na(sos, x, zi=zi, return_zf=True)
        return y.astype(np.float32), zf.astype(np.float32)
    return sosfilt_na(sos, x, zi=zi).astype(np.float32)


def sosfilt_na(sos, x, zi=None, return_zf=False):
    """NumPy float64 oracle twin of :func:`sosfilt`: the sequential
    direct-form recurrence, one sample at a time."""
    sos = _check_sos(sos)
    x = np.asarray(x, np.float64)
    y = x.copy()
    zf = np.zeros(x.shape[:-1] + (len(sos), 2))
    for i, (b0, b1, b2, _, a1, a2) in enumerate(sos):
        xs = y
        ys = np.zeros_like(xs)
        # DF2T form, matching scipy's state convention exactly
        z1 = np.zeros(xs.shape[:-1])
        z2 = np.zeros(xs.shape[:-1])
        if zi is not None:
            z1 = z1 + zi[..., i, 0]
            z2 = z2 + zi[..., i, 1]
        for t in range(xs.shape[-1]):
            xt = xs[..., t]
            yt = b0 * xt + z1
            z1 = b1 * xt - a1 * yt + z2
            z2 = b2 * xt - a2 * yt
            ys[..., t] = yt
        zf[..., i, 0] = z1
        zf[..., i, 1] = z2
        y = ys
    if return_zf:
        return y, zf
    return y


def sos_stream_step(x, sos, zi):
    """TRACEABLE one-block SOS cascade step — the pipeline compiler's
    state-export hook (:mod:`veles.simd_tpu.pipeline`).

    ``x[..., b]`` (``b >= 2``) runs through the associative-scan
    cascade with incoming DF2T state ``zi[..., n_sections, 2]``;
    returns ``(y, zf)`` with ``zf`` the exit states in the same
    convention — thread them into the next block's call and the
    concatenated outputs equal the one-shot cascade to f32 round-off.
    ``sos`` must be a HOST array (it becomes trace-time constants);
    ``x``/``zi`` may be tracers, so a fused outer jit can inline this
    step next to other stages with no extra dispatch.
    """
    sos = _check_sos(sos)
    sos_rows = np.asarray(sos, np.float32)
    zi_rows = [zi[..., i, :] for i in range(len(sos_rows))]
    return _sos_scan(x, sos_rows, zi_rows, want_zf=True)


def sos_stream_step_na(x, sos, zi):
    """NumPy float64 oracle twin of :func:`sos_stream_step` (the
    pipeline's stage-by-stage degradation path): returns ``(y, zf)``."""
    return sosfilt_na(sos, x, zi=zi, return_zf=True)


class StreamingSosfilt:
    """Chunked streaming IIR with carried DF2T state.

    The IIR analog of :class:`~veles.simd_tpu.ops.convolve.\
StreamingConvolution`: chunks arrive one at a time, each section's
    ``(z1, z2)`` state is carried between calls, and the concatenated
    outputs match the one-shot cascade to f32 round-off (~1e-7 — the
    chunk boundary changes the scan's reduction order; no flush needed,
    an IIR has no lookahead)::

        st = StreamingSosfilt(butterworth(4, 0.25))
        ys = [st.process(c) for c in chunks]     # len(c) >= 2
        # np.concatenate(ys) == sosfilt(sos, x)

    Each distinct chunk length compiles once; leading batch dims are
    allowed and fixed across calls.
    """

    def __init__(self, sos, zi=None, simd=None):
        self._sos = _check_sos(sos)
        # validate once; per-chunk calls reuse the cached static key
        self._sos_key = tuple(tuple(float(v) for v in row)
                              for row in self._sos)
        self._simd = resolve_simd(simd, op="iir")
        self.reset(zi)

    def process(self, chunk):
        if np.shape(chunk)[-1] < 2:
            raise ValueError("chunks need at least 2 samples")
        if self._simd:
            y, zf = _sosfilt_xla(jnp.asarray(chunk, jnp.float32),
                                 self._sos_key,
                                 jnp.asarray(self._zi, jnp.float32),
                                 True)
        else:
            y, zf = sosfilt_na(self._sos, chunk, zi=self._zi,
                               return_zf=True)
            y = y.astype(np.float32)
        self._zi = zf
        return y

    def reset(self, zi=None):
        self._zi = (np.zeros((len(self._sos), 2), np.float32)
                    if zi is None else np.asarray(zi, np.float32))


def _odd_ext(x, padlen: int, xp):
    """Odd extension at both ends (scipy's filtfilt default padding)."""
    if padlen == 0:
        return x
    left = 2 * x[..., :1] - x[..., padlen:0:-1]
    right = 2 * x[..., -1:] - x[..., -2:-padlen - 2:-1]
    return xp.concatenate([left, x, right], axis=-1)


def _filtfilt_padlen(sos, n: int, padlen) -> int:
    if padlen is None:
        # scipy.signal.sosfiltfilt's default edge-padding length
        ntaps = 2 * len(sos) + 1
        ntaps -= min((sos[:, 2] == 0).sum(), (sos[:, 5] == 0).sum())
        padlen = 3 * int(ntaps)
    padlen = int(padlen)
    if padlen < 0 or (padlen >= n and padlen > 0):
        raise ValueError(f"padlen {padlen} must be in [0, n-1] "
                         f"(signal length {n})")
    return padlen


def sosfiltfilt(sos, x, padlen=None, simd=None):
    """Zero-phase forward-backward filtering (scipy's ``sosfiltfilt``).

    Odd-extends the signal by ``padlen`` (scipy's default formula,
    roughly ``6 * n_sections + 3``),
    runs the cascade forward with settled initial conditions
    (:func:`sosfilt_zi` scaled by the edge sample), reverses, repeats,
    and trims — doubling the magnitude response and cancelling the
    phase.
    """
    sos = _check_sos(sos)
    zi = sosfilt_zi(sos)
    n = np.shape(x)[-1]
    padlen = _filtfilt_padlen(sos, n, padlen)
    if resolve_simd(simd, op="iir"):
        # outer span; the two sosfilt calls below nest their own
        with obs.span("sosfiltfilt.dispatch", sections=len(sos)):
            xj = jnp.asarray(x, jnp.float32)
            ext = _odd_ext(xj, padlen, jnp)
            zi_j = jnp.asarray(zi, jnp.float32)
            fwd = sosfilt(sos, ext, zi=zi_j * ext[..., :1, None],
                          simd=True)
            bwd = sosfilt(sos, fwd[..., ::-1],
                          zi=zi_j * fwd[..., -1:, None], simd=True)
            out = bwd[..., ::-1]
            return out[..., padlen:padlen + n]
    return sosfiltfilt_na(sos, x, padlen=padlen).astype(np.float32)


def filtfilt(b, a, x, padlen=None, simd=None):
    """Zero-phase forward-backward filtering in ``(b, a)`` form
    (scipy's ``filtfilt`` with its ``method='pad'`` default): routed
    through :func:`tf2sos` + :func:`sosfiltfilt` with scipy's
    ``3 * max(len(a), len(b))`` default padding — the same settled-
    state odd-extension construction, so results match scipy to float
    tolerance (the section pairing only reorders rounding).
    """
    b_arr = np.atleast_1d(np.asarray(b, np.float64))
    a_arr = np.atleast_1d(np.asarray(a, np.float64))
    if padlen is None:
        padlen = 3 * max(len(a_arr), len(b_arr))
    return sosfiltfilt(tf2sos(b_arr, a_arr), x, padlen=padlen,
                       simd=simd)


def sosfiltfilt_na(sos, x, padlen=None):
    """NumPy float64 oracle twin of :func:`sosfiltfilt`."""
    sos = _check_sos(sos)
    zi = sosfilt_zi(sos)
    x = np.asarray(x, np.float64)
    n = x.shape[-1]
    padlen = _filtfilt_padlen(sos, n, padlen)
    ext = _odd_ext(x, padlen, np)
    fwd = sosfilt_na(sos, ext, zi=zi * ext[..., :1, None])
    bwd = sosfilt_na(sos, fwd[..., ::-1], zi=zi * fwd[..., -1:, None])
    out = bwd[..., ::-1]
    return out[..., padlen:padlen + n]


# ---------------------------------------------------------------------------
# general transfer functions (companion-matrix scan)
# ---------------------------------------------------------------------------

_LFILTER_MAX_ORDER = 32  # p^2 scan elements; use sosfilt beyond this


def _normalize_ba(b, a):
    b = np.atleast_1d(np.asarray(b, np.float64))
    a = np.atleast_1d(np.asarray(a, np.float64))
    if a[0] == 0.0:
        raise ValueError("a[0] must be nonzero")
    return b / a[0], a / a[0]


def lfilter_zi(b, a) -> np.ndarray:
    """Steady-state DF2T state for a unit step input (scipy's
    ``lfilter_zi``): scale by the signal's edge value to start
    ``lfilter`` "already settled".  Host-side float64 closed form —
    the transposed-direct-form state recurrence at steady state
    ``z = A z + B`` solved as ``(I - A) z = B``, exactly scipy's
    companion-matrix construction.
    """
    b, a = _normalize_ba(b, a)
    n = max(len(a), len(b))
    a = np.concatenate([a, np.zeros(n - len(a))])
    b = np.concatenate([b, np.zeros(n - len(b))])
    if n == 1:
        return np.zeros(0)
    # DF2T state update for constant input x=1, output y:
    #   z_i = b_{i+1} - a_{i+1} y + z_{i+1}   (z_n = 0)
    # with steady y = sum(b)/sum(a); solve directly by back-substitution
    if a.sum() == 0.0:
        raise ValueError(
            "filter has a pole at z=1 (sum(a) == 0): no steady state "
            "exists for lfilter_zi (scipy raises LinAlgError here)")
    y = b.sum() / a.sum()
    zi = np.zeros(n - 1)
    acc = 0.0
    for i in range(n - 2, -1, -1):
        acc += b[i + 1] - a[i + 1] * y
        zi[i] = acc
    return zi


@functools.partial(obs.instrumented_jit, static_argnames=("b_key", "a_key"))
def _lfilter_xla(x, b_key, a_key):
    b = np.asarray(b_key, np.float32)
    a = np.asarray(a_key, np.float32)
    p = max(len(a) - 1, 1)
    n = x.shape[-1]
    # FIR drive u[t] = sum_k b[k] x[t-k] — concat delays, no scatter
    u = jnp.zeros_like(x)
    for k_tap, bk in enumerate(b):
        if (bk != 0.0 or k_tap == 0) and k_tap < n:
            u = u + np.float32(bk) * _delay(x, k_tap)
    # companion matrix for s[t] = (y[t], ..., y[t-p+1])
    a_comp = np.zeros((p, p), np.float32)
    a_comp[0, : len(a) - 1] = -a[1:]
    a_comp[1:, :-1] = np.eye(p - 1, dtype=np.float32)
    a_mat = jnp.broadcast_to(jnp.asarray(a_comp),
                             x.shape[:-1] + (n, p, p))
    drive = jnp.concatenate(
        [u[..., None], jnp.zeros(x.shape + (p - 1,), x.dtype)], axis=-1)
    _, states = jax.lax.associative_scan(_affine_combine, (a_mat, drive),
                                         axis=-3)
    return states[..., 0]


def lfilter(b, a, x, simd=None):
    """Direct-form transfer-function filter ``y = (b/a) * x``
    (scipy's ``lfilter``), order ≤ {max_order}.

    The denominator recurrence runs as a companion-matrix
    ``associative_scan`` (pxp affine maps, O(log n) depth).  For high
    orders prefer :func:`sosfilt` — cascaded biquads are both better
    conditioned and cheaper (2x2 vs pxp scan elements).
    """
    b, a = _normalize_ba(b, a)
    p = len(a) - 1
    if p > _LFILTER_MAX_ORDER:
        raise ValueError(
            f"denominator order {p} > {_LFILTER_MAX_ORDER}: use sosfilt "
            "(cascaded second-order sections) for high-order filters")
    if resolve_simd(simd, op="iir"):
        if p == 0:
            # pure FIR: no recurrence, just the drive
            a = np.concatenate([a, [0.0]])
        with obs.span("lfilter.dispatch", order=p):
            return _lfilter_xla(jnp.asarray(x, jnp.float32),
                                tuple(float(v) for v in b),
                                tuple(float(v) for v in a))
    return lfilter_na(b, a, x).astype(np.float32)


if lfilter.__doc__:  # stripped under python -OO
    lfilter.__doc__ = lfilter.__doc__.format(max_order=_LFILTER_MAX_ORDER)


def lfilter_na(b, a, x):
    """NumPy float64 oracle twin of :func:`lfilter` (sequential)."""
    b, a = _normalize_ba(b, a)
    x = np.asarray(x, np.float64)
    y = np.zeros_like(x)
    for t in range(x.shape[-1]):
        acc = np.zeros(x.shape[:-1])
        for k_tap, bk in enumerate(b):
            if t - k_tap >= 0:
                acc = acc + bk * x[..., t - k_tap]
        for k_tap, ak in enumerate(a[1:], start=1):
            if t - k_tap >= 0:
                acc = acc - ak * y[..., t - k_tap]
        y[..., t] = acc
    return y
