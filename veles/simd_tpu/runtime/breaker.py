"""Per-class circuit breakers: closed -> open -> half-open dispatch gates.

The fault-policy engine (:mod:`veles.simd_tpu.runtime.faults`) answers
one failing dispatch with bounded retry and a graceful degrade; what it
cannot answer is the *persistently* failing bucket — a shape class
whose route keeps dying burns its full retry budget on every batch,
multiplying the outage's latency damage by the retry ladder.  The
serve health machine (:mod:`veles.simd_tpu.serve.health`) promotes the
degrade to a mode, but globally: one poisoned shape class would drag
every healthy class onto the oracle with it.  This module is the
per-class middle layer — the classic circuit breaker, keyed by
``(site, shape-class)``:

* **closed** — dispatches flow normally; each guarded outcome lands in
  a sliding window of the last ``window`` results, and when the window
  holds at least ``min_events`` outcomes with a failure rate at or
  above ``threshold`` the breaker opens;
* **open** — dispatch goes *straight* to the caller's fallback (the
  oracle in ``serve/``, the single-chip twin in ``parallel/``) without
  paying the retry ladder; every ``probe_every``-th short-circuited
  call is promoted to a **half-open** trial instead;
* **half-open** — the trial dispatches with a zero-retry budget; a
  success closes the breaker (window cleared), a failure reopens it.

Cadence is *call-counted*, not wall-clock — the same determinism
argument as the health machine's probe cadence: reproducible under the
fault-injection plan on CPU CI, and naturally load-proportional in
production.

Every transition is a ``breaker_transition`` decision event and the
current state is a ``breaker_state`` gauge (``veles_simd_breaker_state``
in the Prometheus export, 0 = closed, 0.5 = half-open, 1 = open);
short-circuits, opens, and probes are ``breaker_*`` counters.  The
live registry is in ``obs.caches()`` under ``runtime.breakers``, and
:func:`snapshot` gives the per-breaker JSON view.

Consulted by :func:`veles.simd_tpu.runtime.faults.guarded` callers at
``serve.dispatch`` (key: the batch's shape class), the guarded ``ops/``
dispatch sites, and the sharded dispatch sites in
:mod:`veles.simd_tpu.parallel.ops` (key: ``(op, mesh-class)``).  Typed
``Overloaded`` sheds never reach a breaker — a shed is a policy
outcome, not a fault (``faults.guarded`` re-raises them before any
accounting).

Knobs: ``VELES_SIMD_BREAKER_WINDOW`` (sliding-window size, default 8),
``VELES_SIMD_BREAKER_THRESHOLD`` (failure rate that opens, default
0.5), ``VELES_SIMD_BREAKER_MIN_EVENTS`` (outcomes before the rate
means anything, default 2), ``VELES_SIMD_BREAKER_PROBE_EVERY`` (every
Nth short-circuit probes, default 4).
"""

from __future__ import annotations

import collections
import os
import threading

from veles.simd_tpu import obs

__all__ = [
    "CLOSED", "OPEN", "HALF_OPEN", "Breaker",
    "breaker_for", "lookup", "snapshot", "reset",
    "BREAKER_WINDOW_ENV", "BREAKER_THRESHOLD_ENV",
    "BREAKER_MIN_EVENTS_ENV", "BREAKER_PROBE_EVERY_ENV",
    "DEFAULT_WINDOW", "DEFAULT_THRESHOLD", "DEFAULT_MIN_EVENTS",
    "DEFAULT_PROBE_EVERY", "env_policy",
]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

BREAKER_WINDOW_ENV = "VELES_SIMD_BREAKER_WINDOW"
BREAKER_THRESHOLD_ENV = "VELES_SIMD_BREAKER_THRESHOLD"
BREAKER_MIN_EVENTS_ENV = "VELES_SIMD_BREAKER_MIN_EVENTS"
BREAKER_PROBE_EVERY_ENV = "VELES_SIMD_BREAKER_PROBE_EVERY"

# window 8 / threshold 0.5 / min_events 2: two consecutive retry
# exhaustions on a class open its breaker (one could be a blip; by the
# second the retry ladder has already been paid twice), and a healthy
# class needs sustained failures, not one, to trip.  probe_every 4
# mirrors the health machine's cadence: a recovered class is noticed
# within ~3 short-circuited calls while a dead one only eats one
# zero-retry probe per 4.
DEFAULT_WINDOW = 8
DEFAULT_THRESHOLD = 0.5
DEFAULT_MIN_EVENTS = 2
DEFAULT_PROBE_EVERY = 4

_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 0.5, OPEN: 1.0}


def _env_number(name: str, default, cast, minimum):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = cast(raw)
    except ValueError:
        return default
    return value if value >= minimum else default


def env_policy() -> tuple:
    """``(window, threshold, min_events, probe_every)`` from the
    environment, falling back to the defaults."""
    return (_env_number(BREAKER_WINDOW_ENV, DEFAULT_WINDOW, int, 1),
            _env_number(BREAKER_THRESHOLD_ENV, DEFAULT_THRESHOLD,
                        float, 0.0),
            _env_number(BREAKER_MIN_EVENTS_ENV, DEFAULT_MIN_EVENTS,
                        int, 1),
            _env_number(BREAKER_PROBE_EVERY_ENV, DEFAULT_PROBE_EVERY,
                        int, 1))


class Breaker:
    """One ``(site, key)`` circuit breaker behind one lock.

    The caller's contract is three calls: :meth:`admit` before the
    dispatch (``"closed"`` — dispatch normally; ``"probe"`` — dispatch
    with a zero-retry budget; ``"open"`` — skip the device and answer
    via the fallback), then exactly one of :meth:`success` /
    :meth:`failure` for outcomes that reached the device.
    Short-circuited calls record no outcome — an open breaker's
    window only moves through its probes, so recovery is judged on
    live evidence, not on the fallback's reliability.
    """

    __slots__ = ("site", "key", "window_size", "threshold",
                 "min_events", "probe_every", "_lock", "_state",
                 "_window", "_shorted", "_opens", "_probes",
                 "_failures", "_successes")

    def __init__(self, site: str, key=None, *,
                 window: int | None = None,
                 threshold: float | None = None,
                 min_events: int | None = None,
                 probe_every: int | None = None):
        env_w, env_t, env_m, env_p = env_policy()
        self.site = site
        self.key = key
        self.window_size = int(window) if window else env_w
        self.threshold = (float(threshold) if threshold is not None
                          else env_t)
        self.min_events = int(min_events) if min_events else env_m
        self.probe_every = int(probe_every) if probe_every else env_p
        if self.window_size < 1 or self.min_events < 1 \
                or self.probe_every < 1:
            raise ValueError("breaker window/min_events/probe_every "
                             "must be >= 1")
        self._lock = threading.Lock()
        self._state = CLOSED
        self._window: collections.deque = collections.deque(
            maxlen=self.window_size)
        self._shorted = 0       # short-circuited calls while not closed
        self._opens = 0
        self._probes = 0
        self._failures = 0
        self._successes = 0

    # -- labels / events ---------------------------------------------------

    def _key_label(self) -> str:
        return repr(self.key) if self.key is not None else ""

    def _transition(self, new_state: str, reason: str) -> None:
        """Record one state transition (caller holds the lock)."""
        old = self._state
        self._state = new_state
        obs.gauge("breaker_state", _STATE_GAUGE[new_state],
                  site=self.site, key=self._key_label())
        # lifetime opens/probes ride every transition: the journal
        # (obs v6) makes these events durable, and a postmortem
        # counting breaker *cycles* needs the cumulative context each
        # edge was recorded against, not just the edge itself
        obs.record_decision(
            "breaker_transition", new_state, site=self.site,
            key=self._key_label(), previous=old, reason=reason,
            failures=sum(1 for ok in self._window if not ok),
            window=len(self._window),
            opens=self._opens, probes=self._probes)

    # -- the caller contract -----------------------------------------------

    def admit(self, force_probe: bool = False) -> str:
        """Gate one dispatch: ``"closed"`` / ``"probe"`` / ``"open"``.

        While not closed, every ``probe_every``-th call is promoted to
        a half-open trial (state -> HALF_OPEN on the first promotion);
        the rest short-circuit.  The cadence keeps counting in
        HALF_OPEN too, so a trial whose outcome never lands (a
        non-fault exception propagated past the caller) cannot wedge
        the breaker — the next cadence tick simply re-arms a trial.
        ``force_probe=True`` promotes a not-closed admit to a trial
        regardless of the cadence (the serve health machine's own
        probe batches outrank the short-circuit), with the probe —
        not a short-circuit — counted and the HALF_OPEN transition
        recorded.
        """
        with self._lock:
            if self._state == CLOSED:
                return CLOSED
            self._shorted += 1
            if force_probe or self._shorted % self.probe_every == 0:
                self._probes += 1
                if self._state == OPEN:
                    self._transition(
                        HALF_OPEN, "health_probe" if force_probe
                        else "probe_cadence")
                obs.count("breaker_probe", site=self.site,
                          key=self._key_label())
                return "probe"
            obs.count("breaker_short_circuit", site=self.site,
                      key=self._key_label())
            return OPEN

    def success(self) -> None:
        """A dispatch (or half-open trial) completed on the device."""
        with self._lock:
            self._successes += 1
            if self._state != CLOSED:
                self._window.clear()
                self._shorted = 0
                self._transition(CLOSED, "probe_success")
                return
            self._window.append(True)

    def failure(self) -> None:
        """A dispatch exhausted its transient-fault retries.  Typed
        overload sheds must never land here (``faults.guarded``
        re-raises them before any breaker accounting)."""
        with self._lock:
            self._failures += 1
            if self._state == HALF_OPEN:
                self._transition(OPEN, "probe_failure")
                obs.count("breaker_reopen", site=self.site,
                          key=self._key_label())
                return
            if self._state == OPEN:
                return
            self._window.append(False)
            fails = sum(1 for ok in self._window if not ok)
            if (len(self._window) >= self.min_events
                    and fails / len(self._window) >= self.threshold):
                self._opens += 1
                self._shorted = 0
                self._transition(OPEN, "failure_rate")
                obs.count("breaker_open", site=self.site,
                          key=self._key_label())

    # -- introspection -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def info(self) -> dict:
        """JSON-native view: state, window occupancy, tallies."""
        with self._lock:
            fails = sum(1 for ok in self._window if not ok)
            return {"site": self.site, "key": self._key_label(),
                    "state": self._state,
                    "window": len(self._window),
                    "window_size": self.window_size,
                    "window_failures": fails,
                    "threshold": self.threshold,
                    "min_events": self.min_events,
                    "probe_every": self.probe_every,
                    "opens": self._opens, "probes": self._probes,
                    "failures": self._failures,
                    "successes": self._successes,
                    "short_circuited": self._shorted}


# ---------------------------------------------------------------------------
# the process-wide registry (obs.caches()-introspectable)
# ---------------------------------------------------------------------------

_registry_lock = threading.Lock()
_REGISTRY: dict[tuple, Breaker] = {}


def breaker_for(site: str, key=None) -> Breaker:
    """The breaker for ``(site, key)``, minted on first use (policy
    knobs read from the environment at mint time)."""
    rkey = (site, key)
    with _registry_lock:
        br = _REGISTRY.get(rkey)
        if br is None:
            br = _REGISTRY[rkey] = Breaker(site, key)
        return br


def lookup(site: str, key=None) -> Breaker | None:
    """The breaker for ``(site, key)`` if one was ever minted."""
    with _registry_lock:
        return _REGISTRY.get((site, key))


def snapshot() -> list:
    """JSON-native view of every live breaker (site order)."""
    with _registry_lock:
        breakers = list(_REGISTRY.values())
    return sorted((b.info() for b in breakers),
                  key=lambda i: (i["site"], i["key"]))


def reset() -> None:
    """Drop every breaker (tests; a fresh registry per scenario)."""
    with _registry_lock:
        _REGISTRY.clear()


def _registry_info() -> dict:
    """The ``obs.caches()`` provider: registry occupancy + the
    per-state census (how many breakers are open right now)."""
    snap = snapshot()
    states: dict[str, int] = {}
    for b in snap:
        states[b["state"]] = states.get(b["state"], 0) + 1
    return {"size": len(snap), "states": states}


obs.register_cache("runtime.breakers", _registry_info)
