"""Tests for the bench-regression gate (``tools/bench_regress.py``).

Pure synthetic fixtures — no device, no timing: a fake
``BENCH_DETAILS.json`` run plus a fake ``BENCH_HISTORY.jsonl``
trajectory, asserting the exit-code contract (0 within-noise/improved,
1 regression, 2 no data), the per-row noise overrides, and that every
invocation appends exactly one record to the history.
"""

import importlib.util
import json
import os

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "bench_regress",
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "bench_regress.py"))
bench_regress = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(bench_regress)

HEADLINE = "convolve 1M x 2047 overlap-save"
SUITE = "DWT daub8 512x4096"


def _write_details(path, headline_value, suite_value=500.0):
    rows = [
        {"metric": HEADLINE, "unit": "Msamples/s",
         "value": headline_value, "baseline": 10.0,
         "vs_baseline": (None if headline_value is None
                         else headline_value / 10.0),
         "device": "FakeDevice(id=0)"},
        {"metric": SUITE, "unit": "Msamples/s", "value": suite_value,
         "baseline": 25.0, "vs_baseline": suite_value / 25.0,
         "device": "FakeDevice(id=0)"},
        {"skipped_stages": []},   # tail entry must be ignored
    ]
    with open(path, "w") as f:
        json.dump(rows, f)
    return path


def _write_history(path, headline_values, suite_value=500.0):
    with open(path, "w") as f:
        for v in headline_values:
            f.write(json.dumps({
                "ts": 0.0, "source": "BENCH_DETAILS.json",
                "device": "FakeDevice(id=0)",
                "rows": {
                    HEADLINE: {"value": v, "unit": "Msamples/s",
                               "vs_baseline": v / 10.0},
                    SUITE: {"value": suite_value,
                            "unit": "Msamples/s",
                            "vs_baseline": suite_value / 25.0},
                }}) + "\n")
    return path


def _history_len(path):
    with open(path) as f:
        return sum(1 for line in f if line.strip())


def _run(tmp_path, headline_value, history_values, extra_args=()):
    details = _write_details(str(tmp_path / "DETAILS.json"),
                             headline_value)
    history = _write_history(str(tmp_path / "HISTORY.jsonl"),
                             history_values)
    before = _history_len(history)
    rc = bench_regress.main(["--details", details,
                             "--history", history, *extra_args])
    return rc, history, before


def test_within_noise_passes_and_appends_one_record(tmp_path, capsys):
    rc, history, before = _run(tmp_path, 980.0, [1000.0] * 4)
    assert rc == 0
    assert _history_len(history) == before + 1
    assert "within noise" in capsys.readouterr().out


def test_improvement_passes(tmp_path, capsys):
    rc, history, before = _run(tmp_path, 2000.0, [1000.0] * 4)
    assert rc == 0
    assert _history_len(history) == before + 1
    assert "improved" in capsys.readouterr().out


def test_regression_fails(tmp_path, capsys):
    rc, history, before = _run(tmp_path, 500.0, [1000.0] * 4)
    assert rc == 1
    # the failed run is STILL recorded: the trajectory must show the
    # regression, not pretend the run never happened
    assert _history_len(history) == before + 1
    out = capsys.readouterr()
    assert "REGRESSION" in out.out
    assert HEADLINE in out.err


def test_baseline_is_trailing_median_not_latest(tmp_path):
    # one outlier record must not drag the baseline: median of
    # [1000, 1000, 1000, 100] is 1000, so 950 stays within 10%
    rc, _, _ = _run(tmp_path, 950.0, [1000.0, 1000.0, 1000.0, 100.0])
    assert rc == 0


def test_window_bounds_the_baseline(tmp_path):
    # window=2 sees only the newest two records (the decayed ones), so
    # 450 is within noise of median(500, 500) even though older
    # records say 1000
    rc, _, _ = _run(tmp_path, 480.0, [1000.0, 1000.0, 500.0, 500.0],
                    extra_args=["--window", "2"])
    assert rc == 0


def test_per_row_noise_override(tmp_path):
    # -8% trips the default 10%? no — but a tightened per-row 5%
    # threshold for the headline catches it
    rc, _, _ = _run(tmp_path, 920.0, [1000.0] * 4)
    assert rc == 0
    rc, _, _ = _run(tmp_path, 920.0, [1000.0] * 4,
                    extra_args=["--noise", "convolve 1M=0.05"])
    assert rc == 1


def test_regressed_runs_never_become_baseline(tmp_path):
    # a red gate re-run with no fix must stay red: the regressed
    # records are appended (trajectory) but excluded from the median
    details = _write_details(str(tmp_path / "DETAILS.json"), 500.0)
    history = _write_history(str(tmp_path / "HISTORY.jsonl"),
                             [1000.0] * 3)
    for i in range(3):     # three consecutive red runs
        rc = bench_regress.main(["--details", details,
                                 "--history", history])
        assert rc == 1, f"run {i} laundered the regression"
        assert _history_len(history) == 3 + i + 1
    # a recovered run against the unpolluted baseline passes again
    details = _write_details(str(tmp_path / "DETAILS.json"), 980.0)
    assert bench_regress.main(["--details", details,
                               "--history", history]) == 0


def test_no_baseline_yet_passes(tmp_path):
    rc, history, before = _run(tmp_path, 1000.0, [])
    assert rc == 0
    assert _history_len(history) == before + 1


def test_null_value_not_gated(tmp_path, capsys):
    # bench flagged an unresolved measurement: reported, never failed
    rc, _, _ = _run(tmp_path, None, [1000.0] * 4)
    assert rc == 0
    assert "UNRESOLVED" in capsys.readouterr().out


def test_no_append_compares_without_recording(tmp_path):
    rc, history, before = _run(tmp_path, 500.0, [1000.0] * 4,
                               extra_args=["--no-append"])
    assert rc == 1
    assert _history_len(history) == before


def test_missing_details_exits_2(tmp_path):
    rc = bench_regress.main(
        ["--details", str(tmp_path / "nope.json"),
         "--history", str(tmp_path / "HISTORY.jsonl")])
    assert rc == 2


def test_empty_details_exits_2(tmp_path):
    details = tmp_path / "DETAILS.json"
    details.write_text("[]")
    rc = bench_regress.main(
        ["--details", str(details),
         "--history", str(tmp_path / "HISTORY.jsonl")])
    assert rc == 2


def test_torn_history_line_skipped(tmp_path, capsys):
    details = _write_details(str(tmp_path / "DETAILS.json"), 980.0)
    history = _write_history(str(tmp_path / "HISTORY.jsonl"),
                             [1000.0] * 3)
    with open(history, "a") as f:
        f.write('{"ts": 1.0, "rows": {"conv')   # crashed writer
    rc = bench_regress.main(["--details", details,
                             "--history", history])
    assert rc == 0
    assert "unparseable" in capsys.readouterr().err


@pytest.mark.parametrize("spec", ["no-equals", "x=1.5", "x=notnum"])
def test_bad_noise_spec_rejected(spec):
    with pytest.raises(SystemExit):
        bench_regress.main(["--noise", spec])


# --------------------------------------------------------------------------
# fault-aware gating (PR 6): a row below its floor under recorded
# transient faults is reported-not-gated and excluded from future
# baselines — the r05 host-contention story, without laundering
# --------------------------------------------------------------------------

def _write_faulty_details(path, headline_value, *, row_faults=0,
                          stage_faults=0, failed_probes=0):
    rows = [
        {"metric": HEADLINE, "unit": "Msamples/s",
         "value": headline_value, "baseline": 10.0,
         "vs_baseline": headline_value / 10.0,
         "device": "FakeDevice(id=0)",
         **({"telemetry": {"counters": {
             "fault_retry{site=convolve.dispatch}": row_faults}}}
            if row_faults else {})},
        {"metric": SUITE, "unit": "Msamples/s", "value": 500.0,
         "baseline": 25.0, "vs_baseline": 20.0,
         "device": "FakeDevice(id=0)"},
    ]
    tail = {}
    if stage_faults:
        tail["stage_faults"] = [
            {"stage": "headline:convolve_1m", "attempt": i,
             "kind": "device_lost", "detail": "injected"}
            for i in range(stage_faults)]
    if failed_probes:
        tail["device_probes"] = [
            {"attempt": 1, "ok": False, "devices": 0,
             "detail": "probe timed out"},
            {"attempt": 2, "ok": True, "devices": 1, "detail": ""}]
    if tail:
        rows.append(tail)
    with open(path, "w") as f:
        json.dump(rows, f)
    return path


@pytest.mark.parametrize("kwargs", [
    {"row_faults": 3},
    {"stage_faults": 2},
    {"failed_probes": 1},
])
def test_degraded_under_faults_is_reported_not_gated(tmp_path, capsys,
                                                     kwargs):
    details = _write_faulty_details(str(tmp_path / "DETAILS.json"),
                                    500.0, **kwargs)
    history = _write_history(str(tmp_path / "HISTORY.jsonl"),
                             [1000.0] * 4)
    rc = bench_regress.main(["--details", details,
                             "--history", history])
    assert rc == 0                       # reported, not gated
    out = capsys.readouterr()
    assert "DEGRADED" in out.out
    assert "reported, not gated" in out.err
    # the record carries the fault_degraded marker
    with open(history) as f:
        last = json.loads(f.read().strip().splitlines()[-1])
    assert last["fault_degraded"] == [HEADLINE]


def test_fault_degraded_rows_never_become_baseline(tmp_path):
    history = _write_history(str(tmp_path / "HISTORY.jsonl"),
                             [1000.0] * 4)
    # three consecutive fault-degraded runs at half throughput...
    for _ in range(3):
        details = _write_faulty_details(
            str(tmp_path / "DETAILS.json"), 500.0, stage_faults=1)
        assert bench_regress.main(["--details", details,
                                   "--history", history]) == 0
    # ...must not drag the median: a clean run at 500 is still a
    # regression against the unpolluted 1000 baseline
    details = _write_details(str(tmp_path / "DETAILS.json"), 500.0)
    assert bench_regress.main(["--details", details,
                               "--history", history]) == 1


def test_faults_without_slowdown_change_nothing(tmp_path):
    # a run that recorded faults but stayed within noise is a plain
    # pass and keeps contributing to the baseline
    details = _write_faulty_details(str(tmp_path / "DETAILS.json"),
                                    980.0, row_faults=2)
    history = _write_history(str(tmp_path / "HISTORY.jsonl"),
                             [1000.0] * 4)
    assert bench_regress.main(["--details", details,
                               "--history", history]) == 0
    with open(history) as f:
        last = json.loads(f.read().strip().splitlines()[-1])
    assert last["fault_degraded"] == []
    assert last["rows"][HEADLINE]["faults"] == 2


def test_clean_regression_still_gates(tmp_path):
    # no faults anywhere: the gate is as strict as ever
    rc, _, _ = _run(tmp_path, 500.0, [1000.0] * 4)
    assert rc == 1
