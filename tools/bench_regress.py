#!/usr/bin/env python
"""Bench-regression gate: fold runs into BENCH_HISTORY.jsonl and fail
on a headline/suite slowdown.

The bench trajectory used to be write-only — ``bench.py`` emitted
``BENCH_DETAILS.json`` per run and nothing ever looked back, so a PR
that regressed the 1M-convolve headline was only caught by a human
rereading numbers.  This tool closes the loop:

1. **Fold**: read the newest run's rows (metric, value, unit,
   vs_baseline) from ``BENCH_DETAILS.json`` and append them as exactly
   ONE JSONL record to the append-only ``BENCH_HISTORY.jsonl``.  A run
   that fails the gate is still recorded (the trajectory must show the
   regression, not pretend the run never happened) but its regressed
   rows are marked and **excluded from future baselines** — re-running
   a red gate can never launder a regression into the new normal; only
   a row that passes rejoins the median.
2. **Compare**: for every row, form a trailing baseline — the median of
   that metric's values over the previous ``--window`` records that
   contain it — and flag a regression when the new value falls below
   ``baseline * (1 - threshold)``.  All rows here are throughput
   (higher is better).  The threshold is per-row: ``--noise
   METRIC_SUBSTRING=FRAC`` overrides the ``--threshold`` default for
   rows whose metric name contains the substring (device-time rows are
   noisier than host-time rows; the headline deserves a tighter gate
   than the smoke-sized configs).  The spectral rows ship built-in
   defaults (``DEFAULT_NOISE``); CLI overrides apply after them, so
   the last matching substring still wins.
3. **Gate**: exit 0 when every row is within noise or improved (or has
   no baseline yet), 1 when any row regressed, 2 when there was
   nothing to compare (missing/empty details file).  ``make
   bench-regress`` wires this as the CI gate after ``make bench``.

Rows whose value is null (bench flagged an unresolved measurement) are
reported but never counted as regressions — a wedged relay is
``bench.py``'s rc=2 story, not a performance signal.

Fault-aware gating (PR 6): every history row records its fault count
(the ``fault_*`` counters the fault-policy engine embedded in the
row's telemetry), and a row that falls below its floor while the run
carries recorded transient faults — row counters, stage-fault records,
or failed device probes in the details tail — is DEGRADED-not-gated:
reported loudly, excluded from future baselines (like regressed rows),
but not an rc=1.  The r05 lesson both ways: host contention must not
fail the gate as a code regression, and a fault-degraded median must
not become the new normal.

Usage:  python tools/bench_regress.py
        python tools/bench_regress.py --details BENCH_DETAILS.json \\
            --history BENCH_HISTORY.jsonl --window 5 --threshold 0.10 \\
            --noise "convolve 1M=0.08" --noise "elementwise=0.25"
        python tools/bench_regress.py --no-append   # compare only
        make bench-regress
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

DEFAULT_DETAILS = "BENCH_DETAILS.json"
DEFAULT_HISTORY = "BENCH_HISTORY.jsonl"
DEFAULT_WINDOW = 5
DEFAULT_THRESHOLD = 0.10
# built-in per-row noise thresholds, applied BEFORE the CLI --noise
# overrides (later matches win, so the CLI always has the last word).
# The spectral rows are device-time rows at smaller work totals than
# the 1M headline, so their chained-timer jitter is wider; the batched
# ratio row divides two measurements and is the noisiest of all.
DEFAULT_NOISE = [
    ("stft", 0.15),
    ("istft round-trip", 0.15),
    ("spectrogram", 0.15),
    ("batched stft", 0.25),
    # the autotuned-headline row's baseline is the STATIC choice's
    # throughput measured in the same stage (not the CPU oracle), and
    # both sides carry probe/chained-timing noise
    ("autotuned", 0.15),
    # the MULTICHIP family (tools/bench_multichip.py --details
    # MULTICHIP_DETAILS.json): collective-heavy device-time rows whose
    # jitter includes ICI/host contention on shared pods; the
    # above-cutoff stft row divides two burst measurements
    ("sharded rfft", 0.25),
    ("sharded stft", 0.30),
    # the serve family (bench.py config + tools/loadgen.py --details
    # SERVE_DETAILS.json): wall-clock req/s through a threaded server
    # — queueing + host scheduling jitter on top of device jitter, and
    # the inverse-p99 row is a single order statistic
    ("serve", 0.35),
    ("serve p99", 0.40),
    # the tracing-overhead row is a throughput RATIO near 1.0 (traced
    # over untraced loadgen runs): the 5% threshold IS the obs-v4
    # overhead budget — request tracing + the scrape endpoint must
    # stay under 5% of serving throughput (narrower than the raw
    # serve rows because dividing the two runs cancels shared host
    # jitter)
    ("tracing overhead", 0.05),
    # the chaos family (tools/chaos.py --details CHAOS_DETAILS.json):
    # wall-clock throughput of a seconds-long scripted campaign whose
    # phases deliberately inject faults — the noisiest rows we gate —
    # and the deadline/fairness ratio rows, which are order statistics
    # of small per-phase samples
    ("chaos", 0.50),
    ("deadline hit rate", 0.25),
    ("tenant fairness", 0.40),
    # the replicated campaign (tools/chaos.py --replicas --details
    # REPLICA_DETAILS.json): wall-clock req/s of waves that
    # deliberately kill / drain a replica mid-measurement — the
    # failover wave carries an abrupt kill (throughput dips with the
    # kill's timing), the drain wave a graceful removal; both are
    # chaos_phase-stamped so dips report DEGRADED-not-gated anyway
    ("replica failover", 0.50),
    ("replica drain", 0.50),
    # the precision-route family (bench.py configs 14-16 + the
    # multichip bf16_comp row): device-time rows whose baseline is
    # the SAME geometry on the fp32/highest route measured in the
    # same stage — both sides carry chained-timer jitter, and the
    # gemm row's 2048 GEMM resolves fast enough that its marginal is
    # the noisiest of the three.  These defaults make the rows gate
    # from their first clean run.
    ("gemm 2048 bf16_comp", 0.20),
    ("convolve 1M x 2047 bf16_comp", 0.12),
    ("stft 16k x 512 bf16_comp", 0.15),
    ("sharded rfft bf16_comp", 0.25),
    # the pipeline family (bench.py configs 12/13): wall-clock blocks/s
    # through the fused sensor chain vs its stage-by-stage twin — host
    # dispatch + device jitter on both sides — and the inverse-p99 row
    # is a single order statistic of a small per-block sample
    ("pipeline sensor chain", 0.30),
    ("pipeline sensor chain p99", 0.45),
    # the cold-start family (tools/cold_start.py + bench.py config 17,
    # COLD_START_DETAILS.json): SUBPROCESS birth-to-first-request wall
    # clocks — interpreter spawn + imports + compiles under whatever
    # host contention the run hits — and the headline is a ratio of
    # two of them.  Wide on purpose; the x2 acceptance bar leaves
    # plenty of floor under a clean trajectory median.
    ("cold start", 0.40),
    # the cold-replica-restart phase of the replicated campaign: one
    # single-request latency on a just-restarted replica (an order
    # statistic of ONE sample, chaos_phase-stamped anyway)
    ("replica restart", 0.50),
    # the fleet-axis family (obs v5).  "serve goodput" is a useful/
    # dispatched row RATIO in (0, 1] — mostly deterministic for a
    # fixed request matrix, but batch formation (and therefore pow2
    # row padding) shifts with worker/timer scheduling; "fleet signal
    # lag" is the inverse of one kill-to-signals-visible wall-clock
    # measurement on the collector tick cadence (an order statistic
    # of one sample, chaos_phase-stamped anyway); the campaign's
    # goodput twin rides the same chaos waves
    ("serve goodput", 0.20),
    ("fleet signal lag", 0.50),
    ("replica campaign goodput", 0.25),
    # the collector-armed twin of "serve tracing overhead": the same
    # A/B throughput ratio near 1.0, measured while the fleet
    # collector sweeps in the background — same 5% budget
    ("fleet tracing overhead", 0.05),
    # the history-axis twin (obs v6): armed/disarmed throughput
    # ratio with the durable event journal toggled — appending every
    # decision to disk must also stay under the 5% budget
    ("journal overhead", 0.05),
    # the goodput-at-saturation family (tools/loadgen.py --saturation,
    # GOODPUT_DETAILS.json): "goodput saturation" is the after-side
    # useful/dispatched SAMPLE ratio — near-deterministic for a fixed
    # seed, but batch formation (and therefore the packed plans and
    # refill opportunities) shifts with worker/timer scheduling;
    # "goodput p99" is a single order statistic of a saturated
    # wall-clock run; "goodput recovery" divides two waste
    # measurements, compounding both sides' scheduling jitter
    ("goodput saturation", 0.15),
    ("goodput p99", 0.40),
    ("goodput recovery", 0.30),
    # the control-axis family (obs v7, tools/chaos.py --scale,
    # SCALE_DETAILS.json): "scale p99 under ramp" is the inverse of a
    # single order statistic measured across a deliberately-unpaced
    # ~10x burst (chaos_phase-stamped anyway); "scale replica-seconds
    # vs oracle" divides an integral of sampled alive-counts by a
    # schedule built from one measured capacity number — scheduling
    # jitter on both sides; "scale decision lag" is the inverse of
    # one peak-to-first-spawn wall-clock sample on the 30 ms control
    # cadence
    ("scale p99 under ramp", 0.45),
    ("scale replica-seconds", 0.30),
    ("scale decision lag", 0.50),
    # the rpc data-plane family (PR 20, tools/loadgen.py
    # --rpc-overhead, RPC_DETAILS.json).  "rpc overhead" divides the
    # subprocess group's throughput by the thread group's — and the
    # thread side finishes the whole fixed-request window in tens of
    # milliseconds, so one scheduler hiccup on either side swings the
    # ratio by integer factors (measured 0.03x..4.5x run to run on a
    # shared host).  The real in-run gate is loadgen's added-p50
    # budget (rc=1 over 75 ms); the history row exists for trajectory
    # visibility, so its noise band is deliberately near-total.
    # "rpc added p50" is the inverse of a p50-of-p50s difference of
    # two small samples — an order statistic minus an order statistic.
    ("rpc overhead", 0.90),
    ("rpc added p50", 0.50),
]


def row_fault_count(row: dict) -> int:
    """Transient/injected faults recorded in one row's embedded
    telemetry: the sum of every ``fault_*`` counter (retries,
    demotions, degradations, injections) the fault-policy engine
    bumped while that config ran."""
    counters = (row.get("telemetry") or {}).get("counters") or {}
    return sum(int(v) for k, v in counters.items()
               if k.startswith("fault_"))


def load_rows(details_path: str) -> list:
    """The comparable rows of one bench run: every BENCH_DETAILS.json
    entry with a ``metric`` key (the tail ``skipped_stages`` entry and
    other non-row records are ignored)."""
    return load_run(details_path)[0]


def load_run(details_path: str) -> tuple:
    """``(rows, run_faults)`` for one bench run.  ``run_faults`` is
    the run-level transient-fault evidence from the tail entry:
    stage-fault records the retry policy absorbed plus failed
    device-reachability probes — the r05 story, where host/relay
    trouble (not code) degraded the headline."""
    with open(details_path) as f:
        entries = json.load(f)
    if not isinstance(entries, list):
        raise ValueError(f"{details_path}: expected a list of configs")
    rows = [e for e in entries if isinstance(e, dict) and "metric" in e]
    run_faults = 0
    for e in entries:
        if not isinstance(e, dict) or "metric" in e:
            continue
        run_faults += len(e.get("stage_faults") or ())
        run_faults += sum(1 for p in e.get("device_probes") or ()
                          if not p.get("ok", True))
    return rows, run_faults


def rows_to_record(rows: list, source: str, regressed: list = (),
                   fault_degraded: list = (),
                   run_faults: int = 0) -> dict:
    """One append-only history record for this run.  ``regressed``
    names the rows that failed the gate this run — recorded for the
    trajectory, skipped by :func:`trailing_baseline` so a red run
    cannot drag the future baseline down.  ``fault_degraded`` names
    rows that fell below their floor *under recorded faults* —
    reported, not gated, and equally excluded from future baselines
    so a transient-fault run cannot launder the median either way."""
    return {
        "ts": time.time(),
        "source": source,
        "device": next((r.get("device") for r in rows
                        if r.get("device")), None),
        "regressed": sorted(regressed),
        "fault_degraded": sorted(fault_degraded),
        "run_faults": int(run_faults),
        "rows": {
            r["metric"]: {
                "value": r.get("value"),
                "unit": r.get("unit"),
                "vs_baseline": r.get("vs_baseline"),
                **({"faults": row_fault_count(r)}
                   if row_fault_count(r) else {}),
                # recovered-padding evidence (the goodput family):
                # waste before/after + refill counts ride into the
                # trajectory so a recovery regression is diagnosable
                # from the history alone
                **({"recovered": r["recovered"]}
                   if r.get("recovered") else {}),
            } for r in rows
        },
    }


def read_history(history_path: str) -> list:
    """All prior records, oldest first.  Unparseable lines (a crashed
    writer predating atomic appends, manual edits) are skipped with a
    warning rather than poisoning the gate forever."""
    records = []
    if not os.path.exists(history_path):
        return records
    with open(history_path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                print(f"bench_regress: {history_path}:{lineno}: "
                      f"skipping unparseable record", file=sys.stderr)
    return records


def append_history(history_path: str, record: dict) -> None:
    """Append exactly one JSONL record (single write + flush; JSONL
    appends are atomic at sane record sizes, and a torn tail line is
    skipped by :func:`read_history`)."""
    with open(history_path, "a") as f:
        f.write(json.dumps(record, allow_nan=False) + "\n")


def trailing_baseline(history: list, metric: str, window: int):
    """Median of the metric's values over the newest ``window`` prior
    records that measured it (None values, absent rows, and rows that
    were REGRESSED when recorded are skipped — a red run never becomes
    baseline).  Returns (baseline, n_samples); baseline None when
    unmeasured."""
    values = []
    for rec in reversed(history):
        if metric in rec.get("regressed", ()):
            continue
        if metric in rec.get("fault_degraded", ()):
            continue
        row = rec.get("rows", {}).get(metric)
        if row and isinstance(row.get("value"), (int, float)):
            values.append(float(row["value"]))
            if len(values) == window:
                break
    if not values:
        return None, 0
    return statistics.median(values), len(values)


def row_threshold(metric: str, default: float, overrides: list) -> float:
    """Per-row noise threshold: the last ``--noise substring=frac``
    whose substring appears in the metric name wins; the global
    ``--threshold`` otherwise."""
    thr = default
    for substr, frac in overrides:
        if substr in metric:
            thr = frac
    return thr


def compare(rows: list, history: list, window: int, default_thr: float,
            overrides: list, run_faults: int = 0) -> tuple:
    """Judge every row against its trailing baseline.

    Returns ``(regressions, fault_degraded, report_lines)``.
    ``regressions`` gates (rc=1); ``fault_degraded`` names rows that
    fell below their floor while the run carried recorded transient
    faults (row-embedded ``fault_*`` counters, run-level
    stage-fault/probe records, or a ``chaos_phase`` stamp — a row
    measured while a scripted chaos phase was actively injecting
    faults is fault-carrying by construction) — those are REPORTED
    but not gated (the r05 host-contention story: a relay hiccup is
    not a code regression), and :func:`trailing_baseline` excludes
    them from future medians so a degraded run cannot launder the
    baseline."""
    regressions = []
    fault_degraded = []
    lines = []
    for r in rows:
        metric = r["metric"]
        value = r.get("value")
        unit = r.get("unit", "")
        baseline, n = trailing_baseline(history, metric, window)
        thr = row_threshold(metric, default_thr, overrides)
        faults_n = row_fault_count(r) + run_faults
        if r.get("chaos_phase"):
            faults_n += 1
        if value is None:
            verdict = "UNRESOLVED (null value; not gated)"
        elif baseline is None:
            verdict = "no baseline yet"
        else:
            delta = (value - baseline) / baseline
            floor = baseline * (1.0 - thr)
            if value < floor and faults_n:
                verdict = (f"DEGRADED {delta:+.1%} under {faults_n} "
                           f"recorded fault(s) — reported, not gated; "
                           f"excluded from future baselines")
                fault_degraded.append(metric)
            elif value < floor:
                verdict = (f"REGRESSION {delta:+.1%} vs median of "
                           f"{n} (threshold -{thr:.0%})")
                regressions.append(metric)
            elif delta > thr:
                verdict = f"improved {delta:+.1%} vs median of {n}"
            else:
                verdict = (f"within noise {delta:+.1%} "
                           f"(threshold -{thr:.0%})")
        val_s = "null" if value is None else f"{value:.1f}"
        base_s = "-" if baseline is None else f"{baseline:.1f}"
        lines.append(f"  {metric:40s} {val_s:>10s} {unit:11s} "
                     f"baseline {base_s:>10s}  {verdict}")
    return regressions, fault_degraded, lines


def parse_noise(spec: str) -> tuple:
    if "=" not in spec:
        raise argparse.ArgumentTypeError(
            f"--noise wants METRIC_SUBSTRING=FRACTION, got {spec!r}")
    substr, _, frac = spec.rpartition("=")
    try:
        frac_f = float(frac)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--noise fraction {frac!r} is not a number")
    if not 0 <= frac_f < 1:
        raise argparse.ArgumentTypeError(
            f"--noise fraction {frac_f} must be in [0, 1)")
    return substr, frac_f


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Gate on bench regressions vs BENCH_HISTORY.jsonl")
    ap.add_argument("--details", default=DEFAULT_DETAILS,
                    help="bench.py output to fold in (default: "
                         f"{DEFAULT_DETAILS})")
    ap.add_argument("--history", default=DEFAULT_HISTORY,
                    help="append-only JSONL trajectory (default: "
                         f"{DEFAULT_HISTORY})")
    ap.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                    help="trailing records forming the baseline median "
                         f"(default: {DEFAULT_WINDOW})")
    ap.add_argument("--threshold", type=float,
                    default=DEFAULT_THRESHOLD,
                    help="default per-row noise fraction (default: "
                         f"{DEFAULT_THRESHOLD})")
    ap.add_argument("--noise", action="append", default=[],
                    type=parse_noise, metavar="SUBSTRING=FRAC",
                    help="per-row threshold override (repeatable; "
                         "last matching substring wins)")
    ap.add_argument("--no-append", action="store_true",
                    help="compare only; do not record this run")
    args = ap.parse_args(argv)

    try:
        rows, run_faults = load_run(args.details)
    except (OSError, ValueError) as e:
        print(f"bench_regress: cannot read run rows: {e}",
              file=sys.stderr)
        return 2
    if not rows:
        print(f"bench_regress: {args.details} holds no metric rows "
              "(bench captured nothing)", file=sys.stderr)
        return 2

    history = read_history(args.history)
    overrides = DEFAULT_NOISE + list(args.noise)
    regressions, fault_degraded, lines = compare(
        rows, history, args.window, args.threshold, overrides,
        run_faults=run_faults)
    if not args.no_append:
        append_history(args.history,
                       rows_to_record(rows, args.details,
                                      regressed=regressions,
                                      fault_degraded=fault_degraded,
                                      run_faults=run_faults))

    print(f"bench_regress: {len(rows)} rows vs {len(history)} prior "
          f"records in {args.history}"
          + (f" ({run_faults} run-level fault record(s))"
             if run_faults else "")
          + (" (not recorded)" if args.no_append else ""))
    for line in lines:
        print(line)
    if fault_degraded:
        print(f"bench_regress: {len(fault_degraded)} row(s) degraded "
              f"under recorded faults (reported, not gated): "
              f"{', '.join(fault_degraded)}", file=sys.stderr)
    if regressions:
        print(f"bench_regress: REGRESSION in {len(regressions)} "
              f"row(s): {', '.join(regressions)}", file=sys.stderr)
        return 1
    print("bench_regress: no regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
