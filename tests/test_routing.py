"""The unified routing engine (PR 7): candidate tables, selection
parity with the pre-engine hand-rolled selectors, the measured
autotuner with a deterministic injected timer, and the persistent
tune cache (round-trip / corrupt file / version mismatch / readonly).

The parity suite pins the acceptance criterion: for the geometries the
route suites exercise (test_convolve / test_spectral_routes /
test_wavelet parity shapes), the engine's static selection equals the
pre-migration hand-written ladders, re-implemented inline here as the
frozen spec.
"""

import json
import os

import numpy as np
import pytest

from veles.simd_tpu import obs
from veles.simd_tpu.ops import convolve as cv
from veles.simd_tpu.ops import convolve2d as cv2
from veles.simd_tpu.ops import pallas_kernels as pk
from veles.simd_tpu.ops import spectral as sp
from veles.simd_tpu.ops import wavelet as wv
from veles.simd_tpu.runtime import faults, routing

RNG = np.random.RandomState(71)


@pytest.fixture
def fresh_cache(tmp_path, monkeypatch):
    """A tune cache bound to a temp file, torn down after the test."""
    path = str(tmp_path / "tune.json")
    monkeypatch.setenv(routing.AUTOTUNE_CACHE_ENV, path)
    routing.set_cache_path(None)     # rebuild from env on next lookup
    yield path
    routing.set_cache_path(None)


@pytest.fixture
def autotune_on(monkeypatch):
    monkeypatch.setenv(routing.AUTOTUNE_ENV, "on")
    yield
    routing.set_cache_path(None)


def _fake_timer(table):
    """Deterministic probe timer: seconds per route from ``table``;
    routes absent from the table raise (probe-failure path)."""
    def timer(thunk, name):
        thunk()
        if name not in table:
            raise RuntimeError(f"no timing for {name}")
        return table[name]
    return timer


# ---------------------------------------------------------------------------
# engine basics
# ---------------------------------------------------------------------------

class TestEngine:
    def _family(self, **kw):
        return routing.Family("t", (
            routing.Route("fast",
                          predicate=lambda n, **_: n <= 64,
                          disable_env="VELES_TEST_DISABLE_FAST",
                          **kw),
            routing.Route("slow"),
        ))

    def test_table_order_is_priority(self):
        fam = self._family()
        assert fam.static_select(n=16) == "fast"
        assert fam.static_select(n=1000) == "slow"
        assert fam.eligible(n=16) == ["fast", "slow"]

    def test_env_opt_out(self, monkeypatch):
        fam = self._family()
        monkeypatch.setenv("VELES_TEST_DISABLE_FAST", "1")
        assert not fam.gate("fast", n=16)
        assert fam.static_select(n=16) == "slow"

    def test_terminal_fallback_when_all_gated(self, monkeypatch):
        fam = routing.Family("t2", (
            routing.Route("only", predicate=lambda n, **_: False),))
        assert fam.eligible(n=1) == ["only"]
        assert fam.static_select(n=1) == "only"

    def test_unknown_route_raises(self):
        fam = self._family()
        with pytest.raises(ValueError, match="route"):
            fam.route("bogus")

    def test_rejection_cache_outranks_armed_plan(self):
        rejected = set()
        fam = routing.Family("t3", (
            routing.Route("fast",
                          predicate=lambda n, **_: True,
                          fault_site="t3.fast",
                          rejection_cache=lambda: rejected,
                          rejection_key=lambda n, **_: n),
            routing.Route("slow"),
        ))
        assert fam.route_allowed("fast", n=5)
        rejected.add(5)
        assert not fam.route_allowed("fast", n=5)
        # an armed plan opens the gate — but never past the rejection
        with faults.fault_plan("t3.fast:vmem_oom:1"):
            assert not fam.route_allowed("fast", n=5)
            assert fam.route_allowed("fast", n=6)

    def test_armed_plan_opens_closed_gate(self):
        fam = routing.Family("t4", (
            routing.Route("fast", predicate=lambda n, **_: False,
                          fault_site="t4.fast"),
            routing.Route("slow"),
        ))
        assert fam.static_select(n=1) == "slow"
        with faults.fault_plan("t4.fast:vmem_oom:1"):
            assert fam.static_select(n=1) == "fast"

    def test_armed_plan_outranks_cached_winner(self, fresh_cache,
                                               monkeypatch):
        """An armed injection plan must really dispatch the doomed
        route — a tune-cache winner consulted first would bypass the
        gate the plan opened and leave the demote-and-remember path
        unexercised by CI (review finding)."""
        fam = routing.Family("t4b", (
            routing.Route("doomed", predicate=lambda n, **_: True,
                          fault_site="t4b.doomed"),
            routing.Route("safe"),
        ))
        routing.tune_cache().store("t4b", {"n": 1}, "safe")
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "readonly")
        assert fam.select(n=1) == "safe"          # cache honored...
        with faults.fault_plan("t4b.doomed:vmem_oom:1"):
            assert fam.select(n=1) == "doomed"    # ...never over a plan


    def test_family_registry(self):
        fam = routing.family("t5", (routing.Route("only"),))
        assert routing.get_family("t5") is fam
        assert "t5" in routing.families()
        with pytest.raises(ValueError, match="unknown route family"):
            routing.get_family("nope")

    def test_describe_is_json_native(self):
        fam = self._family()
        d = fam.describe()
        json.dumps(d)
        assert [r["name"] for r in d["routes"]] == ["fast", "slow"]

    def test_mode_override_is_thread_local(self, monkeypatch):
        """The supervised-worker idiom: an override set in a worker
        thread (even one abandoned mid-scope) never flips routing for
        other threads — bench stages must not poison the process."""
        import threading

        monkeypatch.delenv(routing.AUTOTUNE_ENV, raising=False)
        seen = {}

        def worker():
            with routing.autotune_mode_override("on"):
                seen["worker"] = routing.autotune_mode()
                # simulate abandonment: main thread reads while the
                # override is still active in this thread
                seen["main_during"] = None

        t = threading.Thread(target=worker)
        with routing.autotune_mode_override("readonly"):
            assert routing.autotune_mode() == "readonly"
        assert routing.autotune_mode() == "off"
        t.start()
        t.join()
        assert seen["worker"] == "on"
        assert routing.autotune_mode() == "off"
        with pytest.raises(ValueError, match="mode"):
            with routing.autotune_mode_override("bogus"):
                pass

    def test_autotune_mode_env(self, monkeypatch):
        monkeypatch.delenv(routing.AUTOTUNE_ENV, raising=False)
        assert routing.autotune_mode() == "off"
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "on")
        assert routing.autotune_mode() == "on"
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "READONLY")
        assert routing.autotune_mode() == "readonly"
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "typo")
        assert routing.autotune_mode() == "off"


# ---------------------------------------------------------------------------
# measured autotune (deterministic injected timer)
# ---------------------------------------------------------------------------

class TestMeasuredAutotune:
    def _family(self):
        return routing.Family("probe_fam", (
            routing.Route("a", predicate=lambda n, **_: True),
            routing.Route("b"),
        ))

    def test_measured_winner_beats_static_prior(self, fresh_cache,
                                                autotune_on):
        fam = self._family()
        calls = []
        runners = {"a": lambda: calls.append("a"),
                   "b": lambda: calls.append("b")}
        obs.enable()
        obs.reset()
        try:
            with routing.probe_timer(_fake_timer({"a": 9.0, "b": 2.0})):
                assert fam.select(runners=runners, n=8) == "b"
            # both candidates were actually probed (forced uniformly)
            assert set(calls) == {"a", "b"}
            ev = [e for e in obs.events() if e["op"] == "autotune"]
            assert ev and ev[-1]["decision"] == "b"
            assert ev[-1]["static"] == "a"
            assert "a=" in ev[-1]["timings"]
            assert obs.counter_value("autotune_measured",
                                     family="probe_fam") == 1
        finally:
            obs.disable()
            obs.reset()

    def test_winner_persists_and_reloads_across_processes(
            self, fresh_cache, autotune_on):
        fam = self._family()
        with routing.probe_timer(_fake_timer({"a": 9.0, "b": 2.0})):
            assert fam.select(runners={"a": lambda: 1,
                                       "b": lambda: 1}, n=8) == "b"
        # the decision landed on disk, version-stamped
        data = json.load(open(fresh_cache))
        assert data["version"] == routing.TUNE_CACHE_VERSION
        (key, entry), = data["entries"].items()
        assert key == "probe_fam|n=8" and entry["route"] == "b"
        # a NEW cache object (≈ a new process) serves the winner with
        # no probing — the timer would fail loudly if consulted
        routing.set_cache_path(None)
        with routing.probe_timer(_fake_timer({})):
            assert fam.select(runners={"a": lambda: 1,
                                       "b": lambda: 1}, n=8) == "b"
        assert routing.tune_cache().info()["hits"] >= 1

    def test_readonly_consults_but_never_probes(self, fresh_cache,
                                                monkeypatch):
        fam = self._family()
        cache = routing.TuneCache(fresh_cache)
        cache.store("probe_fam", {"n": 8}, "b", source="sweep")
        routing.set_cache_path(None)
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "readonly")

        def never(thunk, name):
            raise AssertionError("readonly mode must not probe")

        with routing.probe_timer(never):
            assert fam.select(runners={"a": lambda: 1,
                                       "b": lambda: 1}, n=8) == "b"
            # unseen geometry: the static prior, still no probe
            assert fam.select(runners={"a": lambda: 1,
                                       "b": lambda: 1}, n=9) == "a"

    def test_cached_winner_no_longer_eligible_is_ignored(
            self, fresh_cache, autotune_on):
        rejected = set()
        fam = routing.Family("probe_fam2", (
            routing.Route("a", predicate=lambda n, **_: True,
                          rejection_cache=lambda: rejected,
                          rejection_key=lambda n, **_: n),
            routing.Route("b"),
        ))
        routing.TuneCache(fresh_cache).store("probe_fam2", {"n": 8},
                                             "a")
        routing.set_cache_path(None)
        rejected.add(8)      # 'a' was demoted since the cache was cut
        # eligible is now just ['b'] -> single candidate, no probing
        assert fam.select(runners={"b": lambda: 1}, n=8) == "b"

    def test_probe_failure_skips_candidate(self, fresh_cache,
                                           autotune_on):
        fam = self._family()

        def boom():
            raise RuntimeError("candidate cannot run here")

        with routing.probe_timer(_fake_timer({"b": 1.0})):
            # 'a' raises inside the injected timer; 'b' wins
            assert fam.select(runners={"a": boom, "b": lambda: 1},
                              n=8) == "b"
        entry = routing.tune_cache().entry("probe_fam", {"n": 8})
        assert entry["route"] == "b"
        assert entry["timings_us"]["a"] is None

    def test_probe_vmem_oom_feeds_rejection_cache(self, fresh_cache,
                                                  autotune_on):
        rejected = set()
        fam = routing.Family("probe_fam3", (
            routing.Route("a", predicate=lambda n, **_: True,
                          rejection_cache=lambda: rejected,
                          rejection_key=lambda n, **_: n),
            routing.Route("b"),
        ))

        def oom():
            raise RuntimeError(
                "Ran out of memory in memory space vmem: scoped "
                "allocation with size 22.34M and limit 16.00M")

        def timer(thunk, name):
            thunk()
            return 1.0

        with routing.probe_timer(timer):
            assert fam.select(runners={"a": oom, "b": lambda: 1},
                              n=8) == "b"
        assert 8 in rejected     # demote-and-remember from the probe

    def test_all_probes_fail_returns_static(self, fresh_cache,
                                            autotune_on):
        fam = self._family()

        def boom():
            raise RuntimeError("nope")

        with routing.probe_timer(_fake_timer({})):
            assert fam.select(runners={"a": boom, "b": boom},
                              n=8) == "a"
        assert routing.tune_cache().entry("probe_fam", {"n": 8}) is None

    def test_transient_probe_failure_is_inconclusive(
            self, fresh_cache, autotune_on, monkeypatch):
        """One device hiccup during a probe must not launder the
        surviving candidate into a persisted 'measured' winner a
        readonly pack then obeys forever (review finding): the probe
        gets the same bounded retry dispatch gets, and if the fault
        persists the round is abandoned — nothing stored, the static
        prior dispatches, the next encounter re-probes."""
        monkeypatch.setenv(faults.FAULT_RETRIES_ENV, "1")
        monkeypatch.setenv(faults.FAULT_BACKOFF_ENV, "0")
        fam = self._family()
        calls = []

        def lost():
            calls.append("a")
            raise RuntimeError("UNAVAILABLE: socket closed")

        obs.enable()
        obs.reset()
        try:
            with routing.probe_timer(_fake_timer({"b": 1.0})):
                assert fam.select(runners={"a": lost,
                                           "b": lambda: 1},
                                  n=8) == "a"       # the static prior
            # retried once (the bounded budget), then abandoned
            assert len(calls) == 2
            assert routing.tune_cache().entry(
                "probe_fam", {"n": 8}) is None
            assert obs.counter_value("autotune_probe_transient",
                                     family="probe_fam",
                                     route="a") == 1
            assert not [e for e in obs.events()
                        if e["op"] == "autotune"]
        finally:
            obs.disable()
            obs.reset()

    def test_transient_probe_retry_then_success_persists(
            self, fresh_cache, autotune_on, monkeypatch):
        """A hiccup that clears within the retry budget still yields a
        measured, persisted winner."""
        monkeypatch.setenv(faults.FAULT_RETRIES_ENV, "2")
        monkeypatch.setenv(faults.FAULT_BACKOFF_ENV, "0")
        fam = self._family()
        failures = iter([True, False])

        def flaky():
            if next(failures):
                raise RuntimeError("deadline exceeded")

        with routing.probe_timer(_fake_timer({"a": 9.0, "b": 2.0})):
            assert fam.select(runners={"a": flaky, "b": lambda: 1},
                              n=8) == "b"
        entry = routing.tune_cache().entry("probe_fam", {"n": 8})
        assert entry["route"] == "b"
        assert entry["timings_us"]["a"] is not None

    def test_stale_cached_winner_never_overwritten(self, fresh_cache,
                                                   autotune_on):
        """A cached winner whose route is TEMPORARILY ineligible
        (env opt-out, demotion) must not be replaced by a re-probe of
        only the surviving candidates — one debug session's opt-out
        would permanently poison the operator's pack (review
        finding).  The static prior dispatches, the entry survives,
        and the cached winner serves again once its route returns."""
        routing.TuneCache(fresh_cache).store("probe_fam5", {"n": 8},
                                             "a")
        routing.set_cache_path(None)
        rejected = {8}
        fam = routing.Family("probe_fam5", (
            routing.Route("a", predicate=lambda n, **_: True,
                          rejection_cache=lambda: rejected,
                          rejection_key=lambda n, **_: n),
            routing.Route("b"),
            routing.Route("c"),
        ))

        def never(thunk, name):
            raise AssertionError("a stale entry must not re-probe")

        runners = {"a": lambda: 1, "b": lambda: 1, "c": lambda: 1}
        with routing.probe_timer(never):
            # 'a' demoted: >=2 candidates remain, but no probe fires
            # and the pack entry is untouched
            assert fam.select(runners=runners, n=8) == "b"
        assert routing.TuneCache(fresh_cache).lookup(
            "probe_fam5", {"n": 8}) == "a"
        rejected.clear()                 # the route comes back...
        with routing.probe_timer(never):
            assert fam.select(runners=runners, n=8) == "a"

    def test_off_mode_never_touches_cache(self, fresh_cache,
                                          monkeypatch):
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "off")
        fam = self._family()
        assert fam.select(runners={"a": lambda: 1, "b": lambda: 1},
                          n=8) == "a"
        assert not os.path.exists(fresh_cache)


# ---------------------------------------------------------------------------
# the tune cache itself
# ---------------------------------------------------------------------------

class TestTuneCache:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "c.json")
        c1 = routing.TuneCache(path)
        c1.store("fam", {"n": 4, "k": 2}, "fast",
                 timings_us={"fast": 10.0, "slow": 20.0})
        c2 = routing.TuneCache(path)
        assert c2.lookup("fam", {"k": 2, "n": 4}) == "fast"  # key order
        entry = c2.entry("fam", {"n": 4, "k": 2})
        assert entry["timings_us"] == {"fast": 10.0, "slow": 20.0}
        assert entry["source"] == "measured"

    def test_corrupt_file_degrades_to_empty(self, tmp_path):
        path = str(tmp_path / "c.json")
        with open(path, "w") as f:
            f.write("{not json")
        c = routing.TuneCache(path)
        assert c.lookup("fam", {"n": 4}) is None
        assert c.info()["load_errors"] == 1

    def test_version_mismatch_ignored_and_counted(self, tmp_path):
        path = str(tmp_path / "c.json")
        with open(path, "w") as f:
            json.dump({"version": routing.TUNE_CACHE_VERSION + 1,
                       "entries": {"fam|n=4": {"route": "fast"}}}, f)
        c = routing.TuneCache(path)
        assert c.lookup("fam", {"n": 4}) is None
        assert c.info()["version_mismatch"] == 1

    def test_malformed_entries_are_skipped(self, tmp_path):
        path = str(tmp_path / "c.json")
        with open(path, "w") as f:
            json.dump({"version": routing.TUNE_CACHE_VERSION,
                       "entries": {"fam|n=4": {"route": "ok"},
                                   "fam|n=5": "not a dict",
                                   "fam|n=6": {"no_route": 1}}}, f)
        c = routing.TuneCache(path)
        assert c.lookup("fam", {"n": 4}) == "ok"
        assert c.lookup("fam", {"n": 5}) is None
        assert c.lookup("fam", {"n": 6}) is None

    def test_missing_file_is_empty(self, tmp_path):
        c = routing.TuneCache(str(tmp_path / "absent.json"))
        assert c.lookup("fam", {"n": 4}) is None
        assert c.info()["load_errors"] == 0

    def test_device_mismatch_ignored_and_counted(self, tmp_path):
        """A pack measured on a different accelerator must degrade to
        empty — winners are device-specific (review finding).  A pack
        WITHOUT a stamp (hand-authored) is accepted."""
        path = str(tmp_path / "c.json")
        with open(path, "w") as f:
            json.dump({"version": routing.TUNE_CACHE_VERSION,
                       "device": "TPU v9 imaginary",
                       "entries": {"fam|n=4": {"route": "fast"}}}, f)
        c = routing.TuneCache(path)
        assert c.lookup("fam", {"n": 4}) is None
        assert c.info()["device_mismatch"] == 1
        # unstamped pack: accepted
        with open(path, "w") as f:
            json.dump({"version": routing.TUNE_CACHE_VERSION,
                       "entries": {"fam|n=4": {"route": "fast"}}}, f)
        assert routing.TuneCache(path).lookup("fam", {"n": 4}) == "fast"

    def test_save_stamps_this_device(self, tmp_path):
        path = str(tmp_path / "c.json")
        routing.TuneCache(path).store("fam", {"n": 1}, "r")
        data = json.load(open(path))
        assert data["device"] == routing.device_kind()

    def test_save_refuses_to_destroy_foreign_pack(self, tmp_path):
        """A valid pack stamped for another device (or schema
        version) degrades to empty on LOAD — but a store() must not
        then overwrite the file with this process's private view: a
        CPU plumbing run pointed at an operator's TPU pack would
        permanently destroy the measured winners (review finding)."""
        path = str(tmp_path / "c.json")
        foreign = {"version": routing.TUNE_CACHE_VERSION,
                   "device": "TPU v9 imaginary",
                   "entries": {"fam|n=4": {"route": "fast"}}}
        with open(path, "w") as f:
            json.dump(foreign, f)
        c = routing.TuneCache(path)
        c.store("fam", {"n": 8}, "mine")
        assert json.load(open(path)) == foreign      # untouched
        assert c.info()["save_refused"] >= 1
        assert c.lookup("fam", {"n": 8}) == "mine"   # in-memory only
        # version mismatch: same refusal
        with open(path, "w") as f:
            json.dump({"version": routing.TUNE_CACHE_VERSION + 1,
                       "entries": {"fam|n=4": {"route": "fast"}}}, f)
        c2 = routing.TuneCache(path)
        c2.store("fam", {"n": 8}, "mine")
        assert json.load(open(path))["version"] == \
            routing.TUNE_CACHE_VERSION + 1
        # a MISSING or corrupt file is still written (fresh cache)
        path3 = str(tmp_path / "fresh.json")
        routing.TuneCache(path3).store("fam", {"n": 1}, "r")
        assert json.load(open(path3))["entries"]

    def test_transient_unknown_device_does_not_pin_rejection(
            self, tmp_path, monkeypatch):
        """A device-stamped pack touched while the backend is still
        initializing (device_kind transiently "unknown") must load on
        a LATER touch — a one-shot rejection would silently run static
        routes for the process lifetime (review finding)."""
        path = str(tmp_path / "c.json")
        with open(path, "w") as f:
            json.dump({"version": routing.TUNE_CACHE_VERSION,
                       "device": routing.device_kind(),
                       "entries": {"fam|n=4": {"route": "fast"}}}, f)
        c = routing.TuneCache(path)
        monkeypatch.setattr(routing, "device_kind", lambda: "unknown")
        assert c.lookup("fam", {"n": 4}) is None   # backend down
        # deferred is NOT a rejection: no device_mismatch counted,
        # and touches inside the retry interval don't re-read
        assert c.lookup("fam", {"n": 4}) is None
        assert c.info()["device_mismatch"] == 0
        monkeypatch.undo()
        c._next_load_retry = 0.0                   # interval elapsed
        assert c.lookup("fam", {"n": 4}) == "fast"  # retried, loaded
        assert c.info()["device_mismatch"] == 0    # accepted: stays 0

    def test_eviction_drops_oldest_stamp_not_alphabetical(
            self, tmp_path, monkeypatch):
        """Eviction follows the per-entry measurement timestamp, not
        dict order — a save/reload cycle serializes sorted, which
        would otherwise make eviction alphabetical and evict the
        hottest class (review finding)."""
        entries = {"a_newest": {"route": "r", "unix": 300.0},
                   "b_oldest": {"route": "r", "unix": 100.0},
                   "c_mid": {"route": "r", "unix": 200.0}}
        monkeypatch.setattr(routing, "TUNE_CACHE_MAX_ENTRIES", 2)
        routing._evict_oldest(entries)
        assert set(entries) == {"a_newest", "c_mid"}
        # end to end across a reload: the alphabetically-FIRST key is
        # the newest and must survive the third store
        path = str(tmp_path / "c.json")
        c = routing.TuneCache(path)
        c.store("fam", {"n": 1}, "r1")
        c.store("fam", {"n": 2}, "r2")
        c2 = routing.TuneCache(path)         # sorted serialization
        c2.store("fam", {"n": 0}, "r0")      # sorts first, is newest
        assert c2.entry("fam", {"n": 1}) is None      # oldest evicted
        assert c2.entry("fam", {"n": 0})["route"] == "r0"
        assert c2.entry("fam", {"n": 2})["route"] == "r2"
        assert c2.info()["evictions"] == 1

    def test_device_kind_failure_not_cached(self, monkeypatch):
        """A transient jax.devices() failure must not pin "unknown"
        for the process lifetime — that would reject every
        device-stamped pack as a device_mismatch forever (review
        finding)."""
        real = routing._device_kind_cached
        monkeypatch.setattr(routing, "_device_kind_cached", None)
        import jax

        def boom():
            raise RuntimeError("backend not initialized")

        monkeypatch.setattr(jax, "devices", boom)
        assert routing.device_kind() == "unknown"
        assert routing._device_kind_cached is None  # NOT pinned
        monkeypatch.undo()
        monkeypatch.setattr(routing, "_device_kind_cached", None)
        assert routing.device_kind() == str(
            jax.devices()[0].device_kind)
        routing._device_kind_cached = real

    def test_concurrent_writers_merge_not_clobber(self, tmp_path):
        """Two caches sharing one path: each store merges the disk
        state instead of overwriting it with a private snapshot
        (review finding: lost updates in the exploration deployment)."""
        path = str(tmp_path / "c.json")
        a = routing.TuneCache(path)
        b = routing.TuneCache(path)   # loads (empty) before a stores
        b.lookup("famb", {"n": 1})    # force the (empty) load
        a.store("fama", {"n": 1}, "ra")
        b.store("famb", {"n": 1}, "rb")
        merged = routing.TuneCache(path)
        assert merged.lookup("fama", {"n": 1}) == "ra"
        assert merged.lookup("famb", {"n": 1}) == "rb"

    def test_memory_only_without_path(self):
        c = routing.TuneCache(None)
        c.store("fam", {"n": 1}, "r")
        assert c.lookup("fam", {"n": 1}) == "r"
        assert c.save() is None

    def test_registered_in_obs_caches(self):
        assert "autotune_cache" in obs.caches()

    def test_key_format_is_shared(self):
        assert routing.tune_key_str("stft", {"hop": 128,
                                             "frame_length": 512}) \
            == "stft|frame_length=512,hop=128"


# ---------------------------------------------------------------------------
# parity: engine selection == the pre-migration hand-rolled ladders
# ---------------------------------------------------------------------------

class TestSelectorParity:
    def test_convolve_algorithm_parity(self):
        """select_algorithm vs the frozen pre-engine ladder, across
        the geometries test_convolve pins plus a boundary sweep."""
        def frozen(x_len, h_len):
            if x_len * h_len < cv.AUTO_FFT_MIN_PRODUCT:
                return cv.ConvolutionAlgorithm.BRUTE_FORCE
            if x_len >= cv.AUTO_OVERLAP_SAVE_MIN_RATIO * h_len:
                return cv.ConvolutionAlgorithm.OVERLAP_SAVE
            return cv.ConvolutionAlgorithm.FFT

        geoms = [(16, 4), (50, 50), (100, 10), (256, 256), (350, 21),
                 (1000, 50), (2000, 950), (4096, 63), (1 << 20, 64),
                 (4096, 4096), (128, 16), (1 << 20, 2047),
                 # threshold boundaries
                 (1 << 13, 1), ((1 << 13) - 1, 1), (8 * 97, 97),
                 (8 * 97 - 1, 97)]
        for x_len, h_len in geoms:
            assert cv.select_algorithm(x_len, h_len) is \
                frozen(x_len, h_len), (x_len, h_len)

    def test_stft_selection_parity(self, monkeypatch):
        """_select_stft_route vs the frozen priority ladder, with the
        pallas gate both closed (CPU) and forced open."""
        def frozen(fl, hop, frames, pallas_ok):
            if (fl, hop) not in sp._STFT_PALLAS_REJECTED and (
                    pallas_ok and fl % hop == 0 and hop % 128 == 0
                    and fl // hop >= 2
                    and frames >= pk.PALLAS_STFT_MIN_FRAMES
                    and pk.fits_vmem_stft(fl, hop)):
                return "pallas_fused"
            if fl <= sp.AUTO_DFT_MATMUL_MAX_FRAME:
                return "rdft_matmul"
            return "xla_fft"

        geoms = [(64, 16, 500), (64, 32, 500), (64, 64, 500),
                 (65, 16, 500), (512, 128, 1000), (512, 128, 10),
                 (512, 96, 1000), (512, 64, 1000), (4096, 1024, 100),
                 (8192, 1024, 100), (16384, 2048, 100)]
        for pallas_ok in (False, True):
            if pallas_ok:
                monkeypatch.setattr(pk, "pallas_available",
                                    lambda: True)
            for fl, hop, frames in geoms:
                assert sp._select_stft_route(fl, hop, frames) == \
                    frozen(fl, hop, frames,
                           pallas_ok and pk.stft_pallas_allowed()), \
                    (fl, hop, frames, pallas_ok)

    def test_wavelet_gate_parity(self):
        """_use_pallas vs the frozen row/VMEM formula on the parity
        suite's shapes."""
        shapes = [((512, 4096), 8, 1, 2), ((8, 4_000_000), 8, 1, 2),
                  ((4, 256), 8, 1, 2), ((64, 4096), 16, 4, 1),
                  ((256,), 8, 1, 2)]
        for src_shape, order, dil, stride in shapes:
            rows = (int(np.prod(src_shape[:-1]))
                    if len(src_shape) > 1 else 1)
            n = src_shape[-1]
            want = pk.should_route(
                rows, (n + order * dil) + 2 * (n // stride))
            assert wv._use_pallas(src_shape, order, dil, stride) == \
                want, (src_shape, order, dil, stride)

    def test_conv2d_selection_parity(self):
        """select_algorithm2d (no-shape form) vs the frozen area
        ladder on CPU (pallas unavailable -> always fft) — the
        shape-aware form is pinned by test_convolve2d."""
        for k0, k1 in ((3, 3), (16, 16), (17, 17), (33, 33)):
            want = ("direct" if (pk.pallas_available()
                                 and pk.pallas2d_compiled_allowed()
                                 and k0 * k1
                                 <= pk.PALLAS_2D_MAX_KERNEL_AREA)
                    else "fft")
            assert cv2.select_algorithm2d(k0, k1) == want

    def test_every_family_is_registered(self):
        fams = routing.families()
        for name in ("convolve", "convolve.direct", "convolve.os",
                     "convolve2d", "wavelet", "wavelet.cascade",
                     "stft", "istft", "hilbert", "morlet_cwt"):
            assert name in fams, name


# ---------------------------------------------------------------------------
# wavelet route parity satellite: env opt-out + forced routes
# ---------------------------------------------------------------------------

class TestWaveletRouteParity:
    def test_disable_env_closes_the_gate(self, monkeypatch):
        src_shape, order = (512, 4096), 8
        monkeypatch.setattr(pk, "should_route", lambda *a: True)
        assert wv._use_pallas(src_shape, order, 1, 2)
        monkeypatch.setenv("VELES_SIMD_DISABLE_PALLAS_WAVELET", "1")
        assert not wv._use_pallas(src_shape, order, 1, 2)

    def test_forced_routes_match_oracle(self):
        x = RNG.randn(8, 256).astype(np.float32)
        want_hi, want_lo = wv.wavelet_apply_na(
            wv.WaveletType.DAUBECHIES, 8, wv.ExtensionType.PERIODIC, x)
        for route in ("pallas", "xla_conv"):
            hi, lo = wv.wavelet_apply(
                wv.WaveletType.DAUBECHIES, 8,
                wv.ExtensionType.PERIODIC, x, simd=True, route=route)
            np.testing.assert_allclose(np.asarray(hi), want_hi,
                                       atol=1e-4, err_msg=route)
            np.testing.assert_allclose(np.asarray(lo), want_lo,
                                       atol=1e-4, err_msg=route)

    def test_forced_swt_routes_match_oracle(self):
        x = RNG.randn(8, 256).astype(np.float32)
        want_hi, want_lo = wv.stationary_wavelet_apply_na(
            wv.WaveletType.DAUBECHIES, 8, 2,
            wv.ExtensionType.PERIODIC, x)
        for route in ("pallas", "xla_conv"):
            hi, lo = wv.stationary_wavelet_apply(
                wv.WaveletType.DAUBECHIES, 8, 2,
                wv.ExtensionType.PERIODIC, x, simd=True, route=route)
            np.testing.assert_allclose(np.asarray(hi), want_hi,
                                       atol=1e-4, err_msg=route)

    def test_bad_route_rejected(self):
        x = RNG.randn(4, 64).astype(np.float32)
        with pytest.raises(ValueError, match="route"):
            wv.wavelet_apply(wv.WaveletType.DAUBECHIES, 8,
                             wv.ExtensionType.PERIODIC, x, simd=True,
                             route="bogus")
        with pytest.raises(ValueError, match="route"):
            wv.stationary_wavelet_apply(
                wv.WaveletType.DAUBECHIES, 8, 1,
                wv.ExtensionType.PERIODIC, x, simd=True, route="bogus")

    def test_forced_route_reraises_never_degrades(self, monkeypatch):
        """A pinned route must never silently answer via the other
        implementation (the faults.guarded forced semantics)."""
        def boom(*a, **k):
            raise RuntimeError("kernel exploded")

        monkeypatch.setattr(wv, "_filter_bank_pallas", boom)
        x = RNG.randn(4, 64).astype(np.float32)
        with pytest.raises(RuntimeError, match="exploded"):
            wv.wavelet_apply(wv.WaveletType.DAUBECHIES, 8,
                             wv.ExtensionType.PERIODIC, x, simd=True,
                             route="pallas")
        # the un-forced path is untouched (the gate refuses pallas on
        # CPU, so the boom is never reached)
        wv.wavelet_apply(wv.WaveletType.DAUBECHIES, 8,
                         wv.ExtensionType.PERIODIC, x, simd=True)

    def test_forced_route_recorded(self):
        obs.enable()
        obs.reset()
        try:
            x = RNG.randn(4, 64).astype(np.float32)
            wv.wavelet_apply(wv.WaveletType.DAUBECHIES, 8,
                             wv.ExtensionType.PERIODIC, x, simd=True,
                             route="xla_conv")
            ev = [e for e in obs.events()
                  if e["op"] == "wavelet_apply"][-1]
            assert ev["decision"] == "xla_conv"
            assert ev["forced"] is True
        finally:
            obs.disable()
            obs.reset()

    def test_env_documented(self):
        guide = open(os.path.join(os.path.dirname(__file__), os.pardir,
                                  "docs", "GUIDE.md")).read()
        assert "VELES_SIMD_DISABLE_PALLAS_WAVELET" in guide
        assert "VELES_SIMD_AUTOTUNE" in guide
        assert "VELES_SIMD_AUTOTUNE_CACHE" in guide


# ---------------------------------------------------------------------------
# end-to-end: the measured winner steers a real op and survives a
# "process restart" (fresh cache object, same file)
# ---------------------------------------------------------------------------

class TestAutotunedDispatch:
    def test_stft_measured_winner_selected_persisted_reloaded(
            self, fresh_cache, autotune_on):
        """Acceptance: with VELES_SIMD_AUTOTUNE=on the measured winner
        is selected, persisted, and reloaded across processes —
        decision events + cache introspection prove it."""
        x = RNG.randn(4096).astype(np.float32)
        # static prior for frame 256 is rdft_matmul; the injected
        # timer makes xla_fft the measured winner
        timer = _fake_timer({"rdft_matmul": 5.0, "xla_fft": 1.0,
                             "pallas_fused": 9.0})
        obs.enable()
        obs.reset()
        try:
            with routing.probe_timer(timer):
                sp.stft(x, 256, 128, simd=True)
            route_ev = [e for e in obs.events()
                        if e["op"] == "stft_route"][-1]
            assert route_ev["decision"] == "xla_fft"
            tune_ev = [e for e in obs.events()
                       if e["op"] == "autotune"][-1]
            assert tune_ev["decision"] == "xla_fft"
            assert tune_ev["family"] == "stft"
            assert tune_ev["static"] == "rdft_matmul"
            # persisted...
            data = json.load(open(fresh_cache))
            keys = [k for k in data["entries"] if k.startswith("stft|")]
            assert keys and data["entries"][keys[0]]["route"] == \
                "xla_fft"
            # ...and reloaded by a fresh cache object (= new process):
            # the winner dispatches with NO probing
            routing.set_cache_path(None)
            obs.reset()
            with routing.probe_timer(_fake_timer({})):
                sp.stft(x, 256, 128, simd=True)
            route_ev = [e for e in obs.events()
                        if e["op"] == "stft_route"][-1]
            assert route_ev["decision"] == "xla_fft"
            assert not [e for e in obs.events()
                        if e["op"] == "autotune"]
            assert obs.counter_value("autotune_cache_hit",
                                     family="stft") >= 1
            info = obs.caches()["autotune_cache"]
            assert info["hits"] >= 1 and info["path"] == fresh_cache
        finally:
            obs.disable()
            obs.reset()

    def test_stft_geometry_classes_are_finite(self, fresh_cache,
                                              autotune_on):
        """Variable-length signals at one (frame, hop) share ONE tune
        entry (frames bucketed at the pallas gate threshold) — a
        length-churning service must not probe per length or grow the
        cache without bound (review finding)."""
        timer = _fake_timer({"rdft_matmul": 1.0, "xla_fft": 5.0,
                             "pallas_fused": 9.0})
        probes = []

        def counting(thunk, name):
            probes.append(name)
            return timer(thunk, name)

        with routing.probe_timer(counting):
            sp.stft(RNG.randn(4096).astype(np.float32), 256, 128,
                    simd=True)
            first = len(probes)
            assert first > 0
            # different signal length, same (frame, hop) class: the
            # cached winner serves it, no new probes, no new entry
            sp.stft(RNG.randn(8192).astype(np.float32), 256, 128,
                    simd=True)
            assert len(probes) == first
        stft_keys = [k for k in routing.tune_cache().entries()
                     if k.startswith("stft|")]
        assert len(stft_keys) == 1
        assert "frames_class=" in stft_keys[0]

    def test_private_tune_cache_shields_the_bound_pack(
            self, fresh_cache, autotune_on):
        """A measuring scope must neither consult nor overwrite the
        operator's $VELES_SIMD_AUTOTUNE_CACHE pack (review finding:
        bench's autotuned stage vs a production pack)."""
        # the bound pack has a (stale) winner...
        routing.TuneCache(fresh_cache).store("probe_pf", {"n": 1},
                                             "stale")
        routing.set_cache_path(None)
        fam = routing.Family("probe_pf", (
            routing.Route("a", predicate=lambda n, **_: True),
            routing.Route("stale"),
        ))
        with routing.probe_timer(_fake_timer({"a": 1.0,
                                              "stale": 9.0})):
            with routing.private_tune_cache() as private:
                # ...which the private scope does NOT see: it probes
                # fresh and stores locally
                assert fam.select(runners={"a": lambda: 1,
                                           "stale": lambda: 1},
                                  n=1) == "a"
                assert private.entry("probe_pf", {"n": 1})["route"] \
                    == "a"
        # and the pack on disk still holds the original entry
        assert routing.TuneCache(fresh_cache).lookup(
            "probe_pf", {"n": 1}) == "stale"

    def test_tune_geom_decouples_class_from_rejection_key(
            self, fresh_cache, autotune_on):
        """convolve2d's shape (review finding): the tune CLASS buckets
        churning dims while the rejection key stays exact.  One probe
        round serves every exact shape in the bucket, and a probe
        vmem-OOM feeds the rejection cache under the EXACT geom."""
        from veles.simd_tpu import obs
        rejected = obs.LRUSet(8)
        fam = routing.Family("probe_tg", (
            routing.Route("a", predicate=lambda n, **_: True,
                          rejection_cache=lambda: rejected,
                          rejection_key=lambda n, **_: n),
            routing.Route("b"),
        ))
        runners = {"a": lambda: 1, "b": lambda: 1}
        with routing.probe_timer(_fake_timer({"a": 1.0, "b": 9.0})):
            assert fam.select(runners=runners,
                              tune_geom={"n": 128}, n=100) == "a"
        # stored under the BUCKETED class, not the exact dims
        cache = routing.tune_cache()
        assert cache.lookup("probe_tg", {"n": 128}) == "a"
        assert cache.lookup("probe_tg", {"n": 100}) is None
        # a different exact shape in the same bucket: cache hit, no
        # second probe round (a probing timer would raise on "b")
        with routing.probe_timer(_fake_timer({})):
            assert fam.select(runners=runners,
                              tune_geom={"n": 128}, n=97) == "a"

        # probe OOM remembers the EXACT geom in the rejection cache
        def _oom():
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Ran out of memory in memory "
                "space vmem while allocating scoped")
        with routing.probe_timer(_fake_timer({"b": 1.0})):
            assert fam.select(
                runners={"a": _oom, "b": lambda: 1},
                tune_geom={"n": 256}, n=200) == "b"
        assert 200 in rejected
        assert 256 not in rejected

    def test_pow2_bucket(self):
        assert routing.pow2_bucket(0) == 0
        assert routing.pow2_bucket(1) == 1
        assert routing.pow2_bucket(2) == 2
        assert routing.pow2_bucket(3) == 4
        assert routing.pow2_bucket(1 << 20) == 1 << 20
        assert routing.pow2_bucket((1 << 20) + 1) == 1 << 21

    def test_runner_factory_only_invoked_when_probing(
            self, fresh_cache, monkeypatch):
        """The factory form: never called in off/readonly mode or for
        single-candidate dispatches (the 9 per-site mode ladders this
        replaced)."""
        fam = routing.Family("probe_fam4", (
            routing.Route("a", predicate=lambda n, **_: True),
            routing.Route("b"),
        ))

        def factory():
            raise AssertionError("factory must not be invoked")

        monkeypatch.setenv(routing.AUTOTUNE_ENV, "off")
        assert fam.select(runners=factory, n=1) == "a"
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "readonly")
        assert fam.select(runners=factory, n=1) == "a"
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "on")
        assert fam.select(eligible=["b"], runners=factory, n=1) == "b"
        # and in the probing case it IS consulted
        with routing.probe_timer(_fake_timer({"a": 2.0, "b": 1.0})):
            assert fam.select(
                runners=lambda: {"a": lambda: 1, "b": lambda: 1},
                n=1) == "b"

    def test_probe_refused_under_trace(self, fresh_cache, autotune_on):
        """probe_operand tracer check: selection under an outer jit
        returns the static prior and persists nothing."""
        import jax

        fam = routing.Family("probe_fam5", (
            routing.Route("a", predicate=lambda n, **_: True),
            routing.Route("b"),
        ))
        picked = []

        def f(v):
            picked.append(fam.select(
                runners=lambda: {"a": lambda: v, "b": lambda: v},
                probe_operand=v, n=7))
            return v

        jax.jit(f)(np.float32(1.0))
        assert picked == ["a"]
        assert routing.tune_cache().entry("probe_fam5",
                                          {"n": 7}) is None

    def test_tune_cache_is_bounded(self):
        c = routing.TuneCache(None)
        for i in range(routing.TUNE_CACHE_MAX_ENTRIES + 5):
            c.store("fam", {"n": i}, "r")
        info = c.info()
        assert info["size"] == routing.TUNE_CACHE_MAX_ENTRIES
        assert info["evictions"] == 5
        assert c.lookup("fam", {"n": 0}) is None      # oldest evicted

    def test_wavelet_measured_winner(self, fresh_cache, autotune_on,
                                     monkeypatch):
        """The wavelet family really probes under the measured mode
        (review finding: runners were never wired)."""
        monkeypatch.setattr(pk, "should_route", lambda *a: True)
        x = RNG.randn(8, 256).astype(np.float32)
        with routing.probe_timer(_fake_timer({"pallas": 9.0,
                                              "xla_conv": 1.0})):
            wv.wavelet_apply(wv.WaveletType.DAUBECHIES, 8,
                             wv.ExtensionType.PERIODIC, x, simd=True)
        entry = routing.tune_cache().entry(
            "wavelet", {"rows": 8, "n": 256, "order": 8,
                        "dilation": 1, "stride": 2})
        assert entry is not None and entry["route"] == "xla_conv"

    def test_off_mode_is_bit_identical_static(self, monkeypatch):
        """The default mode must reproduce the static prior exactly
        (the parity acceptance: env opt-outs and selector decisions
        are unchanged pre/post engine migration)."""
        monkeypatch.delenv(routing.AUTOTUNE_ENV, raising=False)
        obs.enable()
        obs.reset()
        try:
            x = RNG.randn(4096).astype(np.float32)
            sp.stft(x, 256, 128, simd=True)
            ev = [e for e in obs.events()
                  if e["op"] == "stft_route"][-1]
            assert ev["decision"] == sp._select_stft_route(
                256, 128, sp.frame_count(4096, 256, 128))
            assert not [e for e in obs.events()
                        if e["op"] == "autotune"]
        finally:
            obs.disable()
            obs.reset()

    def test_hilbert_autotune_probe_runs_real_candidates(
            self, fresh_cache, autotune_on):
        """The probe thunks run the REAL route runners (device calls),
        so a winner is always a route that actually worked here."""
        x = RNG.randn(300).astype(np.float32)
        with routing.probe_timer(_fake_timer({"matmul_dft": 2.0,
                                              "xla_fft": 1.0})):
            got = sp.hilbert(x, simd=True)
        np.testing.assert_allclose(np.asarray(got),
                                   sp.hilbert_na(x).astype(
                                       np.complex64).real
                                   + 1j * sp.hilbert_na(x).astype(
                                       np.complex64).imag,
                                   atol=1e-3)
        # stored under the pow2-bucketed CLASS (n=300 -> 512), not
        # the exact length — length churn shares finite entries
        entry = routing.tune_cache().entry(
            "hilbert", {"n": routing.pow2_bucket(300), "rows": 1})
        assert entry["route"] == "xla_fft"
        # another length in the same bucket: cache hit, no re-probe
        # (a probing timer would raise on the empty table)
        with routing.probe_timer(_fake_timer({})):
            sp.hilbert(RNG.randn(400).astype(np.float32), simd=True)
        keys = [k for k in routing.tune_cache().entries()
                if k.startswith("hilbert|")]
        assert len(keys) == 1

    def test_batched_stft_honors_pack_winner(self, fresh_cache,
                                             monkeypatch):
        """batched_stft routes through the SAME engine selection as
        stft, so a pack winner steers both entry points (review
        finding: the batched path used the static prior only)."""
        from veles.simd_tpu.ops import batched as bt
        frames = sp.frame_count(4096, 512, 128)
        static = sp._select_stft_route(512, 128, frames)
        assert static == "rdft_matmul"
        routing.tune_cache().store(
            "stft", sp._stft_tune_class(512, 128, frames, rows=4),
            "xla_fft")
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "readonly")
        assert sp._stft_route_for(512, 128, frames, 4) == "xla_fft"
        x = RNG.randn(4, 4096).astype(np.float32)
        before = routing.tune_cache().info()["hits"]
        got = bt.batched_stft(x, 512, 128)
        assert routing.tune_cache().info()["hits"] > before
        np.testing.assert_allclose(
            np.asarray(got), sp.stft_na(x, 512, 128), atol=1e-3)
        # off mode: back to the static prior
        monkeypatch.setenv(routing.AUTOTUNE_ENV, "off")
        assert sp._stft_route_for(512, 128, frames, 4) == \
            "rdft_matmul"


# ---------------------------------------------------------------------------
# mesh-keyed tune classes (PR 8): the topology stamp — a 4-chip winner
# must never steer an 8-chip dispatch
# ---------------------------------------------------------------------------

class _FakeMesh:
    def __init__(self, shape):
        self.shape = shape
        self.axis_names = tuple(shape)


class TestMeshStamp:
    def test_mesh_class_token(self):
        m = _FakeMesh({"dp": 2, "sp": 4})
        assert routing.mesh_class(m) == "dp2xsp4"
        assert routing.mesh_class(m, "sp") == "dp2xsp4@sp"

    def test_mesh_token_separates_tune_keys(self):
        g4 = {"op": "rfft", "n": 4096,
              "mesh": routing.mesh_class(_FakeMesh({"sp": 4}), "sp")}
        g8 = {"op": "rfft", "n": 4096,
              "mesh": routing.mesh_class(_FakeMesh({"sp": 8}), "sp")}
        assert routing.tune_key_str("parallel.fourier", g4) != \
            routing.tune_key_str("parallel.fourier", g8)

    def test_lookup_distrusts_other_topology_stamp(self, tmp_path):
        """An entry stamped for another mesh is consulted-not-trusted:
        counted as mesh_mismatch, served as a miss (the hand-authored
        pack case where the key itself lacks the mesh token)."""
        cache = routing.TuneCache(str(tmp_path / "t.json"))
        cache.store("parallel.fourier", {"n": 4096}, "sharded_matmul_dft",
                    mesh="sp4@sp")
        assert cache.lookup("parallel.fourier", {"n": 4096},
                            mesh="sp4@sp") == "sharded_matmul_dft"
        assert cache.lookup("parallel.fourier", {"n": 4096},
                            mesh="sp8@sp") is None
        info = cache.info()
        assert info["mesh_mismatch"] == 1
        # unstamped entries stay accepted (like an unstamped device)
        cache.store("parallel.fourier", {"n": 512}, "local_fft")
        assert cache.lookup("parallel.fourier", {"n": 512},
                            mesh="sp8@sp") == "local_fft"

    def test_store_refuses_cross_mesh_overwrite(self, tmp_path):
        """A store that would replace an entry stamped for a DIFFERENT
        topology is refused and counted (mesh_refused) — the save-side
        twin of save_refused: clobbering another mesh's measured
        winner would be permanent."""
        cache = routing.TuneCache(str(tmp_path / "t.json"))
        cache.store("parallel.fourier", {"n": 4096},
                    "sharded_matmul_dft", mesh="sp8@sp")
        cache.store("parallel.fourier", {"n": 4096}, "local_fft",
                    mesh="sp4@sp")
        assert cache.info()["mesh_refused"] == 1
        assert cache.entry("parallel.fourier",
                           {"n": 4096})["route"] == "sharded_matmul_dft"
        # same-mesh re-store still updates (fresh measurements win)
        cache.store("parallel.fourier", {"n": 4096}, "local_fft",
                    mesh="sp8@sp")
        assert cache.entry("parallel.fourier",
                           {"n": 4096})["route"] == "local_fft"

    def test_select_threads_mesh_stamp_through(self, fresh_cache,
                                               autotune_on):
        """Family.select(mesh=...) stamps the measured winner's entry
        and distrusts a cached winner stamped for another mesh."""
        fam = routing.Family("probe_mesh", (
            routing.Route("a", predicate=lambda n, **_: True),
            routing.Route("b"),
        ))
        with routing.probe_timer(_fake_timer({"a": 9.0, "b": 1.0})):
            got = fam.select(runners={"a": lambda: 1, "b": lambda: 1},
                             mesh="sp8@sp", n=1)
        assert got == "b"
        entry = routing.tune_cache().entry("probe_mesh", {"n": 1})
        assert entry["mesh"] == "sp8@sp"
        # a different topology refuses the stamped winner: probes anew
        probes = []

        def counting(thunk, name):
            probes.append(name)
            thunk()
            return {"a": 1.0, "b": 9.0}[name]

        with routing.probe_timer(counting):
            got4 = fam.select(runners={"a": lambda: 1,
                                       "b": lambda: 1},
                              mesh="sp4@sp", n=1)
        assert probes and got4 == "a"
