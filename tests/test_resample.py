"""Resample family: polyphase rational resampling + Fourier method.

Patterns per SURVEY.md §4: XLA-vs-oracle cross-validation (the XLA path
is a dilated conv, the oracle an explicit zero-stuff + convolve — two
genuinely different algorithms), analytic goldens, sweeps, contracts.
"""

import numpy as np
import pytest

from veles.simd_tpu.ops import resample as rs

RNG = np.random.RandomState(23)


def _rel(got, want):
    got, want = np.asarray(got, np.float64), np.asarray(want, np.float64)
    scale = np.max(np.abs(want)) or 1.0
    return np.max(np.abs(got - want)) / scale


# ---------------------------------------------------------------- oracle


@pytest.mark.parametrize("up,down", [
    (1, 2), (2, 1), (3, 2), (2, 3), (4, 1), (1, 4), (5, 3), (160, 147),
])
def test_poly_vs_oracle(up, down):
    x = RNG.randn(730).astype(np.float32)
    got = np.asarray(rs.resample_poly(x, up, down, simd=True))
    want = rs.resample_poly_na(x, up, down)
    assert got.shape == want.shape
    assert got.shape[-1] == rs.resample_length(730, up, down)
    assert _rel(got, want) < 1e-4


def test_poly_batched():
    x = RNG.randn(3, 4, 256).astype(np.float32)
    got = np.asarray(rs.resample_poly(x, 3, 4, simd=True))
    want = rs.resample_poly_na(x, 3, 4)
    assert got.shape == want.shape == (3, 4, 192)
    assert _rel(got, want) < 1e-4


@pytest.mark.parametrize("num", [100, 128, 333, 512, 1024])
def test_fourier_vs_oracle(num):
    x = RNG.randn(2, 512).astype(np.float32)
    got = np.asarray(rs.resample_fourier(x, num, simd=True))
    want = rs.resample_fourier_na(x, num)
    assert got.shape == want.shape == (2, num)
    assert _rel(got, want) < 1e-4


# ---------------------------------------------------------------- golden


def test_dc_gain():
    """Resampling a constant stays that constant (interior)."""
    x = np.full(400, 3.5, np.float32)
    for up, down in ((2, 1), (1, 2), (3, 2)):
        y = np.asarray(rs.resample_poly(x, up, down, simd=True))
        core = y[40:-40]
        # ~1.2e-3 ripple is the windowed-sinc polyphase-branch imbalance
        # (same order as scipy.signal.resample_poly's default filter)
        np.testing.assert_allclose(core, 3.5, rtol=3e-3)


def test_tone_upsample_golden():
    """Upsampling a bandlimited tone reproduces the dense samples."""
    n, up = 512, 4
    f = 11 / n  # cycles per (input) sample, far below Nyquist
    t_in = np.arange(n)
    x = np.cos(2 * np.pi * f * t_in).astype(np.float32)
    y = np.asarray(rs.upsample(x, up, simd=True))
    t_out = np.arange(n * up) / up
    want = np.cos(2 * np.pi * f * t_out)
    sl = slice(20 * up, -20 * up)  # skip filter edge transients
    np.testing.assert_allclose(y[sl], want[sl], atol=5e-3)


def test_tone_decimate_golden():
    """Anti-aliased decimation of a slow tone keeps the tone."""
    n, down = 2048, 4
    f = 5 / n
    x = np.cos(2 * np.pi * f * np.arange(n)).astype(np.float32)
    y = np.asarray(rs.decimate(x, down, simd=True))
    want = np.cos(2 * np.pi * f * down * np.arange(n // down))
    sl = slice(40, -40)
    np.testing.assert_allclose(y[sl], want[sl], atol=5e-3)


def test_fourier_bandlimited_exact():
    """Fourier upsampling of a bandlimited periodic signal is exact."""
    n, num = 256, 1024
    t = np.arange(n)
    x = (np.cos(2 * np.pi * 7 * t / n)
         + 0.3 * np.sin(2 * np.pi * 19 * t / n)).astype(np.float32)
    y = np.asarray(rs.resample_fourier(x, num, simd=True))
    tt = np.arange(num) * n / num
    want = np.cos(2 * np.pi * 7 * tt / n) + 0.3 * np.sin(2 * np.pi * 19
                                                         * tt / n)
    np.testing.assert_allclose(y, want, atol=1e-4)


def test_fourier_downsample_inverts_upsample():
    x = RNG.randn(256).astype(np.float32)
    up = np.asarray(rs.resample_fourier(x, 1024, simd=True))
    back = np.asarray(rs.resample_fourier(up, 256, simd=True))
    np.testing.assert_allclose(back, x, atol=1e-4)


def test_gcd_reduction():
    """up/down reduce by their gcd: 4/2 == 2/1."""
    x = RNG.randn(300).astype(np.float32)
    a = np.asarray(rs.resample_poly(x, 4, 2, simd=True))
    b = np.asarray(rs.resample_poly(x, 2, 1, simd=True))
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_identity():
    x = RNG.randn(100).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(rs.resample_poly(x, 3, 3, simd=True)), x, atol=0)


# ------------------------------------------------------------ filter/api


def test_design_lowpass_response():
    """Windowed-sinc: unit DC gain, strong stopband rejection."""
    h = rs.design_lowpass(161, 0.25)
    w = np.fft.rfftfreq(4096) * 2  # in Nyquist units
    H = np.abs(np.fft.rfft(h, 4096))
    assert abs(H[0] - 1.0) < 1e-12
    passband = H[w < 0.15]
    stopband = H[w > 0.35]
    assert passband.min() > 0.99
    assert stopband.max() < 1e-3


def test_custom_taps():
    x = RNG.randn(200).astype(np.float32)
    taps = 2 * rs.design_lowpass(31, 0.5)
    got = np.asarray(rs.resample_poly(x, 2, 1, taps=taps, simd=True))
    want = rs.resample_poly_na(x, 2, 1, taps=taps)
    assert _rel(got, want) < 1e-4


def test_contract_violations():
    x = np.zeros(64, np.float32)
    with pytest.raises(ValueError):
        rs.resample_poly(x, 0, 1)
    with pytest.raises(ValueError):
        rs.resample_poly(x, 2, 1, taps=np.ones(4))  # even-length taps
    with pytest.raises(ValueError):
        rs.resample_fourier(x, 0)
    with pytest.raises(ValueError):
        rs.design_lowpass(0, 0.5)
    with pytest.raises(ValueError):
        rs.design_lowpass(11, 1.5)
    with pytest.raises(ValueError):
        rs.resample_poly(np.zeros(0, np.float32), 2, 1)


def test_resample_length():
    assert rs.resample_length(100, 2, 1) == 200
    assert rs.resample_length(100, 1, 3) == 34   # ceil
    assert rs.resample_length(147, 160, 147) == 160


@pytest.mark.parametrize("up,down", [(2, 1), (1, 2), (3, 2), (160, 147)])
def test_edge_semantics_full_range(up, down):
    """Zero-extension edge behavior, pinned over the FULL output range
    (round-3 review: interior-only comparisons left the edges
    untested).  The XLA path and the float64 oracle share the same
    zero-extension, so they must agree everywhere — including the
    filter-length/2 roll-off region at each end — at f32 accuracy, for
    both the default and a custom filter."""
    x = RNG.randn(3, 400).astype(np.float32)
    got = np.asarray(rs.resample_poly(x, up, down, simd=True))
    want = rs.resample_poly_na(x, up, down)
    np.testing.assert_allclose(got, want, atol=2e-5)
    taps = up * rs.design_lowpass(41, 1.0 / max(up, down))
    got = np.asarray(rs.resample_poly(x, up, down, taps=taps, simd=True))
    want = rs.resample_poly_na(x, up, down, taps=taps)
    np.testing.assert_allclose(got, want, atol=2e-5)


@pytest.mark.parametrize("up,down", [(2, 1), (1, 2), (3, 2), (160, 147)])
def test_edge_semantics_match_scipy_same_filter(up, down):
    """With the SAME filter, scipy.signal.resample_poly agrees with the
    oracle to float64 round-off over the full range — the edge
    semantics (zero-extension, group-delay trim) are identical; the
    documented interior ~1e-3 deviation is purely the default filter
    design (Hamming sinc here vs scipy's Kaiser)."""
    from scipy import signal as ss

    x = RNG.randn(400).astype(np.float32)
    taps = rs._resample_taps(up, down, None)
    want = ss.resample_poly(x.astype(np.float64), up, down,
                            window=taps / up)  # scipy scales by up
    got = rs.resample_poly_na(x, up, down)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=1e-12)


class TestUpfirdn:
    """The raw polyphase primitive vs scipy.signal.upfirdn."""

    @pytest.mark.parametrize("up,down,k", [(1, 1, 7), (3, 1, 11),
                                           (1, 4, 9), (7, 3, 21),
                                           (2, 5, 32)])
    def test_matches_scipy(self, up, down, k):
        from scipy import signal as ss

        x = RNG.randn(200).astype(np.float32)
        h = RNG.randn(k)
        got = np.asarray(rs.upfirdn(h, x, up, down, simd=True))
        want = ss.upfirdn(h, x.astype(np.float64), up, down)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, atol=1e-4)
        np.testing.assert_allclose(rs.upfirdn_na(h, x, up, down), want,
                                   atol=1e-12)

    def test_batched(self):
        from scipy import signal as ss

        x = RNG.randn(3, 100).astype(np.float32)
        h = RNG.randn(15)
        got = np.asarray(rs.upfirdn(h, x, 2, 3, simd=True))
        for i in range(3):
            np.testing.assert_allclose(
                got[i], ss.upfirdn(h, x[i].astype(np.float64), 2, 3),
                atol=1e-4)

    def test_identity(self):
        x = RNG.randn(64).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(rs.upfirdn([1.0], x)), x, atol=0)

    def test_contracts(self):
        with pytest.raises(ValueError, match="up and down"):
            rs.upfirdn([1.0], np.zeros(8, np.float32), 0, 1)
        with pytest.raises(ValueError, match="1D filter"):
            rs.upfirdn(np.zeros((2, 2)), np.zeros(8, np.float32))
        with pytest.raises(ValueError, match="empty"):
            rs.upfirdn([1.0], np.zeros(0, np.float32))


class TestDecimateIIR:
    def test_matches_scipy_default(self):
        from scipy import signal as ss

        x = RNG.randn(800).astype(np.float32)
        got = np.asarray(rs.decimate(x, 4, ftype="iir", simd=True))
        want = ss.decimate(x.astype(np.float64), 4)  # scipy's default
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, atol=5e-4)

    def test_causal(self):
        from scipy import signal as ss

        x = RNG.randn(600).astype(np.float32)
        got = np.asarray(rs.decimate(x, 3, ftype="iir",
                                     zero_phase=False, simd=True))
        want = ss.decimate(x.astype(np.float64), 3, zero_phase=False)
        np.testing.assert_allclose(got, want, atol=5e-4)

    def test_contracts(self):
        with pytest.raises(ValueError, match="ftype"):
            rs.decimate(np.zeros(64, np.float32), 2, ftype="butter")
        with pytest.raises(ValueError, match="taps"):
            rs.decimate(np.zeros(64, np.float32), 2, ftype="iir",
                        taps=np.ones(5))
