"""Root pytest config: run the suite on a virtual 8-device CPU mesh.

Must run before any jax backend is initialized: forces the CPU platform
with 8 virtual devices so the multi-chip sharding paths
(veles/simd_tpu/parallel) compile and execute without TPU hardware,
mirroring how the driver validates ``__graft_entry__.dryrun_multichip``.
The axon TPU plugin (registered by a sitecustomize on PYTHONPATH) pins
the platform before env vars are consulted, so the pin goes through
jax.config — see ``veles.simd_tpu.utils.platform``, the single home for
that logic.  Per-op TPU validation happens in ``bench.py --check`` on the
real chip instead.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from veles.simd_tpu.utils.platform import pin_cpu  # noqa: E402

pin_cpu(8)
