"""Ragged segment packing: many short signals in one padded dispatch.

The serving stack's shape classing (:mod:`veles.simd_tpu.serve.batcher`)
pads every request up to its pow-of-two bucket — at saturation under
mixed-length traffic that padding is pure discarded MXU time, and since
the goodput accounting landed it is a *measured* quantity
(``serve_padding_rows`` / ``serve.padding_waste``).  This module
recovers it along the **sample axis**: several short requests are
concatenated into one packed row with a segment plan (offsets +
per-segment extents — the flat representation of a segment-ID mask),
dispatched as ONE batched call, and sliced back per segment.

Two ops are naturally segment-parallel and ride here first:

* **stft** — frame-DFT routes are per-frame: frame ``f`` of segment
  ``i`` at packed offset ``off_i`` is packed frame ``off_i/hop + f``
  with bitwise-identical contents, provided offsets are hop multiples
  (each segment's packed stride is ``ceil(n_i/hop)*hop``).  Frames
  that straddle into a neighbor are computed and *discarded* — no
  guard samples needed.
* **convolve** — direct-form outputs are per-sample MAC windows of
  width ``m`` (the overlap-save halo math): a guard gap of ``m-1``
  zeros between segments makes output slice ``[off_i, off_i+n_i+m-1)``
  depend on segment ``i``'s samples (plus exact zeros) only.

Both give **bit-equal** per-segment results versus the unpacked
dispatch of the same core (extra terms are exact ``0.0``s; the
reduction over the contracted dimension is order-identical) — the
parity gate in ``tests/test_segments.py`` pins this.

Fault semantics per packed batch: the whole dispatch runs behind
``faults.breaker_guarded`` on the ``segments.dispatch`` site.  When
the packed dispatch exhausts its retries the fallback is NOT a whole-
batch oracle — it re-dispatches **per segment** (``segments.segment``
site, zero retries), so one poisoned segment degrades to its oracle
alone while co-packed neighbors still get device answers: one bad
ticket must never drag its neighbors down with it.

Route selection goes through the ``segments`` candidate table
(:func:`veles.simd_tpu.runtime.routing.family`) — the lint rule
``segment_dispatch`` enforces that any ``packed_*`` entry point
consults the table and dispatches through the fault policy; call
sites must not hand-roll packing.
"""

from __future__ import annotations

import numpy as np

from veles.simd_tpu import obs
from veles.simd_tpu.ops import batched
from veles.simd_tpu.ops import convolve as cv
from veles.simd_tpu.ops import spectral as sp
from veles.simd_tpu.runtime import faults, routing
from veles.simd_tpu.utils.config import resolve_simd

__all__ = [
    "plan_pack", "stft_stride", "convolve_stride",
    "packed_stft", "packed_convolve",
]


# Candidate table for the segment-packed dispatch shapes.  Routes key
# which packing geometry applies (frame-aligned for the frame-DFT ops,
# guard-gapped for MAC-window ops); the terminal route doubles as the
# table's fallback so selection never dead-ends.
_SEG_FAMILY = routing.family("segments", (
    routing.Route(
        "stft_pack",
        predicate=lambda op, **_: op == "stft",
        doc="hop-aligned concatenation, straddle frames discarded "
            "(per-frame DFT routes need no guard samples)"),
    routing.Route(
        "convolve_pack",
        doc="guard gap of m-1 zeros between segments; direct-form "
            "MAC windows never cross a gap"),
))

_PACK_OPS = ("stft", "convolve")


def _select_pack_route(op: str) -> str:
    """The packing-geometry route for ``op``, from the ``segments``
    candidate table (single home of the packing layouts)."""
    if op not in _PACK_OPS:
        raise ValueError(f"op must be one of {_PACK_OPS}, got {op!r}")
    return _SEG_FAMILY.static_select(op=str(op))


def stft_stride(n: int, hop: int) -> int:
    """Packed stride of a length-``n`` stft segment: ``n`` rounded up
    to a hop multiple, so every segment offset is a hop multiple and
    packed frame ``off/hop + f`` is exactly local frame ``f``."""
    n, hop = int(n), int(hop)
    return -(-n // hop) * hop


def convolve_stride(n: int, m: int) -> int:
    """Packed stride of a length-``n`` convolve segment against an
    ``m``-tap filter: the segment plus its ``m-1``-zero guard gap (the
    overlap-save halo width — a full-convolution output window never
    reaches past it)."""
    return int(n) + int(m) - 1


def plan_pack(strides, width: int | None = None) -> tuple:
    """First-fit-decreasing packing of segment ``strides`` into rows
    of a common ``width``; returns ``(width, rows, placements)`` with
    ``placements[i] = (row, offset)`` in segment order.

    ``width`` defaults to the pow-of-two bucket of the largest stride
    (:func:`~veles.simd_tpu.runtime.routing.pow2_bucket` — the same
    classing the serve buckets use, so the compiled-geometry set stays
    logarithmic): short segments co-pack several to a row while the
    longest still fits, which is exactly the mixed-length case where
    bucket padding wastes the most.  Placement order is largest-first
    (the classic FFD fill bound — shortest segments slot into the
    gaps the long ones leave) but ties and the returned placements
    stay in segment order, so the plan is deterministic; latency is
    unaffected because every co-packed segment answers with the same
    dispatch anyway."""
    strides = [int(s) for s in strides]
    if any(s < 1 for s in strides):
        raise ValueError("strides must be positive")
    if not strides:
        return 0, 0, []
    need = max(strides)
    width = routing.pow2_bucket(need) if width is None else int(width)
    if width < need:
        raise ValueError(
            f"width {width} < largest segment stride {need}")
    order = sorted(range(len(strides)), key=lambda i: -strides[i])
    fill: list = []
    placements: list = [None] * len(strides)
    for i in order:
        s = strides[i]
        for row, used in enumerate(fill):
            if used + s <= width:
                placements[i] = (row, used)
                fill[row] = used + s
                break
        else:
            placements[i] = (len(fill), 0)
            fill.append(s)
    return width, len(fill), placements


def _as_segments(segments) -> list:
    segs = []
    segments = list(segments)
    if not segments:
        raise ValueError("need at least one segment to pack")
    for i, s in enumerate(segments):
        s = np.asarray(s, np.float32)
        if s.ndim != 1 or s.shape[0] < 1:
            raise ValueError(
                f"segment {i} must be a nonempty 1-D signal, got "
                f"shape {s.shape}")
        segs.append(s)
    return segs


def _salvage_per_segment(segs, device_one, oracle_one):
    """The packed dispatch's degradation path: re-dispatch each
    segment ALONE on the device (``segments.segment`` site, zero
    retries — the packed attempt already spent the retry budget), each
    falling to its own oracle independently.  Returns ``(outputs,
    degraded_flags)`` — only the segments that actually landed on the
    oracle are flagged, so one poisoned segment never degrades its
    co-packed neighbors' tickets."""
    outs, flags = [], []
    for i, seg in enumerate(segs):
        box = {"degraded": False}

        def oracle(seg=seg, box=box):
            box["degraded"] = True
            return oracle_one(seg)

        out = faults.guarded(
            "segments.segment",
            lambda seg=seg: device_one(seg),
            fallback=oracle, fallback_name="oracle",
            retries=0, subsite=str(i))
        outs.append(np.asarray(out))
        flags.append(box["degraded"])
    return outs, flags


def packed_stft(segments, frame_length: int, hop: int, window=None,
                simd=None, *, key=None, budget_s=None, on_fault=None,
                width: int | None = None) -> tuple:
    """STFT of variable-length ``segments`` packed along the sample
    axis into shared rows — ONE batched dispatch for the whole ragged
    set.  Returns ``(outputs, degraded)``: ``outputs[i]`` is complex64
    ``[frames_i, bins]`` (bit-equal to the unpacked
    :func:`~veles.simd_tpu.ops.batched.batched_stft` of segment ``i``
    under the same route), ``degraded[i]`` True iff segment ``i`` was
    answered by its oracle after the fault policy gave up on it.

    ``key`` namespaces the ``segments.dispatch`` circuit breaker (the
    server passes its replica-prefixed shape-class key); ``budget_s``
    bounds the retry loop; ``on_fault`` observes retry/degrade
    decisions (the server fans it out to co-batched request traces).
    """
    frame_length, hop = int(frame_length), int(hop)
    segs = _as_segments(segments)
    for s in segs:
        sp._check_stft_args(s.shape[0], frame_length, hop)
    window = sp._resolve_window(window, frame_length)
    if not segs:
        return [], []
    route = _select_pack_route("stft")
    if not resolve_simd(simd, op="packed_stft"):
        return ([sp.stft_na(s, frame_length, hop, window)
                 .astype(np.complex64) for s in segs],
                [False] * len(segs))
    strides = [stft_stride(s.shape[0], hop) for s in segs]
    width, rows, placements = plan_pack(strides, width=width)
    # EXACT rows, no pow2 row padding: the whole point of packing is
    # a truthful dispatched footprint (rows x width IS what runs);
    # the row-count spread per width is <= max_batch, so the compiled
    # geometry set stays bounded
    packed = np.zeros((rows, width), np.float32)
    for s, (row, off) in zip(segs, placements):
        packed[row, off:off + s.shape[0]] = s
    fcounts = [sp.frame_count(s.shape[0], frame_length, hop)
               for s in segs]

    def device():
        with obs.span("segments.pack.dispatch", op="stft",
                      route=route, rows=rows, width=width,
                      segments=len(segs)):
            ys = np.asarray(batched.batched_stft(
                packed, frame_length, hop, window=window, simd=True))
        return ([np.ascontiguousarray(
                    ys[row, off // hop: off // hop + fc])
                 for (row, off), fc in zip(placements, fcounts)],
                [False] * len(segs))

    def salvage():
        return _salvage_per_segment(
            segs,
            device_one=lambda seg: batched.batched_stft(
                seg[None, :], frame_length, hop, window=window,
                simd=True)[0],
            oracle_one=lambda seg: sp.stft_na(
                seg, frame_length, hop, window).astype(np.complex64))

    return faults.breaker_guarded(
        "segments.dispatch",
        key if key is not None else ("stft", frame_length, hop, width),
        device, fallback=salvage, fallback_name="per_segment",
        subsite="stft", budget_s=budget_s, on_fault=on_fault)


def packed_convolve(segments, h, simd=None, *, key=None, budget_s=None,
                    on_fault=None, width: int | None = None) -> tuple:
    """Full convolution of variable-length ``segments`` against one
    filter ``h``, packed along the sample axis with ``m-1``-zero guard
    gaps — ONE direct-form dispatch for the whole ragged set.  Returns
    ``(outputs, degraded)``: ``outputs[i]`` is float32
    ``[n_i + m - 1]`` (bit-equal to the unpacked direct-form convolve
    of segment ``i``), ``degraded`` as in :func:`packed_stft`.

    The packed rows always run the direct-form core (per-output MAC
    windows respect the guard gaps exactly; the FFT method is global
    over a row and would leak neighbor rounding into a segment's
    samples, so it is never used here)."""
    import jax.numpy as jnp

    h = np.asarray(h, np.float32)
    if h.ndim != 1 or h.shape[0] < 1:
        raise ValueError(f"h must be a nonempty 1-D filter, got "
                         f"shape {h.shape}")
    m = int(h.shape[0])
    segs = _as_segments(segments)
    if not segs:
        return [], []
    route = _select_pack_route("convolve")
    if not resolve_simd(simd, op="packed_convolve"):
        return ([cv.convolve_na(s, h) for s in segs],
                [False] * len(segs))
    strides = [convolve_stride(s.shape[0], m) for s in segs]
    width, rows, placements = plan_pack(strides, width=width)
    # exact rows, same rationale as packed_stft
    packed = np.zeros((rows, width), np.float32)
    for s, (row, off) in zip(segs, placements):
        packed[row, off:off + s.shape[0]] = s
    h_dev = jnp.asarray(h)

    def device():
        with obs.span("segments.pack.dispatch", op="convolve",
                      route=route, rows=rows, width=width,
                      segments=len(segs)):
            ys = np.asarray(cv._direct(jnp.asarray(packed), h_dev))
        return ([np.ascontiguousarray(
                    ys[row, off:off + s.shape[0] + m - 1])
                 for s, (row, off) in zip(segs, placements)],
                [False] * len(segs))

    def salvage():
        return _salvage_per_segment(
            segs,
            device_one=lambda seg: np.asarray(
                cv._direct(jnp.asarray(seg[None, :]), h_dev))[0],
            oracle_one=lambda seg: cv.convolve_na(seg, h))

    return faults.breaker_guarded(
        "segments.dispatch",
        key if key is not None else ("convolve", m, width),
        device, fallback=salvage, fallback_name="per_segment",
        subsite="convolve", budget_s=budget_s, on_fault=on_fault)
