#!/usr/bin/env python
"""Matched-filter pulse detection — the flagship end-to-end pipeline.

Plants a known pulse in noise, normalizes, cross-correlates with the
template (handle auto-selects overlap-save for this geometry), and reads
the pulse position off the correlation peak — the workflow the
reference's convolve/correlate/normalize/detect_peaks ops exist for,
here in one XLA program on the TPU.

Run:  python examples/matched_filter.py
      VELES_SIMD_PLATFORM=cpu python examples/matched_filter.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))

from veles.simd_tpu.utils.platform import maybe_override_platform

maybe_override_platform()

from veles.simd_tpu.ops import correlate as cr  # noqa: E402
from veles.simd_tpu.ops import detect_peaks as dp  # noqa: E402
from veles.simd_tpu.ops import normalize as nz  # noqa: E402


def main():
    rng = np.random.RandomState(0)
    n, k, planted_at = 1 << 20, 2047, 424242

    template = rng.randn(k).astype(np.float32)
    signal = 0.5 * rng.randn(n).astype(np.float32)
    signal[planted_at:planted_at + k] += template

    # normalize the signal to [-1, 1] (minmax1D + scale, ops/normalize)
    mn, mx = nz.minmax1D(signal)
    signal_n = ((signal - mn) / (mx - mn) * 2 - 1).astype(np.float32)

    # matched filter: cross-correlation, algorithm auto-selected
    handle = cr.cross_correlate_initialize(n, k)
    corr = np.asarray(cr.cross_correlate(handle, signal_n, template))
    print(f"algorithm: {handle.algorithm.value}")

    # the peak of the correlation marks the pulse end
    peak = int(np.argmax(corr))
    found = peak - (k - 1)
    print(f"planted at {planted_at}, matched filter says {found}")

    # local-extrema view of the correlation around the match
    pos, vals = dp.detect_peaks(corr.astype(np.float32),
                                dp.ExtremumType.MAXIMUM)
    strongest = pos[np.argmax(vals)]
    print(f"strongest local maximum at {int(strongest) - (k - 1)}")

    assert found == planted_at, (found, planted_at)
    assert int(strongest) - (k - 1) == planted_at
    print("ok")


if __name__ == "__main__":
    main()
