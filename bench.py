#!/usr/bin/env python
"""Benchmark harness: reference workloads on the TPU backend.

Measures the five BASELINE.md configs (the reference's benchmark workloads,
``tests/benchmark.inc`` pattern) on the default JAX device and prints ONE
JSON line for the headline metric — the 1M-point convolution in
Msamples/s (BASELINE.json configs[3], the flagship long-signal path) —
with ``vs_baseline`` = speedup over the single-threaded CPU oracle
(NumPy, the reference's ``*_na`` twin) measured in the same process.

Capture-first ordering (the relay can wedge mid-run, and a partial run
must still yield the headline): the headline config runs FIRST — after a
short clock-ramp warm-up and an inline device-vs-oracle value check — and
its JSON line is printed and flushed immediately.  Every config (headline
included) is appended to BENCH_DETAILS.json as it completes, so however
short the device window, whatever ran is on disk.  The remaining timed
configs run next; the per-family XLA-vs-oracle correctness smoke
(``tools/tpu_smoke.py``, the reference's SIMD-vs-``_na`` discipline on
real hardware) runs LAST and prints one ``TPU-CHECK`` line per family to
stderr — measured live (2026-07-31): the relay wedged mid-smoke, so the
smoke must never be able to shadow a timing config.

Wedge containment: the axon relay has twice been observed to wedge
*mid-run* — an in-flight device call then blocks forever, unkillable
from Python.  Every stage (headline, timed configs, each smoke family)
therefore runs in a supervised worker thread with a
$VELES_SIMD_STAGE_TIMEOUT budget (default 300 s; compiles take
~20-40 s; 0 disables supervision): a stage that stalls past its budget
is SKIPPED — its thread is abandoned (daemon, blocked in native code),
the skip is recorded in BENCH_DETAILS.json's tail entry
(``{"skipped_stages": [...]}``), and the run continues with the
remaining stages (round 5 lost the iir/filters/waveforms/peaks/pallas/
parallel rows to a single ``smoke:resample`` wedge under the old
hard-exit design).  A last-resort watchdog still hard-exits if the
skip machinery itself stops making progress (3x the stage budget):
rc=0 once the headline line is out, rc=2 before that (the driver's
no-data signal, same as ``require_reachable_device``); a skipped
headline also exits rc=2 after the remaining stages have run.

Usage:  python bench.py           # one JSON line on stdout (first!)
        python bench.py --all     # pretty table of every config
        python bench.py --check   # correctness smoke only, no timing
"""

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from veles.simd_tpu import obs
from veles.simd_tpu.runtime import faults, routing
from veles.simd_tpu.utils.benchmark import (
    ROOFLINE_DISAGREEMENT_WARN_PCT, analytical_roofline, conv_roofline,
    device_time, device_time_chained, host_time, rms_normalize,
    roofline_disagreement_pct, stft_roofline)

# headline vs_baseline (speedup over the single-threaded CPU oracle)
# below this multiple is a regression worth shouting about in the
# artifact itself: r05 printed 88.37 and nobody noticed until a human
# reread the history.  The BENCH-WARN line + headline_regressed flag
# make it machine-visible (tools/bench_regress.py gates the trajectory;
# this flags the single run).
HEADLINE_VS_BASELINE_FLOOR = 95.0


def _telemetry_entry():
    """Compact per-config telemetry for BENCH_DETAILS.json: which
    algorithms were picked, how long their host dispatch took, how many
    compiles ran, whether the persistent cache served them — the
    attribution record that turns a bench regression from "slower"
    into "took a different path"."""
    from veles.simd_tpu.obs.export import flatten_counters, span_summary

    snap = obs.snapshot()
    decisions = [{k: v for k, v in e.items() if v is not None}
                 for e in snap["events"]]
    return {
        "decisions": decisions[-16:],
        # the autotune attribution: mode, every measured-winner event
        # (with per-route probe timings), and the tune cache's
        # hit/miss/store traffic — so a route flip between runs is
        # explainable from the artifact alone
        "autotune": {
            "mode": routing.autotune_mode(),
            "decisions": [e for e in decisions
                          if e.get("op") == "autotune"],
            "cache": snap.get("caches", {}).get("autotune_cache", {}),
        },
        "counters": flatten_counters(snap),
        "spans": span_summary(snap),
        "resources": snap.get("resources", []),
        "caches": snap.get("caches", {}),
        "compiles": obs.counter_value("compile.backend_compile"),
        "cache_hits": obs.counter_value("compile.cache_hits"),
        "cache_misses": obs.counter_value("compile.cache_misses"),
        "events_dropped": snap["events_dropped"],
        "spans_dropped": snap["spans_dropped"],
    }


def bench_elementwise(rng):
    """Config 1: f32 add/mul + int16->float, N=4096 (batched to fill the
    chip: 4096 signals of 4096 — per-op timing at N=4096 alone measures
    dispatch, not the VPU)."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import arithmetic as ar

    n = 4096
    batch = 4096
    a_np = rng.randn(batch, n).astype(np.float32)
    b_np = rng.randn(batch, n).astype(np.float32)
    i16 = rng.randint(-3000, 3000, (batch, n)).astype(np.int16)
    b = jnp.asarray(b_np)
    i16j = jnp.asarray(i16)

    def step(v):
        # int16 carry: both conversions run every iteration (nothing is
        # loop-invariant or affine — the trunc-saturate cast is nonlinear,
        # so XLA can neither hoist the converts nor reduce the loop).
        # Values stay in the +-3000 range the saturating cast allows.
        f = ar._int16_to_float(v)                  # convert i16 -> f32
        return ar._float_to_int16((f * 1e-4 + b) * 300.0)  # mul, add, back

    t = device_time_chained(step, i16j)
    elems = batch * n
    t_base = host_time(
        lambda: (a_np + b_np) * i16.astype(np.float32))
    return {"metric": "elementwise add*mul*convert", "unit": "Melem/s",
            "value": elems / t / 1e6, "baseline": elems / t_base / 1e6}


def bench_mathfun(rng):
    """Config 2: sin/cos/log/exp on 1M floats."""
    import jax.numpy as jnp

    n = 1 << 20
    x_np = np.abs(rng.randn(n).astype(np.float32)) + 0.1
    x = jnp.asarray(x_np)

    def step(v):  # 4 transcendentals; output stays in [0.1, ~4.7]
        return jnp.abs(jnp.sin(v) + jnp.cos(v) + jnp.log(v)
                       + jnp.exp(-v)) + 0.1

    t = device_time_chained(step, x)
    t_base = host_time(
        lambda: np.sin(x_np) + np.cos(x_np) + np.log(x_np) + np.exp(-x_np))
    # 4 transcendentals per element
    return {"metric": "sin+cos+log+exp 1M floats", "unit": "Msamples/s",
            "value": 4 * n / t / 1e6, "baseline": 4 * n / t_base / 1e6}


def bench_sgemm(rng):
    """Config 3: sgemm 512x512 (+ a gemv) in GFLOP/s."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import matrix as mx

    n = 512
    a_np = rng.randn(n, n).astype(np.float32)
    b_np = rng.randn(n, n).astype(np.float32)
    a, b = jnp.asarray(a_np), jnp.asarray(b_np)

    def step(v):  # rms-normalized so 256 chained GEMMs don't blow up
        return rms_normalize(mx._matmul_p(v, b))

    t = device_time_chained(step, a)
    flops = 2 * n ** 3
    t_base = host_time(lambda: mx.matrix_multiply_novec(a_np, b_np))
    return {"metric": "sgemm 512", "unit": "GFLOP/s",
            "value": flops / t / 1e9, "baseline": flops / t_base / 1e9}


def bench_convolve_1m(rng):
    """Config 4 (headline): 1M-point convolution, 2047-tap filter,
    overlap-save vs the NumPy-FFT oracle (the strongest CPU formulation
    available — np.convolve direct form would be ~100x slower still).

    Runs first in the capture-first ordering, so it carries its own
    correctness check: one device output is compared against the oracle
    before any number is reported (the smoke suite runs later)."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import convolve as cv

    n, k = 1 << 20, 2047
    x = rng.randn(n).astype(np.float32)
    h = rng.randn(k).astype(np.float32)
    handle = cv.convolve_overlap_save_initialize(n, k)
    xd, hd = jnp.asarray(x), jnp.asarray(h)  # device-resident: measure the
    # chip, not the tunnel

    want = cv._conv_overlap_save_na(x, h, handle.block_length)
    got = np.asarray(cv.convolve_overlap_save(handle, xd, hd, simd=True))
    rel = (np.max(np.abs(got - want)) / np.max(np.abs(want)))
    if rel > 1e-3:
        raise RuntimeError(
            f"headline conv device-vs-oracle rel err {rel:.2e} > 1e-3")
    print(f"TPU-CHECK convolve-headline: ok (rel err {rel:.1e})",
          file=sys.stderr)

    def step(v):  # 1e-30 * y forces the conv without perturbing v
        y = cv.convolve_overlap_save(handle, v, hd, simd=True)
        return v + 1e-30 * y[..., :n]

    t = device_time_chained(step, xd)
    t_base = host_time(lambda: cv._conv_overlap_save_na(
        x, h, handle.block_length), repeats=2)
    out = {"metric": "convolve 1M x 2047 overlap-save",
           "unit": "Msamples/s",
           "value": n / t / 1e6, "baseline": n / t_base / 1e6}
    # roofline attribution: effective TFLOP/s (2k useful FLOPs per
    # output sample) against the f32 MXU bound at the active precision
    # knob — the driver-captured form of BASELINE.md's 69% accounting
    # (omitted when the timer could not resolve: NaN in the JSON tail
    # would break strict parsers)
    if np.isfinite(t):
        roof = conv_roofline(n / t, k, cv.os_precision())
        print(f"CONV-ROOFLINE 1Mx2047: {roof['tflops_effective']:.1f} "
              f"TFLOP/s effective = {roof['pct_of_roofline']:.0f}% of "
              f"the f32-{roof['precision'].upper()} MXU bound "
              f"({roof['roofline_bound_tflops']:.1f} TFLOP/s)",
              file=sys.stderr)
        # analytical twin: the same measurement attributed with XLA's
        # OWN FLOP count for the convolve executable (harvested by the
        # instrumented compile helper during the correctness check
        # above) instead of the hand-maintained 2·h/sample constant.
        # Disagreement beyond the warn threshold means the hand-coded
        # accounting (or the route attribution) drifted — the obs-v3
        # demotion signal for utils/benchmark.py's constants.
        conv_res = [e for e in obs.resources()
                    if e["op"] == "convolve" and e.get("flops")]
        if conv_res:
            e = max(conv_res, key=lambda r: r["flops"])
            ana = analytical_roofline(e["flops"], t, roof["precision"])
            dis = roofline_disagreement_pct(
                roof["pct_of_roofline"],
                ana["analytical_pct_of_roofline"])
            roof.update(ana, analytical_route=e["route"],
                        disagreement_pct=dis)
            print(f"CONV-ROOFLINE analytical ({e['route']}, XLA "
                  f"flops={e['flops']:.3g}): "
                  f"{ana['analytical_pct_of_roofline']:.0f}% of the "
                  f"bound vs measured {roof['pct_of_roofline']:.0f}% "
                  f"(disagreement {dis:.0f}%)", file=sys.stderr)
            if dis > ROOFLINE_DISAGREEMENT_WARN_PCT:
                print(f"CONV-ROOFLINE WARNING: analytical vs "
                      f"hand-coded accounting disagree by {dis:.0f}% "
                      f"(> {ROOFLINE_DISAGREEMENT_WARN_PCT:.0f}%) — "
                      "recalibrate utils/benchmark.py constants "
                      "(algorithmic-redundancy MACs explain part; "
                      "constant drift explains the rest)",
                      file=sys.stderr)
        out["roofline"] = roof
    return out


def bench_autotuned_headline(rng):
    """Config 10: the headline geometry dispatched under the measured
    autotuner (``VELES_SIMD_AUTOTUNE=on``, fresh in-memory tune
    cache): one eager dispatch lets the engine probe the eligible
    ``convolve.os`` candidates and persist the winner, then the
    chained loop times steady-state dispatch through the cached
    decision.  The acceptance gate rides in ``vs_baseline``: baseline
    here is the STATIC choice's throughput on the same shape, so
    ``vs_baseline >= ~1`` means the autotuned choice is never slower
    than the static one — which holds by construction (the winner is
    the measured min over a candidate set that includes the static
    route) and this row verifies it end to end, probe noise and all.
    On single-candidate backends (CPU) the two numbers coincide."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import convolve as cv

    n, k = 1 << 20, 2047
    x = rng.randn(n).astype(np.float32)
    h = rng.randn(k).astype(np.float32)
    handle = cv.convolve_overlap_save_initialize(n, k)
    xd, hd = jnp.asarray(x), jnp.asarray(h)

    def step(v):
        y = cv.convolve_overlap_save(handle, v, hd, simd=True)
        return v + 1e-30 * y[..., :n]

    # static choice first: the prior the autotuner must not lose to.
    # Forced to mode "off" so an ambient VELES_SIMD_AUTOTUNE +
    # bound pack cannot steer this side too — the race must be
    # static-table vs measured, not pack vs pack
    with routing.autotune_mode_override("off"):
        t_static = device_time_chained(step, xd)
    # thread-local overrides, NOT env/global mutations: this stage
    # runs under the supervisor and may be abandoned mid-run — a
    # leaked env flip would silently re-route the rest of the
    # process, and the operator's $VELES_SIMD_AUTOTUNE_CACHE pack
    # must be neither consulted (stale winner) nor overwritten
    # (mid-bench contention noise shipped to production).  Both
    # overrides die with the thread.
    with routing.private_tune_cache() as stage_cache, \
            routing.autotune_mode_override("on"):
        # eager dispatch: the engine probes here (probing never
        # runs under the chained loop's trace), persists the
        # winner in the stage-private cache, and the chained loop
        # then times steady-state dispatch through it
        np.asarray(cv.convolve_overlap_save(handle, xd, hd,
                                            simd=True))
        t_tuned = device_time_chained(step, xd)
        # the stage-private cache dies with this scope; its traffic
        # is THE evidence this row exists to produce, so snapshot it
        # into the row (the process-level autotune section in
        # _telemetry_entry stays all-zeros by design — this stage
        # never touches the operator's cache)
        stage_cache_info = stage_cache.info()
    tuned_entry = None
    for e in obs.events():
        if e["op"] == "autotune" and e.get("family") == "convolve.os":
            tuned_entry = {kk: vv for kk, vv in e.items()
                           if kk in ("decision", "static", "timings")}
    out = {"metric": "convolve 1M x 2047 autotuned",
           "unit": "Msamples/s",
           "value": n / t_tuned / 1e6,
           "baseline": n / t_static / 1e6,
           "autotune_stage": {"mode": "on",
                              "cache": stage_cache_info}}
    if tuned_entry:
        out["autotune_winner"] = tuned_entry
    if np.isfinite(t_tuned) and np.isfinite(t_static):
        # the tuned-vs-static ratio itself rides in vs_baseline
        # (flush derives value/baseline == t_static/t_tuned) — one
        # home, not two fields that can silently diverge
        ratio = t_static / t_tuned
        print(f"AUTOTUNE-HEADLINE: tuned {n / t_tuned / 1e6:.0f} Ms/s "
              f"vs static {n / t_static / 1e6:.0f} Ms/s "
              f"({ratio:.2f}x)", file=sys.stderr)
        if ratio < 0.95:
            print("AUTOTUNE-WARN: the autotuned choice measured "
                  ">5% slower than the static choice on the headline "
                  "geometry — probe noise or a stale winner; rerun "
                  "and inspect the autotune decisions in "
                  "BENCH_DETAILS.json", file=sys.stderr)
    return out


def _precision_err_gate(got, want, precision, label):
    """Accuracy gate before any precision row is timed: the row's
    number is meaningless if the route left its error budget
    (runtime/precision.py ERROR_BUDGETS)."""
    from veles.simd_tpu.runtime import precision as prx

    # no dtype coercion: got may be complex (the stft row)
    rel = float(np.max(np.abs(np.asarray(got) - want))
                / max(1e-30, np.max(np.abs(want))))
    budget = prx.ERROR_BUDGETS[precision]
    if rel > budget:
        raise RuntimeError(
            f"{label} {precision} rel err {rel:.2e} > budget "
            f"{budget:.0e}")
    print(f"TPU-CHECK {label} [{precision}]: ok (rel err {rel:.1e})",
          file=sys.stderr)


def bench_precision_gemm(rng):
    """Config 14: gemm 2048 at bf16_comp vs the fp32 route — the
    precision-routes headline (ISSUE 14 acceptance: >=2x at <=1e-4
    rel err on real MXU hardware; on CPU the row only proves
    plumbing).  vs_baseline IS the comp-vs-fp32 speedup, and each
    side's roofline divides by ITS OWN per-precision MXU bound
    (utils/benchmark.py MXU_F32_PASSES) so bf16_comp is never
    flattered against the 6-pass f32 ceiling."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import matrix as mx
    from veles.simd_tpu.utils.benchmark import gemm_roofline

    n = 2048
    a_np = rng.randn(n, n).astype(np.float32)
    b_np = rng.randn(n, n).astype(np.float32)
    a, b = jnp.asarray(a_np), jnp.asarray(b_np)
    want = np.asarray(a_np, np.float64) @ np.asarray(b_np, np.float64)
    _precision_err_gate(mx._matmul_p(a, b, precision="bf16_comp"),
                        want, "bf16_comp", "gemm-2048")

    def make_step(precision):
        def step(v):
            return rms_normalize(mx._matmul_p(v, b,
                                              precision=precision))
        return step

    t_fp32 = device_time_chained(make_step("highest"), a)
    t_comp = device_time_chained(make_step("bf16_comp"), a)
    flops = 2 * n ** 3
    out = {"metric": "gemm 2048 bf16_comp", "unit": "GFLOP/s",
           "value": flops / t_comp / 1e9,
           "baseline": flops / t_fp32 / 1e9}
    if np.isfinite(t_comp) and np.isfinite(t_fp32):
        roofs = {"bf16_comp": gemm_roofline(flops, t_comp,
                                            "bf16_comp"),
                 "highest": gemm_roofline(flops, t_fp32, "highest")}
        out["roofline_precisions"] = roofs
        print(f"GEMM-PRECISION 2048: bf16_comp "
              f"{flops / t_comp / 1e9:.0f} GFLOP/s "
              f"({roofs['bf16_comp']['pct_of_roofline']:.0f}% of its "
              f"3-pass bound) vs fp32 {flops / t_fp32 / 1e9:.0f} "
              f"({roofs['highest']['pct_of_roofline']:.0f}% of the "
              f"6-pass bound) — {t_fp32 / t_comp:.2f}x",
              file=sys.stderr)
    return out


def bench_precision_convolve(rng):
    """Config 15: the headline overlap-save geometry (1M x 2047) on
    the xla_matmul_bf16_comp route vs the highest-precision block
    matmul — the matmul-bound row the >=2x acceptance names."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import convolve as cv

    n, k = 1 << 20, 2047
    x_np = rng.randn(n).astype(np.float32)
    h_np = rng.randn(k).astype(np.float32)
    x, h = jnp.asarray(x_np), jnp.asarray(h_np)
    step_len = cv.overlap_save_step(k)
    want = np.convolve(np.asarray(x_np[: 1 << 16], np.float64),
                       np.asarray(h_np, np.float64))
    got = cv._conv_os_matmul(jnp.asarray(x_np[: 1 << 16]), h,
                             step_len, precision="bf16_comp")
    _precision_err_gate(got, want, "bf16_comp", "convolve-os")

    def make_step(precision):
        def step(v):
            y = cv._conv_os_matmul(v, h, step_len,
                                   precision=precision)
            return v + 1e-30 * y[..., :n]
        return step

    t_hi = device_time_chained(make_step("highest"), x)
    t_comp = device_time_chained(make_step("bf16_comp"), x)
    out = {"metric": "convolve 1M x 2047 bf16_comp",
           "unit": "Msamples/s",
           "value": n / t_comp / 1e6, "baseline": n / t_hi / 1e6}
    if np.isfinite(t_comp) and np.isfinite(t_hi):
        out["roofline_precisions"] = {
            "bf16_comp": conv_roofline(n / t_comp, k, "bf16_comp"),
            "highest": conv_roofline(n / t_hi, k, "highest")}
        print(f"CONV-PRECISION 1Mx2047: bf16_comp "
              f"{n / t_comp / 1e6:.0f} Ms/s vs highest "
              f"{n / t_hi / 1e6:.0f} Ms/s ({t_hi / t_comp:.2f}x)",
              file=sys.stderr)
    return out


def bench_precision_stft(rng):
    """Config 16: STFT 16k x 512/128 (batch 64) on the
    rdft_matmul_bf16_comp route vs rdft_matmul — the spectral
    matmul-bound row of the precision acceptance."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import spectral as sp

    batch, n, fl, hop = 64, 1 << 14, 512, 128
    x_np = rng.randn(batch, n).astype(np.float32)
    xd = jnp.asarray(x_np)
    want = sp.stft_na(x_np[:2], fl, hop)
    got = np.asarray(sp.stft(xd[:2], fl, hop, simd=True,
                             route="rdft_matmul_bf16_comp"))
    _precision_err_gate(got, want, "bf16_comp", "stft-rdft")

    def make_step(route):
        def step(v):
            s = sp.stft(v, fl, hop, simd=True, route=route)
            return v + 1e-30 * jnp.abs(s).mean()
        return step

    t_hi = device_time_chained(make_step("rdft_matmul"), xd)
    t_comp = device_time_chained(make_step("rdft_matmul_bf16_comp"),
                                 xd)
    samples = batch * n
    frames = sp.frame_count(n, fl, hop)
    out = {"metric": "stft 16k x 512 bf16_comp",
           "unit": "Msamples/s",
           "value": samples / t_comp / 1e6,
           "baseline": samples / t_hi / 1e6}
    if np.isfinite(t_comp) and np.isfinite(t_hi):
        out["roofline_precisions"] = {
            "bf16_comp": stft_roofline(batch * frames / t_comp, fl,
                                       precision="bf16_comp"),
            "highest": stft_roofline(batch * frames / t_hi, fl,
                                     precision="highest")}
        print(f"STFT-PRECISION 16kx512/128: bf16_comp "
              f"{samples / t_comp / 1e6:.0f} Ms/s vs highest "
              f"{samples / t_hi / 1e6:.0f} Ms/s "
              f"({t_hi / t_comp:.2f}x)", file=sys.stderr)
    return out


def bench_cold_start(rng):
    """Config 17: the zero-warmup acceptance number — process-birth ->
    first-request wall clock of a fresh SUBPROCESS serving process,
    warm artifact pack (VELES_SIMD_ARTIFACTS=readonly + preload at
    Server.start) vs cold (artifacts off, full trace+compile per shape
    class).  vs_baseline IS the cold/warm speedup (the >= 2x
    acceptance bar is "warm <= 50% of cold"); the warm child's
    artifact hit/stale/miss counters ride in the row's telemetry via
    tools/cold_start.py, which also writes the standalone
    COLD_START_DETAILS.json family."""
    del rng                        # subprocess children seed themselves
    import tempfile

    from tools import cold_start as cs

    with tempfile.TemporaryDirectory(prefix="veles-warmpack-") as tmp:
        pack = os.path.join(tmp, "pack")
        ns = argparse.Namespace(pack=pack, reuse_pack=False,
                                timeout=600.0)
        rows, evidence = cs.run(ns)
    with open(cs.DEFAULT_DETAILS, "w") as f:
        json.dump(rows + [{"cold_start_evidence": evidence}], f,
                  indent=2)
    out = {"metric": "cold start warm vs cold",
           "unit": "x", "value": evidence["speedup"], "baseline": 1.0,
           "artifact_evidence": rows[0]["telemetry"]}
    print(f"COLD-START: cold {evidence['cold']['wall_s']:.2f}s -> "
          f"warm {evidence['warm']['wall_s']:.2f}s "
          f"(x{evidence['speedup']:.2f}, warm = "
          f"{100 * evidence['warm_fraction_of_cold']:.0f}% of cold)",
          file=sys.stderr)
    return out


def bench_dwt(rng):
    """Config 5: DWT daub8 + SWT sym8, batch of 512 x 4096 signals."""
    from veles.simd_tpu.ops import wavelet as wv
    from veles.simd_tpu.ops.wavelet_coeffs import WaveletType

    import jax.numpy as jnp

    batch, n = 512, 4096
    x = rng.randn(batch, n).astype(np.float32)
    xd = jnp.asarray(x)

    def step(v):  # [B, n] -> hi, lo each [B, n/2] -> concat back to [B, n]
        hi, lo = wv.wavelet_apply(
            WaveletType.DAUBECHIES, 8, wv.ExtensionType.PERIODIC, v,
            simd=True)
        return jnp.concatenate([hi, lo], axis=-1)

    t = device_time_chained(step, xd)
    t_base = host_time(lambda: wv.wavelet_apply_na(
        WaveletType.DAUBECHIES, 8, wv.ExtensionType.PERIODIC, x),
        repeats=2)
    samples = batch * n
    return {"metric": "DWT daub8 512x4096", "unit": "Msamples/s",
            "value": samples / t / 1e6, "baseline": samples / t_base / 1e6}


def bench_stft(rng):
    """Config 6: STFT 16k x 512/64, batch 64 — the auto-selected route
    raced against the forced xla_fft route on the same shape, both
    attributed with measured (hand-constant) and analytical
    (XLA-flops) roofline %.  At hop 64 the fused kernel's 128-lane
    gate is closed, so the selected route here is rdft_matmul (or
    xla_fft past the frame bound); the fused kernel gets its own
    timed comparison at the acceptance geometry — 1M samples, frame
    512, hop 128 — in the second block below, where the selector
    picks pallas_fused on real TPU.  The spectral-family acceptance:
    selected route >= 2x the xla_fft throughput on that shape."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import spectral as sp
    from veles.simd_tpu.utils.platform import to_host

    batch, n, fl, hop = 64, 1 << 14, 512, 64
    x = rng.randn(batch, n).astype(np.float32)
    xd = jnp.asarray(x)
    frames = sp.frame_count(n, fl, hop)
    sel = sp._select_stft_route(fl, hop, frames)

    # inline correctness gate + eager warm-up per route (the eager
    # calls also let instrumented_jit harvest each route's XLA flops
    # for the analytical roofline below)
    want = sp.stft_na(x[:2], fl, hop)
    for route in (sel, "xla_fft"):
        got = to_host(sp.stft(xd, fl, hop, simd=True, route=route))
        rel = np.max(np.abs(got[:2] - want)) / np.max(np.abs(want))
        if rel > 1e-3:
            raise RuntimeError(f"stft route {route} device-vs-oracle "
                               f"rel err {rel:.2e} > 1e-3")
    print(f"TPU-CHECK stft-routes ({sel}, xla_fft): ok",
          file=sys.stderr)

    def make_step(route):
        def step(v):
            s = sp.stft(v, fl, hop, simd=True, route=route)
            # scalar feedback forces the transform without perturbing v
            return v + 1e-30 * jnp.abs(s).mean()
        return step

    t_sel = device_time_chained(make_step(sel), xd)
    t_fft = device_time_chained(make_step("xla_fft"), xd)
    t_base = host_time(lambda: sp.stft_na(x, fl, hop), repeats=2)
    samples = batch * n
    out = {"metric": "stft 16k x 512/64 b64", "unit": "Msamples/s",
           "value": samples / t_sel / 1e6,
           "baseline": samples / t_base / 1e6,
           "stft_route": sel}
    if np.isfinite(t_fft):
        out["xla_fft_msamples_per_s"] = samples / t_fft / 1e6
    if np.isfinite(t_sel) and np.isfinite(t_fft):
        out["speedup_vs_xla_fft"] = t_fft / t_sel
        print(f"STFT-ROUTE {sel}: {samples / t_sel / 1e6:.0f} Ms/s vs "
              f"xla_fft {samples / t_fft / 1e6:.0f} Ms/s "
              f"({t_fft / t_sel:.1f}x)", file=sys.stderr)
    roofs = {}
    for route, t in ((sel, t_sel), ("xla_fft", t_fft)):
        if not np.isfinite(t):
            continue
        roof = stft_roofline(batch * frames / t, fl, route=route)
        res = [e for e in obs.resources()
               if e["op"] == "stft" and e["route"] == route
               and e.get("flops")]
        if res:
            ana = analytical_roofline(res[0]["flops"], t,
                                      roof["precision"])
            dis = roofline_disagreement_pct(
                roof["pct_of_roofline"],
                ana["analytical_pct_of_roofline"])
            roof.update(ana, disagreement_pct=dis)
            print(f"STFT-ROOFLINE {route}: measured "
                  f"{roof['pct_of_roofline']:.0f}% vs analytical "
                  f"{ana['analytical_pct_of_roofline']:.0f}% of the "
                  f"bound (disagreement {dis:.0f}%)", file=sys.stderr)
        roofs[route] = roof
    out["roofline_routes"] = roofs

    # second block: the ACCEPTANCE geometry (1M samples, frame 512,
    # hop 128) where the 128-lane hop gate is open — on real TPU the
    # selector picks pallas_fused and this is the fused kernel's timed
    # row; elsewhere it exercises rdft_matmul at the same shape
    n1m, hop1m = 1 << 20, 128
    x1m = jnp.asarray(rng.randn(n1m).astype(np.float32))
    frames1m = sp.frame_count(n1m, fl, hop1m)
    sel1m = sp._select_stft_route(fl, hop1m, frames1m)

    def mk1m(route):
        def step(v):
            s = sp.stft(v, fl, hop1m, simd=True, route=route)
            return v + 1e-30 * jnp.abs(s).mean()
        return step

    sp.stft(x1m, fl, hop1m, simd=True, route=sel1m)  # warm + harvest
    t1_sel = device_time_chained(mk1m(sel1m), x1m)
    t1_fft = device_time_chained(mk1m("xla_fft"), x1m)
    block = {"route": sel1m}
    if np.isfinite(t1_sel):
        block["msamples_per_s"] = n1m / t1_sel / 1e6
        roof = stft_roofline(frames1m / t1_sel, fl, route=sel1m)
        res = [e for e in obs.resources()
               if e["op"] == "stft" and e["route"] == sel1m
               and e.get("flops")]
        if res:
            ana = analytical_roofline(res[0]["flops"], t1_sel,
                                      roof["precision"])
            roof.update(ana, disagreement_pct=roofline_disagreement_pct(
                roof["pct_of_roofline"],
                ana["analytical_pct_of_roofline"]))
        block["roofline"] = roof
    if np.isfinite(t1_fft):
        block["xla_fft_msamples_per_s"] = n1m / t1_fft / 1e6
    if np.isfinite(t1_sel) and np.isfinite(t1_fft):
        block["speedup_vs_xla_fft"] = t1_fft / t1_sel
        print(f"STFT-ROUTE 1Mx512/128 {sel1m}: "
              f"{n1m / t1_sel / 1e6:.0f} Ms/s vs xla_fft "
              f"{n1m / t1_fft / 1e6:.0f} Ms/s "
              f"({t1_fft / t1_sel:.1f}x)", file=sys.stderr)
    out["stft_1m_512_128"] = block
    return out


def bench_istft_roundtrip(rng):
    """Config 7: istft(stft(x)) round trip, 16k x 512/128, batch 64 —
    the reconstruction pipeline both new route families serve (matmul
    analysis + inverse-basis synthesis into the overlap-add)."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import spectral as sp

    batch, n, fl, hop = 64, 1 << 14, 512, 128
    x = rng.randn(batch, n).astype(np.float32)
    xd = jnp.asarray(x)

    # correctness: one eager round trip reconstructs the interior
    rec = np.asarray(sp.istft(sp.stft(xd, fl, hop, simd=True), n, fl,
                              hop, simd=True))
    err = np.max(np.abs(rec[:, fl:-fl] - x[:, fl:-fl]))
    if err > 1e-3:
        raise RuntimeError(f"istft round-trip err {err:.2e} > 1e-3")

    def step(v):
        # reconstruction == v except edge decay, so the chain stays
        # bounded; the FFT/matmul pipeline is not XLA-reducible
        return sp.istft(sp.stft(v, fl, hop, simd=True), n, fl, hop,
                        simd=True)

    t = device_time_chained(step, xd)
    spec_np = sp.stft_na(x, fl, hop)
    t_base = (host_time(lambda: sp.stft_na(x, fl, hop), repeats=2)
              + host_time(lambda: sp.istft_na(spec_np, n, fl, hop),
                          repeats=2))
    samples = batch * n
    return {"metric": "istft round-trip 16k x 512/128 b64",
            "unit": "Msamples/s", "value": samples / t / 1e6,
            "baseline": samples / t_base / 1e6}


def bench_spectrogram(rng):
    """Config 8: power spectrogram |STFT|^2 at the stft shape."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import spectral as sp

    batch, n, fl, hop = 64, 1 << 14, 512, 128
    x = rng.randn(batch, n).astype(np.float32)
    xd = jnp.asarray(x)

    def step(v):
        p = sp.spectrogram(v, fl, hop, simd=True)
        return v + 1e-30 * p.mean()

    t = device_time_chained(step, xd)
    t_base = host_time(lambda: sp.spectrogram_na(x, fl, hop),
                       repeats=2)
    samples = batch * n
    return {"metric": "spectrogram 16k x 512/128 b64",
            "unit": "Msamples/s", "value": samples / t / 1e6,
            "baseline": samples / t_base / 1e6}


def bench_batched_stft(rng):
    """Config 9: batched_stft (ONE dispatch through the compiled-handle
    LRU) vs the same work as per-signal stft dispatches — vs_baseline
    IS the batched-vs-single ratio (the denominator is dispatch-bound
    by design, the short-signal story ops/batched.py exists for)."""
    import jax.numpy as jnp

    from veles.simd_tpu.ops import batched as bt
    from veles.simd_tpu.ops import spectral as sp

    batch, n, fl, hop = 256, 4096, 512, 128
    x = rng.randn(batch, n).astype(np.float32)
    xd = jnp.asarray(x)

    # abs().mean() keeps every fetched/synced value REAL — complex
    # fetches poison the axon relay (utils/platform.to_host)
    def batched_call():
        return jnp.abs(bt.batched_stft(xd, fl, hop)).mean()

    t_b = device_time(batched_call)

    rows = [xd[i] for i in range(batch)]

    def single_loop():
        acc = None
        for r in rows:
            acc = jnp.abs(sp.stft(r, fl, hop, simd=True)).mean()
        return float(acc)            # sync: the loop really finished

    single_loop()                    # warm the single-signal compile
    t_s = host_time(single_loop)
    samples = batch * n
    return {"metric": "batched stft 256x4096 512/128",
            "unit": "Msamples/s", "value": samples / t_b / 1e6,
            "baseline": samples / t_s / 1e6}


def bench_serve(rng):
    """Config 11: the serving layer's coalescing win — loadgen traffic
    (flat-out arrivals, mixed tenants/shapes) through a Server vs the
    same requests dispatched one-by-one through the single-signal ops.
    vs_baseline IS the serve-vs-sequential ratio: the numerator pays
    batching + padding + queueing, the denominator pays per-request
    dispatch, the regime ROADMAP item 1 exists for."""
    from tools import loadgen
    from veles.simd_tpu import serve

    schedule = loadgen.build_schedule(rng, 160, rate_hz=0.0,
                                      burst_every=0, burst_size=0)
    # warm every (op, bucket) compile outside the measured window, and
    # prove the accounting while at it
    with serve.Server(max_batch=8, max_wait_ms=2.0, workers=2) as srv:
        warm = loadgen.run_load(srv, schedule, result_timeout=600.0)
        if warm["lost"] or warm["double_answered"]:
            raise RuntimeError(f"serve accounting failed: {warm}")
        t0 = time.perf_counter()
        report = loadgen.run_load(srv, schedule, result_timeout=600.0)
        t_serve = time.perf_counter() - t0
    done = report["ok"] + report["degraded"]

    # sequential baseline: the same requests through the single-call
    # path (simd=True, no coalescing), timed after its own warmup
    from veles.simd_tpu.ops import iir, resample as rs, spectral as sp

    def one(req):
        p = req.params
        if req.op == "sosfilt":
            return iir.sosfilt(p["sos"], req.x[None, :], simd=True)
        if req.op == "lfilter":
            return iir.lfilter(p["b"], p["a"], req.x[None, :],
                               simd=True)
        if req.op == "resample_poly":
            return rs.resample_poly(req.x, p["up"], p["down"],
                                    simd=True)
        return sp.stft(req.x, p["frame_length"], p["hop"], simd=True)

    for _, req in schedule:
        one(req)                       # warm the per-request compiles
    t0 = time.perf_counter()
    for _, req in schedule:
        np.asarray(one(req))           # sync per request, like serve
    t_single = time.perf_counter() - t0
    return {"metric": "serve loadgen 160req mixed",
            "unit": "req/s", "value": done / t_serve,
            "baseline": len(schedule) / t_single}


def _bench_sensor_chain(block: int = 2048):
    """The sensor-conditioning chain the pipeline bench family times
    (the ``examples/sensor_pipeline.py`` stages in streaming form):
    despike -> block detrend -> IIR notch -> STFT -> power."""
    from veles.simd_tpu import pipeline as pl
    from veles.simd_tpu.ops import iir

    notch = iir.butterworth(4, (44 / 1000.0, 56 / 1000.0), "bandstop")
    chain = pl.Pipeline(
        [pl.medfilt(5), pl.detrend("linear"), pl.sosfilt(notch),
         pl.stft(256, 64), pl.power()],
        name="sensor_bench")
    return chain.compile(block)


def _pipeline_block_times(cp, blocks, fused: bool) -> list:
    """Per-block wall seconds through the compiled pipeline (each
    block synced like a serving answer); state threads through."""
    state = cp.init_state()
    times = []
    for b in blocks:
        t0 = time.perf_counter()
        out, state = cp.process(b, state, fused=fused)
        np.asarray(out)                     # sync, like a served answer
        times.append(time.perf_counter() - t0)
    return times


# configs 12 and 13 report two views (throughput, tail latency) of ONE
# measurement — memoized so the second config neither pays the
# compile+warm+parity+timing cost again nor reports from a different
# sample (a config abandoned mid-measure leaves the memo unset, so the
# surviving config still measures for itself)
_PIPELINE_MEASURE_MEMO: dict = {}


def _pipeline_measure(rng, n_blocks: int = 24, block: int = 2048):
    """Shared fused-vs-unfused measurement: returns ``(cp, blocks,
    fused_times, unfused_times)`` with both paths warmed (compiles
    outside the measured window) and parity-checked against the
    stage-by-stage oracle."""
    memo_key = (n_blocks, block)
    cached = _PIPELINE_MEASURE_MEMO.get(memo_key)
    if cached is not None:
        return cached
    cp = _bench_sensor_chain(block)
    x = rng.randn(n_blocks * block).astype(np.float32)
    blocks = [x[i:i + block] for i in range(0, len(x), block)]
    for fused in (True, False):             # compile both paths
        state = cp.init_state()
        for b in blocks[:2]:
            out, state = cp.process(b, state, fused=fused)
        np.asarray(out)
    got, _ = cp.stream(x)
    want = cp.oracle(x)
    scale = float(np.max(np.abs(want))) or 1.0
    err = float(np.max(np.abs(got - want)) / scale)
    # sanity bound only (the sharp bandstop notch costs a few f32
    # digits vs the float64 oracle); the tight ≤1e-5 streaming-parity
    # gates live in tests/test_pipeline.py
    if err > 1e-3:
        raise RuntimeError(
            f"pipeline parity failed before timing: rel err {err}")
    fused_times = _pipeline_block_times(cp, blocks, fused=True)
    unfused_times = _pipeline_block_times(cp, blocks, fused=False)
    result = (cp, blocks, fused_times, unfused_times)
    _PIPELINE_MEASURE_MEMO[memo_key] = result
    return result


def bench_pipeline(rng):
    """Config 12: the pipeline compiler's whole-point number — the
    fused sensor chain (ONE dispatch per block) vs the same stage
    kernels dispatched stage-by-stage (the pre-fusion cost), in
    blocks/s.  vs_baseline IS the fusion speedup."""
    cp, blocks, fused_times, unfused_times = _pipeline_measure(rng)
    return {"metric": f"pipeline sensor chain {cp.block_len}-blocks",
            "unit": "blocks/s",
            "value": len(blocks) / sum(fused_times),
            "baseline": len(blocks) / sum(unfused_times)}


def bench_pipeline_p99(rng):
    """Config 13: per-block tail latency of the fused sensor chain vs
    stage-by-stage dispatch — inverse p99 seconds (higher is better,
    so the regression gate's floor logic applies unchanged)."""
    _, _, fused_times, unfused_times = _pipeline_measure(rng)

    def inv_p99(ts):
        ts = np.sort(np.asarray(ts))
        return 1.0 / float(ts[int(0.99 * (len(ts) - 1))])

    return {"metric": "pipeline sensor chain p99 inverse latency",
            "unit": "1/s",
            "value": inv_p99(fused_times),
            "baseline": inv_p99(unfused_times)}


def _warm_device(seconds: float = 1.0):
    """Ramp device clocks with a sustained chained GEMM before the first
    timed config (the first sustained workload in a process has been
    observed 3-20x slow while power/clocks ramp)."""
    import time

    import jax
    import jax.numpy as jnp
    from jax import lax

    a = jnp.asarray(np.random.RandomState(1).randn(1024, 1024)
                    .astype(np.float32))

    @jax.jit
    def runk(x, k):
        return lax.fori_loop(0, k, lambda i, v: rms_normalize(v @ a), x)

    deadline = time.perf_counter() + seconds
    np.asarray(runk(a, 8).ravel()[-1:])  # compile
    while time.perf_counter() < deadline:
        np.asarray(runk(a, 1024).ravel()[-1:])


class _StageWatchdog:
    """LAST-RESORT hard exit when the skip machinery itself stops
    making progress (wedged relay blocking the MAIN thread, e.g. in
    between-stage device work that no per-stage budget covers).

    A wedged in-flight device call blocks in native code and cannot be
    interrupted from Python, so the only safe recovery is process exit —
    acceptable here because every completed result is already flushed to
    stdout/BENCH_DETAILS.json before the next stage starts.  Per-stage
    wedges are handled one level up by :class:`_StageRunner` (skip and
    continue); this watchdog's threshold is a multiple of the stage
    budget so it only fires when that layer is itself stuck.
    """

    def __init__(self, timeout_s: float):
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._stage = "(startup)"
        self._t0 = time.monotonic()
        self._stopped = False
        self.headline_out = False
        if timeout_s > 0:  # 0 disables, matching $VELES_SIMD_DEVICE_WAIT=0
            threading.Thread(target=self._watch, daemon=True).start()

    def stage(self, name: str) -> None:
        with self._lock:
            self._stage = name
            self._t0 = time.monotonic()

    def stop(self) -> None:
        """Disarm on normal completion — the run is over, nothing left
        to guard (and an in-process caller, e.g. the test-suite, must
        not be hard-exited by a leftover daemon minutes later)."""
        with self._lock:
            self._stopped = True

    def _watch(self) -> None:
        while True:
            time.sleep(5.0)
            with self._lock:
                if self._stopped:
                    return
                stalled = time.monotonic() - self._t0
                stage = self._stage
            if stalled > self.timeout_s:
                print(f"bench.py: stage {stage!r} stalled for "
                      f"{stalled:.0f}s (> {self.timeout_s:.0f}s) past "
                      "the per-stage skip layer — relay wedge; exiting "
                      "with the results captured so far",
                      file=sys.stderr)
                sys.stderr.flush()
                sys.stdout.flush()
                os._exit(0 if self.headline_out else 2)


class _StageRunner:
    """Run each bench stage in a supervised worker thread; retry the
    stage on transient device faults, then skip it (and keep going)
    when it stays wedged or broken.

    A wedged device call cannot be cancelled, so a stalled worker is
    simply abandoned — it is a daemon thread blocked in native code and
    dies with the process.  Fault policy (shared with the dispatch
    layer, ``runtime/faults.py``): a stage that *wedges* or raises a
    transient fault (device-lost / timeout per ``faults.is_transient``)
    is retried up to ``$VELES_SIMD_STAGE_RETRIES`` times (default 1,
    with the engine's jittered backoff) before being skipped — runs
    r02-r04 were lost outright to one-shot device-unreachable hangs
    this retry now absorbs.  Every fault is recorded in ``self.faults``
    (landing in BENCH_DETAILS.json's tail) and counted
    (``fault_stage_retry``/``fault_stage_exhausted`` —
    ``veles_simd_fault_*`` in Prometheus), so a fault-degraded run is
    distinguishable from a
    clean one in the artifact itself.  Non-transient errors are NOT
    retried — a deterministic bug does not deserve a second 300 s
    budget — and deterministic *wedges* are the accepted cost of the
    retry: a stage that wedges every time now burns
    ``(retries+1) * budget`` before the skip (bounded by the default
    retries=1; the watchdog threshold is 3x the budget, so the skip
    layer still wins), and a merely-SLOW first attempt may still be
    running when its retry starts — the same zombie-contention
    trade-off the skip path below already documents, now also flagged
    in the artifact by the stage's fault record.

    KNOWN TRADE-OFF: a stage that was merely SLOW (not truly wedged)
    may resume after being skipped and run concurrently with later
    stages — its device work and obs events then bleed into the next
    config's telemetry/timings.  Stage-private RandomStates keep the
    data draws race-free; the telemetry bleed is accepted (each config
    still obs.reset()s first, and a truly wedged thread never wakes).
    Size VELES_SIMD_STAGE_TIMEOUT above the slowest honest stage.

    ``timeout_s <= 0`` disables supervision (stages run inline on the
    main thread — the debugging mode).
    """

    _WEDGED = object()

    def __init__(self, timeout_s: float, watchdog: _StageWatchdog,
                 retries: int | None = None):
        self.timeout_s = timeout_s
        self._watchdog = watchdog
        self.skipped = []          # [{"stage": ..., "reason": ...}]
        self.faults = []           # transient-fault records (tail)
        if retries is None:
            try:
                retries = int(os.environ.get(
                    "VELES_SIMD_STAGE_RETRIES", "1"))
            except ValueError:
                retries = 1
        self.retries = max(0, retries)

    def run(self, name: str, fn):
        """Execute ``fn()`` under the stage budget and fault policy.
        Returns ``(ok, result)``; ``ok`` is False when the stage
        wedged past its retries (skip recorded) or raised (error
        recorded) — the caller just moves on."""
        for attempt in range(self.retries + 1):
            self._watchdog.stage(name)   # fresh clock per attempt
            outcome, payload = self._attempt(name, fn)
            if outcome == "ok":
                return True, payload
            transient = (outcome == "wedged"
                         or faults.is_transient(payload))
            if transient:
                kind = ("wedged" if outcome == "wedged" else
                        "timeout" if faults.is_timeout(payload)
                        else "device_lost")
                self.faults.append({
                    "stage": name, "attempt": attempt, "kind": kind,
                    "detail": (f"> {self.timeout_s:.0f}s"
                               if outcome == "wedged"
                               else repr(payload)[:300])})
                if attempt < self.retries:
                    obs.count("fault_stage_retry", stage=name)
                    print(f"bench.py: stage {name!r} hit a transient "
                          f"fault ({kind}); retry "
                          f"{attempt + 1}/{self.retries}",
                          file=sys.stderr)
                    time.sleep(faults.backoff_delay(attempt))
                    continue
                obs.count("fault_stage_exhausted", stage=name)
            if outcome == "wedged":
                print(f"bench.py: stage {name!r} stalled past "
                      f"{self.timeout_s:.0f}s — relay wedge; skipping "
                      "it and continuing with the remaining stages",
                      file=sys.stderr)
                self.skipped.append(
                    {"stage": name,
                     "reason": f"wedged (> {self.timeout_s:.0f}s)"})
                return False, self._WEDGED
            return self._failed(name, payload)

    def _attempt(self, name: str, fn):
        """One supervised execution: ('ok', result) / ('error', exc) /
        ('wedged', None)."""
        if self.timeout_s <= 0:
            try:
                return "ok", fn()
            except Exception as e:  # noqa: BLE001 — record, keep going
                return "error", e
        box = {}

        def work():
            try:
                box["result"] = fn()
            except Exception as e:  # noqa: BLE001
                box["error"] = e

        t = threading.Thread(target=work, daemon=True,
                             name=f"bench-stage-{name}")
        t.start()
        t.join(self.timeout_s)
        if t.is_alive():
            return "wedged", None
        if "error" in box:
            return "error", box["error"]
        return "ok", box.get("result")

    def _failed(self, name, e):
        print(f"bench.py: stage {name!r} failed ({e!r}); continuing",
              file=sys.stderr)
        self.skipped.append({"stage": name, "reason": f"error: {e!r}"})
        return False, e


def main():
    from veles.simd_tpu.utils.platform import (
        maybe_override_platform, require_reachable_device)

    maybe_override_platform()  # VELES_SIMD_PLATFORM=cpu runs without TPU
    # fail fast on a wedged relay rather than hanging, but give it a
    # 10-min recovery window first (wedges have been observed to clear);
    # $VELES_SIMD_DEVICE_WAIT overrides (0 restores pure fail-fast)
    require_reachable_device(wait=600.0)
    import jax

    from tools.tpu_smoke import FAMILIES, run_smoke

    stage_timeout = float(os.environ.get("VELES_SIMD_STAGE_TIMEOUT",
                                         "300"))
    # the watchdog is the backstop for the skip layer itself: 3x the
    # per-stage budget (a stage that wedges is skipped long before)
    dog = _StageWatchdog(3 * stage_timeout)
    runner = _StageRunner(stage_timeout, dog)

    try:
        if "--check" in sys.argv:
            # smoke-only mode, each family under its own stage budget so one
            # wedge cannot cost the remaining families.  rc: 0 all pass,
            # 1 numerical failure, 2 incomplete (a family wedged)
            all_ok = True
            for fam, _ in FAMILIES:
                ok, res = runner.run(f"smoke:{fam}",
                                     lambda fam=fam: run_smoke(families=[fam]))
                all_ok &= ok and bool(res)
            if runner.skipped:
                print(f"bench.py: smoke incomplete — skipped "
                      f"{[s['stage'] for s in runner.skipped]}",
                      file=sys.stderr)
                sys.exit(2)
            sys.exit(0 if all_ok else 1)

        device = str(jax.devices()[0])
        # telemetry ON for the whole run: every BENCH_DETAILS.json entry
        # carries the algorithm decisions / compile counts behind its number
        obs.enable()
        obs.reset()
        # PER-STAGE RandomState: an abandoned (slow-but-not-wedged)
        # stage thread may resume later; a shared rng would then race
        # the live stage's draws.  Derived obs/telemetry pollution from
        # such a zombie is accepted (documented at _StageRunner).
        rng = np.random.RandomState(0)
        results = []

        def write_details():
            # the tail entry records which stages were skipped/failed,
            # every transient stage fault the retry policy absorbed,
            # and the device-probe history — so a partial or
            # fault-degraded run is distinguishable from a clean one
            # in the artifact itself (not just in stderr), and
            # tools/bench_regress.py can treat fault-degraded rows as
            # reported-not-gated
            from veles.simd_tpu.utils.platform import probe_history

            tail_info = {}
            if runner.skipped:
                tail_info["skipped_stages"] = runner.skipped
            if runner.faults:
                tail_info["stage_faults"] = runner.faults
            probes = probe_history()
            if any(not p["ok"] for p in probes):
                tail_info["device_probes"] = probes
            tail = [tail_info] if tail_info else []
            with open("BENCH_DETAILS.json", "w") as f:
                json.dump(results + tail, f, indent=2, allow_nan=False)

        def flush(r):
            r["vs_baseline"] = r["value"] / r["baseline"]
            r["device"] = device
            # per-config telemetry (reset right after, so each entry's
            # decisions/compiles are attributable to that config alone)
            r["telemetry"] = _telemetry_entry()
            obs.reset()
            # device_time_chained returns NaN for unresolvable measurements;
            # NaN is not valid strict JSON, so flag it and null the numbers
            if not all(np.isfinite(r[k]) for k in ("value", "baseline",
                                                   "vs_baseline")):
                r["flagged"] = "unresolved measurement (timer returned NaN)"
                r = {k: (None if isinstance(v, float) and not np.isfinite(v)
                         else v) for k, v in r.items()}
            results.append(r)
            write_details()
            if "--all" in sys.argv:
                def fmt(v, spec):
                    return format(v, spec) if v is not None else "  (flagged)"
                print(f"{r['metric']:36s} {fmt(r['value'], '12.1f')} "
                      f"{r['unit']:11s} "
                      f"(cpu-oracle {fmt(r['baseline'], '10.1f')}, "
                      f"x{fmt(r['vs_baseline'], '.1f')})", file=sys.stderr)
            return r

        # headline first: warm clocks, measure, print the parseable line NOW —
        # everything after this point is gravy if the device window closes
        runner.run("warmup", _warm_device)
        obs.reset()  # warmup compiles are not the headline's to report
        ok, res = runner.run("headline:convolve_1m",
                             lambda: bench_convolve_1m(rng))
        if ok:
            head = flush(res)
            print(json.dumps({
                "metric": head["metric"],
                "value": (None if head["value"] is None
                          else round(head["value"], 2)),
                "unit": head["unit"],
                "vs_baseline": (None if head["vs_baseline"] is None
                                else round(head["vs_baseline"], 2)),
            }, allow_nan=False), flush=True)
            dog.headline_out = True  # a wedge from here on still exits 0
            if (head.get("vs_baseline") is not None
                    and head["vs_baseline"] < HEADLINE_VS_BASELINE_FLOOR):
                # make the single-run regression machine-visible in the
                # artifact (r05 printed 88.37 and nothing flagged it);
                # the trajectory gate stays tools/bench_regress.py's job
                head["headline_regressed"] = True
                write_details()
                print(f"BENCH-WARN: headline vs_baseline "
                      f"{head['vs_baseline']:.2f} < "
                      f"{HEADLINE_VS_BASELINE_FLOOR:.0f} — the 1M-conv "
                      "headline regressed vs the CPU-oracle multiple "
                      "(recorded as headline_regressed in "
                      "BENCH_DETAILS.json)", file=sys.stderr)
        else:
            # the headline could not be measured; say so in the parseable
            # slot (nulls, never a fabricated number) and keep capturing
            # the remaining stages — rc=2 at the end marks the run partial
            write_details()
            print(json.dumps({
                "metric": "convolve 1M x 2047 overlap-save", "value": None,
                "unit": "Msamples/s", "vs_baseline": None,
                "skipped": runner.skipped[-1]["reason"]
                if runner.skipped else "stage failed"}), flush=True)

        # after the headline attempt, a failure/wedge must not turn the
        # artifact red or cost independent configs — skip and keep going.
        # Timed configs BEFORE the smoke: the 2026-07-31 window wedged inside
        # the smoke, which under the old ordering cost configs 1/2/3/5.
        configs = (bench_elementwise, bench_mathfun, bench_sgemm,
                   bench_dwt, bench_stft, bench_istft_roundtrip,
                   bench_spectrogram, bench_batched_stft,
                   bench_serve, bench_pipeline, bench_pipeline_p99,
                   bench_autotuned_headline, bench_precision_gemm,
                   bench_precision_convolve, bench_precision_stft,
                   bench_cold_start)
        for i, fn in enumerate(configs):
            # a failed/skipped config never reaches flush()'s reset — drop
            # its events here so they can't masquerade as the next config's
            obs.reset()
            cfg_rng = np.random.RandomState(i + 1)  # stage-private
            cfg_ok, cfg_res = runner.run(f"config:{fn.__name__}",
                                         lambda fn=fn, r=cfg_rng: fn(r))
            if cfg_ok:
                flush(cfg_res)
            else:
                write_details()
        # per-family smoke, each under its own budget: one wedged family
        # costs one TPU-CHECK line, not every family after it (the round-5
        # failure mode this runner exists for)
        smoke_ok = True
        for fam, _ in FAMILIES:
            fam_ok, fam_res = runner.run(
                f"smoke:{fam}", lambda fam=fam: run_smoke(families=[fam]))
            smoke_ok &= fam_ok and bool(fam_res)
            if not fam_ok:
                write_details()
        if not smoke_ok:
            print(f"bench.py: correctness smoke incomplete or FAILED on "
                  f"{device!r}; timing numbers are suspect", file=sys.stderr)
        if not dog.headline_out:
            sys.exit(2)  # partial run: no headline measurement was captured

    finally:
        dog.stop()   # disarm: never hard-exit a finished run


if __name__ == "__main__":
    main()
