"""1D decimated (DWT) and stationary (SWT) wavelet filter banks.

TPU-native rebuild of ``/root/reference/src/wavelet.c`` (1940 LoC of
hand-written per-order AVX/NEON kernels) + ``inc/simd/wavelet.h``.

Semantics preserved exactly from the scalar reference:

* **QMF construction** from the lowpass table: ``lowpass[i] = C[i]``,
  ``highpass[order-1-i] = (i odd ? +C[i] : -C[i])``
  (``src/wavelet.c:187-209``) — see
  :func:`veles.simd_tpu.ops.wavelet_coeffs.qmf_highpass`.
* **DWT** (``wavelet_apply_na``, ``src/wavelet.c:271-324``): the signal is
  extended on the right by ``order`` samples per the extension mode, then
  for each even offset ``i``: ``desthi[i/2] = Σ_j hp[j]·x_ext[i+j]`` (and
  ``destlo`` with the lowpass) — i.e. *cross-correlation with stride 2*,
  output length ``length/2``.
* **SWT** level ℓ (``stationary_wavelet_apply_na``, ``src/wavelet.c:326-382``):
  filters are à-trous upsampled by ``stride = 2^(ℓ-1)``
  (``src/wavelet.c:211-246``; the upsampled highpass satisfies
  ``hp_up[stride·k] = hp[k]``), extension length ``order·stride``, no
  decimation — *dilated cross-correlation*, output length ``length``.
* **Boundary extensions** periodic / mirror / constant / zero
  (``src/wavelet.c:248-269``, enum ``inc/simd/wavelet_types.h:44-53``);
  note mirror repeats the last sample first (``src[length-1-(i%length)]``).

On TPU both transforms are a single ``lax.conv_general_dilated`` with two
output channels (hi, lo): stride 2 for DWT, ``rhs_dilation`` 2^(ℓ-1) for
SWT.  XLA lowers the small-filter conv to MXU-tiled matmuls; the
reference's "prepared array" AVX layout machinery
(``src/wavelet.c:64-165``) is alignment hackery XLA makes obsolete — its
API surface survives as thin shims (:func:`wavelet_prepare_array`,
:func:`wavelet_allocate_destination`, :func:`wavelet_recycle_source`) so
ported call sites keep working.

All entry points accept leading batch dimensions; batched multi-level
cascades are the data-parallel unit that shards over a mesh in
:mod:`veles.simd_tpu.parallel`.

Normalization note: the reference's Daubechies table sums to √2 (an
orthonormal filter bank — energy is preserved), but its Symlet and Coiflet
tables sum to **1**, so those transforms scale output energy by 1/2 per
level.  This module reproduces that behavior exactly for parity; multiply
outputs by √2 per level for orthonormal scaling.

Beyond the reference (which is analysis-only, 1D-only): synthesis
(:func:`wavelet_reconstruct`, :func:`stationary_wavelet_reconstruct`,
the cascade inverses) for **all four extensions** — exact for PERIODIC
(scaled-orthogonal adjoint) and for the SWT under any extension
(full-rank frame, least-squares solve); least-squares for the
non-periodic DWT, whose fixed-size analysis is provably rank-deficient
(see the boundary-correction section comment) — plus the separable
image transforms (:func:`wavelet_apply2d` / :func:`wavelet_reconstruct2d`,
the 2D pyramid, and the undecimated :func:`stationary_wavelet_apply2d`)
and the full binary wavelet-packet tree
(:func:`wavelet_packet_transform` and its inverse).
"""

from __future__ import annotations

import enum
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from veles.simd_tpu import obs
from veles.simd_tpu.ops import pallas_kernels as _pk
from veles.simd_tpu.ops.wavelet_coeffs import (
    WaveletType, qmf_highpass, scaling_coefficients, supported_orders,
    validate_order)
from veles.simd_tpu.runtime import routing
from veles.simd_tpu.runtime import precision as prx
from veles.simd_tpu.utils.config import resolve_simd

__all__ = [
    "WaveletType", "ExtensionType",
    "wavelet_apply", "wavelet_apply_na",
    "stationary_wavelet_apply", "stationary_wavelet_apply_na",
    "wavelet_transform", "stationary_wavelet_transform",
    "wavelet_packet_transform", "wavelet_packet_inverse_transform",
    "wavelet_packet_transform2d", "wavelet_packet_inverse_transform2d",
    "wavelet_reconstruct", "wavelet_reconstruct_na",
    "stationary_wavelet_reconstruct", "stationary_wavelet_reconstruct_na",
    "wavelet_inverse_transform", "stationary_wavelet_inverse_transform",
    "wavelet_apply2d", "wavelet_reconstruct2d",
    "stationary_wavelet_apply2d", "stationary_wavelet_reconstruct2d",
    "wavelet_transform2d", "wavelet_inverse_transform2d",
    "wavelet_prepare_array", "wavelet_allocate_destination",
    "wavelet_recycle_source", "wavelet_validate_order",
    "supported_orders",
]


class ExtensionType(enum.Enum):
    """``ExtensionType`` (``inc/simd/wavelet_types.h:44-53``)."""

    PERIODIC = "periodic"
    MIRROR = "mirror"
    CONSTANT = "constant"
    ZERO = "zero"


def _filters(type, order):
    lo = scaling_coefficients(type, order).astype(np.float32)
    hi = qmf_highpass(lo)
    return hi, lo


def _check_apply_args(type, order, length):
    if not validate_order(type, order):
        raise ValueError(
            f"unsupported {WaveletType(type).value} order {order} "
            f"(src/wavelet.c:167-185 contract)")
    if length < 2 or length % 2:
        raise ValueError(
            "signal length must be even and >= 2 "
            "(inc/simd/wavelet.h check_length contract)")


# --------------------------------------------------------------------------
# boundary extension
# --------------------------------------------------------------------------

def _extension_indices(ext, ext_len, length):
    """Index/array recipe for the right-extension of a length-`length`
    signal by `ext_len` samples (``src/wavelet.c:248-269``)."""
    ext = ExtensionType(ext)
    i = np.arange(ext_len)
    if ext is ExtensionType.PERIODIC:
        return i % length
    if ext is ExtensionType.MIRROR:
        return length - 1 - (i % length)
    if ext is ExtensionType.CONSTANT:
        return np.full(ext_len, length - 1)
    return None  # ZERO


def _extend(x, ext, ext_len, xp):
    length = x.shape[-1]
    idx = _extension_indices(ext, ext_len, length)
    if idx is None:
        pad = [(0, 0)] * (x.ndim - 1) + [(0, ext_len)]
        return xp.pad(x, pad)
    return xp.concatenate([x, xp.take(x, xp.asarray(idx), axis=-1)], axis=-1)


# --------------------------------------------------------------------------
# jitted XLA kernels
# --------------------------------------------------------------------------

@functools.partial(obs.instrumented_jit,
                   static_argnames=("ext", "stride", "dilation",
                                    "out_len"))
def _filter_bank(x, hi, lo, ext, stride, dilation, out_len):
    """Shared DWT/SWT kernel: extend, then 2-channel strided/dilated
    cross-correlation.  DWT: stride=2, dilation=1.  SWT: stride=1,
    dilation=2^(level-1)."""
    order = hi.shape[-1]
    ext_len = order * dilation
    x_ext = _extend(x.astype(jnp.float32), ext, ext_len, jnp)
    batch_shape = x.shape[:-1]
    lhs = x_ext.reshape((-1, 1, x_ext.shape[-1]))          # [N, C=1, W]
    rhs = jnp.stack([hi, lo]).reshape((2, 1, order))        # [O=2, I=1, W]
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(stride,), padding="VALID",
        rhs_dilation=(dilation,), precision=prx.HIGHEST)
    out = out[..., :out_len]                                # [N, 2, out_len]
    out = out.reshape(batch_shape + (2, out_len))
    return out[..., 0, :], out[..., 1, :]


# The wavelet candidate table (runtime/routing.py).  The Pallas
# shifted-MAC kernel reads each sample once where the XLA conv
# lowering reads it ``order`` times — measured 3.6x on the BASELINE
# config-5 workload (512x4096 daub8, 12.1 -> 43.2 GSamples/s on v5e).
# It needs enough batch rows to fill VPU sublanes and a signal short
# enough that one row fits the kernel's VMEM tile budget;
# single-signal and extreme-length calls stay on the XLA conv path.
# VELES_SIMD_DISABLE_PALLAS_WAVELET is the family's env opt-out —
# route parity with the conv/spectral families, which had escape
# hatches from day one.
_WAVELET_DISABLE_ENV = "VELES_SIMD_DISABLE_PALLAS_WAVELET"

_WAVELET_FAMILY = routing.family("wavelet", (
    routing.Route(
        "pallas",
        predicate=lambda rows, n, order, dilation, stride, **_:
            _pk.should_route(rows, (n + order * dilation)
                             + 2 * (n // stride)),
        disable_env=_WAVELET_DISABLE_ENV,
        doc="VPU shifted-MAC Mosaic kernel (filter bank, one read "
            "per sample)"),
    routing.Route(
        "xla_conv",
        doc="2-channel strided/dilated lax.conv_general_dilated"),
))


def _use_pallas(src_shape, order, dilation, stride) -> bool:
    """Route batched transforms through the hand-written Mosaic
    kernel — thin delegate into the ``wavelet`` candidate table, where
    the VPU row/VMEM gates and the ``VELES_SIMD_DISABLE_PALLAS_WAVELET``
    opt-out live.  Tests monkeypatch this gate to exercise the kernel
    in interpret mode on CPU."""
    rows = int(np.prod(src_shape[:-1])) if len(src_shape) > 1 else 1
    return _WAVELET_FAMILY.gate(
        "pallas", rows=rows, n=int(src_shape[-1]), order=int(order),
        dilation=int(dilation), stride=int(stride))


@functools.partial(obs.instrumented_jit,
                   static_argnames=("type", "order", "ext",
                                    "stride", "dilation", "out_len"))
def _filter_bank_pallas(x, type, order, ext, stride, dilation, out_len):
    """DWT/SWT via the Pallas shifted-MAC kernel.  Tap values are runtime
    SMEM data; (type, order) is static here only because the coefficient
    lookup and the extension length depend on it."""
    hi, lo = _filters(type, order)
    x_ext = _extend(x.astype(jnp.float32), ext, order * dilation, jnp)
    return _pk.filter_bank_pallas(x_ext, np.stack([hi, lo]), stride,
                                  dilation, out_len)


# --------------------------------------------------------------------------
# NumPy oracles (reference *_na semantics, src/wavelet.c:271-382)
# --------------------------------------------------------------------------

def _filter_bank_na(x, hi, lo, ext, stride, dilation, out_len):
    x = np.asarray(x, np.float32)
    order = hi.shape[-1]
    ext_len = order * dilation
    x_ext = _extend(x, ext, ext_len, np)
    taps = np.arange(order) * dilation
    starts = np.arange(out_len) * stride
    idx = starts[:, None] + taps[None, :]                  # [out_len, order]
    windows = np.take(x_ext, idx, axis=-1)             # [..., out_len, order]
    reshi = np.einsum("...ij,j->...i", windows.astype(np.float64),
                      hi.astype(np.float64))
    reslo = np.einsum("...ij,j->...i", windows.astype(np.float64),
                      lo.astype(np.float64))
    return reshi.astype(np.float32), reslo.astype(np.float32)


def wavelet_apply_na(type, order, ext, src):
    """Scalar-oracle DWT (``wavelet_apply_na``, ``src/wavelet.c:271-324``).

    Returns ``(desthi, destlo)``, each of length ``length/2``.
    """
    src = np.asarray(src, np.float32)
    _check_apply_args(type, order, src.shape[-1])
    hi, lo = _filters(type, order)
    return _filter_bank_na(src, hi, lo, ExtensionType(ext), 2, 1,
                           src.shape[-1] // 2)


def stationary_wavelet_apply_na(type, order, level, ext, src):
    """Scalar-oracle SWT (``stationary_wavelet_apply_na``,
    ``src/wavelet.c:326-382``).  Returns ``(desthi, destlo)``, each of
    length ``length``."""
    src = np.asarray(src, np.float32)
    _check_apply_args(type, order, src.shape[-1])
    if level < 1:
        raise ValueError("level must be >= 1")
    hi, lo = _filters(type, order)
    return _filter_bank_na(src, hi, lo, ExtensionType(ext), 1,
                           1 << (level - 1), src.shape[-1])


# --------------------------------------------------------------------------
# public dispatching API
# --------------------------------------------------------------------------

def _wavelet_runners(src, type, order, ext, stride, dilation, out_len):
    """Route name -> zero-arg core call, the ONE home of the candidate
    call expressions: dispatch runs ``runners[chosen]()`` and the
    measured autotuner probes the same thunks (forced semantics), so
    the probe can never measure a different computation than dispatch
    executes."""
    def run_pallas():
        return _filter_bank_pallas(src, WaveletType(type), int(order),
                                   ExtensionType(ext), stride,
                                   dilation, out_len)

    def run_xla():
        hi, lo = _filters(type, order)
        return _filter_bank(src, jnp.asarray(hi), jnp.asarray(lo),
                            ExtensionType(ext), stride, dilation,
                            out_len)

    return {"pallas": run_pallas, "xla_conv": run_xla}


def _select_wavelet_route(src_shape, order, dilation, stride,
                          route=None, runners=None, src=None):
    """Shared DWT/SWT route choice: a forced ``route`` is validated
    and pinned (forced routes re-raise on failure — they never
    silently degrade, mirroring ``faults.guarded``'s forced
    semantics); otherwise the (monkeypatchable) gate builds the
    candidate list and the engine selects — static table order, or
    the measured/cached winner under ``VELES_SIMD_AUTOTUNE``
    (``runners`` is the callers' :func:`_wavelet_runners` table — the
    same thunks dispatch runs — handed to the engine for the measured
    mode; ``src`` is the engine's traced-operand check)."""
    forced = route is not None
    if forced:
        if route not in _WAVELET_FAMILY.names():
            raise ValueError(
                f"route must be one of "
                f"{sorted(_WAVELET_FAMILY.names())}, got {route!r}")
        return route, True
    eligible = (["pallas", "xla_conv"]
                if _use_pallas(src_shape, order, dilation, stride)
                else ["xla_conv"])
    rows = int(np.prod(src_shape[:-1])) if len(src_shape) > 1 else 1
    # rows/n pow2-bucketed (finite tune classes under batch/length
    # churn); order/dilation/stride — the filter design — key exactly
    chosen = _WAVELET_FAMILY.select(
        eligible=eligible, runners=runners, probe_operand=src,
        rows=routing.pow2_bucket(rows),
        n=routing.pow2_bucket(int(src_shape[-1])), order=int(order),
        dilation=int(dilation), stride=int(stride))
    return chosen, False


def wavelet_apply(type, order, ext, src, simd=None, route=None):
    """Single DWT analysis step (``wavelet_apply``,
    ``inc/simd/wavelet.h:80-97``): returns ``(desthi, destlo)`` of length
    ``length/2`` each.

    ``route`` forces ``pallas`` (the Mosaic filter-bank kernel) or
    ``xla_conv`` (None auto-selects through the ``wavelet`` candidate
    table); a forced route re-raises on failure — it never silently
    degrades to the other implementation."""
    if not resolve_simd(simd, op="wavelet_apply"):
        return wavelet_apply_na(type, order, ext, src)
    src = jnp.asarray(src)
    _check_apply_args(type, order, src.shape[-1])
    runners = _wavelet_runners(src, type, order, ext, 2, 1,
                               src.shape[-1] // 2)
    chosen, forced = _select_wavelet_route(
        src.shape, int(order), 1, 2, route, runners, src)
    obs.record_decision(
        "wavelet_apply", chosen,
        family=WaveletType(type).value, order=int(order),
        ext=ExtensionType(ext).value, length=int(src.shape[-1]),
        forced=forced)
    with obs.span("wavelet_apply.dispatch", route=chosen):
        return runners[chosen]()


def stationary_wavelet_apply(type, order, level, ext, src, simd=None,
                             route=None):
    """Single SWT (à-trous) step at ``level`` ≥ 1
    (``stationary_wavelet_apply``, ``inc/simd/wavelet.h:119-139``):
    returns ``(desthi, destlo)`` of length ``length`` each.

    ``route`` forces ``pallas`` / ``xla_conv`` like
    :func:`wavelet_apply` (forced routes re-raise, never degrade)."""
    if not resolve_simd(simd, op="stationary_wavelet_apply"):
        return stationary_wavelet_apply_na(type, order, level, ext, src)
    src = jnp.asarray(src)
    _check_apply_args(type, order, src.shape[-1])
    if level < 1:
        raise ValueError("level must be >= 1")
    runners = _wavelet_runners(src, type, order, ext, 1,
                               1 << (level - 1), src.shape[-1])
    chosen, forced = _select_wavelet_route(
        src.shape, int(order), 1 << (level - 1), 1, route, runners, src)
    obs.record_decision(
        "stationary_wavelet_apply", chosen,
        family=WaveletType(type).value, order=int(order),
        level=int(level), ext=ExtensionType(ext).value,
        length=int(src.shape[-1]), forced=forced)
    return runners[chosen]()


# -- fused multi-level cascade --------------------------------------------
#
# The level loop below reads the running lowpass from HBM once per
# level.  For the PERIODIC extension, filtering commutes with the
# extension, so the whole cascade collapses into L independent
# decimated FIR banks on the ORIGINAL signal with composed filters
# (the "algorithme a trous" identity):
#
#   hi_l = (h upsampled by 2^(l-1)) * L_{l-1},   L_l = (l ^ 2^(l-1)) * L_{l-1}
#
# Phase-decomposing every level's stride-2^l output over ONE
# 2^L-phase deinterleave of the input makes every kernel access
# unit-stride at a static offset (the Mosaic constraint), so all
# levels run in a single Pallas pass: each input sample is read from
# HBM once for the entire cascade instead of once per level.
# Non-PERIODIC extensions do NOT commute with filtering (the cascade
# re-extends each computed lowpass), so they keep the level loop.

# unrolled-MAC budget for the fused kernel: compile time grows with the
# statement count; 3 levels of daub8 is ~176, sym16/3 levels ~368
_FUSED_MAX_MACS = 512
_FUSED_MAX_LEVELS = 4


def _composed_cascade_filters(type, order, levels):
    """Per-level equivalent filters of the PERIODIC DWT cascade,
    float64 host-side: ``[g_hi_1 .. g_hi_L]`` and the final composed
    lowpass ``L_L`` (correlation orientation, matching
    :func:`_filter_bank`)."""
    hi, lo = _filters(type, order)
    h = hi.astype(np.float64)
    low = lo.astype(np.float64)

    def up(f, s):
        out = np.zeros((len(f) - 1) * s + 1)
        out[::s] = f
        return out

    gs, l_prev = [], np.array([1.0])
    for lvl in range(1, int(levels) + 1):
        gs.append(np.convolve(up(h, 1 << (lvl - 1)), l_prev))
        l_prev = np.convolve(up(low, 1 << (lvl - 1)), l_prev)
    return gs, l_prev


def _cascade_plan(gs, g_lo, levels):
    """Static (plans, taps) for :func:`_pk.cascade_bank_pallas`: one
    channel per output phase of each level's highpass (phase r of
    ``hi_l`` is a unit-stride bank over the 2^L input phases: sample
    ``2^l j + m`` lands on phase ``(2^l r + m) % 2^L`` at offset
    ``(2^l r + m) // 2^L``), plus the final composed lowpass."""
    n_split = 1 << levels
    plans, taps, chans = [], [], []
    for lvl, g in enumerate(gs, start=1):
        for r in range(1 << (levels - lvl)):
            base = (1 << lvl) * r
            plans.append(tuple(((base + m) % n_split,
                                (base + m) // n_split)
                               for m in range(len(g))))
            taps.append(np.asarray(g, np.float32))
            chans.append((lvl, r))
    plans.append(tuple((m % n_split, m // n_split)
                       for m in range(len(g_lo))))
    taps.append(np.asarray(g_lo, np.float32))
    chans.append((levels + 1, 0))
    return tuple(plans), taps, chans


def _fused_cascade_gate(rows, n, order, ext, levels, **_):
    # DEMOTED round 5 (measured): on TPU v5e hardware the fused pass
    # LOSES to the level loop — 14,765 vs 17,384 Msamples/s (daub8 L3,
    # 512x4096, idle-host chained timing, 2026-07-31; reproduced twice).
    # The one-HBM-read premise undercounts the composed filters' extra
    # MACs: level-l taps grow to (order-1)(2^l - 1)+1, so the cascade
    # trades bandwidth it wasn't actually bound by for ~2x the FLOPs.
    # The kernel stays (tests exercise it; VELES_SIMD_FORCE_FUSED_CASCADE
    # opts in) as the measured record of a hypothesis that didn't pay —
    # per the 1D-kernel standard, a fused route must WIN to route.
    if os.environ.get("VELES_SIMD_FORCE_FUSED_CASCADE",
                      "0").strip().lower() not in ("1", "true", "yes",
                                                   "on"):
        return False
    levels = int(levels)
    if (ext != ExtensionType.PERIODIC.value
            or not 2 <= levels <= _FUSED_MAX_LEVELS):
        return False
    if n % (1 << levels):
        return False
    reach = (order - 1) * ((1 << levels) - 1)
    if reach >= n:       # composed filter wraps more than once
        return False
    n_macs = sum((1 << (levels - lvl))
                 * ((order - 1) * ((1 << lvl) - 1) + 1)
                 for lvl in range(1, levels + 1))
    n_macs += (order - 1) * ((1 << levels) - 1) + 1
    if n_macs > _FUSED_MAX_MACS:
        return False
    row_elems = (n + reach + (1 << levels)) + 2 * n
    return _pk.should_route(rows, row_elems)


# the cascade's own two-candidate table: the fused one-HBM-pass kernel
# is OPT-IN (it measured slower — the gate note above), the level loop
# is the terminal fallback and measured winner
_CASCADE_FAMILY = routing.family("wavelet.cascade", (
    routing.Route("fused_cascade", predicate=_fused_cascade_gate,
                  doc="whole PERIODIC DWT cascade in one Pallas pass "
                      "(opt-in: VELES_SIMD_FORCE_FUSED_CASCADE)"),
    routing.Route("level_loop",
                  doc="one filter-bank pass per level — the measured "
                      "winner on v5e"),
))


def _use_fused_cascade(src_shape, order, ext, levels) -> bool:
    """Thin delegate into the ``wavelet.cascade`` candidate table
    (gate note at :func:`_fused_cascade_gate`)."""
    rows = int(np.prod(src_shape[:-1])) if len(src_shape) > 1 else 1
    return _CASCADE_FAMILY.gate(
        "fused_cascade", rows=rows, n=int(src_shape[-1]),
        order=int(order), ext=ExtensionType(ext).value,
        levels=int(levels))


@functools.partial(obs.instrumented_jit,
                   static_argnames=("type", "order", "levels"))
def _fused_cascade(src, type, order, levels):
    """The whole PERIODIC DWT cascade in one Pallas pass (see the
    routing note above): returns ``(hi_1, ..., hi_L, lo_L)``."""
    gs, g_lo = _composed_cascade_filters(type, order, levels)
    plans, taps, chans = _cascade_plan(gs, g_lo, levels)
    n = src.shape[-1]
    n_split = 1 << levels
    reach = len(g_lo) - 1
    x_ext = _extend(src.astype(jnp.float32), ExtensionType.PERIODIC,
                    reach + n_split, jnp)
    outs = _pk.cascade_bank_pallas(x_ext, taps, plans, n_split,
                                   n // n_split)
    # re-interleave each level's output phases back to natural order
    coeffs = []
    for lvl in range(1, levels + 1):
        phases = [o for o, (lv, _) in zip(outs, chans) if lv == lvl]
        if len(phases) == 1:
            coeffs.append(phases[0])
        else:
            stacked = jnp.stack(phases, axis=-1)
            coeffs.append(stacked.reshape(
                stacked.shape[:-2] + (n >> lvl,)))
    coeffs.append(outs[-1])
    return tuple(coeffs)


def wavelet_transform(type, order, ext, src, levels, simd=None):
    """Multi-level DWT cascade: repeatedly split the lowpass band.

    The reference drives this manually via ``wavelet_recycle_source``
    (``tests/wavelet.cc`` cascade pattern); returns
    ``[hi_1, hi_2, ..., hi_levels, lo_levels]`` like the usual pyramid.

    Runs as the level loop (one filter-bank pass per level).  A fused
    one-HBM-pass Pallas cascade exists for PERIODIC but measured SLOWER
    on v5e hardware (fused 14,765 vs level-loop 17,384 Ms/s —
    composed-filter MACs outweigh the saved reads), so it is opt-in:
    ``VELES_SIMD_FORCE_FUSED_CASCADE=1`` (gate note at
    :func:`_use_fused_cascade`).
    """
    levels = int(levels)
    if resolve_simd(simd, op="wavelet_transform"):
        src_j = jnp.asarray(src)
        _check_apply_args(type, order, src_j.shape[-1])
        fused = _use_fused_cascade(src_j.shape, int(order), ext, levels)
        obs.record_decision(
            "wavelet_transform",
            "fused_cascade" if fused else "level_loop",
            family=WaveletType(type).value, order=int(order),
            levels=levels, ext=ExtensionType(ext).value,
            length=int(src_j.shape[-1]))
        if fused:
            with obs.span("wavelet_transform.dispatch",
                          route="fused_cascade", levels=levels):
                return list(_fused_cascade(src_j, WaveletType(type),
                                           int(order), levels))
        src = src_j
    coeffs = []
    cur = src
    for _ in range(levels):
        hi, lo = wavelet_apply(type, order, ext, cur, simd=simd)
        coeffs.append(hi)
        cur = lo
    coeffs.append(cur)
    return coeffs


def stationary_wavelet_transform(type, order, ext, src, levels, simd=None):
    """Multi-level SWT: level ℓ uses dilation 2^(ℓ-1) on the running
    lowpass (à-trous cascade).  Returns ``[hi_1, ..., hi_levels, lo_levels]``,
    all of the input length."""
    coeffs = []
    cur = src
    for lvl in range(1, int(levels) + 1):
        hi, lo = stationary_wavelet_apply(type, order, lvl, ext, cur,
                                          simd=simd)
        coeffs.append(hi)
        cur = lo
    coeffs.append(cur)
    return coeffs


# --------------------------------------------------------------------------
# synthesis (inverse transforms) — NEW capability beyond the reference
# --------------------------------------------------------------------------
#
# The reference ships analysis only; synthesis is its exact adjoint-based
# inverse for the PERIODIC extension, where the analysis operator is a
# scaled orthogonal map: A = c·Q with c² = Σ lowpass² (1 for the
# √2-normalized Daubechies table, ½ for the Symlet/Coiflet tables — the
# normalization note at the top of this module), so A⁻¹ = Aᵀ/c².  The
# adjoint of {extend periodically, stride-s dilated *correlation*} is
# {upsample, dilated *convolution* with the same (unflipped) filters,
# fold the tail back periodically}.  SWT is a 2× redundant frame:
# AᵀA = 2c²·I, hence the extra ½.


def _c2(lo_f) -> np.float32:
    """Filter energy Σ lowpass² — the analysis operator's scale² (single
    home for the normalization used by every synthesis path)."""
    return np.float32(np.sum(np.asarray(lo_f, np.float64) ** 2))


def _synth_conv(hi_band, lo_band, fh, fl, lhs_dil, rhs_dil, out_len, xp):
    """Shared synthesis kernel: y = conv(up_{lhs_dil}(hi), dil_{rhs_dil}(fh))
    + (same for lo), tail folded mod ``out_len`` (periodic adjoint)."""
    order = fh.shape[-1]
    pad = (order - 1) * rhs_dil
    batch_shape = hi_band.shape[:-1]
    m = hi_band.shape[-1]
    if m == 1:
        # dilating a singleton is the identity; the degenerate
        # lhs-dilated conv miscompiles on the TPU lowering (NaNs), so
        # clamp it away — output length is unchanged
        lhs_dil = 1
    if xp is np:
        def up(a):
            if lhs_dil == 1:
                return a
            u = np.zeros(a.shape[:-1] + ((m - 1) * lhs_dil + 1,), np.float64)
            u[..., ::lhs_dil] = a
            return u

        def dil(f):
            if rhs_dil == 1:
                return f.astype(np.float64)
            u = np.zeros((order - 1) * rhs_dil + 1)
            u[::rhs_dil] = f
            return u

        hi2 = up(hi_band.astype(np.float64)).reshape(-1, (m - 1) * lhs_dil + 1)
        lo2 = up(lo_band.astype(np.float64)).reshape(hi2.shape)
        y = np.stack([np.convolve(h, dil(fh)) + np.convolve(l, dil(fl))
                      for h, l in zip(hi2, lo2)])
    else:
        lhs = jnp.stack([hi_band, lo_band], axis=-2).reshape((-1, 2, m))
        rhs = jnp.stack([jnp.flip(fh, -1), jnp.flip(fl, -1)]
                        ).reshape(1, 2, order)
        y = jax.lax.conv_general_dilated(
            lhs.astype(jnp.float32), rhs.astype(jnp.float32),
            window_strides=(1,), padding=[(pad, pad)],
            lhs_dilation=(lhs_dil,), rhs_dilation=(rhs_dil,),
            precision=prx.HIGHEST)[:, 0]
    out = y[:, :out_len]
    if xp is np:
        out = out.copy()
    t = out_len
    while t < y.shape[-1]:           # static loop: shapes are concrete
        chunk = y[:, t:t + out_len]
        if xp is np:
            out[:, :chunk.shape[-1]] += chunk
        else:
            out = out.at[:, :chunk.shape[-1]].add(chunk)
        t += out_len
    return out.reshape(batch_shape + (out_len,))


@functools.partial(obs.instrumented_jit, static_argnames=("type", "order"))
def _dwt_synth(hi_band, lo_band, type, order):
    hi_f, lo_f = _filters(type, order)
    out = _synth_conv(hi_band, lo_band, jnp.asarray(hi_f), jnp.asarray(lo_f),
                      2, 1, 2 * hi_band.shape[-1], jnp)
    return (out / _c2(lo_f)).astype(jnp.float32)


@functools.partial(obs.instrumented_jit,
                   static_argnames=("type", "order", "level"))
def _swt_synth(hi_band, lo_band, type, order, level):
    hi_f, lo_f = _filters(type, order)
    out = _synth_conv(hi_band, lo_band, jnp.asarray(hi_f), jnp.asarray(lo_f),
                      1, 1 << (level - 1), hi_band.shape[-1], jnp)
    return (out / (2 * _c2(lo_f))).astype(jnp.float32)


def _check_synth_args(type, order, hi_band, lo_band):
    if not validate_order(type, order):
        raise ValueError(
            f"unsupported {WaveletType(type).value} order {order}")
    if hi_band.shape != lo_band.shape:
        raise ValueError(
            f"band shapes differ: {hi_band.shape} vs {lo_band.shape}")


# --------------------------------------------------------------------------
# non-periodic synthesis: Woodbury boundary correction
# --------------------------------------------------------------------------
#
# For MIRROR/CONSTANT/ZERO extensions (``src/wavelet.c:248-269`` modes) the
# analysis operator A_ext differs from the periodic A_per only in the
# boundary rows whose window crosses the right edge — order−2 rows for the
# DWT, 2·(order−1)·2^(ℓ−1) for the SWT — and every differing row has
# support confined to the first/last L samples (L = order·dilation).
# Reconstruction is the normal-equations least-squares solve
#
#   x = G⁻¹·A_extᵀy,   G = A_extᵀA_ext = g·I + U·C·Uᵀ
#
# with g = c² (DWT, A_per a scaled-orthogonal square map) or 2c² (SWT, a
# tight 2× frame), U = [s_k | d_k] the periodic boundary rows and the
# (ext − periodic) row differences, C = [[0,I],[I,I]] — so G⁻¹ applies by
# Woodbury as the fast periodic adjoint plus a compact boundary
# correction against a precomputed small system.  All U columns live on
# the boundary index set J = [0,L) ∪ [n−L,n); precompute is float64 NumPy
# cached per (type, order, ext, n, level); runtime is two compact matmuls
# + static slice updates on either backend.
#
# Exactness caveats (measured, tests pin them):
# * SWT: A_ext is full-rank but no longer tight — cond(A_ext) ≈ 450 for
#   daub8 — so f32 coefficient rounding amplifies to ~1e-4 relative
#   round-trip error concentrated at the boundary.  With float64 inputs
#   the reconstruction is exact to ~1e-13.
# * DWT: the reference's fixed-size non-periodic analysis is provably
#   RANK-DEFICIENT — order/2 − 1 singular values are exactly zero, i.e.
#   the transform itself destroys that many dimensions — so no inverse
#   exists.  The solve below (pinv of the small system when singular)
#   returns the least-squares reconstruction: re-analyzing it reproduces
#   the given coefficients, and signals in the row space round-trip
#   exactly; the lost null component is unrecoverable by any method.


def _analysis_row_compact(f, start, dil, n, L, ext):
    """Analysis row (window at ``start``, taps dilated by ``dil``,
    extension ``ext``) restricted to J = [0,L) ∪ [n−L,n), as a length-2L
    float64 vector.  Caller guarantees the row's support lies in J."""
    v = np.zeros(2 * L)

    def jpos(col):
        if col < L:
            return col
        assert col >= n - L, "boundary-row support escaped J"
        return L + col - (n - L)

    ext = ExtensionType(ext)
    for j, fj in enumerate(np.asarray(f, np.float64)):
        col = start + j * dil
        if col < n:
            v[jpos(col)] += fj
            continue
        e = col - n                       # extension sample index (< L ≤ n)
        if ext is ExtensionType.PERIODIC:
            v[jpos(e)] += fj
        elif ext is ExtensionType.MIRROR:
            v[jpos(n - 1 - e)] += fj
        elif ext is ExtensionType.CONSTANT:
            v[jpos(n - 1)] += fj
        # ZERO contributes nothing
    return v


def _check_ext_synth_length(n, L, what):
    if n < 2 * L:
        raise ValueError(
            f"non-periodic {what} synthesis needs length >= {2 * L} "
            f"(2x the boundary support order*dilation={L}) — got {n}; "
            "use ext=PERIODIC for shorter signals")


@functools.lru_cache(maxsize=256)
def _synth_boundary_correction(type, order, ext, n, stride, level):
    """(D, P, Q, r_band) for the normal-equations Woodbury boundary
    correction; None when no analysis window crosses the edge (e.g.
    order 2 DWT, where all four extensions coincide).

    ``stride=2, level=1`` is the DWT (g = c²); ``stride=1`` the SWT at
    ``level`` (g = 2c²).  ``Q = (C⁻¹ + UᵀU/g)⁻¹`` — pinv when the
    non-periodic DWT's rank deficiency makes it singular."""
    hi_f, lo_f = _filters(type, order)
    c2 = float(np.sum(np.asarray(lo_f, np.float64) ** 2))
    g = c2 * (2.0 / stride)
    dil = 1 << (level - 1)
    L = order * dil
    n_out = n // stride
    # first window i whose span [i·stride, i·stride + (order-1)·dil]
    # crosses the right edge: i ≥ ceil((n − (order−1)·dil) / stride)
    i_min = max(0, -(-(n - (order - 1) * dil) // stride))
    rows = [(f, i) for f in (hi_f, lo_f) for i in range(i_min, n_out)]
    if not rows:
        return None
    r = len(rows)
    D = np.zeros((r, 2 * L))
    S = np.zeros((r, 2 * L))
    for k, (f, i) in enumerate(rows):
        per = _analysis_row_compact(f, i * stride, dil, n, L,
                                    ExtensionType.PERIODIC)
        D[k] = _analysis_row_compact(f, i * stride, dil, n, L, ext) - per
        S[k] = per
    # G = A_extᵀA_ext = gI + U·C·Uᵀ, U = [Sᵀ Dᵀ], C = [[0,I],[I,I]]
    # (the S·Dᵀ + D·Sᵀ + D·Dᵀ expansion of (A_per+E)ᵀ(A_per+E) − gI)
    P = np.concatenate([S, D], axis=0)            # 2r x 2L
    eye = np.eye(r)
    c_inv = np.block([[-eye, eye], [eye, np.zeros((r, r))]])
    mid = c_inv + (P @ P.T) / g
    Q = (np.linalg.inv(mid) if np.linalg.cond(mid) < 1e12
         else np.linalg.pinv(mid, rcond=1e-10))
    return D, P, Q, n_out - i_min


def _apply_boundary(x, corr_j, n, L, xp):
    """x[J] -= corr_j, J = [0,L) ∪ [n−L,n) (slices are static)."""
    if xp is np:
        x[..., :L] -= corr_j[..., :L]
        x[..., n - L:] -= corr_j[..., L:]
        return x
    x = x.at[..., :L].add(-corr_j[..., :L])
    return x.at[..., n - L:].add(-corr_j[..., L:])


def _gather_boundary(x, n, L, xp):
    return xp.concatenate([x[..., :L], x[..., n - L:]], axis=-1)


def _synth_ext(hi_band, lo_band, type, order, level, ext, stride):
    """Least-squares inverse of the ``ext``-extended analysis: the
    periodic adjoint plus the compact normal-equations boundary
    correction (see the section comment), all in float64 NumPy — the
    solve must not run in f32 (cond(G) ≈ cond(A)² amplification; the
    device path handles this via :func:`_synth_ext_device`'s hybrid).
    ``stride=2`` DWT (output length 2m), ``stride=1`` SWT at ``level``."""
    hi_f, lo_f = _filters(type, order)
    c2 = _c2(lo_f)
    g = float(c2) * 2.0 / stride
    dil = 1 << (int(level) - 1)
    n = hi_band.shape[-1] * stride
    L = order * dil
    _check_ext_synth_length(n, L, "DWT" if stride == 2 else "SWT")
    z = np.asarray(_synth_conv(hi_band, lo_band, hi_f, lo_f, stride,
                               dil, n, np), np.float64)
    corr = _synth_boundary_correction(WaveletType(type), int(order),
                                      ExtensionType(ext), n, stride,
                                      int(level))
    if corr is None:
        return (z / g).astype(np.float32)
    D, P, Q, r_band = corr
    # A_extᵀy = A_perᵀy + Dᵀ·y_boundary (the differing rows' outputs)
    m_out = n // stride
    yb = np.concatenate([hi_band[..., m_out - r_band:],
                         lo_band[..., m_out - r_band:]], axis=-1)
    z = _apply_boundary(z, -(yb.astype(np.float64) @ D), n, L, np)
    zj = _gather_boundary(z, n, L, np)
    corr_j = ((zj @ P.T) @ Q.T) @ P / (g * g)
    x = z / g
    return _apply_boundary(x, corr_j, n, L, np).astype(np.float32)


@functools.lru_cache(maxsize=256)
def _synth_boundary_zmap(type, order, n, stride, level):
    """(M_z, B): float64 matrix mapping the per-band boundary coefficient
    chunks (first B and last B of hi then lo, concatenated → 4B values)
    to the periodic adjoint restricted to J = [0,L) ∪ [n−L,n).

    Lets the device path recompute the ill-conditioned boundary algebra
    on host in float64 — G⁻¹ equals I/g off J (U is supported on J), so
    only x[J] needs the higher precision."""
    hi_f, lo_f = _filters(type, order)
    dil = 1 << (level - 1)
    L = order * dil
    n_out = n // stride
    # windows contributing to J: starts in [0, L) ∪ [n−L−(order−1)dil, n)
    B = max(-(-L // stride), -(-(L + (order - 1) * dil) // stride))
    assert n_out >= 2 * B, "caller guarantees n >= 4L"
    chunk = list(range(B)) + list(range(n_out - B, n_out))
    M = np.zeros((2 * L, 4 * B))
    for b, f in enumerate((hi_f, lo_f)):
        f64 = np.asarray(f, np.float64)
        for c, i in enumerate(chunk):
            for j in range(order):
                t = (i * stride + j * dil) % n
                if t < L:
                    M[t, b * 2 * B + c] += f64[j]
                elif t >= n - L:
                    M[L + t - (n - L), b * 2 * B + c] += f64[j]
    return M, B


def _synth_ext_device(hi_band, lo_band, type, order, level, ext, stride):
    """Device-path non-periodic synthesis: bulk periodic adjoint on the
    accelerator (f32; exact off the boundary set), boundary samples
    recomputed on host in float64 — a pure-f32 solve would amplify
    rounding by cond(G) ≈ cond(A)² (measured ~1e-2 worst case vs ~1e-4
    for this hybrid, which matches the oracle path)."""
    type, order, level = WaveletType(type), int(order), int(level)
    ext = ExtensionType(ext)
    if isinstance(hi_band, jax.core.Tracer) or isinstance(
            lo_band, jax.core.Tracer):
        raise ValueError(
            "non-PERIODIC reconstruction cannot run inside jit: its "
            "boundary correction is computed on host in float64 (a pure "
            "in-graph f32 solve would amplify rounding by the boundary "
            "subsystem's squared condition number — see the "
            "boundary-correction section comment).  Call it outside jit, "
            "or use ext=PERIODIC (exact, fully jittable)")
    hi_f, lo_f = _filters(type, order)
    g = float(_c2(lo_f)) * 2.0 / stride
    dil = 1 << (level - 1)
    n = hi_band.shape[-1] * stride
    L = order * dil
    _check_ext_synth_length(n, L, "DWT" if stride == 2 else "SWT")
    if n < 4 * L:
        # boundary windows overlap both ends: run the whole (small)
        # problem through the float64 host path
        return jnp.asarray(_synth_ext(np.asarray(hi_band),
                                      np.asarray(lo_band), type, order,
                                      level, ext, stride))
    z = _synth_conv_jit(hi_band, lo_band, type, order, stride, dil, n)
    x = z / g
    corr = _synth_boundary_correction(type, order, ext, n, stride, level)
    if corr is None:
        return x.astype(jnp.float32)
    D, P, Q, r_band = corr
    M_z, B = _synth_boundary_zmap(type, order, n, stride, level)
    n_out = n // stride
    # one small device→host transfer: the boundary coefficient chunks
    chunks = np.concatenate(
        [np.asarray(hi_band[..., :B]), np.asarray(hi_band[..., n_out - B:]),
         np.asarray(lo_band[..., :B]), np.asarray(lo_band[..., n_out - B:])],
        axis=-1).astype(np.float64)
    z_j = chunks @ M_z.T                          # A_perᵀy over J, f64
    yb = np.concatenate([chunks[..., 2 * B - r_band:2 * B],
                         chunks[..., 4 * B - r_band:]], axis=-1)
    z_j += yb @ D                                 # + Eᵀy (all on J)
    corr_j = ((z_j @ P.T) @ Q.T) @ P / (g * g)
    x_j = jnp.asarray((z_j / g - corr_j).astype(np.float32))
    x = x.at[..., :L].set(x_j[..., :L])
    return x.at[..., n - L:].set(x_j[..., L:]).astype(jnp.float32)


@functools.partial(obs.instrumented_jit,
                   static_argnames=("type", "order", "stride",
                                    "dil", "n"))
def _synth_conv_jit(hi_band, lo_band, type, order, stride, dil, n):
    hi_f, lo_f = _filters(type, order)
    return _synth_conv(hi_band, lo_band, jnp.asarray(hi_f),
                       jnp.asarray(lo_f), stride, dil, n, jnp)





def wavelet_reconstruct(type, order, desthi, destlo, simd=None,
                        ext=ExtensionType.PERIODIC):
    """Exact inverse of :func:`wavelet_apply`: ``(hi, lo)`` of length
    ``m`` each → signal of length ``2m``.

    ``ext`` must name the extension the *analysis* used — PERIODIC uses
    the scaled-orthogonal adjoint directly; MIRROR/CONSTANT/ZERO add a
    Woodbury boundary correction (see the section comment above) and
    require ``2m >= 2*order``.  ZERO analysis of some signals is not
    injective at the last sample; the correction then returns the
    least-squares reconstruction.

    No reference analog (the reference is analysis-only); provided because
    synthesis is half of every real wavelet workflow.  The PERIODIC round
    trip is exact to f32 for every supported family/order; non-periodic
    reconstructions are least-squares (re-analysis consistency is exact;
    the round trip cannot be — the analysis is rank-deficient).  Tests in
    ``tests/test_wavelet_synthesis.py`` pin both guarantees.
    """
    if not resolve_simd(simd, op="wavelet"):
        return wavelet_reconstruct_na(type, order, desthi, destlo, ext=ext)
    desthi, destlo = jnp.asarray(desthi), jnp.asarray(destlo)
    _check_synth_args(type, order, desthi, destlo)
    ext = ExtensionType(ext)
    if ext is ExtensionType.PERIODIC:
        return _dwt_synth(desthi, destlo, WaveletType(type), int(order))
    return _synth_ext_device(desthi, destlo, type, order, 1, ext, 2)


def wavelet_reconstruct_na(type, order, desthi, destlo,
                           ext=ExtensionType.PERIODIC):
    """NumPy oracle twin of :func:`wavelet_reconstruct`."""
    desthi = np.asarray(desthi, np.float32)
    destlo = np.asarray(destlo, np.float32)
    _check_synth_args(type, order, desthi, destlo)
    ext = ExtensionType(ext)
    if ext is not ExtensionType.PERIODIC:
        return _synth_ext(desthi, destlo, type, order, 1, ext, 2)
    hi_f, lo_f = _filters(type, order)
    c2 = _c2(lo_f)
    out = _synth_conv(desthi, destlo, hi_f, lo_f, 2, 1,
                      2 * desthi.shape[-1], np)
    return (out / c2).astype(np.float32)


def stationary_wavelet_reconstruct(type, order, level, desthi, destlo,
                                   simd=None,
                                   ext=ExtensionType.PERIODIC):
    """Exact inverse of :func:`stationary_wavelet_apply`: the SWT is a
    2× redundant frame, so synthesis is the adjoint over ``2c²`` —
    plus, for non-PERIODIC ``ext`` (which must match the analysis), a
    Woodbury boundary correction on the normal equations (needs
    ``length >= 2*order*2^(level-1)``)."""
    if not resolve_simd(simd, op="wavelet"):
        return stationary_wavelet_reconstruct_na(type, order, level,
                                                 desthi, destlo, ext=ext)
    desthi, destlo = jnp.asarray(desthi), jnp.asarray(destlo)
    _check_synth_args(type, order, desthi, destlo)
    if level < 1:
        raise ValueError("level must be >= 1")
    ext = ExtensionType(ext)
    if ext is ExtensionType.PERIODIC:
        return _swt_synth(desthi, destlo, WaveletType(type), int(order),
                          int(level))
    return _synth_ext_device(desthi, destlo, type, order, level, ext, 1)


def stationary_wavelet_reconstruct_na(type, order, level, desthi, destlo,
                                      ext=ExtensionType.PERIODIC):
    """NumPy oracle twin of :func:`stationary_wavelet_reconstruct`."""
    desthi = np.asarray(desthi, np.float32)
    destlo = np.asarray(destlo, np.float32)
    _check_synth_args(type, order, desthi, destlo)
    if level < 1:
        raise ValueError("level must be >= 1")
    ext = ExtensionType(ext)
    if ext is not ExtensionType.PERIODIC:
        return _synth_ext(desthi, destlo, type, order, level, ext, 1)
    hi_f, lo_f = _filters(type, order)
    c2 = _c2(lo_f)
    out = _synth_conv(desthi, destlo, hi_f, lo_f, 1, 1 << (level - 1),
                      desthi.shape[-1], np)
    return (out / (2 * c2)).astype(np.float32)


def wavelet_inverse_transform(type, order, coeffs, simd=None,
                              ext=ExtensionType.PERIODIC):
    """Invert :func:`wavelet_transform`: ``[hi_1, ..., hi_L, lo_L]`` →
    the original signal (``ext`` must match the analysis cascade)."""
    coeffs = list(coeffs)
    if len(coeffs) < 2:
        raise ValueError("need [hi_1, ..., hi_L, lo_L] with L >= 1")
    cur = coeffs[-1]
    for hi in reversed(coeffs[:-1]):
        cur = wavelet_reconstruct(type, order, hi, cur, simd=simd, ext=ext)
    return cur


def stationary_wavelet_inverse_transform(type, order, coeffs, simd=None,
                                         ext=ExtensionType.PERIODIC):
    """Invert :func:`stationary_wavelet_transform` (à-trous cascade;
    ``ext`` must match the analysis)."""
    coeffs = list(coeffs)
    if len(coeffs) < 2:
        raise ValueError("need [hi_1, ..., hi_L, lo_L] with L >= 1")
    cur = coeffs[-1]
    for lvl in range(len(coeffs) - 1, 0, -1):
        cur = stationary_wavelet_reconstruct(type, order, lvl,
                                             coeffs[lvl - 1], cur,
                                             simd=simd, ext=ext)
    return cur


# --------------------------------------------------------------------------
# wavelet packet transform — NEW capability beyond the reference
# --------------------------------------------------------------------------
#
# The full binary filter-bank tree: unlike the DWT cascade (which only
# re-splits the lowpass), every band is split at every level, giving
# 2^levels uniform-bandwidth leaves.  The reference's own
# wavelet_recycle_source API (src/wavelet.c:138-165: a buffer quartered
# into desthihi/hilo/lohi/lolo) is shaped for exactly this two-level
# pattern, but the reference never ships the transform; here it is, with
# its inverse.


def wavelet_packet_transform(type, order, ext, src, levels, simd=None):
    """Full wavelet-packet decomposition: ``2^levels`` leaf bands, each
    ``[..., n / 2^levels]``, in natural (filter-bank) order — leaf ``i``'s
    bit ``b`` (MSB = level 1) says whether the hi (0) or lo (1) branch
    was taken at level ``b+1`` (hi comes first at every split, so leaf 0
    is the all-hi band).

    The two-level leaf layout matches the reference's
    ``wavelet_recycle_source`` quartering (``src/wavelet.c:138-165``):
    ``[hihi, hilo, lohi, lolo]``.
    """
    levels = int(levels)
    if levels < 1:
        raise ValueError("levels must be >= 1")
    xp = jnp if resolve_simd(simd, op="wavelet") else np
    # one stacked dispatch per level (all bands at a level share a
    # length), as wavelet_apply2d does for its column pass — 2^l
    # sequential calls would waste dispatches and shrink the batch the
    # Pallas routing gate sees
    stack = xp.asarray(src)[None]                    # [m=1, ..., n]
    for _ in range(levels):
        hi, lo = wavelet_apply(type, order, ext, stack, simd=simd)
        # interleave so band index doubles as 2i (hi) / 2i+1 (lo):
        # natural hi-first order at every level
        stack = xp.stack([hi, lo], axis=1).reshape(
            (2 * stack.shape[0],) + hi.shape[1:])
    return [stack[i] for i in range(stack.shape[0])]


def wavelet_packet_inverse_transform(type, order, coeffs, simd=None,
                                     ext=ExtensionType.PERIODIC):
    """Invert :func:`wavelet_packet_transform` (``ext`` must match the
    analysis; PERIODIC is exact, like :func:`wavelet_reconstruct`)."""
    bands = list(coeffs)
    n = len(bands)
    if n < 2 or n & (n - 1):
        raise ValueError(
            f"need 2^levels leaf bands, got {n}")
    xp = jnp if resolve_simd(simd, op="wavelet") else np
    stack = xp.stack([xp.asarray(b) for b in bands])   # [2m, ..., len]
    while stack.shape[0] > 1:
        pairs = stack.reshape((stack.shape[0] // 2, 2) + stack.shape[1:])
        stack = wavelet_reconstruct(type, order, pairs[:, 0], pairs[:, 1],
                                    simd=simd, ext=ext)
    return stack[0]


# --------------------------------------------------------------------------
# separable 2D transform — NEW capability beyond the reference
# --------------------------------------------------------------------------

def _apply_last(fn, x):
    """Run a last-axis transform along axis -2 by transposing around it
    (.swapaxes keeps NumPy arrays NumPy on the oracle path and jax
    arrays on-device on the XLA path)."""
    return tuple(o.swapaxes(-1, -2) for o in fn(x.swapaxes(-1, -2)))


def _separable_apply2d(rows, src, simd, what):
    """Shared separable-2D analysis plumbing: one row pass, then ONE
    stacked column pass (doubles the batch the Pallas routing gate sees
    and halves the dispatches vs transforming hi_r/lo_r apart).
    Returns ``(ll, lh, hl, hh)``."""
    if np.ndim(src) < 2:
        raise ValueError(f"{what} needs [..., n0, n1]")
    xp = jnp if resolve_simd(simd, op="wavelet") else np
    hi_r, lo_r = rows(xp.asarray(src))                # along n1
    bands, lows = _apply_last(rows, xp.stack([hi_r, lo_r]))
    hh, lh = bands[0], bands[1]
    hl, ll = lows[0], lows[1]
    return ll, lh, hl, hh


def _separable_reconstruct2d(synth, ll, lh, hl, hh, simd):
    """Shared separable-2D synthesis plumbing: one stacked column
    synthesis for both row bands, then the row synthesis."""
    xp = jnp if resolve_simd(simd, op="wavelet") else np
    hi_b = xp.stack([xp.asarray(hh), xp.asarray(lh)]).swapaxes(-1, -2)
    lo_b = xp.stack([xp.asarray(hl), xp.asarray(ll)]).swapaxes(-1, -2)
    rec = synth(hi_b, lo_b).swapaxes(-1, -2)
    return synth(rec[0], rec[1])


def wavelet_apply2d(type, order, ext, src, simd=None):
    """Separable single-level 2D DWT of ``[..., n0, n1]``: rows then
    columns.  Returns ``(LL, LH, HL, HH)``, each ``[..., n0/2, n1/2]``
    — the standard image-compression quad (first letter = row band,
    second = column band; L = lowpass).  No reference analog (the
    reference transforms 1D signals only)."""
    return _separable_apply2d(
        lambda v: wavelet_apply(type, order, ext, v, simd=simd),
        src, simd, "wavelet_apply2d")


def wavelet_reconstruct2d(type, order, ll, lh, hl, hh, simd=None,
                          ext=ExtensionType.PERIODIC):
    """Exact inverse of :func:`wavelet_apply2d`: columns then rows, each
    the 1D synthesis (separability makes any per-axis-exact ``ext``
    exact in 2D; ``ext`` must match the analysis)."""
    return _separable_reconstruct2d(
        lambda a, b: wavelet_reconstruct(type, order, a, b, simd=simd,
                                         ext=ext),
        ll, lh, hl, hh, simd)


def stationary_wavelet_apply2d(type, order, level, ext, src, simd=None):
    """Separable single-level 2D SWT (à-trous, undecimated) of
    ``[..., n0, n1]``: rows then columns at the same dilation.  Returns
    ``(LL, LH, HL, HH)``, each full ``[..., n0, n1]`` size — the
    shift-invariant quad image denoising wants (no decimation, so
    thresholding artifacts don't alias).  No reference analog."""
    return _separable_apply2d(
        lambda v: stationary_wavelet_apply(type, order, level, ext, v,
                                           simd=simd),
        src, simd, "stationary_wavelet_apply2d")


def stationary_wavelet_reconstruct2d(type, order, level, ll, lh, hl, hh,
                                     simd=None,
                                     ext=ExtensionType.PERIODIC):
    """Exact inverse of :func:`stationary_wavelet_apply2d`: columns then
    rows, each the 1D SWT least-squares synthesis (exact for PERIODIC;
    every extension round-trips within the boundary conditioning since
    the SWT frame is full-rank per axis)."""
    return _separable_reconstruct2d(
        lambda a, b: stationary_wavelet_reconstruct(type, order, level,
                                                    a, b, simd=simd,
                                                    ext=ext),
        ll, lh, hl, hh, simd)


def wavelet_packet_transform2d(type, order, ext, src, levels, simd=None):
    """Full 2D wavelet-packet (quad-tree) decomposition: every band is
    re-split at every level, giving ``4^levels`` uniform leaves, each
    ``[..., n0/2^levels, n1/2^levels]``, in natural order — leaf index
    interleaves the per-level quad choice ``(ll, lh, hl, hh)`` =
    ``(0, 1, 2, 3)``, MSB pair = level 1 — so leaf 0 is the all-LL
    (approximation) band.  NOTE this is LL-first, the reverse of the 1D
    :func:`wavelet_packet_transform`'s hi-first order (leaf 0 there is
    the all-hi band); 2D follows the ``(ll, lh, hl, hh)`` quad
    convention of :func:`wavelet_apply2d`.  No reference analog."""
    levels = int(levels)
    if levels < 1:
        raise ValueError("levels must be >= 1")
    xp = jnp if resolve_simd(simd, op="wavelet") else np
    stack = xp.asarray(src)[None]               # [m=1, ..., n0, n1]
    for _ in range(levels):
        quad = wavelet_apply2d(type, order, ext, stack, simd=simd)
        # [m, 4, ..., n0/2, n1/2] -> [4m, ...]: leaf index grows a
        # base-4 digit per level, natural (ll, lh, hl, hh) order
        stack = xp.stack(quad, axis=1).reshape(
            (4 * stack.shape[0],) + quad[0].shape[1:])
    return [stack[i] for i in range(stack.shape[0])]


def wavelet_packet_inverse_transform2d(type, order, coeffs, simd=None,
                                       ext=ExtensionType.PERIODIC):
    """Invert :func:`wavelet_packet_transform2d` (``ext`` must match the
    analysis; PERIODIC is exact)."""
    bands = list(coeffs)
    n = len(bands)
    levels = 0
    while 4 ** levels < n:
        levels += 1
    if n < 4 or 4 ** levels != n:
        raise ValueError(f"need 4^levels leaf bands, got {n}")
    xp = jnp if resolve_simd(simd, op="wavelet") else np
    stack = xp.stack([xp.asarray(b) for b in bands])
    while stack.shape[0] > 1:
        quads = stack.reshape((stack.shape[0] // 4, 4) + stack.shape[1:])
        stack = wavelet_reconstruct2d(
            type, order, quads[:, 0], quads[:, 1], quads[:, 2],
            quads[:, 3], simd=simd, ext=ext)
    return stack[0]


def wavelet_transform2d(type, order, ext, src, levels, simd=None):
    """Multi-level 2D DWT pyramid: recursively split the LL band.

    Returns ``[(lh_1, hl_1, hh_1), ..., (lh_L, hl_L, hh_L), ll_L]`` —
    the standard image-compression layout (detail triples coarse-ward,
    final approximation last)."""
    coeffs = []
    cur = src
    for _ in range(int(levels)):
        ll, lh, hl, hh = wavelet_apply2d(type, order, ext, cur, simd=simd)
        coeffs.append((lh, hl, hh))
        cur = ll
    coeffs.append(cur)
    return coeffs


def wavelet_inverse_transform2d(type, order, coeffs, simd=None,
                                ext=ExtensionType.PERIODIC):
    """Invert :func:`wavelet_transform2d` (``ext`` must match the
    analysis cascade)."""
    coeffs = list(coeffs)
    if len(coeffs) < 2:
        raise ValueError("need [(lh_1, hl_1, hh_1), ..., ll_L] with L >= 1")
    cur = coeffs[-1]
    for lh, hl, hh in reversed(coeffs[:-1]):
        cur = wavelet_reconstruct2d(type, order, cur, lh, hl, hh,
                                    simd=simd, ext=ext)
    return cur


# --------------------------------------------------------------------------
# API shims for the reference's layout helpers
# --------------------------------------------------------------------------

def wavelet_validate_order(type, order):
    """``inc/simd/wavelet.h:40-44``."""
    return validate_order(type, order)


def wavelet_prepare_array(order, src, length=None):
    """``inc/simd/wavelet.h:55-68``: on AVX this builds shifted duplicated
    copies so every load is aligned (``src/wavelet.c:64-119``); XLA owns
    layout, so it degenerates to a defensive copy — exactly the
    reference's own no-SIMD behavior (``src/wavelet.c:110-113``)."""
    src = np.asarray(src, np.float32)
    if length is not None and src.shape[-1] != int(length):
        raise ValueError("length does not match src")
    return src.copy()


def wavelet_allocate_destination(order, source_length):
    """``inc/simd/wavelet.h:69-80``: half-length zero buffer."""
    source_length = int(source_length)
    if source_length % 4:
        raise ValueError("sourceLength must be a multiple of 4 "
                         "(src/wavelet.c:126-127 contract)")
    return np.zeros(source_length // 2, np.float32)


def wavelet_recycle_source(order, src, length=None):
    """``inc/simd/wavelet.h:82-88``: split a scratch buffer into 4 quarter
    views for the next cascade level (``src/wavelet.c:138-165``).  Returns
    ``(desthihi, desthilo, destlohi, destlolo)`` or ``(None,)*4`` when the
    length is not a positive multiple of 4."""
    src = np.asarray(src)
    n = src.shape[-1] if length is None else int(length)
    if n == 0 or n % 4:
        return (None, None, None, None)
    lq = n // 4
    return tuple(src[..., i * lq:(i + 1) * lq] for i in range(4))
