"""smoke:resample wedge guard (PR 5).

BENCH_r05: the ``smoke:resample`` stage stalled 301 s on the relay and
got skipped — the (160, 147) case with DEFAULT taps compiles a
3201-tap dilated+strided conv.  The smoke now pins an explicit short
filter; these tests hold that line: every geometry the stage runs must
compile EAGERLY (``.lower().compile()`` on the exact shapes, no
deferred surprises on hardware), the filter budget must stay
smoke-sized, and the whole stage must pass on the CPU backend.
"""

import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools import tpu_smoke  # noqa: E402

from veles.simd_tpu.ops import resample as rs  # noqa: E402

# every resample_poly filter the smoke compiles must stay well under
# the default 20*max(up,down)+1 design that wedged r05 (3201 taps)
SMOKE_TAPS_BUDGET = 1024


def _smoke_geometries():
    """The exact (x2d, taps, up, down, out_len) set the smoke stage
    dispatches, reconstructed from its shared constants."""
    rows, n = tpu_smoke.RESAMPLE_SMOKE_SHAPE
    for up, down in tpu_smoke.RESAMPLE_SMOKE_RATES:
        taps = tpu_smoke._resample_smoke_taps(rs, up, down)
        up_r, down_r, taps_r = rs._normalize_resample_args(
            n, up, down, taps)
        out_len = rs.resample_length(n, up_r, down_r)
        yield rows, n, up_r, down_r, taps_r, out_len


def test_smoke_filter_stays_inside_budget():
    for rows, n, up, down, taps, out_len in _smoke_geometries():
        assert len(taps) <= SMOKE_TAPS_BUDGET, (
            f"({up}, {down}) smoke filter re-fattened to {len(taps)} "
            f"taps (> {SMOKE_TAPS_BUDGET}) — the r05 wedge class")


def test_smoke_shapes_compile_eagerly():
    """AOT-compile each geometry the stage will dispatch: the compile
    (the wedge-prone step) happens HERE, inside the test budget, on the
    exact shapes — never first on the relay."""
    import jax.numpy as jnp

    for rows, n, up, down, taps, out_len in _smoke_geometries():
        x = jnp.zeros((rows, n), jnp.float32)
        t = jnp.asarray(taps, jnp.float32)
        compiled = rs._resample_conv.lower(
            x, t, up, down, out_len).compile()
        assert compiled is not None, (up, down)


def test_resample_smoke_stage_passes_on_cpu():
    """The whole stage, as bench.py runs it (reproduces the r05 wedge
    scenario under JAX_PLATFORMS=cpu: it must finish and pass)."""
    err, tol = tpu_smoke._check_resample(np.random.RandomState(7))
    assert err <= tol
