"""The spectral route-dispatch lint rule (PR 5): every *_ROUTES table
entry must reach an instrumented_jit core, and public dispatchers must
index the table inside a ``with obs.span(...)`` scope."""

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import lint  # noqa: E402

GOOD = '''
import functools
from veles.simd_tpu import obs
from veles.simd_tpu.ops import pallas_kernels as _pk


@functools.partial(obs.instrumented_jit, op="stft", route="xla_fft")
def _core_xla(x):
    return x


def _run_xla(x):
    return _core_xla(x)


def _run_pallas(x):
    return _pk.stft_pallas(x, 256, 128)


_STFT_ROUTES = {"xla_fft": _run_xla, "pallas_fused": _run_pallas}


def stft(x, route):
    with obs.span("stft.dispatch", route=route):
        return _STFT_ROUTES[route](x)
'''

UNINSTRUMENTED = '''
from veles.simd_tpu import obs


def _run_raw(x):
    return x + 1


_STFT_ROUTES = {"raw": _run_raw}


def stft(x, route):
    with obs.span("stft.dispatch"):
        return _STFT_ROUTES[route](x)
'''

UNSPANNED = '''
import functools
from veles.simd_tpu import obs


@functools.partial(obs.instrumented_jit, op="stft", route="xla_fft")
def _core(x):
    return x


def _run(x):
    return _core(x)


_STFT_ROUTES = {"xla_fft": _run}


def stft(x, route):
    return _STFT_ROUTES[route](x)
'''

NO_TABLES = '''
def stft(x):
    return x
'''


def _errors(src):
    return lint.spectral_dispatch_errors(ast.parse(src), "spectral.py")


def test_good_module_passes():
    assert _errors(GOOD) == []


def test_uninstrumented_runner_flagged():
    errs = _errors(UNINSTRUMENTED)
    assert any("instrumented_jit" in e for e in errs)


def test_unspanned_dispatch_flagged():
    errs = _errors(UNSPANNED)
    assert any("obs.span" in e for e in errs)


def test_missing_tables_flagged():
    errs = _errors(NO_TABLES)
    assert any("_ROUTES" in e for e in errs)


def test_real_spectral_module_is_clean():
    src = (REPO / "veles/simd_tpu/ops/spectral.py").read_text()
    assert lint.spectral_dispatch_errors(
        ast.parse(src), "veles/simd_tpu/ops/spectral.py") == []


# --------------------------------------------------------------------------
# the fault-policy rule (PR 6): no raw `except Exception` around
# pallas/compile call sites in ops//parallel — failure policy lives in
# runtime/faults.py
# --------------------------------------------------------------------------

FAULT_BAD_PALLAS = '''
from veles.simd_tpu.ops import pallas_kernels as _pk


def run(x):
    try:
        return _pk.stft_pallas(x, 256, 128)
    except Exception:
        return None
'''

FAULT_BAD_PALLAS_ALIAS = '''
import veles.simd_tpu.ops.pallas_kernels as pkmod


def run(x):
    try:
        return pkmod.overlap_save_pallas(x, x)
    except Exception as e:
        raise
'''

FAULT_BAD_INSTRUMENTED = '''
import functools
from veles.simd_tpu import obs


@functools.partial(obs.instrumented_jit, op="conv", route="pallas")
def _core(x):
    return x


def run(x):
    try:
        return _core(x)
    except Exception:
        return None
'''

FAULT_BAD_BARE_EXCEPT = '''
from veles.simd_tpu.ops import pallas_kernels as _pk


def run(x):
    try:
        return _pk.filter_2d_pallas(x, x, 4, 4)
    except:  # noqa: E722
        return None
'''

FAULT_OK_NARROW = '''
from veles.simd_tpu.ops import pallas_kernels as _pk


def run(x):
    try:
        return _pk.stft_pallas(x, 256, 128)
    except ValueError:
        return None
'''

FAULT_OK_NO_COMPILE_SITE = '''
def load():
    try:
        return open("table.npz").read()
    except Exception:
        return None
'''


def _fault_errors(src):
    return lint.fault_handler_errors(ast.parse(src), "mod.py")


def test_fault_rule_flags_pallas_except():
    assert any("fault-policy" in e for e in _fault_errors(
        FAULT_BAD_PALLAS))


def test_fault_rule_tracks_import_alias():
    assert _fault_errors(FAULT_BAD_PALLAS_ALIAS)


def test_fault_rule_flags_instrumented_call():
    assert _fault_errors(FAULT_BAD_INSTRUMENTED)


def test_fault_rule_flags_bare_except():
    assert _fault_errors(FAULT_BAD_BARE_EXCEPT)


def test_fault_rule_allows_narrow_handler():
    assert _fault_errors(FAULT_OK_NARROW) == []


def test_fault_rule_ignores_non_compile_sites():
    assert _fault_errors(FAULT_OK_NO_COMPILE_SITE) == []


# --------------------------------------------------------------------------
# the routing rule (PR 7): selector predicates and route tables in
# ops//parallel must go through runtime/routing.py's candidate tables
# --------------------------------------------------------------------------

ROUTING_GOOD = '''
from veles.simd_tpu.runtime import routing

_FAMILY = routing.family("demo", (
    routing.Route("fast", predicate=lambda n, **_: n <= 4),
    routing.Route("slow"),
))


def _use_fast(n):
    return _FAMILY.gate("fast", n=n)


def _select_demo_route(n):
    return _FAMILY.static_select(n=n)


def _run_fast(x):
    return x


_DEMO_ROUTES = {"fast": _run_fast}
'''

ROUTING_GOOD_ALIASED = '''
import veles.simd_tpu.runtime.routing as rt

_FAMILY = rt.family("demo", (rt.Route("only"),))


def _use_only(n):
    return _FAMILY.gate("only", n=n)
'''

ROUTING_BAD_SELECTOR = '''
def _use_pallas_thing(n, k):
    return k <= 2047 and n >= 8 * k
'''

ROUTING_BAD_SELECT = '''
def _select_thing_route(n):
    return "fast" if n < 64 else "slow"
'''

ROUTING_BAD_TABLE = '''
def _run_fast(x):
    return x


_THING_ROUTES = {"fast": _run_fast}
'''


def _routing_errors(src):
    return lint.routing_selector_errors(ast.parse(src), "mod.py")


def test_routing_rule_passes_table_backed_selectors():
    assert _routing_errors(ROUTING_GOOD) == []


def test_routing_rule_tracks_module_alias():
    assert _routing_errors(ROUTING_GOOD_ALIASED) == []


def test_routing_rule_flags_hand_rolled_use_gate():
    errs = _routing_errors(ROUTING_BAD_SELECTOR)
    assert any("runtime.routing" in e for e in errs)


def test_routing_rule_flags_hand_rolled_select():
    assert _routing_errors(ROUTING_BAD_SELECT)


def test_routing_rule_flags_routes_table_without_family():
    errs = _routing_errors(ROUTING_BAD_TABLE)
    assert any("routing.family" in e for e in errs)


ROUTING_BAD_DECOY_IMPORT = '''
from veles.simd_tpu.runtime.routing import tune_key_str

_K = tune_key_str("f", {})


def _run_fast(x):
    return x


_FOO_ROUTES = {"fast": _run_fast}


def _use_bar(n):
    return n < 64 and bool(_K)
'''


def test_routing_rule_not_satisfied_by_decoy_import():
    """Importing some OTHER routing symbol and calling it must not
    count as declaring a candidate table (review finding: only the
    `family` factory mints tables)."""
    errs = _routing_errors(ROUTING_BAD_DECOY_IMPORT)
    assert any("routing.family" in e for e in errs)          # table half
    assert any("_use_bar" in e for e in errs)                # selector half


ROUTING_BAD_MODULE_ALIAS_DECOY = '''
from veles.simd_tpu.runtime import routing

_FAMILY = routing.family("demo", (routing.Route("only"),))


def _use_newkernel(n):
    return n <= 4096 and routing.pow2_bucket(n) >= 64
'''


def test_routing_rule_not_satisfied_by_module_alias_decoy():
    """A hand-rolled selector that merely CALLS an unrelated helper
    off the routing module alias (pow2_bucket) is not delegating to
    the engine — only a family-bound table, the family factory, or
    <alias>.family/get_family count (review finding)."""
    errs = _routing_errors(ROUTING_BAD_MODULE_ALIAS_DECOY)
    assert any("_use_newkernel" in e for e in errs)


ROUTING_GOOD_FAMILY_FN = '''
from veles.simd_tpu.runtime.routing import Route, family

_FAMILY = family("demo", (Route("only"),))


def _use_only(n):
    return _FAMILY.gate("only", n=n)
'''


def test_routing_rule_accepts_family_fn_import():
    assert _routing_errors(ROUTING_GOOD_FAMILY_FN) == []


def test_real_compute_modules_pass_routing_rule():
    """Acceptance gate: zero hand-rolled selectors left in ops/ —
    every route constant lives in a runtime.routing candidate table."""
    for sub in ("ops", "parallel"):
        for path in sorted((REPO / "veles/simd_tpu" / sub).glob("*.py")):
            rel = path.relative_to(REPO).as_posix()
            errs = lint.routing_selector_errors(
                ast.parse(path.read_text()), rel)
            assert errs == [], errs


def test_real_compute_modules_have_no_inline_fault_handlers():
    """Acceptance gate: zero hand-rolled demote try/except blocks
    remain anywhere in ops/ or parallel/ — all three demotion paths
    (convolve os, convolve2d, stft) went through runtime/faults.py."""
    for sub in ("ops", "parallel"):
        for path in sorted((REPO / "veles/simd_tpu" / sub).glob("*.py")):
            rel = path.relative_to(REPO).as_posix()
            errs = lint.fault_handler_errors(
                ast.parse(path.read_text()), rel)
            assert errs == [], errs


# --------------------------------------------------------------------------
# the dispatch rule extended to parallel/fourier.py (PR 8): sharded
# route runners may reach the resource axis through the module-level
# `_instrumented` shard_map wrapper (transitively), and the sharded
# selectors must delegate to a routing.family-bound table like every
# other compute module
# --------------------------------------------------------------------------

PARALLEL_GOOD = '''
import functools
from veles.simd_tpu import obs


def _instrumented(op, run_fn):
    return obs.instrumented_jit(run_fn, op=op, route="shard_map")


def _ct_sharded(v):
    def _run(x):
        return x
    return _instrumented("sharded_rfft", _run)(v)


def _run_matmul(x, mesh):
    return _ct_sharded(x)


_RFFT_ROUTES = {"sharded_matmul_dft": _run_matmul}


def sharded_rfft(x, mesh, route):
    with obs.span("sharded_rfft.dispatch", route=route):
        return _RFFT_ROUTES[route](x, mesh)
'''

PARALLEL_BAD_RUNNER = '''
from veles.simd_tpu import obs


def _run_matmul(x, mesh):
    return x + 1


_RFFT_ROUTES = {"sharded_matmul_dft": _run_matmul}


def sharded_rfft(x, mesh, route):
    with obs.span("sharded_rfft.dispatch", route=route):
        return _RFFT_ROUTES[route](x, mesh)
'''


def test_parallel_runner_via_instrumented_wrapper_passes():
    """A runner reaching obs.instrumented_jit TRANSITIVELY through the
    parallel `_instrumented` shard_map wrapper satisfies the dispatch
    rule (the resource axis sees the compile)."""
    assert _errors(PARALLEL_GOOD) == []


def test_parallel_runner_without_instrumented_core_flagged():
    errs = _errors(PARALLEL_BAD_RUNNER)
    assert any("instrumented_jit" in e for e in errs)


def test_dispatch_rule_covers_parallel_fourier():
    """The rule is WIRED for parallel/fourier.py (not just spectral)
    and the real module is clean."""
    assert ("veles/simd_tpu/parallel/fourier.py"
            in lint._DISPATCH_RULE_FILES)
    src = (REPO / "veles/simd_tpu/parallel/fourier.py").read_text()
    assert lint.spectral_dispatch_errors(
        ast.parse(src), "veles/simd_tpu/parallel/fourier.py") == []


ROUTING_BAD_SHARDED_SELECT = '''
def select_frame_route(frame_length):
    return "rdft_matmul" if frame_length <= 4096 else "xla_fft"
'''


def test_routing_rule_flags_hand_rolled_sharded_selector():
    """A public `select_*` sharded selector with inline constants (no
    family table) is a lint failure — the parallel/ extension of the
    routing rule."""
    errs = _routing_errors(ROUTING_BAD_SHARDED_SELECT)
    assert any("select_frame_route" in e for e in errs)


# --- the serving-layer rule (PR 9) -----------------------------------------

SERVE_GOOD = '''
from veles.simd_tpu import obs
from veles.simd_tpu.ops import batched
from veles.simd_tpu.runtime import faults


def _device_call(xs, params):
    return batched.batched_sosfilt(params["sos"], xs, simd=True)


def _oracle_call(xs, params):
    return batched.batched_sosfilt(params["sos"], xs, simd=False)


def dispatch(xs, params):
    def thunk():
        return _device_call(xs, params)

    with obs.span("serve.dispatch"):
        return faults.guarded(
            "serve.dispatch", thunk,
            fallback=lambda: _oracle_call(xs, params))
'''

SERVE_BARE_DISPATCH = '''
from veles.simd_tpu import obs
from veles.simd_tpu.ops import batched


def dispatch(xs, sos):
    with obs.span("serve.dispatch"):
        return batched.batched_sosfilt(sos, xs, simd=True)
'''

SERVE_RAW_TIME = '''
import time

from veles.simd_tpu import obs


def deadline():
    return time.monotonic() + 0.002
'''

SERVE_NO_OBS = '''
from veles.simd_tpu.ops import batched
from veles.simd_tpu.runtime import faults


def dispatch(xs, sos):
    def thunk():
        return batched.batched_sosfilt(sos, xs, simd=True)

    return faults.guarded("serve.dispatch", thunk)
'''

SERVE_ALIAS_DODGE = '''
import time as _clock

from veles.simd_tpu import obs
from veles.simd_tpu.ops import batched as _b
from veles.simd_tpu.runtime import faults


def dispatch(xs, sos):
    _ = _clock.monotonic()
    with obs.span("serve.dispatch"):
        return _b.batched_sosfilt(sos, xs, simd=True)
'''


def _serve_errs(src):
    return lint.serve_layer_errors(ast.parse(src), "mod.py")


def test_serve_rule_passes_guarded_module():
    assert _serve_errs(SERVE_GOOD) == []


def test_serve_rule_flags_bare_dispatch():
    errs = _serve_errs(SERVE_BARE_DISPATCH)
    assert any("faults.guarded" in e for e in errs)


def test_serve_rule_flags_raw_time():
    errs = _serve_errs(SERVE_RAW_TIME)
    assert any("faults.monotonic" in e for e in errs)


def test_serve_rule_requires_obs_recording():
    errs = _serve_errs(SERVE_NO_OBS)
    assert any("unobservable" in e for e in errs)


def test_serve_rule_tracks_aliases():
    errs = _serve_errs(SERVE_ALIAS_DODGE)
    assert any("time import" in e for e in errs)
    assert any("faults.guarded" in e for e in errs)


def test_serve_rule_exempts_oracle_paths():
    src = SERVE_GOOD + '''

def degraded_answer(xs, params):
    with obs.span("serve.degraded"):
        return _oracle_call(xs, params)
'''
    assert _serve_errs(src) == []


def test_real_serve_modules_pass_serve_rule():
    serve_dir = REPO / "veles" / "simd_tpu" / "serve"
    files = sorted(serve_dir.glob("*.py"))
    assert files, "serve package missing?"
    for f in files:
        tree = ast.parse(f.read_text(), str(f))
        assert lint.serve_layer_errors(tree, str(f)) == [], f


SERVE_DOTTED_DODGE = '''
from veles.simd_tpu import obs, ops


def dispatch(xs, sos):
    with obs.span("serve.dispatch"):
        return ops.batched.batched_sosfilt(sos, xs, simd=True)
'''

SERVE_ROOT_DODGE = '''
import veles.simd_tpu.ops

from veles.simd_tpu import obs


def dispatch(xs, sos):
    with obs.span("serve.dispatch"):
        return veles.simd_tpu.ops.batched.batched_sosfilt(
            sos, xs, simd=True)
'''


def test_serve_rule_flags_dotted_package_dodge():
    for src in (SERVE_DOTTED_DODGE, SERVE_ROOT_DODGE):
        errs = _serve_errs(src)
        assert any("faults.guarded" in e for e in errs), src


def test_serve_rule_ignores_cache_introspection():
    src = SERVE_GOOD + '''

def peek():
    obs.count("serve_peek")
    return batched.handle_cache_info()
'''
    assert _serve_errs(src) == []


# ---------------------------------------------------------------------------
# the cluster router rule (serve/cluster.py, PR 13)
# ---------------------------------------------------------------------------

CLUSTER_GOOD = '''
class Router:
    def _submit_to_replica(self, replica, request, ctx):
        remaining = self._remaining(ctx)
        return replica.server.submit(request, deadline_ms=remaining)

    def _place(self, replica, request, ctx):
        return self._submit_to_replica(replica, request, ctx)
'''

CLUSTER_BYPASS = '''
class Router:
    def _submit_to_replica(self, replica, request, ctx):
        return replica.server.submit(request)

    def _failover(self, replica, request):
        # fresh-deadline drift: submits around the funnel
        return replica.server.submit(request, deadline_ms=1000.0)
'''

CLUSTER_HELPER_BYPASS = '''
def quick_place(group, request):
    return group.replicas[0].server.submit(request)
'''


def _cluster_errs(src):
    return lint.cluster_router_errors(ast.parse(src), "mod.py")


def test_cluster_rule_passes_funnelled_router():
    assert _cluster_errs(CLUSTER_GOOD) == []


def test_cluster_rule_flags_submit_outside_funnel():
    errs = _cluster_errs(CLUSTER_BYPASS)
    assert len(errs) == 1
    assert "_submit_to_replica" in errs[0]


def test_cluster_rule_flags_module_level_helper():
    errs = _cluster_errs(CLUSTER_HELPER_BYPASS)
    assert len(errs) == 1


def test_real_cluster_module_passes_cluster_rule():
    f = REPO / "veles" / "simd_tpu" / "serve" / "cluster.py"
    tree = ast.parse(f.read_text(), str(f))
    assert lint.cluster_router_errors(tree, str(f)) == []
    # and the generic serve rules hold for it too (no raw time,
    # request-trace terminal metrics banned)
    assert lint.serve_layer_errors(tree, str(f)) == []
    assert lint.request_trace_errors(tree, str(f)) == []


# ---------------------------------------------------------------------------
# the fleet funnel rule (obs v5): serve code reads cross-replica
# metrics ONLY through the collector funnel / obs.signals() —
# ad-hoc scraping beside it forks the fleet's view
# ---------------------------------------------------------------------------

FLEET_GOOD = '''
from veles.simd_tpu import obs
from veles.simd_tpu.obs import export as obs_export


class Group:
    def _collect_fleet_sample(self):
        store = obs.fleet_series()
        parsed = obs_export.parse_prometheus(self._scrape("r0"))
        obs.fleet_record("r0", "completed", sum(parsed.values()),
                         t_s=0.0)
        store.tick()

    def autoscale_input(self):
        # the read side of the contract stays legal everywhere
        return obs.signals()
'''

FLEET_SCRAPE_BYPASS = '''
from veles.simd_tpu import obs
from veles.simd_tpu.obs import export as obs_export


class Group:
    def _collect_fleet_sample(self):
        obs.fleet_record("r0", "up", 1.0, t_s=0.0)

    def _peek(self, body):
        # ad-hoc scrape beside the funnel: a second reader with a
        # second cadence
        return obs_export.parse_prometheus(body)
'''

FLEET_STORE_BYPASS = '''
from veles.simd_tpu import obs as telemetry


def route_score():
    return telemetry.fleet_series().value("r0", "depth")
'''

FLEET_SNAPSHOT_BYPASS = '''
from veles.simd_tpu import obs


def router_peek():
    return obs.snapshot()["counters"]
'''

FLEET_IMPORT_ALIAS_BYPASS = '''
from veles.simd_tpu.obs.export import parse_prometheus as pp


def sneak(body):
    return pp(body)
'''


def _fleet_errs(src):
    return lint.fleet_funnel_errors(ast.parse(src), "mod.py")


def test_fleet_rule_passes_funnelled_collector():
    assert _fleet_errs(FLEET_GOOD) == []


def test_fleet_rule_flags_scrape_outside_funnel():
    errs = _fleet_errs(FLEET_SCRAPE_BYPASS)
    assert len(errs) == 1
    assert "_collect_fleet_sample" in errs[0]
    assert "parse_prometheus" in errs[0]


def test_fleet_rule_flags_store_and_snapshot_reads():
    for src in (FLEET_STORE_BYPASS, FLEET_SNAPSHOT_BYPASS):
        errs = _fleet_errs(src)
        assert len(errs) == 1, src
        assert "_collect_fleet_sample" in errs[0]


def test_fleet_rule_tracks_import_alias():
    errs = _fleet_errs(FLEET_IMPORT_ALIAS_BYPASS)
    assert len(errs) == 1
    assert "pp(...)" in errs[0]


def test_real_serve_modules_pass_fleet_rule():
    serve_dir = REPO / "veles" / "simd_tpu" / "serve"
    for f in sorted(serve_dir.glob("*.py")):
        tree = ast.parse(f.read_text(), str(f))
        assert lint.fleet_funnel_errors(tree, str(f)) == [], f.name


# ---------------------------------------------------------------------------
# the request-trace rule (obs v4): terminal request accounting in
# serve//pipeline/ must flow through the request-trace API — a
# hand-rolled obs.count/observe of the terminal metrics drifts
# ---------------------------------------------------------------------------

TRACE_HAND_ROLLED_COUNT = '''
from veles.simd_tpu import obs


def finish(op, status):
    obs.count("serve_completed", op=op, status=status)
'''

TRACE_HAND_ROLLED_OBSERVE = '''
from veles.simd_tpu import obs


def finish(op, wait):
    obs.observe("serve.request_latency", wait, op=op)
'''

TRACE_HAND_ROLLED_MISS = '''
from veles.simd_tpu import obs


def expire(op, tenant):
    obs.count("serve_deadline_miss", op=op, tenant=tenant)
'''

TRACE_ALIAS_DODGE = '''
from veles.simd_tpu import obs as _o


def finish(op, status):
    _o.count("serve_completed", op=op, status=status)
'''

TRACE_CLEAN = '''
from veles.simd_tpu import obs


def submit(op, tenant):
    trace = obs.request_trace(op, tenant=tenant)
    obs.count("serve_submitted", op=op, tenant=tenant)
    return trace


def finish(trace, status):
    trace.finish(status)
'''


def _trace_errs(src):
    return lint.request_trace_errors(ast.parse(src), "mod.py")


def test_request_trace_rule_flags_terminal_count():
    errs = _trace_errs(TRACE_HAND_ROLLED_COUNT)
    assert any("request-trace API" in e for e in errs)


def test_request_trace_rule_flags_terminal_observe():
    errs = _trace_errs(TRACE_HAND_ROLLED_OBSERVE)
    assert any("serve.request_latency" in e for e in errs)


def test_request_trace_rule_flags_deadline_miss_count():
    errs = _trace_errs(TRACE_HAND_ROLLED_MISS)
    assert any("serve_deadline_miss" in e for e in errs)


def test_request_trace_rule_tracks_obs_alias():
    errs = _trace_errs(TRACE_ALIAS_DODGE)
    assert any("request-trace API" in e for e in errs)


def test_request_trace_rule_passes_trace_api_and_nonterminal():
    assert _trace_errs(TRACE_CLEAN) == []


def test_real_serve_and_pipeline_pass_request_trace_rule():
    for pkg in ("serve", "pipeline"):
        pkg_dir = REPO / "veles" / "simd_tpu" / pkg
        files = sorted(pkg_dir.glob("*.py"))
        assert files, f"{pkg} package missing?"
        for f in files:
            tree = ast.parse(f.read_text(), str(f))
            assert lint.request_trace_errors(tree, str(f)) == [], f


# ---------------------------------------------------------------------------
# the sharded-dispatch rule (PR 10): instrumented shard_map programs in
# parallel/ops.py must dispatch inside faults.guarded thunks
# ---------------------------------------------------------------------------

def _parallel_errs(src):
    return lint.parallel_guard_errors(ast.parse(src), "mod.py")


PGUARD_GOOD = '''
from veles.simd_tpu import obs
from veles.simd_tpu.runtime import faults


def _instrumented(op, run_fn):
    return obs.instrumented_jit(run_fn, op=op, route="shard_map")


def _sharded_guard(op, thunk, fallback, mesh, axis):
    return faults.guarded(f"parallel.{op}", thunk, fallback=fallback)


def sharded_thing(x, mesh, axis="sp"):
    def _run(x_local):
        return x_local

    jfn = _instrumented("sharded_thing", _run)
    return _sharded_guard("sharded_thing", lambda: jfn(x),
                          lambda: x, mesh, axis)
'''

PGUARD_BARE = '''
from veles.simd_tpu import obs
from veles.simd_tpu.runtime import faults


def _instrumented(op, run_fn):
    return obs.instrumented_jit(run_fn, op=op, route="shard_map")


def sharded_thing(x, mesh, axis="sp"):
    def _run(x_local):
        return x_local

    return _instrumented("sharded_thing", _run)(x)
'''

PGUARD_HANDLE_DODGE = '''
from veles.simd_tpu import obs
from veles.simd_tpu.runtime import faults


def _instrumented(op, run_fn):
    return obs.instrumented_jit(run_fn, op=op, route="shard_map")


def sharded_thing(x, mesh, axis="sp"):
    def _run(x_local):
        return x_local

    jfn = _instrumented("sharded_thing", _run)
    return jfn(x)
'''

PGUARD_DIRECT_JIT = '''
from veles.simd_tpu import obs


def sharded_thing(x):
    def _run(x_local):
        return x_local

    return obs.instrumented_jit(_run, op="t", route="shard_map")(x)
'''

PGUARD_GUARDED_DIRECT = '''
from veles.simd_tpu import obs
from veles.simd_tpu.runtime import faults


def _instrumented(op, run_fn):
    return obs.instrumented_jit(run_fn, op=op, route="shard_map")


def sharded_thing(x, mesh, axis="sp"):
    def _run(x_local):
        return x_local

    jfn = _instrumented("sharded_thing", _run)
    return faults.guarded("parallel.sharded_thing", lambda: jfn(x),
                          fallback=lambda: x)
'''


def test_parallel_guard_rule_passes_wrapper_convention():
    assert _parallel_errs(PGUARD_GOOD) == []


def test_parallel_guard_rule_passes_direct_guarded():
    assert _parallel_errs(PGUARD_GUARDED_DIRECT) == []


def test_parallel_guard_rule_flags_bare_dispatch():
    errs = _parallel_errs(PGUARD_BARE)
    assert any("faults.guarded" in e for e in errs)


def test_parallel_guard_rule_flags_bound_handle_dodge():
    errs = _parallel_errs(PGUARD_HANDLE_DODGE)
    assert any("faults.guarded" in e for e in errs)


def test_parallel_guard_rule_flags_direct_instrumented_jit():
    errs = _parallel_errs(PGUARD_DIRECT_JIT)
    assert any("faults.guarded" in e for e in errs)


def test_real_parallel_ops_passes_guard_rule():
    f = REPO / "veles" / "simd_tpu" / "parallel" / "ops.py"
    tree = ast.parse(f.read_text(), str(f))
    assert lint.parallel_guard_errors(tree, str(f)) == []


PGUARD_BREAKER_GUARDED = '''
from veles.simd_tpu import obs
from veles.simd_tpu.runtime import faults


def _instrumented(op, run_fn):
    return obs.instrumented_jit(run_fn, op=op, route="shard_map")


def _sharded_guard(op, thunk, fallback, mesh, axis):
    return faults.breaker_guarded(f"parallel.{op}", (op,), thunk,
                                  fallback=fallback,
                                  breaker_site="parallel.dispatch")


def sharded_thing(x, mesh, axis="sp"):
    def _run(x_local):
        return x_local

    jfn = _instrumented("sharded_thing", _run)
    return _sharded_guard("sharded_thing", lambda: jfn(x),
                          lambda: x, mesh, axis)
'''


def test_parallel_guard_rule_accepts_breaker_guarded():
    assert _parallel_errs(PGUARD_BREAKER_GUARDED) == []


# --- the pipeline rule (stage routing + guarded compiled step) -------------

def _pipe_route_errs(src):
    return lint.pipeline_route_errors(ast.parse(src), "<mem>")


def _pipe_guard_errs(src):
    return lint.pipeline_guard_errors(ast.parse(src), "<mem>")


PIPE_ROUTE_GOOD_HOOK = '''
from veles.simd_tpu.ops import convolve as _cv
from veles.simd_tpu.runtime import routing


class _FirStage:
    def resolve(self, tune_stamp):
        self.route = _cv.select_stream_route(
            1024, 33, tune_geom=tune_stamp({"h_length": 33}))
        return self.route
'''

PIPE_ROUTE_GOOD_ENGINE = '''
from veles.simd_tpu.runtime import routing


class _Stage:
    def resolve(self, tune_stamp):
        fam = routing.get_family("stft")
        self.route = fam.select(frame_length=256, hop=64, frames=8)
        return self.route
'''

PIPE_ROUTE_TRIVIAL = '''
class _Stage:
    def resolve(self, tune_stamp):
        return None
'''

PIPE_ROUTE_HAND_ROLLED = '''
class _Stage:
    def resolve(self, tune_stamp):
        # a hand-written ladder: no family table consulted
        self.route = "fast" if self.k <= 2047 else "slow"
        return self.route
'''

PIPE_ROUTE_DECOY_MODULE = '''
import math as _cv


class _Stage:
    def resolve(self, tune_stamp):
        # select_-named attr on a NON-ops module must not satisfy
        self.route = _cv.select_stream_route(1024, 33)
        return self.route
'''


def test_pipeline_route_rule_accepts_ops_hook():
    assert _pipe_route_errs(PIPE_ROUTE_GOOD_HOOK) == []


def test_pipeline_route_rule_accepts_engine_direct():
    assert _pipe_route_errs(PIPE_ROUTE_GOOD_ENGINE) == []


def test_pipeline_route_rule_skips_trivial_resolve():
    assert _pipe_route_errs(PIPE_ROUTE_TRIVIAL) == []


def test_pipeline_route_rule_flags_hand_rolled_ladder():
    errs = _pipe_route_errs(PIPE_ROUTE_HAND_ROLLED)
    assert any("routing.family" in e for e in errs)


def test_pipeline_route_rule_flags_non_ops_decoy():
    errs = _pipe_route_errs(PIPE_ROUTE_DECOY_MODULE)
    assert any("routing.family" in e for e in errs)


PIPE_GUARD_GOOD = '''
from veles.simd_tpu import obs
from veles.simd_tpu.runtime import faults


class Compiled:
    def __init__(self, fn):
        self._step = obs.instrumented_jit(fn, op="pipeline")

    def _run_fused(self, block, state):
        return self._step(block, state)

    def process(self, block, state):
        return faults.breaker_guarded(
            "pipeline.dispatch", ("p", 512),
            lambda: self._run_fused(block, state),
            fallback=lambda: (block, state))
'''

PIPE_GUARD_BARE = '''
from veles.simd_tpu import obs


class Compiled:
    def __init__(self, fn):
        self._step = obs.instrumented_jit(fn, op="pipeline")

    def process(self, block, state):
        return self._step(block, state)
'''

PIPE_GUARD_UNREFERENCED_METHOD = '''
from veles.simd_tpu import obs
from veles.simd_tpu.runtime import faults


class Compiled:
    def __init__(self, fn):
        self._step = obs.instrumented_jit(fn, op="pipeline")

    def _run_fused(self, block, state):
        return self._step(block, state)

    def process(self, block, state):
        # the guard never references _run_fused: the step dispatch
        # inside it is unguarded
        return faults.breaker_guarded(
            "pipeline.dispatch", ("p", 512),
            lambda: (block, state),
            fallback=lambda: (block, state))

    def sneak(self, block, state):
        return self._run_fused(block, state)
'''

PIPE_GUARD_ALIAS_DODGE = '''
from veles.simd_tpu import obs as telemetry


class Compiled:
    def __init__(self, fn):
        self.step = telemetry.instrumented_jit(fn, op="pipeline")

    def process(self, block, state):
        return self.step(block, state)
'''


def test_pipeline_guard_rule_passes_guarded_step():
    assert _pipe_guard_errs(PIPE_GUARD_GOOD) == []


def test_pipeline_guard_rule_flags_bare_step():
    errs = _pipe_guard_errs(PIPE_GUARD_BARE)
    assert any("breaker_guarded" in e for e in errs)


def test_pipeline_guard_rule_flags_unreferenced_method():
    errs = _pipe_guard_errs(PIPE_GUARD_UNREFERENCED_METHOD)
    assert any("breaker_guarded" in e for e in errs)


def test_pipeline_guard_rule_tracks_obs_alias():
    errs = _pipe_guard_errs(PIPE_GUARD_ALIAS_DODGE)
    assert any("breaker_guarded" in e for e in errs)


def test_real_pipeline_modules_pass_pipeline_rules():
    pkg = REPO / "veles" / "simd_tpu" / "pipeline"
    for f in sorted(pkg.glob("*.py")):
        tree = ast.parse(f.read_text(), str(f))
        assert lint.pipeline_route_errors(tree, str(f)) == []
        assert lint.pipeline_guard_errors(tree, str(f)) == []


# ---------------------------------------------------------------------------
# precision-literal rule (the bf16_comp PR): raw jax.lax.Precision /
# preferred_element_type literals are forbidden in ops//parallel
# compute cores — precision belongs to runtime/precision.py
# ---------------------------------------------------------------------------

PRECISION_GOOD = '''
import jax.numpy as jnp
from veles.simd_tpu.runtime import precision as prx


def _core(a, b):
    return prx.p_einsum("ij,jk->ik", a, b, precision="bf16_comp")


def _core2(a, b):
    return jnp.matmul(a, b, precision=prx.HIGHEST)
'''

PRECISION_RAW_LITERAL = '''
import jax
import jax.numpy as jnp


def _core(a, b):
    return jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
'''

PRECISION_LAX_ALIAS = '''
from jax import lax as _l
import jax.numpy as jnp


def _core(a, b):
    return jnp.matmul(a, b, precision=_l.Precision.HIGH)
'''

PRECISION_FROM_IMPORT = '''
from jax.lax import Precision as _P
import jax.numpy as jnp


def _core(a, b):
    return jnp.matmul(a, b, precision=_P.HIGHEST)
'''

PRECISION_PET_KWARG = '''
import jax.numpy as jnp


def _core(a, b):
    return jnp.matmul(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)
'''


def _precision_errs(src):
    return lint.precision_literal_errors(ast.parse(src), "mod.py")


def test_precision_rule_passes_layer_usage():
    assert _precision_errs(PRECISION_GOOD) == []


def test_precision_rule_flags_raw_literal():
    errs = _precision_errs(PRECISION_RAW_LITERAL)
    assert any("jax.lax.Precision" in e for e in errs)


def test_precision_rule_tracks_lax_alias():
    errs = _precision_errs(PRECISION_LAX_ALIAS)
    assert any("Precision" in e for e in errs)


def test_precision_rule_tracks_from_import():
    errs = _precision_errs(PRECISION_FROM_IMPORT)
    assert any("Precision" in e for e in errs)


def test_precision_rule_flags_preferred_element_type():
    errs = _precision_errs(PRECISION_PET_KWARG)
    assert any("preferred_element_type" in e for e in errs)


def test_real_compute_modules_pass_precision_rule():
    """Acceptance gate: zero raw precision literals left in ops/ or
    parallel/ outside the exempt Mosaic kernel module — every
    contraction's precision flows through runtime/precision.py."""
    for sub in ("ops", "parallel"):
        for path in sorted((REPO / "veles/simd_tpu" / sub).glob("*.py")):
            rel = path.relative_to(REPO).as_posix()
            if rel in lint._PRECISION_RULE_EXEMPT:
                continue
            errs = lint.precision_literal_errors(
                ast.parse(path.read_text()), rel)
            assert errs == [], errs


PRECISION_BARE_JAX_LAX = '''
import jax.lax
import jax.numpy as jnp


def _core(a, b):
    return jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
'''


def test_precision_rule_flags_bare_jax_lax_import():
    errs = _precision_errs(PRECISION_BARE_JAX_LAX)
    assert any("Precision" in e for e in errs)


# --- artifact-serialization rule (zero-warmup PR) ---------------------------

ARTIFACT_CLEAN = '''
from veles.simd_tpu import obs


def _core(x):
    return x + 1


def run(x):
    with obs.span("demo.dispatch"):
        return _core(x)
'''

ARTIFACT_RAW_EXPORT = '''
import jax


def pack(jfn, spec):
    return jax.export.export(jfn)(spec)
'''

ARTIFACT_IMPORT_ALIAS = '''
import jax.export as je


def pack(jfn, spec):
    return je.export(jfn)(spec)
'''

ARTIFACT_FROM_IMPORT = '''
from jax.export import deserialize as load_exe


def unpack(data):
    return load_exe(data)
'''

ARTIFACT_SERIALIZE_CALL = '''
def pack(exported):
    return bytes(exported.serialize())
'''

ARTIFACT_DESERIALIZE_CALL = '''
def unpack(mod, data):
    return mod.deserialize(data)
'''


def _artifact_errs(src):
    return lint.artifact_serialization_errors(ast.parse(src), "m.py")


def test_artifact_rule_passes_clean_module():
    assert _artifact_errs(ARTIFACT_CLEAN) == []


def test_artifact_rule_flags_raw_jax_export():
    errs = _artifact_errs(ARTIFACT_RAW_EXPORT)
    assert any("jax.export" in e for e in errs)


def test_artifact_rule_tracks_import_alias():
    errs = _artifact_errs(ARTIFACT_IMPORT_ALIAS)
    assert any("jax.export" in e for e in errs)


def test_artifact_rule_tracks_from_import():
    errs = _artifact_errs(ARTIFACT_FROM_IMPORT)
    assert errs, "aliased deserialize import must be flagged"


def test_artifact_rule_flags_serialize_call():
    errs = _artifact_errs(ARTIFACT_SERIALIZE_CALL)
    assert any(".serialize()" in e for e in errs)


def test_artifact_rule_flags_deserialize_call():
    errs = _artifact_errs(ARTIFACT_DESERIALIZE_CALL)
    assert any(".deserialize()" in e for e in errs)


def test_artifact_rule_would_catch_the_store_itself():
    """The rule has teeth: runtime/artifacts.py — the ONE module
    allowed to serialize (it is outside the policed directories) —
    would trip the rule if it ever moved into them."""
    src = (REPO / "veles/simd_tpu/runtime/artifacts.py").read_text()
    errs = lint.artifact_serialization_errors(
        ast.parse(src), "artifacts.py")
    assert errs, "the store's own serialize/deserialize calls must " \
                 "be visible to the rule"


def test_real_modules_pass_artifact_rule():
    """Acceptance gate: zero raw serialization calls in the policed
    layers — every export/deserialize flows through the store."""
    for sub in ("ops", "parallel", "serve", "pipeline"):
        for path in sorted(
                (REPO / "veles/simd_tpu" / sub).glob("*.py")):
            rel = path.relative_to(REPO).as_posix()
            errs = lint.artifact_serialization_errors(
                ast.parse(path.read_text()), rel)
            assert errs == [], errs


# --- segment-packing rule (PR 17) ------------------------------------------

SEGMENT_GOOD = '''
from veles.simd_tpu.runtime import faults, routing

_SEG_FAMILY = routing.family("segments", (
    routing.Route("stft_pack",
                  predicate=lambda op, **_: op == "stft"),
    routing.Route("convolve_pack"),
))


def _select_pack_route(op):
    return _SEG_FAMILY.static_select(op=str(op))


def packed_stft(segments, frame_length, hop):
    route = _select_pack_route("stft")
    def device():
        return route
    def salvage():
        return None
    return faults.breaker_guarded("segments.dispatch", "k", device,
                                  fallback=salvage,
                                  fallback_name="per_segment")
'''

SEGMENT_NO_BREAKER = '''
from veles.simd_tpu.runtime import routing

_SEG_FAMILY = routing.family("segments", (
    routing.Route("stft_pack"),
))


def packed_stft(segments, frame_length, hop):
    route = _SEG_FAMILY.static_select(op="stft")
    return [route for _ in segments]
'''

SEGMENT_PLAIN_GUARD_ONLY = '''
from veles.simd_tpu.runtime import faults, routing

_SEG_FAMILY = routing.family("segments", (
    routing.Route("stft_pack"),
))


def packed_stft(segments, frame_length, hop):
    route = _SEG_FAMILY.static_select(op="stft")
    def device():
        return route
    return faults.guarded("segments.dispatch", device, fallback=None)
'''

SEGMENT_NO_TABLE = '''
from veles.simd_tpu.runtime import faults


def packed_stft(segments, frame_length, hop):
    def device():
        return [s for s in segments]
    def salvage():
        return None
    return faults.breaker_guarded("segments.dispatch", "k", device,
                                  fallback=salvage)
'''

SEGMENT_ALIAS_DODGE = '''
import veles.simd_tpu.runtime.faults as flt
from veles.simd_tpu.runtime import routing

_SEG_FAMILY = routing.family("segments", (
    routing.Route("stft_pack"),
))


def _dispatch(device, salvage):
    return flt.breaker_guarded("segments.dispatch", "k", device,
                               fallback=salvage)


def packed_stft(segments, frame_length, hop):
    route = _SEG_FAMILY.static_select(op="stft")
    def device():
        return route
    def salvage():
        return None
    return _dispatch(device, salvage)
'''


def _segment_errs(src):
    return lint.segment_dispatch_errors(ast.parse(src), "segments.py")


def test_segment_rule_passes_table_and_breaker():
    assert _segment_errs(SEGMENT_GOOD) == []


def test_segment_rule_flags_unguarded_pack():
    errs = _segment_errs(SEGMENT_NO_BREAKER)
    assert any("breaker_guarded" in e for e in errs)


def test_segment_rule_plain_guarded_is_not_enough():
    """``faults.guarded`` has no per-class breaker — a packed dispatch
    must go through ``breaker_guarded`` specifically."""
    errs = _segment_errs(SEGMENT_PLAIN_GUARD_ONLY)
    assert any("breaker_guarded" in e for e in errs)


def test_segment_rule_flags_hand_rolled_packing():
    errs = _segment_errs(SEGMENT_NO_TABLE)
    assert any("routing-family" in e for e in errs)


def test_segment_rule_tracks_aliases_and_helpers():
    """``import ... as`` plus a module-level dispatch helper must
    still satisfy the rule (transitive closure, alias-tracked)."""
    assert _segment_errs(SEGMENT_ALIAS_DODGE) == []


def test_real_segments_module_passes_segment_rule():
    """Acceptance gate: ops/segments.py itself satisfies its own
    contract — packed entry points route through the family table and
    the breaker-guarded fault policy."""
    src = (REPO / "veles/simd_tpu/ops/segments.py").read_text()
    errs = lint.segment_dispatch_errors(
        ast.parse(src), "veles/simd_tpu/ops/segments.py")
    assert errs == [], errs


# ---------------------------------------------------------------------------
# the journal funnel rule (obs v6): serve/runtime/pipeline code never
# opens journal files raw or mints its own JournalWriter — the
# obs.journal facade owns line-atomicity, rotation, and the disk
# budget
# ---------------------------------------------------------------------------

JOURNAL_GOOD = '''
import os

from veles.simd_tpu import obs
from veles.simd_tpu.obs import journal as obs_journal


def emit(op, decision):
    # the funnel: record_decision is journal-tapped when armed
    obs.record_decision(op, decision, site="serve.dispatch")


def where():
    # reading the facade's state is legal; only raw writes are not
    return obs_journal.journal_dir(), obs.journal_cursor()


def unrelated(path):
    # plain file IO on non-journal paths stays untouched
    with open(path) as f:
        return f.read()
'''

JOURNAL_RAW_OPEN_ENV = '''
import json
import os


def sneak_append(record):
    d = os.environ.get("VELES_SIMD_JOURNAL_DIR")
    path = os.path.join(d, "journal-0-000000.jsonl")
    with open(path, "ab") as f:
        f.write(json.dumps(record).encode())
'''

JOURNAL_RAW_OPEN_ALIAS = '''
import os

from veles.simd_tpu.obs import journal as obs_journal


def peek():
    pack = obs_journal.journal_dir()
    target = os.path.join(pack, "latest")
    return open(target, "rb").read()
'''

JOURNAL_WRITER_MINT = '''
from veles.simd_tpu.obs import journal as obs_journal


def second_writer(tmp):
    return obs_journal.JournalWriter(tmp)
'''

JOURNAL_WRITER_MINT_IMPORTED = '''
from veles.simd_tpu.obs.journal import JournalWriter as JW


def second_writer(tmp):
    return JW(tmp)
'''

JOURNAL_LITERAL_PATH = '''
import io


def tail(n):
    return io.open("/var/run/journal-12-000003.jsonl", "rb").read()
'''


def _journal_errs(src):
    return lint.journal_funnel_errors(ast.parse(src), "mod.py")


def test_journal_rule_passes_funnelled_module():
    assert _journal_errs(JOURNAL_GOOD) == []


def test_journal_rule_flags_env_derived_open():
    errs = _journal_errs(JOURNAL_RAW_OPEN_ENV)
    assert len(errs) == 1
    assert "obs" in errs[0] and "journal" in errs[0]


def test_journal_rule_tracks_alias_taint():
    # pack = journal_dir(); target = join(pack, ...); open(target)
    # — taint propagated through both assignments
    errs = _journal_errs(JOURNAL_RAW_OPEN_ALIAS)
    assert len(errs) == 1
    assert "raw open()" in errs[0]


def test_journal_rule_flags_writer_mint():
    for src in (JOURNAL_WRITER_MINT, JOURNAL_WRITER_MINT_IMPORTED):
        errs = _journal_errs(src)
        assert len(errs) == 1, src
        assert "JournalWriter" in errs[0]


def test_journal_rule_flags_literal_journal_path():
    errs = _journal_errs(JOURNAL_LITERAL_PATH)
    assert len(errs) == 1


def test_real_modules_pass_journal_rule():
    for pkg in ("serve", "runtime", "pipeline"):
        pkg_dir = REPO / "veles" / "simd_tpu" / pkg
        for f in sorted(pkg_dir.glob("*.py")):
            tree = ast.parse(f.read_text(), str(f))
            assert lint.journal_funnel_errors(tree, str(f)) == [], \
                f.name


# ---------------------------------------------------------------------------
# the control-axis rule (obs v7): serve/scaler.py reads fleet state
# ONLY through obs.signals() and acts ONLY through ReplicaGroup verbs
# — a second unrecorded view (scrapes, obs side-doors, direct Server
# access) breaks the "every decision is explainable from its journaled
# input vector" claim
# ---------------------------------------------------------------------------

SCALER_GOOD = '''
from veles.simd_tpu import obs


class Engine:
    def tick(self):
        sig = obs.signals()
        if sig.queue_depth_total > 8 * self.group.alive():
            rid = self.group.spawn_replica().rid
            obs.record_decision("scaler", "scale_up", replica=rid)
            obs.count("scaler_action", action="scale_up")
        for r in self.group.live_replicas():
            pass
        self.group.retire("r1", reason="scaler")
        self.group.restart("r0")
'''

SCALER_SCRAPE_IMPORT = '''
import urllib.request

from veles.simd_tpu import obs


def peek(url):
    with urllib.request.urlopen(url) as resp:
        return resp.read()
'''

SCALER_PARSE_PROMETHEUS = '''
from veles.simd_tpu.obs import export as obs_export


def second_view(body):
    return obs_export.parse_prometheus(body)
'''

SCALER_SERVER_ATTR = '''
class Engine:
    def depth(self):
        # reaching through the replica to its Server bypasses the
        # group verbs' locking
        return sum(r.server.depth()
                   for r in self.group.live_replicas())
'''

SCALER_SUBMIT = '''
class Engine:
    def probe(self, req):
        return self.router.submit(req)
'''

SCALER_OBS_SIDE_DOOR = '''
from veles.simd_tpu import obs as telemetry


class Engine:
    def tick(self):
        # alias-tracked: a snapshot() read is a second, unrecorded
        # view of the fleet
        return telemetry.snapshot()["counters"]
'''

SCALER_BAD_VERB = '''
class Engine:
    def panic(self):
        self.group.stop(drain=False)
'''


def _scaler_errs(src):
    return lint.scaler_control_errors(ast.parse(src), "mod.py")


def test_scaler_rule_passes_contract_shaped_engine():
    assert _scaler_errs(SCALER_GOOD) == []


def test_scaler_rule_flags_scrape_imports():
    errs = _scaler_errs(SCALER_SCRAPE_IMPORT)
    assert len(errs) == 1
    assert "urllib" in errs[0] and "signals" in errs[0]


def test_scaler_rule_flags_parse_prometheus():
    errs = _scaler_errs(SCALER_PARSE_PROMETHEUS)
    assert len(errs) == 1
    assert "parse_prometheus" in errs[0]


def test_scaler_rule_flags_direct_server_access():
    errs = _scaler_errs(SCALER_SERVER_ATTR)
    assert len(errs) == 1
    assert ".server" in errs[0]


def test_scaler_rule_flags_request_submission():
    errs = _scaler_errs(SCALER_SUBMIT)
    assert len(errs) == 1
    assert "submit" in errs[0]


def test_scaler_rule_flags_obs_side_door_reads():
    errs = _scaler_errs(SCALER_OBS_SIDE_DOOR)
    assert len(errs) == 1
    assert "telemetry.snapshot" in errs[0]


def test_scaler_rule_flags_unapproved_group_verb():
    errs = _scaler_errs(SCALER_BAD_VERB)
    assert len(errs) == 1
    assert "self.group.stop" in errs[0]


def test_real_scaler_module_passes_control_rule():
    f = REPO / "veles" / "simd_tpu" / "serve" / "scaler.py"
    tree = ast.parse(f.read_text(), str(f))
    assert lint.scaler_control_errors(tree, str(f)) == []


# ---------------------------------------------------------------------------
# the rpc transport rule (PR 20): serve/rpc.py is the ONE serve module
# allowed to open request-carrying transport toward a replica — any
# http.client/socket import or body-carrying urllib submission in the
# rest of serve/ re-invents the wire schema, the deadline re-stamp,
# and the typed-error mapping, wrong.  GET scrapes stay legal.
# ---------------------------------------------------------------------------

RPC_GOOD_SCRAPE = '''
def probe(url):
    import urllib.error
    import urllib.request
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status
    except urllib.error.HTTPError as e:
        return e.code
'''

RPC_HTTP_CLIENT_IMPORT = '''
import http.client as hc


def side_channel(host, port, body):
    conn = hc.HTTPConnection(host, port)
    conn.request("POST", "/submit", body)
    return conn.getresponse().read()
'''

RPC_SOCKET_IMPORT = '''
from socket import create_connection


def side_channel(host, port, frame):
    with create_connection((host, port)) as s:
        s.sendall(frame)
'''

RPC_URLOPEN_DATA_KWARG = '''
import urllib.request


def side_channel(url, frame):
    with urllib.request.urlopen(url, data=frame) as r:
        return r.read()
'''

RPC_URLOPEN_DATA_POSITIONAL = '''
from urllib.request import urlopen as _open


def side_channel(url, frame):
    with _open(url, frame) as r:
        return r.read()
'''

RPC_REQUEST_POST = '''
from urllib import request as _rq


def side_channel(url, frame):
    req = _rq.Request(url, data=frame, method="POST")
    with _rq.urlopen(req) as r:
        return r.read()
'''

RPC_REQUEST_GET_STAYS_LEGAL = '''
from urllib.request import Request, urlopen


def scrape(url):
    with urlopen(Request(url, method="GET"), timeout=5) as r:
        return r.read()
'''


def _rpc_errs(src):
    return lint.rpc_transport_errors(ast.parse(src), "mod.py")


def test_rpc_rule_passes_get_scrape():
    assert _rpc_errs(RPC_GOOD_SCRAPE) == []


def test_rpc_rule_passes_explicit_get_request():
    assert _rpc_errs(RPC_REQUEST_GET_STAYS_LEGAL) == []


def test_rpc_rule_flags_http_client_import_alias():
    errs = _rpc_errs(RPC_HTTP_CLIENT_IMPORT)
    assert len(errs) == 1
    assert "http.client" in errs[0] and "rpc.py" in errs[0]


def test_rpc_rule_flags_socket_from_import():
    errs = _rpc_errs(RPC_SOCKET_IMPORT)
    assert len(errs) == 1
    assert "socket" in errs[0]


def test_rpc_rule_flags_urlopen_data_kwarg():
    errs = _rpc_errs(RPC_URLOPEN_DATA_KWARG)
    assert len(errs) == 1
    assert "urllib.request.urlopen" in errs[0]


def test_rpc_rule_flags_urlopen_positional_body_via_alias():
    errs = _rpc_errs(RPC_URLOPEN_DATA_POSITIONAL)
    assert len(errs) == 1
    assert "_open" in errs[0]


def test_rpc_rule_flags_post_request_via_module_alias():
    # the Request carrying the body is the flagged call; the urlopen
    # that ships it takes a pre-built object, not a data argument
    errs = _rpc_errs(RPC_REQUEST_POST)
    assert len(errs) == 1
    assert "_rq.Request" in errs[0]


def test_rpc_rule_would_catch_the_client_itself():
    """serve/rpc.py is exempt by dispatch, not by rule — prove the
    rule fires on its transport imports when applied."""
    f = REPO / "veles" / "simd_tpu" / "serve" / "rpc.py"
    tree = ast.parse(f.read_text(), str(f))
    errs = lint.rpc_transport_errors(tree, str(f))
    assert any("http.client" in e for e in errs)
    assert any("(socket)" in e for e in errs)


def test_real_serve_modules_pass_rpc_rule():
    serve_dir = REPO / "veles" / "simd_tpu" / "serve"
    for f in sorted(serve_dir.glob("*.py")):
        if f.name == "rpc.py":
            continue
        tree = ast.parse(f.read_text(), str(f))
        assert lint.rpc_transport_errors(tree, str(f)) == [], f.name
