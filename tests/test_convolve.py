"""Tests for veles.simd_tpu.ops.convolve.

Port of ``tests/convolve.cc``: golden-value convolutions of known arrays
(``tests/convolve.cc:53-71``), cross-validation of every algorithm against
the direct-form oracle (``:139-166``), and the algorithm-crossover size
sweep the reference benchmarks cover (``:168-401``).
"""

import numpy as np
import pytest

from veles.simd_tpu.ops import convolve as cv

RNG = np.random.RandomState(11)

ALGOS = [cv.ConvolutionAlgorithm.BRUTE_FORCE,
         cv.ConvolutionAlgorithm.FFT,
         cv.ConvolutionAlgorithm.OVERLAP_SAVE]


def _ref_full(x, h):
    return np.convolve(np.asarray(x, np.float64),
                       np.asarray(h, np.float64)).astype(np.float32)


def test_golden_small():
    """Known-array golden values (tests/convolve.cc:53-71 pattern)."""
    x = np.array([1.0, 2.0, 3.0], np.float32)
    h = np.array([4.0, 5.0], np.float32)
    want = np.array([4.0, 13.0, 22.0, 15.0], np.float32)
    np.testing.assert_allclose(np.asarray(cv.convolve_simd(x, h, simd=True)),
                               want, atol=1e-5)
    np.testing.assert_allclose(cv.convolve_na(x, h), want, atol=1e-6)


def test_golden_identity_kernel():
    x = RNG.randn(64).astype(np.float32)
    h = np.array([1.0], np.float32)
    np.testing.assert_allclose(np.asarray(cv.convolve_simd(x, h, simd=True)),
                               x, atol=1e-6)


@pytest.mark.parametrize("xlen,hlen", [
    (16, 4), (50, 50), (100, 10), (256, 256), (350, 21), (1000, 50),
    (2000, 950), (4096, 63),
])
def test_algorithms_cross_validate(xlen, hlen):
    """Every algorithm × every backend agrees with the float64 direct form
    (tests/convolve.cc:139-166)."""
    x = RNG.randn(xlen).astype(np.float32)
    h = RNG.randn(hlen).astype(np.float32)
    want = _ref_full(x, h)
    tol = 1e-3 * max(1.0, np.abs(want).max())
    for algo in ALGOS:
        if algo is cv.ConvolutionAlgorithm.OVERLAP_SAVE and \
                not hlen < xlen / 2:
            continue
        handle = cv.convolve_initialize(xlen, hlen, algo)
        for simd in (True, False):
            got = np.asarray(cv.convolve(handle, x, h, simd=simd))
            assert got.shape == (xlen + hlen - 1,)
            np.testing.assert_allclose(got, want, atol=tol,
                                       err_msg=f"{algo} simd={simd}")


def test_overlap_save_long_signal():
    """The long-signal path (BASELINE.md config 4 shape, scaled down)."""
    x = RNG.randn(1 << 16).astype(np.float32)
    h = RNG.randn(127).astype(np.float32)
    handle = cv.convolve_overlap_save_initialize(x.size, h.size)
    got = np.asarray(cv.convolve_overlap_save(handle, x, h, simd=True))
    want = _ref_full(x, h)
    np.testing.assert_allclose(got, want, atol=1e-2)


def test_batched_leading_dims():
    x = RNG.randn(4, 128).astype(np.float32)
    h = RNG.randn(9).astype(np.float32)
    got = np.asarray(cv.convolve_simd(x, h, simd=True))
    assert got.shape == (4, 136)
    for i in range(4):
        np.testing.assert_allclose(got[i], _ref_full(x[i], h), atol=1e-4)


def test_block_length_matches_reference():
    """L = 2^(⌊log2 h⌋+2) (src/convolve.c:115-121)."""
    assert cv.overlap_save_block_length(50) == 128
    assert cv.overlap_save_block_length(64) == 256
    assert cv.overlap_save_block_length(1) == 4
    assert cv.overlap_save_block_length(950) == 2048


def test_tpu_block_length():
    """8x the reference rule, capped by the padded problem size."""
    assert cv.tpu_block_length(2047, 1 << 20) == 8 * 4096
    assert cv.tpu_block_length(50, 1 << 20) == 8 * 128
    # small signal: cap kicks in but never below the reference length
    assert cv.tpu_block_length(50, 300) == 512
    assert cv.tpu_block_length(50, 120) == 256


def test_fft_length():
    h = cv.convolve_fft_initialize(100, 29)
    assert h.fft_length == 128
    h = cv.convolve_fft_initialize(100, 28)   # 127 → 128
    assert h.fft_length == 128
    h = cv.convolve_fft_initialize(65, 64)    # 128 exactly stays 128
    assert h.fft_length == 128


def test_contract_violations():
    """Reference asserts (src/convolve.c:44-48,105); we raise."""
    with pytest.raises(ValueError):
        cv.convolve_initialize(0, 5)
    with pytest.raises(ValueError):
        cv.convolve_overlap_save_initialize(10, 6)  # h >= x/2
    handle = cv.convolve_initialize(8, 3)
    with pytest.raises(ValueError):
        cv.convolve(handle, np.zeros(9, np.float32),
                    np.zeros(3, np.float32), simd=True)


def test_auto_select_shape():
    """Heuristic has the reference's shape: long+thin → overlap-save,
    big balanced → FFT, small → direct (src/convolve.c:328-364)."""
    assert cv.select_algorithm(1 << 20, 64) is \
        cv.ConvolutionAlgorithm.OVERLAP_SAVE
    assert cv.select_algorithm(4096, 4096) is cv.ConvolutionAlgorithm.FFT
    assert cv.select_algorithm(128, 16) is \
        cv.ConvolutionAlgorithm.BRUTE_FORCE


def test_convenience_form():
    x = RNG.randn(100).astype(np.float32)
    h = RNG.randn(7).astype(np.float32)
    np.testing.assert_allclose(np.asarray(cv.convolve(x, h)),
                               _ref_full(x, h), atol=1e-4)


def test_conv_precision_config_plumbing():
    """Config.conv_precision reaches the block matmul as its precision
    (numerically a no-op on CPU, which always computes full f32 — the
    check is that every setting produces the correct result and the
    config round-trips)."""
    from veles.simd_tpu.utils.config import get_config, set_config

    x = RNG.randn(4096).astype(np.float32)
    h = RNG.randn(63).astype(np.float32)
    want = _ref_full(x, h)
    prev = get_config().conv_precision
    try:
        for prec in ("highest", "high"):
            set_config(conv_precision=prec)
            assert cv.os_precision() == prec
            handle = cv.convolve_overlap_save_initialize(len(x), len(h))
            np.testing.assert_allclose(
                np.asarray(cv.convolve_overlap_save(handle, x, h, simd=True)),
                want, atol=1e-3)
    finally:
        set_config(conv_precision=prev)


def test_conv_precision_config_validated():
    from veles.simd_tpu.utils.config import set_config

    with pytest.raises(ValueError, match="conv_precision"):
        set_config(conv_precision="default")  # 1-pass bf16: explicit only
    with pytest.raises(ValueError, match="conv_precision"):
        set_config(conv_precision="hihg")


class TestModes:
    """numpy/scipy mode slicing on the convenience forms."""

    @pytest.mark.parametrize("n,k", [(100, 17), (17, 100), (64, 64)])
    @pytest.mark.parametrize("mode", ["full", "same", "valid"])
    def test_convolve_modes_match_numpy(self, n, k, mode):
        rng = np.random.RandomState(42)
        x = rng.randn(n).astype(np.float32)
        h = rng.randn(k).astype(np.float32)
        got = np.asarray(cv.convolve(x, h, mode=mode))
        want = np.convolve(x.astype(np.float64), h.astype(np.float64),
                           mode=mode)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want,
                                   atol=1e-3 * max(1, np.abs(want).max()))

    def test_handle_form_mode(self):
        rng = np.random.RandomState(43)
        x = rng.randn(256).astype(np.float32)
        h = rng.randn(31).astype(np.float32)
        handle = cv.convolve_initialize(256, 31)
        got = np.asarray(cv.convolve(handle, x, h, mode="same"))
        want = np.convolve(x.astype(np.float64), h.astype(np.float64),
                           mode="same")
        np.testing.assert_allclose(got, want, atol=1e-3)

    @pytest.mark.parametrize("n,k", [(200, 21), (21, 200), (4, 10),
                                     (10, 4), (64, 64)])
    def test_correlate_modes(self, n, k):
        """Both length orderings, including the swap-and-reverse case
        where numpy's 'same' window shifts by one (review regression)."""
        from veles.simd_tpu.ops import correlate as cr

        rng = np.random.RandomState(44)
        x = rng.randn(n).astype(np.float32)
        h = rng.randn(k).astype(np.float32)
        for mode in ("full", "same", "valid"):
            got = np.asarray(cr.cross_correlate(x, h, mode=mode))
            want = np.correlate(x.astype(np.float64),
                                h.astype(np.float64), mode=mode)
            assert got.shape == want.shape, mode
            np.testing.assert_allclose(got, want, atol=1e-3, err_msg=mode)

    def test_bad_mode(self):
        with pytest.raises(ValueError, match="mode"):
            cv.convolve(np.zeros(8, np.float32), np.zeros(3, np.float32),
                        mode="circular")

    def test_reverse_handle_through_convolve_entry(self):
        """A reverse=True handle computes correlation even when called
        through convolve(); its 'same' slice must follow the correlate
        convention (review regression)."""
        rng = np.random.RandomState(45)
        x = rng.randn(4).astype(np.float32)
        v = rng.randn(10).astype(np.float32)
        handle = cv.convolve_initialize(4, 10, reverse=True)
        got = np.asarray(cv.convolve(handle, x, v, mode="same"))
        want = np.correlate(x.astype(np.float64), v.astype(np.float64),
                            mode="same")
        np.testing.assert_allclose(got, want, atol=1e-4)


class TestScipyNameAliases:
    """fftconvolve / oaconvolve by their scipy names (round 5)."""

    @pytest.mark.parametrize("mode", ["full", "same", "valid"])
    def test_fftconvolve_matches_scipy(self, mode):
        import scipy.signal as ss

        rng = np.random.RandomState(95)
        x = rng.randn(500).astype(np.float32)
        h = rng.randn(37).astype(np.float32)
        got = np.asarray(cv.fftconvolve(x, h, mode=mode, simd=True))
        want = ss.fftconvolve(x.astype(np.float64),
                              h.astype(np.float64), mode=mode)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want,
                                   atol=1e-4 * np.abs(want).max())

    def test_oaconvolve_long_signal(self):
        import scipy.signal as ss

        rng = np.random.RandomState(96)
        x = rng.randn(1 << 14).astype(np.float32)
        h = rng.randn(255).astype(np.float32)
        got = np.asarray(cv.oaconvolve(x, h, mode="same", simd=True))
        want = ss.oaconvolve(x.astype(np.float64),
                             h.astype(np.float64), mode="same")
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want,
                                   atol=1e-4 * np.abs(want).max())

    def test_2d_kernel_routes_to_conv2d(self):
        import scipy.signal as ss

        rng = np.random.RandomState(97)
        x = rng.randn(32, 40).astype(np.float32)
        h = rng.randn(5, 7).astype(np.float32)
        got = np.asarray(cv.fftconvolve(x, h, mode="same", simd=True))
        want = ss.fftconvolve(x.astype(np.float64),
                              h.astype(np.float64), mode="same")
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want,
                                   atol=1e-4 * np.abs(want).max())

    def test_oaconvolve_short_signal_falls_back(self):
        """Sizes outside the overlap-save contract fall back to the
        spectral path (scipy handles them; review finding)."""
        import scipy.signal as ss

        rng = np.random.RandomState(98)
        x = rng.randn(100).astype(np.float32)
        h = rng.randn(60).astype(np.float32)
        got = np.asarray(cv.oaconvolve(x, h, simd=True))
        want = ss.oaconvolve(x.astype(np.float64), h.astype(np.float64))
        assert got.shape == want.shape == (159,)
        np.testing.assert_allclose(got, want,
                                   atol=1e-4 * np.abs(want).max())

    def test_nd_kernel_rejected(self):
        with pytest.raises(ValueError, match="rank 3"):
            cv.fftconvolve(np.zeros((4, 5, 16), np.float32),
                           np.zeros((4, 5, 3), np.float32))
        with pytest.raises(ValueError, match="rank 3"):
            cv.oaconvolve(np.zeros((4, 5, 16), np.float32),
                          np.zeros((4, 5, 3), np.float32))
