#!/usr/bin/env python
"""Scripted chaos-campaign runner: the resilience triad, proven end to end.

Drives a live :class:`veles.simd_tpu.serve.Server` (via
``tools/loadgen.py`` traffic) *and* sharded ``parallel/`` dispatches
through a deterministic phase schedule of injected faults
(``VELES_SIMD_FAULT_PLAN`` phase syntax, ``label=entries;...``,
stepped with :func:`veles.simd_tpu.runtime.faults.advance_phase`):

1. **baseline** — no faults; traffic + sharded calls establish the
   healthy numbers;
2. **overload** — injected admission overloads force the typed shed
   path under burst traffic;
3. **pipeline_poison** — a persistent ``device_lost`` poisons ONE
   served PIPELINE class (``pipeline.dispatch@chaosline``): its
   per-pipeline-class breaker opens, its invocation streams keep
   answering (degraded, state threading exact — parity still holds),
   and PLAIN-op traffic in the same phase stays entirely "ok";
4. **mesh_loss** — a persistent ``device_lost`` poisons ONE serve
   shape class (``serve.dispatch@sosfilt``) and the whole sharded
   matmul mesh (``parallel.sharded_matmul``): the per-class breaker
   opens after the retry ladder is paid twice, the health machine
   trips DEGRADED and recovers on a healthy-class probe, and sharded
   dispatch degrades to the single-chip twin (``mesh_degrade``);
5. **recovery** — injection cleared; half-open breaker probes re-close
   every breaker and the server finishes HEALTHY.

Invariants asserted (rc=1 on any failure):

* zero lost / zero double-answered requests, answers parity-checked
  (pipeline streams included — degraded blocks must not corrupt the
  carried state);
* only *typed* errors reach clients (``Overloaded`` /
  ``DeadlineExceeded``; untyped per-request errors are a bug);
* deadline misses bounded (every request carries ``--deadline-ms``);
* the poisoned class's breaker walks closed -> open -> half_open ->
  closed, and its steady-state open segment records ZERO retry
  attempts (straight-to-fallback) while other classes keep answering;
* ``mesh_degrade`` recorded with mesh geometry; sharded dispatch
  re-enabled after recovery;
* the poisoned PIPELINE class's breaker cycles and re-closes while
  plain-op traffic in that phase records zero degraded answers;
* serve health walks DEGRADED -> HEALTHY;
* the REQUEST AXIS stays complete under fire (obs v4): every completed
  ticket in every phase carries a causal trace whose terminal status
  matches the ticket and whose phase latencies sum to its total, every
  degraded ticket carries a ``degraded`` edge, per-tenant SLO burn
  gauges are exported, and the live scrape endpoint
  (``/metrics`` + ``/healthz`` + ``/debug/requests``) answers
  MID-CAMPAIGN with a poisoned class and injection active.

The evidence — decision events, breaker/fault/serve counters, and the
``veles_simd_breaker_*``/``veles_simd_mesh_*`` Prometheus lines — is
embedded in ``CHAOS_DETAILS.json`` alongside ``BENCH_DETAILS``-format
metric rows, so ``python tools/bench_regress.py --details
CHAOS_DETAILS.json`` gates the campaign like any bench family (rows
stamped ``chaos_phase`` are DEGRADED-not-gated when they dip).

Usage::

    python tools/chaos.py --smoke            # make chaos-smoke
    python tools/chaos.py --replicas --smoke # make chaos-replicas
    python tools/chaos.py --replicas --spawn subprocess --smoke \\
                                             # make chaos-replicas-rpc
    python tools/chaos.py --scale --smoke    # make chaos-scale
    python tools/chaos.py --details CHAOS_DETAILS.json

``--replicas --spawn subprocess`` runs the replica campaign over the
RPC data plane (``serve/rpc.py``): three CHILD PROCESSES behind the
front router, the abrupt kill a real SIGKILL mid-traffic — the same
zero-lost / carried-deadline / journal-reconstruction invariants must
hold across the wire, and the rows land spawn-suffixed (``replica
failover throughput subprocess``) in ``REPLICA_RPC_DETAILS.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, os.path.dirname(__file__))

import loadgen  # noqa: E402
import obs_query  # noqa: E402
from veles.simd_tpu import obs  # noqa: E402
from veles.simd_tpu import serve  # noqa: E402
from veles.simd_tpu.obs import incidents as obs_incidents  # noqa: E402
from veles.simd_tpu.obs import journal as obs_journal  # noqa: E402
from veles.simd_tpu.runtime import breaker, faults  # noqa: E402

MESH_AXIS = "sp"

# the poisoned shape class: one op, one length — a single serve bucket,
# so its breaker sees every failure (determinism over realism here;
# the mixed loadgen traffic supplies the realism)
POISON_OP = "sosfilt"
POISON_LEN = 512

# the poisoned served-pipeline class (loadgen's small compiled chain)
PIPE_NAME = "chaosline"

PHASE_SPEC = (
    "baseline=;"
    "overload=serve.admission:overload:{overloads};"
    "pipeline_poison=pipeline.dispatch@{pipe}:device_lost:9999;"
    "mesh_loss=serve.dispatch@{poison}:device_lost:9999,"
    "parallel.sharded_matmul:device_lost:9999;"
    "recovery="
)


def _poison_requests(rng, n: int, deadline_ms) -> list:
    """``n`` identical-class requests for the poisoned bucket."""
    from veles.simd_tpu.ops import iir

    sos = iir.butterworth(4, 0.25, "lowpass")
    return [(0.0, serve.Request(
        POISON_OP, rng.randn(POISON_LEN).astype(np.float32),
        {"sos": sos}, tenant="chaos",
        deadline_ms=deadline_ms)) for _ in range(n)]


def _run_serial(server, items, timeout: float) -> dict:
    """Submit ``items`` one at a time, waiting for each answer — every
    request is its own batch, so breaker/health cadences tick once per
    request (the determinism the campaign's counting arguments
    need)."""
    return _merge_reports([
        loadgen.run_load(server, [item], result_timeout=timeout)
        for item in items])


def _merge_reports(reports: list) -> dict:
    """Sum the accounting categories across phase reports (request
    outcomes AND the request-axis trace-completeness categories)."""
    total: dict = {}
    for rep in reports:
        for k in ("requests", "ok", "degraded", "shed", "closed",
                  "errors", "lost", "deadline_miss",
                  "parity_failures") + loadgen.TRACE_KEYS:
            total[k] = total.get(k, 0) + rep.get(k, 0)
    total["double_answered"] = (obs.counter_value(
        "serve_double_answer") if obs.enabled() else 0)
    return total


def _counter_total(name: str) -> int:
    """Sum of one counter across every label set."""
    snap = obs.snapshot()
    return sum(c["value"] for c in snap["counters"]
               if c["name"] == name)


def _decisions(op: str) -> list:
    return [e for e in obs.events() if e["op"] == op]


def _mesh_calls(mesh, n_calls: int, a, b, want) -> int:
    """``n_calls`` sharded matmuls, each answer checked against the
    host oracle regardless of which path (mesh or single-chip twin)
    served it.  Returns the number of wrong answers."""
    from veles.simd_tpu import parallel as par

    bad = 0
    for _ in range(n_calls):
        got = np.asarray(par.sharded_matmul(a, b, mesh,
                                            axis=MESH_AXIS))
        scale = float(np.max(np.abs(want))) or 1.0
        if float(np.max(np.abs(got - want)) / scale) > 2e-3:
            bad += 1
    return bad


def run_campaign(args) -> tuple:
    """Execute the four-phase campaign; returns ``(invariants, rows,
    evidence)`` — all JSON-native."""
    from veles.simd_tpu import parallel as par

    rng = np.random.RandomState(args.seed)
    mesh = par.make_mesh({MESH_AXIS: args.mesh_devices})
    a = rng.randn(32, 64).astype(np.float32)
    b = rng.randn(64, 16).astype(np.float32)
    want = a.astype(np.float64) @ b.astype(np.float64)

    spec = PHASE_SPEC.format(overloads=args.overloads,
                             poison=POISON_OP, pipe=PIPE_NAME)
    faults.set_fault_plan(spec)
    phase_reports: dict = {}
    mesh_bad = 0
    retry_steady = None
    plain_degraded_during_pipe = None
    scrape_mid = None
    try:
        # endpoint armed on an ephemeral port: the campaign proves it
        # serves live data MID-CAMPAIGN, faults active
        server = serve.Server(max_batch=4, max_wait_ms=5.0,
                              workers=args.workers, probe_every=2,
                              obs_port=0)
        compiled = loadgen.build_pipeline(PIPE_NAME)
        # per-tenant SLOs so burn-rate gauges export under chaos (the
        # campaign gates that the gauges EXIST, not a latency number)
        for tenant in loadgen.DEFAULT_TENANTS + ("chaos",):
            obs.slo(tenant, target_ms=60000.0, hit_rate=0.99)
        with server:
            pipe_op = server.register_pipeline(PIPE_NAME, compiled)
            # -- phase 1: baseline ------------------------------------
            t0 = time.perf_counter()
            sched = loadgen.build_schedule(
                rng, args.requests, rate_hz=0.0,
                deadline_ms=args.deadline_ms)
            base_load = loadgen.run_load(
                server, sched, verify=args.verify, rng=rng,
                result_timeout=args.result_timeout)
            base_pipe = loadgen.run_pipeline_streams(
                server, pipe_op, compiled, rng, streams=2, blocks=3,
                deadline_ms=args.deadline_ms,
                result_timeout=args.result_timeout)
            phase_reports["baseline"] = _merge_reports(
                [base_load, base_pipe])
            mesh_bad += _mesh_calls(mesh, 1, a, b, want)
            phase_reports["baseline"]["phase_wall_s"] = \
                time.perf_counter() - t0

            # -- phase 2: overload ------------------------------------
            assert faults.advance_phase() == "overload"
            t0 = time.perf_counter()
            sched = loadgen.build_schedule(
                rng, args.requests, rate_hz=0.0, burst_every=8,
                burst_size=4, deadline_ms=args.deadline_ms)
            phase_reports["overload"] = loadgen.run_load(
                server, sched, verify=args.verify, rng=rng,
                result_timeout=args.result_timeout)
            phase_reports["overload"]["phase_wall_s"] = \
                time.perf_counter() - t0

            # -- phase 3: pipeline_poison -----------------------------
            assert faults.advance_phase() == "pipeline_poison"
            t0 = time.perf_counter()
            # the poisoned pipeline class keeps answering — degraded,
            # through its OWN breaker, with exact state threading
            # (single-invocation batches so the breaker cadence ticks
            # once per block)
            pipe_poisoned = loadgen.run_pipeline_streams(
                server, pipe_op, compiled, rng, streams=1,
                blocks=max(4, args.steady),
                deadline_ms=args.deadline_ms,
                result_timeout=args.result_timeout)
            # plain-op traffic through the SAME server must be
            # untouched: zero degraded answers while the pipeline
            # class is poisoned
            mixed_pp = loadgen.run_load(
                server, loadgen.build_schedule(
                    rng, args.requests, rate_hz=0.0,
                    deadline_ms=args.deadline_ms),
                verify=args.verify, rng=rng,
                result_timeout=args.result_timeout)
            plain_degraded_during_pipe = mixed_pp["degraded"]
            rep = _merge_reports([pipe_poisoned, mixed_pp])
            rep["phase_wall_s"] = time.perf_counter() - t0
            rep["throughput_rps"] = (
                (rep["ok"] + rep["degraded"]) / rep["phase_wall_s"]
                if rep["phase_wall_s"] > 0 else 0.0)
            rep["pipeline_degraded"] = pipe_poisoned["degraded"]
            phase_reports["pipeline_poison"] = rep

            # -- phase 4: mesh_loss -----------------------------------
            assert faults.advance_phase() == "mesh_loss"
            t0 = time.perf_counter()
            # warm-up: enough poisoned-class dispatches to pay the
            # retry ladder twice and open the class breaker
            warm = _run_serial(
                server, _poison_requests(rng, 4, args.deadline_ms),
                args.result_timeout)
            # steady state: the open breaker must answer straight from
            # the oracle — zero retry attempts on the poisoned class
            retries_before = _counter_total("fault_retry")
            steady = _run_serial(
                server,
                _poison_requests(rng, args.steady, args.deadline_ms),
                args.result_timeout)
            retry_steady = _counter_total("fault_retry") \
                - retries_before
            # sibling classes keep flowing while the class is poisoned
            mixed = loadgen.run_load(
                server, loadgen.build_schedule(
                    rng, args.requests, rate_hz=0.0,
                    deadline_ms=args.deadline_ms),
                verify=args.verify, rng=rng,
                result_timeout=args.result_timeout)
            # the live-endpoint proof, at the campaign's worst moment:
            # a poisoned class, an open breaker, injection active
            scrape_mid = loadgen.scrape_endpoint(server.obs_port)
            mesh_bad += _mesh_calls(mesh, args.mesh_loss_calls,
                                    a, b, want)
            rep = _merge_reports([warm, steady, mixed])
            rep["phase_wall_s"] = time.perf_counter() - t0
            rep["throughput_rps"] = (
                (rep["ok"] + rep["degraded"]) / rep["phase_wall_s"]
                if rep["phase_wall_s"] > 0 else 0.0)
            phase_reports["mesh_loss"] = rep

            # -- phase 5: recovery ------------------------------------
            assert faults.advance_phase() == "recovery"
            t0 = time.perf_counter()
            rec_poison = _run_serial(
                server,
                _poison_requests(rng, args.recovery_calls,
                                 args.deadline_ms),
                args.result_timeout)
            rec_pipe = loadgen.run_pipeline_streams(
                server, pipe_op, compiled, rng, streams=1,
                blocks=max(4, args.recovery_calls),
                deadline_ms=args.deadline_ms,
                result_timeout=args.result_timeout)
            rec_mixed = loadgen.run_load(
                server, loadgen.build_schedule(
                    rng, args.requests, rate_hz=0.0,
                    deadline_ms=args.deadline_ms),
                verify=args.verify, rng=rng,
                result_timeout=args.result_timeout)
            mesh_bad += _mesh_calls(mesh, args.recovery_calls,
                                    a, b, want)
            rep = _merge_reports([rec_poison, rec_pipe, rec_mixed])
            rep["phase_wall_s"] = time.perf_counter() - t0
            rep["throughput_rps"] = (
                (rep["ok"] + rep["degraded"]) / rep["phase_wall_s"]
                if rep["phase_wall_s"] > 0 else 0.0)
            phase_reports["recovery"] = rep
            stats = server.stats()
            health = stats["health"]
            breakers = stats["breakers"]
    finally:
        faults.set_fault_plan(None)

    total = _merge_reports(list(phase_reports.values()))

    # -- invariants ---------------------------------------------------
    def _cycle_ok(seq: list) -> bool:
        """closed -> open -> half_open -> closed, in order."""
        try:
            i = seq.index("open")
            j = seq.index("half_open", i)
            seq.index("closed", j)
            return True
        except ValueError:
            return False

    poison_tag = f", {POISON_LEN})"
    poison_transitions = [
        e["decision"] for e in _decisions("breaker_transition")
        if e.get("site") == "serve.dispatch"
        and POISON_OP in e.get("key", "")
        and e.get("key", "").endswith(poison_tag)]
    mesh_transitions = [
        e["decision"] for e in _decisions("breaker_transition")
        if e.get("site") == "parallel.dispatch"]
    serve_events = [e["decision"] for e in _decisions("serve_health")]
    mesh_events = _decisions("mesh_degrade")
    poison_breaker = next(
        (i for i in breakers if POISON_OP in i["key"]
         and i["key"].endswith(poison_tag)), None)
    mesh_breaker = breaker.lookup(
        "parallel.dispatch",
        ("sharded_matmul", f"{MESH_AXIS}{args.mesh_devices}"
                           f"@{MESH_AXIS}"))
    pipe_transitions = [
        e["decision"] for e in _decisions("breaker_transition")
        if e.get("site") == "pipeline.dispatch"
        and PIPE_NAME in e.get("key", "")]
    pipe_breaker = breaker.lookup(
        "pipeline.dispatch", (PIPE_NAME, compiled.block_len))
    answered = total["ok"] + total["degraded"]
    invariants = {
        "zero_lost": total["lost"] == 0,
        "zero_double_answered": total["double_answered"] == 0,
        "zero_untyped_errors": total["errors"] == 0,
        "parity_clean": (total["parity_failures"] == 0
                         and mesh_bad == 0),
        "sheds_typed": phase_reports["overload"]["shed"]
        == args.overloads,
        "deadline_misses_bounded": total["deadline_miss"]
        <= max(1, int(args.max_miss_frac * total["requests"])),
        "breaker_cycle": _cycle_ok(poison_transitions),
        "breaker_closed_at_end": (
            poison_breaker is not None
            and poison_breaker["state"] == breaker.CLOSED),
        "zero_retry_steady_state": retry_steady == 0,
        "mesh_degrade_observed": (
            len(mesh_events) >= 1
            and all(e.get("mesh") for e in mesh_events)),
        "mesh_breaker_cycle": _cycle_ok(mesh_transitions),
        "mesh_breaker_closed_at_end": (
            mesh_breaker is not None
            and mesh_breaker.state == breaker.CLOSED),
        "pipeline_breaker_cycle": _cycle_ok(pipe_transitions),
        "pipeline_breaker_closed_at_end": (
            pipe_breaker is not None
            and pipe_breaker.state == breaker.CLOSED),
        "pipeline_degraded_then_served": (
            phase_reports["pipeline_poison"]["pipeline_degraded"]
            >= 1),
        "plain_ok_during_pipeline_poison":
            plain_degraded_during_pipe == 0,
        "health_degraded_then_healthy": (
            "degrade" in serve_events and "recover" in serve_events
            and health["state"] == serve.HEALTHY),
        "answers_accounted": (answered + total["shed"]
                              + total["deadline_miss"]
                              + total["closed"] + total["errors"]
                              == total["requests"]),
        # the request axis (obs v4): every completed ticket across
        # every phase carried a complete causal chain...
        "zero_orphaned_traces": (total["trace_checked"] > 0
                                 and total["trace_orphans"] == 0),
        # ...whose phase latencies sum to its total...
        "trace_phases_sum_to_total": total["trace_phase_err"] == 0,
        # ...and every degraded ticket carries a degrade edge
        "degraded_tickets_have_degrade_edge":
            total["trace_degraded_missing_edge"] == 0,
        # the scrape endpoint served all three routes mid-campaign
        "scrape_live_mid_campaign": (
            scrape_mid is not None and scrape_mid["ok"] == 3
            and scrape_mid["failed"] == 0),
        # per-tenant SLO burn gauges exported under chaos
        "slo_gauges_exported": any(
            g["name"] == "slo_burn_rate"
            for g in obs.snapshot()["gauges"]),
    }

    # -- CHAOS_DETAILS rows + evidence tail ---------------------------
    wall = sum(r["phase_wall_s"] for r in phase_reports.values())
    rows = [
        {"metric": "chaos requests answered", "value": float(answered),
         "unit": "req", "vs_baseline": None},
        {"metric": "chaos campaign throughput",
         "value": round(total["requests"] / wall, 2) if wall else 0.0,
         "unit": "req/s", "vs_baseline": None},
        {"metric": "chaos deadline hit rate",
         "value": round(answered / (answered + total["deadline_miss"]),
                        4) if answered + total["deadline_miss"]
         else 1.0,
         "unit": "fraction", "vs_baseline": None},
    ]
    for label in ("mesh_loss", "pipeline_poison", "recovery"):
        rows.append({
            "metric": f"chaos {label} throughput",
            "value": round(
                phase_reports[label].get("throughput_rps", 0.0), 2),
            "unit": "req/s", "vs_baseline": None,
            # rows measured with injection active are
            # DEGRADED-not-gated by bench_regress
            **({"chaos_phase": label} if label != "recovery"
               else {}),
        })
    snap = obs.snapshot()
    counters = {}
    for c in snap["counters"]:
        if c["name"].startswith(("serve_", "fault_", "breaker_",
                                 "mesh_")):
            counters[c["name"]] = counters.get(c["name"], 0) \
                + c["value"]
    rows.append({
        "metric": "chaos breaker short circuits",
        "value": float(counters.get("breaker_short_circuit", 0)),
        "unit": "calls", "vs_baseline": None,
        "telemetry": {"counters": counters},
    })
    prom = [line for line in obs.to_prometheus(snap).splitlines()
            if "breaker_" in line or "mesh_" in line
            or "deadline" in line]
    evidence = {
        "chaos_invariants": invariants,
        "phase_reports": {k: {kk: vv for kk, vv in v.items()
                              if not isinstance(vv, np.ndarray)}
                          for k, v in phase_reports.items()},
        "fault_phases": [e["decision"]
                         for e in _decisions("fault_phase")],
        "breaker_transitions": _decisions("breaker_transition"),
        "mesh_degrade_events": mesh_events[:8],
        "serve_health_events": _decisions("serve_health"),
        "prometheus_breaker_lines": prom,
        "retry_attempts_steady_state": retry_steady,
        "plain_degraded_during_pipeline_poison":
            plain_degraded_during_pipe,
        "pipeline_breaker_transitions": pipe_transitions,
        "scrape_mid_campaign": scrape_mid,
        "request_axis": obs.request_summary(),
        "slo": obs.slo_snapshot(),
    }
    return invariants, rows, evidence


# ---------------------------------------------------------------------------
# the replicated campaign (make chaos-replicas): kill one, drain one
# ---------------------------------------------------------------------------

# merged accounting keys specific to routed traffic (run_load only
# emits them when they fire, so merge with .get defaults)
_ROUTER_KEYS = ("failovers", "failover_deadline_checked",
                "failover_deadline_violations", "prior_trace_checked",
                "prior_trace_orphans")


def _replica_submit(replica, req):
    """Place one request directly on a replica over whichever
    transport it serves: the in-process Server, or the armed RPC data
    plane (``serve.rpc.RpcClient``) of a subprocess replica."""
    if replica.spawn == "thread":
        return replica.server.submit(req)
    return replica.rpc.submit(req)


def _merge_router(reports: list) -> dict:
    total = _merge_reports(reports)
    for rep in reports:
        for k in _ROUTER_KEYS:
            total[k] = total.get(k, 0) + rep.get(k, 0)
    return total


def run_replica_campaign(args) -> tuple:
    """Arm the goodput-at-saturation features, then run the replica
    campaign body: the kill/drain/restart invariants (zero lost, zero
    double-answered, every trace terminal) must hold WITH continuous
    batching refilling freed row slots and ragged packing co-packing
    the mix's short stft requests — the chaos gate for both features
    (the mix's stft lengths sit under the ragged cap, so the packed
    dispatch path really runs).  The history axis (obs v6) is armed
    alongside: the whole campaign journals to a fresh pack directory
    and ticks the incident engine on a tight cadence, so the body can
    gate postmortem reconstruction purely from the on-disk journal
    after the replicas are gone."""
    from veles.simd_tpu.serve import server as serve_server

    journal_pack = tempfile.mkdtemp(prefix="veles-chaos-journal-")
    armed = {serve_server.CONTINUOUS_ENV: "1",
             serve_server.RAGGED_ENV: "1",
             obs_journal.JOURNAL_DIR_ENV: journal_pack,
             # fast incident cadence so open (2 firing ticks) and
             # close (5 quiet ticks) both land inside a smoke run
             obs_incidents.TICK_MS_ENV: "50"}
    prior = {k: os.environ.get(k) for k in armed}

    def _restore():
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    os.environ.update(armed)
    # the process-wide incident engine may carry another epoch's state
    # (an earlier campaign or test in this process): a stale CLOSED
    # replica_down incident would satisfy the campaign's close-wait
    # instantly — before its own incident closes into the armed
    # journal — and leftover streaks skew the hysteresis.  The fresh
    # pack's incident story starts from a clean ledger.
    obs_incidents.reset()
    try:
        return _replica_campaign_body(args, _restore, journal_pack)
    finally:
        _restore()


def _replica_campaign_body(args, restore_features=lambda: None,
                           journal_pack=None) -> tuple:
    """The 3-phase replica-kill campaign over a 3-replica group behind
    the front router: (1) kill one replica abruptly — no drain —
    MID-TRAFFIC (its queued work must fail over, deadlines carried);
    (2) drain another gracefully mid-traffic (answered, then removed)
    while the router-level ``/healthz`` answers throughout; (3) COLD
    RESTART the killed replica (``ReplicaGroup.restart`` — the
    preemption-recovery moment the zero-warmup artifact subsystem
    serves) and assert its first request lands within budget of a
    survivor's steady state.  The fleet axis (obs v5) is gated
    alongside: the kill must become visible through ``obs.signals()``
    within bounded collector ticks, a failed-over request must stitch
    into one cross-replica fleet trace with the original deadline
    carried, campaign goodput must be a sane fraction, and the
    tracing-overhead budget must hold with the collector armed.
    Returns ``(invariants, rows, evidence)``."""
    from veles.simd_tpu.serve import cluster

    rng = np.random.RandomState(args.seed)
    # max_wait large enough that the mid-traffic kill catches queued
    # work (the failover path must actually fire), max_batch above the
    # wave size so batches wait rather than dispatch instantly
    group = cluster.ReplicaGroup(3, max_batch=32, max_wait_ms=150.0,
                                 workers=args.workers,
                                 heartbeat_ms=40.0, obs_port=0,
                                 # a tight collector cadence so the
                                 # kill-visibility gate below measures
                                 # ticks, not seconds
                                 fleet_tick_ms=25.0,
                                 # --spawn subprocess runs the SAME
                                 # campaign over the RPC data plane:
                                 # the abrupt kill is then a real
                                 # child SIGKILL mid-traffic, and the
                                 # failover/carried-deadline/journal
                                 # invariants gate the wire
                                 spawn=args.spawn)
    router = cluster.FrontRouter(group)
    scrapes: dict = {}
    phase_reports: dict = {}

    # -- fleet-signal kill visibility (obs v5) ----------------------
    # the autoscaler contract in action: after the abrupt kill, r0
    # must read non-healthy in obs.signals() within a bounded number
    # of collector ticks.  The mid_hook stamps the kill, a watcher
    # thread polls the signals facade (the SAME read path an
    # autoscaler would use — not the group's internals) until the
    # state flips.
    kill_vis = {"t_kill": None, "t_visible": None}
    watcher: list = []

    def _watch_kill_visibility():
        deadline = faults.monotonic() + 60 * group.fleet_tick_s + 5.0
        while faults.monotonic() < deadline:
            sig = obs.signals()
            if sig.health.get("r0") not in (None, "healthy"):
                kill_vis["t_visible"] = faults.monotonic()
                return
            threading.Event().wait(group.fleet_tick_s / 5.0)

    def _kill_r0():
        kill_vis["t_kill"] = faults.monotonic()
        group.kill("r0")
        w = threading.Thread(target=_watch_kill_visibility,
                             daemon=True)
        w.start()
        watcher.append(w)
    with group:
        # -- warmup: compile the traffic mix's handles so the kill
        # wave measures routing, not XLA compiles
        warm = loadgen.run_load(
            router, loadgen.build_schedule(
                rng, 8, rate_hz=0.0, deadline_ms=args.deadline_ms),
            verify=0, rng=rng, result_timeout=args.result_timeout)
        scrapes["baseline"] = loadgen.scrape_endpoint(group.obs_port)
        # wait until every replica has beaten at least once
        deadline = faults.monotonic() + 2.0
        while faults.monotonic() < deadline and not all(
                r.last_beat is not None for r in group.replicas):
            threading.Event().wait(0.02)
        beats_seen = all(r.last_beat is not None
                         for r in group.replicas)

        # -- phase 1: abrupt kill, no drain, mid-traffic ------------
        kill_tickets: list = []
        t0 = time.perf_counter()
        rep_kill = loadgen.run_load(
            router, loadgen.build_schedule(
                rng, args.requests, rate_hz=0.0,
                deadline_ms=args.deadline_ms),
            verify=args.verify, rng=rng,
            result_timeout=args.result_timeout,
            mid_hook=_kill_r0, ticket_sink=kill_tickets)
        rep_kill["phase_wall_s"] = time.perf_counter() - t0
        rep_kill["throughput_rps"] = (
            (rep_kill["ok"] + rep_kill["degraded"])
            / rep_kill["phase_wall_s"]
            if rep_kill["phase_wall_s"] > 0 else 0.0)
        phase_reports["replica_kill"] = rep_kill
        scrapes["after_kill"] = loadgen.scrape_endpoint(
            group.obs_port)
        answered_after_kill = dict(
            router.stats()["answered_by_replica"])
        if watcher:
            watcher[0].join(timeout=60 * group.fleet_tick_s + 10.0)
        fleet_lag_s = (
            kill_vis["t_visible"] - kill_vis["t_kill"]
            if kill_vis["t_visible"] is not None
            and kill_vis["t_kill"] is not None else None)
        # fish ONE failed-over ticket out of the kill wave and stitch
        # its cross-replica story into a single fleet trace
        stitched = None
        for t in kill_tickets:
            if getattr(t, "failovers", 0) \
                    and getattr(t, "prior_traces", None):
                stitched = obs.stitch_fleet_trace(t)
                break

        # -- phase 2: graceful drain, mid-traffic -------------------
        t0 = time.perf_counter()
        rep_drain = loadgen.run_load(
            router, loadgen.build_schedule(
                rng, args.requests, rate_hz=0.0,
                deadline_ms=args.deadline_ms),
            verify=args.verify, rng=rng,
            result_timeout=args.result_timeout,
            mid_hook=lambda: group.drain("r1"))
        rep_drain["phase_wall_s"] = time.perf_counter() - t0
        rep_drain["throughput_rps"] = (
            (rep_drain["ok"] + rep_drain["degraded"])
            / rep_drain["phase_wall_s"]
            if rep_drain["phase_wall_s"] > 0 else 0.0)
        phase_reports["replica_drain"] = rep_drain
        scrapes["after_drain"] = loadgen.scrape_endpoint(
            group.obs_port)
        rstats = router.stats()
        answered_final = dict(rstats["answered_by_replica"])
        group_stats = group.stats()

        # -- phase 3: cold replica restart --------------------------
        # the zero-warmup story at replica scale: revive the killed
        # replica (Server.start preloads the warm artifact pack when
        # VELES_SIMD_ARTIFACTS is armed) and clock its FIRST request
        # against a survivor's steady-state single-request latency.
        # Honesty note: thread-mode replicas share the process's
        # compiled-handle caches, so what this gate holds to budget is
        # the restart PLUMBING (lifecycle, prober rejoin, preload
        # hook, first-request dispatch path) — the compile-elimination
        # number itself is tools/cold_start.py's subprocess
        # measurement, where the caches are genuinely empty
        survivor = group.replica("r2")
        probe_req = lambda: serve.Request(  # noqa: E731 — tiny local
            "sosfilt", rng.randn(512).astype(np.float32),
            {"sos": loadgen._sos()}, tenant="restart-probe")
        t0 = time.perf_counter()
        _replica_submit(survivor, probe_req()).result(
            timeout=args.result_timeout)
        lat_survivor = time.perf_counter() - t0
        restarted = group.restart("r0")
        t0 = time.perf_counter()
        restart_ticket = _replica_submit(restarted, probe_req())
        restart_ticket.result(timeout=args.result_timeout)
        lat_restart = time.perf_counter() - t0
        restart_status = restart_ticket.status

        # -- history axis (obs v6): breaker cycle + incident close --
        # one deterministic breaker cycle through the REAL Breaker
        # event seam (open -> half_open -> closed) so the journal
        # pack holds a complete breaker story to reconstruct — the
        # replica mix is healthy traffic, so no breaker trips
        # naturally in this campaign
        jbr = breaker.Breaker("serve.chaos", key="journal_cycle",
                              window=4, threshold=0.5, min_events=2,
                              probe_every=1)
        jbr.failure()
        jbr.failure()           # failure_rate -> open
        jbr.admit()             # probe cadence -> half_open
        jbr.success()           # probe_success -> closed
        # revive the drained replica too: with the whole fleet
        # healthy again the replica_down incident the kill opened can
        # CLOSE through the engine's quiet-period hysteresis while
        # the journal is still armed
        group.restart("r1")
        incident_deadline = faults.monotonic() + 30.0
        incident_closed_live = False
        while faults.monotonic() < incident_deadline:
            isnap = obs.incidents_snapshot()
            if any(i["rule"] == "replica_down"
                   and i["state"] == "closed"
                   for i in isnap.get("incidents", ())):
                incident_closed_live = True
                break
            threading.Event().wait(0.05)

        # -- fleet tracing overhead (collector armed) ---------------
        # the <5% request-axis overhead budget, re-measured while the
        # fleet collector sweeps the (still-started) group in the
        # background — the v5 axis must not buy its time series with
        # request latency.  Same A/B interleave as loadgen's row,
        # renamed so bench_regress tracks it as its own series (it
        # still matches the existing "tracing overhead" 5% noise
        # entry by substring).
        # the overhead row must measure the SAME flag configuration
        # as loadgen's gated "tracing overhead" series — the traffic
        # phases above ran with continuous batching + ragged packing
        # armed; disarm back to the caller's flags before measuring
        # (idempotent: the wrapper's finally restores again)
        restore_features()
        ov_args = argparse.Namespace(
            overhead_requests=(80 if args.smoke else 300),
            workers=args.workers)
        fleet_overhead = loadgen.overhead_row(ov_args, rng)
        fleet_overhead["metric"] = "fleet tracing overhead"
        fleet_overhead.setdefault("telemetry", {})[
            "collector_armed"] = True
        # -- journal-armed overhead (obs v6) ------------------------
        # same A/B interleave, toggling the durable journal instead
        # of the request axis: appending every decision to disk must
        # not buy history with request latency (loose in-campaign
        # floor here; the tight 5% gate is bench_regress's, via the
        # "journal overhead" noise entry)
        journal_overhead = loadgen.journal_overhead_row(ov_args, rng)

        # goodput counters live with the DISPATCHER: in-process that
        # is this process's obs counters; over the RPC data plane each
        # child owns its own, so sum them off the live children's
        # /metrics before the group stops (r0/r1 were restarted —
        # their reborn counters still make the fraction sane)
        child_rows = None
        if group.spawn != "thread":
            import urllib.request
            child_rows = {"useful": 0.0, "dispatched": 0.0}
            for r in group.replicas:
                if r.port is None:
                    continue
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{r.port}/metrics",
                            timeout=10) as resp:
                        text = resp.read().decode("utf-8")
                except Exception:  # noqa: BLE001 — partial sum ok
                    continue
                for line in text.splitlines():
                    if line.startswith(
                            "veles_simd_serve_useful_rows_total"):
                        child_rows["useful"] += float(
                            line.rsplit(None, 1)[1])
                    elif line.startswith(
                            "veles_simd_serve_dispatched_rows_total"):
                        child_rows["dispatched"] += float(
                            line.rsplit(None, 1)[1])

    total = _merge_router([warm, rep_kill, rep_drain])
    answered = total["ok"] + total["degraded"]
    drain_delta_survivors = (
        sum(answered_final.get(r, 0) for r in ("r1", "r2"))
        - sum(answered_after_kill.get(r, 0) for r in ("r1", "r2")))
    healthz_200 = {
        label: s["routes"].get("/healthz", "").startswith("200")
        for label, s in scrapes.items()}
    lifecycle = [
        (e["decision"], e.get("replica"))
        for e in _decisions("replica_lifecycle")]
    # -- postmortem reconstruction (obs v6) -------------------------
    # the group is stopped and (in subprocess mode) its replicas are
    # DEAD — everything below must come back from the on-disk journal
    # pack ALONE, through the same reader tools/obs_query.py uses.
    # In-memory obs state is deliberately not consulted.
    j_records, j_skipped = obs_journal.read_pack(journal_pack) \
        if journal_pack else ([], 0)
    j_files = [os.path.basename(p)
               for p in obs_journal.discover(journal_pack)] \
        if journal_pack else []
    j_decisions = [r for r in j_records if r.get("kind") == "decision"]
    j_lifecycle = [
        (r.get("decision"), (r.get("data") or {}).get("replica"))
        for r in j_decisions if r.get("op") == "replica_lifecycle"]
    j_breaker_edges = [
        r.get("decision") for r in j_decisions
        if r.get("op") == "breaker_transition"]
    j_incidents = obs_query.incidents_from(j_records)
    j_replica_down = [i for i in j_incidents
                      if i["rule"] == "replica_down"]
    # the restart budget: the revived replica's first request must
    # land within a generous multiple of the survivor's single-request
    # latency (plus an absolute floor for host-scheduling jitter —
    # both probes pay the same batcher max_wait).  In subprocess mode
    # a restart that recompiled under traffic would blow through this
    # by seconds; in the thread-mode campaign it bounds the restart
    # plumbing (see the phase-3 note above).
    restart_budget_s = max(0.5, 25.0 * lat_survivor)
    if args.spawn != "thread":
        # a restarted CHILD is a genuinely cold process: its first
        # request pays XLA compilation (no shared handle caches, no
        # warm pack armed here), so the budget bounds "restart +
        # compile under traffic", not restart plumbing
        restart_budget_s = max(restart_budget_s, 30.0)
    # fleet goodput: useful rows / dispatched rows across the whole
    # campaign, straight from the _finish_batch counters — a sane
    # value is a fraction in (0, 1] (pow2 padding means < 1 whenever
    # any batch padded; == 1 when every row was useful)
    useful_rows = _counter_total("serve_useful_rows")
    dispatched_rows = _counter_total("serve_dispatched_rows")
    if child_rows is not None:
        useful_rows += child_rows["useful"]
        dispatched_rows += child_rows["dispatched"]
    campaign_goodput = (useful_rows / dispatched_rows
                        if dispatched_rows else None)
    fleet_lag_ticks = (fleet_lag_s / group.fleet_tick_s
                       if fleet_lag_s is not None else None)
    stitch_meta = (stitched or {}).get("otherData", {})
    stitch_events = (stitched or {}).get("traceEvents", [])
    # both replicas' edges visible: every attempt track carries at
    # least one lifecycle instant event, and ≥2 distinct replicas
    # appear on the attempt list
    stitch_tids = {e.get("tid") for e in stitch_events
                   if e.get("ph") == "i"
                   and e.get("name") != "failover_hop"}
    stitch_dls = [d for d in stitch_meta.get("deadlines_ms", ())
                  if d is not None]
    invariants = {
        "zero_lost": total["lost"] == 0,
        "zero_double_answered": (
            total["double_answered"] == 0
            and _counter_total("router_dedup") == 0),
        "zero_untyped_errors": total["errors"] == 0,
        "parity_clean": total["parity_failures"] == 0,
        # the kill actually orphaned queued work and the router
        # re-routed every bit of it onto survivors
        "failover_observed": total["failovers"] >= 1,
        # every re-submission carried the ORIGINAL deadline's
        # remaining budget — never a fresh stamp
        "failover_deadlines_carried": (
            total["failover_deadline_checked"] >= 1
            and total["failover_deadline_violations"] == 0),
        # the killed replica's requests all reached a terminal edge
        # before re-routing — no orphaned causal chains
        "killed_replica_traces_terminal": (
            total["prior_trace_checked"] >= 1
            and total["prior_trace_orphans"] == 0),
        # the dead replica answers nothing after its kill; the
        # survivors absorb the whole drain-phase wave
        "killed_replica_frozen": (
            answered_final.get("r0", 0)
            == answered_after_kill.get("r0", 0)),
        "survivors_absorb_traffic": (
            drain_delta_survivors
            == rep_drain["ok"] + rep_drain["degraded"]
            and rep_drain["ok"] + rep_drain["degraded"] >= 1),
        # graceful drain loses nothing and leaves exactly one
        # survivor taking traffic
        "drain_graceful": (group_stats["alive"] == 1
                           and ("drain", "r1") in lifecycle
                           and ("dead", "r1") in lifecycle),
        "kill_recorded": ("kill", "r0") in lifecycle,
        # the cold-restart phase: the revived replica answered its
        # first request OK, within budget of the survivor's steady
        # state, and the lifecycle recorded the restart
        "restart_recorded": ("restart", "r0") in lifecycle,
        "restart_answered": restart_status in ("ok", "degraded"),
        "restart_within_budget": lat_restart <= restart_budget_s,
        "heartbeats_observed": beats_seen,
        # the router-level aggregation endpoint answered all three
        # routes — 200 on /healthz — before, between, and after the
        # failures (one replica always remained healthy)
        "group_healthz_live": all(
            s["ok"] == 3 and s["failed"] == 0
            for s in scrapes.values()),
        "group_healthz_200": all(healthz_200.values()),
        # the request axis stays complete across the group
        "zero_orphaned_traces": (total["trace_checked"] > 0
                                 and total["trace_orphans"] == 0),
        "trace_phases_sum_to_total": total["trace_phase_err"] == 0,
        "answers_accounted": (
            answered + total["shed"] + total["deadline_miss"]
            + total["closed"] + total["errors"]
            == total["requests"]),
        # -- fleet axis (obs v5) --------------------------------
        # the kill became visible through obs.signals() — the
        # autoscaler read path, not group internals — within a
        # bounded number of collector ticks (generous 60-tick CI
        # bound; typically 1-2 ticks of 25 ms)
        "fleet_kill_visible": (
            fleet_lag_ticks is not None and fleet_lag_ticks <= 60.0),
        # one failed-over request stitched into ONE fleet trace:
        # ≥2 attempts on ≥2 distinct replicas, every attempt track
        # carrying lifecycle edges
        "fleet_trace_stitched": (
            stitched is not None
            and stitch_meta.get("attempts", 0) >= 2
            and len(set(stitch_meta.get("replicas", ()))) >= 2
            and stitch_tids >= set(
                range(1, stitch_meta.get("attempts", 0) + 1))),
        # the stitched per-attempt deadline stamps only ever shrink —
        # the carried-deadline proof, readable off the fleet trace
        "fleet_trace_deadline_carried": (
            len(stitch_dls) >= 2
            and all(later <= earlier + 1e-6 for earlier, later
                    in zip(stitch_dls, stitch_dls[1:]))),
        # goodput is a sane fraction: some rows dispatched, useful
        # never exceeds dispatched
        "fleet_goodput_sane": (
            campaign_goodput is not None
            and 0.0 < campaign_goodput <= 1.0),
        # the request axis stays affordable with the collector
        # sweeping (loose in-campaign floor; the tight 5% gate is
        # bench_regress's, via the "tracing overhead" noise entry).
        # 0.70 not 0.80: under a full `make tests` run the throughput
        # ratio has measured as low as 0.74 from suite CPU contention
        # alone — the floor guards against a collapse, not noise
        "fleet_tracing_overhead_ok": (
            fleet_overhead["value"] is not None
            and fleet_overhead["value"] >= 0.70),
        # -- history axis (obs v6) ------------------------------
        # every parseable journal line recovered, no torn lines in
        # a cleanly-flushed pack, and at least one file per writer
        "journal_pack_readable": (
            len(j_files) >= 1 and j_skipped == 0
            and len(j_records) >= 1),
        # the kill/drain/restart story reconstructed purely from
        # disk — including BOTH revivals — matching what the live
        # decision log saw
        "journal_lifecycle_recovered": (
            ("kill", "r0") in j_lifecycle
            and ("drain", "r1") in j_lifecycle
            and ("dead", "r1") in j_lifecycle
            and ("restart", "r0") in j_lifecycle
            and ("restart", "r1") in j_lifecycle),
        # the scripted breaker cycle came back whole from disk:
        # open, half_open and re-closed edges all journaled
        "journal_breaker_cycle_recovered": (
            {"open", "half_open", "closed"}
            <= set(j_breaker_edges)),
        # the kill window's replica_down incident was OPENED by the
        # engine's hysteresis and CLOSED after the revived fleet's
        # quiet period — both edges reconstructed from disk alone
        "journal_incident_reconstructed": any(
            i["open"] is not None and i["close"] is not None
            for i in j_replica_down),
        # the same closure was visible live through /incidents
        # before the group stopped (diagnosis aid: separates an
        # engine problem from a journaling problem)
        "incident_closed_live": incident_closed_live,
        # journaling every decision stays affordable (loose floor;
        # the 5% gate is bench_regress's "journal overhead" entry).
        # 0.70 not 0.80: the A/B ratio measures 0.97 standalone but
        # dips to ~0.79 under full-suite CPU contention — like
        # fleet_tracing_overhead, this floor guards collapse, not
        # scheduler noise
        "journal_overhead_ok": (
            journal_overhead["value"] is not None
            and journal_overhead["value"] >= 0.70),
    }

    rows = [
        {"metric": "replica campaign answered",
         "value": float(answered), "unit": "req",
         "vs_baseline": None},
        {"metric": "replica failover throughput",
         "value": round(rep_kill["throughput_rps"], 2),
         "unit": "req/s", "vs_baseline": None,
         # measured while a replica dies mid-wave: fault-carrying,
         # DEGRADED-not-gated on a dip
         "chaos_phase": "replica_kill"},
        {"metric": "replica drain throughput",
         "value": round(rep_drain["throughput_rps"], 2),
         "unit": "req/s", "vs_baseline": None,
         "chaos_phase": "replica_drain"},
        {"metric": "replica restart first request",
         "value": round(1.0 / lat_restart, 3) if lat_restart else 0.0,
         "unit": "1/s", "vs_baseline": None,
         # one order statistic measured right after an abrupt kill +
         # restart: fault-carrying by construction
         "chaos_phase": "replica_restart",
         "telemetry": {"restart_s": round(lat_restart, 4),
                       "survivor_s": round(lat_survivor, 4),
                       "budget_s": round(restart_budget_s, 4)}},
    ]
    snap = obs.snapshot()
    counters = {}
    for c in snap["counters"]:
        if c["name"].startswith(("router_", "replica_", "serve_")):
            counters[c["name"]] = counters.get(c["name"], 0) \
                + c["value"]
    rows.append({
        "metric": "replica failovers",
        "value": float(total["failovers"]), "unit": "requests",
        "vs_baseline": None, "chaos_phase": "replica_kill",
        "telemetry": {"counters": counters},
    })
    if fleet_lag_s:
        rows.append({
            # higher-is-better form (1/lag) so the gate's floor logic
            # applies; one kill-to-visible wall-clock sample on the
            # collector cadence
            "metric": "fleet signal lag",
            "value": round(1.0 / fleet_lag_s, 3), "unit": "1/s",
            "vs_baseline": None, "chaos_phase": "replica_kill",
            "telemetry": {"lag_s": round(fleet_lag_s, 4),
                          "lag_ticks": round(fleet_lag_ticks, 2),
                          "tick_s": group.fleet_tick_s}})
    if campaign_goodput is not None:
        rows.append({
            "metric": "replica campaign goodput",
            "value": round(campaign_goodput, 4),
            "unit": "useful/dispatched rows", "vs_baseline": None,
            "telemetry": {"useful_rows": useful_rows,
                          "dispatched_rows": dispatched_rows}})
    rows.append(fleet_overhead)
    rows.append(journal_overhead)
    # --spawn subprocess writes its own bench series (the suffix keeps
    # substring-matched noise entries like "replica failover" applying
    # to both) and every row records the transport it measured; the
    # overhead rows stay unsuffixed — they A/B a fresh in-process
    # server regardless of campaign spawn
    suffix = "" if args.spawn == "thread" else f" {args.spawn}"
    for row in rows:
        if suffix and "overhead" not in row["metric"]:
            row["metric"] += suffix
        row["spawn"] = args.spawn
    evidence = {
        "replica_invariants": invariants,
        "spawn": args.spawn,
        "restart": {"first_request_s": lat_restart,
                    "survivor_s": lat_survivor,
                    "budget_s": restart_budget_s,
                    "status": restart_status},
        "phase_reports": {k: {kk: vv for kk, vv in v.items()
                              if not isinstance(vv, np.ndarray)}
                          for k, v in phase_reports.items()},
        "router": {k: rstats[k] for k in
                   ("policy", "max_failovers", "placed_by_replica",
                    "answered_by_replica", "failovers",
                    "placement_failures")},
        "answered_after_kill": answered_after_kill,
        "answered_final": answered_final,
        "replica_lifecycle_events":
            _decisions("replica_lifecycle"),
        "router_failover_events": _decisions("router_failover"),
        "scrapes": scrapes,
        "group": group_stats,
        "fleet": {
            "tick_s": group.fleet_tick_s,
            "kill_visible_lag_s": fleet_lag_s,
            "kill_visible_lag_ticks": fleet_lag_ticks,
            "goodput": campaign_goodput,
            "stitched_trace": stitch_meta,
        },
        "journal": {
            "pack": journal_pack,
            "files": j_files,
            "records": len(j_records),
            "skipped": j_skipped,
            "lifecycle": j_lifecycle,
            "breaker_edges": j_breaker_edges,
            "incidents": [
                {"id": i["id"], "rule": i["rule"],
                 "opened": i["open"] is not None,
                 "closed": i["close"] is not None}
                for i in j_incidents],
        },
    }
    return invariants, rows, evidence


# -- the control-axis campaign (obs v7): make chaos-scale -------------------

class _ShimReplica:
    def __init__(self, rid):
        self.rid = rid


class _ShimGroup:
    """A group-shaped stub for the SYNTHETIC scaler segments (the
    flap-storm and the deterministic incident chain): real verbs are
    recorded, no servers are born.  The live-ramp segment uses a real
    ``ReplicaGroup`` — this shim only exists so the synthetic engines
    can act without disturbing it."""

    def __init__(self, n=1):
        self.rids = [f"s{i}" for i in range(n)]
        self.calls = []

    def alive(self) -> int:
        return len(self.rids)

    def live_replicas(self) -> list:
        return [_ShimReplica(r) for r in self.rids]

    def spawn_replica(self):
        rid = f"s{len(self.calls) + len(self.rids)}"
        self.rids.append(rid)
        self.calls.append(("spawn", rid))
        return _ShimReplica(rid)

    def retire(self, rid, reason="scaler"):
        self.rids.remove(rid)
        self.calls.append(("retire", rid))

    def restart(self, rid):
        self.calls.append(("restart", rid))
        return _ShimReplica(rid)


def _synth_sig(t, *, burn=0.0, bvel=0.0, depth=0.0, flaps=0,
               goodput=1.0, health=None, incidents=()):
    """A FleetSignals-shaped bundle with a scripted clock — the same
    duck type ``ScalerEngine.tick`` and ``IncidentEngine.tick`` read,
    so the synthetic segments drive REAL engines deterministically."""
    return argparse.Namespace(
        at_s=t,
        slo_burn={"carol": burn} if burn else {},
        slo_burn_velocity={"carol": bvel} if bvel else {},
        queue_depth={}, queue_depth_total=depth,
        breaker_flaps={"chaos": flaps} if flaps else {},
        goodput_overall=goodput, health=dict(health or {}),
        incidents=list(incidents))


def run_scale_campaign(args) -> tuple:
    """Arm the durable journal + a fast incident cadence around the
    control-axis campaign body, exactly like the replica campaign: the
    whole run journals to a fresh pack so the decision sequence can be
    gated purely from disk after every replica is gone."""
    journal_pack = tempfile.mkdtemp(prefix="veles-chaos-scale-")
    armed = {obs_journal.JOURNAL_DIR_ENV: journal_pack,
             obs_incidents.TICK_MS_ENV: "50"}
    prior = {k: os.environ.get(k) for k in armed}

    def _restore():
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    os.environ.update(armed)
    # a stale incident ledger from an earlier campaign/test in this
    # process would pollute the pack's incident -> action chain
    obs_incidents.reset()
    try:
        return _scale_campaign_body(args, journal_pack)
    finally:
        _restore()


def _scale_campaign_body(args, journal_pack=None) -> tuple:
    """The obs v7 proof, four segments:

    1. **diurnal ramp** — low -> ~10x peak -> low over a LIVE armed
       group (``scaler=True``): the queue-backlog rule must spawn at
       least one warm replica under the peak, the sustained-idle rule
       must retire back to ``min`` after, p99 + SLO hit rate stay in
       budget, zero lost/double-answered across the scale events, and
       replica-seconds land within a factor of the oracle-optimal
       schedule (self-calibrated from measured 1-replica capacity);
    2. **flap-storm** — a synthetic oscillating signal (burn + breaker
       flaps flipping every tick) over a REAL engine: hysteresis must
       produce ZERO actions — only typed no-ops;
    3. **deterministic incident chain** — a real ``IncidentEngine``
       opens an ``slo_burn`` incident, a real ``ScalerEngine`` acts on
       it (the decision event carries the incident id), the signals
       recover, the incident closes — all journaled;
    4. **offline reconstruction** — the pack alone (``obs_journal`` +
       ``tools/obs_query``) must recover every live decision, the
       scale_up/scale_down story, and render the postmortem's
       incident -> action -> effect chain with signal deltas.

    Returns ``(invariants, rows, evidence)``."""
    import urllib.request

    from veles.simd_tpu.serve import cluster
    from veles.simd_tpu.serve import scaler as serve_scaler

    rng = np.random.RandomState(args.seed)
    # generous per-tenant SLOs (the loadgen idiom): the gate is that
    # the accounting runs and scaling KEEPS the hit rate ~1.0 through
    # the ramp, not that a CPU smoke hits production latencies
    for tenant in loadgen.DEFAULT_TENANTS:
        obs.slo(tenant, target_ms=args.deadline_ms, hit_rate=0.99)

    scale_max = args.scale_max
    # control config tuned to the smoke clock: 30 ms ticks, 2-tick
    # up hysteresis, a ~0.4 s sustained-idle window, cooldown between
    # every action.  depth_high is the deterministic CPU trigger — the
    # peak burst lands as one backlog far above it, while the paced
    # low phases never accumulate depth.
    group = cluster.ReplicaGroup(
        1, max_batch=8, max_wait_ms=4.0, workers=args.workers,
        heartbeat_ms=40.0, obs_port=0, fleet_tick_ms=25.0,
        scaler=True, scaler_tick_ms=30.0,
        scaler_kwargs=dict(
            min_replicas=1, max_replicas=scale_max,
            cooldown_s=0.35, up_ticks=2, down_ticks=12,
            depth_high=6.0, idle_depth=1.0))
    router = cluster.FrontRouter(group)
    phase_reports: dict = {}

    # replica-seconds sampler: integrate alive-count over the ramp
    samples: list = []
    sampler_stop = threading.Event()

    def _sample():
        while not sampler_stop.wait(0.02):
            samples.append((time.monotonic(), group.alive()))

    def _settle_to_min(deadline_s):
        """Wait for the idle rule to retire back to min (best effort:
        the gates below assert the counts, not this wait)."""
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline and group.alive() > 1:
            threading.Event().wait(0.05)

    with group:
        # -- warmup: compile the mix's handles off the clock --------
        warm = loadgen.run_load(
            router, loadgen.build_schedule(
                rng, 6, rate_hz=0.0, deadline_ms=args.deadline_ms),
            verify=0, rng=rng, result_timeout=args.result_timeout)
        phase_reports["warm"] = warm
        # -- calibrate 1-replica capacity for the oracle ------------
        t0 = time.perf_counter()
        calib = loadgen.run_load(
            router, loadgen.build_schedule(
                rng, args.low_requests, rate_hz=0.0,
                deadline_ms=args.deadline_ms),
            verify=0, rng=rng, result_timeout=args.result_timeout)
        calib_wall = max(time.perf_counter() - t0, 1e-6)
        rate1 = max((calib["ok"] + calib["degraded"]) / calib_wall,
                    1e-6)
        phase_reports["calib"] = calib
        _settle_to_min(8.0)

        # -- the diurnal ramp ---------------------------------------
        sampler = threading.Thread(target=_sample, daemon=True)
        t_ramp0 = time.monotonic()
        sampler.start()
        phase_meta = []
        t0 = time.perf_counter()
        low1 = loadgen.run_load(
            router, loadgen.build_schedule(
                rng, args.low_requests, rate_hz=args.low_rate,
                deadline_ms=args.deadline_ms),
            verify=args.verify, rng=rng,
            result_timeout=args.result_timeout)
        phase_meta.append(("low1", args.low_requests,
                           max(time.perf_counter() - t0, 1e-6)))
        phase_reports["scale_low1"] = low1
        # peak: ~10x the low offered rate, submitted unpaced — the
        # whole burst lands as queue backlog, the deterministic
        # scale-up trigger on a CPU box that is never latency-bound
        t_peak_wall = time.time()
        t0 = time.perf_counter()
        peak = loadgen.run_load(
            router, loadgen.build_schedule(
                rng, args.peak_requests, rate_hz=0.0,
                deadline_ms=args.deadline_ms),
            verify=args.verify, rng=rng,
            result_timeout=args.result_timeout)
        peak_wall = max(time.perf_counter() - t0, 1e-6)
        phase_meta.append(("peak", args.peak_requests, peak_wall))
        phase_reports["scale_peak"] = peak
        t0 = time.perf_counter()
        low2 = loadgen.run_load(
            router, loadgen.build_schedule(
                rng, args.low_requests, rate_hz=args.low_rate,
                deadline_ms=args.deadline_ms),
            verify=args.verify, rng=rng,
            result_timeout=args.result_timeout)
        phase_meta.append(("low2", args.low_requests,
                           max(time.perf_counter() - t0, 1e-6)))
        phase_reports["scale_low2"] = low2
        # ramp down: the sustained-idle window must retire the extra
        # replicas back to min while the journal is still armed
        _settle_to_min(10.0)
        t_ramp1 = time.monotonic()
        sampler_stop.set()
        sampler.join(timeout=2.0)

        # -- live surfaces while armed ------------------------------
        live_snap = obs.scaler_snapshot()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{group.obs_port}/scaler",
                timeout=10) as r:
            route_snap = json.loads(r.read().decode("utf-8"))
        scaler_summary = group.stats()["scaler"]
        alive_end = group.alive()
        slo_snap = obs.slo_snapshot()
        live_actions = dict(serve_scaler.snapshot()["actions"])

    # -- segment 2: flap-storm over a real engine (zero thrash) -----
    storm_shim = _ShimGroup(2)
    storm = serve_scaler.ScalerEngine(
        storm_shim, min_replicas=1, max_replicas=scale_max,
        cooldown_s=0.2, up_ticks=2, down_ticks=100)
    for i in range(40):
        hot = bool(i % 2)
        storm.tick(_synth_sig(
            2000.0 + 0.05 * i, burn=5.0 if hot else 0.0,
            flaps=12 if hot else 0, depth=0.0,
            goodput=0.3 if hot else 1.0))
    storm_snap = storm.snapshot()

    # -- segment 3: deterministic incident -> action -> effect ------
    ieng = obs_incidents.IncidentEngine(open_ticks=2, close_ticks=2,
                                        burn=1.0)
    # ids are inc-<pid>-<seq> per ENGINE: offset this engine's seq so
    # its ids can never collide with whatever the process engine
    # opened during the ramp (both live in the same journal pack)
    ieng._seq = 9000
    base = 3000.0
    ieng.tick(_synth_sig(base, burn=4.0))
    opened = ieng.tick(_synth_sig(base + 0.05, burn=4.0))
    det_id = opened[0].id if opened else None
    open_incs = [{"rule": i.rule, "id": i.id} for i in opened]
    det_shim = _ShimGroup(1)
    det_eng = serve_scaler.ScalerEngine(
        det_shim, min_replicas=1, max_replicas=3,
        cooldown_s=0.1, up_ticks=2, down_ticks=400)
    det_eng.tick(_synth_sig(base + 0.10, burn=4.0,
                            incidents=open_incs))
    det_act = det_eng.tick(_synth_sig(base + 0.15, burn=4.0,
                                      incidents=open_incs))
    # the spawn lands, the burn falls: the effect window's "after"
    det_eng.tick(_synth_sig(base + 0.20, burn=0.3))
    det_eng.tick(_synth_sig(base + 0.25, burn=0.1))
    ieng.tick(_synth_sig(base + 0.30))
    closed = ieng.tick(_synth_sig(base + 0.35))

    # -- segment 4: offline reconstruction from the pack alone ------
    j_records, j_skipped = obs_journal.read_pack(journal_pack) \
        if journal_pack else ([], 0)
    j_files = [os.path.basename(p)
               for p in obs_journal.discover(journal_pack)] \
        if journal_pack else []
    j_scaler = [r for r in j_records
                if r.get("kind") == "decision"
                and r.get("op") == "scaler"]
    j_actions = [r for r in j_scaler
                 if r.get("decision") not in (None, "noop")]
    j_action_kinds = {r.get("decision") for r in j_actions}
    j_noop_reasons = {(r.get("data") or {}).get("reason")
                      for r in j_scaler
                      if r.get("decision") == "noop"}
    j_incidents = obs_query.incidents_from(j_records)
    det_rec = next((i for i in j_incidents if i["id"] == det_id),
                   None)
    linked = obs_query.scaler_actions(j_records, det_id) \
        if det_id else []
    pm_text = ""
    effect = []
    if det_rec is not None and det_rec["open"] is not None:
        pm_text = obs_query.postmortem(j_records, det_rec)
        t_close = (det_rec["close"] or {}).get(
            "t_wall", float("inf"))
        effect = obs_query.scaler_effect(j_records, linked, t_close)
    effect_map = {k: (b, a) for k, b, a in effect}

    # -- the numbers ------------------------------------------------
    total = _merge_router(
        [warm, calib, low1, peak, low2])
    answered = total["ok"] + total["degraded"]
    # replica-seconds across the ramp window vs the oracle schedule:
    # per phase, the replicas a clairvoyant controller would hold at
    # the measured 1-replica capacity — a smoke-level sanity bound
    # (factor --oracle-factor) whose real job is catching a scaler
    # that pins max replicas forever
    measured_rs = 0.0
    prev_t, prev_alive = t_ramp0, 1
    for t, alive in samples:
        measured_rs += (t - prev_t) * prev_alive
        prev_t, prev_alive = t, alive
    measured_rs += max(t_ramp1 - prev_t, 0.0) * prev_alive
    window_s = max(t_ramp1 - t_ramp0, 1e-6)
    oracle_rs = 0.0
    for _name, n_req, wall in phase_meta:
        offered = n_req / wall
        need = min(max(1, int(np.ceil(offered / rate1))), scale_max)
        oracle_rs += need * wall
    oracle_rs += max(window_s - sum(w for _, _, w in phase_meta),
                     0.0) * 1.0   # settle tail: oracle holds min
    rs_budget = args.oracle_factor * oracle_rs
    # decision lag: peak start -> the first scale_up the LIVE engine
    # committed after it, read back from the journal (the in-memory
    # decision tail is bounded and the ramp outlives it).  Live
    # replicas are r<N>; the synthetic segments' shim rids are s<N>,
    # so the filter can't match a scripted action.
    lag_s = None
    for r in j_actions:
        data = r.get("data") or {}
        if (r.get("decision") == "scale_up"
                and str(data.get("replica", "")).startswith("r")
                and r.get("t_wall", 0.0) >= t_peak_wall):
            lag_s = r["t_wall"] - t_peak_wall
            break
    peak_p99 = peak.get("wait_p99_s") or 0.0
    hit_rates = [t["hit_rate_observed"]
                 for t in slo_snap.get("accounts", {}).values()
                 if isinstance(t, dict)
                 and t.get("hit_rate_observed") is not None]
    hit_rate_min = min(hit_rates) if hit_rates else None
    alive_seen = [a for _, a in samples] or [1]

    invariants = {
        # the request path stayed whole across every scale event
        "zero_lost": total["lost"] == 0,
        "zero_double_answered": (
            total["double_answered"] == 0
            and _counter_total("router_dedup") == 0),
        "zero_untyped_errors": total["errors"] == 0,
        "parity_clean": total["parity_failures"] == 0,
        "answers_accounted": (
            answered + total["shed"] + total["deadline_miss"]
            + total["closed"] + total["errors"]
            == total["requests"]),
        # the controller actually controlled: up under the peak, back
        # down after, never outside [min, max], settled at min
        "scaled_up": live_actions.get("scale_up", 0) >= 1,
        "scaled_down": live_actions.get("scale_down", 0) >= 1,
        "bounds_respected": (min(alive_seen) >= 1
                             and max(alive_seen) <= scale_max),
        "settled_to_min": alive_end == 1,
        # latency + SLO stayed in budget THROUGH the ramp
        "p99_within_budget": peak_p99 <= args.p99_budget_s,
        "slo_hit_rate_held": (hit_rate_min is not None
                              and hit_rate_min >= 0.95),
        # efficiency: replica-seconds within a factor of the oracle
        "replica_seconds_bounded": measured_rs <= rs_budget,
        # the live control surfaces served while armed
        "scaler_route_live": (
            route_snap.get("schema") == serve_scaler.SCHEMA
            and route_snap.get("armed") is True
            and route_snap.get("ticks", 0) > 0),
        "scaler_snapshot_live": (
            live_snap.get("armed") is True
            and live_snap.get("ticks", 0) > 0
            and scaler_summary is not None
            and scaler_summary["ticks"] > 0),
        # segment 2: the flap-storm produced ZERO actions — only
        # typed no-ops — through the same hysteresis that let the
        # real ramp act
        "flap_storm_no_thrash": (
            storm_snap["ticks"] == 40
            and not storm_snap["actions"]
            and not storm_shim.calls
            and set(storm_snap["noops"])
            <= set(serve_scaler.NOOP_REASONS)),
        # segment 3 happened as scripted: open -> linked action ->
        # close, entirely through real engines
        "incident_chain_scripted": (
            det_id is not None and bool(closed)
            and det_act.get("action") == "scale_up"
            and det_act.get("incident_id") == det_id),
        # segment 4: the pack alone recovers every live decision and
        # the whole scale story
        "journal_pack_readable": (
            len(j_files) >= 1 and j_skipped == 0
            and len(j_records) >= 1),
        "journal_every_tick_recovered": (
            scaler_summary["ticks"] > 0
            and len(j_scaler) >= scaler_summary["ticks"]),
        "journal_scale_story_recovered": (
            {"scale_up", "scale_down"} <= j_action_kinds),
        "journal_noops_typed": (
            j_noop_reasons
            and j_noop_reasons <= set(serve_scaler.NOOP_REASONS)),
        # the postmortem renders the causal incident -> action ->
        # effect chain offline, and the effect window shows the burn
        # actually falling across the action
        "postmortem_chain_rendered": (
            det_rec is not None and det_rec["close"] is not None
            and len(linked) == 1
            and "scaler actions linked" in pm_text
            and "effect window" in pm_text),
        "postmortem_effect_moved": (
            "burn_max" in effect_map
            and effect_map["burn_max"][0] is not None
            and effect_map["burn_max"][1] is not None
            and effect_map["burn_max"][1]
            < effect_map["burn_max"][0]),
    }

    rows = [
        {"metric": "scale campaign answered",
         "value": float(answered), "unit": "req",
         "vs_baseline": None},
        {"metric": "scale p99 under ramp",
         # higher-is-better form (1/p99) so the gate's floor logic
         # applies; measured across the unpaced ~10x peak burst —
         # deliberately overloaded, DEGRADED-not-gated on a dip
         "value": round(1.0 / peak_p99, 3) if peak_p99 else 0.0,
         "unit": "1/s", "vs_baseline": None,
         "chaos_phase": "scale_peak",
         "telemetry": {"p99_s": round(peak_p99, 4),
                       "budget_s": args.p99_budget_s,
                       "peak_requests": args.peak_requests,
                       "peak_wall_s": round(peak_wall, 3)}},
        {"metric": "scale replica-seconds vs oracle",
         "value": round(oracle_rs / measured_rs, 3)
         if measured_rs else 0.0,
         "unit": "oracle/measured", "vs_baseline": None,
         "chaos_phase": "scale_ramp",
         "telemetry": {"measured_rs": round(measured_rs, 3),
                       "oracle_rs": round(oracle_rs, 3),
                       "rate1_rps": round(rate1, 2),
                       "factor_budget": args.oracle_factor,
                       "window_s": round(window_s, 3)}},
        {"metric": "scale slo hit rate",
         "value": (round(hit_rate_min, 4)
                   if hit_rate_min is not None else 0.0),
         "unit": "fraction", "vs_baseline": None},
    ]
    if lag_s is not None and lag_s > 0:
        rows.append({
            # higher-is-better (1/lag): peak start -> first committed
            # scale_up, on the 30 ms control cadence
            "metric": "scale decision lag",
            "value": round(1.0 / lag_s, 3), "unit": "1/s",
            "vs_baseline": None, "chaos_phase": "scale_peak",
            "telemetry": {"lag_s": round(lag_s, 4),
                          "tick_s": 0.03}})
    for row in rows:
        # the scaler-armed ramp group is thread-mode; the stamp keeps
        # SCALE rows self-describing next to the REPLICA families
        row["spawn"] = group.spawn
    evidence = {
        "scale_invariants": invariants,
        "phase_reports": {k: {kk: vv for kk, vv in v.items()
                              if not isinstance(vv, np.ndarray)}
                          for k, v in phase_reports.items()},
        "scaler": {"live": live_snap, "route": route_snap,
                   "summary": scaler_summary,
                   "storm": {k: storm_snap[k]
                             for k in ("ticks", "actions", "noops")},
                   "deterministic_action": det_act},
        "ramp": {"samples": len(samples),
                 "alive_min": min(alive_seen),
                 "alive_max": max(alive_seen),
                 "measured_replica_s": measured_rs,
                 "oracle_replica_s": oracle_rs,
                 "rate1_rps": rate1,
                 "decision_lag_s": lag_s,
                 "phases": [{"name": n, "requests": r,
                             "wall_s": round(w, 3)}
                            for n, r, w in phase_meta]},
        "slo": slo_snap,
        "journal": {
            "pack": journal_pack,
            "files": j_files,
            "records": len(j_records),
            "skipped": j_skipped,
            "scaler_decisions": len(j_scaler),
            "scaler_actions": sorted(j_action_kinds),
            "noop_reasons": sorted(r for r in j_noop_reasons if r),
            "incidents": [
                {"id": i["id"], "rule": i["rule"],
                 "opened": i["open"] is not None,
                 "closed": i["close"] is not None}
                for i in j_incidents],
            "postmortem": pm_text,
        },
    }
    return invariants, rows, evidence


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--requests", type=int, default=48,
                    help="mixed-traffic requests per phase slice")
    ap.add_argument("--steady", type=int, default=12,
                    help="poisoned-class requests in the steady "
                         "(breaker-open) segment")
    ap.add_argument("--recovery-calls", type=int, default=8)
    ap.add_argument("--mesh-loss-calls", type=int, default=4)
    ap.add_argument("--mesh-devices", type=int, default=8)
    ap.add_argument("--overloads", type=int, default=6,
                    help="injected admission overloads in phase 2")
    ap.add_argument("--deadline-ms", type=float, default=30000.0,
                    help="end-to-end deadline stamped on every "
                         "request (generous: only real stalls miss)")
    ap.add_argument("--max-miss-frac", type=float, default=0.25,
                    help="deadline misses allowed, as a fraction of "
                         "total requests")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--verify", type=int, default=8)
    ap.add_argument("--result-timeout", type=float, default=300.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--details", default=None,
                    help="write BENCH_DETAILS-format rows + evidence "
                         "here (default CHAOS_DETAILS.json, or "
                         "REPLICA_DETAILS.json with --replicas)")
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-long CPU campaign (the CI gate)")
    ap.add_argument("--replicas", action="store_true",
                    help="run the 3-phase REPLICATED campaign "
                         "instead (make chaos-replicas): kill one "
                         "replica abruptly mid-traffic, drain "
                         "another gracefully, gate group-wide "
                         "zero-lost/failover/healthz invariants")
    ap.add_argument("--spawn", choices=("thread", "subprocess"),
                    default="thread",
                    help="[--replicas] replica isolation: subprocess "
                         "runs the campaign over the RPC data plane "
                         "(make chaos-replicas-rpc) — the abrupt "
                         "kill is a real child SIGKILL mid-traffic")
    ap.add_argument("--scale", action="store_true",
                    help="run the CONTROL-AXIS campaign instead "
                         "(make chaos-scale): a ~10x diurnal ramp "
                         "over a scaler-armed group, gating p99/SLO "
                         "through the scale events, replica-seconds "
                         "vs oracle, flap-storm zero-thrash, and "
                         "the decision sequence recovered from the "
                         "journal pack alone")
    ap.add_argument("--peak-requests", type=int, default=96,
                    help="[--scale] unpaced requests in the peak "
                         "burst (the ~10x overload)")
    ap.add_argument("--low-requests", type=int, default=10,
                    help="[--scale] requests per paced low phase")
    ap.add_argument("--low-rate", type=float, default=12.0,
                    help="[--scale] offered req/s in the low phases")
    ap.add_argument("--scale-max", type=int, default=3,
                    help="[--scale] scaler max_replicas bound")
    ap.add_argument("--oracle-factor", type=float, default=4.0,
                    help="[--scale] replica-seconds budget as a "
                         "multiple of the oracle schedule")
    ap.add_argument("--p99-budget-s", type=float, default=25.0,
                    help="[--scale] queue-wait p99 budget across "
                         "the peak burst")
    args = ap.parse_args(argv)
    if args.details is None:
        args.details = (
            ("REPLICA_RPC_DETAILS.json" if args.spawn != "thread"
             else "REPLICA_DETAILS.json") if args.replicas
            else "SCALE_DETAILS.json" if args.scale
            else "CHAOS_DETAILS.json")
    if args.smoke:
        args.requests = min(args.requests, 24)
        args.steady = min(args.steady, 8)
        args.verify = min(args.verify, 4)
        args.peak_requests = min(args.peak_requests, 72)
        args.low_requests = min(args.low_requests, 8)

    if not (args.replicas or args.scale):
        # the sharded phase needs the virtual CPU mesh (the pin must
        # win the race to backend init); in-process callers (tests)
        # already pinned it, in which case the failed re-pin is fine
        # as long as enough devices exist
        import jax

        from veles.simd_tpu.utils.platform import pin_cpu

        try:
            pin_cpu(args.mesh_devices)
        except RuntimeError:
            if len(jax.devices()) < args.mesh_devices:
                raise

    obs.enable()
    obs.reset()
    breaker.reset()
    faults.reset_fault_history()
    if args.replicas:
        invariants, rows, evidence = run_replica_campaign(args)
    elif args.scale:
        invariants, rows, evidence = run_scale_campaign(args)
    else:
        # a tight half-open cadence keeps the recovery phase's
        # counting argument exact: a closed-at-end breaker within the
        # scripted number of calls (restored after the campaign)
        prev_cadence = os.environ.get(
            breaker.BREAKER_PROBE_EVERY_ENV)
        os.environ[breaker.BREAKER_PROBE_EVERY_ENV] = "2"
        try:
            invariants, rows, evidence = run_campaign(args)
        finally:
            if prev_cadence is None:
                os.environ.pop(breaker.BREAKER_PROBE_EVERY_ENV,
                               None)
            else:
                os.environ[breaker.BREAKER_PROBE_EVERY_ENV] = \
                    prev_cadence

    print(json.dumps({"invariants": invariants,
                      "rows": rows}, indent=2, default=str))
    if args.details:
        with open(args.details, "w") as f:
            json.dump(rows + [evidence], f, indent=2, default=str)
        print(f"chaos: wrote {args.details}", file=sys.stderr)
    failed = sorted(k for k, ok in invariants.items() if not ok)
    if failed:
        print(f"chaos: FAILED invariants: {', '.join(failed)}",
              file=sys.stderr)
        return 1
    print("chaos: campaign green — all invariants hold",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
