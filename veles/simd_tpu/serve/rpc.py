"""RPC data plane: request submission over a subprocess replica's wire.

Until now a ``spawn="subprocess"`` replica only exposed *telemetry*
(``/healthz`` + ``/metrics``): the group could heartbeat it, scrape
it, kill it — but never place a request on it, and the
:class:`~veles.simd_tpu.serve.cluster.FrontRouter` refused subprocess
groups typed.  This module is the missing data plane (ROADMAP item 1's
multi-host half): the child's existing obs endpoint grows a ``POST
/submit`` route serving the FULL request surface (plain ops, pipeline
invocations, deadlines, tenants, params), and the router gains a
pooled persistent-connection client so subprocess groups serve traffic
through the same ``_submit_to_replica`` funnel as thread groups.

Design rules, in order of importance:

* **semantics are bit-identical to in-process.**  The typed error
  surface crosses the wire losslessly — the mapping table
  (:data:`ERROR_KINDS`, pinned both directions by tests):

  ==============  ==========================================  =======
  wire ``kind``   Python type                                 status
  ==============  ==========================================  =======
  ``overloaded``  :class:`~veles.simd_tpu.serve.admission.
                  Overloaded` (``tenant``/``scope`` carried;
                  ``scope="cluster"`` round-trips as
                  :class:`~veles.simd_tpu.serve.cluster.
                  NoReplicaAvailable`)                        ``shed``
  ``deadline``    :class:`~veles.simd_tpu.serve.server.
                  DeadlineExceeded`                        ``expired``
  ``closed``      :class:`~veles.simd_tpu.serve.server.
                  ServerClosed`                             ``closed``
  ``bad_request`` :class:`ValueError` (a caller bug, never
                  traffic)                                   ``error``
  ``error``       :class:`RuntimeError`                      ``error``
  ==============  ==========================================  =======

  so the router's failover/shed handling cannot tell a remote terminal
  from a local one.  A transport failure (connection reset, refused,
  timed out, garbage reply) is a ``closed`` ticket — exactly what an
  in-process replica dying under a queued request produces, so the
  failover hook re-routes it — unless the request's own deadline
  already passed, in which case it is ``expired`` (a caller who gave
  up must read ``DEADLINE_EXCEEDED``, not a transport story).
* **deadlines are re-stamped as remaining budget.**  The router
  resolves one absolute deadline per request; every wire submission
  carries the *remaining* milliseconds at send time (the same
  arithmetic ``_submit_to_replica`` applies to thread replicas), and
  the child re-anchors it on its own clock — monotonic clocks don't
  cross process boundaries, remaining budgets do.
* **arrays ride binary npy framing, never base64-JSON.**  A frame is
  ``VSRPC1`` + a 4-byte big-endian header length + a JSON header + the
  concatenated npy blobs it references; signals, params arrays, and
  answer payloads (including pipeline ``(out, state)`` trees) are
  ``np.save``-serialized — bytes-exact dtype/shape round-trips at
  memcpy cost (:func:`pack_frame` / :func:`unpack_frame`).
* **perf is the headline.**  :class:`RpcClient` keeps
  ``$VELES_SIMD_RPC_CONNS`` (default 4) persistent keep-alive
  connections per replica, each owned by a dedicated sender thread, so
  submissions overlap in flight (RTT hides under device time) and no
  request pays TCP setup.  ``tools/loadgen.py --rpc-overhead`` is the
  gated proof: loadgen through an in-process group vs an identical
  subprocess group, added p50 budgeted, throughput ratio floored via
  ``bench_regress``.
* **a malformed or truncated body answers typed, never hangs.**  The
  server side wraps every parse in one funnel that degrades to a
  ``bad_request`` response; the client side maps an unparseable reply
  to a ``closed`` ticket (ops are pure — re-execution on a survivor is
  safe, and router dedup keeps double answers impossible).

Trace edges: the client stamps ``rpc_submit`` / ``rpc_sent`` /
``rpc_transport_error`` on the local request trace, and the response
carries the CHILD's trace events, absorbed via
:meth:`~veles.simd_tpu.obs.requests.RequestTrace.absorb_remote` with
their replica identity — ``obs.stitch_fleet_trace`` renders one story
across the process boundary.

Knobs: ``$VELES_SIMD_RPC_CONNS`` (pooled connections = max in-flight
per replica; default 4), ``$VELES_SIMD_RPC_TIMEOUT_MS`` (transport
timeout + the no-deadline response wait bound; default 30000).
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import queue
import struct
import threading

import numpy as np

from veles.simd_tpu import obs
from veles.simd_tpu.runtime import faults
from veles.simd_tpu.serve.admission import Overloaded
from veles.simd_tpu.serve.server import (DeadlineExceeded, Request,
                                         ServerClosed, Ticket,
                                         classify_request,
                                         env_deadline_ms)

__all__ = [
    "RpcClient", "RpcTicket", "serve_submit",
    "pack_frame", "unpack_frame", "pack_request", "unpack_request",
    "pack_response", "unpack_response", "encode_error", "decode_error",
    "MAGIC", "WIRE_SCHEMA", "ERROR_KINDS", "CONTENT_TYPE",
    "RPC_CONNS_ENV", "RPC_TIMEOUT_ENV", "DEFAULT_RPC_CONNS",
    "DEFAULT_RPC_TIMEOUT_MS", "env_conns", "env_timeout_s",
]

MAGIC = b"VSRPC1"
WIRE_SCHEMA = "veles-simd-rpc-v1"
CONTENT_TYPE = "application/x-veles-rpc"

RPC_CONNS_ENV = "VELES_SIMD_RPC_CONNS"
RPC_TIMEOUT_ENV = "VELES_SIMD_RPC_TIMEOUT_MS"

# 4 in-flight submissions per replica overlap RTT with device time at
# loadgen's concurrency without minting a thread per request
DEFAULT_RPC_CONNS = 4
DEFAULT_RPC_TIMEOUT_MS = 30000.0

# the server-side response wait extends this far past the request's
# own deadline: the replica expires overdue work itself (typed), the
# margin only covers the expiry sweep + response packing
RESPONSE_MARGIN_S = 5.0

# wire kind <-> Python type (the table the tests pin both directions);
# decode_error / encode_error are the implementation
ERROR_KINDS = ("overloaded", "deadline", "closed", "bad_request",
               "error")

# one JSON header is bounded by construction (arrays ride blobs); a
# bigger one is a corrupt frame, not a bigger request
_MAX_HEADER = 1 << 24


def env_conns() -> int:
    """Pooled connections per replica from ``$VELES_SIMD_RPC_CONNS``
    (default 4; malformed / non-positive falls back)."""
    raw = os.environ.get(RPC_CONNS_ENV, "").strip()
    if raw:
        try:
            v = int(raw)
        except ValueError:
            return DEFAULT_RPC_CONNS
        if v >= 1:
            return v
    return DEFAULT_RPC_CONNS


def env_timeout_s() -> float:
    """Transport timeout in seconds from
    ``$VELES_SIMD_RPC_TIMEOUT_MS`` (default 30 s; malformed /
    non-positive falls back)."""
    raw = os.environ.get(RPC_TIMEOUT_ENV, "").strip()
    if raw:
        try:
            v = float(raw)
        except ValueError:
            return DEFAULT_RPC_TIMEOUT_MS / 1e3
        if v > 0:
            return v / 1e3
    return DEFAULT_RPC_TIMEOUT_MS / 1e3


# ---------------------------------------------------------------------------
# wire codec: npy-framed trees
# ---------------------------------------------------------------------------


def _encode_tree(node, blobs: list):
    """JSON-able form of one payload tree; every ndarray (and numpy
    scalar) becomes an indexed npy blob — bytes-exact, never
    base64-JSON.  Reserved ``__``-prefixed dict keys and non-string
    keys escape through ``__map__``.  Unsupported types raise
    ValueError (a caller bug)."""
    if node is None or isinstance(node, (bool, int, float, str)):
        return node
    if isinstance(node, np.ndarray):
        buf = io.BytesIO()
        np.save(buf, node, allow_pickle=False)
        blobs.append(buf.getvalue())
        return {"__blob__": len(blobs) - 1}
    if isinstance(node, np.generic):
        buf = io.BytesIO()
        np.save(buf, np.asarray(node), allow_pickle=False)
        blobs.append(buf.getvalue())
        return {"__scalar__": len(blobs) - 1}
    if isinstance(node, tuple):
        return {"__tuple__": [_encode_tree(v, blobs) for v in node]}
    if isinstance(node, list):
        return [_encode_tree(v, blobs) for v in node]
    if isinstance(node, dict):
        if all(isinstance(k, str) and not k.startswith("__")
               for k in node):
            return {k: _encode_tree(v, blobs)
                    for k, v in node.items()}
        return {"__map__": [[_encode_tree(k, blobs),
                             _encode_tree(v, blobs)]
                            for k, v in node.items()]}
    raise ValueError(
        f"rpc wire cannot encode {type(node).__name__} values")


def _decode_tree(node, blobs: list):
    """Inverse of :func:`_encode_tree` over already-deserialized
    blobs."""
    if isinstance(node, dict):
        if "__blob__" in node:
            return blobs[int(node["__blob__"])]
        if "__scalar__" in node:
            return blobs[int(node["__scalar__"])][()]
        if "__tuple__" in node:
            return tuple(_decode_tree(v, blobs)
                         for v in node["__tuple__"])
        if "__map__" in node:
            return {_decode_tree(k, blobs): _decode_tree(v, blobs)
                    for k, v in node["__map__"]}
        return {k: _decode_tree(v, blobs) for k, v in node.items()}
    if isinstance(node, list):
        return [_decode_tree(v, blobs) for v in node]
    return node


def pack_frame(header: dict, blobs: list) -> bytes:
    """One wire frame: ``MAGIC`` + 4-byte big-endian JSON-header
    length + header + concatenated npy blobs (sizes in
    ``header["blobs"]``)."""
    header = dict(header)
    header["schema"] = WIRE_SCHEMA
    header["blobs"] = [len(b) for b in blobs]
    hj = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return b"".join([MAGIC, struct.pack(">I", len(hj)), hj] + blobs)


def unpack_frame(data: bytes) -> tuple:
    """``(header, blob_arrays)`` from one frame.  EVERY malformation —
    wrong magic, truncated header or blobs, non-JSON, schema drift, a
    blob npy can't parse — raises ValueError: the one exception type
    both ends translate into a typed answer, never a hang."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        raise ValueError("rpc frame must be bytes")
    data = bytes(data)
    if len(data) < len(MAGIC) + 4:
        raise ValueError(
            f"rpc frame truncated ({len(data)} bytes)")
    if data[:len(MAGIC)] != MAGIC:
        raise ValueError("rpc frame has wrong magic")
    (hlen,) = struct.unpack(
        ">I", data[len(MAGIC):len(MAGIC) + 4])
    if hlen > _MAX_HEADER:
        raise ValueError(f"rpc header length {hlen} out of bounds")
    off = len(MAGIC) + 4
    if len(data) < off + hlen:
        raise ValueError("rpc frame truncated inside header")
    try:
        header = json.loads(data[off:off + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ValueError(f"rpc header is not JSON: {e}") from e
    if not isinstance(header, dict):
        raise ValueError("rpc header must be a JSON object")
    if header.get("schema") != WIRE_SCHEMA:
        raise ValueError(
            f"rpc schema mismatch: got {header.get('schema')!r}, "
            f"want {WIRE_SCHEMA!r}")
    off += hlen
    sizes = header.get("blobs") or []
    blobs = []
    for size in sizes:
        size = int(size)
        if len(data) < off + size:
            raise ValueError("rpc frame truncated inside blobs")
        try:
            blobs.append(np.load(io.BytesIO(data[off:off + size]),
                                 allow_pickle=False))
        except Exception as e:  # noqa: BLE001 — any npy rot = ValueError
            raise ValueError(f"rpc blob unparseable: {e}") from e
        off += size
    if off != len(data):
        raise ValueError(
            f"rpc frame has {len(data) - off} trailing bytes")
    return header, blobs


def pack_request(op: str, x, params: dict, *,
                 tenant: str = "default",
                 deadline_ms: float | None = None,
                 block: bool = False,
                 timeout: float | None = None) -> bytes:
    """One ``POST /submit`` body.  ``deadline_ms`` is the REMAINING
    budget at send time (the receiver re-anchors it on its own
    clock)."""
    blobs: list = []
    header = {
        "kind": "request",
        "op": str(op),
        "tenant": str(tenant),
        "deadline_ms": (float(deadline_ms)
                        if deadline_ms is not None else None),
        "block": bool(block),
        "timeout": float(timeout) if timeout is not None else None,
        "x": _encode_tree(np.asarray(x), blobs),
        "params": _encode_tree(dict(params or {}), blobs),
    }
    return pack_frame(header, blobs)


def unpack_request(data: bytes) -> dict:
    """Decoded request fields (``op``/``x``/``params``/``tenant``/
    ``deadline_ms``/``block``/``timeout``); ValueError on any
    malformation."""
    header, blobs = unpack_frame(data)
    if header.get("kind") != "request":
        raise ValueError(
            f"expected a request frame, got {header.get('kind')!r}")
    if not isinstance(header.get("op"), str):
        raise ValueError("rpc request has no op")
    params = _decode_tree(header.get("params"), blobs)
    if params is None:
        params = {}
    if not isinstance(params, dict):
        raise ValueError("rpc request params must decode to a dict")
    return {
        "op": header["op"],
        "tenant": str(header.get("tenant") or "default"),
        "deadline_ms": header.get("deadline_ms"),
        "block": bool(header.get("block")),
        "timeout": header.get("timeout"),
        "x": _decode_tree(header.get("x"), blobs),
        "params": params,
    }


def pack_response(*, status: str, value=None, error: dict | None = None,
                  wait_s: float | None = None, events=(),
                  replica: str | None = None) -> bytes:
    """One ``/submit`` response body: the ticket outcome (status +
    value tree or encoded error), the replica identity, and the
    child-side trace events for cross-process stitching."""
    blobs: list = []
    header = {
        "kind": "response",
        "status": str(status),
        "wait_s": float(wait_s) if wait_s is not None else None,
        "replica": replica,
        "error": error,
        "events": list(events),
        "value": _encode_tree(value, blobs),
    }
    return pack_frame(header, blobs)


def unpack_response(data: bytes) -> dict:
    """Decoded response fields; ValueError on any malformation (the
    client maps it to a ``closed`` ticket — failover-safe)."""
    header, blobs = unpack_frame(data)
    if header.get("kind") != "response":
        raise ValueError(
            f"expected a response frame, got {header.get('kind')!r}")
    status = header.get("status")
    if not isinstance(status, str) or not status:
        raise ValueError("rpc response has no status")
    events = header.get("events")
    return {
        "status": status,
        "wait_s": header.get("wait_s"),
        "replica": header.get("replica"),
        "error": header.get("error"),
        "events": events if isinstance(events, list) else [],
        "value": _decode_tree(header.get("value"), blobs),
    }


# ---------------------------------------------------------------------------
# typed-error mapping (lossless across the HTTP boundary)
# ---------------------------------------------------------------------------


def encode_error(exc: BaseException) -> dict:
    """Wire form of one typed serving error (the :data:`ERROR_KINDS`
    table).  Subclass order matters: the typed serve errors are
    RuntimeError subclasses, so they classify before the catch-all."""
    if isinstance(exc, Overloaded):
        return {"kind": "overloaded", "message": str(exc),
                "tenant": getattr(exc, "tenant", "default"),
                "scope": getattr(exc, "scope", "global")}
    if isinstance(exc, DeadlineExceeded):
        return {"kind": "deadline", "message": str(exc)}
    if isinstance(exc, ServerClosed):
        return {"kind": "closed", "message": str(exc)}
    if isinstance(exc, ValueError):
        return {"kind": "bad_request", "message": str(exc)}
    return {"kind": "error", "message": f"{type(exc).__name__}: {exc}"}


def decode_error(info: dict) -> Exception:
    """The Python twin of one wire error dict — inverse of
    :func:`encode_error`, so shed/expired/closed semantics survive the
    boundary bit-identically.  Unknown kinds decode as RuntimeError
    (forward compatibility beats a parse crash)."""
    if not isinstance(info, dict):
        return RuntimeError(f"malformed rpc error payload: {info!r}")
    kind = info.get("kind")
    message = str(info.get("message") or "rpc error")
    if kind == "overloaded":
        tenant = str(info.get("tenant") or "default")
        if info.get("scope") == "cluster":
            # router-scope exhaustion round-trips as its own type
            from veles.simd_tpu.serve.cluster import \
                NoReplicaAvailable
            return NoReplicaAvailable(message, tenant=tenant)
        return Overloaded(message, tenant=tenant,
                          scope=str(info.get("scope") or "global"))
    if kind == "deadline":
        return DeadlineExceeded(message)
    if kind == "closed":
        return ServerClosed(message)
    if kind == "bad_request":
        return ValueError(message)
    return RuntimeError(message)


# ---------------------------------------------------------------------------
# server side: the POST /submit body
# ---------------------------------------------------------------------------


def serve_submit(server, body: bytes) -> tuple:
    """Answer one ``POST /submit`` body against ``server`` (a live
    :class:`~veles.simd_tpu.serve.server.Server`); returns ``(http_
    code, response_bytes)``.  EVERY outcome is a packed response —
    malformed bodies answer ``bad_request`` (HTTP 400), typed serving
    errors ride the payload under HTTP 200, and the response wait is
    bounded (deadline + margin, else the rpc timeout) so a wedged
    ticket can never pin the handler thread forever."""
    try:
        req = unpack_request(body)
    except ValueError as e:
        return 400, pack_response(
            status="error",
            error={"kind": "bad_request",
                   "message": f"malformed rpc request: {e}"},
            replica=getattr(server, "name", None))
    name = getattr(server, "name", None)
    deadline_ms = req["deadline_ms"]
    try:
        ticket = server.submit(
            Request(op=req["op"], x=req["x"], params=req["params"],
                    tenant=req["tenant"], deadline_ms=deadline_ms),
            block=req["block"], timeout=req["timeout"])
    except ValueError as e:
        return 200, pack_response(status="error",
                                  error=encode_error(e),
                                  replica=name)
    except ServerClosed as e:
        return 200, pack_response(status="closed",
                                  error=encode_error(e),
                                  replica=name)
    done = threading.Event()
    ticket.add_done_callback(lambda _t: done.set())
    bound = env_timeout_s()
    if deadline_ms is not None and deadline_ms > 0:
        bound = float(deadline_ms) / 1e3 + RESPONSE_MARGIN_S
    if not done.wait(bound):
        # the ticket may still answer later (server-side accounting is
        # its own); THIS exchange answers typed — the client fails the
        # request over rather than hanging a connection slot
        obs.count("rpc_response_timeout", op=req["op"])
        return 200, pack_response(
            status="error",
            error={"kind": "error",
                   "message": f"replica did not answer within "
                              f"{bound:.1f}s"},
            replica=name)
    events = ticket.trace.events() if ticket.trace is not None else []
    error = (encode_error(ticket._error)
             if ticket._error is not None else None)
    return 200, pack_response(status=ticket.status,
                              value=ticket._value,
                              error=error, wait_s=ticket.wait_s,
                              events=events, replica=name)


# ---------------------------------------------------------------------------
# client side: the router's pooled persistent-connection submitter
# ---------------------------------------------------------------------------


class RpcTicket(Ticket):
    """A :class:`~veles.simd_tpu.serve.server.Ticket` completed by the
    RPC client instead of a local worker — same contract (result /
    done / status / trace / add_done_callback / exactly-once), so the
    front router's failover hook cannot tell the difference.
    ``remote`` is the answering replica's id once terminal."""

    __slots__ = ("remote",)

    def __init__(self, op: str, tenant: str):
        super().__init__(op, tenant)
        self.remote = None


class RpcClient:
    """Pooled persistent-connection submitter for ONE subprocess
    replica's ``POST /submit`` route.

    ``conns`` dedicated sender threads each own one keep-alive
    ``http.client.HTTPConnection`` (rebuilt transparently after a
    transport error), so up to ``conns`` submissions are in flight
    concurrently and none pays TCP setup.  :meth:`submit` mirrors
    :meth:`~veles.simd_tpu.serve.server.Server.submit` — synchronous
    ValueError for malformed requests, a ServerClosed raise once
    closed, a ticket for everything else — and every ticket resolves
    typed: transport failures answer ``closed`` (or ``expired`` when
    the request's own deadline already passed), garbage replies answer
    ``closed``, remote outcomes map through :func:`decode_error`.

    This class is the ONLY place serve-layer code speaks raw HTTP
    request submission (tools/lint.py rpc-funnel rule)."""

    def __init__(self, host: str, port: int, *,
                 replica: str | None = None,
                 conns: int | None = None,
                 timeout_s: float | None = None):
        self.host = str(host)
        self.port = int(port)
        self.replica = replica
        self.conns = int(conns) if conns else env_conns()
        if self.conns < 1:
            raise ValueError("conns must be >= 1")
        self.timeout_s = (float(timeout_s) if timeout_s
                          else env_timeout_s())
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._closed = False
        self._in_flight = 0
        self._stats = {"submitted": 0, "completed": 0, "sends": 0,
                       "reused": 0, "transport_errors": 0,
                       "bad_replies": 0}
        self._conn_slots: list = [None] * self.conns
        self._workers: list = []
        for i in range(self.conns):
            t = threading.Thread(
                target=self._worker, args=(i,), daemon=True,
                name=f"veles-rpc-{self.replica or self.port}-{i}")
            t.start()
            self._workers.append(t)

    # -- intake ------------------------------------------------------------

    def submit(self, request: Request | None = None, *,
               op: str | None = None, x=None,
               params: dict | None = None, tenant: str = "default",
               block: bool = False, timeout: float | None = None,
               deadline_ms: float | None = None) -> RpcTicket:
        """Queue one request onto the replica's wire; returns its
        :class:`RpcTicket`.  Same call shape and synchronous-error
        contract as :meth:`Server.submit` (malformed requests raise
        ValueError here, before any bytes move; a closed client raises
        ServerClosed — the router's placement-failure path).  One
        remote-only difference: pipeline registration is the CHILD's
        (an unregistered pipeline answers a ``bad_request`` ticket
        instead of raising here — the client cannot see the child's
        registry without a round trip)."""
        if request is None:
            request = Request(op=op, x=x, params=params or {},
                              tenant=tenant, deadline_ms=deadline_ms)
        elif deadline_ms is not None:
            request = dataclasses.replace(request,
                                          deadline_ms=deadline_ms)
        xarr, _n, _cparams, key = classify_request(
            request.op, request.x, request.params)
        dl_ms = request.deadline_ms
        if dl_ms is None:
            dl_ms = env_deadline_ms()
        has_deadline = dl_ms is not None and dl_ms > 0
        ticket = RpcTicket(request.op, request.tenant)
        ticket.trace = obs.request_trace(
            request.op, tenant=request.tenant, shape_class=key[2],
            deadline_s=(float(dl_ms) / 1e3 if has_deadline else None))
        body = pack_request(
            request.op, xarr, request.params, tenant=request.tenant,
            deadline_ms=(float(dl_ms) if has_deadline else None),
            block=block, timeout=timeout)
        abs_deadline = (faults.monotonic() + float(dl_ms) / 1e3
                        if has_deadline else None)
        with self._lock:
            if self._closed:
                raise ServerClosed(
                    f"rpc client for replica "
                    f"{self.replica or self.host} is closed")
            self._stats["submitted"] += 1
            self._in_flight += 1
            # the put rides the same lock as the closed check: every
            # enqueued ticket happens-before close()'s sentinels, so a
            # sender always processes it (typed), never strands it
            self._q.put((ticket, body, abs_deadline))
        ticket.trace.event("rpc_submit", replica=self.replica,
                           deadline_ms=(float(dl_ms)
                                        if has_deadline else None))
        return ticket

    # -- the sender loop ---------------------------------------------------

    def _worker(self, slot: int) -> None:
        while True:
            item = self._q.get()
            if item is None:
                conn = self._conn_slots[slot]
                self._conn_slots[slot] = None
                if conn is not None:
                    try:
                        conn.close()
                    except Exception:  # noqa: BLE001 — teardown
                        pass
                return
            try:
                self._roundtrip(slot, item)
            except Exception as e:  # noqa: BLE001 — never lose a ticket
                if not item[0].done():
                    self._finish(item[0], status="error",
                                 error=RuntimeError(
                                     f"rpc client internal error: "
                                     f"{e!r}"))

    def _finish(self, ticket: RpcTicket, *, value=None, error=None,
                status="ok", wait_s=None) -> None:
        """Complete one ticket exactly once + the in-flight
        accounting (every roundtrip outcome funnels through here)."""
        with self._lock:
            self._in_flight -= 1
            self._stats["completed"] += 1
        ticket.remote = self.replica
        ticket._complete(value=value, error=error, status=status,
                         wait_s=wait_s)

    def _transport_failed(self, slot: int, ticket: RpcTicket,
                          abs_deadline, exc, *,
                          bad_reply: bool = False) -> None:
        """One transport-layer failure: drop the poisoned connection,
        count it, answer typed — ``expired`` when the request's own
        deadline already passed (the caller gave up; the transport
        story is noise), ``closed`` otherwise (the failover signal)."""
        conn = self._conn_slots[slot]
        self._conn_slots[slot] = None
        if conn is not None:
            try:
                conn.close()
            except Exception:  # noqa: BLE001 — already broken
                pass
        with self._lock:
            self._stats["transport_errors"] += 1
            if bad_reply:
                self._stats["bad_replies"] += 1
        obs.count("rpc_transport_error",
                  replica=self.replica or "unknown",
                  kind="bad_reply" if bad_reply else "io")
        ticket.trace.event("rpc_transport_error",
                           replica=self.replica,
                           error=repr(exc)[:200])
        if abs_deadline is not None \
                and faults.monotonic() >= abs_deadline:
            self._finish(
                ticket, status="expired",
                error=DeadlineExceeded(
                    f"DEADLINE_EXCEEDED: rpc request "
                    f"{ticket.op!r} missed its end-to-end deadline "
                    f"in flight to replica {self.replica}"))
        else:
            self._finish(
                ticket, status="closed",
                error=ServerClosed(
                    f"rpc transport to replica {self.replica} "
                    f"failed: {exc!r:.200}"))

    def _roundtrip(self, slot: int, item) -> None:
        import http.client
        import socket

        ticket, body, abs_deadline = item
        with self._lock:
            closed = self._closed
        if closed:
            self._finish(ticket, status="closed",
                         error=ServerClosed(
                             f"rpc client for replica {self.replica} "
                             f"closed before dispatch"))
            return
        if abs_deadline is not None \
                and faults.monotonic() >= abs_deadline:
            self._finish(
                ticket, status="expired",
                error=DeadlineExceeded(
                    f"DEADLINE_EXCEEDED: rpc request {ticket.op!r} "
                    f"missed its end-to-end deadline before "
                    f"dispatch to replica {self.replica}"))
            return
        conn = self._conn_slots[slot]
        reused = conn is not None
        try:
            if conn is None:
                conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout_s)
                # http.client writes headers and body as separate
                # segments; without TCP_NODELAY that is a Nagle +
                # delayed-ACK stall (~40ms) per exchange
                conn.connect()
                conn.sock.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_NODELAY, 1)
                self._conn_slots[slot] = conn
            ticket.trace.event("rpc_sent", replica=self.replica,
                               reused=reused)
            conn.request("POST", "/submit", body=body,
                         headers={"Content-Type": CONTENT_TYPE})
            resp = conn.getresponse()
            data = resp.read()
        except Exception as e:  # noqa: BLE001 — any io rot = typed
            self._transport_failed(slot, ticket, abs_deadline, e)
            return
        with self._lock:
            self._stats["sends"] += 1
            if reused:
                self._stats["reused"] += 1
        try:
            payload = unpack_response(data)
        except ValueError as e:
            # a truncated/garbage reply left the connection state
            # unknowable — drop it with the same typed closed/expired
            # answer a reset would get (re-execution is safe: ops are
            # pure, router dedup forbids double answers)
            self._transport_failed(slot, ticket, abs_deadline, e,
                                   bad_reply=True)
            return
        events = payload["events"]
        if events:
            ticket.trace.absorb_remote(
                events, replica=payload.get("replica")
                or self.replica)
        status = payload["status"]
        error = (decode_error(payload["error"])
                 if payload.get("error") is not None else None)
        if status in ("ok", "degraded"):
            self._finish(ticket, value=payload["value"],
                         status=status, wait_s=payload.get("wait_s"))
            return
        if error is None:
            error = RuntimeError(
                f"rpc response carried status {status!r} with no "
                f"error payload")
        self._finish(ticket, status=status, error=error,
                     wait_s=payload.get("wait_s"))

    # -- lifecycle + introspection -----------------------------------------

    def close(self) -> None:
        """Stop intake and the sender pool.  Queued-but-unsent
        requests answer ``closed`` (the senders drain them under the
        closed flag before eating their sentinels); in-flight
        exchanges resolve through their own transport errors once the
        peer dies.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for _ in self._workers:
                self._q.put(None)
        # unblock senders parked inside a response read: closing the
        # socket under them turns the park into a transport error,
        # which answers their ticket typed
        for conn in list(self._conn_slots):
            if conn is not None:
                try:
                    conn.close()
                except Exception:  # noqa: BLE001 — teardown
                    pass
        for t in self._workers:
            t.join(timeout=5.0)
        self._workers = []

    def in_flight(self) -> int:
        """Requests submitted but not yet completed — the router's
        depth signal for a subprocess replica (the in-process twin is
        :meth:`Server.depth`)."""
        with self._lock:
            return self._in_flight

    def stats(self) -> dict:
        """JSON-native client health: in-flight, submissions,
        connection-reuse ratio, transport errors — the per-replica RPC
        block the fleet collector exports (``rpc_in_flight`` /
        ``rpc_reuse_ratio`` / ``rpc_transport_errors`` series)."""
        with self._lock:
            counts = dict(self._stats)
            in_flight = self._in_flight
        sends = counts["sends"]
        return {
            "replica": self.replica,
            "host": self.host,
            "port": self.port,
            "conns": self.conns,
            "in_flight": in_flight,
            "reuse_ratio": ((counts["reused"] / sends)
                            if sends else None),
            **counts,
        }

    def __repr__(self):
        return (f"RpcClient({self.host}:{self.port}, "
                f"replica={self.replica!r}, conns={self.conns})")
