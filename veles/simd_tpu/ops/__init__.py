"""TPU-lowered op library (replaces the reference's L3/L4 layers).

Each module mirrors one reference header (SURVEY.md §2):

* :mod:`.arithmetic`   — conversions, complex/real multiply, reductions
* :mod:`.mathfun`      — vectorized sin/cos/log/exp
* :mod:`.matrix`       — BLAS L1/L2/L3 subset on the MXU
* :mod:`.convolve`     — 1D convolution (brute / FFT / overlap-save,
  auto-select)
* :mod:`.correlate`    — 1D cross-correlation (reversed-h reuse of convolve)
* :mod:`.wavelet`      — 1D DWT / stationary SWT filter banks
* :mod:`.wavelet_coeffs` — generated Daubechies / Symlet / Coiflet tables
* :mod:`.normalize`    — 1D/2D min-max normalization
* :mod:`.spectral`     — STFT/ISTFT, spectrogram, Hilbert envelope,
  Morlet CWT (beyond-reference: batched-FFT time-frequency analysis)
* :mod:`.resample`     — polyphase rational-rate conversion as one
  dilated/strided conv + Fourier resampling (beyond-reference)
* :mod:`.iir`          — Butterworth design + IIR cascades as O(log n)
  associative-scan recurrences, zero-phase filtfilt (beyond-reference)
* :mod:`.batched`      — batched-throughput entry points (many short
  signals, one dispatch): LRU-cached compiled handles with donated
  buffers for resample_poly / sosfilt / lfilter (beyond-reference)
* :mod:`.filters`      — median/rank filtering (gather + lane sort),
  Savitzky-Golay smoothing/derivatives, window-method FIR design
  (beyond-reference)
* :mod:`.waveforms`    — chirps, square/sawtooth, Gaussian pulses as
  fused elementwise generators (beyond-reference)
* :mod:`.detect_peaks` — 1D local-extrema detection
* :mod:`.segments`     — ragged segment packing: variable-length
  signals concatenated along the sample axis into shared rows, one
  dispatch, bit-equal per-segment slices back out (beyond-reference)

Every public op takes the reference-compatible ``simd=`` flag: truthy (the
default) runs the jitted XLA path; falsy runs the NumPy oracle twin, keeping
the reference's cross-validation discipline
(``/root/reference/tests/matrix.cc:94-98``).
"""
